#!/usr/bin/env python
"""Headline benchmark: 1000-replication FAVAR IRF wild bootstrap on the
Stock-Watson panel (BASELINE.json north star: < 10 s on TPU).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"} where
vs_baseline = 10s-target / measured wall-clock (>1 is better than target).
Also reports EM iterations/sec as an auxiliary field.
"""

import json
import sys
import time

sys.path.insert(0, __file__.rsplit("/", 1)[0])

import jax
import jax.numpy as jnp
import numpy as np


def main():
    from dynamic_factor_models_tpu.io.cache import cached_dataset
    from dynamic_factor_models_tpu.models.dfm import DFMConfig, estimate_factor
    from dynamic_factor_models_tpu.models.favar import wild_bootstrap_irfs
    from dynamic_factor_models_tpu.models.ssm import em_step, SSMParams
    from dynamic_factor_models_tpu.ops.masking import fillz, mask_of

    dev = jax.devices()[0]
    ds = cached_dataset("Real")

    # factors via ALS (f32-safe tolerance; parity is covered by the CPU tests)
    cfg = DFMConfig(nfac_u=4, tol=1e-6, max_iter=2000)
    F, _ = estimate_factor(ds.bpdata, ds.inclcode, 2, 223, cfg)

    n_reps, horizon = 1000, 24
    run = lambda seed: wild_bootstrap_irfs(
        F, 4, 2, 223, horizon=horizon, n_reps=n_reps, seed=seed
    )
    run(0).draws.block_until_ready()  # compile
    t0 = time.perf_counter()
    bs = run(1)
    bs.draws.block_until_ready()
    dt = time.perf_counter() - t0

    # auxiliary: EM iterations/sec on the included panel (steady state)
    est = jnp.asarray(np.asarray(ds.bpdata))[:, np.asarray(ds.inclcode) == 1][2:224]
    from dynamic_factor_models_tpu.ops.linalg import standardize_data

    xstd, _ = standardize_data(est)
    xz, m = fillz(xstd), mask_of(xstd)
    r, p, N = 4, 4, xz.shape[1]
    params = SSMParams(
        lam=jnp.zeros((N, r)).at[:, 0].set(1.0),
        R=jnp.ones(N),
        A=jnp.concatenate([0.5 * jnp.eye(r)[None], jnp.zeros((p - 1, r, r))]),
        Q=jnp.eye(r),
    )
    params, _ = em_step(params, xz, m)  # compile
    jax.block_until_ready(params)
    n_iter = 20
    t1 = time.perf_counter()
    for _ in range(n_iter):
        params, ll = em_step(params, xz, m)
    jax.block_until_ready(params)
    em_ips = n_iter / (time.perf_counter() - t1)

    # auxiliary: fused Pallas masked-Gram vs XLA einsum at large-panel scale
    # (the regime beyond the 224 x 233 reference panel the kernel targets)
    from dynamic_factor_models_tpu.ops.pallas_gram import (
        masked_gram_pallas,
        masked_gram_xla,
    )

    rng = np.random.default_rng(0)
    Tbig, Nbig, K = 2048, 4096, 8
    Xb = jnp.asarray(rng.standard_normal((Tbig, K)), jnp.float32)
    Yb = jnp.asarray(rng.standard_normal((Tbig, Nbig)), jnp.float32)
    Wb = jnp.asarray((rng.random((Tbig, Nbig)) > 0.2), jnp.float32)

    def _time(fn):
        out = fn(Xb, Yb, Wb)
        jax.block_until_ready(out)  # compile
        t = time.perf_counter()
        for _ in range(5):
            out = fn(Xb, Yb, Wb)
        jax.block_until_ready(out)
        return (time.perf_counter() - t) / 5

    try:
        t_pallas = _time(masked_gram_pallas)
        t_xla = _time(jax.jit(masked_gram_xla))
        gram_speedup = round(t_xla / t_pallas, 2)
    except Exception:  # pallas unavailable on this backend: report neutral
        gram_speedup = None

    print(
        json.dumps(
            {
                "metric": "favar_irf_wild_bootstrap_1000rep_wallclock",
                "value": round(dt, 4),
                "unit": "s",
                "vs_baseline": round(10.0 / dt, 2),
                "device": str(dev),
                "em_iters_per_sec": round(em_ips, 2),
                "pallas_gram_speedup_large_panel": gram_speedup,
            }
        )
    )


if __name__ == "__main__":
    main()
