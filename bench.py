#!/usr/bin/env python
"""Headline benchmark: 1000-replication FAVAR IRF wild bootstrap on the
Stock-Watson panel (BASELINE.json north star: < 10 s on TPU).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"} where
vs_baseline = 10s-target / measured wall-clock (>1 is better than target).

Process layout (the round-2 lesson: one 240 s probe at process start is a
single coin flip against a tunnel that wedges and recovers on hour scales):

  bench.py                 orchestrator — never touches jax devices itself.
                           Probes the tunnel in killable subprocesses,
                           RETRIES across the run (first probe, then again
                           after the CPU fallback sections complete, then on
                           a backoff loop up to DFM_BENCH_PROBE_BUDGET_S),
                           and launches the measuring children below.
  bench.py --run-main      the measured sections in one process (TPU when
                           reachable; --force-cpu pins the CPU platform
                           config-level before any device touch).
  bench.py --run-parity-programs --out F.npz [--factor-in G.npz]
                           the three parity programs (ALS factor, Kalman
                           smoother, bootstrap IRF) on the CPU platform at
                           the ambient precision; run twice (f64 via
                           JAX_ENABLE_X64=1, then f32) to decompose parity
                           into precision-effect vs device-effect.
  bench.py --crossover     manual: Pallas-vs-XLA masked-Gram crossover table
                           on the live chip (documents _PALLAS_MIN_CELLS).
  bench.py --run-tpu-remainder
                           manual/watcher mode for short tunnel windows:
                           only the TPU sections missing from the salvaged
                           2026-07-31 live record, cheapest compile first
                           (pallas -> device parity -> large panel ->
                           refscale decomposition -> crossover), each
                           folded into the durable evidence store
                           docs/TPU_EVIDENCE.json, which the orchestrator
                           merges (tpu_live_* fields) into any CPU-fallback
                           report.
  bench.py --run-em-refscale [--grid] [--force-cpu]
                           child: reference-scale latency leg at the
                           ambient DFM_SCAN_UNROLL (dispatch round-trip,
                           EM iters/sec; --grid adds the (T, N) tiling and
                           bootstrap-replication cells).
  bench.py --stage-refscale / --refscale-staged-fresh
                           pre-stage / freshness-check the CPU twin of the
                           reference-scale decomposition
                           (build/refscale_cpu.json), mirroring the parity
                           staging pattern.
  bench.py --run-compile-split --cache-dir D
                           child: one compile-once invocation (AOT
                           precompile for the BASELINE bucket + bucketed EM
                           estimate) against cache dir D; the orchestrator
                           runs it twice (cold, then persistent-cache warm)
                           and reports the wall-clock ratio.
  bench.py --warm-cache    populate the repo-local persistent compile cache
                           + AOT registry for the BASELINE bucket on the
                           ambient platform (first step of a live TPU
                           window — see tools/tpu_watch.sh).

JSON fields beyond the headline:
- em_iters_per_sec[_host_sync|_assoc|_sqrt]  state-space EM throughput on
  the real 222x139 panel: on-device lax.while_loop, host-synced driver, the
  associative (parallel-in-time) E-step, and the square-root (QR array)
  E-step — the f32-precision option's speed cost made visible.
- em_iters_per_sec_mf_monthly           mixed-frequency EM on the real
  672x207 monthly panel (io.readin_data_monthly).
- em_iters_per_sec_steady / em_steady_speedup / riccati_doubling_iters /
  steady_tail_frac / steady_t_star       steady-state fast-path EM
  (models/steady.py: DARE fixed point + constant-gain tail).  Measured on
  the real panel when its mask is head-ragged-only, else on a
  reference-scale complete-tail synthetic panel with sequential re-timed
  on the same panel (em_iters_per_sec_steady_baseline); all keys null when
  the fast path is gated off everywhere (steady_bench_panel names the leg).
- als_large_* / em_large_*              synthetic large-panel section
  (T=2048, N=4096, r=8 — the regime ops/pallas_gram.py targets): iters/sec,
  a documented FLOPs-model throughput, and the MFU estimate against the
  v5e bf16 peak; *_cpu_ratio = TPU time advantage over the same program on
  the host CPU (null when the whole bench runs on CPU).
- pallas_gram_*                         fused kernel vs XLA einsum at the
  flagship size (TPU only; kernel failure is fatal, not swallowed).
- compile_s / run_s / cache_hits        compile-once layer split (CPU
  children): XLA seconds vs execution seconds on the cold leg, persistent
  compilation-cache hits on the warm leg; warm_cache_speedup = cold wall /
  warm wall of the identical invocation (utils/compile.py counters).
- parity_factor/smoother/irf            CPU-f32 vs TPU-f32 max-abs-diff
  (device effect); parity_precision_*   CPU-f64 vs CPU-f32 of the same
  programs (precision effect) — together they decompose the documented
  f32 thresholds (docs/PARITY.md).  Exits nonzero on parity failure.

If the TPU tunnel never answers within the probe budget, the bench reports
CPU numbers with "tpu_unreachable": true and null TPU-only fields.
DFM_BENCH_FORCE_CPU=1 forces the fallback path deterministically.
"""

import argparse
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, REPO)

# documented f32 parity thresholds (north star is 1e-5 in f64; the v5e has
# no f64, so the device comparison runs f32 on both backends under
# jax.default_matmul_precision("highest") — measured diffs and rationale are
# recorded in docs/PARITY.md; the precision-effect fields below show the
# same programs' f64-vs-f32 gap on one device)
PARITY_THRESHOLDS = {
    "parity_factor": 1e-3,
    "parity_smoother": 1e-3,
    "parity_smoother_sqrt": 1e-3,
    "parity_irf": 1e-3,
}

# v5e single-chip peak: 197 TFLOP/s bf16 on the MXU.  The float32 programs
# below run at a fraction of that peak by construction; MFU against the
# bf16 ceiling is the honest, hardware-anchored denominator (it cannot
# flatter the result).  Aliased from the runtime roofline ledger
# (utils/roofline) so bench and the live MFU gauges share ONE denominator
# and one provenance vocabulary (mfu_peak_source / flop_proxy).
from dynamic_factor_models_tpu.utils.roofline import (  # noqa: E402
    PEAK_FLOPS_V5E_BF16,
)

# large-panel regime (the scale ops/pallas_gram.py's docstring targets,
# beyond the reference's 224x233 panel)
LARGE_T, LARGE_N, LARGE_R = 2048, 4096, 8


def als_iter_flops(T: int, N: int, r: int) -> float:
    """FLOPs model of one ALS iteration (models/dfm._als_core).

    Loading step: masked Gram over (T, N) with K=r regressors — 2TNr^2 for
    the N per-series Gram matrices + 2TNr for the right-hand sides.  F-step:
    the same contraction with series/time roles swapped.  Residual/SSR pass:
    2TNr.  Per-series r x r solves are O(N r^3), negligible at N >> r.
    """
    return 4.0 * T * N * r * r + 6.0 * T * N * r


def em_iter_flops(T: int, N: int, r: int, p: int) -> float:
    """FLOPs model of one EM iteration (models/ssm.em_step_stats, the
    collapsed production path).

    Jungbacker-Koopman collapse (ssm._collapse_obs_stats): C_t precompute
    is one (T, N) @ (N, r(r+1)/2) GEMM ~ TNr^2, b_t one (T, N) @ (N, r)
    GEMM ~ 2TNr; the scan body is N-free, ~10 k^3 per step for the
    predict/Cholesky/solve block with k = r*p, RTS smoother ~8 k^3.
    M-step (suff-stat form): packed Sff GEMM ~ TNr^2 + Sxf 2TNr.
    Constants are documented estimates — MFU derived from them is an
    estimate for trend-tracking, not a hardware counter measurement.
    """
    k = r * p
    return 2.0 * T * N * r * r + 4.0 * T * N * r + 18.0 * T * k**3


def _sign_align(a, b):
    """Align column signs of b to a (factors are identified up to sign)."""
    import numpy as np

    s = np.sign(np.nansum(a * b, axis=0))
    s[s == 0] = 1.0
    return b * s


# ---------------------------------------------------------------------------
# parity programs (shared by the device comparison and the precision pair)
# ---------------------------------------------------------------------------


def parity_programs(ds, backend, factor_override=None):
    """Run the three parity programs on one backend; return arrays.

    The ALS comparison fixes the iteration count (tol=0, max_iter=60) so
    every run executes the same number of iterations — with a convergence
    tolerance two backends stop at slightly different points of the same
    fixed-point approach and the diff measures the tolerance, not the
    numerics.  `factor_override` feeds a canonical factor into the IRF
    program so its diff isolates the bootstrap/VAR numerics.
    """
    import jax.numpy as jnp
    import numpy as np

    from dynamic_factor_models_tpu.models.dfm import DFMConfig, estimate_factor
    from dynamic_factor_models_tpu.models.favar import wild_bootstrap_irfs
    from dynamic_factor_models_tpu.models.ssm import SSMParams, kalman_smoother
    from dynamic_factor_models_tpu.ops.linalg import standardize_data

    # ONE window slice + standardization feeds every program below: the
    # polish, the smoother, and the (2, 223) bounds passed to the ALS/IRF
    # calls all describe the same 222-row window — keep a single copy
    est = jnp.asarray(np.asarray(ds.bpdata))[:, np.asarray(ds.inclcode) == 1][2:224]
    xstd, _ = standardize_data(est)
    dtype = xstd.dtype

    cfg = DFMConfig(nfac_u=4, tol=0.0, max_iter=60)
    F_raw, _ = estimate_factor(ds.bpdata, ds.inclcode, 2, 223, cfg, backend=backend)
    F_raw = np.asarray(F_raw)
    # the production 1e-5-parity path: float64 fixed-point polish +
    # canonical rotation, applied to the raw leg's own terminal iterate
    # (exactly what estimate_factor(..., polish="float64") computes, minus
    # a second run of the jitted ALS — the polish output is a function of
    # the data alone, so any in-basin start yields the same array; pinned
    # equal to the API path in tests/test_polish.py).  The raw 60-iter
    # iterate stays alongside as the device/precision-effect diagnostic.
    from dynamic_factor_models_tpu.models.dfm import _polish_fixed_point_f64
    from dynamic_factor_models_tpu.ops.masking import fillz as _fillz, mask_of as _mask_of

    m_w = _mask_of(xstd).astype(dtype)
    lam_ok_w = np.asarray(m_w.sum(axis=0)) >= cfg.nt_min_factor
    F_pol_w, _, _, _, pol_converged = _polish_fixed_point_f64(
        np.asarray(_fillz(xstd)), np.asarray(m_w), lam_ok_w, F_raw[2:224]
    )
    F = np.full_like(F_raw, np.nan, dtype=np.float64)
    F[2:224] = F_pol_w
    F = F.astype(F_raw.dtype)
    r, p, N = 4, 2, xstd.shape[1]
    rng = np.random.default_rng(0)
    params = SSMParams(
        lam=jnp.asarray(rng.standard_normal((N, r)) * 0.3, dtype),
        R=jnp.ones(N, dtype),
        A=jnp.concatenate(
            [0.5 * jnp.eye(r, dtype=dtype)[None], jnp.zeros((p - 1, r, r), dtype)]
        ),
        Q=jnp.eye(r, dtype=dtype),
    )
    sm_means, _, _ = kalman_smoother(params, xstd, backend=backend)
    # the square-root device leg (round-3 verdict weak #4: never measured)
    sm_sqrt, _, ll_sqrt = kalman_smoother(
        params, xstd, backend=backend, method="sqrt"
    )

    F_irf = F if factor_override is None else factor_override.astype(F.dtype)
    bs = wild_bootstrap_irfs(
        jnp.asarray(F_irf), 4, 2, 223, horizon=24, n_reps=64, seed=0,
        backend=backend,
    )
    return {
        "factor": F,
        "factor_raw": F_raw,
        "smoother": np.asarray(sm_means),
        "smoother_sqrt": np.asarray(sm_sqrt),
        "loglik_sqrt": np.asarray(ll_sqrt),
        "irf_point": np.asarray(bs.point),
        "irf_quantiles": np.asarray(bs.quantiles),
        # a capped (non-converged) f64 polish voids the 1e-5 parity
        # guarantee — recorded so the evidence says so explicitly
        "polish_converged": np.asarray(pol_converged),
    }


def _parity_code_rev() -> str:
    """Digest of the sources that define the parity programs' numerics:
    the staged CPU leg is only valid against the code revision that wrote
    it (comparing legs from different revisions would measure code drift,
    not device effect)."""
    import hashlib

    h = hashlib.sha256()
    for rel in (
        "bench.py",
        "dynamic_factor_models_tpu/models/dfm.py",
        "dynamic_factor_models_tpu/models/ssm.py",
        "dynamic_factor_models_tpu/models/favar.py",
        "dynamic_factor_models_tpu/models/emloop.py",
        "dynamic_factor_models_tpu/ops/linalg.py",
        "dynamic_factor_models_tpu/ops/pallas_gram.py",
    ):
        try:
            with open(os.path.join(REPO, rel), "rb") as fh:
                h.update(fh.read())
        except OSError:
            h.update(b"missing:" + rel.encode())
    return h.hexdigest()


def _parity_diffs(cpu, tpu):
    """Max-abs-diffs between two parity-program result dicts."""
    import numpy as np

    out = {}
    out["parity_factor"] = float(
        np.nanmax(
            np.abs(cpu["factor"] - _sign_align(cpu["factor"], tpu["factor"]))
        )
    )
    if "factor_raw" in cpu and "factor_raw" in tpu:
        # unpolished 60-iteration iterate: the pure device/precision effect
        # on the ALS trajectory (diagnostic, not gated — the production
        # parity path is the polished field above)
        out["parity_factor_raw"] = float(
            np.nanmax(
                np.abs(
                    cpu["factor_raw"]
                    - _sign_align(cpu["factor_raw"], tpu["factor_raw"])
                )
            )
        )
    out["parity_smoother"] = float(np.abs(cpu["smoother"] - tpu["smoother"]).max())
    if "smoother_sqrt" in cpu and "smoother_sqrt" in tpu:
        out["parity_smoother_sqrt"] = float(
            np.abs(cpu["smoother_sqrt"] - tpu["smoother_sqrt"]).max()
        )
    out["parity_irf"] = float(
        max(
            np.abs(cpu["irf_point"] - tpu["irf_point"]).max(),
            np.abs(cpu["irf_quantiles"] - tpu["irf_quantiles"]).max(),
        )
    )
    if "polish_converged" in cpu:
        # both legs must have converged polishes for parity_factor to be a
        # device-effect measurement (a capped polish is start-dependent)
        out["parity_polish_converged"] = bool(
            np.asarray(cpu["polish_converged"]).all()
            and np.asarray(tpu.get("polish_converged", True)).all()
        )
    return out


def device_parity_checks(ds):
    """CPU vs TPU max-abs-diff of the parity programs in one process.

    The CPU leg loads from the pre-staged file (build/parity_staged_cpu.npz,
    written by `bench.py --stage-parity`) when present and fresh enough —
    the round-3 lesson: the tunnel opens in short windows, so everything
    that does not need the chip should already be on disk."""
    import numpy as np

    staged = os.path.join(REPO, "build", "parity_staged_cpu.npz")
    cpu = None
    # freshness rule shared with the watcher: code_rev match implies the
    # file was written by this bench.py, which always includes every leg
    if parity_staged_fresh():
        try:
            cpu = dict(np.load(staged))
            cpu.pop("code_rev", None)
            print(
                f"bench: using pre-staged CPU parity leg {staged}",
                file=sys.stderr,
            )
        except Exception:
            cpu = None
    if cpu is None:
        cpu = parity_programs(ds, "cpu")
    # one TPU pass: its own factor comes out regardless of the override, and
    # the override feeds the canonical (CPU) factor into its IRF program —
    # matching the precision pair's --factor-in protocol
    tpu = parity_programs(ds, "tpu", factor_override=cpu["factor"])
    return _parity_diffs(cpu, tpu)


def stage_parity():
    """Pre-stage the CPU leg of the device-parity comparison to disk so a
    short tunnel window needs only the TPU leg (`device_parity_checks`
    picks the file up automatically)."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    from dynamic_factor_models_tpu.io.cache import cached_dataset

    ds = cached_dataset("Real")
    os.makedirs(os.path.join(REPO, "build"), exist_ok=True)
    out = os.path.join(REPO, "build", "parity_staged_cpu.npz")
    with jax.default_matmul_precision("highest"):
        res = parity_programs(ds, "cpu")
    np.savez(out, code_rev=_parity_code_rev(), **res)
    print(f"staged CPU parity leg: {out}", file=sys.stderr)


def parity_staged_fresh() -> bool:
    """True when the staged CPU parity leg exists and matches the current
    code revision — the single copy of the freshness rule, shared by
    `device_parity_checks` and the watcher (`bench.py --parity-staged-fresh`
    exits 0/1 on it; reads one npz member lazily, no jax import)."""
    import numpy as np

    staged = os.path.join(REPO, "build", "parity_staged_cpu.npz")
    try:
        with np.load(staged) as z:
            return str(z["code_rev"]) == _parity_code_rev()
    except Exception:
        # any unreadable state (missing, truncated zip from a killed
        # np.savez, wrong schema) means "not fresh" — the caller recomputes
        return False


def run_parity_programs(out_path, factor_in):
    """Child mode: CPU-platform parity programs at the ambient precision."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    from dynamic_factor_models_tpu.io.cache import cached_dataset

    ds = cached_dataset("Real")
    fo = np.load(factor_in)["factor"] if factor_in else None
    with jax.default_matmul_precision("highest"):
        res = parity_programs(ds, "cpu", factor_override=fo)
    np.savez(out_path, **res)
    print(f"parity programs saved: {out_path}", file=sys.stderr)


# ---------------------------------------------------------------------------
# measured sections (child: --run-main)
# ---------------------------------------------------------------------------


def _time_fixed_iters(fn, n_timing_runs=3):
    """Best wall-clock of `fn()` (blocking) over n runs; fn pre-compiled."""
    best = float("inf")
    for _ in range(n_timing_runs):
        t0 = time.perf_counter()
        fn()
        best = min(best, time.perf_counter() - t0)
    return best


def _synthetic_large_panel(T, N, r, dtype):
    """Factor DGP with 20% missingness at the large-panel benchmark size."""
    import numpy as np

    rng = np.random.default_rng(7)
    f = np.zeros((T, r), np.float64)
    e = rng.standard_normal((T, r))
    for t in range(1, T):
        f[t] = 0.7 * f[t - 1] + e[t]
    lam = rng.standard_normal((N, r)) * 0.5
    x = f @ lam.T + rng.standard_normal((T, N))
    x[rng.random((T, N)) < 0.2] = np.nan
    return x.astype(dtype)


def large_panel_section(tpu_ok, persist=None):
    """ALS + EM at (T, N, r) = (2048, 4096, 8): seconds per iteration, the
    FLOPs-model throughput, MFU vs the v5e bf16 peak, and (on TPU) the
    CPU-host comparison ratio for the same compiled program.

    `persist`, when given, is called with the accumulated fields after
    EVERY measured program (TPU ALS, TPU EM, then the CPU legs): this
    section's remote compiles are where the 2026-07-31 window died, so
    each live timing must hit disk the moment it exists."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from dynamic_factor_models_tpu.models.dfm import _als_core
    from dynamic_factor_models_tpu.models.ssm import (
        SSMParams,
        compute_panel_stats,
        em_step_stats,
    )
    from dynamic_factor_models_tpu.ops.linalg import (
        pca_score_np,
        standardize_data,
        standardize_data_np,
    )
    from dynamic_factor_models_tpu.ops.masking import fillz, mask_of
    from dynamic_factor_models_tpu.utils.backend import on_backend

    T, N, r = LARGE_T, LARGE_N, LARGE_R
    x = _synthetic_large_panel(T, N, r, np.float32)

    n_als, n_em = 8, 4

    # init on the host: f0 quality does not affect the timed
    # fixed-iteration program, and the (2048, 4096) device SVD is the
    # single biggest remote-compile surface in the whole bench — it is
    # where the 2026-07-31 live window died
    xh, _, _ = standardize_data_np(x)
    f0_host = pca_score_np(xh, r)

    def run_als(backend, gram_dtype=None):
        with on_backend(backend):
            xj = jnp.asarray(x)
            xstd, _ = standardize_data(xj)
            xz, m = fillz(xstd), mask_of(xstd).astype(xstd.dtype)
            f0 = jnp.asarray(f0_host, xstd.dtype)
            lam_ok = jnp.ones(N, bool)
            args = (xz, m, lam_ok, f0, jnp.float32(0.0), r, n_als)
            run = lambda: _als_core(*args, gram_dtype=gram_dtype)[
                0
            ].block_until_ready()
            run()  # compile
            return _time_fixed_iters(run)

    def run_em(backend, bf16=False):
        with on_backend(backend):
            xj = jnp.asarray(x)
            xstd, _ = standardize_data(xj)
            xz, m = fillz(xstd), mask_of(xstd).astype(xstd.dtype)
            params = SSMParams(
                lam=jnp.zeros((N, r), xz.dtype).at[:, 0].set(1.0),
                R=jnp.ones(N, xz.dtype),
                A=0.5 * jnp.eye(r, dtype=xz.dtype)[None],
                Q=jnp.eye(r, dtype=xz.dtype),
            )

            # the production estimate_dfm_em loop threads loop-invariant
            # PanelStats through every iteration; the bench measures the
            # same per-iteration program (bf16=True: the mixed-precision
            # bulk-phase program — panel GEMMs on bf16 twins)
            stats = compute_panel_stats(xz, m, bf16=bf16)

            def iters():
                p = params
                for _ in range(n_em):
                    p, _ = em_step_stats(p, xz, m, stats)
                return p

            iters().lam.block_until_ready()  # compile
            return _time_fixed_iters(lambda: iters().lam.block_until_ready())

    out = {}

    def _emit(fields):
        out.update(fields)
        if persist is not None:
            persist(dict(out))

    # provenance labels first (ROADMAP item 5 honesty contract, enforced
    # by tools/check_bench_honesty.py): the *_flops_per_sec fields below
    # divide the documented FLOPs model by wall-clock — a proxy off-TPU —
    # and every *_mfu_* field is normalized by the v5e bf16 datasheet peak
    _emit({
        "flop_proxy": not tpu_ok,
        "mfu_peak_source": "v5e_bf16_datasheet",
    })
    als_t = run_als(None) / n_als
    als_flops = als_iter_flops(T, N, r) / als_t
    fields = {
        "als_large_iters_per_sec": round(1.0 / als_t, 2),
        "als_large_flops_per_sec": round(als_flops, 0),
    }
    if tpu_ok:
        fields["als_large_mfu_bf16_peak_pct"] = round(
            100.0 * als_flops / PEAK_FLOPS_V5E_BF16, 2
        )
    _emit(fields)
    em_t = run_em(None) / n_em
    em_flops = em_iter_flops(T, N, r, 1) / em_t
    fields = {
        "em_large_iters_per_sec": round(1.0 / em_t, 2),
        "em_large_flops_per_sec": round(em_flops, 0),
    }
    if tpu_ok:
        fields["em_large_mfu_bf16_peak_pct"] = round(
            100.0 * em_flops / PEAK_FLOPS_V5E_BF16, 2
        )
    _emit(fields)
    if tpu_ok:
        # bf16-Gram ALS iteration (mixed-precision bulk phase): quantifies
        # the HBM-bandwidth option at the flagship size on real hardware
        als_bf16_t = run_als(None, gram_dtype="bfloat16") / n_als
        _emit(
            {
                "als_large_iters_per_sec_bf16": round(1.0 / als_bf16_t, 2),
                "als_large_bf16_speedup_vs_f32": round(als_t / als_bf16_t, 2),
            }
        )
        em_bf16_t = run_em(None, bf16=True) / n_em
        _emit(
            {
                "em_large_iters_per_sec_bf16": round(1.0 / em_bf16_t, 2),
                "em_large_bf16_speedup_vs_f32": round(em_t / em_bf16_t, 2),
            }
        )
        # same programs pinned to the host CPU: the attribution ratio
        als_cpu_t = run_als("cpu") / n_als
        _emit({"als_large_tpu_over_cpu": round(als_cpu_t / als_t, 1)})
        em_cpu_t = run_em("cpu") / n_em
        _emit({"em_large_tpu_over_cpu": round(em_cpu_t / em_t, 1)})
    else:
        _emit(
            {
                "als_large_mfu_bf16_peak_pct": None,
                "em_large_mfu_bf16_peak_pct": None,
                "als_large_tpu_over_cpu": None,
                "em_large_tpu_over_cpu": None,
            }
        )
    return out


def mixed_freq_section():
    """EM iters/sec on the real 672x207 monthly mixed-frequency panel."""
    import numpy as np

    from dynamic_factor_models_tpu.io.cache import cached_monthly_dataset
    from dynamic_factor_models_tpu.models.mixed_freq import estimate_mixed_freq_dfm

    ds = cached_monthly_dataset("All")
    keep = np.asarray(ds.inclcode) == 1
    x = ds.data[:, keep]
    is_q = ds.is_quarterly[keep]
    import jax

    n_iter = 10
    # block on x_hat: the post-EM filter/RTS/x_hat work is dispatched
    # asynchronously, and an un-awaited tail would bleed into the next
    # timing run, deflating the reported iters/sec
    run = lambda: jax.block_until_ready(
        estimate_mixed_freq_dfm(x, is_q, r=4, p=5, max_em_iter=n_iter, tol=0.0).x_hat
    )
    run()  # compile
    dt = _time_fixed_iters(run, n_timing_runs=2)
    return {
        "em_iters_per_sec_mf_monthly": round(n_iter / dt, 2),
        "mf_monthly_panel": list(x.shape),
    }


def chaos_section():
    """Guardrail cost + recovery drills (bench.py --chaos).

    Three measurements on a reference-scale synthetic panel:

    - guard overhead: guarded vs unguarded on-device EM iters/sec at a
      fixed iteration count (acceptance bar: guarded within 5%);
    - program isolation: the unguarded while-loop's stableHLO is
      byte-identical before and after the guarded program compiles and
      runs — guards off means the pre-guardrail program, bit for bit;
    - recovery drills: one estimation per injectable fault kind
      (DFM_FAULTS grammar), each reporting the ladder digest (rungs
      used, final health) and the max |param delta| against the
      uninjected run — transient faults must recover to ~0 delta.

    Prints one JSON line and returns the dict.
    """
    import tempfile

    import jax
    import jax.numpy as jnp
    import numpy as np

    from dynamic_factor_models_tpu.models.emloop import (
        _em_while_jit,
        _fresh_carry,
        run_em_loop,
    )
    from dynamic_factor_models_tpu.models.ssm import (
        SSMParams,
        compute_panel_stats,
        em_step_stats,
    )
    from dynamic_factor_models_tpu.ops.linalg import standardize_data
    from dynamic_factor_models_tpu.ops.masking import fillz, mask_of
    from dynamic_factor_models_tpu.utils import faults
    from dynamic_factor_models_tpu.utils.compile import donation_enabled

    T, N, r, p = 224, 139, 4, 4
    x = _synthetic_large_panel(T, N, r, np.float32)
    xstd, _ = standardize_data(jnp.asarray(x))
    xz, m = fillz(xstd), mask_of(xstd).astype(xstd.dtype)
    params = SSMParams(
        lam=jnp.zeros((N, r), xz.dtype).at[:, 0].set(1.0),
        R=jnp.ones(N, xz.dtype),
        A=jnp.concatenate(
            [0.5 * jnp.eye(r, dtype=xz.dtype)[None],
             jnp.zeros((p - 1, r, r), xz.dtype)]
        ),
        Q=jnp.eye(r, dtype=xz.dtype),
    )
    stats = compute_panel_stats(xz, m)
    args, n_iter = (xz, m, stats), 50

    # the unguarded program, lowered exactly as _run_device_unguarded
    # dispatches it (same statics, same traced operands)
    def _unguarded_hlo():
        tol_arr = jnp.asarray(0.0, jnp.result_type(float))
        carry = _fresh_carry(params, tol_arr, n_iter)
        return _em_while_jit(donation_enabled()).lower(
            em_step_stats, carry, args, tol_arr, n_iter,
            jnp.asarray(n_iter, jnp.int32), 0,
        ).as_text()

    hlo_before = _unguarded_hlo()

    def _ips(guard):
        run = lambda: jax.block_until_ready(
            run_em_loop(em_step_stats, params, args, 0.0, n_iter,
                        guard=guard).params
        )
        run()  # compile
        return n_iter / _time_fixed_iters(run)

    ips_unguarded = _ips(False)
    ips_guarded = _ips(True)
    overhead = ips_unguarded / ips_guarded - 1.0
    hlo_identical = _unguarded_hlo() == hlo_before

    clean = run_em_loop(em_step_stats, params, args, 0.0, n_iter, guard=True)

    def _delta(res):
        return max(
            float(np.max(np.abs(np.asarray(a) - np.asarray(b))))
            for a, b in zip(
                jax.tree.leaves(clean.params), jax.tree.leaves(res.params)
            )
        )

    drills = {}
    for spec in ("nan_estep@5", "chol_fail@5"):
        with faults.inject(spec):
            res = run_em_loop(
                em_step_stats, params, args, 0.0, n_iter, guard=True
            )
        drills[spec] = {
            "n_iter": res.n_iter,
            "final_health": int(res.health),
            "faults_detected": res.faults_detected,
            "recoveries": res.recoveries,
            "rungs_used": list(res.rungs_used),
            "max_param_delta_vs_clean": _delta(res),
        }
    with tempfile.TemporaryDirectory() as td:
        ck = os.path.join(td, "chaos.npz")
        # corrupt the LAST chunk's save (earlier saves would be healed by
        # the atomic rewrite of later chunks before any resume reads them)
        with faults.inject("ckpt_corrupt@5"):
            run_em_loop(em_step_stats, params, args, 0.0, n_iter,
                        guard=True, checkpoint_path=ck, checkpoint_every=10)
        # the corrupted file quarantines on the resume attempt; the run
        # restarts clean and must still match the uninjected result
        res = run_em_loop(em_step_stats, params, args, 0.0, n_iter,
                          guard=True, checkpoint_path=ck, checkpoint_every=10)
        drills["ckpt_corrupt@5"] = {
            "quarantined": os.path.exists(ck + ".corrupt"),
            "max_param_delta_vs_clean": _delta(res),
        }
    with tempfile.TemporaryDirectory() as td:
        ck = os.path.join(td, "chaos.npz")
        try:
            with faults.inject("preempt@2"):
                run_em_loop(em_step_stats, params, args, 0.0, n_iter,
                            guard=True, checkpoint_path=ck,
                            checkpoint_every=10)
            preempted = False
        except faults.SimulatedPreemption:
            preempted = True
        res = run_em_loop(em_step_stats, params, args, 0.0, n_iter,
                          guard=True, checkpoint_path=ck,
                          checkpoint_every=10)
        drills["preempt@2"] = {
            "preempted": preempted,
            "max_param_delta_vs_clean": _delta(res),
        }

    fields = {
        "chaos_panel": [T, N, r, p],
        "em_iters_per_sec_unguarded": round(ips_unguarded, 2),
        "em_iters_per_sec_guarded": round(ips_guarded, 2),
        "em_guard_overhead_frac": round(overhead, 4),
        "em_guard_within_5pct": bool(overhead <= 0.05),
        "em_unguarded_hlo_identical": hlo_identical,
        "chaos_drills": drills,
    }
    print(json.dumps(fields))
    return fields


def serving_section():
    """Multi-tenant serving throughput (bench.py --serving).

    Three fields into the BENCH json (present-but-null when the section
    fails — e.g. gated off-platform):

    - serving_updates_per_sec: O(1) constant-gain online ticks through
      the precompiled executable, timed over a request loop (includes
      per-request dispatch overhead — the number a request loop sees);
    - serving_batched_em_panels_per_sec: B same-bucket tenants refit in
      ONE vmapped guarded EM loop, fixed iteration count;
    - serving_batched_vs_sequential_x: that loop vs the same refits run
      one tenant at a time (acceptance bar on CPU: >= 2x).

    `serving_cpu_count` rides along so the ratio is interpretable:
    batched and sequential refits execute identical FLOPs, so the
    speedup comes from (a) amortizing per-tenant dispatch / while-loop
    overhead and (b) XLA CPU threading the leading batch dimension of
    every gemm/cholesky across cores.  On a single-core host only (a)
    applies and the measured ratio tops out around 1.5-1.8x; the >= 2x
    bar is about (b) and needs >= 2 cores.

    Prints one JSON line and returns the dict.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    import os

    fields = {
        "serving_updates_per_sec": None,
        "serving_batched_em_panels_per_sec": None,
        "serving_batched_vs_sequential_x": None,
        "serving_cpu_count": os.cpu_count(),
    }
    try:
        from dynamic_factor_models_tpu.serving.batch import (
            RefitRequest,
            refit_batch,
            refit_sequential,
        )
        from dynamic_factor_models_tpu.serving.online import (
            FilterState,
            derive_serving_model,
            online_tick,
        )
        from dynamic_factor_models_tpu.models.ssm import SSMParams
        from dynamic_factor_models_tpu.utils.compile import (
            CompileSpec,
            bucket_shape,
            precompile,
        )

        B, T, N, r, p = 8, 64, 16, 4, 4
        n_em = 30
        rng = np.random.default_rng(11)
        dt = jnp.result_type(float)

        def mk_params(scale=1.0):
            return SSMParams(
                lam=jnp.asarray(
                    scale * rng.standard_normal((N, r)), dt
                ),
                R=jnp.ones(N, dt),
                A=jnp.concatenate(
                    [0.5 * jnp.eye(r, dtype=dt)[None],
                     jnp.zeros((p - 1, r, r), dt)]
                ),
                Q=jnp.eye(r, dtype=dt),
            )

        # -- online ticks through the AOT-registered executable --------
        _, n_pad = bucket_shape(T, N)
        precompile(CompileSpec(
            T=T, N=N, r=r, p=p, dtype=str(dt),
            kernels=(), serving_period=1, tick_batch=64,
        ))
        model = derive_serving_model(mk_params(), n_pad=n_pad)
        st = FilterState(
            s=jnp.zeros(r * p, dt), t=jnp.asarray(0, jnp.int32)
        )
        rows = jnp.asarray(rng.standard_normal((64, n_pad)), dt)
        mask_row = np.ones(n_pad, bool)
        st = online_tick(model, st, rows[0], mask_row)  # warm

        n_ticks = 2000

        def tick_loop():
            s = st
            for i in range(n_ticks):
                s = online_tick(model, s, rows[i % 64], mask_row)
            return s

        wall_ticks = _time_fixed_iters(tick_loop)
        fields["serving_updates_per_sec"] = round(n_ticks / wall_ticks, 1)

        # -- batched vs sequential refits ------------------------------
        reqs = []
        for i in range(B):
            true = mk_params()
            f = np.asarray(rng.standard_normal((T, r)).cumsum(0) * 0.3)
            x = f @ np.asarray(true.lam).T + rng.standard_normal((T, N))
            reqs.append(RefitRequest(
                f"tenant{i}",
                jnp.asarray(x, dt),
                jnp.ones((T, N), bool),
                mk_params(scale=0.1),
            ))
        kw = dict(tol=0.0, max_em_iter=n_em)  # fixed-iteration timing
        refit_batch(reqs, **kw)  # compile both programs
        refit_sequential(reqs, **kw)
        wall_b = _time_fixed_iters(lambda: refit_batch(reqs, **kw))
        wall_s = _time_fixed_iters(lambda: refit_sequential(reqs, **kw))
        fields["serving_batched_em_panels_per_sec"] = round(B / wall_b, 2)
        fields["serving_batched_vs_sequential_x"] = round(wall_s / wall_b, 2)
    except Exception as e:  # present-but-null contract
        fields["serving_error"] = f"{type(e).__name__}: {e}"
    print(json.dumps(fields))
    return fields


def chaos_serving_section():
    """Serving-resilience drill (bench.py --chaos-serving).

    Runs a 240-tick single-tenant request stream whose middle third is a
    `tick_nan@1+` fault storm (every computed tick poisoned), then
    measures what the hardened request loop delivered:

    - chaos_serving_typed_response_frac: fraction of requests answered
      with a typed Response envelope rather than an exception
      (acceptance bar: 1.0 — the loop never leaks a traceback);
    - chaos_serving_availability: fraction answered ok or degraded
      (degraded nowcasts from last-good state still count as answered);
    - chaos_serving_degraded_frac: fraction of answers carrying a
      degraded/staleness stamp;
    - chaos_serving_recovery_requests: requests from storm end until the
      first healthy tick (breaker cooldown burn + one reconcile);
    - chaos_serving_recovery_ms: wall time of that reconcile tick (one
      exact refilter folding the whole replay buffer back in; includes
      the XLA compile of the refilter at the recovered panel length, a
      first-encounter cost);
    - chaos_serving_recovery_parity_err: max |state diff| vs a
      never-faulted engine fed the identical stream (bar: <= 1e-10);
    - chaos_serving_envelope_us / chaos_serving_envelope_overhead_frac:
      host cost of the full request envelope (validation, breaker,
      deadline, fault probes, telemetry stamps, history append) per
      tick, measured with the device program stubbed out so the number
      is deterministic (a wall-clock A/B against the bare loop swings
      +-20% with machine load from jax dispatch-queue interaction that
      is not envelope work), divided by the bare online_tick wall time
      from the same run.  Acceptance bar: < 5%;
    - chaos_serving_handle_updates_per_sec: end-to-end eng.handle()
      ticks/s for context (compare serving_updates_per_sec);
    - chaos_serving_worker_failover: availability under worker kill
      (PR 19) — a live OS-process router worker is SIGKILLed mid-way
      through a 40-tick stream; records the typed-response fraction
      (bar: 1.0), availability, survivor-shard availability (bar:
      1.0), supervisor detect latency vs the heartbeat deadline, and
      the measured RTO (detect → respawn → recover → first ack).  The
      same object is read-modify-written into docs/BENCH_load.json
      under ``worker_failover``.

    Prints one JSON line and returns the dict.
    """
    import numpy as np

    fields = {
        "chaos_serving_typed_response_frac": None,
        "chaos_serving_availability": None,
        "chaos_serving_degraded_frac": None,
        "chaos_serving_recovery_requests": None,
        "chaos_serving_recovery_ms": None,
        "chaos_serving_recovery_parity_err": None,
        "chaos_serving_envelope_us": None,
        "chaos_serving_envelope_overhead_frac": None,
        "chaos_serving_handle_updates_per_sec": None,
        "chaos_serving_worker_failover": None,
    }
    try:
        from dynamic_factor_models_tpu.serving.engine import ServingEngine
        from dynamic_factor_models_tpu.serving.online import online_tick
        from dynamic_factor_models_tpu.serving.resilience import (
            Response,
            RetryPolicy,
        )
        from dynamic_factor_models_tpu.utils import faults

        T, N, n_ticks = 64, 16, 240
        rng = np.random.default_rng(17)
        f = rng.standard_normal((T, 4)).cumsum(0) * 0.1
        lam = rng.standard_normal((N, 4))
        panel = f @ lam.T + 0.5 * rng.standard_normal((T, N))
        rows = rng.standard_normal((n_ticks, N))

        policy = RetryPolicy(max_retries=2, backoff_base_s=0.0)
        eng = ServingEngine(retry_policy=policy, max_em_iter=5)
        ref = ServingEngine(retry_policy=policy, max_em_iter=5)
        eng.register("bench", panel)
        ref.register("bench", panel)

        responses = []

        def req(i):
            responses.append(eng.handle(
                {"kind": "tick", "tenant": "bench", "x": rows[i]}
            ))
            responses.append(eng.handle({"kind": "nowcast", "tenant": "bench"}))

        third = n_ticks // 3
        for i in range(third):
            req(i)
        with faults.inject("tick_nan@1+"):
            for i in range(third, 2 * third):
                req(i)
        # recovery: burn the open breaker down with read-only requests,
        # then one reconcile tick folds the whole replay buffer back in
        storm_end = len(responses)
        burns = 0
        while eng._tenants["bench"].breaker.state == "open" and burns < 16:
            responses.append(eng.handle({"kind": "nowcast", "tenant": "bench"}))
            burns += 1
        t0 = time.perf_counter()
        req(2 * third)
        fields["chaos_serving_recovery_ms"] = round(
            1e3 * (time.perf_counter() - t0), 2
        )
        first_ok = next(
            j for j in range(storm_end, len(responses))
            if responses[j].ok and responses[j].kind == "tick"
        )
        fields["chaos_serving_recovery_requests"] = first_ok - storm_end + 1
        for i in range(2 * third + 1, n_ticks):
            req(i)

        typed = sum(isinstance(r, Response) for r in responses)
        answered = sum(r.ok for r in responses if isinstance(r, Response))
        degraded = sum(
            r.degraded for r in responses if isinstance(r, Response)
        )
        fields["chaos_serving_typed_response_frac"] = round(
            typed / len(responses), 4
        )
        fields["chaos_serving_availability"] = round(
            answered / len(responses), 4
        )
        fields["chaos_serving_degraded_frac"] = round(
            degraded / len(responses), 4
        )

        # parity: the identical stream through a never-faulted engine
        for i in range(n_ticks):
            ref.handle({"kind": "tick", "tenant": "bench", "x": rows[i]})
        err = np.max(np.abs(
            np.asarray(eng._tenants["bench"].state.s)
            - np.asarray(ref._tenants["bench"].state.s)
        ))
        fields["chaos_serving_recovery_parity_err"] = float(err)

        # envelope overhead: host cost of the wrapper, device stubbed
        import jax

        import dynamic_factor_models_tpu.serving.engine as _eng_mod

        n_bench = 2000
        eng2 = ServingEngine(max_em_iter=5)
        eng2.register("t", panel)
        ten = eng2._tenants["t"]
        model, st_pin = ten.model, ten.state
        xr = [rows[i % n_ticks] for i in range(n_bench)]

        def handle_loop():
            for i in range(n_bench):
                eng2.handle({"kind": "tick", "tenant": "t", "x": xr[i]})

        def raw_loop():  # fresh arrays per tick, like real traffic
            s = st_pin
            for i in range(n_bench):
                m = np.isfinite(xr[i])
                s = online_tick(model, s, np.where(m, xr[i], 0.0), m)
            return jax.block_until_ready(s)

        raw_loop()
        handle_loop()  # warm both
        wall_r = _time_fixed_iters(raw_loop)
        wall_h = _time_fixed_iters(handle_loop)
        real_tick = _eng_mod.online_tick
        _eng_mod.online_tick = lambda model, state, x, m: st_pin
        try:
            wall_env = _time_fixed_iters(handle_loop)
        finally:
            _eng_mod.online_tick = real_tick
        env_us = 1e6 * wall_env / n_bench
        fields["chaos_serving_envelope_us"] = round(env_us, 1)
        fields["chaos_serving_envelope_overhead_frac"] = round(
            wall_env / wall_r, 4
        )
        fields["chaos_serving_handle_updates_per_sec"] = round(
            n_bench / wall_h, 1
        )

        # --- availability under worker kill (PR 19) ---
        # SIGKILL one live router worker mid-stream: every request must
        # come back typed, the survivor shard must stay at 100%
        # availability, and the supervisor must respawn + recover the
        # victim — the measured detect latency and RTO are the
        # committed failover numbers.
        import tempfile

        from dynamic_factor_models_tpu.serving.router import TenantRouter

        with tempfile.TemporaryDirectory() as td:
            rt = TenantRouter(
                2, store_dir=os.path.join(td, "rt"), backend="process",
            )
            try:
                rt.register_seed("seed", panel)
                ids = [f"w{i}" for i in range(4)]
                for tid in ids:
                    rt.register_shared(tid, "seed")
                for tid in ids:  # warm every shard's tick program
                    r = rt.handle(
                        {"kind": "tick", "tenant": tid, "x": rows[0]}
                    )
                    assert r.ok, r
                rt.rpc_timeout_s, rt.suspect_grace_s = 5.0, 1.0
                n_stream = 40
                kill_at = rt._rpc_no + n_stream // 2
                drill = []
                t0 = time.perf_counter()
                with faults.inject(f"kill_worker@{kill_at}"):
                    for i in range(n_stream):
                        tid = ids[i % len(ids)]
                        drill.append((tid, rt.handle(
                            {"kind": "tick", "tenant": tid,
                             "x": rows[(i + 1) % n_ticks]}
                        )))
                wall_s = time.perf_counter() - t0
                sup = rt.supervisor
                victim = max(
                    range(rt.n_workers), key=lambda w: sup.deaths[w]
                )
                survivors = [
                    r for tid, r in drill if rt.worker_of(tid) != victim
                ]
                typed = sum(isinstance(r, Response) for _, r in drill)
                okd = sum(
                    r.ok for _, r in drill if isinstance(r, Response)
                )
                failover = {
                    "backend": "process",
                    "n_workers": rt.n_workers,
                    "n_requests": n_stream,
                    "typed_response_frac": round(typed / n_stream, 4),
                    "availability": round(okd / n_stream, 4),
                    "survivor_ok_frac": round(
                        sum(r.ok for r in survivors) / len(survivors), 4
                    ),
                    "unavailable_responses": n_stream - okd,
                    "deaths": int(sup.deaths[victim]),
                    "detect_s": (
                        None if sup.detect_s[victim] is None
                        else round(sup.detect_s[victim], 3)
                    ),
                    "heartbeat_deadline_s": (
                        rt.rpc_timeout_s + rt.suspect_grace_s
                    ),
                    "rto_s": (
                        None if sup.rto_s[victim] is None
                        else round(sup.rto_s[victim], 3)
                    ),
                    "drill_wall_s": round(wall_s, 3),
                    "time_unix": round(time.time(), 1),
                }
            finally:
                rt.close()
        fields["chaos_serving_worker_failover"] = failover
        # read-modify-write so --load's full rewrite and this leg can
        # each run without clobbering the other's committed record
        path = os.path.join(REPO, "docs", "BENCH_load.json")
        try:
            with open(path) as fh:
                cur = json.load(fh)
        except Exception:
            cur = {}
        cur["worker_failover"] = failover
        tmp = path + f".tmp.{os.getpid()}"
        with open(tmp, "w") as fh:
            json.dump(cur, fh, indent=1, sort_keys=True)
        os.replace(tmp, path)
    except Exception as e:  # present-but-null contract
        fields["chaos_serving_error"] = f"{type(e).__name__}: {e}"
    print(json.dumps(fields))
    return fields


def load_section(smoke: bool = False):
    """Open-loop mixed-traffic load generator (bench.py --load).

    Drives the serving engine with a 70/24/5/1 tick/nowcast/refit/
    scenario mix at three synthetic-tenant scales (1k / 10k / 100k;
    `--smoke` shrinks to one 50-tenant scale), each probed at three
    offered rates (0.25x / 0.75x / 1.5x of the scale's measured
    closed-loop capacity).  The generator is OPEN-LOOP: request i is
    scheduled at ``t0 + i/rate`` regardless of when request i-1
    finished, and latency is ``completion - scheduled arrival`` — so a
    stalled server keeps accruing offered load and the p99/p99.9 numbers
    include queueing delay (no coordinated omission, the closed-loop
    generator's classic lie).  Registration at scale rides
    `ServingEngine.register_shared` (shared fit + copy-on-append
    history); tenant t0 is reserved for scenarios so its panel length —
    and therefore the compiled fan program — never changes mid-run.

    Per point: p50/p99/p99.9 (utils.histogram.LatencyHistogram, overall
    and per kind), availability (fraction `Response.ok`), and a tick
    SLO (p95 within 250 ms) judged on the open-loop latency via
    utils.slo burn rates.  Acceptance fields:

    - load_slo_green_at_low_load: the tick SLO is green at every
      scale's LOWEST offered rate (bar: true);
    - load_envelope_overhead_frac: instrumented clean-path envelope
      (validation + breaker + telemetry + histogram + trace stamps)
      over the bare online_tick wall, device program stubbed as in
      chaos_serving_section (bar: < 1.05);
    - load_eviction_resident_frac: the EVICTION-PRESSURE leg registers
      100k tenants (200 under --smoke) against a resident budget of 10%
      and drives locality-skewed tick traffic, so the hot working set
      stays resident while the cold tail lives in the snapshot+journal
      store (bar: <= 0.1 + slack for the protected scenario tenant);
    - load_eviction_batched_vs_sequential_x: ticks/sec through the
      continuous-batching submit/flush_period path over the same
      traffic through sequential handle() (bar: >= 1.0 — batching must
      not lose to the PR 12 sequential baseline).

    The eviction leg also records resident bytes, p99 fault-in latency
    (HDR histogram + a fault_in SLO) with the snapshot-load vs
    journal-replay legs split out (``fault_in_load`` /
    ``fault_in_replay``), and whole-process `recover()` timing, all
    nested under ``eviction`` in docs/BENCH_load.json.

    The PREFILL leg (dual-form burst catch-up) crash-restarts engines
    against deep write-ahead journals and times resume() fault-ins with
    the GEMM dual off (DFM_PREFILL=0, sequential replay) vs on:

    - load_prefill_fault_in_speedup_x: off-arm p50 over on-arm p50 at
      journal depth 256 (bar: >= 5 — the replay leg collapses from
      k sequential tick dispatches to one batched GEMM);
    - load_prefill_parity_rel_err: max relative state divergence
      between the arms (bar: <= 1e-5 — f32 serving dtype; the exact
      1e-14/1e-12 parity pins live in tests/test_prefill.py under the
      suite's x64 config).

    Per-arm p50/p99 plus the load/replay split (the fault-in path's
    before/after occupancy attribution) nest under ``prefill`` in
    docs/BENCH_load.json, flop_proxy-labeled on CPU.

    The PIPELINE leg (async pipelined serving) adds three fields:

    - load_pipeline_vs_sequential_x: store-backed tick throughput with
      `ServingPipeline` (thread backstage, round-coalesced fsync
      overlapping the next round's admit+dispatch) over the sequential
      per-request handle() path at saturation (bar: >= 3);
    - load_pipeline_slo_green_at_seq_capacity: the tick SLO judged
      open-loop with the pipeline offered the OFF path's measured
      capacity rate (bar: true — overlap must not trade latency at the
      previous capacity point);
    - load_sharded_m2_x: `TenantRouter` OS-process workers, M=2 over
      M=1 on identical traffic (bar: >= 1.7 on multi-core; a
      single-core container reports the honest ratio).

    Stage-occupancy splits for pipeline-off (batched flush_period) vs
    pipeline-on runs, plus the sharded rows and a `flop_proxy` label on
    CPU, nest under ``pipeline`` in docs/BENCH_load.json.

    Persists docs/BENCH_load.json; prints one JSON line and returns the
    headline dict.
    """
    import numpy as np

    fields = {
        "load_scales": None,
        "load_slo_green_at_low_load": None,
        "load_envelope_us": None,
        "load_envelope_overhead_frac": None,
        "load_eviction_resident_frac": None,
        "load_eviction_batched_vs_sequential_x": None,
        "load_prefill_fault_in_speedup_x": None,
        "load_prefill_parity_rel_err": None,
        "load_pipeline_vs_sequential_x": None,
        "load_pipeline_slo_green_at_seq_capacity": None,
        "load_sharded_m2_x": None,
    }
    out = {"smoke": bool(smoke)}
    try:
        import jax

        import dynamic_factor_models_tpu.serving.engine as _eng_mod
        from dynamic_factor_models_tpu.serving.engine import ServingEngine
        from dynamic_factor_models_tpu.serving.online import online_tick
        from dynamic_factor_models_tpu.utils.histogram import (
            LatencyHistogram,
        )
        from dynamic_factor_models_tpu.utils.slo import SLO

        T, N = 64, 16
        rng = np.random.default_rng(23)
        f = rng.standard_normal((T, 4)).cumsum(0) * 0.1
        lam = rng.standard_normal((N, 4))
        panel = f @ lam.T + 0.5 * rng.standard_normal((T, N))

        scales = [50] if smoke else [1_000, 10_000, 100_000]
        n_req = 200 if smoke else 2_000
        n_burst = 100 if smoke else 400
        mix = {"tick": 0.70, "nowcast": 0.24, "refit": 0.05,
               "scenario": 0.01}
        slo_thresh_s, slo_obj = 0.25, 0.95
        scenario_req = {
            "kind": "scenario", "tenant": "t0",
            "scenario": {"kind": "stress", "horizon": 4,
                         "shocks": np.eye(4)[:1].tolist()},
        }

        def make_stream(rs, n, n_tenants):
            kinds = rs.choice(
                list(mix), size=n, p=list(mix.values())
            )
            reqs = []
            for k in kinds:
                if k == "scenario" or n_tenants == 1:
                    reqs.append(dict(scenario_req) if k == "scenario"
                                else {"kind": k, "tenant": "t0"})
                    if k == "tick" and n_tenants == 1:
                        reqs[-1]["x"] = rs.standard_normal(N)
                    continue
                r = {"kind": k, "tenant": f"t{rs.integers(1, n_tenants)}"}
                if k == "tick":
                    r["x"] = rs.standard_normal(N)
                reqs.append(r)
            return reqs

        def run_point(eng, reqs, rate):
            slo = SLO("tick_p95_250ms", kind="tick",
                      threshold_s=slo_thresh_s, objective=slo_obj)
            hist = LatencyHistogram()
            per_kind = {k: LatencyHistogram() for k in mix}
            n_ok = 0
            t0 = time.perf_counter()
            for i, req in enumerate(reqs):
                sched = t0 + i / rate
                now = time.perf_counter()
                if now < sched:
                    time.sleep(sched - now)
                resp = eng.handle(req)
                lat = time.perf_counter() - sched
                hist.record(lat)
                per_kind[req["kind"]].record(lat)
                if req["kind"] == "tick":
                    slo.observe(lat, resp.ok)
                n_ok += bool(resp.ok)
            wall = time.perf_counter() - t0
            eng._refit_queue.clear()  # refits only queue in this drill
            p = hist.percentiles()
            st = slo.status()
            return {
                "offered_rps": round(rate, 1),
                "achieved_rps": round(len(reqs) / wall, 1),
                "n_requests": len(reqs),
                "availability": round(n_ok / len(reqs), 4),
                "p50_ms": round(p["p50_ms"], 3),
                "p99_ms": round(p["p99_ms"], 3),
                "p999_ms": round(p["p999_ms"], 3),
                "per_kind": {
                    k: {"n": h.n,
                        "p50_ms": round(1e3 * h.quantile(0.5), 3),
                        "p99_ms": round(1e3 * h.quantile(0.99), 3)}
                    for k, h in sorted(per_kind.items()) if h.n
                },
                "slo": st,
                "slo_green": st["green"],
            }

        scale_rows, green_low = [], True
        for n_tenants in scales:
            eng = ServingEngine(max_em_iter=5)
            t_reg0 = time.perf_counter()
            eng.register("t0", panel)
            for i in range(1, n_tenants):
                eng.register_shared(f"t{i}", "t0")
            reg_s = time.perf_counter() - t_reg0
            rs = np.random.default_rng(n_tenants)
            # warm every program in the mix before any timing
            for req in make_stream(rs, 8, n_tenants) + [scenario_req]:
                eng.handle(req)
            burst = make_stream(rs, n_burst, n_tenants)
            tb = time.perf_counter()
            for req in burst:
                eng.handle(req)
            cap_rps = n_burst / (time.perf_counter() - tb)
            eng._refit_queue.clear()
            points = []
            for frac in (0.25, 0.75, 1.5):
                reqs = make_stream(rs, n_req, n_tenants)
                pt = run_point(eng, reqs, frac * cap_rps)
                pt["offered_frac"] = frac
                points.append(pt)
            green_low = green_low and points[0]["slo_green"]
            scale_rows.append({
                "n_tenants": n_tenants,
                "register_s": round(reg_s, 3),
                "capacity_rps": round(cap_rps, 1),
                "points": points,
            })

        # instrumented clean-path envelope, device stubbed (same
        # protocol as chaos_serving_section: wall-clock A/B against the
        # live device program swings with dispatch-queue noise)
        n_bench = 500 if smoke else 2000
        eng2 = ServingEngine(max_em_iter=5)
        eng2.register("t", panel)
        ten = eng2._tenants["t"]
        model, st_pin = ten.model, ten.state
        xr = [rng.standard_normal(N) for _ in range(n_bench)]

        def handle_loop():
            for i in range(n_bench):
                eng2.handle({"kind": "tick", "tenant": "t", "x": xr[i]})

        def raw_loop():
            s = st_pin
            for i in range(n_bench):
                m = np.isfinite(xr[i])
                s = online_tick(model, s, np.where(m, xr[i], 0.0), m)
            return jax.block_until_ready(s)

        raw_loop()
        handle_loop()
        wall_r = _time_fixed_iters(raw_loop)
        real_tick = _eng_mod.online_tick
        _eng_mod.online_tick = lambda model, state, x, m: st_pin
        try:
            wall_env = _time_fixed_iters(handle_loop)
        finally:
            _eng_mod.online_tick = real_tick

        # -- eviction-pressure leg (PR 13) ------------------------------
        # 100k registered tenants, resident budget 10%, locality-skewed
        # traffic: the hot set stays resident, the cold tail faults in
        # through snapshot + journal replay.  Batched admission
        # (submit/flush_period) races sequential handle() on identical
        # traffic shapes; a fresh engine then times whole-process
        # recover() against the populated store.
        import shutil
        import tempfile

        n_ev = 200 if smoke else 100_000
        ev_budget = max(4, n_ev // 10)
        n_ev_req = 400 if smoke else 4_000
        flush_lanes = 64  # submissions coalesced per serving period
        ev_dir = tempfile.mkdtemp(prefix="dfm-bench-evict-")
        try:
            fault_slo = SLO("fault_in_p99_250ms", kind="fault_in",
                            threshold_s=0.25, objective=0.99)
            ev_eng = ServingEngine(
                max_em_iter=5, store_dir=ev_dir,
                resident_tenants=ev_budget, slos=[fault_slo],
            )
            t_reg0 = time.perf_counter()
            ev_eng.register("e0", panel)
            for i in range(1, n_ev):
                ev_eng.register_shared(f"e{i}", "e0")
            ev_reg_s = time.perf_counter() - t_reg0

            rs = np.random.default_rng(13)
            hot = max(2, ev_budget // 2)

            def ev_stream(n):
                ids = np.where(
                    rs.random(n) < 0.8,
                    rs.integers(0, hot, size=n),
                    rs.integers(0, n_ev, size=n),
                )
                return [
                    {"kind": "tick", "tenant": f"e{j}",
                     "x": rs.standard_normal(N)}
                    for j in ids
                ]

            for req in ev_stream(32):  # warm tick + fault-in programs
                ev_eng.handle(req)
            for req in ev_stream(flush_lanes):  # warm the batched kernel
                ev_eng.submit(req)
            ev_eng.flush_period()

            # both admission paths race the SAME request list — the
            # fault-in count under an LRU budget is sensitive to the
            # exact id sequence, so distinct random streams would
            # measure stream luck, not admission overhead
            race_reqs = ev_stream(n_ev_req)
            t_seq = time.perf_counter()
            for req in race_reqs:
                ev_eng.handle(req)
            seq_rps = n_ev_req / (time.perf_counter() - t_seq)

            t_bat = time.perf_counter()
            for i, req in enumerate(race_reqs):
                ev_eng.submit(req)
                if (i + 1) % flush_lanes == 0:
                    ev_eng.flush_period()
            ev_eng.flush_period()
            bat_rps = n_ev_req / (time.perf_counter() - t_bat)

            def _hist_ms(h):
                return None if h is None or h.n == 0 else {
                    "n": h.n,
                    "p50_ms": round(1e3 * h.quantile(0.5), 3),
                    "p99_ms": round(1e3 * h.quantile(0.99), 3),
                }

            fi_hist = ev_eng._lat_hists.get(("fault_in", "ok"))
            resident = len(ev_eng._tenants)
            resident_bytes = ev_eng._resident_nbytes

            rec_eng = ServingEngine(
                max_em_iter=5, store_dir=ev_dir,
                resident_tenants=ev_budget,
            )
            rec_info = rec_eng.recover(prewarm=min(ev_budget, 64))

            fields["load_eviction_resident_frac"] = round(
                resident / n_ev, 4
            )
            fields["load_eviction_batched_vs_sequential_x"] = round(
                bat_rps / seq_rps, 3
            )
            out["eviction"] = {
                "n_tenants": n_ev,
                "resident_budget": ev_budget,
                "register_s": round(ev_reg_s, 3),
                "resident_tenants": resident,
                "resident_bytes": int(resident_bytes),
                "sequential_rps": round(seq_rps, 1),
                "batched_rps": round(bat_rps, 1),
                "flush_lanes": flush_lanes,
                "fault_in": _hist_ms(fi_hist),
                # split timers: eviction persists the snapshot BEFORE
                # demoting, so this leg's fault-ins replay ~zero journal
                # rows — the split makes that visible (load dominates)
                # instead of blaming the replay path for the whole cost
                "fault_in_load": _hist_ms(
                    ev_eng._lat_hists.get(("fault_in_load", "ok"))
                ),
                "fault_in_replay": _hist_ms(
                    ev_eng._lat_hists.get(("fault_in_replay", "ok"))
                ),
                "fault_in_slo": fault_slo.status(),
                "recover": {
                    k: (round(v, 3) if isinstance(v, float) else v)
                    for k, v in rec_info.items()
                },
            }
        finally:
            shutil.rmtree(ev_dir, ignore_errors=True)

        # -- prefill A/B: crash-restart fault-in over deep journals -----
        # The eviction leg above replays ~zero journal rows per
        # fault-in (evict persists first), so the replay-bound path is
        # the CRASH restart: kill without evicting and the write-ahead
        # journal holds every tick since the last snapshot.  Seed n_pf
        # tenants with `pf_depth` journaled ticks, drop the engine
        # un-evicted, then time resume() on fresh engines with the GEMM
        # dual disabled (DFM_PREFILL=0 — the sequential before-arm) vs
        # enabled (after-arm).  The first resume of each arm warms that
        # arm's replay program so the split measures steady-state
        # fault-ins, not XLA compiles; the load/replay p50s per arm are
        # the before/after occupancy split of the fault-in path.
        pf_depth = 256  # the acceptance depth; cheap even under --smoke
        n_pf = 6 if smoke else 24
        pf_dir = tempfile.mkdtemp(prefix="dfm-bench-prefill-")
        try:
            seed_eng = ServingEngine(max_em_iter=5, store_dir=pf_dir)
            seed_eng.register("p0", panel)
            for i in range(1, n_pf):
                seed_eng.register_shared(f"p{i}", "p0")
            rs3 = np.random.default_rng(17)
            for i in range(n_pf):  # one burst block per tenant per flush
                for _ in range(pf_depth):
                    seed_eng.submit({
                        "kind": "tick", "tenant": f"p{i}",
                        "x": rs3.standard_normal(N),
                    })
                seed_eng.flush_period()
            del seed_eng  # "crash": journals stay at pf_depth rows

            from dynamic_factor_models_tpu.utils import telemetry as _ptel

            pf_arms = {}
            pf_states = {}
            for arm in ("off", "on"):
                pf_old = os.environ.pop("DFM_PREFILL", None)
                if arm == "off":
                    os.environ["DFM_PREFILL"] = "0"
                try:
                    # warm this arm's replay program on a THROWAWAY
                    # engine (XLA caches programs process-wide), then
                    # reset the telemetry registry: the latency hists
                    # are GLOBAL (register_hist dedups by name+labels),
                    # so without the reset each arm's load/replay split
                    # would absorb the eviction leg's, the other arm's,
                    # and the warm resume's compile-laden samples;
                    # everything the earlier legs report is already
                    # materialized into `out` by now
                    pf_warm = ServingEngine(
                        max_em_iter=5, store_dir=pf_dir
                    )
                    pf_warm.resume("p0")
                    del pf_warm
                    _ptel.reset()
                    pf_eng = ServingEngine(
                        max_em_iter=5, store_dir=pf_dir
                    )
                    pf_lats = []
                    for i in range(1, n_pf):
                        t1 = time.perf_counter()
                        pf_eng.resume(f"p{i}")
                        pf_lats.append(time.perf_counter() - t1)
                    q50, q99 = np.quantile(pf_lats, [0.5, 0.99])
                    pf_arms[arm] = {
                        "p50_ms": round(1e3 * float(q50), 3),
                        "p99_ms": round(1e3 * float(q99), 3),
                        "split": {
                            "load": _hist_ms(pf_eng._lat_hists.get(
                                ("fault_in_load", "ok"))),
                            "replay": _hist_ms(pf_eng._lat_hists.get(
                                ("fault_in_replay", "ok"))),
                        },
                    }
                    pf_states[arm] = np.asarray(
                        pf_eng._tenants[f"p{n_pf - 1}"].state.s
                    )
                finally:
                    os.environ.pop("DFM_PREFILL", None)
                    if pf_old is not None:
                        os.environ["DFM_PREFILL"] = pf_old
            pf_scale = max(1.0, float(np.max(np.abs(pf_states["off"]))))
            pf_par = float(
                np.max(np.abs(pf_states["on"] - pf_states["off"]))
                / pf_scale
            )
            pf_speed = (
                pf_arms["off"]["p50_ms"] / pf_arms["on"]["p50_ms"]
            )
            fields["load_prefill_fault_in_speedup_x"] = round(
                pf_speed, 2
            )
            fields["load_prefill_parity_rel_err"] = pf_par
            out["prefill"] = {
                "flop_proxy": not _is_tpu_platform(
                    jax.devices()[0].platform
                ),
                "journal_depth": pf_depth,
                "n_tenants": n_pf,
                "before": pf_arms["off"],
                "after": pf_arms["on"],
                "speedup_p50_x": round(pf_speed, 2),
                "parity_rel_err": pf_par,
            }
        finally:
            shutil.rmtree(pf_dir, ignore_errors=True)

        # -- pipeline on/off A/B leg (async pipelined serving) ----------
        # Runs in a CHILD process (the same idiom as --multihost /
        # --composed): the legs above leave up-to-100k-tenant object
        # graphs and a large program cache behind, which drags the
        # allocation-heavy pipelined path and would understate the A/B.
        # The child (`--run-pipeline-ab`) measures sequential handle()
        # vs `ServingPipeline` at saturation, captures the before/after
        # occupancy splits, and judges the tick SLO with the pipeline
        # offered the sequential path's capacity rate.
        from dynamic_factor_models_tpu.serving.router import (
            TenantRouter,
            worker_of,
        )

        pipe_lanes = 64
        ab_args = ["--run-pipeline-ab"] + (["--smoke"] if smoke else [])
        frag = _parse_fragment(
            _run_child(ab_args, timeout_s=600 if smoke else 1800)
        )
        if frag is None:
            out["pipeline"] = {
                "error": "pipeline-ab child produced no JSON"
            }
        else:
            fields["load_pipeline_vs_sequential_x"] = round(
                frag["pipelined_rps"] / frag["sequential_rps"], 3
            )
            fields["load_pipeline_slo_green_at_seq_capacity"] = bool(
                frag["slo_at_seq_capacity"]["green"]
            )
            out["pipeline"] = {
                "flop_proxy": not _is_tpu_platform(
                    jax.devices()[0].platform
                ),
                **frag,
            }

        pipe_dir = tempfile.mkdtemp(prefix="dfm-bench-pipe-")
        try:
            # -- tenant-sharded workers: M=1 vs M=2 OS processes --------
            # spawn workers re-import jax, so this is the slow part of
            # the leg; on a single-core container the M=2 ratio is an
            # honest CPU proxy (reported, labeled, not inflated)
            n_sh = 64 if smoke else 256      # sharded-leg tenants
            n_sr = 256 if smoke else 2048    # sharded-leg requests
            sh_rows = {}
            for m in (1, 2):
                with TenantRouter(
                    m, store_dir=os.path.join(pipe_dir, f"m{m}"),
                    backend="process", pipelined=True,
                    engine_kwargs={"max_em_iter": 5},
                    pipeline_kwargs={"backstage": "thread",
                                     "max_round_lanes": pipe_lanes},
                ) as rt:
                    rt.register_seed("s0", panel)
                    for i in range(1, n_sh):
                        rt.register_shared(f"s{i}", "s0")
                    rs2 = np.random.default_rng(41)
                    # route-aware bucket warm: for every worker and
                    # every lane bucket it can form (rounds hold
                    # DISTINCT tenants, so max round size = owned
                    # count), send exactly b owned tenants so the
                    # bucket-b executable compiles before the timed
                    # region — a cold bucket mid-measurement costs an
                    # XLA compile and swings the ratio 3-4x
                    owned = {
                        w: [t for t in range(n_sh)
                            if worker_of(f"s{t}", m) == w]
                        for w in range(m)
                    }
                    b = 1
                    while b <= pipe_lanes:
                        rt.submit([
                            {"kind": "tick", "tenant": f"s{t}",
                             "x": rs2.standard_normal(N)}
                            for w in range(m)
                            for t in owned[w][:b]
                        ])
                        rt.flush_all()
                        b *= 2
                    reqs = [
                        {"kind": "tick", "tenant": f"s{j}",
                         "x": rs2.standard_normal(N)}
                        for j in rs2.integers(0, n_sh, size=n_sr)
                    ]
                    t0 = time.perf_counter()
                    for i in range(0, n_sr, pipe_lanes * m):
                        rt.submit(reqs[i:i + pipe_lanes * m])
                        rt.flush_all()
                    sh_rows[m] = n_sr / (time.perf_counter() - t0)
            fields["load_sharded_m2_x"] = round(
                sh_rows[2] / sh_rows[1], 3
            )
            out["pipeline"]["sharded"] = {
                "cpu_count": os.cpu_count(),
                "n_tenants": n_sh,
                "n_requests": n_sr,
                "m1_rps": round(sh_rows[1], 1),
                "m2_rps": round(sh_rows[2], 1),
            }
        finally:
            shutil.rmtree(pipe_dir, ignore_errors=True)

        fields["load_scales"] = [s["n_tenants"] for s in scale_rows]
        fields["load_slo_green_at_low_load"] = bool(green_low)
        fields["load_envelope_us"] = round(1e6 * wall_env / n_bench, 1)
        fields["load_envelope_overhead_frac"] = round(wall_env / wall_r, 4)
        out.update({
            "time_unix": round(time.time(), 1),
            # root-scope label: every throughput/speedup figure in this
            # record is wall-clock on the recording platform (the
            # honesty checker's speedup rule keys off this)
            "flop_proxy": not _is_tpu_platform(jax.devices()[0].platform),
            "mix": mix,
            "slo": {"kind": "tick", "threshold_s": slo_thresh_s,
                    "objective": slo_obj},
            "scales": scale_rows,
            **fields,
        })
        path = os.path.join(REPO, "docs", "BENCH_load.json")
        try:  # --chaos-serving owns this key: carry it across rewrites
            with open(path) as fh:
                prev = json.load(fh)
            if "worker_failover" in prev:
                out.setdefault("worker_failover", prev["worker_failover"])
        except Exception:
            pass
        tmp = path + ".tmp"
        with open(tmp, "w") as fh:
            json.dump(out, fh, indent=1, sort_keys=True)
        os.replace(tmp, path)
    except Exception as e:  # present-but-null contract
        fields["load_error"] = f"{type(e).__name__}: {e}"
    print(json.dumps(fields), flush=True)
    return fields


def run_pipeline_ab(smoke: bool = False):
    """Child leg for the pipeline on/off A/B (``--run-pipeline-ab``).

    Runs in its own fresh interpreter (spawned by load_section through
    `_run_child`) so the measurement is not dragged by the 100k-tenant
    object graphs and program caches the earlier load legs leave in the
    parent.  Tick-only store-backed traffic at saturation: OFF is the
    per-request handle() path (one journal fsync per tick), ON is
    `ServingPipeline` with the thread backstage (round-coalesced fsync
    overlapping the next round's admit+dispatch).  The occupancy splits
    re-run shorter with telemetry enabled so the before/after stage
    attribution lands in the json; wall-clock numbers come from the
    untelemetered runs.  Prints ONE json line:

        {sequential_rps, pipelined_rps, pipelined_availability,
         occupancy_s: {off, on}, slo_at_seq_capacity, n_tenants,
         n_requests, round_lanes}
    """
    import shutil
    import tempfile

    import numpy as np

    from dynamic_factor_models_tpu.serving.engine import ServingEngine
    from dynamic_factor_models_tpu.serving.pipeline import ServingPipeline
    from dynamic_factor_models_tpu.utils import telemetry as _tel
    from dynamic_factor_models_tpu.utils.slo import SLO

    T, N = 64, 16
    rng = np.random.default_rng(23)
    f = rng.standard_normal((T, 4)).cumsum(0) * 0.1
    lam = rng.standard_normal((N, 4))
    panel = f @ lam.T + 0.5 * rng.standard_normal((T, N))
    slo_thresh_s, slo_obj = 0.25, 0.95

    n_pt = 64 if smoke else 512          # pipeline-leg tenants
    n_pr = 512 if smoke else 4096        # pipeline-leg requests
    pipe_lanes = 64
    pipe_dir = tempfile.mkdtemp(prefix="dfm-bench-pipe-ab-")
    try:
        def _pipe_engine(sub):
            e = ServingEngine(
                max_em_iter=5,
                store_dir=os.path.join(pipe_dir, sub),
            )
            e.register("p0", panel)
            for i in range(1, n_pt):
                e.register_shared(f"p{i}", "p0")
            return e

        rs = np.random.default_rng(31)

        def pipe_stream(n):
            ids = rs.integers(0, n_pt, size=n)
            return [
                {"kind": "tick", "tenant": f"p{j}",
                 "x": rs.standard_normal(N)}
                for j in ids
            ]

        def run_sequential(eng, reqs):
            t0 = time.perf_counter()
            for req in reqs:
                eng.handle(req)
            return len(reqs) / (time.perf_counter() - t0)

        def warm_buckets(submit, flush):
            # Compile every lane bucket the round former can produce
            # BEFORE the timed region: per-tenant dedup and drain tails
            # make round sizes data-dependent, so any cold bucket means
            # an XLA compile lands mid-measurement (observed as a 3-4x
            # rps swing between otherwise identical runs).  b distinct
            # tenants -> one round padded to exactly bucket b.
            b = 1
            while b <= pipe_lanes:
                for j in range(min(b, n_pt)):
                    submit({"kind": "tick", "tenant": f"p{j}",
                            "x": rs.standard_normal(N)})
                flush()
                b *= 2

        def run_pipelined(eng, reqs, warm=True):
            with ServingPipeline(
                eng, backstage="thread", max_round_lanes=pipe_lanes,
            ) as pipe:
                if warm:
                    warm_buckets(pipe.submit,
                                 lambda: (pipe.pump(), pipe.drain()))
                t0 = time.perf_counter()
                for i, req in enumerate(reqs):
                    pipe.submit(req)
                    if (i + 1) % pipe_lanes == 0:
                        pipe.pump()
                out_r = pipe.drain()
                wall = time.perf_counter() - t0
            n_ok = sum(bool(r.ok) for r in out_r)
            return len(reqs) / wall, n_ok / max(1, len(out_r))

        seq_eng = _pipe_engine("seq")
        for req in pipe_stream(32):  # warm tick + journal programs
            seq_eng.handle(req)
        seq_rps = run_sequential(seq_eng, pipe_stream(n_pr))

        on_eng = _pipe_engine("on")
        pipe_rps, pipe_avail = run_pipelined(on_eng, pipe_stream(n_pr))

        # occupancy splits, telemetry on, shorter run: "off" is the
        # batched submit/flush_period attribution (the pre-pipeline
        # serving path), "on" is the pipelined round attribution with
        # its admit phase and envelope overlap
        def occ_split(run):
            # warm (and reset the attribution) before enabling
            # telemetry so the splits describe steady-state rounds, not
            # bucket compiles
            eng = _pipe_engine(f"occ-{run}")
            occ_sink = os.path.join(pipe_dir, f"occ-{run}.jsonl")
            reqs = pipe_stream(max(pipe_lanes, n_pr // 4))
            if run == "on":
                with ServingPipeline(
                    eng, backstage="thread",
                    max_round_lanes=pipe_lanes,
                ) as pipe:
                    warm_buckets(pipe.submit,
                                 lambda: (pipe.pump(), pipe.drain()))
                    eng._occ_s.clear()
                    _tel.enable(sink=occ_sink)
                    try:
                        for i, req in enumerate(reqs):
                            pipe.submit(req)
                            if (i + 1) % pipe_lanes == 0:
                                pipe.pump()
                        pipe.drain()
                    finally:
                        _tel.disable()
            else:
                warm_buckets(eng.submit, eng.flush_period)
                eng._occ_s.clear()
                _tel.enable(sink=occ_sink)
                try:
                    for i, req in enumerate(reqs):
                        eng.submit(req)
                        if (i + 1) % pipe_lanes == 0:
                            eng.flush_period()
                    eng.flush_period()
                finally:
                    _tel.disable()
            return {
                k: round(v, 6)
                for k, v in sorted(eng._occ_s.items())
            }

        occ_off = occ_split("off")
        occ_on = occ_split("on")

        # SLO at the previous capacity point: offer the pipelined
        # engine the OFF path's measured saturation rate open-loop;
        # the acceptance bar is the tick SLO staying green there
        slo_eng = _pipe_engine("slo")
        pipe_slo = SLO("tick_p95_250ms", kind="tick",
                       threshold_s=slo_thresh_s, objective=slo_obj)
        with ServingPipeline(
            slo_eng, backstage="thread", max_round_lanes=pipe_lanes,
        ) as pipe:
            warm_buckets(pipe.submit,
                         lambda: (pipe.pump(), pipe.drain()))
            reqs = pipe_stream(n_pr // 2)
            # pump eagerly (quarter-rounds): at the offered rate a
            # full 64-lane round takes ~lanes/rate to even FORM —
            # latency at fixed capacity is round depth, so the
            # latency-sensitive point trades bucket size for it
            slo_chunk = max(8, pipe_lanes // 4)
            sched = {}
            t0 = time.perf_counter()
            for i, req in enumerate(reqs):
                at = t0 + i / seq_rps
                now = time.perf_counter()
                if now < at:
                    time.sleep(at - now)
                sched[pipe.submit(req)] = at
                if (i + 1) % slo_chunk == 0:
                    pipe.pump()
                    now = time.perf_counter()
                    for r in pipe.poll():
                        pipe_slo.observe(now - sched.pop(min(sched)),
                                         r.ok)
            out_r = pipe.drain()
            now = time.perf_counter()
            for r in out_r:
                pipe_slo.observe(now - sched.pop(min(sched)), r.ok)

        print(json.dumps({
            "n_tenants": n_pt,
            "n_requests": n_pr,
            "round_lanes": pipe_lanes,
            "sequential_rps": round(seq_rps, 1),
            "pipelined_rps": round(pipe_rps, 1),
            "pipelined_availability": round(pipe_avail, 4),
            "occupancy_s": {"off": occ_off, "on": occ_on},
            "slo_at_seq_capacity": pipe_slo.status(),
        }), flush=True)
    finally:
        shutil.rmtree(pipe_dir, ignore_errors=True)


def scenarios_section():
    """Scenario-engine throughput (bench.py --scenarios).

    Four fields into the BENCH json (present-but-null when the section
    fails):

    - scenario_draws_per_sec_1k / _10k: posterior-predictive forward
      simulations through the vmapped "scenario_fan" kernel
      (scenarios/fanout.forecast_fan — the posterior_forecast program)
      at 1k and 10k parameter draws;
    - scenario_chains_per_sec: guarded multi-chain Gibbs
      (scenarios/gibbs.sample_chains), 4 chains in one
      scan-outside/vmap-inside program;
    - scenario_vs_sequential_x: the 1k-draw vmapped fan vs the same 1k
      draws dispatched one at a time from a Python loop (acceptance
      bar: >= 3x — the fan amortizes per-dispatch overhead and lets
      XLA thread the draw axis).

    Prints one JSON line and returns the dict.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    fields = {
        "scenario_draws_per_sec_1k": None,
        "scenario_draws_per_sec_10k": None,
        "scenario_chains_per_sec": None,
        "scenario_vs_sequential_x": None,
    }
    try:
        from dynamic_factor_models_tpu.models.bayes import BayesPriors
        from dynamic_factor_models_tpu.models.ssm import SSMParams
        from dynamic_factor_models_tpu.scenarios.fanout import (
            _forecast_fan_impl,
        )
        from dynamic_factor_models_tpu.scenarios.gibbs import sample_chains

        T, N, r, p, h = 64, 16, 4, 4, 12
        k = r * p
        rng = np.random.default_rng(17)
        dt = jnp.result_type(float)
        params = SSMParams(
            lam=jnp.asarray(rng.standard_normal((N, r)), dt),
            R=jnp.ones(N, dt),
            A=jnp.concatenate(
                [0.5 * jnp.eye(r, dtype=dt)[None],
                 jnp.zeros((p - 1, r, r), dt)]
            ),
            Q=jnp.eye(r, dtype=dt),
        )

        # -- vmapped forward-simulation fans ---------------------------
        def fan_args(D):
            stk = lambda a: jnp.broadcast_to(a, (D,) + a.shape)  # noqa: E731
            return (
                stk(params.lam), stk(params.R), stk(params.A),
                stk(params.Q), jnp.zeros((D, k), dt),
                jax.random.split(jax.random.PRNGKey(3), D),
            )

        walls = {}
        for D, name in (
            (1_000, "scenario_draws_per_sec_1k"),
            (10_000, "scenario_draws_per_sec_10k"),
        ):
            args = fan_args(D)
            jax.block_until_ready(
                _forecast_fan_impl(*args, horizon=h)
            )  # compile
            walls[D] = _time_fixed_iters(lambda: jax.block_until_ready(
                _forecast_fan_impl(*args, horizon=h)
            ))
            fields[name] = round(D / walls[D], 1)

        # -- the same 1k draws, one Python dispatch per draw -----------
        D = 1_000
        args1k = fan_args(D)
        one = tuple(a[:1] for a in args1k)
        jax.block_until_ready(_forecast_fan_impl(*one, horizon=h))

        def seq_loop():
            for i in range(D):
                jax.block_until_ready(_forecast_fan_impl(
                    *(a[i:i + 1] for a in args1k), horizon=h
                ))

        wall_seq = _time_fixed_iters(seq_loop, n_timing_runs=2)
        fields["scenario_vs_sequential_x"] = round(wall_seq / walls[D], 2)

        # -- guarded multi-chain Gibbs ---------------------------------
        C, n_burn, n_keep = 4, 30, 30
        f = np.asarray(rng.standard_normal((T, r)).cumsum(0) * 0.3)
        x = f @ np.asarray(params.lam).T + rng.standard_normal((T, N))
        xz = jnp.asarray((x - x.mean(0)) / x.std(0), dt)
        m = jnp.ones((T, N), dt)
        pr = BayesPriors()
        prior_t = (
            float(pr.lam_scale), float(pr.r_shape), float(pr.r_rate),
            float(pr.q_df_extra), float(pr.q_scale),
        )
        keys = jax.random.split(jax.random.PRNGKey(5), C)
        kw = dict(n_burn=n_burn, n_keep=n_keep, thin=1, p=p,
                  priors=prior_t)
        sample_chains(keys, params, xz, m, **kw)  # compile
        wall_g = _time_fixed_iters(
            lambda: sample_chains(keys, params, xz, m, **kw),
            n_timing_runs=2,
        )
        fields["scenario_chains_per_sec"] = round(C / wall_g, 2)
    except Exception as e:  # present-but-null contract
        fields["scenario_error"] = f"{type(e).__name__}: {e}"
    print(json.dumps(fields))
    return fields


def scenarios_nl_section(smoke: bool = False):
    """Particle-filter scenario throughput (bench.py --scenarios-nl).

    Fields into docs/BENCH_scenarios_nl.json (present-but-null when the
    section fails):

    - smc_particle_steps_per_sec: {model: {P: particles*steps*lanes /
      sec}} for the lg and sv particle filters at P in {1k, 10k} with 8
      vmapped scenario lanes (--smoke: P=256, 2 lanes) — the
      scan-outside/vmap-inside program through the production
      `smc_filter` entry;
    - smc_vs_looped_x: the vmapped multi-lane filter vs the same pure
      kernels (propose / weight+normalize / adaptive-resample) dispatched
      individually from a Python loop over lanes and steps — the
      composition the one-scan program replaces.  Measured at P=256 (the
      tier-1 fast-lane particle count), where per-kernel compute is small
      and host dispatch dominates — exactly the regime the fused scan
      exists for (acceptance bar: >= 10x on CPU; at P >= 1k the kernels
      are compute-bound and the ratio honestly shrinks to ~4x);
    - smc_ess_trip_rate: fraction of (lane, step) pairs whose ESS fell
      below the 0.5*P floor and triggered a systematic resample.

    Persists docs/BENCH_scenarios_nl.json, prints one JSON line and
    returns the dict.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    fields = {
        "smc_particle_steps_per_sec": None,
        "smc_vs_looped_x": None,
        "smc_ess_trip_rate": None,
        "smc_smoke": bool(smoke),
    }
    try:
        from dynamic_factor_models_tpu.models.ssm import SSMParams
        from dynamic_factor_models_tpu.scenarios import particles as pk
        from dynamic_factor_models_tpu.scenarios import smc as smc_mod

        T, N, r = 64, 16, 4
        S = 2 if smoke else 8
        plist = (256,) if smoke else (1_000, 10_000)
        rng = np.random.default_rng(23)
        dt = jnp.result_type(float)
        lam = rng.standard_normal((N, r))
        params = SSMParams(
            lam=jnp.asarray(lam, dt),
            R=jnp.ones(N, dt),
            A=0.5 * jnp.eye(r, dtype=dt)[None],
            Q=jnp.eye(r, dtype=dt),
        )
        f = np.zeros((T, r))
        for t in range(1, T):
            f[t] = 0.5 * f[t - 1] + rng.standard_normal(r)
        x = f @ lam.T + 0.5 * rng.standard_normal((T, N))
        aux_sv = (jnp.zeros(r, dt), jnp.full((r,), 0.95, dt),
                  jnp.full((r,), 0.2, dt))

        thr: dict = {}
        trips: dict = {}
        for model, aux in (("lg", ()), ("sv", aux_sv)):
            thr[model] = {}
            for P in plist:
                kw = dict(model=model, aux=aux, n_particles=P, n_lanes=S)
                res = smc_mod.smc_filter(params, x, **kw)  # compile
                wall = _time_fixed_iters(lambda: jax.block_until_ready(
                    smc_mod.smc_filter(params, x, **kw).summary
                ))
                thr[model][str(P)] = round(P * T * S / wall, 1)
            trips[model] = round(float(np.asarray(res.resampled).mean()), 4)
        fields["smc_particle_steps_per_sec"] = thr
        fields["smc_ess_trip_rate"] = trips

        # -- the same lg filter, the pure kernels dispatched one at a
        # time from Python over lanes and steps: the composition style
        # the single scan-outside/vmap-inside program replaces.  P=256
        # is the dispatch-dominated fast-lane size the bar targets.
        Pb = 256
        pm = smc_mod._lg_model(params, (), Pb)
        yz = jnp.asarray(np.nan_to_num(x), dt)
        mk = jnp.ones((T, N), dt)

        propose_j = jax.jit(lambda k, p_: pm.propose(k, p_, 0))
        weight_j = jax.jit(lambda lw, p_, y, m: pk.normalize_logw(
            lw + pm.log_obs(p_, y, m, 0)
        ))
        resample_j = jax.jit(
            lambda k, p_, lw: pk.adaptive_resample(k, p_, lw, 0.5)
        )
        split_j = jax.jit(lambda k: jax.random.split(k, 3))
        lw0 = jnp.full((Pb,), -np.log(Pb), dt)

        def looped():
            for s in range(S):
                key = jax.random.PRNGKey(s)
                parts = pm.init(key)
                logw = lw0
                for t in range(T):
                    key, k1, k2 = split_j(key)
                    parts = propose_j(k1, parts)
                    logw, _ = weight_j(logw, parts, yz[t], mk[t])
                    parts, logw, _, _ = resample_j(k2, parts, logw)
            jax.block_until_ready(logw)

        looped()  # compile
        wall_loop = _time_fixed_iters(looped, n_timing_runs=2)
        kw = dict(model="lg", n_particles=Pb, n_lanes=S)
        smc_mod.smc_filter(params, x, **kw)
        wall_vmap = _time_fixed_iters(lambda: jax.block_until_ready(
            smc_mod.smc_filter(params, x, **kw).summary
        ))
        fields["smc_vs_looped_x"] = round(wall_loop / wall_vmap, 1)

        out = {"time_unix": round(time.time(), 1), "T": T, "N": N, "r": r,
               "lanes": S, **fields}
        path = os.path.join(REPO, "docs", "BENCH_scenarios_nl.json")
        tmp = path + ".tmp"
        with open(tmp, "w") as fh:
            json.dump(out, fh, indent=1, sort_keys=True)
        os.replace(tmp, path)
    except Exception as e:  # present-but-null contract
        fields["scenarios_nl_error"] = f"{type(e).__name__}: {e}"
    print(json.dumps(fields), flush=True)
    return fields


def chaos_preempt_drill():
    """One injected-preemption resume (bench.py --chaos-preempt-drill).

    A small-panel cut of chaos_section's preempt drill, sized for a
    scarce live TPU window: kill a checkpointed EM run right after its
    second chunk save, resume from the surviving checkpoint, and report
    whether the resumed parameters are bit-identical to an unkilled run.
    tools/tpu_watch.sh appends this JSON digest to its probe log once
    per live window.
    """
    import tempfile

    import jax
    import jax.numpy as jnp
    import numpy as np

    from dynamic_factor_models_tpu.models.emloop import run_em_loop
    from dynamic_factor_models_tpu.models.ssm import (
        SSMParams,
        compute_panel_stats,
        em_step_stats,
    )
    from dynamic_factor_models_tpu.ops.linalg import standardize_data
    from dynamic_factor_models_tpu.ops.masking import fillz, mask_of
    from dynamic_factor_models_tpu.utils import faults

    T, N, r, p = 64, 24, 2, 1
    x = _synthetic_large_panel(T, N, r, np.float32)
    xstd, _ = standardize_data(jnp.asarray(x))
    xz, m = fillz(xstd), mask_of(xstd).astype(xstd.dtype)
    params = SSMParams(
        lam=jnp.zeros((N, r), xz.dtype).at[:, 0].set(1.0),
        R=jnp.ones(N, xz.dtype),
        A=0.5 * jnp.eye(r, dtype=xz.dtype)[None],
        Q=jnp.eye(r, dtype=xz.dtype),
    )
    stats = compute_panel_stats(xz, m)
    args, n_iter = (xz, m, stats), 20

    clean = run_em_loop(em_step_stats, params, args, 0.0, n_iter, guard=True)
    with tempfile.TemporaryDirectory() as td:
        ck = os.path.join(td, "preempt.npz")
        try:
            with faults.inject("preempt@2"):
                run_em_loop(em_step_stats, params, args, 0.0, n_iter,
                            guard=True, checkpoint_path=ck,
                            checkpoint_every=5)
            preempted = False
        except faults.SimulatedPreemption:
            preempted = True
        res = run_em_loop(em_step_stats, params, args, 0.0, n_iter,
                          guard=True, checkpoint_path=ck,
                          checkpoint_every=5)
    delta = max(
        float(np.max(np.abs(np.asarray(a) - np.asarray(b))))
        for a, b in zip(
            jax.tree.leaves(clean.params), jax.tree.leaves(res.params)
        )
    )
    fields = {
        "preempt_panel": [T, N, r, p],
        "preempted": preempted,
        "resumed_n_iter": res.n_iter,
        "final_health": int(res.health),
        "max_param_delta_vs_unkilled": delta,
        "resume_bit_identical": bool(preempted and delta == 0.0),
    }
    print(json.dumps(fields))
    return fields


def steady_section(xz, m, params, stats, em_ips_seq, n_dev_iter=100):
    """Steady-state fast-path EM throughput (models/steady.py).

    Tries the real panel first; its interior/trailing missingness gates the
    fast path off (`ssm._steady_plan` returns None — only ragged HEADS are
    compatible with a converged constant-gain tail), so the measured leg is
    a reference-scale complete-tail synthetic panel (T=224, N=139, ragged
    heads on a third of the series), with `method="sequential"` re-timed on
    the SAME panel so the speedup ratio is apples-to-apples.  All keys stay
    present-but-null when the fast path is gated off everywhere, keeping
    BENCH JSON schemas comparable across rounds.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    from dynamic_factor_models_tpu.models.emloop import run_em_loop
    from dynamic_factor_models_tpu.models.ssm import (
        SteadyEMState,
        _steady_block_for,
        _steady_plan,
        _steady_step_for,
        em_step_stats,
    )

    fields = {
        "em_iters_per_sec_steady": None,
        "em_iters_per_sec_steady_baseline": None,
        "em_steady_speedup": None,
        "riccati_doubling_iters": None,
        "steady_tail_frac": None,
        "steady_t_star": None,
        "steady_bench_panel": None,
    }

    def _try(pxz, pm, pparams, pstats, label):
        plan = _steady_plan(pparams, np.asarray(pm, bool))
        if plan is None:
            return None
        t_star, st0, _rho = plan
        T0 = pxz.shape[0]
        block = _steady_block_for(T0 - t_star)
        step = _steady_step_for(t_star, block)
        carry0 = SteadyEMState(
            pparams,
            jnp.asarray(st0.Pp, pxz.dtype),
            jnp.asarray(0, jnp.int32),
        )
        args = (pxz, pm, pstats)
        run_em_loop(step, carry0, args, 0.0, n_dev_iter)  # compile
        t1 = time.perf_counter()
        out, _, n_ran, _ = run_em_loop(step, carry0, args, 0.0, n_dev_iter)
        ips = n_ran / (time.perf_counter() - t1)
        fields.update(
            {
                "em_iters_per_sec_steady": round(ips, 2),
                "riccati_doubling_iters": round(
                    int(out.riccati_iters) / max(n_ran, 1), 2
                ),
                "steady_tail_frac": round((T0 - t_star) / T0, 4),
                "steady_t_star": int(t_star),
                "steady_bench_panel": label,
            }
        )
        return ips

    ips = _try(xz, m, params, stats, "real")
    if ips is not None:
        fields["em_iters_per_sec_steady_baseline"] = round(em_ips_seq, 2)
        fields["em_steady_speedup"] = round(ips / em_ips_seq, 2)
        return fields

    # synthetic reference-scale complete-tail panel (BASELINE pca_real
    # dims), sequential re-timed on the same panel for an honest ratio
    from dynamic_factor_models_tpu.models.ssm import (
        SSMParams,
        compute_panel_stats,
    )

    rng = np.random.default_rng(0)
    T, N, r, p = 224, 139, 4, 4
    dt_ = xz.dtype
    f = np.zeros((T + 8, r))
    for t in range(1, T + 8):
        f[t] = 0.6 * f[t - 1] + rng.standard_normal(r)
    lam_true = rng.standard_normal((N, r))
    xs = f[8:] @ lam_true.T + rng.standard_normal((T, N))
    ms = np.ones((T, N), bool)
    for i in range(N // 3):  # ragged heads, complete tail
        ms[: rng.integers(4, 20), i] = False
    xs = jnp.asarray(np.where(ms, xs, 0.0), dt_)
    msj = jnp.asarray(ms.astype(np.asarray(xz).dtype))
    sparams = SSMParams(
        lam=jnp.zeros((N, r), dt_).at[:, 0].set(1.0),
        R=jnp.ones(N, dt_),
        A=jnp.concatenate(
            [0.5 * jnp.eye(r, dtype=dt_)[None], jnp.zeros((p - 1, r, r), dt_)]
        ),
        Q=jnp.eye(r, dtype=dt_),
    )
    sstats = compute_panel_stats(xs, msj)
    ips = _try(xs, msj, sparams, sstats, "synthetic_ref")
    if ips is None:
        return fields
    run_em_loop(em_step_stats, sparams, (xs, msj, sstats), 0.0, n_dev_iter)
    t1 = time.perf_counter()
    _, _, n_ran, _ = run_em_loop(
        em_step_stats, sparams, (xs, msj, sstats), 0.0, n_dev_iter
    )
    seq_ips = n_ran / (time.perf_counter() - t1)
    fields["em_iters_per_sec_steady_baseline"] = round(seq_ips, 2)
    fields["em_steady_speedup"] = round(ips / seq_ips, 2)
    return fields


def _gram_loop_seconds(fn, X, Y, W, n: int, n_timing: int = 5):
    """Per-call seconds of `fn(X, Y, W)` measured as one on-device
    fori_loop of n calls (best of n_timing runs).  The carry perturbs W —
    the one input EVERY output depends on (A and rhs both contract W):
    perturbing only Y lets XLA hoist the Y-independent A-einsum out of the
    loop (LICM), and anything less than full output dependence lets it
    dead-code-eliminate the op — either way the XLA side would be
    under-timed vs the opaque kernel.  The perturbation is cast to W's
    dtype so a bf16 W stays bf16 (1e-30 is representable in bf16: same
    exponent range as f32)."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    def body(i, carry):
        A, b = fn(X, Y, W + (carry * 1e-30).astype(W.dtype))
        return A.sum() * 1e-30 + b.sum() * 1e-30

    @jax.jit
    def loop():
        return lax.fori_loop(0, n, body, jnp.float32(0.0))

    loop().block_until_ready()  # compile
    best = float("inf")
    for _ in range(n_timing):
        t = time.perf_counter()
        loop().block_until_ready()
        best = min(best, time.perf_counter() - t)
    return best / n


def pallas_section():
    """Fused Pallas masked-Gram vs XLA einsum at the flagship size (TPU).
    No exception guard: if the compiled kernel cannot run on this chip the
    bench must fail visibly (round-1 lesson), not report null."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax import lax

    from dynamic_factor_models_tpu.ops.pallas_gram import (
        masked_gram_pallas,
        masked_gram_xla,
    )

    rng = np.random.default_rng(0)
    Tbig, Nbig, K = LARGE_T, LARGE_N, LARGE_R
    Xb = jnp.asarray(rng.standard_normal((Tbig, K)), jnp.float32)
    Yb = jnp.asarray(rng.standard_normal((Tbig, Nbig)), jnp.float32)
    Wb = jnp.asarray((rng.random((Tbig, Nbig)) > 0.2), jnp.float32)

    # n large enough that kernel time (~250us/call) swamps the ~30ms fixed
    # dispatch cost of one remote loop launch
    n_gram = 1000
    t_pallas = _gram_loop_seconds(masked_gram_pallas, Xb, Yb, Wb, n_gram)
    t_xla = _gram_loop_seconds(masked_gram_xla, Xb, Yb, Wb, n_gram)
    # bf16 operand legs: the HBM-bandwidth option (panel cast OUTSIDE the
    # loop, f32 accumulation inside the kernels — ops/pallas_gram.py dtype
    # contract); the fields quantify the bandwidth claim on real hardware
    X16, Y16, W16 = (a.astype(jnp.bfloat16) for a in (Xb, Yb, Wb))
    t_pallas16 = _gram_loop_seconds(masked_gram_pallas, X16, Y16, W16, n_gram)
    t_xla16 = _gram_loop_seconds(masked_gram_xla, X16, Y16, W16, n_gram)
    return {
        "pallas_gram_speedup_large_panel": round(t_xla / t_pallas, 2),
        "pallas_gram_us_per_call": round(t_pallas * 1e6, 1),
        "pallas_gram_bf16_speedup_vs_f32": round(t_pallas / t_pallas16, 2),
        "xla_gram_bf16_speedup_vs_f32": round(t_xla / t_xla16, 2),
        "pallas_gram_bf16_us_per_call": round(t_pallas16 * 1e6, 1),
    }


# ---------------------------------------------------------------------------
# multichip: sharded-EM scaling + measured-FLOPs MFU (CPU-testable via the
# forced 8-device host platform; see docs/sharding.md)
# ---------------------------------------------------------------------------


def _measured_gemm_peak():
    """Measured f32 GEMM throughput of the current backend, FLOP/s.

    The CPU container has no published MXU ceiling, so MFU there is
    normalized by what the backend's own GEMM actually sustains (best of
    five 10-deep on-device matmul loops).  docs/EVIDENCE.md records why the
    two denominators are not comparable: the TPU number is a datasheet
    bf16 peak, this one is a measured f32 peak.

    Delegates to utils/roofline.measured_gemm_peak (the same probe the
    runtime ledger uses), which also caches the result so the live MFU
    gauges adopt the measured denominator from here on."""
    from dynamic_factor_models_tpu.utils.roofline import measured_gemm_peak

    return measured_gemm_peak(reps=5)


def _compiled_flops(compiled):
    """FLOPs of a compiled executable from XLA's own cost model — the
    measured-program counterpart of the hand estimates in als/em_iter_flops.
    None when the backend reports no cost analysis."""
    try:
        ca = compiled.cost_analysis()
    except Exception:
        return None
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    f = float(ca.get("flops", 0.0) or 0.0)
    return f if f > 0 else None


def run_multichip(force_cpu: bool):
    """Child mode (spawned by --multichip with the forced-8-device XLA flag
    already in the environment, which must precede jax init): measured
    cost_analysis() MFU for the flagship EM/ALS programs, the Pallas Gram
    timing (interpret mode on CPU), and sharded-vs-1-device EM scaling at
    N in {1k, 4k, 16k}.  Prints one JSON line."""
    import functools

    import jax

    if force_cpu:
        from dynamic_factor_models_tpu.utils.backend import fall_back_to_cpu

        fall_back_to_cpu("multichip forced CPU", caller="bench")
    import jax.numpy as jnp
    import numpy as np

    from dynamic_factor_models_tpu.models.dfm import _als_core
    from dynamic_factor_models_tpu.models.ssm import (
        SSMParams,
        _sharded_step_for,
        compute_panel_stats,
        em_step_stats,
    )
    from dynamic_factor_models_tpu.ops.linalg import (
        pca_score_np,
        standardize_data,
        standardize_data_np,
    )
    from dynamic_factor_models_tpu.ops.masking import fillz, mask_of
    from dynamic_factor_models_tpu.ops.pallas_gram import (
        masked_gram_pallas,
        masked_gram_xla,
    )

    dev = jax.devices()[0]
    tpu_ok = _is_tpu_platform(dev.platform)
    n_dev = jax.device_count()
    out = {
        "device": str(dev),
        "n_devices": n_dev,
        "tpu_unreachable": not tpu_ok,
    }

    if tpu_ok:
        peak = PEAK_FLOPS_V5E_BF16
        out["mfu_peak_source"] = "v5e_bf16_datasheet"
    else:
        peak = _measured_gemm_peak()
        out["mfu_peak_source"] = "measured_f32_gemm"
    out["mfu_peak_flops"] = round(peak, 0)

    def _prep(T, N, r, dtype=None):
        x = _synthetic_large_panel(T, N, r, np.float32)
        xstd, _ = standardize_data(jnp.asarray(x))
        xz, m = fillz(xstd), mask_of(xstd).astype(xstd.dtype)
        params = SSMParams(
            lam=jnp.zeros((N, r), xz.dtype).at[:, 0].set(1.0),
            R=jnp.ones(N, xz.dtype),
            A=0.5 * jnp.eye(r, dtype=xz.dtype)[None],
            Q=jnp.eye(r, dtype=xz.dtype),
        )
        # tw=ones so the stats pytree matches the sharded step's in_specs
        # (the estimate path always pads, which supplies tw) — inert for
        # the single-device step, bit-identical semantics on both paths
        stats = compute_panel_stats(xz, m)._replace(
            tw=jnp.ones(T, xz.dtype)
        )
        return params, xz, m, stats

    # --- measured-FLOPs MFU at the flagship size: XLA's cost model on the
    # ACTUAL compiled executables, not the hand FLOPs model
    T, N, r = LARGE_T, LARGE_N, LARGE_R
    params, xz, m, stats = _prep(T, N, r)
    em_exec = jax.jit(em_step_stats).lower(params, xz, m, stats).compile()
    em_flops = _compiled_flops(em_exec) or em_iter_flops(T, N, r, 1)
    em_run = lambda: em_exec(params, xz, m, stats)[0].lam.block_until_ready()
    em_run()  # warm
    em_t = _time_fixed_iters(em_run)
    out["em_large_flops_measured"] = round(em_flops, 0)
    out["em_large_mfu_bf16_peak_pct"] = round(
        100.0 * em_flops / em_t / peak, 3
    )

    x_np = _synthetic_large_panel(T, N, r, np.float32)
    xh, _, _ = standardize_data_np(x_np)
    f0 = jnp.asarray(pca_score_np(xh, r), xz.dtype)
    lam_ok = jnp.ones(N, bool)
    n_als = 4
    als_args = (xz, m, lam_ok, f0, jnp.float32(0.0), r, n_als)
    als_exec = _als_core.lower(*als_args).compile()
    als_flops = _compiled_flops(als_exec) or n_als * als_iter_flops(T, N, r)
    als_run = lambda: als_exec(
        xz, m, lam_ok, f0, jnp.float32(0.0)
    )[0].block_until_ready()
    als_run()  # warm
    als_t = _time_fixed_iters(als_run)
    out["als_large_flops_measured"] = round(als_flops, 0)
    out["als_large_mfu_bf16_peak_pct"] = round(
        100.0 * als_flops / als_t / peak, 3
    )

    # --- Pallas masked Gram: compiled at the flagship size on TPU; on CPU
    # the kernel runs in interpret mode at a one-tile shape (the interpreter
    # is orders of magnitude slower than compiled code, so the "speedup"
    # field is honest-but-damning there — the docs say to read it only as
    # "the kernel path executes and agrees", never as CPU perf evidence)
    if tpu_ok:
        Tg, Ng, n_gram, n_timing = LARGE_T, LARGE_N, 1000, 5
        gram_fn = masked_gram_pallas
        out["pallas_gram_mode"] = "compiled"
    else:
        Tg, Ng, n_gram, n_timing = 256, 512, 2, 2
        gram_fn = functools.partial(masked_gram_pallas, interpret=True)
        out["pallas_gram_mode"] = "interpret"
    rng = np.random.default_rng(0)
    Xg = jnp.asarray(rng.standard_normal((Tg, LARGE_R)), jnp.float32)
    Yg = jnp.asarray(rng.standard_normal((Tg, Ng)), jnp.float32)
    Wg = jnp.asarray((rng.random((Tg, Ng)) > 0.2), jnp.float32)
    t_pal = _gram_loop_seconds(gram_fn, Xg, Yg, Wg, n_gram, n_timing)
    t_xla = _gram_loop_seconds(masked_gram_xla, Xg, Yg, Wg, n_gram, n_timing)
    out["pallas_gram_us_per_call"] = round(t_pal * 1e6, 1)
    out["pallas_gram_speedup_large_panel"] = round(t_xla / t_pal, 4)
    out["pallas_gram_bench_shape"] = [Tg, Ng]

    # --- sharded-vs-1-device EM scaling: same step, same inputs, padded N
    # already a multiple of the shard count at all three sizes
    ns = min(8, n_dev)
    out["em_sharded_n_shards"] = ns
    Ts, rs = 256, 4
    for Nn in (1024, 4096, 16384):
        params_n, xzn, mn, statsn = _prep(Ts, Nn, rs)
        single = jax.jit(em_step_stats)
        single(params_n, xzn, mn, statsn)[0].lam.block_until_ready()
        t1 = _time_fixed_iters(
            lambda: single(params_n, xzn, mn, statsn)[0].lam.block_until_ready()
        )
        out[f"em_1dev_iters_per_sec_n{Nn}"] = round(1.0 / t1, 2)
        if ns > 1:
            sh = _sharded_step_for(ns)
            sh(params_n, xzn, mn, statsn)[0].lam.block_until_ready()
            t8 = _time_fixed_iters(
                lambda: sh(params_n, xzn, mn, statsn)[0].lam.block_until_ready()
            )
            out[f"em_sharded_iters_per_sec_n{Nn}"] = round(1.0 / t8, 2)
            out[f"em_sharded_speedup_n{Nn}"] = round(t1 / t8, 3)
            if Nn == 1024:
                p1, ll1 = single(params_n, xzn, mn, statsn)
                p8, ll8 = sh(params_n, xzn, mn, statsn)
                diff = max(
                    float(jnp.max(jnp.abs(a - b)))
                    for a, b in zip(
                        jax.tree_util.tree_leaves(p1),
                        jax.tree_util.tree_leaves(p8),
                    )
                )
                diff = max(diff, abs(float(ll1) - float(ll8)))
                out["em_sharded_parity_max_abs"] = diff
    print(json.dumps(out))


def multichip_orchestrate(force_cpu: bool):
    """--multichip: run the sharded/MFU section in a child with the forced
    8-device flag set BEFORE jax initializes (device count is frozen at
    backend init, so the parent cannot force it for itself), then append
    the precision-parity legs and the parity fill so the fragment carries
    non-null parity_* fields even on a CPU-only container."""
    import tempfile

    flags = os.environ.get("XLA_FLAGS", "")
    if "host_platform_device_count" not in flags:
        flags = (flags + " --xla_force_host_platform_device_count=8").strip()
    child_args = ["--run-multichip"]
    if force_cpu or os.environ.get("DFM_BENCH_FORCE_CPU") == "1":
        child_args.append("--force-cpu")
    pr = _run_child(child_args, env_extra={"XLA_FLAGS": flags})
    fragment = _parse_fragment(pr)
    if fragment is None:
        print("bench: multichip child produced no JSON", file=sys.stderr)
        sys.exit(2)
    with tempfile.TemporaryDirectory() as workdir:
        fragment.update(_precision_parity(workdir))
    _fill_parity_from_precision(fragment)
    print(json.dumps(fragment))
    sys.exit(pr.returncode)


def _mh_sizes(smoke: bool):
    """(T, N, r) grid for the multi-host legs; N is already a multiple of
    8 so the sharded step needs no padding on either topology."""
    return [(128, 1024, 4)] if smoke else [(256, 4096, 4), (256, 16384, 4)]


def _mh_prep_sharded(T, N, r):
    """Inputs for `_sharded_step_for` (run_multichip's _prep, returned as
    HOST numpy arrays: committed single-device jax.Arrays cannot reshard
    onto a process-spanning mesh, numpy can — the same contract the
    estimators follow in a multi-process runtime)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from dynamic_factor_models_tpu.models.ssm import (
        SSMParams,
        compute_panel_stats,
    )
    from dynamic_factor_models_tpu.ops.linalg import standardize_data
    from dynamic_factor_models_tpu.ops.masking import fillz, mask_of

    x = _synthetic_large_panel(T, N, r, np.float32)
    xstd, _ = standardize_data(jnp.asarray(x))
    xz, m = fillz(xstd), mask_of(xstd).astype(xstd.dtype)
    params = SSMParams(
        lam=jnp.zeros((N, r), xz.dtype).at[:, 0].set(1.0),
        R=jnp.ones(N, xz.dtype),
        A=0.5 * jnp.eye(r, dtype=xz.dtype)[None],
        Q=jnp.eye(r, dtype=xz.dtype),
    )
    stats = compute_panel_stats(xz, m)._replace(tw=jnp.ones(T, xz.dtype))
    to_np = lambda t: jax.tree.map(np.asarray, t)
    return to_np(params), np.asarray(xz), np.asarray(m), to_np(stats)


def _mh_measure(out, step, smoke):
    """Per-size module FLOPs (XLA cost model on the ACTUAL partitioned
    executable — per-partition, since SPMD runs one module per device) and
    wall iters/sec into `out`."""
    import jax

    for T, N, r in _mh_sizes(smoke):
        params, xz, m, stats = _mh_prep_sharded(T, N, r)
        ex = step.lower(params, xz, m, stats).compile()
        out[f"module_flops_n{N}"] = _compiled_flops(ex)
        run = lambda: step(params, xz, m, stats)[0].lam.block_until_ready()
        run()  # warm (jit dispatch path, shared executable cache with ex)
        out[f"iters_per_sec_n{N}"] = round(
            1.0 / _time_fixed_iters(run), 3
        )


def run_multihost_single(force_cpu: bool, smoke: bool):
    """Child mode (spawned by multihost_section with the forced-8-device
    flag): the 1-process x 8-device reference leg — the flat ("data",)
    mesh program.  Prints one JSON line."""
    import jax

    if force_cpu:
        from dynamic_factor_models_tpu.utils.backend import fall_back_to_cpu

        fall_back_to_cpu("multihost forced CPU", caller="bench")

    from dynamic_factor_models_tpu.models.ssm import _sharded_step_for

    n_dev = jax.device_count()
    ns = min(8, n_dev)
    out = {
        "role": "single",
        "device": str(jax.devices()[0]),
        "n_devices": n_dev,
        "n_shards": ns,
        "local_partitions": ns,
        "mesh": [1, ns],
        "flop_proxy": not _is_tpu_platform(jax.devices()[0].platform),
    }
    _mh_measure(out, _sharded_step_for(ns), smoke)
    print(json.dumps(out), flush=True)


def run_multihost_worker(nproc: int, pid: int, port: str, smoke: bool):
    """Child mode: one of `nproc` OS processes (4 forced devices each)
    joined by jax.distributed into a global mesh; `_sharded_step_for(8)`
    auto-resolves hosts=nproc onto the ("dcn", "ici") topology.  Every
    worker executes the same SPMD program; rank 0 prints the JSON line."""
    from dynamic_factor_models_tpu.parallel.distributed import (
        initialize_distributed,
    )

    ok = initialize_distributed(f"127.0.0.1:{port}", nproc, pid)
    import jax

    assert ok and jax.process_count() == nproc, "distributed init failed"

    from dynamic_factor_models_tpu.models.ssm import _sharded_step_for

    out = {
        "role": "worker",
        "device": str(jax.devices()[0]),
        "process_count": nproc,
        "n_devices": jax.device_count(),
        "n_shards": 8,
        "local_partitions": jax.local_device_count(),
        "mesh": [nproc, 8 // nproc],
        "flop_proxy": not _is_tpu_platform(jax.devices()[0].platform),
    }
    _mh_measure(out, _sharded_step_for(8), smoke)
    if pid == 0:
        print(json.dumps(out), flush=True)


def multihost_section(force_cpu: bool, smoke: bool = False) -> dict:
    """Both multi-host legs: 1proc x 8dev (flat mesh) vs 2proc x 4dev
    (process-spanning mesh over real OS processes + Gloo DCN analogue),
    then the FLOP-partition accounting.

    The headline `flop_partition_speedup_nX` is per-PROCESS executed
    FLOPs: local_partitions x module_flops.  Both topologies compile the
    same per-partition module (the reduction epilogue differs only in
    collective shape), so two hosts each execute ~half the program —
    that, not CPU wall-clock, is the scale-out evidence; wall columns on
    a CPU container carry `flop_proxy: true` and must be read as
    'the program runs', never as perf."""
    import re
    import socket
    import tempfile

    forced = force_cpu or os.environ.get("DFM_BENCH_FORCE_CPU") == "1"
    base = re.sub(
        r"--xla_force_host_platform_device_count=\S+",
        "",
        os.environ.get("XLA_FLAGS", ""),
    ).strip()
    flags8 = (base + " --xla_force_host_platform_device_count=8").strip()
    flags4 = (base + " --xla_force_host_platform_device_count=4").strip()
    sizes = _mh_sizes(smoke)
    out = {
        "n_shards": 8,
        "smoke": smoke,
        "grid_t_n": [[T, N] for T, N, _ in sizes],
    }

    single_args = ["--run-multihost"] + (["--smoke"] if smoke else [])
    if forced:
        single_args.append("--force-cpu")
    pr = _run_child(single_args, env_extra={"XLA_FLAGS": flags8},
                    timeout_s=1800 if smoke else 3600)
    single = _parse_fragment(pr)
    out["single_process"] = single if single is not None else {
        "error": "single-process child produced no JSON"
    }

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    env = dict(os.environ)
    env["XLA_FLAGS"] = flags4
    if forced:
        env["JAX_PLATFORMS"] = "cpu"
    nproc = 2
    procs, tmpd = [], tempfile.mkdtemp(prefix="bench_mh_")
    logs = [
        (os.path.join(tmpd, f"w{i}.out"), os.path.join(tmpd, f"w{i}.err"))
        for i in range(nproc)
    ]
    try:
        for i in range(nproc):
            with open(logs[i][0], "w") as fo, open(logs[i][1], "w") as fe:
                procs.append(
                    subprocess.Popen(
                        [sys.executable, os.path.join(REPO, "bench.py"),
                         "--run-multihost-worker", "--mh-pid", str(i),
                         "--mh-nproc", str(nproc), "--mh-port", str(port)]
                        + (["--smoke"] if smoke else []),
                        stdout=fo, stderr=fe, env=env,
                    )
                )
        deadline = time.monotonic() + (900 if smoke else 3600)
        while any(p.poll() is None for p in procs):
            if any(p.poll() not in (None, 0) for p in procs):
                break  # dead worker strands the peer at the DCN barrier
            if time.monotonic() > deadline:
                break
            time.sleep(0.5)
    finally:
        for p in procs:  # never leak an orphan worker
            if p.poll() is None:
                p.kill()
                p.wait()

    import types

    with open(logs[0][0]) as fh:
        worker = _parse_fragment(types.SimpleNamespace(stdout=fh.read()))
    if worker is None or any(p.returncode != 0 for p in procs):
        tails = {
            f"worker{i}_stderr_tail": open(logs[i][1]).read()[-1500:]
            for i in range(nproc)
        }
        out["two_process"] = {
            "error": "worker pair failed",
            "rc": [p.returncode for p in procs],
            **tails,
        }
        if worker is not None:
            out["two_process"]["fragment"] = worker
        return out
    out["two_process"] = worker

    for T, N, r in sizes:
        fa = (single or {}).get(f"module_flops_n{N}")
        fb = worker.get(f"module_flops_n{N}")
        if fa and fb:
            per_proc_a = fa * single["local_partitions"]
            per_proc_b = fb * worker["local_partitions"]
            out[f"flop_partition_speedup_n{N}"] = round(
                per_proc_a / per_proc_b, 3
            )
        # one cross-host DCN psum per EM iteration: the packed collapse
        # payload (T, q(q+1)/2 + 1 + q) at q = r*p, float32
        q = r * 1
        out[f"dcn_payload_bytes_per_iter_n{N}"] = (
            T * (q * (q + 1) // 2 + 1 + q) * 4
        )
    out["flop_proxy"] = bool(
        (single or {}).get("flop_proxy", True) or worker.get("flop_proxy")
    )
    sp = out.get("flop_partition_speedup_n16384")
    if not smoke:
        out["accept_flop_partition_ge_1p7_n16384"] = (
            None if sp is None else bool(sp >= 1.7)
        )
    return out


def multihost_orchestrate(force_cpu: bool):
    """--multihost: run both legs, persist docs/BENCH_multihost.json,
    print the fragment."""
    fragment = multihost_section(force_cpu)
    path = os.path.join(REPO, "docs", "BENCH_multihost.json")
    tmp = path + ".tmp"
    with open(tmp, "w") as fh:
        json.dump(fragment, fh, indent=1, sort_keys=True)
    os.replace(tmp, path)
    print(json.dumps(fragment))
    two = fragment.get("two_process", {})
    sys.exit(2 if "error" in two else 0)


def _synthetic_ragged_panel(T, N, r, dtype):
    """Factor + AR(1)-idio DGP with CONTIGUOUS per-series observation runs
    (ragged heads/tails, no interior gaps) — the mask class the
    quasi-differenced collapsed-AR path is exact for."""
    import numpy as np

    rng = np.random.default_rng(11)
    f = np.zeros((T, r), np.float64)
    for t in range(1, T):
        f[t] = 0.7 * f[t - 1] + rng.standard_normal(r)
    lam = rng.standard_normal((N, r)) * 0.5
    phi = rng.uniform(-0.5, 0.7, N)
    e = np.zeros((T, N))
    for t in range(1, T):
        e[t] = phi * e[t - 1] + rng.standard_normal(N) * 0.5
    x = f @ lam.T + e
    heads = rng.integers(0, max(2, T // 8), N)
    tails = rng.integers(0, max(2, T // 8), N)
    for i in range(N):
        x[: heads[i], i] = np.nan
        if tails[i]:
            x[T - tails[i]:, i] = np.nan
    return x.astype(dtype)


def large_n_section(force_cpu: bool = False):
    """--large-n: is the collapsed-AR EM step's cost really N-free?

    Measured, per N in {1k, 10k, 100k}: em_iters_per_sec of
    `em_step_ar_qd` and the compiled executable's peak memory (XLA's
    memory_analysis: temp + argument space, the number an accelerator
    allocator actually reserves).  The 100k leg is memory-gated against
    DFM_MEM_BUDGET — recorded null (never skipped silently) when the
    QDStats panels alone would blow the budget.  Plus the two acceptance
    numbers: collapsed-vs-dense speedup at N = 512 on the SAME panel
    (target >= 10x) and a 64-lane scenario fan at N = 10k through the
    collapsed smoother, with the byte count the uncollapsed per-lane
    panel stacks would have needed.  Prints one JSON line and persists
    docs/BENCH_large_n.json."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    if force_cpu:
        from dynamic_factor_models_tpu.utils.backend import fall_back_to_cpu

        fall_back_to_cpu("large-n forced CPU", caller="bench")

    from dynamic_factor_models_tpu.models import ssm_ar
    from dynamic_factor_models_tpu.ops.masking import fillz, mask_of
    from dynamic_factor_models_tpu.scenarios import fanout

    dev = jax.devices()[0]
    T, r, p = 128, 4, 1
    budget = float(os.environ.get("DFM_MEM_BUDGET", 8e9))
    out = {
        "device": str(dev),
        # the speedup rows below are wall-clock ratios, not hardware
        # FLOP counters: label the whole record off-TPU so
        # tools/check_bench_honesty.py's speedup rule passes
        "flop_proxy": not _is_tpu_platform(dev.platform),
        "large_n": True,
        "T": T, "r": r, "p": p,
        "mem_budget_bytes": budget,
    }

    def _prep(N, dtype=np.float32):
        x = _synthetic_ragged_panel(T, N, r, dtype)
        xj = jnp.asarray(x)
        xz, m = fillz(xj), mask_of(xj)
        assert ssm_ar.qd_mask_supported(np.asarray(m))
        qd = ssm_ar.compute_qd_stats(xz, m)
        rng = np.random.default_rng(0)
        params = ssm_ar.SSMARParams(
            lam=jnp.asarray(0.3 * rng.standard_normal((N, r)), xz.dtype),
            phi=jnp.zeros(N, xz.dtype),
            sigv2=jnp.ones(N, xz.dtype),
            A=0.5 * jnp.eye(r, dtype=xz.dtype)[None],
            Q=jnp.eye(r, dtype=xz.dtype),
        )
        return params, xz, m, qd

    for N in (1000, 10_000, 100_000):
        key = f"n{N // 1000}k"
        # the collapsed step's footprint is the QDStats panels (9 (T, N)
        # + 2 vectors) + the panel itself; gate the attempt, never the key
        est = 10 * T * N * 4
        if est > budget:
            out[f"em_ar_qd_iters_per_sec_{key}"] = None
            out[f"em_ar_qd_peak_bytes_{key}"] = None
            out[f"em_ar_qd_gated_{key}"] = (
                f"estimated {est:.2e} B QD panels > DFM_MEM_BUDGET "
                f"{budget:.2e} B"
            )
            continue
        params, xz, m, qd = _prep(N)
        ex = jax.jit(ssm_ar.em_step_ar_qd).lower(params, xz, qd).compile()
        ma = ex.memory_analysis()
        peak = None
        if ma is not None:
            peak = int(
                getattr(ma, "temp_size_in_bytes", 0)
                + getattr(ma, "argument_size_in_bytes", 0)
                + getattr(ma, "output_size_in_bytes", 0)
            )
        jax.block_until_ready(ex(params, xz, qd))
        t = _time_fixed_iters(
            lambda: jax.block_until_ready(ex(params, xz, qd))
        )
        out[f"em_ar_qd_iters_per_sec_{key}"] = round(1.0 / t, 2)
        out[f"em_ar_qd_peak_bytes_{key}"] = peak
        print(json.dumps({key: round(1.0 / t, 2)}), file=sys.stderr, flush=True)

    # acceptance: collapsed >= 10x dense at N = 512 on the same panel
    N = 512
    params, xz, m, qd = _prep(N)
    exq = jax.jit(ssm_ar.em_step_ar_qd).lower(params, xz, qd).compile()
    jax.block_until_ready(exq(params, xz, qd))
    tq = _time_fixed_iters(lambda: jax.block_until_ready(exq(params, xz, qd)))
    exd = jax.jit(ssm_ar.em_step_ar).lower(params, xz, m).compile()
    jax.block_until_ready(exd(params, xz, m))
    td = _time_fixed_iters(
        lambda: jax.block_until_ready(exd(params, xz, m)), n_timing_runs=2
    )
    out["em_ar_qd_iters_per_sec_n512"] = round(1.0 / tq, 2)
    out["em_ar_dense_iters_per_sec_n512"] = round(1.0 / td, 2)
    out["em_ar_collapse_speedup_n512"] = round(td / tq, 1)

    # scenario fan at N = 10k: the ISSUE's 1k-lane fan through the
    # collapsed smoother (per-lane scan state is r-sized, so 1024 lanes
    # fit easily); the uncollapsed fan would carry S stacked (T+h, N)
    # panels (plus the per-lane N-row collapse intermediates) — report
    # the stack bytes it would have needed next to the measured run
    S, h = 1024, 8
    Nf = 10_000
    xf = _synthetic_ragged_panel(T, Nf, r, np.float32)
    cond = np.full((S, h, Nf), np.nan, np.float32)
    cond[:, 0, 0] = np.linspace(-2, 2, S)
    from dynamic_factor_models_tpu.models.ssm import SSMParams

    rng = np.random.default_rng(3)
    pfan = SSMParams(
        lam=jnp.asarray(0.3 * rng.standard_normal((Nf, r)), jnp.float32),
        R=jnp.ones(Nf, jnp.float32),
        A=0.5 * jnp.eye(r, dtype=jnp.float32)[None],
        Q=jnp.eye(r, dtype=jnp.float32),
    )
    t0 = time.perf_counter()
    fmean, fcov = fanout.conditional_fan(
        pfan, xf, h, cond, collapsed=True, observables=False
    )
    jax.block_until_ready((fmean, fcov))
    out["fan_collapsed_wall_s_n10k_s1024"] = round(time.perf_counter() - t0, 3)
    out["fan_collapsed_ok_n10k_s1024"] = bool(
        np.isfinite(np.asarray(fmean)).all()
    )
    dense_stack = 2 * S * (T + h) * Nf * 4  # xz + mask stacks alone
    out["fan_dense_stack_bytes_n10k_s1024"] = dense_stack
    out["fan_dense_exceeds_budget_n10k_s1024"] = dense_stack > budget

    path = os.path.join(REPO, "docs", "BENCH_large_n.json")
    tmp = path + ".tmp"
    with open(tmp, "w") as fh:
        json.dump(out, fh, indent=1, sort_keys=True)
    os.replace(tmp, path)
    print(json.dumps(out), flush=True)


def _synthetic_heads_panel(T, N, r, dtype):
    """`_synthetic_ragged_panel` with heads-only raggedness: the steady
    tail requires a COMPLETE interior suffix (emcore.ar_steady_plan gates
    off any panel whose last rows have missing cells), so the composed
    grid confines missingness to contiguous head runs."""
    import numpy as np

    x = _synthetic_ragged_panel(T, N, r, dtype)
    # refill the ragged tails from the same DGP statistics: any finite
    # value keeps the mask class; zeros match the standardized scale
    x[T - max(2, T // 8):] = np.nan_to_num(x[T - max(2, T // 8):])
    return x


def run_composed(force_cpu: bool = False, smoke: bool = False):
    """--run-composed (child of --composed): do composed transform stacks
    multiply their wins on ONE panel?

    Grid: N in {1k, 10k, 100k} x {sequential, collapsed, steady, sharded,
    all} on a T=384 heads-ragged AR panel, every step resolved from its
    transform stack (models/transforms).  Per leg: iters/sec of the
    compiled step and the XLA cost-model FLOPs.  On the 8-virtual-device
    CPU platform the shard legs share one socket, so shard scaling is
    reported as per-device FLOP partitioning (collapsed FLOPs / sharded
    per-device FLOPs), honestly labeled via "flop_proxy": wall-clock
    shard scaling needs the real mesh.  Acceptance fields: steady
    speedup >= 2x over collapsed-alone at N=100k (wall clock), sharded
    pre-scan FLOP scaling >= 3x at 8 devices, and the all-axes stack's
    FLOP reduction within 40% of the steady x shard product.  The dense
    sequential leg is O((r p + N)^3 T) — minutes of CPU per iteration
    past N ~ 512 — so wide legs record the gate reason (never a silent
    skip); docs/BENCH_large_n.json carries the measured dense point.
    Prints one JSON line; the parent persists docs/BENCH_composed.json."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    if force_cpu:
        from dynamic_factor_models_tpu.utils.backend import fall_back_to_cpu

        fall_back_to_cpu("composed forced CPU", caller="bench")

    from dynamic_factor_models_tpu.models import emcore, ssm_ar
    from dynamic_factor_models_tpu.models import transforms as tfm
    from dynamic_factor_models_tpu.ops.masking import fillz, mask_of
    from dynamic_factor_models_tpu.parallel.mesh import series_pad

    dev = jax.devices()[0]
    n_dev = jax.device_count()
    ns = min(8, n_dev)
    T, r, p = (96, 2, 1) if smoke else (384, 4, 1)
    Ns = (768,) if smoke else (1000, 10_000, 100_000)
    budget = float(os.environ.get("DFM_MEM_BUDGET", 8e9))
    out = {
        "device": str(dev), "composed": True, "smoke": smoke,
        "T": T, "r": r, "p": p, "n_devices": n_dev, "n_shards": ns,
        "mem_budget_bytes": budget,
        "flop_proxy": not _is_tpu_platform(dev.platform),
    }

    def _prep(N, dtype=np.float32):
        x = _synthetic_heads_panel(T, N, r, dtype)
        xj = jnp.asarray(x)
        xz, m = fillz(xj), mask_of(xj)
        assert ssm_ar.qd_mask_supported(np.asarray(m))
        qd = ssm_ar.compute_qd_stats(xz, m)
        rng = np.random.default_rng(0)
        params = ssm_ar.SSMARParams(
            lam=jnp.asarray(0.3 * rng.standard_normal((N, r)), xz.dtype),
            phi=jnp.zeros(N, xz.dtype),
            sigv2=jnp.ones(N, xz.dtype),
            A=0.5 * jnp.eye(r, dtype=xz.dtype)[None],
            Q=jnp.eye(r, dtype=xz.dtype),
        )
        return params, xz, m, qd

    def _ips(ex, *args, n_timing_runs=3):
        jax.block_until_ready(ex(*args))  # warm outside the clock
        t = _time_fixed_iters(
            lambda: jax.block_until_ready(ex(*args)), n_timing_runs
        )
        return round(1.0 / t, 2)

    for N in Ns:
        key = f"n{N // 1000}k" if N >= 1000 else f"n{N}"
        est = 12 * T * N * 4  # QDStats panels + panel + shard copies
        if est > budget:
            for v in ("sequential", "collapsed", "steady", "sharded", "all"):
                out[f"em_ar_{v}_iters_per_sec_{key}"] = None
            out[f"em_ar_gated_{key}"] = (
                f"estimated {est:.2e} B > DFM_MEM_BUDGET {budget:.2e} B"
            )
            continue
        params, xz, m, qd = _prep(N)

        # collapsed: the one-axis baseline every product is measured against
        step_c = tfm.resolve(tfm.Stack("ar", (tfm.collapse(),))).step
        exc = jax.jit(step_c).lower(params, xz, qd).compile()
        ips_c = _ips(exc, params, xz, qd)
        fc = _compiled_flops(exc)
        out[f"em_ar_collapsed_iters_per_sec_{key}"] = ips_c

        if N <= 1000 and not smoke:
            # one timing run: ~2 min/iteration of dense filter at N=1k
            exd = jax.jit(ssm_ar.em_step_ar).lower(params, xz, m).compile()
            ips_d = _ips(exd, params, xz, m, n_timing_runs=1)
            out[f"em_ar_sequential_iters_per_sec_{key}"] = ips_d
            out[f"em_ar_collapse_speedup_{key}"] = round(ips_c / ips_d, 1)
        else:
            out[f"em_ar_sequential_iters_per_sec_{key}"] = None
            out[f"em_ar_sequential_gated_{key}"] = (
                f"dense AR state dim {r * p + N}: O(k^3) per scan step is "
                "minutes of CPU wall clock per iteration; the measured "
                "dense baseline lives in docs/BENCH_large_n.json (N=512)"
            )

        # + steady tail (host-gated, like estimate_dfm_em_ar(steady=True))
        plan = emcore.ar_steady_plan(params, np.asarray(m))
        sp = None
        if plan is None:
            out[f"em_ar_steady_iters_per_sec_{key}"] = None
            out[f"em_ar_steady_gated_{key}"] = "ar_steady_plan gated off"
        else:
            t_star, st0, rho = plan
            res_s = tfm.resolve(
                tfm.Stack("ar", (tfm.collapse(), tfm.steady_tail(t_star)))
            )
            tail = emcore.compute_qd_tail_stats(qd, t_star)
            state = emcore.ARSteadyState(
                params=params,
                Pp=jnp.asarray(st0.Pp, xz.dtype),
                riccati_iters=jnp.asarray(0, jnp.int32),
            )
            exs = jax.jit(res_s.step).lower(state, xz, qd, tail).compile()
            ips_s = _ips(exs, state, xz, qd, tail)
            fs = _compiled_flops(exs)
            sp = round(ips_s / ips_c, 2)
            out[f"em_ar_steady_iters_per_sec_{key}"] = ips_s
            out[f"t_star_{key}"] = int(t_star)
            out[f"steady_frac_{key}"] = round(float(T - t_star) / T, 3)
            out[f"em_ar_steady_speedup_{key}"] = sp
            if fc and fs:
                out[f"em_ar_steady_flop_reduction_{key}"] = round(fc / fs, 2)

        # + shard: the collapse's pre-scan GEMMs shard-local on the mesh
        if ns > 1:
            Npad = series_pad(N, ns)
            params_p, xz_p, m_p = params, xz, m
            if Npad != N:
                z = jnp.zeros((T, Npad - N), xz.dtype)
                xz_p = jnp.concatenate([xz, z], axis=1)
                m_p = jnp.concatenate([m, jnp.zeros(z.shape, bool)], axis=1)
                params_p = emcore.pad_ar_params(params, Npad)
            qd_p = ssm_ar.compute_qd_stats(xz_p, m_p)
            res_h = tfm.resolve(
                tfm.Stack("ar", (tfm.collapse(), tfm.shard(ns)))
            )
            exh = jax.jit(res_h.step).lower(params_p, xz_p, qd_p).compile()
            ips_h = _ips(exh, params_p, xz_p, qd_p)
            fh = _compiled_flops(exh)
            out[f"em_ar_sharded_iters_per_sec_{key}"] = ips_h
            if fc and fh:
                # SPMD cost analysis counts ONE device's program, so the
                # ratio is the per-device pre-scan work reduction
                out[f"em_ar_shard_prescan_scaling_{key}"] = round(fc / fh, 2)
            if plan is not None:
                # all three speed axes on one panel
                res_a = tfm.resolve(
                    tfm.Stack(
                        "ar",
                        (tfm.collapse(), tfm.steady_tail(t_star),
                         tfm.shard(ns)),
                    )
                )
                tail_p = emcore.compute_qd_tail_stats(qd_p, t_star)
                state_p = emcore.ARSteadyState(
                    params=params_p,
                    Pp=jnp.asarray(st0.Pp, xz.dtype),
                    riccati_iters=jnp.asarray(0, jnp.int32),
                )
                exa = (
                    jax.jit(res_a.step)
                    .lower(state_p, xz_p, qd_p, tail_p)
                    .compile()
                )
                ips_a = _ips(exa, state_p, xz_p, qd_p, tail_p)
                fa = _compiled_flops(exa)
                out[f"em_ar_all_iters_per_sec_{key}"] = ips_a
                out[f"em_ar_all_speedup_{key}"] = round(ips_a / ips_c, 2)
                if fc and fa:
                    out[f"em_ar_all_flop_reduction_{key}"] = round(
                        fc / fa, 2
                    )
        print(
            json.dumps({k: v for k, v in out.items() if key in k}),
            file=sys.stderr, flush=True,
        )

    # acceptance summary (None when the contributing leg was gated)
    sp = out.get("em_ar_steady_speedup_n100k")
    out["accept_steady_2x_n100k"] = None if sp is None else bool(sp >= 2.0)
    sc = out.get("em_ar_shard_prescan_scaling_n100k")
    out["accept_shard_scaling_3x_n100k"] = (
        None if sc is None else bool(sc >= 3.0)
    )
    sf = out.get("em_ar_steady_flop_reduction_n100k")
    fr = out.get("em_ar_all_flop_reduction_n100k")
    out["accept_composed_multiplies_n100k"] = (
        None
        if None in (sf, sc, fr)
        else bool(fr >= 0.6 * sf * sc)
    )
    print(json.dumps(out), flush=True)


def composed_orchestrate(force_cpu: bool):
    """--composed: run the composed transform-stack grid in a child with
    the forced 8-device flag set BEFORE jax initializes (same reason
    --multichip is a child), then persist docs/BENCH_composed.json."""
    flags = os.environ.get("XLA_FLAGS", "")
    if "host_platform_device_count" not in flags:
        flags = (flags + " --xla_force_host_platform_device_count=8").strip()
    child_args = ["--run-composed"]
    if force_cpu or os.environ.get("DFM_BENCH_FORCE_CPU") == "1":
        child_args.append("--force-cpu")
    pr = _run_child(child_args, env_extra={"XLA_FLAGS": flags},
                    timeout_s=7200)
    fragment = _parse_fragment(pr)
    if fragment is None:
        print("bench: composed child produced no JSON", file=sys.stderr)
        sys.exit(2)
    path = os.path.join(REPO, "docs", "BENCH_composed.json")
    tmp = path + ".tmp"
    with open(tmp, "w") as fh:
        json.dump(fragment, fh, indent=1, sort_keys=True)
    os.replace(tmp, path)
    print(json.dumps(fragment))
    sys.exit(pr.returncode)


def run_time_parallel(force_cpu: bool = False, smoke: bool = False):
    """--run-time-parallel (child of --time-parallel): does the
    parallel-in-time EM family earn its keep?

    Four legs, all on the forced 8-device platform:

      * refscale — the real-panel dims (T=222, N=92, r=4, p=4): iters/sec
        + cost-model FLOPs of the sequential collapsed step, the RETIRED
        unfused associative step (elements from the N-dim observation
        model), and the fused collapsed-element step.  Acceptance: fused
        beats unfused on wall clock (the regression the fused elements
        fix).
      * scaling — T in {1e4, 1e5, 1e6} at small N (16, r=2, p=1):
        sequential vs fused-assoc vs the blocked-slab step
        (emtime.em_step_tp_for(8)) with per-T ips and FLOPs.  On CPU the
        8 virtual devices share one socket and every ppermute is an
        emulated rendezvous, so the slab step's wall clock is NOT the
        story here — its per-device FLOPs are ("flop_proxy").
      * slab_partition — the scan itself at the largest T: per-device
        FLOPs of `sharded_scan(local="sequential")` (1x combine work
        split over 8 slabs, O(k^2) boundary exchange) vs the one-device
        `lax.associative_scan` (~2x combine work, log-depth).
        Acceptance: >= 3x FLOP reduction at T=1e6.
      * crossover — smallest T (small-N dims) where the fused associative
        step's wall clock catches the sequential scan: at T=222 the
        sequential recursion wins (dispatch-light), by T~1e4 the
        log-depth form's vectorized combines win even on CPU.

    Prints one JSON line; the parent persists
    docs/BENCH_time_parallel.json."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    if force_cpu:
        from dynamic_factor_models_tpu.utils.backend import fall_back_to_cpu

        fall_back_to_cpu("time-parallel forced CPU", caller="bench")

    from dynamic_factor_models_tpu.models import emtime
    from dynamic_factor_models_tpu.models import pkalman as pk
    from dynamic_factor_models_tpu.models.ssm import (
        SSMParams,
        _collapse_obs_stats,
        _psd_floor,
        compute_panel_stats,
        em_step_assoc,
        em_step_assoc_fused,
        em_step_stats,
    )
    from dynamic_factor_models_tpu.parallel.mesh import data_mesh
    from dynamic_factor_models_tpu.parallel.timescan import sharded_scan

    dev = jax.devices()[0]
    n_dev = jax.device_count()
    tb = min(8, n_dev)
    out = {
        "device": str(dev), "time_parallel": True, "smoke": smoke,
        "n_devices": n_dev, "t_blocks": tb,
        "flop_proxy": not _is_tpu_platform(dev.platform),
    }

    def _ips(ex, *args, n_timing_runs=3):
        jax.block_until_ready(ex(*args))  # warm outside the clock
        t = _time_fixed_iters(
            lambda: jax.block_until_ready(ex(*args)), n_timing_runs
        )
        return round(1.0 / t, 2)

    def _panel(T, N, r, p, seed=0):
        rng = np.random.default_rng(seed)
        f = np.zeros((T, r))
        e = rng.standard_normal((T, r))
        for t in range(1, T):
            f[t] = 0.7 * f[t - 1] + e[t]
        lam = 0.5 * rng.standard_normal((N, r))
        x = jnp.asarray(f @ lam.T + rng.standard_normal((T, N)))
        m = jnp.ones((T, N))
        A = jnp.zeros((p, r, r)).at[0].set(0.5 * jnp.eye(r))
        params = SSMParams(
            lam=jnp.asarray(lam), R=jnp.ones(N), A=A, Q=jnp.eye(r)
        )
        return params, x, m, compute_panel_stats(x, m)

    # -- refscale: the dims of the real panel, where the unfused
    #    associative variant measurably LOST to the sequential scan
    T0, N0, r0, p0 = (96, 24, 2, 1) if smoke else (222, 92, 4, 4)
    params, x, m, stats = _panel(T0, N0, r0, p0)
    ex_seq = jax.jit(em_step_stats).lower(params, x, m, stats).compile()
    ex_unf = jax.jit(em_step_assoc).lower(params, x, m).compile()
    ex_fus = jax.jit(em_step_assoc_fused).lower(params, x, m).compile()
    ref = {
        "T": T0, "N": N0, "r": r0, "p": p0,
        "seq_iters_per_sec": _ips(ex_seq, params, x, m, stats),
        "assoc_unfused_iters_per_sec": _ips(ex_unf, params, x, m),
        "assoc_fused_iters_per_sec": _ips(ex_fus, params, x, m),
        "seq_flops": _compiled_flops(ex_seq),
        "assoc_unfused_flops": _compiled_flops(ex_unf),
        "assoc_fused_flops": _compiled_flops(ex_fus),
    }
    if ref["assoc_unfused_iters_per_sec"]:
        ref["fused_vs_unfused_speedup"] = round(
            ref["assoc_fused_iters_per_sec"]
            / ref["assoc_unfused_iters_per_sec"], 2
        )
    if ref["assoc_unfused_flops"] and ref["assoc_fused_flops"]:
        ref["fused_vs_unfused_flop_reduction"] = round(
            ref["assoc_unfused_flops"] / ref["assoc_fused_flops"], 2
        )
    if ref["seq_iters_per_sec"]:
        # the honest refscale verdict: at T=222 the sequential recursion
        # still wins one-device wall clock — parallel-in-time is a
        # long-T tool (see the crossover leg)
        ref["fused_over_seq_wallclock"] = round(
            ref["assoc_fused_iters_per_sec"] / ref["seq_iters_per_sec"], 3
        )
    if n_dev > 1:
        # per-device FLOP share of the blocked-slab step at refscale
        # (flops only: on the CPU container its wall clock is emulated-
        # collective rendezvous, not compute)
        ex_tp = (
            emtime.em_step_tp_for(tb).lower(params, x, m, stats).compile()
        )
        ref["tp_flops_per_device"] = _compiled_flops(ex_tp)
        if ref["seq_flops"] and ref["tp_flops_per_device"]:
            ref["tp_per_device_over_seq_flops"] = round(
                ref["tp_flops_per_device"] / ref["seq_flops"], 2
            )
    out["refscale"] = ref
    print(json.dumps({"refscale": ref}), file=sys.stderr, flush=True)

    # -- scaling in T at small N: the regime the time mesh exists for
    Ns, rs, ps = 16, 2, 1
    Ts = (1_000, 10_000) if smoke else (10_000, 100_000, 1_000_000)
    step_tp = emtime.em_step_tp_for(tb) if tb > 1 else None
    rows = []
    for T in Ts:
        params, x, m, stats = _panel(T, Ns, rs, ps, seed=1)
        nt = 1 if T >= 100_000 else 3
        ex_s = jax.jit(em_step_stats).lower(params, x, m, stats).compile()
        ex_f = jax.jit(em_step_assoc_fused).lower(params, x, m).compile()
        row = {
            "T": T,
            "seq_iters_per_sec": _ips(ex_s, params, x, m, stats,
                                      n_timing_runs=nt),
            "fused_iters_per_sec": _ips(ex_f, params, x, m,
                                        n_timing_runs=nt),
            "seq_flops": _compiled_flops(ex_s),
            "fused_flops": _compiled_flops(ex_f),
        }
        if step_tp is not None:
            ex_t = step_tp.lower(params, x, m, stats).compile()
            row["tp_iters_per_sec"] = _ips(ex_t, params, x, m, stats,
                                           n_timing_runs=nt)
            # SPMD cost analysis counts ONE device's program, so this is
            # the per-device share of the blocked-slab step
            row["tp_flops_per_device"] = _compiled_flops(ex_t)
            if row["fused_flops"] and row["tp_flops_per_device"]:
                row["tp_step_flop_partition"] = round(
                    row["fused_flops"] / row["tp_flops_per_device"], 2
                )
        rows.append(row)
        print(json.dumps({"scaling_row": row}), file=sys.stderr, flush=True)
    out["scaling"] = rows
    out["scaling_dims"] = {"N": Ns, "r": rs, "p": ps}

    # -- the slab partition itself: the scan is the thing the time axis
    #    shards, so its per-device FLOP share is the acceptance quantity
    #    (the step-level ratio above also carries the replicated collapse
    #    + element build + M-step; see models/emtime.py)
    T_big = Ts[-1]
    params, x, m, stats = _panel(T_big, Ns, rs, ps, seed=1)
    params = params._replace(Q=_psd_floor(params.Q))
    C, b, ld_R, xRx, n_obs, llc = _collapse_obs_stats(
        params.lam, params.R, x, stats
    )
    elems = pk.filter_elements_collapsed(params, C, b)
    ex_a = jax.jit(
        lambda e: jax.lax.associative_scan(pk.combine_filter, e)
    ).lower(elems).compile()
    slab = {"T": T_big, "assoc_scan_flops": _compiled_flops(ex_a)}
    if tb > 1:
        mesh = data_mesh(1, hosts=1, t_blocks=tb)
        ex_b = jax.jit(
            lambda e: sharded_scan(
                pk.combine_filter, e, mesh, local="sequential"
            )
        ).lower(elems).compile()
        slab["slab_scan_flops_per_device"] = _compiled_flops(ex_b)
        if slab["assoc_scan_flops"] and slab["slab_scan_flops_per_device"]:
            slab["slab_partition_flop_ratio"] = round(
                slab["assoc_scan_flops"]
                / slab["slab_scan_flops_per_device"], 2
            )
    out["slab_partition"] = slab
    print(json.dumps({"slab_partition": slab}), file=sys.stderr, flush=True)

    # -- wall-clock crossover in T: sequential wins small T (one cheap
    #    combine per step), the log-depth fused form wins large T
    Tx = (250, 1_000) if smoke else (250, 1_000, 4_000, 16_000)
    xrows, crossover_T = [], None
    for T in Tx:
        params, x, m, stats = _panel(T, Ns, rs, ps, seed=2)
        ex_s = jax.jit(em_step_stats).lower(params, x, m, stats).compile()
        ex_f = jax.jit(em_step_assoc_fused).lower(params, x, m).compile()
        ips_s = _ips(ex_s, params, x, m, stats)
        ips_f = _ips(ex_f, params, x, m)
        ratio = round(ips_f / ips_s, 3) if ips_s else None
        xrows.append({"T": T, "seq_iters_per_sec": ips_s,
                      "fused_iters_per_sec": ips_f,
                      "fused_over_seq": ratio})
        if crossover_T is None and ratio is not None and ratio >= 1.0:
            crossover_T = T
    out["crossover"] = {"rows": xrows, "crossover_T": crossover_T}
    print(json.dumps({"crossover": out["crossover"]}), file=sys.stderr,
          flush=True)

    # acceptance summary (None when the contributing leg was gated)
    fu = ref.get("fused_vs_unfused_speedup")
    out["accept_fused_beats_unfused_refscale"] = (
        None if fu is None else bool(fu >= 1.0)
    )
    sr = slab.get("slab_partition_flop_ratio")
    out["accept_slab_partition_3x"] = None if sr is None else bool(sr >= 3.0)
    out["accept_assoc_seq_crossover"] = bool(crossover_T is not None)
    print(json.dumps(out), flush=True)


def time_parallel_orchestrate(force_cpu: bool):
    """--time-parallel: run the parallel-in-time EM legs in a child with
    the forced 8-device flag set BEFORE jax initializes (same reason
    --multichip and --composed are children), then persist
    docs/BENCH_time_parallel.json."""
    flags = os.environ.get("XLA_FLAGS", "")
    if "host_platform_device_count" not in flags:
        flags = (flags + " --xla_force_host_platform_device_count=8").strip()
    child_args = ["--run-time-parallel"]
    if force_cpu or os.environ.get("DFM_BENCH_FORCE_CPU") == "1":
        child_args.append("--force-cpu")
    pr = _run_child(child_args, env_extra={"XLA_FLAGS": flags},
                    timeout_s=7200)
    fragment = _parse_fragment(pr)
    if fragment is None:
        print("bench: time-parallel child produced no JSON", file=sys.stderr)
        sys.exit(2)
    path = os.path.join(REPO, "docs", "BENCH_time_parallel.json")
    tmp = path + ".tmp"
    with open(tmp, "w") as fh:
        json.dump(fragment, fh, indent=1, sort_keys=True)
    os.replace(tmp, path)
    print(json.dumps(fragment))
    sys.exit(pr.returncode)


def crossover_table():
    """Manual mode: Pallas-vs-XLA crossover sweep on the live chip; prints a
    markdown table for ops/pallas_gram.py and docs/PARITY.md."""
    import jax.numpy as jnp
    import numpy as np

    from dynamic_factor_models_tpu.ops.pallas_gram import (
        masked_gram_pallas,
        masked_gram_xla,
    )
    import jax
    from jax import lax

    sizes = [
        (224, 256), (512, 512), (1024, 1024), (1024, 2048),
        (2048, 2048), (2048, 4096), (4096, 4096), (4096, 8192),
    ]
    K = LARGE_R
    print(
        "| T x N | cells | XLA us | Pallas us | speedup "
        "| Pallas bf16 us | bf16 speedup |"
    )
    print("|---|---|---|---|---|---|---|")
    for T, N in sizes:
        rng = np.random.default_rng(0)
        X = jnp.asarray(rng.standard_normal((T, K)), jnp.float32)
        Y = jnp.asarray(rng.standard_normal((T, N)), jnp.float32)
        W = jnp.asarray((rng.random((T, N)) > 0.2), jnp.float32)
        X16, Y16, W16 = (a.astype(jnp.bfloat16) for a in (X, Y, W))
        tx = _gram_loop_seconds(masked_gram_xla, X, Y, W, 300, n_timing=3)
        tp = _gram_loop_seconds(masked_gram_pallas, X, Y, W, 300, n_timing=3)
        tp16 = _gram_loop_seconds(
            masked_gram_pallas, X16, Y16, W16, 300, n_timing=3
        )
        print(
            f"| {T} x {N} | 2^{int(np.log2(T*N))} | {tx*1e6:.1f} "
            f"| {tp*1e6:.1f} | {tx/tp:.2f}x "
            f"| {tp16*1e6:.1f} | {tp/tp16:.2f}x |"
        )


# ---------------------------------------------------------------------------
# reference-scale latency decomposition (round-4 verdict item 3): why does
# one chip behind a tunnel lose to the host CPU at T=222, and at what (T, N,
# n_reps) does it cross over?  Measured, not argued: an unroll sweep finds
# the chip's best scan configuration, then a (T, N) tiling grid + a
# bootstrap-replication grid locate the crossover against a pre-staged CPU
# twin of the exact same protocol (each side at its own best unroll).
# ---------------------------------------------------------------------------

REFSCALE_STAGED = os.path.join(REPO, "build", "refscale_cpu.json")


def run_em_refscale(force_cpu: bool, grid: bool):
    """Child mode: reference-scale latency measurements at the ambient
    DFM_SCAN_UNROLL (ssm._SCAN_UNROLL is read once at import, so each
    unroll variant needs its own process).  Prints one JSON line.

    Base: dispatch round-trip and EM iters/sec on the real 222x139 panel
    (on-device while_loop, 100 fixed iterations, best of 3).  --grid adds
    the (T, N) tiling cells and the wild-bootstrap replication grid."""
    import jax

    if force_cpu:
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np

    from dynamic_factor_models_tpu.io.cache import cached_dataset
    from dynamic_factor_models_tpu.models import ssm as _ssm
    from dynamic_factor_models_tpu.models.dfm import DFMConfig, estimate_factor
    from dynamic_factor_models_tpu.models.emloop import run_em_loop
    from dynamic_factor_models_tpu.models.favar import wild_bootstrap_irfs
    from dynamic_factor_models_tpu.models.ssm import (
        SSMParams,
        compute_panel_stats,
        em_step_stats,
    )
    from dynamic_factor_models_tpu.ops.linalg import standardize_data
    from dynamic_factor_models_tpu.ops.masking import fillz, mask_of

    dev = jax.devices()[0]
    out = {
        "platform": dev.platform,
        "scan_unroll": _ssm._SCAN_UNROLL,
    }

    # fixed dispatch+transfer floor of one trivial program round-trip:
    # the tunnel's contribution to every host-synced step
    f_null = jax.jit(lambda v: v + 1.0)
    z = jnp.zeros(())
    f_null(z).block_until_ready()
    ts = []
    for _ in range(30):
        t0 = time.perf_counter()
        f_null(z).block_until_ready()
        ts.append(time.perf_counter() - t0)
    out["dispatch_roundtrip_us"] = round(float(np.median(ts)) * 1e6, 1)

    ds = cached_dataset("Real")
    est = jnp.asarray(np.asarray(ds.bpdata))[:, np.asarray(ds.inclcode) == 1][
        2:224
    ]
    xstd, _ = standardize_data(est)
    xz0, m0 = fillz(xstd), mask_of(xstd).astype(xstd.dtype)
    r, p = 4, 4

    def em_ips(xz, m, n_iter=100):
        N = xz.shape[1]
        params = SSMParams(
            lam=jnp.zeros((N, r), xz.dtype).at[:, 0].set(1.0),
            R=jnp.ones(N, xz.dtype),
            A=jnp.concatenate(
                [0.5 * jnp.eye(r, dtype=xz.dtype)[None],
                 jnp.zeros((p - 1, r, r), xz.dtype)]
            ),
            Q=jnp.eye(r, dtype=xz.dtype),
        )
        stats = compute_panel_stats(xz, m)
        run_em_loop(em_step_stats, params, (xz, m, stats), 0.0, n_iter)
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            _, _, n_ran, _ = run_em_loop(
                em_step_stats, params, (xz, m, stats), 0.0, n_iter
            )
            best = min(best, time.perf_counter() - t0)
        return round(n_ran / best, 2)

    out["em_refscale_ips"] = em_ips(xz0, m0)

    if grid:
        T0 = xz0.shape[0]
        for mult in (2, 4, 8):
            out[f"em_ips_T{T0 * mult}"] = em_ips(
                jnp.tile(xz0, (mult, 1)), jnp.tile(m0, (mult, 1))
            )
        out[f"em_ips_N{4 * xz0.shape[1]}"] = em_ips(
            jnp.tile(xz0, (1, 4)), jnp.tile(m0, (1, 4))
        )
        out[f"em_ips_T{4 * T0}_N{4 * xz0.shape[1]}"] = em_ips(
            jnp.tile(xz0, (4, 4)), jnp.tile(m0, (4, 4))
        )

        cfg = DFMConfig(nfac_u=4, tol=1e-6, max_iter=2000)
        F, _ = estimate_factor(ds.bpdata, ds.inclcode, 2, 223, cfg)
        for reps in (1000, 4000, 16000):
            run = lambda seed: wild_bootstrap_irfs(
                F, 4, 2, 223, horizon=24, n_reps=reps, seed=seed
            )
            run(0).draws.block_until_ready()
            best = float("inf")
            for s in (1, 2):
                t0 = time.perf_counter()
                run(s).draws.block_until_ready()
                best = min(best, time.perf_counter() - t0)
            out[f"bootstrap_{reps}rep_s"] = round(best, 4)

    print(json.dumps(out), flush=True)


def _refscale_measure(force_cpu: bool):
    """Unroll sweep (one child per DFM_SCAN_UNROLL) then the grid at the
    winning unroll — shared by the live section and the CPU staging."""
    cpu_flag = ["--force-cpu"] if force_cpu else []
    out = {}
    best_u, best_ips = None, -1.0
    for u in (4, 8, 16):
        pr = _run_child(
            ["--run-em-refscale", *cpu_flag],
            env_extra={"DFM_SCAN_UNROLL": str(u)},
            timeout_s=1500,
        )
        o = _parse_fragment(pr) if pr.returncode == 0 else None
        if not o or "em_refscale_ips" not in o:
            continue
        out[f"em_refscale_ips_unroll{u}"] = o["em_refscale_ips"]
        out.setdefault("dispatch_roundtrip_us", o.get("dispatch_roundtrip_us"))
        # which backend the children ACTUALLY ran on (they re-initialize
        # their own jax backend): the section refuses to record chip
        # evidence from a leg that silently landed on CPU
        out.setdefault("refscale_platform", o.get("platform"))
        if o["em_refscale_ips"] > best_ips:
            best_u, best_ips = u, o["em_refscale_ips"]
    if best_u is None:
        return out
    out["em_refscale_best_unroll"] = best_u
    out["em_refscale_best_ips"] = best_ips
    pr = _run_child(
        ["--run-em-refscale", "--grid", *cpu_flag],
        env_extra={"DFM_SCAN_UNROLL": str(best_u)},
        timeout_s=3000,
    )
    o = _parse_fragment(pr) if pr.returncode == 0 else None
    if o:
        for k, v in o.items():
            if k.startswith(("em_ips_", "bootstrap_")):
                out[k] = v
    return out


def stage_refscale():
    """Pre-stage the CPU twin of the reference-scale decomposition so the
    live window only spends time on the chip's own legs."""
    fields = _refscale_measure(force_cpu=True)
    os.makedirs(os.path.join(REPO, "build"), exist_ok=True)
    payload = {"code_rev": _parity_code_rev(), **fields}
    tmp = REFSCALE_STAGED + ".tmp"
    with open(tmp, "w") as fh:
        json.dump(payload, fh, indent=1)
    os.replace(tmp, REFSCALE_STAGED)
    print(f"staged CPU refscale twin: {REFSCALE_STAGED}", file=sys.stderr)


def refscale_staged_fresh() -> bool:
    try:
        with open(REFSCALE_STAGED) as fh:
            return json.load(fh).get("code_rev") == _parity_code_rev()
    except (OSError, ValueError):
        return False


def refscale_section():
    """Live leg + crossover summary against the staged CPU twin."""
    out = _refscale_measure(force_cpu=False)
    if not _is_tpu_platform(out.get("refscale_platform", "")):
        # the children landed on CPU (leaked platform env / tunnel fell
        # over between the parent's check and the child's init): never
        # compute "chip" ratios from a CPU leg
        out["refscale_live_leg_on_tpu"] = False
        return out
    out["refscale_live_leg_on_tpu"] = True
    staged = None
    if refscale_staged_fresh():
        try:
            with open(REFSCALE_STAGED) as fh:
                staged = json.load(fh)
        except (OSError, ValueError):
            staged = None
    if not staged:
        out["refscale_cpu_staged"] = False
        return out
    out["refscale_cpu_staged"] = True
    # per-cell ratios: >1 means the chip wins that cell (ips: higher is
    # better; bootstrap seconds: lower is better)
    for k in sorted(out):
        c = staged.get(k)
        if not isinstance(c, (int, float)) or not isinstance(
            out[k], (int, float)
        ):
            continue
        if k.startswith(("em_refscale_best_ips", "em_ips_")):
            out[f"{k}_tpu_over_cpu"] = round(out[k] / c, 3)
        elif k.startswith("bootstrap_"):
            out[f"{k}_tpu_over_cpu"] = round(c / out[k], 3)
    # measured crossovers: smallest T (N fixed) and smallest n_reps where
    # the chip matches or beats the host.  Emitted ONLY when the grid leg
    # actually produced cells on both sides — a timed-out/crashed grid
    # child must not be recorded as "measured, chip never crossed"
    grid_t = [
        (k, int(k.split("T")[1]))
        for k in out
        if k.startswith("em_ips_T") and "_N" not in k and "_tpu" not in k
        and isinstance(staged.get(k), (int, float))
    ]
    if grid_t:
        t_cells = [("em_refscale_best_ips", 222)] + grid_t
        cross_t = [
            T
            for k, T in sorted(t_cells, key=lambda kv: kv[1])
            if isinstance(staged.get(k), (int, float))
            and isinstance(out.get(k), (int, float))
            and out[k] >= staged[k]
        ]
        # 0 = no crossover within the measured grid (None would be dropped
        # by the evidence store, and "never crossed" is itself a finding)
        out["em_T_crossover"] = cross_t[0] if cross_t else 0
    grid_b = [
        reps
        for reps in (1000, 4000, 16000)
        if isinstance(staged.get(f"bootstrap_{reps}rep_s"), (int, float))
        and isinstance(out.get(f"bootstrap_{reps}rep_s"), (int, float))
    ]
    if grid_b:
        cross_b = [
            reps
            for reps in grid_b
            if out[f"bootstrap_{reps}rep_s"]
            <= staged[f"bootstrap_{reps}rep_s"]
        ]
        out["bootstrap_reps_crossover"] = cross_b[0] if cross_b else 0
    return out


EVIDENCE_PATH = os.path.join(REPO, "docs", "TPU_EVIDENCE.json")


def _load_evidence():
    """The durable evidence store's contents, or None."""
    try:
        with open(EVIDENCE_PATH) as fh:
            return json.load(fh)
    except (OSError, ValueError):
        return None


def _update_live_evidence(fields: dict):
    """Accumulate live-TPU-measured fields into the durable evidence store
    (docs/TPU_EVIDENCE.json).  The tunnel opens in short windows hours
    apart, so every live number is written to disk the moment it exists;
    the orchestrator merges the store (prefixed tpu_live_*) into any
    CPU-fallback report so evidence from an earlier window survives a
    wedged driver-time tunnel."""
    if fields.get("tpu_unreachable", True):
        return
    ev = _load_evidence() or {}
    new = {
        k: v
        for k, v in fields.items()
        if v is not None
        and k not in ("remainder", "tpu_unreachable")
        and ev.get(k) != v
    }
    if not new:
        return
    ev.update(new)
    now = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
    ev["captured_at_utc"] = now
    # per-window capture log: each write records WHICH fields it set, so a
    # field's provenance stays traceable to the window that measured it
    # even after later windows update other fields
    ev.setdefault("windows", []).append({"at": now, "fields": sorted(new)})
    tmp = EVIDENCE_PATH + ".tmp"
    try:
        with open(tmp, "w") as fh:
            json.dump(ev, fh, indent=1, sort_keys=True)
        os.replace(tmp, EVIDENCE_PATH)
    except OSError as e:
        # never kill a measuring child over the store, but a lost write of
        # scarce live-window evidence must be loud in the child's stderr
        print(f"bench: EVIDENCE STORE WRITE FAILED: {e}", file=sys.stderr)


def _persist_partial(fields: dict):
    """Write the accumulated section results to DFM_BENCH_PARTIAL (atomic
    rename) after every completed section: if the tunnel wedges mid-run and
    this child dies, the orchestrator salvages the TPU sections that DID
    finish instead of losing the whole run (round-3 verdict item 2).  Live
    TPU fields are additionally folded into the durable evidence store."""
    _update_live_evidence(fields)
    path = os.environ.get("DFM_BENCH_PARTIAL")
    if not path:
        return
    tmp = path + ".tmp"
    with open(tmp, "w") as fh:
        json.dump(fields, fh)
    os.replace(tmp, path)


def _is_tpu_platform(platform: str) -> bool:
    """Injectable for the orchestration tests (tests/test_bench_remainder.py
    stub it to exercise the unattended remainder path on CPU)."""
    return platform in ("tpu", "axon")


def obs_overhead_section(smoke: bool = True):
    """Observability-overhead leg: the SAME small EM estimate timed with
    telemetry disabled and enabled (RunRecord + roofline ledger + flight
    ring armed-but-idle), plus the ledger's own cumulative snapshot —
    the live check that the PR 17 instrumentation stays inside the
    telemetry budget on the estimation path.  Returns the fields dict
    (the remainder folds it in; --obs-overhead prints it)."""
    import tempfile

    import jax.numpy as jnp
    import numpy as np

    from dynamic_factor_models_tpu.models.dfm import DFMConfig
    from dynamic_factor_models_tpu.models.ssm import estimate_dfm_em
    from dynamic_factor_models_tpu.utils import compile as cc
    from dynamic_factor_models_tpu.utils import roofline, telemetry

    T, N = (96, 32) if smoke else (224, 128)
    n_iter = 10
    rng = np.random.default_rng(0)
    x = rng.standard_normal((T, N)).astype(np.float32)
    cfg = DFMConfig(nfac_u=2)
    # ledger costs are captured at AOT registration — precompile the
    # guarded-loop executable the runs below dispatch
    cc.precompile(
        cc.CompileSpec(
            T=T, N=N, r=2, p=cfg.n_factorlag,
            dtype=str(jnp.asarray(0.0).dtype),  # f64 iff x64 is on
            kernels=("em_loop_guarded",), max_em_iter=n_iter,
        ),
        warmup=False,
    )

    def run():
        estimate_dfm_em(
            x, np.ones(N), 0, T - 1, cfg, max_em_iter=n_iter, tol=0.0,
            bucket=True,
        )

    # remember the caller's telemetry state: disable() is sticky (it
    # shadows DFM_TELEMETRY), and the remainder's later sections must
    # keep recording into the live-window sink
    prev_enabled = telemetry._explicit_enabled
    prev_sink = telemetry._explicit_sink
    telemetry.disable()
    run()  # compile any remaining misses outside both timings
    t_off = _time_fixed_iters(run)
    with tempfile.TemporaryDirectory() as d:
        telemetry.enable(sink=os.path.join(d, "obs.jsonl"))
        try:
            run()  # warm the enabled path (hist registration etc.)
            t_on = _time_fixed_iters(run)
            snap = roofline.publish_gauges()
        finally:
            telemetry.disable()
            telemetry._explicit_enabled = prev_enabled
            telemetry._explicit_sink = prev_sink
    out = {
        "obs_em_wall_s_off": round(t_off, 4),
        "obs_em_wall_s_on": round(t_on, 4),
        "obs_overhead_pct": round(100.0 * (t_on - t_off) / t_off, 2),
        "obs_ledger_flops_total": round(snap["flops_total"], 0),
        "obs_ledger_bytes_total": round(snap["bytes_total"], 0),
        "obs_ledger_kernels": len(snap["per_kernel"]),
        "obs_comm_axes": sorted(snap["comm"]["per_axis"]),
        "mfu_peak_source": snap["mfu_peak_source"],
        "flop_proxy": snap["flop_proxy"],
    }
    if "mfu_pct" in snap:
        out["obs_mfu_pct"] = snap["mfu_pct"]
    if "intensity_flops_per_byte" in snap:
        out["obs_intensity_flops_per_byte"] = snap[
            "intensity_flops_per_byte"
        ]
    return out


def run_tpu_remainder(force_cpu: bool = False):
    """Child mode for short tunnel windows: ONLY the TPU sections the
    2026-07-31 salvaged live record is missing, cheapest compile surface
    first (pallas -> device parity -> large panel -> refscale
    decomposition -> crossover), persisting to DFM_BENCH_PARTIAL after
    every section so a mid-run wedge keeps whatever finished.  Prints the
    accumulated JSON on stdout.

    NOTE: call only after a successful tunnel probe (tools/tpu_watch.sh
    does) — a direct jax.devices() against a wedged tunnel hangs rather
    than failing.  --force-cpu pins the CPU platform first, which drives
    the no-TPU error exit deterministically."""
    import io as _io
    from contextlib import redirect_stdout

    import jax

    if force_cpu:
        jax.config.update("jax_platforms", "cpu")
    dev = jax.devices()[0]
    if not _is_tpu_platform(dev.platform):
        print(json.dumps({"error": f"no TPU device ({dev.platform})"}), flush=True)
        sys.exit(2)
    partial = {"device": str(dev), "tpu_unreachable": False, "remainder": True}
    _persist_partial(partial)

    partial.update(pallas_section())
    _persist_partial(partial)
    print(json.dumps(partial), file=sys.stderr, flush=True)

    from dynamic_factor_models_tpu.io.cache import cached_dataset

    ds = cached_dataset("Real")
    with jax.default_matmul_precision("highest"):
        parity = device_parity_checks(ds)
    partial.update(parity)
    partial["parity_ok"] = all(
        parity.get(k) is not None and parity[k] <= thresh
        for k, thresh in PARITY_THRESHOLDS.items()
    )
    _persist_partial(partial)
    print(json.dumps(partial), file=sys.stderr, flush=True)

    def _persist_large(fields):
        snap = dict(partial)
        snap.update(fields)
        _persist_partial(snap)

    partial.update(large_panel_section(True, persist=_persist_large))
    _persist_partial(partial)
    print(json.dumps(partial), file=sys.stderr, flush=True)

    # reference-scale latency decomposition BEFORE the crossover sweep:
    # the decomposition (win-or-prove-the-floor) is a verdict done-bar,
    # the markdown sweep is documentation — a short window should capture
    # the former first
    partial.update(refscale_section())
    _persist_partial(partial)
    print(json.dumps(partial), file=sys.stderr, flush=True)

    # sharded/MFU leg: a child process because the forced-8-device XLA
    # flag must precede jax init (same reason --multichip is a child of
    # the orchestrator).  A failed leg records the error and moves on —
    # the remainder's later sections are independent of it.
    mc_flags = os.environ.get("XLA_FLAGS", "")
    if "host_platform_device_count" not in mc_flags:
        mc_flags = (
            mc_flags + " --xla_force_host_platform_device_count=8"
        ).strip()
    mc_args = ["--run-multichip"]
    if force_cpu:
        mc_args.append("--force-cpu")
    mc_pr = _run_child(mc_args, env_extra={"XLA_FLAGS": mc_flags})
    mc = _parse_fragment(mc_pr)
    if mc is None:
        partial["multichip"] = {"error": "multichip child produced no JSON"}
    else:
        partial["multichip"] = mc
    _persist_partial(partial)
    print(json.dumps(partial), file=sys.stderr, flush=True)

    # composed transform-stack smoke: same 8-device child pattern — the
    # full grid is bench.py --composed; the smoke proves the composed
    # kernels (collapsed x steady x sharded AR steps) compile and run on
    # the live chip inside a short window
    cp_args = ["--run-composed", "--smoke"]
    if force_cpu:
        cp_args.append("--force-cpu")
    cp_pr = _run_child(cp_args, env_extra={"XLA_FLAGS": mc_flags})
    cp = _parse_fragment(cp_pr)
    partial["composed_smoke"] = (
        cp if cp is not None
        else {"error": "composed child produced no JSON"}
    )
    _persist_partial(partial)
    print(json.dumps(partial), file=sys.stderr, flush=True)

    # parallel-in-time smoke: same 8-device child pattern — the full
    # T-scaling grid is bench.py --time-parallel; the smoke proves the
    # fused collapsed elements and the blocked-slab scan compile and run
    # on the live chip inside a short window
    tp_args = ["--run-time-parallel", "--smoke"]
    if force_cpu:
        tp_args.append("--force-cpu")
    tp_pr = _run_child(tp_args, env_extra={"XLA_FLAGS": mc_flags})
    tp = _parse_fragment(tp_pr)
    partial["time_parallel_smoke"] = (
        tp if tp is not None
        else {"error": "time-parallel child produced no JSON"}
    )
    _persist_partial(partial)
    print(json.dumps(partial), file=sys.stderr, flush=True)

    # multi-host smoke: the two-OS-process ("dcn", "ici") mesh leg at one
    # small size — proves the process-spanning sharded step compiles and
    # runs and the FLOP-partition accounting holds; the full N in
    # {4k, 16k} grid is bench.py --multihost on a long window
    partial["multihost_smoke"] = multihost_section(force_cpu, smoke=True)
    _persist_partial(partial)
    print(json.dumps(partial), file=sys.stderr, flush=True)

    # particle-filter scenario smoke: proves the SMC scan compiles and
    # runs on the live chip; the full P in {1k, 10k} sweep is
    # bench.py --scenarios-nl on a long window
    buf = _io.StringIO()
    with redirect_stdout(buf):
        nl = scenarios_nl_section(smoke=True)
    partial["scenarios_nl_smoke"] = nl
    _persist_partial(partial)
    print(json.dumps(partial), file=sys.stderr, flush=True)

    # serving-resilience drill: cheap (tiny panel, no extra compile
    # surface beyond the serving bucket) and platform-agnostic, but the
    # live record wants the on-device envelope-overhead number
    buf = _io.StringIO()
    with redirect_stdout(buf):
        cs = chaos_serving_section()
    partial.update(cs)
    _persist_partial(partial)
    print(json.dumps(partial), file=sys.stderr, flush=True)

    # observability-overhead smoke: proves the roofline ledger + flight
    # ring keep the estimation path inside the telemetry budget on the
    # live chip (and records the on-device ledger MFU fields)
    partial["obs_overhead"] = obs_overhead_section(smoke=True)
    _persist_partial(partial)
    print(json.dumps(partial), file=sys.stderr, flush=True)

    buf = _io.StringIO()
    with redirect_stdout(buf):
        crossover_table()
    partial["crossover_markdown"] = buf.getvalue()
    _persist_partial(partial)
    print(json.dumps(partial), flush=True)
    if not partial["parity_ok"]:
        # all sections captured, but the device-parity gate failed: exit 1
        # (distinct from the incomplete-run exit) so the watcher surfaces
        # the failure instead of declaring the evidence complete
        print("bench: REMAINDER COMPLETE BUT PARITY FAILED", file=sys.stderr)
        sys.exit(1)


def bench_main(force_cpu: bool):
    import jax

    if force_cpu:
        from dynamic_factor_models_tpu.utils.backend import fall_back_to_cpu

        fall_back_to_cpu("orchestrator probe exhausted", caller="bench")
    import jax.numpy as jnp
    import numpy as np

    from dynamic_factor_models_tpu.io.cache import cached_dataset
    from dynamic_factor_models_tpu.models.dfm import DFMConfig, estimate_factor
    from dynamic_factor_models_tpu.models.emloop import run_em_loop
    from dynamic_factor_models_tpu.models.favar import wild_bootstrap_irfs
    from dynamic_factor_models_tpu.models.ssm import (
        SSMParams,
        compute_panel_stats,
        em_step_assoc,
        em_step_sqrt,
        em_step_stats,
    )
    from dynamic_factor_models_tpu.ops.linalg import standardize_data
    from dynamic_factor_models_tpu.ops.masking import fillz, mask_of

    dev = jax.devices()[0]
    tpu_ok = _is_tpu_platform(dev.platform)
    ds = cached_dataset("Real")
    partial = {"device": str(dev), "tpu_unreachable": not tpu_ok}

    # headline: 1000-rep wild bootstrap (factors via f32-safe ALS)
    cfg = DFMConfig(nfac_u=4, tol=1e-6, max_iter=2000)
    F, _ = estimate_factor(ds.bpdata, ds.inclcode, 2, 223, cfg)
    n_reps, horizon = 1000, 24
    run = lambda seed: wild_bootstrap_irfs(
        F, 4, 2, 223, horizon=horizon, n_reps=n_reps, seed=seed
    )
    run(0).draws.block_until_ready()  # compile
    t0 = time.perf_counter()
    bs = run(1)
    bs.draws.block_until_ready()
    dt = time.perf_counter() - t0
    partial.update(
        {
            "metric": "favar_irf_wild_bootstrap_1000rep_wallclock",
            "value": round(dt, 4),
            "unit": "s",
            "vs_baseline": round(10.0 / dt, 2),
        }
    )
    _persist_partial(partial)

    # EM on the real included panel: host-synced driver, on-device
    # while_loop (production PanelStats path), and the associative
    # (parallel-in-time) + square-root E-steps
    est = jnp.asarray(np.asarray(ds.bpdata))[:, np.asarray(ds.inclcode) == 1][2:224]
    xstd, _ = standardize_data(est)
    xz, m = fillz(xstd), mask_of(xstd).astype(xstd.dtype)
    r, p, N = 4, 4, xz.shape[1]
    params = SSMParams(
        lam=jnp.zeros((N, r)).at[:, 0].set(1.0),
        R=jnp.ones(N),
        A=jnp.concatenate([0.5 * jnp.eye(r)[None], jnp.zeros((p - 1, r, r))]),
        Q=jnp.eye(r),
    )
    stats = compute_panel_stats(xz, m)
    _, _, _, trace = run_em_loop(
        em_step_stats, params, (xz, m, stats), 0.0, 30, collect_path=True
    )
    em_ips_host = trace.iters_per_sec
    n_dev_iter = 100
    em_ips = {}
    for name, step, args in (
        ("seq", em_step_stats, (xz, m, stats)),
        ("assoc", em_step_assoc, (xz, m)),
        ("sqrt", em_step_sqrt, (xz, m)),
    ):
        run_em_loop(step, params, args, 0.0, n_dev_iter)  # compile
        t1 = time.perf_counter()
        _, _, n_ran, _ = run_em_loop(step, params, args, 0.0, n_dev_iter)
        em_ips[name] = n_ran / (time.perf_counter() - t1)
    partial.update(
        {
            "em_iters_per_sec": round(em_ips["seq"], 2),
            "em_iters_per_sec_host_sync": round(em_ips_host, 2),
            "em_iters_per_sec_assoc": round(em_ips["assoc"], 2),
            "em_iters_per_sec_sqrt": round(em_ips["sqrt"], 2),
        }
    )
    _persist_partial(partial)
    steady = steady_section(xz, m, params, stats, em_ips["seq"])
    partial.update(steady)
    _persist_partial(partial)

    def _persist_large(fields):
        snap = dict(partial)
        snap.update(fields)
        _persist_partial(snap)

    large = large_panel_section(tpu_ok, persist=_persist_large)
    partial.update(large)
    _persist_partial(partial)
    mf = mixed_freq_section()
    partial.update(mf)
    _persist_partial(partial)

    if tpu_ok:
        pallas = pallas_section()
        partial.update(pallas)
        _persist_partial(partial)
        with jax.default_matmul_precision("highest"):
            parity = device_parity_checks(ds)
        parity_ok = all(
            parity.get(k) is not None and parity[k] <= thresh
            for k, thresh in PARITY_THRESHOLDS.items()
        )
    else:
        pallas = {
            "pallas_gram_speedup_large_panel": None,
            "pallas_gram_us_per_call": None,
        }
        parity = {k: None for k in PARITY_THRESHOLDS}
        parity_ok = None  # not checked — requires both backends

    fragment = {
        "metric": "favar_irf_wild_bootstrap_1000rep_wallclock",
        "value": round(dt, 4),
        "unit": "s",
        "vs_baseline": round(10.0 / dt, 2),
        "device": str(dev),
        "tpu_unreachable": not tpu_ok,
        "em_iters_per_sec": round(em_ips["seq"], 2),
        "em_iters_per_sec_host_sync": round(em_ips_host, 2),
        "em_iters_per_sec_assoc": round(em_ips["assoc"], 2),
        "em_iters_per_sec_sqrt": round(em_ips["sqrt"], 2),
        **steady,
        **mf,
        **large,
        **pallas,
        **{
            k: (round(v, 8) if v is not None else None)
            for k, v in parity.items()
        },
        "parity_ok": parity_ok,
    }
    print(json.dumps(fragment))
    if parity_ok is False:
        print(
            f"PARITY FAILURE: {parity} exceeds {PARITY_THRESHOLDS}",
            file=sys.stderr,
        )
        sys.exit(1)


# ---------------------------------------------------------------------------
# orchestrator
# ---------------------------------------------------------------------------


def _probe_tunnel(timeout_s: int):
    """Killable-subprocess device probe; returns (tpu_ok, detail).

    The child inherits the ambient platform config (the axon sitecustomize
    pins jax_platforms at import); a wedged tunnel hangs the child inside
    native code, which the timeout kills — the orchestrator never touches
    jax devices itself.
    """
    probe = (
        "import jax, jax.numpy as jnp\n"
        "jax.block_until_ready(jnp.ones(8).sum())\n"
        "print('DEVICE_PLATFORM', jax.devices()[0].platform)\n"
    )
    try:
        pr = subprocess.run(
            [sys.executable, "-c", probe],
            capture_output=True,
            text=True,
            timeout=timeout_s,
        )
    except subprocess.TimeoutExpired:
        return False, f"device probe exceeded {timeout_s}s (tunnel wedged?)"
    if pr.returncode != 0:
        return False, f"rc={pr.returncode}, stderr={pr.stderr[-300:]!r}"
    for line in pr.stdout.splitlines():
        if line.startswith("DEVICE_PLATFORM"):
            platform = line.split()[-1]
            return _is_tpu_platform(platform), f"platform={platform}"
    return False, f"no DEVICE_PLATFORM line in {pr.stdout[-200:]!r}"


class _FailedChild:
    """Stand-in result for a child that timed out (e.g. the tunnel wedged
    mid-run, after a successful probe): a failed proc, not an exception, so
    the orchestrator keeps any already-computed fallback fragment."""

    returncode = -1
    stdout = ""


def _run_child(args, env_extra=None, timeout_s=3600):
    env = dict(os.environ)
    env.update(env_extra or {})
    try:
        pr = subprocess.run(
            [sys.executable, os.path.join(REPO, "bench.py"), *args],
            capture_output=True,
            text=True,
            timeout=timeout_s,
            env=env,
        )
    except subprocess.TimeoutExpired as exc:
        print(f"bench: child {args[0]} timed out after {exc.timeout}s", file=sys.stderr)
        return _FailedChild()
    sys.stderr.write(pr.stderr)
    return pr


def run_compile_split(cache_dir: str | None):
    """Child: one full compile-once invocation — AOT-precompile the EM
    kernel family for the BASELINE bucket, then run a bucketed EM estimate
    end to end on a reference-scale synthetic panel.  The orchestrator runs
    this child TWICE against one fresh cache dir: the first leg pays XLA
    (compile_s), the second is served by the persistent executable cache,
    and the wall-clock ratio is the cache's measured value.  Prints one
    JSON line."""
    t0 = time.monotonic()
    import jax
    import numpy as np

    from dynamic_factor_models_tpu.models.dfm import DFMConfig
    from dynamic_factor_models_tpu.models.ssm import estimate_dfm_em
    from dynamic_factor_models_tpu.utils import compile as cc

    cc.configure_compilation_cache(cache_dir=cache_dir)
    spec = cc.CompileSpec(
        T=224, N=139,
        kernels=("em_step_stats", "em_step", "em_step_sqrt", "em_loop"),
        max_em_iter=60,
    )
    report = cc.precompile(spec, warmup=False)

    # production dispatch at a DIFFERENT panel shape inside the same
    # (256, 256) bucket: em_loop must come from the AOT registry; the ALS
    # init, panel stats, and smoother readout come from the persistent
    # cache on the warm leg
    rng = np.random.default_rng(0)
    T, N, r = 222, 139, 4
    f = rng.standard_normal((T, r))
    lam = rng.standard_normal((N, r))
    x = f @ lam.T + 0.5 * rng.standard_normal((T, N))
    res = estimate_dfm_em(
        x, np.ones(N), 0, T - 1, DFMConfig(nfac_u=r),
        max_em_iter=spec.max_em_iter, bucket=True,
    )
    cnt = cc.counters()
    ev = cc.persistent_cache_events()
    out = {
        "platform": jax.default_backend(),
        "wall_s": round(time.monotonic() - t0, 2),
        "compile_s": report["compile_s_total"],
        "run_s": round(
            sum(c["run_s"] for c in cnt.values()), 4
        ),
        "cache_hits": ev.get("hits", 0),
        "cache_misses": ev.get("misses", 0),
        "aot_hits": sum(c["aot_hits"] for c in cnt.values()),
        # warm-leg correctness witness: the orchestrator checks the two
        # legs agree bit-for-bit (same data, same program, cached or not)
        "em_loglik_final": float(np.asarray(res.loglik_path)[res.n_iter - 1]),
        "em_n_iter": int(res.n_iter),
    }
    print(json.dumps(out))


def _compile_split(workdir):
    """Cold-vs-warm compile split on CPU: two --run-compile-split children
    share one fresh persistent-cache dir.  Returns the compile_s/run_s/
    cache_hits fields plus warm_cache_speedup for the bench fragment."""
    cache_dir = os.path.join(workdir, "jax_cache")
    env = {
        "JAX_PLATFORMS": "cpu",
        # persist EVERY program (default 0.35 s floor would keep the small
        # readout jits out of the cache and dilute the warm-leg win)
        "DFM_COMPILE_CACHE_MIN_S": "0",
        "DFM_COMPILE_CACHE_DIR": cache_dir,
    }
    out = {}
    cold = _run_child(
        ["--run-compile-split", "--cache-dir", cache_dir],
        env_extra=env, timeout_s=900,
    )
    o_cold = _parse_fragment(cold) if cold.returncode == 0 else None
    if not o_cold:
        print("bench: compile-split cold child failed", file=sys.stderr)
        return out
    warm = _run_child(
        ["--run-compile-split", "--cache-dir", cache_dir],
        env_extra=env, timeout_s=900,
    )
    o_warm = _parse_fragment(warm) if warm.returncode == 0 else None
    out["compile_s"] = o_cold["compile_s"]
    out["run_s"] = o_cold["run_s"]
    out["compile_split_cold_wall_s"] = o_cold["wall_s"]
    if o_warm:
        out["cache_hits"] = o_warm["cache_hits"]
        out["compile_split_warm_wall_s"] = o_warm["wall_s"]
        out["warm_cache_speedup"] = round(
            o_cold["wall_s"] / max(o_warm["wall_s"], 1e-9), 2
        )
        out["compile_split_deterministic"] = (
            o_cold["em_loglik_final"] == o_warm["em_loglik_final"]
            and o_cold["em_n_iter"] == o_warm["em_n_iter"]
        )
    else:
        out["cache_hits"] = o_cold["cache_hits"]
        print("bench: compile-split warm child failed", file=sys.stderr)
    return out


def warm_cache():
    """Populate the repo-local persistent compile cache AND the in-process
    AOT registry for the BASELINE bucket on the ambient platform.  In a
    live TPU window run this FIRST (tools/tpu_watch.sh does) so every
    later section dispatches precompiled executables instead of burning
    tunnel time in XLA.  Prints the precompile report as one JSON line."""
    import jax

    from dynamic_factor_models_tpu.utils import compile as cc

    t0 = time.monotonic()
    report = cc.precompile(cc.CompileSpec(T=224, N=139))
    report["platform"] = jax.default_backend()
    report["wall_s"] = round(time.monotonic() - t0, 2)
    print(json.dumps(report))


def _precision_parity(workdir):
    """CPU f64-vs-f32 of the parity programs (two children; the f32 leg
    reuses the f64 leg's factor for its IRF program, mirroring the device
    comparison's canonical-factor protocol)."""
    import numpy as np

    f64_path = os.path.join(workdir, "parity_f64.npz")
    f32_path = os.path.join(workdir, "parity_f32.npz")
    pr = _run_child(
        ["--run-parity-programs", "--out", f64_path],
        env_extra={"JAX_ENABLE_X64": "1"},
    )
    if pr.returncode != 0:
        return {f"parity_precision_{k}": None for k in ("factor", "smoother", "irf")}
    pr = _run_child(
        ["--run-parity-programs", "--out", f32_path, "--factor-in", f64_path],
        env_extra={"JAX_ENABLE_X64": "0"},
    )
    if pr.returncode != 0:
        return {f"parity_precision_{k}": None for k in ("factor", "smoother", "irf")}
    a = np.load(f64_path)
    b = np.load(f32_path)
    return {
        "parity_precision_factor": round(
            float(
                np.nanmax(
                    np.abs(a["factor"] - _sign_align(a["factor"], b["factor"]))
                )
            ),
            8,
        ),
        "parity_precision_factor_raw": round(
            float(
                np.nanmax(
                    np.abs(
                        a["factor_raw"]
                        - _sign_align(a["factor_raw"], b["factor_raw"])
                    )
                )
            ),
            8,
        )
        if "factor_raw" in a and "factor_raw" in b
        else None,
        "parity_precision_smoother": round(
            float(np.abs(a["smoother"] - b["smoother"]).max()), 8
        ),
        "parity_precision_smoother_sqrt": round(
            float(np.abs(a["smoother_sqrt"] - b["smoother_sqrt"]).max()), 8
        )
        if "smoother_sqrt" in a and "smoother_sqrt" in b
        else None,
        # point IRF only: the PRNG consumes its bit-stream differently with
        # x64 on/off, so the two legs' bootstrap draws are different samples
        # and the quantile diff would measure Monte-Carlo noise, not
        # precision (the device comparison runs one precision on both
        # backends, where draws ARE bit-identical, so it compares quantiles)
        "parity_precision_irf": round(
            float(np.abs(a["irf_point"] - b["irf_point"]).max()), 8
        ),
    }


def _fill_parity_from_precision(fragment):
    """Fill null device-parity fields from the precision-parity legs.

    BENCH_r05 regression: on a CPU-only container `parity_factor` /
    `parity_smoother` / `parity_smoother_sqrt` / `parity_irf` /
    `parity_ok` stayed null even though `_precision_parity` had measured
    the SAME three programs' f64-vs-f32 gap on the same device.  When the
    device comparison could not run, those measurements are the parity
    evidence we have — copy them into the parity_* fields, tag the
    provenance (`parity_source`: "device" when both backends ran,
    "precision" when filled from the one-device pair), and evaluate
    `parity_ok` against the documented thresholds either way, so the
    parsed dict never carries nulls on a healthy run."""
    mapping = {
        "parity_factor": "parity_precision_factor",
        "parity_smoother": "parity_precision_smoother",
        "parity_smoother_sqrt": "parity_precision_smoother_sqrt",
        "parity_irf": "parity_precision_irf",
    }
    filled = False
    for k, src in mapping.items():
        if fragment.get(k) is None and fragment.get(src) is not None:
            fragment[k] = fragment[src]
            filled = True
    if filled:
        fragment["parity_source"] = "precision"
    elif any(fragment.get(k) is not None for k in mapping):
        fragment.setdefault("parity_source", "device")
    if fragment.get("parity_ok") is None:
        vals = {k: fragment.get(k) for k in PARITY_THRESHOLDS}
        if all(v is not None for v in vals.values()):
            fragment["parity_ok"] = all(
                vals[k] <= thr for k, thr in PARITY_THRESHOLDS.items()
            )
    return fragment


def orchestrate():
    import tempfile

    t_start = time.monotonic()
    budget = float(os.environ.get("DFM_BENCH_PROBE_BUDGET_S", "900"))
    probe_timeout = int(os.environ.get("DFM_BENCH_PROBE_TIMEOUT_S", "120"))
    forced_cpu = os.environ.get("DFM_BENCH_FORCE_CPU") == "1"

    attempts = 0
    tpu_ok = False
    if not forced_cpu:
        attempts += 1
        tpu_ok, detail = _probe_tunnel(probe_timeout)
        if not tpu_ok:
            print(f"bench: probe {attempts} failed ({detail})", file=sys.stderr)

    fragment = None
    with tempfile.TemporaryDirectory() as workdir:
        tpu_partial_path = os.path.join(workdir, "tpu_partial.json")

        def _load_partial():
            """TPU sections salvaged from a child that died mid-run."""
            try:
                with open(tpu_partial_path) as fh:
                    return json.load(fh)
            except (OSError, ValueError):
                return None

        def _merge_salvage(fragment):
            """Merge the dead TPU child's completed sections into the CPU
            fragment, prefixed tpu_partial_*.  Skipped when the child
            itself recorded tpu_unreachable (its numbers would be CPU
            numbers mislabeled as TPU evidence)."""
            salvage = _load_partial()
            if fragment is None or not salvage:
                return
            if salvage.get("tpu_unreachable"):
                return
            tpu_fields = {
                k: v
                for k, v in salvage.items()
                if k not in ("device", "tpu_unreachable")
            }
            fragment.update(
                {f"tpu_partial_{k}": v for k, v in tpu_fields.items()}
            )
            fragment["tpu_partial_device"] = salvage.get("device")
            print(
                f"bench: salvaged {len(tpu_fields)} TPU fields from the "
                "dead child's partial file",
                file=sys.stderr,
            )

        if tpu_ok:
            pr = _run_child(
                ["--run-main"],
                env_extra={"DFM_BENCH_PARTIAL": tpu_partial_path},
            )
            fragment = _parse_fragment(pr)
            main_rc = pr.returncode
            if fragment is None:
                # the round-2 failure mode: probe passed, then the tunnel
                # wedged mid-run and the TPU child died/hung.  Labeled CPU
                # numbers beat an empty exit — and any TPU sections the
                # child completed before dying are salvaged from the
                # partial file and merged in, labeled as such.
                print(
                    "bench: TPU main child produced no JSON — "
                    "falling back to CPU",
                    file=sys.stderr,
                )
                pr = _run_child(["--run-main", "--force-cpu"])
                fragment = _parse_fragment(pr)
                main_rc = pr.returncode
                _merge_salvage(fragment)
        else:
            # CPU fallback numbers first — then keep re-probing: the tunnel
            # wedges and recovers on hour scales, so a late success upgrades
            # the whole report to TPU evidence.  The retry budget starts
            # AFTER the fallback child returns (that run can exceed the whole
            # budget by itself), and at least one late probe always happens.
            pr = _run_child(["--run-main", "--force-cpu"])
            fragment = _parse_fragment(pr)
            main_rc = pr.returncode
            t_retry = time.monotonic()
            while not forced_cpu:
                attempts += 1
                tpu_ok, detail = _probe_tunnel(probe_timeout)
                if tpu_ok:
                    print(
                        f"bench: probe {attempts} succeeded — re-running the "
                        "measured sections on TPU",
                        file=sys.stderr,
                    )
                    pr = _run_child(
                        ["--run-main"],
                        env_extra={"DFM_BENCH_PARTIAL": tpu_partial_path},
                    )
                    tpu_fragment = _parse_fragment(pr)
                    if tpu_fragment is not None:
                        fragment = tpu_fragment
                        main_rc = pr.returncode
                    else:
                        _merge_salvage(fragment)
                    break
                print(
                    f"bench: probe {attempts} failed ({detail})", file=sys.stderr
                )
                remaining = budget - (time.monotonic() - t_retry)
                if remaining <= 0:
                    break
                time.sleep(min(60, remaining))

        precision = _precision_parity(workdir)
        compile_split = _compile_split(workdir)

    if fragment is None:
        print("bench: measured child produced no JSON", file=sys.stderr)
        sys.exit(2)
    fragment.update(precision)
    fragment.update(compile_split)
    _fill_parity_from_precision(fragment)
    if fragment.get("tpu_unreachable"):
        # fold in live numbers captured in an earlier tunnel window (clearly
        # labeled with their capture timestamp) so a wedged driver-time
        # tunnel does not erase evidence that already exists on disk
        ev = _load_evidence()
        if ev:
            fragment.update({f"tpu_live_{k}": v for k, v in ev.items()})
            print(
                "bench: merged prior live-window TPU evidence "
                f"({len(ev)} fields from docs/TPU_EVIDENCE.json)",
                file=sys.stderr,
            )
    fragment["probe_attempts"] = attempts
    fragment["probe_elapsed_s"] = round(time.monotonic() - t_start, 1)
    print(json.dumps(fragment))
    sys.exit(main_rc)


def _parse_fragment(pr):
    for line in reversed(pr.stdout.strip().splitlines() or []):
        line = line.strip()
        if line.startswith("{"):
            try:
                return json.loads(line)
            except json.JSONDecodeError:
                continue
    return None


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--run-main", action="store_true")
    ap.add_argument("--force-cpu", action="store_true")
    ap.add_argument("--run-parity-programs", action="store_true")
    ap.add_argument("--out")
    ap.add_argument("--factor-in")
    ap.add_argument("--crossover", action="store_true")
    ap.add_argument("--stage-parity", action="store_true")
    ap.add_argument("--run-tpu-remainder", action="store_true")
    ap.add_argument("--parity-staged-fresh", action="store_true")
    ap.add_argument("--run-em-refscale", action="store_true")
    ap.add_argument("--grid", action="store_true")
    ap.add_argument("--stage-refscale", action="store_true")
    ap.add_argument("--refscale-staged-fresh", action="store_true")
    ap.add_argument("--chaos", action="store_true",
                    help="guardrail overhead + fault-injection recovery "
                         "drills (chaos_section); prints one JSON line")
    ap.add_argument("--serving", action="store_true",
                    help="multi-tenant serving throughput: O(1) online "
                         "ticks + batched-vs-sequential EM refits "
                         "(serving_section); prints one JSON line")
    ap.add_argument("--scenarios", action="store_true",
                    help="scenario-engine throughput: vmapped draw fans "
                         "vs python-looped dispatch + multi-chain Gibbs "
                         "(scenarios_section); prints one JSON line")
    ap.add_argument("--scenarios-nl", action="store_true",
                    help="particle-filter scenario throughput: lg/sv SMC "
                         "particles*steps/sec at P in {1k, 10k} x 8 "
                         "lanes, vmapped-vs-looped dispatch ratio, and "
                         "ESS-floor trip rates (scenarios_nl_section); "
                         "persists docs/BENCH_scenarios_nl.json and "
                         "prints one JSON line (--smoke: P=256, 2 lanes)")
    ap.add_argument("--chaos-serving", action="store_true",
                    help="serving-resilience drill: typed-response "
                         "fraction / availability / degraded fraction "
                         "under a tick_nan storm, recovery latency + "
                         "parity, and envelope overhead vs the bare tick "
                         "executable (chaos_serving_section); prints one "
                         "JSON line")
    ap.add_argument("--load", action="store_true",
                    help="open-loop mixed-traffic load generator at 1k-"
                         "100k shared-fit tenants with p50/p99/p99.9, "
                         "availability, and SLO burn-rate acceptance "
                         "(load_section); persists docs/BENCH_load.json "
                         "and prints one JSON line (--smoke: one tiny "
                         "50-tenant scale)")
    ap.add_argument("--chaos-preempt-drill", action="store_true",
                    help="one injected-preemption resume on a small panel "
                         "(tpu_watch live-window drill); prints one JSON "
                         "line")
    ap.add_argument("--multichip", action="store_true",
                    help="sharded-EM scaling + measured-FLOPs MFU + Pallas "
                         "Gram + parity fill, CPU-testable on the forced "
                         "8-device host platform; prints one JSON line")
    ap.add_argument("--large-n", action="store_true",
                    help="large-N collapse scaling: collapsed-AR EM "
                         "iters/sec + compiled peak memory at N in "
                         "{1k, 10k, 100k} (100k memory-gated to null), "
                         "collapsed-vs-dense speedup at N=512, and a "
                         "1024-lane scenario fan at N=10k "
                         "(large_n_section); prints one JSON line and "
                         "persists docs/BENCH_large_n.json")
    ap.add_argument("--run-multichip", action="store_true")
    ap.add_argument("--multihost", action="store_true",
                    help="multi-host scale-out accounting: 1proc x 8dev "
                         "vs 2 real OS processes x 4dev on the process-"
                         "spanning ('dcn','ici') mesh, per-process FLOP-"
                         "partition speedup + cross-host collective bytes "
                         "at N in {4k, 16k}; persists "
                         "docs/BENCH_multihost.json (CPU legs carry "
                         "flop_proxy labels)")
    ap.add_argument("--run-multihost", action="store_true")
    ap.add_argument("--run-multihost-worker", action="store_true")
    ap.add_argument("--run-pipeline-ab", action="store_true")
    ap.add_argument("--mh-pid", type=int, default=0)
    ap.add_argument("--mh-nproc", type=int, default=2)
    ap.add_argument("--mh-port", default="0")
    ap.add_argument("--composed", action="store_true",
                    help="composed transform-stack grid: N in {1k, 10k, "
                         "100k} x {sequential, collapsed, steady, "
                         "sharded, all} AR EM steps resolved from "
                         "models/transforms stacks, with steady-speedup "
                         "and shard-FLOP-partition acceptance fields; "
                         "runs in an 8-device child and persists "
                         "docs/BENCH_composed.json")
    ap.add_argument("--run-composed", action="store_true")
    ap.add_argument("--time-parallel", action="store_true",
                    help="parallel-in-time EM legs: refscale fused-vs-"
                         "unfused associative steps, T in {1e4, 1e5, 1e6} "
                         "seq/fused/blocked-slab scaling, the slab-scan "
                         "per-device FLOP partition (>= 3x acceptance at "
                         "T=1e6), and the assoc-vs-sequential wall-clock "
                         "crossover in T; runs in an 8-device child and "
                         "persists docs/BENCH_time_parallel.json")
    ap.add_argument("--run-time-parallel", action="store_true")
    ap.add_argument("--smoke", action="store_true",
                    help="with --run-composed: tiny grid (T=96, N=768) "
                         "proving the composed kernels compile and run; "
                         "used by --run-tpu-remainder")
    ap.add_argument("--run-compile-split", action="store_true")
    ap.add_argument("--cache-dir")
    ap.add_argument("--warm-cache", action="store_true")
    ap.add_argument("--obs-overhead", action="store_true",
                    help="observability-overhead smoke: time a small EM "
                         "estimate with telemetry off vs on and report "
                         "the roofline-ledger snapshot (--smoke shrinks "
                         "the panel)")
    ap.add_argument("--telemetry", metavar="PATH",
                    help="record a RunRecord JSONL for every estimation "
                         "call (sets DFM_TELEMETRY; inherited by bench "
                         "child processes)")
    args = ap.parse_args()
    if args.telemetry:
        path = os.path.abspath(args.telemetry)
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        os.environ["DFM_TELEMETRY"] = path
    if args.chaos:
        chaos_section()
        return
    if args.serving:
        serving_section()
        return
    if args.scenarios:
        scenarios_section()
        return
    if args.scenarios_nl:
        scenarios_nl_section(smoke=args.smoke)
        return
    if args.chaos_serving:
        chaos_serving_section()
        return
    if args.chaos_preempt_drill:
        chaos_preempt_drill()
        return
    if args.obs_overhead:
        print(json.dumps(obs_overhead_section(smoke=args.smoke)))
        return
    if args.load:
        load_section(smoke=args.smoke)
        return
    if args.run_pipeline_ab:
        run_pipeline_ab(smoke=args.smoke)
        return
    if args.large_n:
        large_n_section(force_cpu=args.force_cpu)
        return
    if args.composed:
        composed_orchestrate(force_cpu=args.force_cpu)
        return
    if args.run_composed:
        run_composed(force_cpu=args.force_cpu, smoke=args.smoke)
        return
    if args.time_parallel:
        time_parallel_orchestrate(force_cpu=args.force_cpu)
        return
    if args.run_time_parallel:
        run_time_parallel(force_cpu=args.force_cpu, smoke=args.smoke)
        return
    if args.run_multichip:
        run_multichip(force_cpu=args.force_cpu)
        return
    if args.multichip:
        multichip_orchestrate(force_cpu=args.force_cpu)
        return
    if args.run_multihost:
        run_multihost_single(force_cpu=args.force_cpu, smoke=args.smoke)
        return
    if args.run_multihost_worker:
        run_multihost_worker(args.mh_nproc, args.mh_pid, args.mh_port,
                             smoke=args.smoke)
        return
    if args.multihost:
        multihost_orchestrate(force_cpu=args.force_cpu)
        return
    if args.run_compile_split:
        run_compile_split(args.cache_dir)
        return
    elif args.warm_cache:
        warm_cache()
        return
    if args.parity_staged_fresh:
        sys.exit(0 if parity_staged_fresh() else 1)
    elif args.refscale_staged_fresh:
        sys.exit(0 if refscale_staged_fresh() else 1)
    elif args.run_em_refscale:
        run_em_refscale(force_cpu=args.force_cpu, grid=args.grid)
    elif args.stage_refscale:
        stage_refscale()
    elif args.run_tpu_remainder:
        run_tpu_remainder(force_cpu=args.force_cpu)
    elif args.run_parity_programs:
        run_parity_programs(args.out, args.factor_in)
    elif args.run_main:
        bench_main(force_cpu=args.force_cpu)
    elif args.crossover:
        crossover_table()
    elif args.stage_parity:
        stage_parity()
    else:
        orchestrate()


if __name__ == "__main__":
    main()
