#!/usr/bin/env python
"""Headline benchmark: 1000-replication FAVAR IRF wild bootstrap on the
Stock-Watson panel (BASELINE.json north star: < 10 s on TPU).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"} where
vs_baseline = 10s-target / measured wall-clock (>1 is better than target).

Auxiliary fields:
- em_iters_per_sec            state-space EM throughput on the real panel
- pallas_gram_speedup_large_panel   fused Pallas masked-Gram kernel vs the
  XLA einsum pair at 2048 x 4096 (compiled on the real chip — any kernel
  failure is fatal, not swallowed)
- parity_*                    CPU vs TPU max-abs-diff of the same program
  (north star: <= 1e-5 in f64; both backends run f32 here — TPU has no f64
  — so the enforced thresholds below are the documented f32 equivalents).
  Exits nonzero if any parity threshold is exceeded.

If the TPU tunnel is unreachable (liveness probe times out), the bench
falls back to the CPU platform and still reports the bootstrap/EM numbers
with "tpu_unreachable": true; the Pallas and parity sections (TPU-only)
report null.  DFM_BENCH_FORCE_CPU=1 forces this path for testing.
"""

import json
import sys
import time

sys.path.insert(0, __file__.rsplit("/", 1)[0])

import jax
import jax.numpy as jnp
import numpy as np

# documented f32 parity thresholds (north star is 1e-5 in f64; TPU has no
# f64, so parity runs f32 on both backends under
# jax.default_matmul_precision("highest") — measured diffs and rationale
# are recorded in docs/PARITY.md)
PARITY_THRESHOLDS = {
    "parity_factor": 1e-3,
    "parity_smoother": 1e-3,
    "parity_irf": 1e-3,
}


def _sign_align(a, b):
    """Align column signs of b to a (factors are identified up to sign)."""
    s = np.sign(np.nansum(a * b, axis=0))
    s[s == 0] = 1.0
    return b * s


def parity_checks(ds):
    """Run factor ALS, Kalman smoother, and bootstrap point IRFs under
    backend="cpu" and backend="tpu" in one process; return max-abs-diffs.

    Runs under matmul precision "highest" (true-f32 MXU passes; the default
    bf16 passes are a throughput choice, not a correctness baseline).  The
    ALS comparison fixes the iteration count (tol=0, max_iter=60) so both
    backends execute the same number of iterations — with a convergence
    tolerance the two backends stop at slightly different points of the
    same fixed-point approach and the diff measures the tolerance, not the
    numerics."""
    from dynamic_factor_models_tpu.models.dfm import DFMConfig, estimate_factor
    from dynamic_factor_models_tpu.models.favar import wild_bootstrap_irfs
    from dynamic_factor_models_tpu.models.ssm import SSMParams, kalman_smoother
    from dynamic_factor_models_tpu.ops.linalg import standardize_data
    from dynamic_factor_models_tpu.ops.masking import fillz, mask_of

    cfg = DFMConfig(nfac_u=4, tol=0.0, max_iter=60)
    F = {}
    for b in ("cpu", "tpu"):
        f, _ = estimate_factor(ds.bpdata, ds.inclcode, 2, 223, cfg, backend=b)
        F[b] = np.asarray(f)
    parity_factor = float(
        np.nanmax(np.abs(F["cpu"] - _sign_align(F["cpu"], F["tpu"])))
    )

    # smoother: fixed params, standardized included panel
    est = jnp.asarray(np.asarray(ds.bpdata))[:, np.asarray(ds.inclcode) == 1][2:224]
    xstd, _ = standardize_data(est)
    r, p, N = 4, 2, xstd.shape[1]
    rng = np.random.default_rng(0)
    params = SSMParams(
        lam=jnp.asarray(rng.standard_normal((N, r)) * 0.3, jnp.float32),
        R=jnp.ones(N, jnp.float32),
        A=jnp.concatenate(
            [0.5 * jnp.eye(r, dtype=jnp.float32)[None], jnp.zeros((p - 1, r, r), jnp.float32)]
        ),
        Q=jnp.eye(r, dtype=jnp.float32),
    )
    sm = {}
    for b in ("cpu", "tpu"):
        means, _, ll = kalman_smoother(params, xstd, backend=b)
        sm[b] = (np.asarray(means), float(ll))
    parity_smoother = float(np.abs(sm["cpu"][0] - sm["tpu"][0]).max())

    # IRFs: identical factor input (CPU's) on both backends; the bootstrap
    # PRNG (threefry) is bit-identical across backends, so draws compare too
    irf = {}
    for b in ("cpu", "tpu"):
        bs = wild_bootstrap_irfs(
            jnp.asarray(F["cpu"]), 4, 2, 223, horizon=24, n_reps=64, seed=0, backend=b
        )
        irf[b] = (np.asarray(bs.point), np.asarray(bs.quantiles))
    parity_irf = float(
        max(
            np.abs(irf["cpu"][0] - irf["tpu"][0]).max(),
            np.abs(irf["cpu"][1] - irf["tpu"][1]).max(),
        )
    )
    return {
        "parity_factor": parity_factor,
        "parity_smoother": parity_smoother,
        "parity_irf": parity_irf,
    }


def _guarded_device(timeout_s: int = 240):
    """First device touch behind the shared subprocess liveness probe
    (utils.backend.probe_default_device).  When the tunnel is wedged
    (round-2 observation: the axon terminal can hang for hours), fall back
    to the CPU platform and produce real — clearly labeled — numbers
    instead of none: the TPU-only sections (Pallas kernel, CPU<->TPU
    parity) are skipped and the JSON carries "tpu_unreachable": true.

    Returns (device, tpu_ok).  DFM_BENCH_FORCE_CPU=1 exercises the
    fallback deterministically (tests/test_replication_utils.py covers the
    branch; the full fallback run is driven manually)."""
    import os

    from dynamic_factor_models_tpu.utils.backend import (
        fall_back_to_cpu,
        probe_default_device,
    )

    forced = os.environ.get("DFM_BENCH_FORCE_CPU") == "1"
    ok, detail = (False, "forced CPU fallback") if forced else (
        probe_default_device(timeout_s)
    )
    if not ok:
        # shared guard: raises instead of pinning when a backend is already
        # initialized (the pin would silently not take effect and the next
        # array touch would hang on the wedged device)
        fall_back_to_cpu(detail, caller="bench")
        return jax.devices()[0], False
    return jax.devices()[0], True


def main():
    from dynamic_factor_models_tpu.io.cache import cached_dataset
    from dynamic_factor_models_tpu.models.dfm import DFMConfig, estimate_factor
    from dynamic_factor_models_tpu.models.favar import wild_bootstrap_irfs
    from dynamic_factor_models_tpu.models.ssm import em_step, SSMParams
    from dynamic_factor_models_tpu.ops.masking import fillz, mask_of

    dev, tpu_ok = _guarded_device()
    ds = cached_dataset("Real")

    # factors via ALS (f32-safe tolerance; parity is covered below)
    cfg = DFMConfig(nfac_u=4, tol=1e-6, max_iter=2000)
    F, _ = estimate_factor(ds.bpdata, ds.inclcode, 2, 223, cfg)

    n_reps, horizon = 1000, 24
    run = lambda seed: wild_bootstrap_irfs(
        F, 4, 2, 223, horizon=horizon, n_reps=n_reps, seed=seed
    )
    run(0).draws.block_until_ready()  # compile
    t0 = time.perf_counter()
    bs = run(1)
    bs.draws.block_until_ready()
    dt = time.perf_counter() - t0

    # auxiliary: EM iterations/sec on the included panel, measured through
    # the library's own convergence driver (models/emloop.run_em_loop): the
    # host-synced path reports iters/sec from its ConvergenceTrace result
    # object; the on-device lax.while_loop path is timed over a full run
    est = jnp.asarray(np.asarray(ds.bpdata))[:, np.asarray(ds.inclcode) == 1][2:224]
    from dynamic_factor_models_tpu.models.emloop import run_em_loop
    from dynamic_factor_models_tpu.ops.linalg import standardize_data

    xstd, _ = standardize_data(est)
    xz, m = fillz(xstd), mask_of(xstd)
    r, p, N = 4, 4, xz.shape[1]
    params = SSMParams(
        lam=jnp.zeros((N, r)).at[:, 0].set(1.0),
        R=jnp.ones(N),
        A=jnp.concatenate([0.5 * jnp.eye(r)[None], jnp.zeros((p - 1, r, r))]),
        Q=jnp.eye(r),
    )
    _, _, _, trace = run_em_loop(
        em_step, params, (xz, m.astype(xz.dtype)), 0.0, 30, collect_path=True
    )
    em_ips_host = trace.iters_per_sec
    n_dev_iter = 100
    run_em_loop(em_step, params, (xz, m.astype(xz.dtype)), 0.0, n_dev_iter)  # compile
    t1 = time.perf_counter()
    _, _, n_ran, _ = run_em_loop(
        em_step, params, (xz, m.astype(xz.dtype)), 0.0, n_dev_iter
    )
    em_ips = n_ran / (time.perf_counter() - t1)

    # auxiliary: fused Pallas masked-Gram vs XLA einsum at large-panel scale
    # (the regime beyond the 224 x 233 reference panel the kernel targets).
    # No exception guard: if the compiled kernel cannot run on this chip the
    # bench must fail visibly (round-1 lesson), not report null.  Skipped
    # entirely in the CPU fallback (the kernel is a TPU Mosaic program).
    if tpu_ok:
        from dynamic_factor_models_tpu.ops.pallas_gram import (
            masked_gram_pallas,
            masked_gram_xla,
        )
        from jax import lax

        rng = np.random.default_rng(0)
        Tbig, Nbig, K = 2048, 4096, 8
        Xb = jnp.asarray(rng.standard_normal((Tbig, K)), jnp.float32)
        Yb = jnp.asarray(rng.standard_normal((Tbig, Nbig)), jnp.float32)
        Wb = jnp.asarray((rng.random((Tbig, Nbig)) > 0.2), jnp.float32)

        def _loop_time(body, n):
            """Total wall time of an on-device fori_loop (best of 5)."""

            @jax.jit
            def loop():
                return lax.fori_loop(0, n, body, jnp.float32(0.0))

            loop().block_until_ready()  # compile
            best = float("inf")
            for _ in range(5):
                t = time.perf_counter()
                loop().block_until_ready()
                best = min(best, time.perf_counter() - t)
            return best

        def _gram_body(fn):
            # the carry must feed an input EVERY output depends on (W feeds
            # both the A and rhs contractions): perturbing only Y lets XLA
            # hoist the Y-independent A-einsum out of the loop (LICM), and
            # anything less than full output dependence lets it dead-code-
            # eliminate the op — either way the XLA side would be
            # under-timed vs the opaque kernel
            def body(i, carry):
                A, b = fn(Xb, Yb, Wb + carry * 1e-30)
                return A.sum() * 1e-30 + b.sum() * 1e-30

            return body

        # n large enough that kernel time (~250us/call) swamps the ~30ms
        # fixed dispatch cost of one remote loop launch
        n_gram = 1000
        t_pallas = _loop_time(_gram_body(masked_gram_pallas), n_gram) / n_gram
        t_xla = _loop_time(_gram_body(masked_gram_xla), n_gram) / n_gram
        gram_speedup = round(t_xla / t_pallas, 2)
        pallas_us = round(t_pallas * 1e6, 1)

        with jax.default_matmul_precision("highest"):
            parity = parity_checks(ds)
        parity_ok = all(
            parity[k] <= thresh for k, thresh in PARITY_THRESHOLDS.items()
        )
    else:
        gram_speedup = pallas_us = None
        parity = {k: None for k in PARITY_THRESHOLDS}
        parity_ok = None  # not checked — requires both backends

    print(
        json.dumps(
            {
                "metric": "favar_irf_wild_bootstrap_1000rep_wallclock",
                "value": round(dt, 4),
                "unit": "s",
                "vs_baseline": round(10.0 / dt, 2),
                "device": str(dev),
                "tpu_unreachable": not tpu_ok,
                "em_iters_per_sec": round(em_ips, 2),
                "em_iters_per_sec_host_sync": round(em_ips_host, 2),
                "pallas_gram_speedup_large_panel": gram_speedup,
                "pallas_gram_us_per_call": pallas_us,
                **{
                    k: (round(v, 8) if v is not None else None)
                    for k, v in parity.items()
                },
                "parity_ok": parity_ok,
            }
        )
    )
    if parity_ok is False:
        print(
            f"PARITY FAILURE: {parity} exceeds {PARITY_THRESHOLDS}",
            file=sys.stderr,
        )
        sys.exit(1)


if __name__ == "__main__":
    main()
