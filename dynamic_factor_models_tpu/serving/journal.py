"""Crash-safe write-ahead tick journal: replayable O(1) updates.

A tenant's snapshot (`TenantStore.save`) lands only at `_install` time —
register, resume, or a successful refit.  Every online tick between two
snapshots would die with the process, forcing the caller to re-supply
the panel on restart.  The journal closes that gap with write-ahead
logging: the engine appends the tick's `(t, x, mask)` row BEFORE
committing the new `FilterState`, so after a kill the next process
replays `snapshot + journal` through the SAME `online_tick` executable
and lands on a bit-identical state — same program, same inputs, same
floats.

Format: one JSONL file per tenant next to its snapshot.

    line 0:  {"magic", "version", "base_t", "sha"}          header
    line k:  {"t", "dtype", "x", "mask", "sha"}             one tick

`x` is the base64 of the zero-filled row's raw bytes, `mask` the base64
of the uint8 mask bytes; `sha` is a sha256 over the record's payload
fields, so torn writes and silent corruption are both detected per
SEGMENT, like PR 4's checkpoints.  Appends are a single `write()` of
one line (O_APPEND semantics: a crash can tear at most the final line)
followed by flush+fsync — the journal is the commit point.

Recovery policy on damage: the intact prefix is TRUSTED, everything
from the first bad record on is dropped; the damaged file is preserved
whole at ``<path>.corrupt`` for forensics and the live file rewritten
to the intact prefix (counter ``serving.journal.quarantined``).  A bad
HEADER poisons the whole journal: quarantine and report empty.

I/O faults: every disk touch first calls the owning store's `io_probe`
(see `TenantStore.io_probe`), the shared site counter behind the
``store_io@n`` chaos grammar — so snapshot saves and journal appends
draw from one deterministic fault sequence.
"""

from __future__ import annotations

import base64
import hashlib
import json
import os

import numpy as np

from ..utils.telemetry import inc

__all__ = ["TickJournal", "PendingSync", "JOURNAL_MAGIC"]

JOURNAL_MAGIC = "dfm-tick-journal"
_VERSION = 1


def _header_sha(base_t: int) -> str:
    payload = f"{JOURNAL_MAGIC}|{_VERSION}|{int(base_t)}".encode()
    return hashlib.sha256(payload).hexdigest()


def _record_sha(t: int, dtype: str, x_b64: str, mask_b64: str) -> str:
    payload = f"{int(t)}|{dtype}|{x_b64}|{mask_b64}".encode()
    return hashlib.sha256(payload).hexdigest()


class PendingSync:
    """A coalesced journal append whose bytes are WRITTEN (buffered
    through the OS) but not yet DURABLE: `sync()` fsyncs and closes.

    The write-ahead contract for a batched round: every lane's
    `append_many(..., sync=False)` write lands first, then ALL pending
    syncs complete, and only then may any lane commit in memory — the
    fsync sweep is the round's acked⇔durable line.  Dropping a
    PendingSync without `sync()` leaves a possibly-torn tail that
    replay quarantines, exactly like a crash between write and fsync.
    """

    __slots__ = ("_f",)

    def __init__(self, f):
        self._f = f

    def sync(self) -> None:
        f, self._f = self._f, None
        if f is None:
            return
        try:
            os.fsync(f.fileno())
        finally:
            f.close()

    def close(self) -> None:
        """Abandon without fsync (error paths only)."""
        f, self._f = self._f, None
        if f is not None:
            f.close()


class TickJournal:
    """One tenant's append-only tick log.  Constructed by the store
    (`TenantStore.journal`), which supplies the fault-counted
    `io_probe`; safe to construct standalone with `io_probe=None`."""

    def __init__(self, path: str, io_probe=None):
        self.path = path
        self._probe = io_probe or (lambda: None)

    # -- writes ----------------------------------------------------------

    def reset(self, base_t: int) -> None:
        """Start a fresh journal anchored at snapshot time `base_t`
        (atomic: temp file + rename, like the snapshot itself)."""
        self._probe()
        hdr = {
            "magic": JOURNAL_MAGIC,
            "version": _VERSION,
            "base_t": int(base_t),
            "sha": _header_sha(base_t),
        }
        tmp = f"{self.path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            f.write(json.dumps(hdr) + "\n")
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.path)

    def append(self, t: int, x: np.ndarray, mask: np.ndarray) -> None:
        """Write-ahead one tick: a single one-line append + fsync.  The
        caller commits its in-memory state only after this returns — an
        OSError here (real or ``store_io@n``-injected) means the tick
        never happened.

        A missing file is created with a header anchored at ``base_t =
        t``: the first journaled tick after a snapshot is BY
        CONSTRUCTION at the snapshot's own t (the engine journals the
        pre-increment clock), so lazy header creation is equivalent to
        an eager `reset` at snapshot time — and lets a million-tenant
        registration skip a million empty journal files."""
        self.append_many([(t, x, mask)])

    def append_many(self, rows, sync: bool = True):
        """Coalesced write-ahead: encode every ``(t, x, mask)`` row,
        ONE buffered write of all lines, one fsync — bytes on disk
        identical to the same rows appended one `append()` at a time
        (pinned in tests/test_eviction.py), at one write+fsync instead
        of k.

        ``sync=False`` defers durability: the bytes are written and
        flushed to the OS but NOT fsynced; the returned `PendingSync`'s
        ``sync()`` completes the append.  The batched engine round uses
        this to write every lane's records first and then run one fsync
        sweep — all appends become durable before any lane commits, so
        the write-ahead ordering is preserved per lane.  Returns None
        when ``sync=True`` (or `rows` is empty).

        The store's fault probe (``store_io@n`` / ``crash_io@n``) fires
        ONCE per call, before any byte is written: a coalesced append
        is one store op, atomic under the injected-crash model the
        kill-matrix drills enumerate."""
        rows = list(rows)
        if not rows:
            return None
        encoded = []
        for t, x, mask in rows:
            x = np.ascontiguousarray(x)
            mask = np.ascontiguousarray(mask, dtype=np.uint8)
            x_b64 = base64.b64encode(x.tobytes()).decode()
            mask_b64 = base64.b64encode(mask.tobytes()).decode()
            encoded.append(json.dumps({
                "t": int(t),
                "dtype": x.dtype.str,
                "x": x_b64,
                "mask": mask_b64,
                "sha": _record_sha(t, x.dtype.str, x_b64, mask_b64),
            }))
        self._probe()
        lines = []
        if not os.path.exists(self.path):
            t0 = int(rows[0][0])
            lines.append(json.dumps({
                "magic": JOURNAL_MAGIC,
                "version": _VERSION,
                "base_t": t0,
                "sha": _header_sha(t0),
            }))
        lines.extend(encoded)
        f = open(self.path, "a")
        try:
            f.write("\n".join(lines) + "\n")
            f.flush()
        except BaseException:
            f.close()
            raise
        inc("serving.journal.appends", len(rows))
        if not sync:
            return PendingSync(f)
        try:
            os.fsync(f.fileno())
        finally:
            f.close()
        return None

    # -- reads -----------------------------------------------------------

    def replay(self):
        """Read the journal back: ``(base_t, rows)`` with `rows` a list
        of ``(t, x, mask)`` in append order, or None when the file is
        absent or its header is damaged.  A damaged record quarantines
        the file (kept whole at ``.corrupt``) and truncates the live
        journal to the intact prefix, which is returned."""
        if not os.path.exists(self.path):
            return None
        with open(self.path, "rb") as f:
            raw = f.read()
        lines = raw.split(b"\n")
        hdr = self._parse_header(lines[0] if lines else b"")
        if hdr is None:
            self._quarantine(raw, base_t=None, good=[])
            return None
        rows, good = [], []
        for line in lines[1:]:
            if not line.strip():
                continue
            rec = self._parse_record(line)
            if rec is None:  # torn append or flipped bytes: drop the tail
                self._quarantine(raw, base_t=hdr, good=good)
                break
            rows.append(rec)
            good.append(line)
        if rows:  # recovery visible in metrics, not just logs
            inc("serving.journal.replayed_ticks", len(rows))
        return hdr, rows

    def _parse_header(self, line: bytes):
        try:
            hdr = json.loads(line)
            if (
                hdr.get("magic") != JOURNAL_MAGIC
                or hdr.get("version") != _VERSION
                or hdr.get("sha") != _header_sha(hdr["base_t"])
            ):
                return None
            return int(hdr["base_t"])
        except (ValueError, KeyError, TypeError):
            return None

    def _parse_record(self, line: bytes):
        try:
            rec = json.loads(line)
            if rec["sha"] != _record_sha(
                rec["t"], rec["dtype"], rec["x"], rec["mask"]
            ):
                return None
            x = np.frombuffer(
                base64.b64decode(rec["x"]), dtype=np.dtype(rec["dtype"])
            )
            mask = np.frombuffer(
                base64.b64decode(rec["mask"]), dtype=np.uint8
            ).astype(bool)
            if mask.shape != x.shape:
                return None
            return int(rec["t"]), x, mask
        except (ValueError, KeyError, TypeError):
            return None

    def _quarantine(self, raw: bytes, base_t, good: list) -> None:
        """Preserve the damaged file, rewrite the live one to the intact
        prefix (or remove it entirely on a bad header)."""
        with open(self.path + ".corrupt", "wb") as f:
            f.write(raw)
        if base_t is None:
            os.remove(self.path)
        else:
            tmp = f"{self.path}.tmp.{os.getpid()}"
            hdr = {
                "magic": JOURNAL_MAGIC,
                "version": _VERSION,
                "base_t": int(base_t),
                "sha": _header_sha(base_t),
            }
            with open(tmp, "wb") as f:
                f.write((json.dumps(hdr) + "\n").encode())
                for line in good:
                    f.write(line + b"\n")
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self.path)
        inc("serving.journal.quarantined")

    # -- lifecycle -------------------------------------------------------

    def exists(self) -> bool:
        return os.path.exists(self.path)

    def delete(self) -> None:
        try:
            os.remove(self.path)
        except FileNotFoundError:
            pass
