"""Crash-safe write-ahead tick journal: replayable O(1) updates.

A tenant's snapshot (`TenantStore.save`) lands only at `_install` time —
register, resume, or a successful refit.  Every online tick between two
snapshots would die with the process, forcing the caller to re-supply
the panel on restart.  The journal closes that gap with write-ahead
logging: the engine appends the tick's `(t, x, mask)` row BEFORE
committing the new `FilterState`, so after a kill the next process
replays `snapshot + journal` through the SAME `online_tick` executable
and lands on a bit-identical state — same program, same inputs, same
floats.

Format: one JSONL file per tenant next to its snapshot.

    line 0:  {"magic", "version", "base_t", "sha"}          header
    line k:  {"t", "dtype", "x", "mask", "sha"}             one tick

`x` is the base64 of the zero-filled row's raw bytes, `mask` the base64
of the uint8 mask bytes; `sha` is a sha256 over the record's payload
fields, so torn writes and silent corruption are both detected per
SEGMENT, like PR 4's checkpoints.  Appends are a single `write()` of
one line (O_APPEND semantics: a crash can tear at most the final line)
followed by flush+fsync — the journal is the commit point.

Recovery policy on damage: the intact prefix is TRUSTED, everything
from the first bad record on is dropped; the damaged file is preserved
whole at ``<path>.corrupt`` for forensics and the live file rewritten
to the intact prefix (counter ``serving.journal.quarantined``).  A bad
HEADER poisons the whole journal: quarantine and report empty.

I/O faults: every disk touch first calls the owning store's `io_probe`
(see `TenantStore.io_probe`), the shared site counter behind the
``store_io@n`` chaos grammar — so snapshot saves and journal appends
draw from one deterministic fault sequence.
"""

from __future__ import annotations

import base64
import hashlib
import json
import os

import numpy as np

from ..utils.telemetry import inc

__all__ = ["TickJournal", "JOURNAL_MAGIC"]

JOURNAL_MAGIC = "dfm-tick-journal"
_VERSION = 1


def _header_sha(base_t: int) -> str:
    payload = f"{JOURNAL_MAGIC}|{_VERSION}|{int(base_t)}".encode()
    return hashlib.sha256(payload).hexdigest()


def _record_sha(t: int, dtype: str, x_b64: str, mask_b64: str) -> str:
    payload = f"{int(t)}|{dtype}|{x_b64}|{mask_b64}".encode()
    return hashlib.sha256(payload).hexdigest()


class TickJournal:
    """One tenant's append-only tick log.  Constructed by the store
    (`TenantStore.journal`), which supplies the fault-counted
    `io_probe`; safe to construct standalone with `io_probe=None`."""

    def __init__(self, path: str, io_probe=None):
        self.path = path
        self._probe = io_probe or (lambda: None)

    # -- writes ----------------------------------------------------------

    def reset(self, base_t: int) -> None:
        """Start a fresh journal anchored at snapshot time `base_t`
        (atomic: temp file + rename, like the snapshot itself)."""
        self._probe()
        hdr = {
            "magic": JOURNAL_MAGIC,
            "version": _VERSION,
            "base_t": int(base_t),
            "sha": _header_sha(base_t),
        }
        tmp = f"{self.path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            f.write(json.dumps(hdr) + "\n")
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.path)

    def append(self, t: int, x: np.ndarray, mask: np.ndarray) -> None:
        """Write-ahead one tick: a single one-line append + fsync.  The
        caller commits its in-memory state only after this returns — an
        OSError here (real or ``store_io@n``-injected) means the tick
        never happened.

        A missing file is created with a header anchored at ``base_t =
        t``: the first journaled tick after a snapshot is BY
        CONSTRUCTION at the snapshot's own t (the engine journals the
        pre-increment clock), so lazy header creation is equivalent to
        an eager `reset` at snapshot time — and lets a million-tenant
        registration skip a million empty journal files."""
        x = np.ascontiguousarray(x)
        mask = np.ascontiguousarray(mask, dtype=np.uint8)
        x_b64 = base64.b64encode(x.tobytes()).decode()
        mask_b64 = base64.b64encode(mask.tobytes()).decode()
        rec = {
            "t": int(t),
            "dtype": x.dtype.str,
            "x": x_b64,
            "mask": mask_b64,
            "sha": _record_sha(t, x.dtype.str, x_b64, mask_b64),
        }
        self._probe()
        lines = []
        if not os.path.exists(self.path):
            lines.append(json.dumps({
                "magic": JOURNAL_MAGIC,
                "version": _VERSION,
                "base_t": int(t),
                "sha": _header_sha(t),
            }))
        lines.append(json.dumps(rec))
        with open(self.path, "a") as f:
            f.write("\n".join(lines) + "\n")
            f.flush()
            os.fsync(f.fileno())
        inc("serving.journal.appends")

    # -- reads -----------------------------------------------------------

    def replay(self):
        """Read the journal back: ``(base_t, rows)`` with `rows` a list
        of ``(t, x, mask)`` in append order, or None when the file is
        absent or its header is damaged.  A damaged record quarantines
        the file (kept whole at ``.corrupt``) and truncates the live
        journal to the intact prefix, which is returned."""
        if not os.path.exists(self.path):
            return None
        with open(self.path, "rb") as f:
            raw = f.read()
        lines = raw.split(b"\n")
        hdr = self._parse_header(lines[0] if lines else b"")
        if hdr is None:
            self._quarantine(raw, base_t=None, good=[])
            return None
        rows, good = [], []
        for line in lines[1:]:
            if not line.strip():
                continue
            rec = self._parse_record(line)
            if rec is None:  # torn append or flipped bytes: drop the tail
                self._quarantine(raw, base_t=hdr, good=good)
                break
            rows.append(rec)
            good.append(line)
        if rows:  # recovery visible in metrics, not just logs
            inc("serving.journal.replayed_ticks", len(rows))
        return hdr, rows

    def _parse_header(self, line: bytes):
        try:
            hdr = json.loads(line)
            if (
                hdr.get("magic") != JOURNAL_MAGIC
                or hdr.get("version") != _VERSION
                or hdr.get("sha") != _header_sha(hdr["base_t"])
            ):
                return None
            return int(hdr["base_t"])
        except (ValueError, KeyError, TypeError):
            return None

    def _parse_record(self, line: bytes):
        try:
            rec = json.loads(line)
            if rec["sha"] != _record_sha(
                rec["t"], rec["dtype"], rec["x"], rec["mask"]
            ):
                return None
            x = np.frombuffer(
                base64.b64decode(rec["x"]), dtype=np.dtype(rec["dtype"])
            )
            mask = np.frombuffer(
                base64.b64decode(rec["mask"]), dtype=np.uint8
            ).astype(bool)
            if mask.shape != x.shape:
                return None
            return int(rec["t"]), x, mask
        except (ValueError, KeyError, TypeError):
            return None

    def _quarantine(self, raw: bytes, base_t, good: list) -> None:
        """Preserve the damaged file, rewrite the live one to the intact
        prefix (or remove it entirely on a bad header)."""
        with open(self.path + ".corrupt", "wb") as f:
            f.write(raw)
        if base_t is None:
            os.remove(self.path)
        else:
            tmp = f"{self.path}.tmp.{os.getpid()}"
            hdr = {
                "magic": JOURNAL_MAGIC,
                "version": _VERSION,
                "base_t": int(base_t),
                "sha": _header_sha(base_t),
            }
            with open(tmp, "wb") as f:
                f.write((json.dumps(hdr) + "\n").encode())
                for line in good:
                    f.write(line + b"\n")
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self.path)
        inc("serving.journal.quarantined")

    # -- lifecycle -------------------------------------------------------

    def exists(self) -> bool:
        return os.path.exists(self.path)

    def delete(self) -> None:
        try:
            os.remove(self.path)
        except FileNotFoundError:
            pass
