"""Tenant-sharded serving: M engine workers behind one router.

One `ServingEngine` is single-threaded by construction — its journal
fsyncs, LRU bookkeeping, and breaker state all assume one writer.  To
scale past one process WITHOUT revisiting any of that, the router
shards the TENANT SPACE instead of the engine: each of `n_workers`
workers owns a stable hash slice of tenant ids, with its OWN store
partition (`store.worker_partition` — disjoint snapshot + journal
trees) and its own admission pipeline.  Every per-tenant invariant —
write-ahead ordering, acked ⇔ durable, breaker and eviction accounting
— is therefore a per-worker fact; the router adds routing, fan-out, and
gang-scheduled refits, never shared mutable state.

Backends:

* ``inproc`` — workers are in-process `ServingEngine`s.  Zero IPC;
  what the fast tests drive, and the degenerate M=1 case is exactly a
  plain engine behind one hash lookup.
* ``process`` — workers are OS processes (spawn), one duplex pipe
  each.  Requests pickle over the pipe; responses are sanitized to
  numpy leaves first (a device buffer must not cross a process
  boundary).  Fan-out calls (`flush_all`, `stats`, `close`) send to
  EVERY worker before receiving from any, so workers overlap.

Refits GANG-SCHEDULE: workers only queue refit requests
(`engine._queue_refit`); `flush_refits()` pulls every worker's queue,
runs ONE `refit_batch` in the router process — inside
`parallel.distributed.global_mesh` when the process-spanning init (PR
15) is active, so a multi-host mesh sees one batched EM across all
shards — and installs the fitted params back into the owning workers.
`init_spec="module:function"` runs an arbitrary initializer in each
worker at startup (e.g. `parallel.distributed.initialize_distributed`
wired from env) for deployments where workers join the mesh
themselves.

Per-worker isolation is the failure story: one worker's eviction
budget, circuit breakers, and fault drills never touch another's
tenants, and a crashed worker loses only its slice — `recover()` on a
fresh router replays each partition independently.
"""

from __future__ import annotations

import hashlib
import importlib
import multiprocessing as mp
import os

import numpy as np

from ..utils.telemetry import inc
from .store import worker_partition

__all__ = ["TenantRouter", "worker_of"]

_BACKENDS = ("inproc", "process")


def worker_of(tenant_id: str, n_workers: int) -> int:
    """Stable tenant → worker shard map: sha256 of the id, mod M.
    Independent of registration order and identical across processes
    and restarts — the partition layout on disk IS the routing table."""
    h = hashlib.sha256(tenant_id.encode()).hexdigest()[:8]
    return int(h, 16) % int(n_workers)


def _sanitize(obj):
    """Replace device arrays with host numpy in a response pytree so it
    pickles across a process boundary without dragging jax buffers."""
    import jax

    def leaf(x):
        if hasattr(x, "__array__") and not isinstance(x, np.ndarray):
            return np.asarray(x)
        return x

    return jax.tree.map(leaf, obj)


def _run_init_spec(init_spec: str | None) -> None:
    if not init_spec:
        return
    mod, _, fn = init_spec.partition(":")
    getattr(importlib.import_module(mod), fn or "main")()


def _make_engine(store_dir, worker_id, engine_kwargs):
    from .engine import ServingEngine

    kw = dict(engine_kwargs or {})
    sd = worker_partition(store_dir, worker_id) if store_dir else None
    return ServingEngine(store_dir=sd, **kw)


def _worker_main(conn, worker_id, store_dir, engine_kwargs,
                 pipelined, pipeline_kwargs, init_spec) -> None:
    """Engine-worker process body: one engine (plus optional pipeline)
    serving ops off the pipe until ``close``.  Never raises across the
    pipe — errors return as ``("err", repr)`` so one bad request
    cannot wedge the router's recv."""
    _run_init_spec(init_spec)
    eng = _make_engine(store_dir, worker_id, engine_kwargs)
    pipe = None
    if pipelined:
        from .pipeline import ServingPipeline

        pipe = ServingPipeline(eng, **(pipeline_kwargs or {}))
    while True:
        try:
            op, payload = conn.recv()
        except EOFError:
            break
        try:
            if op == "close":
                if pipe is not None:
                    pipe.close()
                conn.send(("ok", None))
                break
            conn.send(("ok", _worker_op(eng, pipe, op, payload)))
        except Exception as e:  # typed errors stay envelopes; this is
            conn.send(("err", f"{type(e).__name__}: {e}"))  # the backstop
    conn.close()


def _worker_op(eng, pipe, op, payload):
    """Shared op table: the process worker loop and the inproc backend
    dispatch through the SAME function, so both backends are one code
    path up to pickling."""
    if op == "register":
        tid, x, mask, params = payload
        eng.register(tid, x, mask=mask, params=params)
        return None
    if op == "register_shared":
        tid, like = payload
        eng.register_shared(tid, like)
        return None
    if op == "handle":
        return _sanitize(eng.handle(payload))
    if op == "submit":
        if pipe is not None:
            for req in payload:
                pipe.submit(req)
            return None
        for req in payload:
            eng.submit(req)
        return None
    if op == "flush":
        if pipe is not None:
            out = pipe.drain()
        else:
            out = eng.flush_period()
        return _sanitize(out)
    if op == "pump":
        if pipe is not None:
            pipe.pump()
            return _sanitize(pipe.poll())
        return _sanitize(eng.flush_period())
    if op == "refit_pull":
        # gang scheduling: hand the queued refits (panel + params) to
        # the router; the queue empties here, exactly like flush_refits
        queue, eng._refit_queue = eng._refit_queue, []
        out = []
        for tid in queue:
            ten = eng._tenants.get(tid)
            if ten is None or ten.hist is None:
                continue
            out.append((
                tid,
                np.asarray(ten.hist.x), np.asarray(ten.hist.mask),
                _sanitize(ten.params),
            ))
        return out
    if op == "refit_install":
        installed = 0
        for tid, params in payload:
            ten = eng._tenants.get(tid)
            if ten is None or ten.hist is None:
                continue
            eng._install(tid, ten.hist.x, ten.hist.mask, params)
            installed += 1
        return installed
    if op == "recover":
        return eng.recover(prewarm=payload)
    if op == "flush_metrics":
        return eng.flush_metrics()
    if op == "stats":
        st = {
            "resident": len(eng._tenants),
            "requests": eng._requests,
            "ticks": eng._ticks,
        }
        if pipe is not None:
            st["pipeline"] = pipe.stats()
        return st
    if op == "tenant_ids":
        return eng.tenant_ids()
    raise ValueError(f"unknown worker op {op!r}")


class TenantRouter:
    """Shard tenants across M engine workers; route by stable hash.

    The router is the single client-facing object: `register` /
    `handle` / `submit` / `flush_all` mirror the engine API and fan
    out (or route point-wise) to the owning worker.  Per-worker
    eviction budgets and breakers come from `engine_kwargs` — applied
    to EVERY worker, so M workers give M× the configured budget, each
    enforced locally."""

    def __init__(
        self,
        n_workers: int,
        store_dir: str | None = None,
        backend: str = "inproc",
        pipelined: bool = False,
        engine_kwargs: dict | None = None,
        pipeline_kwargs: dict | None = None,
        init_spec: str | None = None,
    ):
        if backend not in _BACKENDS:
            raise ValueError(
                f"backend must be one of {_BACKENDS}, got {backend!r}"
            )
        if n_workers < 1:
            raise ValueError("n_workers must be >= 1")
        self.n_workers = int(n_workers)
        self.store_dir = store_dir
        self.backend = backend
        self.pipelined = bool(pipelined)
        self.engine_kwargs = dict(engine_kwargs or {})
        self.pipeline_kwargs = dict(pipeline_kwargs or {})
        self._closed = False
        self._engines = None
        self._pipes = None
        self._conns = None
        self._procs = None
        if backend == "inproc":
            _run_init_spec(init_spec)
            self._engines = [
                _make_engine(store_dir, i, self.engine_kwargs)
                for i in range(self.n_workers)
            ]
            self._pipes = [None] * self.n_workers
            if self.pipelined:
                from .pipeline import ServingPipeline

                self._pipes = [
                    ServingPipeline(eng, **self.pipeline_kwargs)
                    for eng in self._engines
                ]
        else:
            ctx = mp.get_context("spawn")
            self._conns, self._procs = [], []
            for i in range(self.n_workers):
                parent, child = ctx.Pipe()
                proc = ctx.Process(
                    target=_worker_main,
                    args=(child, i, store_dir, self.engine_kwargs,
                          self.pipelined, self.pipeline_kwargs, init_spec),
                    daemon=True,
                )
                proc.start()
                child.close()
                self._conns.append(parent)
                self._procs.append(proc)

    # -- shard addressing ------------------------------------------------

    def worker_of(self, tenant_id: str) -> int:
        return worker_of(tenant_id, self.n_workers)

    def _call(self, w: int, op, payload=None):
        if self._engines is not None:
            return _worker_op(self._engines[w], self._pipes[w], op, payload)
        self._conns[w].send((op, payload))
        status, out = self._conns[w].recv()
        if status == "err":
            raise RuntimeError(f"worker {w}: {out}")
        return out

    def _fanout(self, op, payload=None) -> list:
        """Send `op` to every worker, THEN collect: with process
        workers the M operations overlap — this is where M× shows up."""
        if self._engines is not None:
            return [
                self._call(w, op, payload) for w in range(self.n_workers)
            ]
        for conn in self._conns:
            conn.send((op, payload))
        out = []
        for w, conn in enumerate(self._conns):
            status, val = conn.recv()
            if status == "err":
                raise RuntimeError(f"worker {w}: {val}")
            out.append(val)
        return out

    # -- engine API, sharded ---------------------------------------------

    def register(self, tenant_id, x, mask=None, params=None) -> int:
        w = self.worker_of(tenant_id)
        self._call(w, "register", (
            tenant_id, np.asarray(x, float),
            None if mask is None else np.asarray(mask, bool),
            None if params is None else _sanitize(params),
        ))
        return w

    def register_seed(self, tenant_id, x, mask=None, params=None) -> None:
        """Install a SEED tenant on EVERY worker so `register_shared`
        can clone it locally regardless of which shard the clone hashes
        to — the sharded analogue of the engine's shared-fit mass
        registration (register once, clone O(1) everywhere)."""
        payload = (
            tenant_id, np.asarray(x, float),
            None if mask is None else np.asarray(mask, bool),
            None if params is None else _sanitize(params),
        )
        self._fanout("register", payload)

    def register_shared(self, tenant_id, like) -> int:
        w = self.worker_of(tenant_id)
        self._call(w, "register_shared", (tenant_id, like))
        return w

    def handle(self, req):
        tid = req.get("tenant") if isinstance(req, dict) else None
        w = self.worker_of(tid) if isinstance(tid, str) else 0
        return self._call(w, "handle", req)

    def submit(self, reqs) -> None:
        """Batch-submit tick requests, bucketed per owning worker (one
        pipe message per worker, not per request)."""
        if isinstance(reqs, dict):
            reqs = [reqs]
        buckets: list = [[] for _ in range(self.n_workers)]
        for req in reqs:
            tid = req.get("tenant") if isinstance(req, dict) else None
            w = self.worker_of(tid) if isinstance(tid, str) else 0
            buckets[w].append(req)
        for w, bucket in enumerate(buckets):
            if bucket:
                self._call(w, "submit", bucket)

    def flush_all(self) -> list:
        """Flush every worker's queue/pipeline; responses concatenated
        in worker order (per-worker submission order preserved)."""
        out = []
        for part in self._fanout("flush"):
            out.extend(part)
        inc("serving.router.flushes")
        return out

    def flush_refits(self):
        """Gang-scheduled refit flush: pull every worker's queued
        refits, run ONE batched EM in the router process — under the
        process-spanning mesh when `parallel.distributed` is initialized
        — then install results back into the owning workers.  Returns
        ``{"n_requests", "installed", "failed"}``."""
        import jax.numpy as jnp

        from .batch import RefitRequest, refit_batch
        from ..parallel import distributed as _dist

        pulls = self._fanout("refit_pull")
        reqs, owner = [], {}
        for w, part in enumerate(pulls):
            for tid, x, mask, params in part:
                reqs.append(RefitRequest(
                    tenant_id=tid, x=jnp.asarray(x),
                    mask=jnp.asarray(mask), params=params,
                ))
                owner[tid] = w
        if not reqs:
            return {"n_requests": 0, "installed": 0, "failed": []}
        import jax

        eng_kw = self.engine_kwargs

        def _run():
            return refit_batch(
                reqs, isolate_errors=True, tol=eng_kw.get("tol", 1e-6),
                max_em_iter=eng_kw.get("max_em_iter", 200),
            )

        if jax.process_count() > 1:
            # process-spanning init active (PR 15): one batched EM over
            # the global mesh gang-schedules the refit across hosts
            with _dist.global_mesh():
                results = _run()
        else:
            results = _run()
        installs: list = [[] for _ in range(self.n_workers)]
        failed = []
        for res in results:
            if res.health == 0:
                installs[owner[res.tenant_id]].append(
                    (res.tenant_id, _sanitize(res.params))
                )
            else:
                failed.append(res.tenant_id)
        installed = 0
        for w, batch in enumerate(installs):
            if batch:
                installed += self._call(w, "refit_install", batch)
        inc("serving.router.gang_refits")
        return {
            "n_requests": len(reqs), "installed": installed,
            "failed": failed,
        }

    def recover(self, prewarm=None) -> list:
        return self._fanout("recover", prewarm)

    def flush_metrics(self) -> list:
        return self._fanout("flush_metrics")

    def stats(self) -> list:
        return self._fanout("stats")

    def tenant_ids(self) -> list:
        out = []
        for part in self._fanout("tenant_ids"):
            out.extend(part)
        return sorted(out)

    # -- lifecycle -------------------------------------------------------

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        if self._engines is not None:
            for pipe in self._pipes:
                if pipe is not None:
                    pipe.close()
            return
        for conn in self._conns:
            try:
                conn.send(("close", None))
            except (BrokenPipeError, OSError):
                pass
        for conn in self._conns:
            try:
                conn.recv()
            except (EOFError, OSError):
                pass
            conn.close()
        for proc in self._procs:
            proc.join(timeout=30.0)
            if proc.is_alive():
                proc.terminate()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
