"""Tenant-sharded serving: M engine workers behind one supervised router.

One `ServingEngine` is single-threaded by construction — its journal
fsyncs, LRU bookkeeping, and breaker state all assume one writer.  To
scale past one process WITHOUT revisiting any of that, the router
shards the TENANT SPACE instead of the engine: each of `n_workers`
workers owns a stable hash slice of tenant ids, with its OWN store
partition (`store.worker_partition` — disjoint snapshot + journal
trees) and its own admission pipeline.  Every per-tenant invariant —
write-ahead ordering, acked ⇔ durable, breaker and eviction accounting
— is therefore a per-worker fact; the router adds routing, fan-out, and
gang-scheduled refits, never shared mutable state.

Backends:

* ``inproc`` — workers are in-process `ServingEngine`s.  Zero IPC;
  what the fast tests drive, and the degenerate M=1 case is exactly a
  plain engine behind one hash lookup.
* ``process`` — workers are OS processes (spawn), one duplex pipe
  each.  Requests pickle over the pipe; responses are sanitized to
  numpy leaves first (a device buffer must not cross a process
  boundary).  Fan-out calls (`flush_all`, `stats`, `close`) send to
  EVERY worker before receiving from any, so workers overlap.

Supervision (docs/robustness.md, worker supervision): every
router→worker RPC is DEADLINE-BOUNDED (`rpc_timeout_s` + a bounded
suspect-grace window), so a dead or stalled worker is detected, never
hung on.  A `resilience.WorkerSupervisor` tracks each worker through
``healthy → suspect → dead → respawning → recovering → healthy``; on a
confirmed death the router

1. sheds the in-flight and subsequently-arriving requests for that
   worker's tenants as typed ``worker_unavailable`` system faults
   (degraded, not dropped — the other workers' tenants never miss a
   tick),
2. reaps the corpse (terminate → SIGKILL escalation for a stalled
   process), dumps a flight-recorder bundle, and
3. respawns the worker and drives it through ``engine.recover()`` on
   its untouched ``worker{i:03d}`` partition — the PR 13
   acked ≤ recovered ≤ acked+1 journal invariant makes failover
   correct by construction.

A worker answering its first successful post-recovery RPC closes the
loop and stamps the RTO (detect→respawn→recover→first-ack) into the
``serving.worker.*`` telemetry.  The ``kill_worker@n`` /
``stall_worker@n`` fault kinds drive the drill at the n-th client RPC;
supervision-internal RPCs (ping, the recovery call) are not sites.

Refits GANG-SCHEDULE: workers only queue refit requests
(`engine._queue_refit`); `flush_refits()` pulls every worker's queue,
runs ONE `refit_batch` in the router process — inside
`parallel.distributed.global_mesh` when the process-spanning init (PR
15) is active, so a multi-host mesh sees one batched EM across all
shards — and installs the fitted params back into the owning workers.
A member worker dying mid-refit ABORTS the barrier for that worker
only (one install retry after its respawn; its unfitted tenants land
in ``failed`` and the worker in ``aborted_workers``) — the gang never
wedges.  `init_spec="module:function"` runs an arbitrary initializer
in each worker at startup (e.g.
`parallel.distributed.initialize_distributed` wired from env) for
deployments where workers join the mesh themselves.

Per-worker isolation is the failure story: one worker's eviction
budget, circuit breakers, and fault drills never touch another's
tenants, and a crashed worker loses only its slice — `recover()` on a
fresh router replays each partition independently.
"""

from __future__ import annotations

import hashlib
import importlib
import math
import multiprocessing as mp
import os
import signal
import time

import numpy as np

from ..utils import faults as _faults
from ..utils import flight as _flight
from ..utils.telemetry import emit_metrics, inc
from .resilience import (
    SYSTEM_FAULT,
    WORKER_DEAD,
    WORKER_HEALTHY,
    WORKER_RESPAWNING,
    ErrorInfo,
    Response,
    WorkerSupervisor,
)
from .store import worker_partition

__all__ = ["TenantRouter", "WorkerUnavailable", "worker_of"]

_BACKENDS = ("inproc", "process")


class WorkerUnavailable(RuntimeError):
    """A router→worker RPC could not be served: the worker is dead (or
    died mid-call) and — if auto-respawn is on — its replacement was
    not yet able to answer.  Data-plane entry points (`handle`,
    `submit`/`flush_all`) convert this into a typed
    ``worker_unavailable`` system-fault Response; control-plane calls
    (`register`, `register_shared`) let it propagate so the caller can
    retry against the recovered worker."""

    def __init__(self, worker: int, reason: str):
        super().__init__(f"worker {worker} unavailable: {reason}")
        self.worker = int(worker)
        self.reason = reason


def worker_of(tenant_id: str, n_workers: int) -> int:
    """Stable tenant → worker shard map: sha256 of the id, mod M.
    Independent of registration order and identical across processes
    and restarts — the partition layout on disk IS the routing table."""
    h = hashlib.sha256(tenant_id.encode()).hexdigest()[:8]
    return int(h, 16) % int(n_workers)


def _sanitize(obj):
    """Host-ify a response pytree so it pickles across a process
    boundary: device arrays become numpy (a jax buffer must not cross),
    and non-finite float SCALARS (NaN/Inf) become None — counted as
    ``serving.sanitize.nonfinite`` — so a sick worker can never emit an
    unparseable JSON-bound payload.  Arrays pass through unmapped:
    they are bulk state, and NaN handling there belongs to the engine's
    typed fault path, not the transport."""
    import jax

    def leaf(x):
        if isinstance(x, (float, np.floating)):
            if not math.isfinite(x):
                inc("serving.sanitize.nonfinite")
                return None
            return x
        if hasattr(x, "__array__") and not isinstance(x, np.ndarray):
            return np.asarray(x)
        return x

    return jax.tree.map(leaf, obj)


def _run_init_spec(init_spec: str | None) -> None:
    if not init_spec:
        return
    mod, _, fn = init_spec.partition(":")
    getattr(importlib.import_module(mod), fn or "main")()


def _make_engine(store_dir, worker_id, engine_kwargs):
    from .engine import ServingEngine

    kw = dict(engine_kwargs or {})
    sd = worker_partition(store_dir, worker_id) if store_dir else None
    eng = ServingEngine(store_dir=sd, **kw)
    eng.set_worker_id(worker_id)
    return eng


def _worker_main(conn, worker_id, store_dir, engine_kwargs,
                 pipelined, pipeline_kwargs, init_spec) -> None:
    """Engine-worker process body: one engine (plus optional pipeline)
    serving ops off the pipe until ``close``.  Never raises across the
    pipe — errors return as ``("err", repr)`` so one bad request
    cannot wedge the router's recv.  Two deliberate exceptions:

    * the injected kills (SimulatedCrash / SimulatedPreemption) model
      an EXTERNAL death, so they are re-raised and take the process
      down — the router's supervisor sees pipe EOF, exactly like a
      real SIGKILL;
    * a ``stall`` op sleeps without replying (the stall_worker drill:
      the router must detect via its RPC deadline, never the pipe).
    """
    _run_init_spec(init_spec)
    eng = _make_engine(store_dir, worker_id, engine_kwargs)
    pipe = None
    if pipelined:
        from .pipeline import ServingPipeline

        pipe = ServingPipeline(eng, **(pipeline_kwargs or {}))
    while True:
        try:
            op, payload = conn.recv()
        except EOFError:
            break
        if op == "stall":
            time.sleep(float(payload or 0.0))
            continue
        try:
            if op == "close":
                if pipe is not None:
                    pipe.close()
                conn.send(("ok", None))
                break
            conn.send(("ok", _worker_op(eng, pipe, op, payload)))
        except (_faults.SimulatedCrash, _faults.SimulatedPreemption):
            raise  # kills kill: the supervisor must see a dead worker
        except Exception as e:  # typed errors stay envelopes; this is
            conn.send(("err", f"{type(e).__name__}: {e}"))  # the backstop
    conn.close()


def _worker_op(eng, pipe, op, payload):
    """Shared op table: the process worker loop and the inproc backend
    dispatch through the SAME function, so both backends are one code
    path up to pickling."""
    if op == "register":
        tid, x, mask, params = payload
        eng.register(tid, x, mask=mask, params=params)
        return None
    if op == "register_shared":
        tid, like = payload
        eng.register_shared(tid, like)
        return None
    if op == "handle":
        return _sanitize(eng.handle(payload))
    if op == "submit":
        if pipe is not None:
            for req in payload:
                pipe.submit(req)
            return None
        for req in payload:
            eng.submit(req)
        return None
    if op == "flush":
        if pipe is not None:
            out = pipe.drain()
        else:
            out = eng.flush_period()
        return _sanitize(out)
    if op == "pump":
        if pipe is not None:
            pipe.pump()
            return _sanitize(pipe.poll())
        return _sanitize(eng.flush_period())
    if op == "refit_pull":
        # gang scheduling: hand the queued refits (panel + params) to
        # the router; the queue empties here, exactly like flush_refits
        queue, eng._refit_queue = eng._refit_queue, []
        out = []
        for tid in queue:
            ten = eng._tenants.get(tid)
            if ten is None or ten.hist is None:
                continue
            out.append((
                tid,
                np.asarray(ten.hist.x), np.asarray(ten.hist.mask),
                _sanitize(ten.params),
            ))
        return out
    if op == "refit_install":
        installed = 0
        for tid, params in payload:
            ten = eng._tenants.get(tid)
            if ten is None or ten.hist is None:
                continue
            eng._install(tid, ten.hist.x, ten.hist.mask, params)
            installed += 1
        return installed
    if op == "recover":
        return eng.recover(prewarm=payload)
    if op == "flush_metrics":
        return eng.flush_metrics()
    if op == "ping":
        # liveness heartbeat over the ordinary pipe protocol: cheap,
        # side-effect free, and it exercises the full request round
        # trip rather than a bespoke channel
        return {"pid": os.getpid(), "requests": eng._requests}
    if op == "stats":
        st = {
            "resident": len(eng._tenants),
            "requests": eng._requests,
            "ticks": eng._ticks,
        }
        if pipe is not None:
            st["pipeline"] = pipe.stats()
        return st
    if op == "tenant_ids":
        return eng.tenant_ids()
    raise ValueError(f"unknown worker op {op!r}")


class TenantRouter:
    """Shard tenants across M engine workers; route by stable hash.

    The router is the single client-facing object: `register` /
    `handle` / `submit` / `flush_all` mirror the engine API and fan
    out (or route point-wise) to the owning worker.  Per-worker
    eviction budgets and breakers come from `engine_kwargs` — applied
    to EVERY worker, so M workers give M× the configured budget, each
    enforced locally.

    Liveness knobs: `rpc_timeout_s` bounds every worker RPC (None =
    wait forever, the pre-supervision behavior — stalls then go
    undetected); after a missed deadline the worker is `suspect` for
    one `suspect_grace_s` window before being declared dead.  The
    heartbeat deadline — the bound on detect latency — is therefore
    ``rpc_timeout_s + suspect_grace_s``.  `spawn_timeout_s` separately
    bounds the (jax-importing, hence slow) worker boot handshake.
    `auto_respawn` controls whether a dead worker is replaced in place;
    off, its tenants stay typed-unavailable until `close()`."""

    def __init__(
        self,
        n_workers: int,
        store_dir: str | None = None,
        backend: str = "inproc",
        pipelined: bool = False,
        engine_kwargs: dict | None = None,
        pipeline_kwargs: dict | None = None,
        init_spec: str | None = None,
        rpc_timeout_s: float | None = 60.0,
        suspect_grace_s: float | None = None,
        spawn_timeout_s: float = 120.0,
        auto_respawn: bool = True,
        close_timeout_s: float = 10.0,
    ):
        if backend not in _BACKENDS:
            raise ValueError(
                f"backend must be one of {_BACKENDS}, got {backend!r}"
            )
        if n_workers < 1:
            raise ValueError("n_workers must be >= 1")
        self.n_workers = int(n_workers)
        self.store_dir = store_dir
        self.backend = backend
        self.pipelined = bool(pipelined)
        self.engine_kwargs = dict(engine_kwargs or {})
        self.pipeline_kwargs = dict(pipeline_kwargs or {})
        self.init_spec = init_spec
        self.rpc_timeout_s = (
            None if rpc_timeout_s is None else float(rpc_timeout_s)
        )
        if suspect_grace_s is None:
            suspect_grace_s = (
                5.0 if self.rpc_timeout_s is None
                else min(5.0, max(0.05, 0.5 * self.rpc_timeout_s))
            )
        self.suspect_grace_s = float(suspect_grace_s)
        self.spawn_timeout_s = float(spawn_timeout_s)
        self.auto_respawn = bool(auto_respawn)
        self.close_timeout_s = float(close_timeout_s)
        self.supervisor = WorkerSupervisor(self.n_workers)
        self._closed = False
        self._rpc_no = 0  # client RPCs: the kill/stall_worker site axis
        self._pending = [[] for _ in range(self.n_workers)]
        self._orphans = [[] for _ in range(self.n_workers)]
        self._kill_reason = [None] * self.n_workers
        self._engines = None
        self._pipes = None
        self._conns = None
        self._procs = None
        if backend == "inproc":
            _run_init_spec(init_spec)
            self._engines = [
                _make_engine(store_dir, i, self.engine_kwargs)
                for i in range(self.n_workers)
            ]
            self._pipes = [None] * self.n_workers
            if self.pipelined:
                from .pipeline import ServingPipeline

                self._pipes = [
                    ServingPipeline(eng, **self.pipeline_kwargs)
                    for eng in self._engines
                ]
        else:
            self._conns = [None] * self.n_workers
            self._procs = [None] * self.n_workers
            for i in range(self.n_workers):
                self._spawn(i)
            # boot handshake: workers import jax on spawn, which can
            # dwarf rpc_timeout_s — ping each (boots overlap; the pings
            # serialize only the residual wait) so the first client RPC
            # runs against a live worker under the NORMAL deadline
            for i in range(self.n_workers):
                self._control(i, "ping", timeout=self.spawn_timeout_s)

    # -- shard addressing ------------------------------------------------

    def worker_of(self, tenant_id: str) -> int:
        return worker_of(tenant_id, self.n_workers)

    def worker_states(self) -> list[str]:
        """Current supervisor state per worker (lifecycle glyph data)."""
        return [self.supervisor.state(w) for w in range(self.n_workers)]

    # -- supervised RPC layer --------------------------------------------

    def _spawn(self, w: int) -> None:
        ctx = mp.get_context("spawn")
        parent, child = ctx.Pipe()
        proc = ctx.Process(
            target=_worker_main,
            args=(child, w, self.store_dir, self.engine_kwargs,
                  self.pipelined, self.pipeline_kwargs, self.init_spec),
            daemon=True,
        )
        proc.start()
        child.close()
        self._conns[w] = parent
        self._procs[w] = proc

    def _inject_kill(self, w: int) -> None:
        """The ``kill_worker@n`` site: SIGKILL the target process (the
        inproc backend discards the worker's in-memory engine — exactly
        the state a process kill loses; its store partition survives
        untouched).  Detection happens on the RPC that follows."""
        if self._engines is not None:
            self._discard_inproc_worker(w, "kill")
            return
        proc = self._procs[w]
        if proc is not None and proc.is_alive():
            os.kill(proc.pid, signal.SIGKILL)
            proc.join(timeout=5.0)

    def _inject_stall(self, w: int) -> None:
        """The ``stall_worker@n`` site: the process worker really stops
        responding (a ``stall`` op it sleeps on without replying), so
        the deadline/suspect/grace detection path runs end to end.  The
        inproc backend cannot sleep its own thread — the drill
        degenerates to a kill recorded with reason="stall"."""
        if self._engines is not None:
            self._discard_inproc_worker(w, "stall")
            return
        budget = (
            60.0 if self.rpc_timeout_s is None
            else 3.0 * (self.rpc_timeout_s + self.suspect_grace_s) + 1.0
        )
        try:
            self._conns[w].send(("stall", budget))
        except (BrokenPipeError, OSError):
            pass

    def _discard_inproc_worker(self, w: int, reason: str) -> None:
        self._engines[w] = None
        self._kill_reason[w] = reason
        pipe = self._pipes[w]
        if pipe is not None:
            self._pipes[w] = None
            try:
                pipe.close()
            except Exception:
                pass

    def _pre_rpc(self, w: int) -> None:
        """Client-RPC preamble: count the site (the kill/stall_worker
        fault axis), fire injections, and gate on worker health — a
        worker that is dead and cannot be respawned sheds immediately
        instead of hanging or cascading."""
        self._rpc_no += 1
        n = self._rpc_no
        if _faults.site_hits("kill_worker", n):
            _faults.fault_fired("kill_worker")
            self._inject_kill(w)
        elif _faults.site_hits("stall_worker", n):
            _faults.fault_fired("stall_worker")
            self._inject_stall(w)
        st = self.supervisor.state(w)
        if st == WORKER_DEAD:
            # lazy respawn retry: an earlier respawn failed (or
            # auto_respawn is off) — try once more before shedding
            if not (self.auto_respawn and self._respawn(w)):
                raise WorkerUnavailable(w, "worker dead")
        elif st == WORKER_RESPAWNING:
            raise WorkerUnavailable(w, "worker respawning")

    def _call(self, w: int, op, payload=None):
        self._pre_rpc(w)
        if self._engines is not None:
            return self._call_inproc(w, op, payload)
        return self._call_process(w, op, payload)

    def _call_inproc(self, w: int, op, payload):
        eng = self._engines[w]
        if eng is None:
            reason = self._kill_reason[w] or "kill"
            self._kill_reason[w] = None
            self._on_worker_dead(w, reason)
            raise WorkerUnavailable(w, f"worker {reason}ed")
        try:
            out = _worker_op(eng, self._pipes[w], op, payload)
        except (_faults.SimulatedCrash, _faults.SimulatedPreemption) as e:
            # the kill fired INSIDE the worker (engine_crash / crash_io
            # site): in-memory state is gone, the partition survives
            self._discard_inproc_worker(w, "crash")
            self._kill_reason[w] = None
            self._on_worker_dead(w, "crash")
            raise WorkerUnavailable(w, str(e)) from None
        self.supervisor.mark_first_ack(w)
        return out

    def _call_process(self, w: int, op, payload):
        try:
            self._conns[w].send((op, payload))
        except (BrokenPipeError, EOFError, OSError):
            self._handle_process_death(w)
            raise WorkerUnavailable(w, "pipe closed") from None
        status, out = self._recv_bounded(w, op)
        if status == "err":
            raise RuntimeError(f"worker {w}: {out}")
        self.supervisor.mark_first_ack(w)
        return out

    def _recv_bounded(self, w: int, op):
        """Deadline-bounded receive: primary `rpc_timeout_s` wait, then
        a suspect-grace window (during which a merely-slow reply still
        clears the alarm), then the worker is declared dead.  Pipe EOF
        short-circuits straight to dead — no deadline is burned on an
        observable corpse."""
        conn = self._conns[w]
        sup = self.supervisor
        try:
            if self.rpc_timeout_s is None or conn.poll(self.rpc_timeout_s):
                return conn.recv()
            sup.mark_suspect(w)
            deadline = time.perf_counter() + self.suspect_grace_s
            while time.perf_counter() < deadline:
                if not self._procs[w].is_alive():
                    break
                if conn.poll(min(0.05, self.suspect_grace_s)):
                    out = conn.recv()
                    sup.mark_healthy_probe(w)
                    return out
        except (EOFError, OSError):
            pass
        self._handle_process_death(w)
        raise WorkerUnavailable(w, f"no reply to {op!r} within deadline")

    def _handle_process_death(self, w: int) -> None:
        reason = (
            "stall"
            if self._procs[w] is not None and self._procs[w].is_alive()
            else "crash"
        )
        self._reap_process(w)
        self._on_worker_dead(w, reason)

    def _reap_process(self, w: int) -> None:
        """Reap one worker corpse with terminate → SIGKILL escalation —
        a stalled (still-running) process must not outlive its own
        death certificate as an orphan."""
        conn, proc = self._conns[w], self._procs[w]
        if conn is not None:
            try:
                conn.close()
            except Exception:
                pass
        if proc is not None:
            try:
                if proc.is_alive():
                    proc.terminate()
                    proc.join(timeout=2.0)
                if proc.is_alive():
                    proc.kill()
                    proc.join(timeout=2.0)
            except Exception:
                pass

    def _on_worker_dead(self, w: int, reason: str) -> None:
        """Confirmed worker death: record it, dump a flight bundle,
        convert the worker's in-flight (submitted-but-unflushed)
        requests into typed orphan responses, and — with auto-respawn —
        bring up the replacement synchronously.  Requests the dead
        worker had already journaled are NOT orphaned twice: the
        journal is the ack barrier, and `recover()` on the respawn
        replays exactly the durable prefix (acked ≤ recovered ≤
        acked+1)."""
        detect = self.supervisor.mark_dead(w, reason=reason)
        _flight.record(
            "worker_dead", severity="error", worker=w, reason=reason,
            detect_s=round(detect, 6), backend=self.backend,
        )
        _flight.dump("worker_dead", force=True, worker=w, reason=reason)
        for kind, tid in self._pending[w]:
            self._orphans[w].append(self._unavailable_response(kind, tid, w))
        self._pending[w].clear()
        if self.auto_respawn:
            self._respawn(w)

    def _respawn(self, w: int) -> bool:
        """Replace a dead worker in place and drive recovery on its
        untouched partition.  True on success (worker is `recovering`
        and will go healthy on its first acked client RPC); False
        leaves it dead — the next client RPC retries the respawn."""
        sup = self.supervisor
        sup.mark_respawning(w)
        try:
            if self._engines is not None:
                self._engines[w] = _make_engine(
                    self.store_dir, w, self.engine_kwargs
                )
                if self.pipelined:
                    from .pipeline import ServingPipeline

                    self._pipes[w] = ServingPipeline(
                        self._engines[w], **self.pipeline_kwargs
                    )
                sup.mark_recovering(w)
                if self.store_dir:
                    self._engines[w].recover()
                return True
            self._spawn(w)
            self._control(w, "ping", timeout=self.spawn_timeout_s)
            sup.mark_recovering(w)
            if self.store_dir:
                self._control(w, "recover", timeout=self.spawn_timeout_s)
            return True
        except Exception:
            # the respawn itself failed (or the replacement was killed
            # before recovering — the double-kill drill): stay dead,
            # requests shed typed, the next RPC retries
            if self._procs is not None:
                self._reap_process(w)
            sup.mark_dead(w, reason="respawn_failed")
            return False

    def _control(self, w: int, op, payload=None, timeout=None):
        """Supervision-internal RPC (ping / recovery): bounded like any
        other, but NOT a fault site and with no death handling — a
        failure raises and `_respawn` decides.  Keeping these off the
        site axis makes `kill_worker@n` deterministic: n counts client
        RPCs only."""
        if timeout is None:
            timeout = self.rpc_timeout_s
        conn = self._conns[w]
        try:
            conn.send((op, payload))
            if timeout is None or conn.poll(timeout):
                status, out = conn.recv()
                if status == "err":
                    raise RuntimeError(f"worker {w}: {out}")
                return out
        except (BrokenPipeError, EOFError, OSError):
            pass
        raise WorkerUnavailable(w, f"no reply to control op {op!r}")

    def _unavailable_response(self, kind, tenant, w: int) -> Response:
        inc("serving.worker.unavailable_responses")
        return Response(
            ok=False,
            kind=kind if isinstance(kind, str) else "invalid",
            tenant=tenant if isinstance(tenant, str) else None,
            error=ErrorInfo(
                SYSTEM_FAULT, "worker_unavailable",
                f"worker {w} is {self.supervisor.state(w)}; tenant "
                f"state is durable and will be served after recovery",
            ),
        )

    def _fanout(self, op, payload=None) -> list:
        """Send `op` to every worker, THEN collect: with process
        workers the M operations overlap — this is where M× shows up.
        A worker that is dead (and could not be respawned) or dies
        mid-fan-out contributes ``None`` in its slot; callers degrade
        per-worker instead of wedging the barrier."""
        if self._engines is not None:
            out = []
            for w in range(self.n_workers):
                try:
                    out.append(self._call(w, op, payload))
                except WorkerUnavailable:
                    out.append(None)
            return out
        out = [None] * self.n_workers
        sent = []
        for w in range(self.n_workers):
            try:
                self._pre_rpc(w)
                self._conns[w].send((op, payload))
                sent.append(w)
            except WorkerUnavailable:
                continue
            except (BrokenPipeError, EOFError, OSError):
                self._handle_process_death(w)
                continue
        for w in sent:
            try:
                status, val = self._recv_bounded(w, op)
                if status == "err":
                    raise RuntimeError(f"worker {w}: {val}")
                self.supervisor.mark_first_ack(w)
                out[w] = val
            except WorkerUnavailable:
                continue
        return out

    # -- engine API, sharded ---------------------------------------------

    def register(self, tenant_id, x, mask=None, params=None) -> int:
        w = self.worker_of(tenant_id)
        self._call(w, "register", (
            tenant_id, np.asarray(x, float),
            None if mask is None else np.asarray(mask, bool),
            None if params is None else _sanitize(params),
        ))
        return w

    def register_seed(self, tenant_id, x, mask=None, params=None) -> None:
        """Install a SEED tenant on EVERY worker so `register_shared`
        can clone it locally regardless of which shard the clone hashes
        to — the sharded analogue of the engine's shared-fit mass
        registration (register once, clone O(1) everywhere).  A dead
        worker misses the seed for this call; with a store the seed is
        durable on the surviving partitions and recoverable there."""
        payload = (
            tenant_id, np.asarray(x, float),
            None if mask is None else np.asarray(mask, bool),
            None if params is None else _sanitize(params),
        )
        self._fanout("register", payload)

    def register_shared(self, tenant_id, like) -> int:
        w = self.worker_of(tenant_id)
        self._call(w, "register_shared", (tenant_id, like))
        return w

    def handle(self, req):
        tid = req.get("tenant") if isinstance(req, dict) else None
        kind = req.get("kind") if isinstance(req, dict) else None
        w = self.worker_of(tid) if isinstance(tid, str) else 0
        try:
            return self._call(w, "handle", req)
        except WorkerUnavailable:
            return self._unavailable_response(kind, tid, w)

    def submit(self, reqs) -> None:
        """Batch-submit tick requests, bucketed per owning worker (one
        pipe message per worker, not per request).  A bucket whose
        worker is (or dies) unavailable is converted to typed
        ``worker_unavailable`` responses delivered by the next
        `flush_all` — one Response per submission, never a drop."""
        if isinstance(reqs, dict):
            reqs = [reqs]
        buckets: list = [[] for _ in range(self.n_workers)]
        for req in reqs:
            tid = req.get("tenant") if isinstance(req, dict) else None
            w = self.worker_of(tid) if isinstance(tid, str) else 0
            buckets[w].append(req)
        for w, bucket in enumerate(buckets):
            if not bucket:
                continue
            meta = [
                (r.get("kind") if isinstance(r, dict) else None,
                 r.get("tenant") if isinstance(r, dict) else None)
                for r in bucket
            ]
            try:
                self._call(w, "submit", bucket)
            except WorkerUnavailable:
                self._orphans[w].extend(
                    self._unavailable_response(k, t, w) for k, t in meta
                )
                continue
            self._pending[w].extend(meta)

    def flush_all(self) -> list:
        """Flush every worker's queue/pipeline; responses concatenated
        in worker order (per-worker submission order preserved).  A
        worker that died holding submitted-but-unflushed requests
        contributes one typed ``worker_unavailable`` Response per such
        request — degraded, never dropped."""
        out = []
        parts = self._fanout("flush")
        for w in range(self.n_workers):
            if self._orphans[w]:
                out.extend(self._orphans[w])
                self._orphans[w].clear()
            part = parts[w]
            if part is None:
                out.extend(
                    self._unavailable_response(kind, tid, w)
                    for kind, tid in self._pending[w]
                )
            else:
                out.extend(part)
            self._pending[w].clear()
        inc("serving.router.flushes")
        return out

    def flush_refits(self):
        """Gang-scheduled refit flush: pull every worker's queued
        refits, run ONE batched EM in the router process — under the
        process-spanning mesh when `parallel.distributed` is initialized
        — then install results back into the owning workers.  A member
        worker dying mid-refit aborts the barrier for that worker only:
        its pull contributes nothing, its install is retried once
        against the respawned worker, and whatever still fails lands in
        ``failed`` — the other members' refits always land.  Returns
        ``{"n_requests", "installed", "failed", "aborted_workers"}``."""
        import jax.numpy as jnp

        from .batch import RefitRequest, refit_batch
        from ..parallel import distributed as _dist

        pulls = self._fanout("refit_pull")
        aborted = [w for w, part in enumerate(pulls) if part is None]
        reqs, owner = [], {}
        for w, part in enumerate(pulls):
            for tid, x, mask, params in part or ():
                reqs.append(RefitRequest(
                    tenant_id=tid, x=jnp.asarray(x),
                    mask=jnp.asarray(mask), params=params,
                ))
                owner[tid] = w
        if not reqs:
            return {
                "n_requests": 0, "installed": 0, "failed": [],
                "aborted_workers": sorted(set(aborted)),
            }
        import jax

        eng_kw = self.engine_kwargs

        def _run():
            return refit_batch(
                reqs, isolate_errors=True, tol=eng_kw.get("tol", 1e-6),
                max_em_iter=eng_kw.get("max_em_iter", 200),
            )

        if jax.process_count() > 1:
            # process-spanning init active (PR 15): one batched EM over
            # the global mesh gang-schedules the refit across hosts
            with _dist.global_mesh():
                results = _run()
        else:
            results = _run()
        installs: list = [[] for _ in range(self.n_workers)]
        failed = []
        for res in results:
            if res.health == 0:
                installs[owner[res.tenant_id]].append(
                    (res.tenant_id, _sanitize(res.params))
                )
            else:
                failed.append(res.tenant_id)
        installed = 0
        for w, batch in enumerate(installs):
            if not batch:
                continue
            try:
                installed += self._call(w, "refit_install", batch)
            except WorkerUnavailable:
                # abort-and-retry: the owner died mid-refit; one retry
                # reaches the respawned worker (freshly recovered
                # tenants without history skip silently there)
                try:
                    installed += self._call(w, "refit_install", batch)
                except WorkerUnavailable:
                    failed.extend(tid for tid, _ in batch)
                    aborted.append(w)
        inc("serving.router.gang_refits")
        return {
            "n_requests": len(reqs), "installed": installed,
            "failed": failed,
            "aborted_workers": sorted(set(aborted)),
        }

    def recover(self, prewarm=None) -> list:
        return self._fanout("recover", prewarm)

    def flush_metrics(self) -> list:
        out = self._fanout("flush_metrics")
        # the supervisor's serving.worker.* gauges live in the ROUTER
        # process registry; snapshot them alongside the workers' flush
        # so summarize's worker column works from the sink alone
        emit_metrics()
        return out

    def check_liveness(self) -> list[str]:
        """Active heartbeat sweep: ping every worker over the ordinary
        pipe protocol (deadline-bounded like any RPC), detecting a dead
        or stalled worker BETWEEN requests instead of on the next
        client call.  Returns the post-sweep state per worker."""
        for w in range(self.n_workers):
            if self.supervisor.state(w) == WORKER_DEAD:
                continue
            try:
                self._call(w, "ping")
            except WorkerUnavailable:
                pass
        return self.worker_states()

    def stats(self) -> list:
        return self._fanout("stats")

    def tenant_ids(self) -> list:
        out = []
        for part in self._fanout("tenant_ids"):
            out.extend(part or ())
        return sorted(out)

    # -- lifecycle -------------------------------------------------------

    def close(self) -> None:
        """Shut every worker down: idempotent, deadline-bounded
        (`close_timeout_s` for the polite phase), and escalating —
        a worker that does not answer the close op within the budget is
        terminated, then SIGKILLed.  Never leaves an orphan process
        behind a failed drill, and never raises."""
        if self._closed:
            return
        self._closed = True
        if self._engines is not None:
            for pipe in self._pipes:
                if pipe is not None:
                    try:
                        pipe.close()
                    except Exception:
                        pass
            return
        for conn in self._conns:
            if conn is None:
                continue
            try:
                conn.send(("close", None))
            except Exception:
                pass
        deadline = time.perf_counter() + self.close_timeout_s
        for conn in self._conns:
            if conn is None:
                continue
            try:
                if conn.poll(max(0.0, deadline - time.perf_counter())):
                    conn.recv()
            except Exception:
                pass
            try:
                conn.close()
            except Exception:
                pass
        for proc in self._procs:
            if proc is None:
                continue
            try:
                proc.join(timeout=max(0.1, deadline - time.perf_counter()))
                if proc.is_alive():
                    proc.terminate()
                    proc.join(timeout=2.0)
                if proc.is_alive():
                    proc.kill()
                    proc.join(timeout=2.0)
            except Exception:
                pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        try:
            self.close()
        except Exception:
            pass
        return False
