"""Synchronous request-loop driver for multi-tenant nowcast serving.

The engine owns per-tenant state (panel, fitted params, ServingModel,
FilterState), routes requests, and brackets every request in a telemetry
RunRecord so the `telemetry summarize` CLI sees serving traffic next to
EM runs.  Request dicts:

    {"kind": "tick",     "tenant": id, "x": (N,) row, "mask": (N,) bool}
    {"kind": "nowcast",  "tenant": id, "horizon": h}
    {"kind": "refit",    "tenant": id}
    {"kind": "scenario", "tenant": id, "scenario": {"kind": ..., ...}}

`tick` is the O(1) constant-gain update (serving/online.py) — no refit,
no refactorization; `refit` only QUEUES the tenant, and `flush_refits()`
executes the queue batched per (T, N) compile bucket (serving/batch.py).
`scenario` hands the inner dict to scenarios.run_scenario against the
tenant's current fit and panel.  State persists per tenant through
serving/store.py.

Availability contract (docs/robustness.md): `handle()` ALWAYS returns a
typed `Response` envelope — client error, tenant fault, or system
fault, never an uncaught exception (injected external kills —
SimulatedCrash / SimulatedPreemption — excepted: those model the
process dying).  The hardening around the clean path:

* requests are validated up front (client errors name the offending
  field), carry an optional wall-clock deadline, and transient store
  I/O faults are retried with bounded exponential backoff and
  deterministic jitter (serving/resilience.py);
* a failed tick lands its row in the tenant's REPLAY BUFFER and the
  tenant serves DEGRADED nowcasts from last-good state (stamped
  `degraded` / `ticks_behind`) until recovery reconciles the buffer via
  one exact refilter — pinned against the never-faulted run;
* k consecutive faults open a per-tenant CIRCUIT BREAKER: ticks
  fast-fail into the buffer with no compute until a cooldown admits a
  half-open probe, whose reconcile closes it;
* every committed tick is WRITE-AHEAD journaled (serving/journal.py)
  before the in-memory commit, so a kill/restart replays snapshot +
  journal to a bit-identical FilterState with no caller-side panel.

The device programs are untouched: all hardening is host-side wrapping
around the same tick/nowcast executables (HLO pinned byte-identical by
tests/test_serving.py).

``python -m dynamic_factor_models_tpu.serve`` runs the demo loop below.
"""

from __future__ import annotations

import argparse
import json
import os
import re as _re
import sys
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np

from ..models import ssm as _ssm
from ..utils import faults as _faults
from ..utils import flight as _flight
from ..utils.compile import bucket_shape
from ..utils.guards import host_finite
from ..utils.telemetry import (
    _NULL_RECORD,
    _NULL_TRACE,
    emit_histograms,
    emit_metrics,
    gauge_set,
    inc,
    register_hist,
    run_record,
    trace_span,
    trace_span_on,
)
from .batch import (
    RefitRequest,
    batched_prefill_dispatch,
    batched_tick_dispatch,
    refit_batch,
)
from .online import (
    FilterState,
    derive_serving_model,
    nowcast,
    online_tick,
)
from .prefill import min_gemm_depth, prefill_enabled, prefill_ticks, tick_block
from .resilience import (
    BREAKER_OPEN,
    CLIENT_ERROR,
    SYSTEM_FAULT,
    TENANT_FAULT,
    CircuitBreaker,
    Deadline,
    ErrorInfo,
    Response,
    RetryPolicy,
    call_with_retries,
)
from .store import TenantState, TenantStore, template_state

__all__ = ["ServingEngine", "default_params", "main"]

_REQ_KINDS = ("tick", "nowcast", "refit", "scenario")


def default_params(N: int, r: int = 4, p: int = 4, dtype=float) -> _ssm.SSMParams:
    """Benign warm start for a tenant registered without a fit: unit
    loading on the first factor, unit noise, mildly persistent stationary
    factor VAR — the same shape bench.py's chaos section seeds with."""
    dt = jnp.result_type(dtype)  # respects the x64 switch
    lam = jnp.zeros((N, r), dt).at[:, 0].set(1.0)
    A = jnp.zeros((p, r, r), dt).at[0].set(0.5 * jnp.eye(r, dtype=dt))
    return _ssm.SSMParams(lam, jnp.ones((N,), dt), A, jnp.eye(r, dtype=dt))


class _History:
    """Amortized-append panel history.

    The old path re-built the panel with `np.vstack` on every tick — an
    O(T) copy per O(1) update, O(T^2) total bytes moved over a tenant's
    life.  This keeps (capacity, N) buffers, doubles capacity on
    overflow, and exposes zero-copy views of the live prefix; appending
    T rows is O(T) amortized.  `reallocs` counts doublings (bounded by
    log2 of the growth factor), which the perf regression test pins
    instead of flaky wall time."""

    __slots__ = ("_x", "_mask", "n", "reallocs", "_shared")

    def __init__(self, x, mask):
        self.n = int(x.shape[0])
        self._x = np.array(x, float, copy=True)
        self._mask = np.array(mask, bool, copy=True)
        self.reallocs = 0
        self._shared = False

    @classmethod
    def share(cls, other: "_History") -> "_History":
        """Zero-copy clone sharing `other`'s buffers copy-on-append.
        Safe against the source growing: the source writes rows only at
        indices >= this clone's frozen `n`, outside its views; the first
        append on the CLONE copies the prefix into private buffers."""
        h = cls.__new__(cls)
        h._x, h._mask, h.n = other._x, other._mask, other.n
        h.reallocs = 0
        h._shared = True
        return h

    @property
    def x(self) -> np.ndarray:
        return self._x[: self.n]

    @property
    def mask(self) -> np.ndarray:
        return self._mask[: self.n]

    def append(self, x_row, mask_row) -> None:
        if self._shared or self.n == self._x.shape[0]:
            cap = max(2 * self._x.shape[0], 8)
            nx = np.zeros((cap,) + self._x.shape[1:], self._x.dtype)
            nm = np.zeros((cap,) + self._mask.shape[1:], bool)
            nx[: self.n] = self._x[: self.n]
            nm[: self.n] = self._mask[: self.n]
            self._x, self._mask = nx, nm
            self.reallocs += 1
            self._shared = False
        self._x[self.n] = x_row
        self._mask[self.n] = mask_row
        self.n += 1


class _Tenant:
    __slots__ = (
        "hist", "params", "model", "state", "breaker", "replay", "suspect",
        "dirty", "breaker_saved", "nbytes", "journal",
    )

    def __init__(self, hist, params, model, state, breaker):
        self.hist = hist        # _History or None (panel-less resume)
        self.params = params
        self.model = model      # ServingModel
        self.state = state      # FilterState (last-good, committed)
        self.breaker = breaker  # CircuitBreaker
        self.replay = []        # [(x_row, mask_row)] failed-tick rows
        self.suspect = False    # force a deep finite check on next tick
        self.dirty = 0          # journaled ticks since the last snapshot
        self.breaker_saved = None  # packed breaker at last snapshot
        self.nbytes = 0         # resident-bytes accounting (upper bound)
        self.journal = None     # cached TickJournal (built on first use)


def _tenant_nbytes(ten: _Tenant) -> int:
    """Upper-bound resident-bytes accounting: the array leaves of
    params / model / state plus a PRIVATE history's live buffers.
    Clones from `register_shared` count their shared fit leaves once
    per clone — the budget is a conservative ceiling, not an
    allocator.  `.nbytes` is shape metadata on both numpy and jax
    arrays: no device transfer happens here."""
    n = 0
    for leaf in jax.tree.leaves((ten.params, ten.model, ten.state)):
        n += int(getattr(leaf, "nbytes", 0))
    h = ten.hist
    if h is not None and not h._shared:
        n += h._x.nbytes + h._mask.nbytes
    return n


class ServingEngine:
    """Single-process, synchronous multi-tenant serving driver.

    Memory is BOUNDED when a resident budget is set (`resident_tenants`
    / `resident_bytes`, env ``DFM_RESIDENT_TENANTS`` /
    ``DFM_RESIDENT_BYTES``): the tenant table is kept in LRU order and
    cold tenants are EVICTED through the snapshot + write-ahead-journal
    path, then faulted back in on next touch by replaying the journal
    through the same tick executable — bit-identical to never having
    been evicted (tests/test_eviction.py).  Eviction drops a tenant's
    in-memory panel history: a faulted-in tenant serves ticks and
    nowcasts normally but answers ``no_history`` to refit/scenario until
    re-registered with a panel (exactly the crash-restart contract)."""

    def __init__(
        self,
        store_dir: str | None = None,
        tol: float = 1e-6,
        max_em_iter: int = 200,
        deadline_s: float | None = None,
        retry_policy: RetryPolicy | None = None,
        breaker_threshold: int = 3,
        breaker_cooldown: int = 4,
        max_refit_retries: int = 2,
        slos=None,
        resident_tenants: int | None = None,
        resident_bytes: int | None = None,
    ):
        self.store = TenantStore(store_dir) if store_dir else None
        self.tol = tol
        self.max_em_iter = max_em_iter
        self.deadline_s = deadline_s  # default per-request budget
        self.retry_policy = retry_policy or RetryPolicy()
        self.breaker_threshold = breaker_threshold
        self.breaker_cooldown = breaker_cooldown
        self.max_refit_retries = max_refit_retries
        self.slos = list(slos or [])  # utils.slo.SLO monitors, by kind
        if resident_tenants is None:
            env = os.environ.get("DFM_RESIDENT_TENANTS")
            resident_tenants = int(env) if env else None
        if resident_bytes is None:
            env = os.environ.get("DFM_RESIDENT_BYTES")
            resident_bytes = int(env) if env else None
        if resident_tenants is not None and resident_tenants < 1:
            raise ValueError("resident_tenants must be >= 1")
        if resident_bytes is not None and resident_bytes < 1:
            raise ValueError("resident_bytes must be >= 1")
        self.resident_tenants = resident_tenants
        self.resident_bytes = resident_bytes
        self._budget_on = (
            resident_tenants is not None or resident_bytes is not None
        )
        if self._budget_on and self.store is None:
            raise ValueError(
                "a resident budget requires store_dir: eviction demotes "
                "cold tenants to the snapshot + journal store"
            )
        self._tenants: dict[str, _Tenant] = {}  # insertion order == LRU
        self._resident_nbytes = 0
        self._tick_queue: list = []  # (req, Deadline, t_submit)
        # tenants of the in-flight batched round, pinned against BUDGET
        # eviction: faulting in lane k must not evict lane j's tenant
        # mid-round (j < k) — the re-fault would both thrash the store
        # and commit lane j's tick onto an orphaned object.  The budget
        # may transiently overshoot by at most one round's lane width;
        # flush_period re-enforces it after every round.
        self._admission_pin: set[str] = set()
        self._refit_queue: list[str] = []
        self._refit_retries: dict[str, int] = {}
        self._requests = 0  # admission counter (slow_req/engine_crash sites)
        self._ticks = 0     # computed-tick counter (tick_nan site)
        # (kind, outcome) -> LatencyHistogram, held directly so the hot
        # path never takes the registry lock (register_hist once per key)
        self._lat_hists: dict = {}
        # serving-loop occupancy (PR 17): per-phase wall-clock split of
        # each round — journal-fsync / device-dispatch / commit /
        # envelope — the measurement baseline ROADMAP item 1's
        # pipelining speedup is claimed against.  `_obs_live` caches the
        # per-request run_record() enabled() probe so the phase timers
        # cost NOTHING when telemetry is off (the <5% envelope bar);
        # accumulated seconds reach the gauge registry only inside
        # flush_metrics, never per tick.
        self._obs_live = False
        self._occ_s: dict = {}       # phase -> cumulative seconds
        self._occ_req = 0.0          # phase seconds inside this request
        self._phase_hists: dict = {}  # phase -> LatencyHistogram
        self._slo_alerting = False   # edge-triggers the SLO-page dump
        # occupancy accumulation must be race-free once a pipeline's
        # backstage thread journals/commits round k while the main
        # thread admits round k+1; taken only while telemetry is live
        self._occ_lock = threading.Lock()
        # pipelined serving (serving/pipeline.py): the attached
        # ServingPipeline (None = plain sequential engine), the
        # deferred metrics-flush flag it drains on its commit stage,
        # and the committed-round counter behind the stall_commit@n
        # fault site
        self._pipeline = None
        self._metrics_due = False
        self._rounds_committed = 0
        # router-worker identity (serving/router.py stamps it via
        # set_worker_id): `_rec_extra` is splatted into every serving
        # RunRecord, so router-routed requests carry `worker_id` and a
        # standalone engine's records stay byte-identical to pre-PR-19
        # sinks (empty splat, no extra field, no extra probe)
        self._worker_id = None
        self._rec_extra: dict = {}

    def set_worker_id(self, worker_id: int) -> None:
        """Mark this engine as router worker `worker_id`: serving
        RunRecords (per-request and per-round) gain a ``worker_id``
        field for shard-level attribution in `summarize`."""
        self._worker_id = int(worker_id)
        self._rec_extra = {"worker_id": self._worker_id}

    # -- registration ----------------------------------------------------

    def register(self, tenant_id: str, x, mask=None, params=None) -> None:
        """Admit a tenant with its history panel.  `x` (T, N) may carry
        NaNs at missing entries when `mask` is omitted; `params` defaults
        to the benign warm start (call refit to actually fit).  Derives
        the ServingModel (one DARE solve) and seeds the filter state from
        one exact pass over the history — ticks are O(1) from here on."""
        x = np.asarray(x, float)
        if mask is None:
            mask = np.isfinite(x)
        mask = np.asarray(mask, bool)
        xz = np.where(mask, x, 0.0)
        if params is None:
            params = default_params(x.shape[1])
        self._install(tenant_id, xz, mask, params)

    def register_shared(self, tenant_id: str, like: str) -> None:
        """Admit `tenant_id` by CLONING tenant `like`'s fit: params,
        ServingModel (the DARE solve), and the history buffer are SHARED
        (history copy-on-append); only the small FilterState is fresh
        per clone.  O(1) per tenant instead of a DARE solve plus a full
        refilter — what makes 1k-100k synthetic tenants registrable in
        seconds for `bench.py --load`.  Ticks/nowcasts/refits/scenarios
        behave exactly as after `register()` with the same panel."""
        src = self._lookup(like)
        if src is None:
            raise KeyError(like)
        state = FilterState(s=src.state.s, t=src.state.t)
        breaker = CircuitBreaker(
            self.breaker_threshold, self.breaker_cooldown
        )
        self._persist(tenant_id, src.params, state, breaker)
        ten = _Tenant(
            None if src.hist is None else _History.share(src.hist),
            src.params, src.model, state, breaker,
        )
        if self.store is not None:
            ten.breaker_saved = breaker.pack()
        self._account_insert(tenant_id, ten)

    def _install(self, tenant_id, xz, mask, params) -> None:
        """(Re)derive a tenant's serving constants from `params` and its
        exact filter state from a full refilter of the panel; persist
        the snapshot and reset the tick journal, THEN commit in memory —
        a persistence failure (OSError after retries) leaves the
        previous tenant state untouched."""
        model = derive_serving_model(params)
        xnan = np.where(mask, xz, np.nan)
        filt = _ssm.kalman_filter(params, xnan)
        state = FilterState(
            s=jnp.asarray(filt.means[-1]),
            t=jnp.asarray(xz.shape[0], jnp.int32),
        )
        prev = self._tenants.get(tenant_id)
        breaker = prev.breaker if prev is not None else CircuitBreaker(
            self.breaker_threshold, self.breaker_cooldown
        )
        self._persist(tenant_id, params, state, breaker)
        ten = _Tenant(_History(xz, mask), params, model, state, breaker)
        if self.store is not None:
            ten.breaker_saved = breaker.pack()
        self._account_insert(tenant_id, ten)

    def _persist(self, tenant_id, params, state, breaker=None) -> int:
        """Snapshot (fsynced, atomic) + journal truncation, retried on
        transient I/O faults.  Returns the retry count consumed (0
        without a store).

        ORDERING INVARIANT: the snapshot is durable on disk BEFORE the
        journal is truncated — never the reverse.  A crash between the
        two leaves a STALE journal (anchored at a t older than the new
        snapshot) whose rows are already folded into the snapshot; the
        fault-in path skips it (satellite regression in
        tests/test_eviction.py).  The truncation is skipped entirely
        when no journal file exists yet — `TickJournal.append` creates
        its header lazily at the snapshot's own t, so a mass
        registration never touches a journal file."""
        if self.store is None:
            return 0
        packed = (
            breaker.pack() if breaker is not None
            else np.zeros((3,), np.int32)
        )

        def _save():
            self.store.save(
                tenant_id,
                TenantState(
                    params=params,
                    s=state.s,
                    t=state.t,
                    r=jnp.asarray(params.r, jnp.int32),
                    p=jnp.asarray(params.p, jnp.int32),
                    breaker=jnp.asarray(packed),
                ),
            )
            journal = self.store.journal(tenant_id)
            if journal.exists():
                journal.reset(int(state.t))

        _, retries = call_with_retries(
            _save, self.retry_policy, key=f"{tenant_id}:install"
        )
        return retries

    def tenant_ids(self) -> list[str]:
        """Sorted ids of RESIDENT tenants (evicted tenants live in the
        store only — `store.list()` enumerates everything on disk)."""
        return sorted(self._tenants)

    # -- resident-set management (LRU eviction / fault-in) ---------------

    def _resident_gauges(self) -> None:
        gauge_set("serving.resident_tenants", len(self._tenants))
        gauge_set("serving.resident_bytes", self._resident_nbytes)

    def _account_insert(self, tenant_id: str, ten: _Tenant) -> None:
        """Install `ten` as the MOST-RECENT entry, maintain the byte
        accounting, and enforce the resident budget (never evicting the
        tenant just inserted)."""
        prev = self._tenants.pop(tenant_id, None)
        if prev is not None:
            self._resident_nbytes -= prev.nbytes
        ten.nbytes = _tenant_nbytes(ten)
        self._tenants[tenant_id] = ten
        self._resident_nbytes += ten.nbytes
        self._enforce_budget(protect=tenant_id)
        self._resident_gauges()

    def _lookup(self, tenant_id):
        """Resident-set accessor: returns the tenant, faulting it back
        in from the store when evicted, None when unknown there too.
        Under an active budget a hit refreshes LRU recency (one dict
        pop / re-insert, O(1)); without a budget this is exactly the
        old single dict probe, keeping the clean-path host envelope
        intact (tests/test_perf_regression.py)."""
        ten = self._tenants.get(tenant_id)
        if ten is not None:
            if self._budget_on:
                del self._tenants[tenant_id]
                self._tenants[tenant_id] = ten
            return ten
        if self.store is not None:
            return self._fault_in(tenant_id)
        return None

    def _enforce_budget(self, protect: str | None = None) -> int:
        """Evict coldest-first until both budgets are satisfied (or
        nothing further is evictable — e.g. every candidate is pinned
        by a non-empty replay buffer).  Returns evictions performed."""
        if not self._budget_on:
            return 0
        evicted = 0
        while (
            self.resident_tenants is not None
            and len(self._tenants) > self.resident_tenants
        ) or (
            self.resident_bytes is not None
            and self._resident_nbytes > self.resident_bytes
        ):
            if not self._evict_coldest(protect):
                break
            evicted += 1
        return evicted

    def _evict_coldest(self, protect: str | None = None) -> bool:
        # fast path: the LRU head is evictable (the common case) — O(1)
        pin = self._admission_pin
        first = next(iter(self._tenants), None)
        if (
            first is not None and first != protect and first not in pin
            and self.evict(first)
        ):
            return True
        # slow path: scan for the coldest evictable tenant
        for tid in list(self._tenants):
            if tid == protect or tid == first or tid in pin:
                continue
            if self.evict(tid):
                return True
        return False

    def evict(self, tenant_id: str) -> bool:
        """Demote a resident tenant to the store and free its memory.

        Returns False when the tenant is not resident, there is no
        store, the tenant is PINNED (a non-empty replay buffer exists
        only in memory — evicting would drop acknowledged degradation
        state), or the snapshot write keeps failing.  A CLEAN tenant —
        zero journaled ticks and an unchanged breaker since its last
        snapshot — evicts with ZERO I/O: the write-ahead invariant
        already guarantees disk reproduces memory."""
        ten = self._tenants.get(tenant_id)
        if ten is None or self.store is None:
            return False
        if ten.replay:
            inc("serving.evict.pinned")
            return False
        packed = ten.breaker.pack()
        clean = ten.dirty == 0 and (
            ten.breaker_saved is not None
            and np.array_equal(packed, ten.breaker_saved)
        )
        if not clean:
            try:
                self._persist(tenant_id, ten.params, ten.state, ten.breaker)
            except OSError:
                inc("serving.evict.failures")
                return False
        del self._tenants[tenant_id]
        self._resident_nbytes -= ten.nbytes
        inc("serving.evictions")
        self._resident_gauges()
        return True

    def _fault_in(self, tenant_id: str, defer_replay: bool = False):
        """Re-admit an evicted (or restart-orphaned) tenant from its
        snapshot + write-ahead journal.

        Read-only except for stale-journal cleanup.  Short journals
        (< `DFM_PREFILL_MIN_K` rows) replay every row through the SAME
        tick executable the live path used, so the faulted-in
        FilterState is bit-identical to the never-evicted one (pinned
        by tests/test_eviction.py); deep journals collapse to the
        dual-form GEMM catch-up (serving/prefill.py — one Ā-power
        stack plus one (k×q) input-response GEMM, parity ≤1e-14
        complete / ≤1e-12 MF pinned by tests/test_prefill.py).  The
        snapshot-load and journal-replay legs are timed separately
        (`fault_in_load` / `fault_in_replay` histograms) on top of the
        combined `fault_in` one, so the prefill A/B in `bench.py
        --load` attributes the win honestly.  The circuit breaker is
        RESTORED from its packed snapshot leaf — an open breaker stays
        open across eviction.  Returns None when the store has no
        intact, consistent state for the id; with `defer_replay=True`
        returns ``(tenant, journal_rows)`` and leaves the rows
        un-applied (recover()'s concurrent replay)."""
        t0 = time.perf_counter()
        stored = self.store.load(tenant_id, template_state(1, 1, 1))
        if stored is None:
            return None
        params = stored.params
        r, p = int(stored.r), int(stored.p)
        if params.lam.shape[1] != r or params.A.shape[0] != p:
            inc("serving.store.inconsistent")
            return None
        try:
            model = derive_serving_model(params)
        except ValueError:
            inc("serving.store.inconsistent")
            return None
        state = FilterState(
            s=jnp.asarray(stored.s), t=jnp.asarray(stored.t, jnp.int32)
        )
        journal = self.store.journal(tenant_id)
        rows = []
        rep = journal.replay()
        if rep is not None:
            base_t, jrows = rep
            if base_t == int(stored.t):
                rows = jrows
            else:
                # a journal anchored below the snapshot's t is STALE:
                # the crash landed between the snapshot save and the
                # journal truncate, so every row is already folded into
                # the fsynced snapshot.  Skip it — never quarantine (the
                # file is intact, just superseded) — and delete it so
                # the next append re-anchors its header at the
                # snapshot's own t.
                if base_t < int(stored.t):
                    inc("serving.journal.stale_skipped")
                else:  # cannot happen under the persist ordering
                    inc("serving.store.inconsistent")
                journal.delete()
        breaker = CircuitBreaker.from_packed(
            self.breaker_threshold, self.breaker_cooldown, stored.breaker
        )
        ten = _Tenant(None, params, model, state, breaker)
        ten.breaker_saved = breaker.pack()
        t_load = time.perf_counter()
        self._observe("fault_in_load", "ok", t_load - t0, True)
        if rows and not defer_replay:
            # prefill_ticks routes short backlogs through the bitwise
            # sequential replay and deep ones through the GEMM dual
            ten.state = prefill_ticks(model, state, rows)
            t_rep = time.perf_counter()
            self._observe("fault_in_replay", "ok", t_rep - t_load, True)
            if self._obs_live:
                self._occ_add("prefill", t_rep - t_load)
        self._account_insert(tenant_id, ten)
        inc("serving.fault_ins")
        self._observe("fault_in", "ok", time.perf_counter() - t0, True)
        return (ten, rows) if defer_replay else ten

    # -- request routing -------------------------------------------------

    def handle(self, req) -> Response:
        """Route one request dict; ALWAYS returns a typed `Response`.

        Successful requests carry the result (new FilterState for tick,
        the (N,) vector for nowcast, queue position for refit, the
        ScenarioResult for scenario); failures carry an `ErrorInfo`
        classifying the cause.  The only exceptions that escape are the
        injected external kills (SimulatedCrash / SimulatedPreemption)
        and KeyboardInterrupt — everything else is an envelope."""
        self._requests += 1
        reqno = self._requests
        if _faults.site_hits("engine_crash", reqno):
            _faults.fault_fired("engine_crash")
            _flight.dump("engine_crash", force=True, reqno=reqno)
            raise _faults.SimulatedCrash(
                f"injected engine_crash at request {reqno}"
            )
        kind = req.get("kind") if isinstance(req, dict) else None
        tenant_id = req.get("tenant") if isinstance(req, dict) else None
        if not isinstance(tenant_id, str):
            tenant_id = None
        rkind = kind if kind in _REQ_KINDS else "invalid"
        t0 = time.perf_counter()
        # one enabled() probe per request: run_record() already performs
        # it, and returning the null singleton tells us the trace layer
        # is off too — a second probe (~1.6µs of env lookups) would blow
        # a visible hole in the <5% envelope bar
        rec_cm = run_record(
            "serving", kind=rkind, config={"tenant": tenant_id},
            **self._rec_extra,
        )
        # occupancy attribution rides the SAME probe: phase timers in
        # _tick/_flush_round fire only while this flag is up, so the
        # disabled path adds one attribute store and nothing else
        self._obs_live = rec_cm is not _NULL_RECORD
        self._occ_req = 0.0
        if rec_cm is _NULL_RECORD:
            tr_cm = _NULL_TRACE
        else:
            # deterministic trace identity: the request's own id, else
            # its admission index — identical request streams yield
            # identical span trees (pinned by tests/test_request_obs.py)
            rid = req.get("request_id") if isinstance(req, dict) else None
            tr_cm = trace_span_on(
                "serving.request", seed=rid or f"{tenant_id}:{reqno}",
                kind=rkind, tenant=tenant_id,
            )
        with tr_cm, rec_cm as rec:
            try:
                resp = self._dispatch(req, kind, tenant_id, reqno)
            except (
                _faults.SimulatedCrash,
                _faults.SimulatedPreemption,
                KeyboardInterrupt,
            ):
                raise
            except Exception as e:  # last resort: nothing else escapes
                inc("serving.internal_absorbed")
                resp = Response(
                    ok=False, kind=rkind, tenant=tenant_id,
                    error=ErrorInfo(
                        SYSTEM_FAULT, "internal",
                        f"{type(e).__name__}: {e}",
                    ),
                )
            outcome = (
                ("degraded" if resp.degraded else "ok")
                if resp.ok else resp.error.category
            )
            latency_s = time.perf_counter() - t0
            if rec is not _NULL_RECORD:
                rec.set(
                    outcome=outcome,
                    error_kind=(
                        None if resp.error is None else resp.error.code
                    ),
                    retries=resp.retries,
                    breaker_state=resp.breaker_state,
                    latency_s=round(latency_s, 9),
                )
        if self._obs_live:
            # envelope = request wall-clock not attributed to a device
            # dispatch / journal append / memory commit phase — the
            # host-side overhead the 5%-of-a-tick budget bounds
            self._occ_add(
                "envelope", max(0.0, latency_s - self._occ_req)
            )
        self._observe(rkind, outcome, latency_s, resp.ok)
        if (reqno & 1023) == 0 and rec is not _NULL_RECORD:
            # with a pipeline attached the request path never blocks on
            # telemetry I/O: the flush is deferred onto the pipeline's
            # commit stage (drained in _commit_lanes); the bare
            # sequential engine keeps the inline flush
            if self._pipeline is not None:
                self._metrics_due = True
            else:
                self.flush_metrics()
        return resp

    def _occ_add(self, phase: str, dt: float) -> None:
        """Accumulate one occupancy phase sample: cumulative seconds
        (gauges pushed by `flush_metrics`, never per tick) plus the
        per-phase HDR histogram.  Callers gate on `_obs_live`, so the
        lock is never taken on the disabled clean path."""
        with self._occ_lock:
            self._occ_s[phase] = self._occ_s.get(phase, 0.0) + dt
            self._occ_req += dt
            try:
                h = self._phase_hists[phase]
            except KeyError:
                h = register_hist(
                    "serving.phase.latency", entry="serving", phase=phase,
                )
                self._phase_hists[phase] = h
            h.record(dt)

    def _observe(self, kind, outcome, latency_s, ok) -> None:
        """O(1) host-side per-request accounting: one histogram bucket
        increment per (kind, outcome) plus the SLO window counters for
        monitors matching this kind.  Never touches a device."""
        try:
            h = self._lat_hists[(kind, outcome)]
        except KeyError:
            h = register_hist(
                "serving.request.latency",
                entry="serving", kind=kind, outcome=outcome,
            )
            self._lat_hists[(kind, outcome)] = h
        h.record(latency_s)
        if self.slos:
            for slo in self.slos:
                if slo.kind == kind:
                    slo.observe(latency_s, ok)

    def flush_metrics(self) -> int:
        """Push SLO burn-rate gauges and the resident-set gauges into
        the telemetry registry, then snapshot one ``entry="metrics"``
        counters/gauges line plus the latency histograms into the JSONL
        sink (when one is active).  Called every 1024th request
        automatically; call explicitly at the end of a run to flush the
        tail."""
        alerting = False
        for slo in self.slos:
            for name, val in slo.gauges().items():
                gauge_set(name, val)
            try:
                alerting = alerting or bool(slo.status().get("alerting"))
            except Exception:
                pass
        # SLO page: edge-triggered flight dump (one bundle per alert
        # transition, not one per flush while the page stays up)
        if alerting and not self._slo_alerting:
            _flight.record("serving.slo_page")
            _flight.dump("slo_page")
        self._slo_alerting = alerting
        with self._occ_lock:
            occ = dict(self._occ_s)
        for phase, s in occ.items():
            gauge_set(f"serving.occupancy.{phase}_s", round(s, 9))
        self._resident_gauges()
        emit_metrics()
        return emit_histograms()

    def _dispatch(self, req, kind, tenant_id, reqno) -> Response:
        if not isinstance(req, dict):
            return Response(
                ok=False, kind="invalid", tenant=None,
                error=ErrorInfo(
                    CLIENT_ERROR, "bad_request",
                    f"request must be a dict, got {type(req).__name__}",
                ),
            )
        if kind is None:
            return self._client_err(
                "invalid", tenant_id, "missing_field",
                "request is missing 'kind'", field="kind",
            )
        if kind not in _REQ_KINDS:
            return self._client_err(
                "invalid", tenant_id, "unknown_kind",
                f"unknown request kind {kind!r} "
                f"(valid: {', '.join(_REQ_KINDS)})", field="kind",
            )
        if tenant_id is None:
            return self._client_err(
                kind, None, "missing_field",
                "request is missing 'tenant'", field="tenant",
            )
        ten = self._lookup(tenant_id)
        if ten is None:
            return self._client_err(
                kind, tenant_id, "unknown_tenant",
                f"unknown tenant {tenant_id!r}", field="tenant",
            )
        deadline = Deadline(req.get("deadline_s", self.deadline_s))
        if _faults.site_hits("slow_req", reqno):
            _faults.fault_fired("slow_req")
            deadline.expire()
        bstate = ten.breaker.on_request()
        if kind == "tick":
            return self._tick(tenant_id, ten, req, deadline, bstate)
        if deadline.exceeded():  # nothing to buffer for read-only kinds
            return self._fault_resp(
                kind, tenant_id, ten,
                ErrorInfo(
                    SYSTEM_FAULT, "deadline_exceeded",
                    f"deadline of {deadline.budget_s}s exceeded",
                ),
            )
        if kind == "nowcast":
            return self._nowcast(tenant_id, ten, req)
        if kind == "refit":
            pos = self._queue_refit(tenant_id)
            return Response(
                ok=True, kind="refit", tenant=tenant_id, result=pos,
                breaker_state=ten.breaker.state,
            )
        return self._scenario(tenant_id, ten, req)

    # -- envelope helpers ------------------------------------------------

    def _client_err(self, kind, tenant_id, code, msg, field) -> Response:
        ten = self._tenants.get(tenant_id) if tenant_id else None
        inc("serving.client_errors")
        return Response(
            ok=False, kind=kind, tenant=tenant_id,
            error=ErrorInfo(CLIENT_ERROR, code, msg, field),
            degraded=bool(ten.replay) if ten else False,
            ticks_behind=len(ten.replay) if ten else 0,
            breaker_state=ten.breaker.state if ten else "closed",
        )

    def _fault_resp(
        self, kind, tenant_id, ten, err, retries=0,
        count_fault=True, recovered=False,
    ) -> Response:
        """A tenant/system fault envelope: stamps the degradation state
        and (unless `count_fault=False`, e.g. a fast-fail against an
        already-open breaker) counts one fault toward the breaker."""
        if count_fault:
            was_open = ten.breaker.state == BREAKER_OPEN
            ten.breaker.record_fault()
            if not was_open and ten.breaker.state == BREAKER_OPEN:
                _flight.record(
                    "serving.breaker_open", tenant=tenant_id, code=err.code,
                )
        inc("serving.faults." + err.code)
        if err.category == SYSTEM_FAULT:
            # typed system fault: ring event + (throttled) bundle dump —
            # the pre-mortem for "the engine started answering
            # system_fault envelopes at 3am"
            _flight.record(
                "serving.system_fault", kind=kind, tenant=tenant_id,
                code=err.code,
            )
            _flight.dump("system_fault", code=err.code)
        return Response(
            ok=False, kind=kind, tenant=tenant_id, error=err,
            degraded=bool(ten.replay), ticks_behind=len(ten.replay),
            retries=retries, breaker_state=ten.breaker.state,
            recovered=recovered,
        )

    # -- tick ------------------------------------------------------------

    def _parse_tick_row(self, tenant_id, ten, req):
        """Validate a tick request's x/mask against the tenant's series
        dimension; returns ``(row, None)`` on success or ``(None,
        Response)`` carrying the client error — one shared path for the
        sequential `_tick` and the batched `flush_period`."""
        # validation: name the offending field, never a raw KeyError
        if "x" not in req:
            return None, self._client_err(
                "tick", tenant_id, "missing_field",
                "tick request is missing 'x'", field="x",
            )
        try:
            x_t = np.asarray(req["x"], float)
        except (TypeError, ValueError):
            return None, self._client_err(
                "tick", tenant_id, "bad_value",
                "'x' is not convertible to a float array", field="x",
            )
        N = ten.model.Wb.shape[0]
        if x_t.shape != (N,):
            return None, self._client_err(
                "tick", tenant_id, "bad_shape",
                f"'x' must have shape ({N},), got {x_t.shape}", field="x",
            )
        if req.get("mask") is None:
            mask_t = np.isfinite(x_t)
        else:
            try:
                mask_t = np.asarray(req["mask"], bool)
            except (TypeError, ValueError):
                return None, self._client_err(
                    "tick", tenant_id, "bad_value",
                    "'mask' is not convertible to a bool array",
                    field="mask",
                )
            if mask_t.shape != (N,):
                return None, self._client_err(
                    "tick", tenant_id, "bad_shape",
                    f"'mask' must have shape ({N},), got {mask_t.shape}",
                    field="mask",
                )
        return (np.where(mask_t, x_t, 0.0), mask_t), None

    def _tick(self, tenant_id, ten, req, deadline, bstate) -> Response:
        row, err = self._parse_tick_row(tenant_id, ten, req)
        if err is not None:
            return err

        if bstate == BREAKER_OPEN:
            ten.replay.append(row)
            return self._fault_resp(
                "tick", tenant_id, ten,
                ErrorInfo(
                    TENANT_FAULT, "breaker_open",
                    "circuit breaker open; tick buffered for replay",
                ),
                count_fault=False,
            )

        # recovery: reconcile any buffered rows before applying this one
        recovered = False
        if ten.replay:
            try:
                with trace_span("serving.reconcile", n_rows=len(ten.replay)):
                    self._reconcile(tenant_id, ten)
                ten = self._tenants[tenant_id]  # reconcile reinstalls
                recovered = True
            except OSError as e:
                ten.replay.append(row)
                return self._fault_resp(
                    "tick", tenant_id, ten,
                    ErrorInfo(
                        SYSTEM_FAULT, "store_io",
                        f"reconcile persistence failed: {e}",
                    ),
                )

        if deadline.exceeded():
            ten.replay.append(row)
            return self._fault_resp(
                "tick", tenant_id, ten,
                ErrorInfo(
                    SYSTEM_FAULT, "deadline_exceeded",
                    f"deadline of {deadline.budget_s}s exceeded",
                ),
                recovered=recovered,
            )

        self._ticks += 1
        obs = self._obs_live
        t_ph = time.perf_counter() if obs else 0.0
        new_state = online_tick(ten.model, ten.state, row[0], row[1])
        if _faults.site_hits("tick_nan", self._ticks):
            _faults.fault_fired("tick_nan")
            new_state = FilterState(s=new_state.s * np.nan, t=new_state.t)
        # The deep check materializes the state on host — a forced device
        # sync that breaks dispatch pipelining, ~the whole envelope
        # budget on its own.  The committed state is provably finite when
        # the previous state and this row were (the update is linear in
        # both with finite install-time constants), so the clean fast
        # path samples the sync every 8th tick and goes deep only when a
        # cheap host signal says it must: an observed non-finite input,
        # an active fault plan (injection bypasses the invariant by
        # poisoning the output directly), a panel-less tenant (its
        # reconcile path cannot refilter from scratch), or a suspect
        # flag raised by a non-finite materialized nowcast.
        deep = (
            ten.suspect
            or ten.hist is None
            or not np.isfinite(row[0]).all()
            or _faults.active_plan().any()
            or (self._ticks & 7) == 0
        )
        if deep and not host_finite(new_state.s):
            ten.replay.append(row)
            return self._fault_resp(
                "tick", tenant_id, ten,
                ErrorInfo(
                    TENANT_FAULT, "nonfinite_state",
                    "tick produced a non-finite filter state; "
                    "row buffered for replay",
                ),
                recovered=recovered,
            )
        if obs:  # device dispatch + (sampled) deep check
            self._occ_add("dispatch", time.perf_counter() - t_ph)
        if deadline.exceeded():  # final probe before the commit point
            ten.replay.append(row)
            return self._fault_resp(
                "tick", tenant_id, ten,
                ErrorInfo(
                    SYSTEM_FAULT, "deadline_exceeded",
                    f"deadline of {deadline.budget_s}s exceeded",
                ),
                recovered=recovered,
            )

        # write-ahead: the journal append is the commit point
        retries = 0
        if self.store is not None:
            journal = ten.journal
            if journal is None:
                journal = ten.journal = self.store.journal(tenant_id)
            t_idx = int(ten.state.t)
            t_ph = time.perf_counter() if obs else 0.0
            try:
                with trace_span("tick.journal_append", t=t_idx):
                    _, retries = call_with_retries(
                        lambda: journal.append(t_idx, row[0], row[1]),
                        self.retry_policy,
                        key=f"{tenant_id}:tick:{t_idx}",
                        deadline=deadline,
                    )
            except OSError as e:
                ten.replay.append(row)
                return self._fault_resp(
                    "tick", tenant_id, ten,
                    ErrorInfo(
                        SYSTEM_FAULT, "store_io",
                        f"tick journal append failed: {e}",
                    ),
                    retries=self.retry_policy.max_retries,
                    recovered=recovered,
                )
            if obs:  # write-ahead append incl. fsync and retries
                self._occ_add("journal", time.perf_counter() - t_ph)

        t_ph = time.perf_counter() if obs else 0.0
        ten.state = new_state
        ten.dirty += 1  # this tick lives in the journal, not the snapshot
        if deep:
            ten.suspect = False  # committed state re-verified on host
        if ten.hist is not None:
            ten.hist.append(row[0], row[1])
        ten.breaker.record_success()
        if obs:
            self._occ_add("commit", time.perf_counter() - t_ph)
        return Response(
            ok=True, kind="tick", tenant=tenant_id, result=new_state,
            retries=retries, breaker_state=ten.breaker.state,
            recovered=recovered,
        )

    def _reconcile(self, tenant_id, ten) -> None:
        """Fold the replay buffer back into committed state.

        Panel tenants get ONE exact refilter over history + buffered
        rows (`_install`), the recovery the chaos tests pin ≤ 1e-10
        against the never-faulted run; panel-less resumed tenants
        journal the whole buffer COALESCED (one `append_many`, durable
        before any state moves) and then catch up through
        `prefill_ticks` — bitwise sequential replay below the GEMM
        threshold, the dual-form burst kernel above it.  Raises
        OSError when persistence keeps failing — the caller leaves the
        buffer intact and reports a system fault."""
        rows, ten.replay = ten.replay, []
        try:
            if ten.hist is not None:
                xs = np.vstack([ten.hist.x] + [r[0][None] for r in rows])
                ms = np.vstack([ten.hist.mask] + [r[1][None] for r in rows])
                self._install(tenant_id, xs, ms, ten.params)
            else:
                if rows and self.store is not None:
                    journal = self.store.journal(tenant_id)
                    t_idx = int(ten.state.t)
                    jrows = [
                        (t_idx + i, x_row, m_row)
                        for i, (x_row, m_row) in enumerate(rows)
                    ]
                    call_with_retries(
                        lambda: journal.append_many(jrows),
                        self.retry_policy,
                        key=f"{tenant_id}:reconcile:{t_idx}",
                    )
                ten.state = prefill_ticks(ten.model, ten.state, rows)
                ten.dirty += len(rows)
        except OSError:
            ten.replay = rows + ten.replay  # keep the rows for next try
            raise
        inc("serving.reconciles")

    # -- nowcast / refit / scenario --------------------------------------

    def _nowcast(self, tenant_id, ten, req) -> Response:
        try:
            horizon = int(req.get("horizon", 0))
        except (TypeError, ValueError):
            return self._client_err(
                "nowcast", tenant_id, "bad_value",
                "'horizon' must be a non-negative integer", field="horizon",
            )
        if horizon < 0:
            return self._client_err(
                "nowcast", tenant_id, "bad_value",
                f"'horizon' must be >= 0, got {horizon}", field="horizon",
            )
        # degraded mode: last-good state still answers, with an explicit
        # staleness stamp, while the tenant's ticks are buffered
        vec = np.asarray(nowcast(ten.model, ten.state, horizon))
        # the result just materialized on host, so this check is free —
        # it is the backstop for the sampled deep check in _tick: a
        # non-finite state can never reach a caller unflagged
        if not np.isfinite(vec).all():
            ten.suspect = True
            return self._fault_resp(
                "nowcast", tenant_id, ten,
                ErrorInfo(
                    TENANT_FAULT, "nonfinite_state",
                    "nowcast drew on a non-finite filter state; "
                    "tenant flagged for deep check",
                ),
            )
        return Response(
            ok=True, kind="nowcast", tenant=tenant_id, result=vec,
            degraded=bool(ten.replay), ticks_behind=len(ten.replay),
            breaker_state=ten.breaker.state,
        )

    def _scenario(self, tenant_id, ten, req) -> Response:
        from ..scenarios import (
            ScenarioRequest,
            ScenarioValidationError,
            run_scenario,
        )

        spec = req.get("scenario")
        if spec is None:
            return self._client_err(
                "scenario", tenant_id, "missing_field",
                "scenario request is missing 'scenario'", field="scenario",
            )
        if not isinstance(spec, dict):
            return self._client_err(
                "scenario", tenant_id, "bad_value",
                f"'scenario' must be a dict, got {type(spec).__name__}",
                field="scenario",
            )
        if ten.hist is None:
            return self._fault_resp(
                "scenario", tenant_id, ten,
                ErrorInfo(
                    TENANT_FAULT, "no_history",
                    "tenant was resumed without a panel; re-register "
                    "with history to run scenarios",
                ),
                count_fault=False,
            )
        try:
            sreq = ScenarioRequest(**spec)
        except TypeError as e:
            m = _re.search(r"'(\w+)'", str(e))
            field = f"scenario.{m.group(1)}" if m else "scenario"
            return self._client_err(
                "scenario", tenant_id, "unknown_scenario_field",
                str(e), field=field,
            )
        x = np.where(ten.hist.mask, ten.hist.x, np.nan)
        try:
            result = run_scenario(ten.params, x, sreq)
        except ScenarioValidationError as e:
            # api-level validation names the offending field — surface it
            # on the ErrorInfo.field slot like every other client error
            return self._client_err(
                "scenario", tenant_id, "bad_scenario",
                str(e), field=f"scenario.{e.field}",
            )
        except ValueError as e:  # bad spec values below the validators
            return self._client_err(
                "scenario", tenant_id, "bad_scenario",
                str(e), field="scenario",
            )
        return Response(
            ok=True, kind="scenario", tenant=tenant_id, result=result,
            degraded=bool(ten.replay), ticks_behind=len(ten.replay),
            breaker_state=ten.breaker.state,
        )

    def _queue_refit(self, tenant_id: str) -> int:
        if tenant_id not in self._refit_queue:
            self._refit_queue.append(tenant_id)
        return self._refit_queue.index(tenant_id)

    # -- batched refits --------------------------------------------------

    def flush_refits(self) -> Response:
        """Execute the refit queue, batched per (T, N) compile bucket.

        Healthy tenants get new params + re-derived serving constants +
        an exact refiltered state; a tenant whose loop tripped keeps its
        previous fit and is RE-QUEUED, up to `max_refit_retries` flushes,
        after which it is surfaced as a permanent failure (and counted in
        telemetry) instead of silently dropped.  Returns a Response whose
        `result` maps tenant_id -> RefitResult and whose `info` carries
        ``installed`` / ``requeued`` / ``permanent_failures``."""
        queue, self._refit_queue = self._refit_queue, []
        if not queue:
            return Response(
                ok=True, kind="refit_flush", tenant=None, result={},
                info={"installed": 0, "requeued": [],
                      "permanent_failures": []},
            )
        reqs = []
        for tid in queue:
            ten = self._tenants.get(tid)
            if ten is None or ten.hist is None:
                # panel-less (nothing to refit against) or evicted while
                # queued (an evicted tenant faults back panel-less — its
                # refit would be a no-op anyway)
                self._refit_retries.pop(tid, None)
                continue
            reqs.append(RefitRequest(
                tenant_id=tid,
                x=jnp.asarray(ten.hist.x),
                mask=jnp.asarray(ten.hist.mask),
                params=ten.params,
            ))
        with run_record(
            "serving", kind="refit_flush", config={"n_tenants": len(reqs)},
            **self._rec_extra,
        ) as rec:
            results = refit_batch(
                reqs, tol=self.tol, max_em_iter=self.max_em_iter,
                isolate_errors=True,
            )
            installed, requeued, permanent = 0, [], []
            for res in results:
                ten = self._tenants.get(res.tenant_id)
                if ten is None:  # evicted mid-flush by budget pressure
                    self._refit_retries.pop(res.tenant_id, None)
                    continue
                ok = res.health == 0
                if ok:
                    try:
                        self._install(
                            res.tenant_id, ten.hist.x, ten.hist.mask,
                            res.params,
                        )
                    except OSError:
                        ok = False  # persistence failed: retry the refit
                if ok:
                    installed += 1
                    self._refit_retries.pop(res.tenant_id, None)
                    continue
                n = self._refit_retries.get(res.tenant_id, 0) + 1
                self._refit_retries[res.tenant_id] = n
                if n <= self.max_refit_retries:
                    requeued.append(res.tenant_id)
                    if res.tenant_id not in self._refit_queue:
                        self._refit_queue.append(res.tenant_id)
                else:
                    permanent.append(res.tenant_id)
                    self._refit_retries.pop(res.tenant_id, None)
                    inc("serving.refit.permanent_failures")
            rec.set(
                n_installed=installed,
                outcome="ok" if not permanent else "tenant_fault",
                error_kind=None if not permanent else "refit_permanent",
                retries=max(
                    (self._refit_retries.get(t, 0) for t in requeued),
                    default=0,
                ),
                breaker_state="closed",
            )
        return Response(
            ok=True, kind="refit_flush", tenant=None,
            result={res.tenant_id: res for res in results},
            info={"installed": installed, "requeued": requeued,
                  "permanent_failures": permanent},
        )

    # -- continuous tick batching ----------------------------------------

    def submit(self, req) -> int:
        """Admit one TICK request into the continuous-batching queue;
        returns the queue depth after admission.

        Ticks submitted here are coalesced across tenants and executed
        by `flush_period()` — one vmapped constant-gain dispatch per
        lane-shape group per round — with write-ahead / exactly-once
        guarantees identical to `handle()`'s sequential path (journal
        appends, in admission order, are the per-lane commit points).
        Non-tick kinds are answered at flush time with a typed
        ``unbatchable_kind`` client error rather than silently dropped.
        Admission shares `handle()`'s fault sites: ``engine_crash`` and
        ``slow_req`` fire against the same request counter."""
        self._requests += 1
        reqno = self._requests
        if _faults.site_hits("engine_crash", reqno):
            _faults.fault_fired("engine_crash")
            _flight.dump("engine_crash", force=True, reqno=reqno)
            raise _faults.SimulatedCrash(
                f"injected engine_crash at request {reqno}"
            )
        budget = (
            req.get("deadline_s", self.deadline_s)
            if isinstance(req, dict) else self.deadline_s
        )
        deadline = Deadline(budget)
        if _faults.site_hits("slow_req", reqno):
            _faults.fault_fired("slow_req")
            deadline.expire()
        self._tick_queue.append((req, deadline, time.perf_counter()))
        return len(self._tick_queue)

    def flush_period(self) -> list:
        """Execute the admission queue as ONE serving period.

        The whole queue forms ONE round: a tenant's queued ticks become
        a BLOCK lane (k sequential ticks in one scan dispatch, bitwise
        equal to k single-tick dispatches — serving/prefill.tick_block),
        single-tick tenants batch into one vmapped dispatch per
        lane-shape group — padded to a compile bucket with inert lanes
        (serving/batch.py) — and one typed Response returns per
        submitted request, in submission order.  Per-tenant FIFO order
        is preserved: lanes admit in submission order and a block
        applies its rows in order.

        Exactly-once: every surviving lane's journal append (fsynced,
        admission order, one coalesced `append_many` per tenant)
        completes BEFORE any lane of the round commits in memory.  A
        kill between the two replays the journaled ticks on restart,
        while un-appended lanes never happened and their callers were
        never acked — no tick is double-applied or dropped.  One
        tenant's failure (tick_nan poison, journal OSError) freezes
        only its own lanes; a poisoned row poisons the REST of its
        block (the rows behind it cannot commit past the hole)."""
        entries, self._tick_queue = self._tick_queue, []
        if not entries:
            return []
        responses: list = [None] * len(entries)
        with run_record(
            "serving", kind="tick_flush",
            config={"n_lanes": len(entries)},
            **self._rec_extra,
        ) as rec:
            self._obs_live = rec is not _NULL_RECORD
            self._occ_req = 0.0
            t_period = time.perf_counter() if self._obs_live else 0.0
            rounds = 1
            self._flush_round(entries, list(range(len(entries))), responses)
            inc("serving.batch.flushes")
            if self._obs_live:
                # envelope = period wall-clock beyond the attributed
                # dispatch/journal/commit phases (admission, batching
                # glue, response assembly)
                self._occ_add("envelope", max(
                    0.0,
                    (time.perf_counter() - t_period) - self._occ_req,
                ))
            ok_n = sum(1 for r in responses if r is not None and r.ok)
            if rec is not _NULL_RECORD:
                rec.set(
                    outcome="ok" if ok_n == len(responses) else "partial",
                    n_lanes=len(entries), n_rounds=rounds, n_ok=ok_n,
                    breaker_state="closed",
                )
        now = time.perf_counter()
        for (req, _dl, t_sub), resp in zip(entries, responses):
            outcome = (
                ("degraded" if resp.degraded else "ok")
                if resp.ok else resp.error.category
            )
            self._observe("tick", outcome, now - t_sub, resp.ok)
        return responses

    def _flush_round(self, entries, idxs, responses) -> None:
        """One batched round: validate/admit each lane sequentially in
        admission order, run ONE batched dispatch for the survivors,
        then journal-append every lane (admission order — the commit
        points) before committing ANY lane in memory."""
        lanes = []  # (qi, tenant_id, ten, row, deadline, recovered)
        self._admission_pin = {
            tid for qi in idxs
            if isinstance(entries[qi][0], dict)
            and isinstance(tid := entries[qi][0].get("tenant"), str)
        }
        try:
            self._flush_round_pinned(entries, idxs, responses, lanes)
        finally:
            self._admission_pin = set()
            self._enforce_budget()

    def _flush_round_pinned(self, entries, idxs, responses, lanes) -> None:
        """One round = the four pipeline stages run back-to-back on the
        caller thread.  serving/pipeline.py calls the same four helpers
        with round k's journal/commit overlapping round k+1's
        admit/dispatch — the stage split IS the pipeline's stage
        structure, so sequential and pipelined rounds cannot drift."""
        obs = self._obs_live
        self._admit_lanes(entries, idxs, responses, lanes, obs=obs)
        staged = self._dispatch_lanes(lanes, obs=obs)
        commits = self._journal_lanes(staged, responses, obs=obs)
        self._commit_lanes(commits, responses, obs=obs)

    # -- round stages (shared by flush_period and ServingPipeline) -------

    def _admit_lanes(self, entries, idxs, responses, lanes, obs=None) -> None:
        """ADMIT stage: validate, look up (faulting in evicted
        tenants), reconcile replay buffers, and deadline-check each
        entry in admission order; survivors land in `lanes` as
        ``(qi, tenant_id, ten, row, deadline, recovered)``."""
        if obs is None:
            obs = self._obs_live
        t_ph = time.perf_counter() if obs else 0.0
        for qi in idxs:
            req, deadline, _t_sub = entries[qi]
            if not isinstance(req, dict):
                inc("serving.client_errors")
                responses[qi] = Response(
                    ok=False, kind="invalid", tenant=None,
                    error=ErrorInfo(
                        CLIENT_ERROR, "bad_request",
                        f"request must be a dict, got {type(req).__name__}",
                    ),
                )
                continue
            kind = req.get("kind")
            tenant_id = req.get("tenant")
            if not isinstance(tenant_id, str):
                tenant_id = None
            if kind != "tick":
                responses[qi] = self._client_err(
                    kind if kind in _REQ_KINDS else "invalid", tenant_id,
                    "unbatchable_kind",
                    "only 'tick' requests can be batch-submitted; use "
                    "handle() for other kinds", field="kind",
                )
                continue
            if tenant_id is None:
                responses[qi] = self._client_err(
                    "tick", None, "missing_field",
                    "request is missing 'tenant'", field="tenant",
                )
                continue
            ten = self._lookup(tenant_id)
            if ten is None:
                responses[qi] = self._client_err(
                    "tick", tenant_id, "unknown_tenant",
                    f"unknown tenant {tenant_id!r}", field="tenant",
                )
                continue
            row, err = self._parse_tick_row(tenant_id, ten, req)
            if err is not None:
                responses[qi] = err
                continue
            if ten.breaker.on_request() == BREAKER_OPEN:
                ten.replay.append(row)
                responses[qi] = self._fault_resp(
                    "tick", tenant_id, ten,
                    ErrorInfo(
                        TENANT_FAULT, "breaker_open",
                        "circuit breaker open; tick buffered for replay",
                    ),
                    count_fault=False,
                )
                continue
            recovered = False
            if ten.replay:
                try:
                    with trace_span(
                        "serving.reconcile", n_rows=len(ten.replay)
                    ):
                        self._reconcile(tenant_id, ten)
                    ten = self._tenants[tenant_id]
                    recovered = True
                except OSError as e:
                    ten.replay.append(row)
                    responses[qi] = self._fault_resp(
                        "tick", tenant_id, ten,
                        ErrorInfo(
                            SYSTEM_FAULT, "store_io",
                            f"reconcile persistence failed: {e}",
                        ),
                    )
                    continue
            if deadline.exceeded():
                ten.replay.append(row)
                responses[qi] = self._fault_resp(
                    "tick", tenant_id, ten,
                    ErrorInfo(
                        SYSTEM_FAULT, "deadline_exceeded",
                        f"deadline of {deadline.budget_s}s exceeded",
                    ),
                    recovered=recovered,
                )
                continue
            lanes.append((qi, tenant_id, ten, row, deadline, recovered))
        if obs:  # validation + fault-in + reconcile, the round's front door
            self._occ_add("admit", time.perf_counter() - t_ph)

    def _dispatch_lanes(self, lanes, obs=None) -> list:
        """DISPATCH stage: single-tick tenants share one vmapped device
        dispatch; a tenant with several lanes this round gets ONE
        decode-form block dispatch (scan over its rows — bitwise equal
        to sequential single-tick dispatches, serving/prefill.py) whose
        trajectory supplies the per-lane states.  Returns
        ``[(lane, new_state, poisoned)]`` in admission order; the tick
        counter advances per lane in admission order, so the tick_nan
        site fires on exactly the tick index it would have under
        sequential serving.  A poisoned row poisons the REST of its
        tenant's block: the later rows were computed past a state that
        will not commit, and committing them would skip the hole."""
        if obs is None:
            obs = self._obs_live
        if not lanes:
            return []
        poisoned = []
        for _lane in lanes:
            self._ticks += 1
            hit = _faults.site_hits("tick_nan", self._ticks)
            if hit:
                _faults.fault_fired("tick_nan")
            poisoned.append(hit)
        groups: dict = {}  # tenant -> lane indices, admission order
        for li, lane in enumerate(lanes):
            groups.setdefault(lane[1], []).append(li)
        for lis in groups.values():
            bad = False
            for li in lis:
                bad = bad or poisoned[li]
                poisoned[li] = bad
        new_states: list = [None] * len(lanes)
        singles = [lis[0] for lis in groups.values() if len(lis) == 1]
        blocks = [lis for lis in groups.values() if len(lis) > 1]
        t_ph = time.perf_counter() if obs else 0.0
        if singles:
            sts = batched_tick_dispatch(
                [(lanes[li][2].model, lanes[li][2].state,
                  lanes[li][3][0], lanes[li][3][1]) for li in singles]
            )
            for li, st in zip(singles, sts):
                new_states[li] = st
        if obs:  # one vmapped device dispatch for the singleton lanes
            self._occ_add("dispatch", time.perf_counter() - t_ph)
        t_pf = time.perf_counter() if obs else 0.0
        for lis in blocks:
            ten = lanes[lis[0]][2]
            _final, traj = tick_block(
                ten.model, ten.state, [lanes[li][3] for li in lis]
            )
            for li, st in zip(lis, traj):
                new_states[li] = st
        if obs and blocks:  # one scan dispatch per burst tenant
            self._occ_add("prefill", time.perf_counter() - t_pf)
        return list(zip(lanes, new_states, poisoned))

    def _journal_lanes(self, staged, responses, obs=None) -> list:
        """JOURNAL stage: deep-check every lane's freshly materialized
        state, then write-ahead the round COALESCED — one buffered
        write per touched journal file (all lanes' records), then one
        fsync sweep.  Every append is durable before this returns, so
        the stage boundary after it IS the round's acked⇔durable line.
        A failed lane buffers its row and freezes only that tenant.
        Returns the commit list for `_commit_lanes`."""
        if obs is None:
            obs = self._obs_live
        if not staged:
            return []
        t_ph = time.perf_counter() if obs else 0.0
        # per-lane isolation: batched serving always deep-checks (the
        # states just materialized on host)
        alive = []
        for (qi, tenant_id, ten, row, deadline, recovered), st, poi in staged:
            if poi:
                st = FilterState(s=st.s * np.nan, t=st.t)
            if not host_finite(st.s):
                ten.replay.append(row)
                responses[qi] = self._fault_resp(
                    "tick", tenant_id, ten,
                    ErrorInfo(
                        TENANT_FAULT, "nonfinite_state",
                        "tick produced a non-finite filter state; "
                        "row buffered for replay",
                    ),
                    recovered=recovered,
                )
                continue
            alive.append((qi, tenant_id, ten, row, st, recovered, deadline))
        commits = []
        if self.store is None:
            commits = [
                (qi, tid, ten, row, st, rc, 0, dl)
                for qi, tid, ten, row, st, rc, dl in alive
            ]
        else:
            # phase A: one buffered write per tenant journal (grouped
            # in admission order; a burst tenant's whole block is one
            # group, so its records land in one buffered write with
            # consecutive tick indices)
            groups: dict = {}
            order = []
            for lane in alive:
                tid = lane[1]
                if tid not in groups:
                    groups[tid] = []
                    order.append(tid)
                groups[tid].append(lane)
            pending = []
            for tid in order:
                group = groups[tid]
                ten = group[0][2]
                deadline = group[0][6]
                journal = ten.journal
                if journal is None:
                    journal = ten.journal = self.store.journal(tid)
                t_idx = int(ten.state.t)
                rows = [
                    (t_idx + i, lane[3][0], lane[3][1])
                    for i, lane in enumerate(group)
                ]
                holder = {}

                def _write(j=journal, r=rows, h=holder):
                    h["p"] = j.append_many(r, sync=False)

                try:
                    with trace_span(
                        "tick.journal_append", t=t_idx, n=len(rows)
                    ):
                        _, retries = call_with_retries(
                            _write,
                            self.retry_policy,
                            key=f"{tid}:tick:{t_idx}",
                            deadline=deadline,
                        )
                except OSError as e:
                    self._fail_lanes(
                        group, responses,
                        ErrorInfo(
                            SYSTEM_FAULT, "store_io",
                            f"tick journal append failed: {e}",
                        ),
                        retries=self.retry_policy.max_retries,
                    )
                    continue
                pending.append((group, holder.get("p"), retries))
            # phase B: the fsync sweep — ALL writes before ANY sync
            # completed, all syncs before any commit (write-ahead)
            for group, pend, retries in pending:
                try:
                    if pend is not None:
                        pend.sync()
                except OSError as e:
                    self._fail_lanes(
                        group, responses,
                        ErrorInfo(
                            SYSTEM_FAULT, "store_io",
                            f"tick journal fsync failed: {e}",
                        ),
                        retries=retries,
                    )
                    continue
                for qi, tid, ten, row, st, rc, dl in group:
                    commits.append((qi, tid, ten, row, st, rc, retries, dl))
        if obs:  # deep checks + coalesced write-ahead appends (fsync)
            self._occ_add("journal", time.perf_counter() - t_ph)
        return commits

    def _fail_lanes(self, group, responses, err, retries=0) -> None:
        """Fail every lane of one journal group: rows to the replay
        buffer (admission order), typed fault envelopes out."""
        for qi, tid, ten, row, _st, recovered, _dl in group:
            ten.replay.append(row)
            responses[qi] = self._fault_resp(
                "tick", tid, ten, err,
                retries=retries, recovered=recovered,
            )

    def _commit_lanes(self, commits, responses, obs=None) -> None:
        """COMMIT stage: apply every journaled lane's state in
        admission order — memory commits strictly after EVERY lane's
        append has settled.  Hosts the ``stall_commit@n`` fault site
        (the n-th committing round sleeps past its deadline budget —
        the lanes are already durable, so the stall delays acks without
        touching exactness) and drains the deferred metrics flush the
        request path parked here."""
        if obs is None:
            obs = self._obs_live
        if not commits:
            if self._metrics_due and obs:
                self._metrics_due = False
                self.flush_metrics()
            return
        t_ph = time.perf_counter() if obs else 0.0
        if commits:
            self._rounds_committed += 1
            rc = self._rounds_committed
            if _faults.site_hits("stall_commit", rc):
                _faults.fault_fired("stall_commit")
                budget = max(
                    (c[7].budget_s or 0.0 for c in commits
                     if c[7] is not None and c[7].budget_s is not None),
                    default=0.0,
                )
                stall_s = budget + 0.02
                time.sleep(stall_s)
                _flight.record(
                    "serving.stall_commit", round=rc,
                    stalled_s=round(stall_s, 6), n_lanes=len(commits),
                )
                _flight.dump("stall_commit", round=rc)
        for qi, tenant_id, ten, row, st, recovered, retries, _dl in commits:
            ten.state = st
            ten.suspect = False
            ten.dirty += 1
            if ten.hist is not None:
                ten.hist.append(row[0], row[1])
            ten.breaker.record_success()
            inc("serving.batch.lanes")
            responses[qi] = Response(
                ok=True, kind="tick", tenant=tenant_id, result=st,
                retries=retries, breaker_state=ten.breaker.state,
                recovered=recovered,
            )
        if obs:
            self._occ_add("commit", time.perf_counter() - t_ph)
        if self._metrics_due and obs:
            self._metrics_due = False
            self.flush_metrics()

    # -- persistence -----------------------------------------------------

    def recover(self, prewarm: int | None = None) -> dict:
        """Whole-process restart recovery: scan the store and rebuild
        the serving set with BOUNDED memory.

        All on-disk tenants stay COLD by default — `_lookup` faults
        each back in lazily on first touch, so recovery cost is O(1) in
        tenant count beyond the directory scan.  ``prewarm > 0``
        eagerly faults in the `prewarm` most-recently-snapshotted
        tenants (capped by the resident budget) and replays their
        journals CONCURRENTLY.  Short journals advance round by round
        — round i ticks every prewarmed tenant holding an i-th
        journaled row through one batched vmapped dispatch,
        bit-identical to sequential replay.  Deep journals (>=
        `min_gemm_depth()` rows) collapse through the lane-batched
        dual-form GEMM prefill instead (serving/batch.
        batched_prefill_dispatch — parity <=1e-14 complete / 1e-12 MF,
        tests/test_prefill.py), which is what makes respawned-worker
        failover (serving/router.py rides this path) O(log k) in
        backlog depth.  Returns a summary dict (``tenants_on_disk`` /
        ``prewarmed`` / ``resident`` / ``resident_bytes`` /
        ``wall_s``)."""
        if self.store is None:
            raise ValueError("recover() requires a store_dir")
        t0 = time.perf_counter()
        ids = self.store.list()
        warmed = 0
        if prewarm:
            cap = int(prewarm)
            if self.resident_tenants is not None:
                cap = min(cap, self.resident_tenants)
            hot = sorted(
                ids, key=self.store.snapshot_mtime, reverse=True
            )[:cap]
            pending = []  # (tenant_id, tenant, journal rows)
            deep = []  # backlogs past the GEMM threshold
            gemm_k = min_gemm_depth() if prefill_enabled() else None
            for tid in hot:
                got = self._fault_in(tid, defer_replay=True)
                if got is None:
                    continue
                warmed += 1
                ten, rows = got
                if not rows:
                    continue
                if gemm_k is not None and len(rows) >= gemm_k:
                    deep.append((tid, ten, rows))
                else:
                    pending.append((tid, ten, rows))
            if deep:
                new_states = batched_prefill_dispatch(
                    [(ten.model, ten.state, rows) for _tid, ten, rows in deep]
                )
                for (tid, ten, _rows), st in zip(deep, new_states):
                    # identity check mirrors the round loop below: never
                    # clobber a re-faulted-in instance
                    if self._tenants.get(tid) is ten:
                        ten.state = st
            step = 0
            while pending:
                lanes, keep = [], []
                for tid, ten, rows in pending:
                    # identity check: if budget pressure evicted this
                    # tenant mid-replay, its partial state was safely
                    # dropped (the journal still covers every row) — do
                    # not clobber a re-faulted-in instance
                    if self._tenants.get(tid) is not ten:
                        continue
                    _t, x_row, m_row = rows[step]
                    lanes.append((ten.model, ten.state, x_row, m_row))
                    keep.append((tid, ten, rows))
                if not lanes:
                    break
                new_states = batched_tick_dispatch(lanes)
                nxt = []
                for (tid, ten, rows), st in zip(keep, new_states):
                    ten.state = st
                    if step + 1 < len(rows):
                        nxt.append((tid, ten, rows))
                pending = nxt
                step += 1
        gauge_set("serving.recover.tenants_on_disk", len(ids))
        self._resident_gauges()
        inc("serving.recoveries")
        return {
            "tenants_on_disk": len(ids),
            "prewarmed": warmed,
            "resident": len(self._tenants),
            "resident_bytes": self._resident_nbytes,
            "wall_s": time.perf_counter() - t0,
        }

    def resume(self, tenant_id: str, x=None, mask=None) -> bool:
        """Re-admit a tenant from the store.  Returns False when the
        store has no intact state for the id (never saved, or its
        archive was quarantined as corrupt) — register() it afresh.

        With a panel `x` supplied, the snapshot's params are re-derived
        against the caller's history (the classic path).  WITHOUT a
        panel — the crash-restart path — this is exactly the eviction
        fault-in: the snapshot's FilterState is restored, the breaker
        rebuilt from its packed snapshot leaf, and the write-ahead tick
        journal replayed through the same tick executable, landing
        bit-identically on the killed process's committed state; the
        tenant then serves ticks and nowcasts normally but answers
        `no_history` to refit/scenario until re-registered with
        history."""
        if self.store is None:
            return False
        if x is None:
            return self._fault_in(tenant_id) is not None
        # the template is structure-only (leaf shapes come from the
        # archive), so one (1, 1, 1) template loads any (N, r, p) tenant
        stored = self.store.load(tenant_id, template_state(1, 1, 1))
        if stored is None:
            return False
        params = stored.params
        r, p = int(stored.r), int(stored.p)
        if params.lam.shape[1] != r or params.A.shape[0] != p:
            inc("serving.store.inconsistent")
            return False
        x = np.asarray(x, float)
        if mask is None:
            mask = np.isfinite(x)
        mask = np.asarray(mask, bool)
        self._install(tenant_id, np.where(mask, x, 0.0), mask, params)
        return True


# -- CLI demo ------------------------------------------------------------


def _synthetic_panel(rng, T: int, N: int, r: int = 4):
    f = rng.standard_normal((T, r)).cumsum(0) * 0.1
    lam = rng.standard_normal((N, r))
    x = f @ lam.T + 0.5 * rng.standard_normal((T, N))
    return x


def main(argv=None) -> int:
    """Demo loop: register a few synthetic tenants, stream ticks, serve
    nowcasts, run one batched refit flush; prints one JSON line per
    phase.  ``python -m dynamic_factor_models_tpu.serve``."""
    ap = argparse.ArgumentParser(
        prog="python -m dynamic_factor_models_tpu.serve",
        description="multi-tenant nowcast serving demo",
    )
    ap.add_argument("--tenants", type=int, default=3)
    ap.add_argument("--T", type=int, default=96)
    ap.add_argument("--N", type=int, default=8)
    ap.add_argument("--ticks", type=int, default=12)
    ap.add_argument("--store-dir", default=None)
    ap.add_argument("--max-em-iter", type=int, default=30)
    args = ap.parse_args(argv)

    rng = np.random.default_rng(0)
    eng = ServingEngine(store_dir=args.store_dir, max_em_iter=args.max_em_iter)
    for i in range(args.tenants):
        eng.register(f"tenant{i}", _synthetic_panel(rng, args.T, args.N))
    print(json.dumps({
        "phase": "register", "tenants": eng.tenant_ids(),
        "bucket": list(bucket_shape(args.T, args.N)),
    }))

    for _ in range(args.ticks):
        for tid in eng.tenant_ids():
            row = rng.standard_normal(args.N)
            eng.handle({"kind": "tick", "tenant": tid, "x": row})
    resp = eng.handle({"kind": "nowcast", "tenant": "tenant0", "horizon": 0})
    print(json.dumps({
        "phase": "ticks", "n_ticks": args.ticks * args.tenants,
        "degraded": resp.degraded,
        "nowcast0_head": [
            round(float(v), 4) for v in np.asarray(resp.result)[:4]
        ],
    }))

    for tid in eng.tenant_ids():
        eng.handle({"kind": "refit", "tenant": tid})
    flush = eng.flush_refits()
    print(json.dumps({
        "phase": "refit",
        "results": {
            tid: {
                "n_iter": r.n_iter,
                "converged": r.converged,
                "health": r.health,
            }
            for tid, r in sorted(flush.result.items())
        },
        "permanent_failures": flush.info["permanent_failures"],
    }))

    sc = eng.handle({
        "kind": "scenario", "tenant": "tenant0",
        "scenario": {
            "kind": "stress", "horizon": 6,
            "shocks": np.eye(4)[:2].tolist(),
        },
    })
    print(json.dumps({
        "phase": "scenario", "scenario": "stress",
        "fan_shape": list(np.asarray(sc.result.mean).shape),
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
