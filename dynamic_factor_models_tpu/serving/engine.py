"""Synchronous request-loop driver for multi-tenant nowcast serving.

The engine owns per-tenant state (panel, fitted params, ServingModel,
FilterState), routes requests, and brackets every request in a telemetry
RunRecord so the `telemetry summarize` CLI sees serving traffic next to
EM runs.  Request dicts:

    {"kind": "tick",     "tenant": id, "x": (N,) row, "mask": (N,) bool}
    {"kind": "nowcast",  "tenant": id, "horizon": h}
    {"kind": "refit",    "tenant": id}
    {"kind": "scenario", "tenant": id, "scenario": {"kind": ..., ...}}

`tick` is the O(1) constant-gain update (serving/online.py) — no refit,
no refactorization; `refit` only QUEUES the tenant, and `flush_refits()`
executes the queue batched per (T, N) compile bucket (serving/batch.py).
`scenario` hands the inner dict to scenarios.run_scenario against the
tenant's current fit and panel — conditional/stress/draw fans and
batched news, each one vmapped device program (see docs/scenarios.md).
A tenant whose batched refit trips the health sentinel keeps its previous
fit (the rollback already happened inside the loop; the engine just
declines to install the frozen iterate) — its bucket-mates are installed
normally.  State persists per tenant through serving/store.py.

``python -m dynamic_factor_models_tpu.serve`` runs the demo loop below.
"""

from __future__ import annotations

import argparse
import json
import sys

import jax.numpy as jnp
import numpy as np

from ..models import ssm as _ssm
from ..utils.compile import bucket_shape
from ..utils.telemetry import run_record
from .batch import RefitRequest, refit_batch
from .online import (
    FilterState,
    derive_serving_model,
    nowcast,
    online_tick,
)
from .store import TenantState, TenantStore

__all__ = ["ServingEngine", "default_params", "main"]


def default_params(N: int, r: int = 4, p: int = 4, dtype=float) -> _ssm.SSMParams:
    """Benign warm start for a tenant registered without a fit: unit
    loading on the first factor, unit noise, mildly persistent stationary
    factor VAR — the same shape bench.py's chaos section seeds with."""
    dt = jnp.result_type(dtype)  # respects the x64 switch
    lam = jnp.zeros((N, r), dt).at[:, 0].set(1.0)
    A = jnp.zeros((p, r, r), dt).at[0].set(0.5 * jnp.eye(r, dtype=dt))
    return _ssm.SSMParams(lam, jnp.ones((N,), dt), A, jnp.eye(r, dtype=dt))


class _Tenant:
    __slots__ = ("x", "mask", "params", "model", "state")

    def __init__(self, x, mask, params, model, state):
        self.x = x          # (T, N) np array, zero-filled at missing
        self.mask = mask    # (T, N) np bool
        self.params = params
        self.model = model  # ServingModel
        self.state = state  # FilterState


class ServingEngine:
    """Single-process, synchronous multi-tenant serving driver."""

    def __init__(
        self,
        store_dir: str | None = None,
        tol: float = 1e-6,
        max_em_iter: int = 200,
    ):
        self.store = TenantStore(store_dir) if store_dir else None
        self.tol = tol
        self.max_em_iter = max_em_iter
        self._tenants: dict[str, _Tenant] = {}
        self._refit_queue: list[str] = []

    # -- registration ----------------------------------------------------

    def register(self, tenant_id: str, x, mask=None, params=None) -> None:
        """Admit a tenant with its history panel.  `x` (T, N) may carry
        NaNs at missing entries when `mask` is omitted; `params` defaults
        to the benign warm start (call refit to actually fit).  Derives
        the ServingModel (one DARE solve) and seeds the filter state from
        one exact pass over the history — ticks are O(1) from here on."""
        x = np.asarray(x, float)
        if mask is None:
            mask = np.isfinite(x)
        mask = np.asarray(mask, bool)
        xz = np.where(mask, x, 0.0)
        if params is None:
            params = default_params(x.shape[1])
        self._install(tenant_id, xz, mask, params)

    def _install(self, tenant_id, xz, mask, params) -> None:
        """(Re)derive a tenant's serving constants from `params` and its
        exact filter state from a full refilter of the panel."""
        model = derive_serving_model(params)
        xnan = np.where(mask, xz, np.nan)
        filt = _ssm.kalman_filter(params, xnan)
        state = FilterState(
            s=jnp.asarray(filt.means[-1]),
            t=jnp.asarray(xz.shape[0], jnp.int32),
        )
        self._tenants[tenant_id] = _Tenant(xz, mask, params, model, state)
        if self.store is not None:
            self.store.save(
                tenant_id, TenantState(params=params, s=state.s, t=state.t)
            )

    def tenant_ids(self) -> list[str]:
        return sorted(self._tenants)

    # -- request routing -------------------------------------------------

    def handle(self, req: dict):
        """Route one request dict; returns the request's result (the new
        FilterState for tick, the (N,) nowcast vector, or the refit-queue
        position).  Unknown kinds / tenants raise ValueError."""
        kind = req.get("kind")
        tenant_id = req.get("tenant")
        if tenant_id not in self._tenants:
            raise ValueError(f"unknown tenant {tenant_id!r}")
        if kind == "tick":
            return self._tick(tenant_id, req["x"], req.get("mask"))
        if kind == "nowcast":
            return self._nowcast(tenant_id, int(req.get("horizon", 0)))
        if kind == "refit":
            return self._queue_refit(tenant_id)
        if kind == "scenario":
            return self._scenario(tenant_id, req.get("scenario") or {})
        raise ValueError(f"unknown request kind {kind!r}")

    def _tick(self, tenant_id: str, x_t, mask_t=None) -> FilterState:
        ten = self._tenants[tenant_id]
        x_t = np.asarray(x_t, float)
        if mask_t is None:
            mask_t = np.isfinite(x_t)
        mask_t = np.asarray(mask_t, bool)
        with run_record("serving", kind="tick", config={"tenant": tenant_id}):
            ten.state = online_tick(ten.model, ten.state, x_t, mask_t)
        ten.x = np.vstack([ten.x, np.where(mask_t, x_t, 0.0)[None]])
        ten.mask = np.vstack([ten.mask, mask_t[None]])
        return ten.state

    def _nowcast(self, tenant_id: str, horizon: int):
        ten = self._tenants[tenant_id]
        with run_record(
            "serving", kind="nowcast",
            config={"tenant": tenant_id, "horizon": horizon},
        ):
            return nowcast(ten.model, ten.state, horizon)

    def _scenario(self, tenant_id: str, spec: dict):
        """Run a scenario fan against the tenant's current fit + panel.
        `spec` supplies ScenarioRequest fields by name; unknown fields
        raise (TypeError from the NamedTuple) rather than being dropped
        silently."""
        from ..scenarios import ScenarioRequest, run_scenario

        ten = self._tenants[tenant_id]
        req = ScenarioRequest(**spec)
        with run_record(
            "serving", kind="scenario",
            config={
                "tenant": tenant_id,
                "scenario": req.kind,
                "horizon": int(req.horizon),
                "n_draws": int(req.n_draws or 0),
            },
        ):
            x = np.where(ten.mask, ten.x, np.nan)
            return run_scenario(ten.params, x, req)

    def _queue_refit(self, tenant_id: str) -> int:
        if tenant_id not in self._refit_queue:
            self._refit_queue.append(tenant_id)
        return self._refit_queue.index(tenant_id)

    # -- batched refits --------------------------------------------------

    def flush_refits(self) -> dict:
        """Execute the refit queue, batched per (T, N) compile bucket.

        Healthy tenants get new params + re-derived serving constants +
        an exact refiltered state; a tenant whose loop tripped keeps its
        previous fit untouched.  Returns {tenant_id: RefitResult}."""
        queue, self._refit_queue = self._refit_queue, []
        if not queue:
            return {}
        reqs = [
            RefitRequest(
                tenant_id=tid,
                x=jnp.asarray(self._tenants[tid].x),
                mask=jnp.asarray(self._tenants[tid].mask),
                params=self._tenants[tid].params,
            )
            for tid in queue
        ]
        with run_record(
            "serving", kind="refit_flush", config={"n_tenants": len(reqs)},
        ) as rec:
            results = refit_batch(
                reqs, tol=self.tol, max_em_iter=self.max_em_iter
            )
            installed = 0
            for res in results:
                ten = self._tenants[res.tenant_id]
                if res.health == 0:
                    self._install(res.tenant_id, ten.x, ten.mask, res.params)
                    installed += 1
            rec.set(n_installed=installed)
        return {res.tenant_id: res for res in results}

    # -- persistence -----------------------------------------------------

    def resume(self, tenant_id: str, x, mask=None) -> bool:
        """Re-admit a tenant from the store (params + filter clock); the
        caller supplies the history panel (panels are not persisted —
        they live in the tenant's data plane).  Returns False when the
        store has no intact state for the id (never saved, or its archive
        was quarantined as corrupt) — register() it afresh instead."""
        if self.store is None:
            return False
        x = np.asarray(x, float)
        if mask is None:
            mask = np.isfinite(x)
        mask = np.asarray(mask, bool)
        N = x.shape[1]
        from .store import template_state

        like = template_state(N, 4, 4)
        stored = self.store.load(tenant_id, like)
        if stored is None:
            return False
        self._install(
            tenant_id, np.where(mask, x, 0.0), mask, stored.params
        )
        return True


# -- CLI demo ------------------------------------------------------------


def _synthetic_panel(rng, T: int, N: int, r: int = 4):
    f = rng.standard_normal((T, r)).cumsum(0) * 0.1
    lam = rng.standard_normal((N, r))
    x = f @ lam.T + 0.5 * rng.standard_normal((T, N))
    return x


def main(argv=None) -> int:
    """Demo loop: register a few synthetic tenants, stream ticks, serve
    nowcasts, run one batched refit flush; prints one JSON line per
    phase.  ``python -m dynamic_factor_models_tpu.serve``."""
    ap = argparse.ArgumentParser(
        prog="python -m dynamic_factor_models_tpu.serve",
        description="multi-tenant nowcast serving demo",
    )
    ap.add_argument("--tenants", type=int, default=3)
    ap.add_argument("--T", type=int, default=96)
    ap.add_argument("--N", type=int, default=8)
    ap.add_argument("--ticks", type=int, default=12)
    ap.add_argument("--store-dir", default=None)
    ap.add_argument("--max-em-iter", type=int, default=30)
    args = ap.parse_args(argv)

    rng = np.random.default_rng(0)
    eng = ServingEngine(store_dir=args.store_dir, max_em_iter=args.max_em_iter)
    for i in range(args.tenants):
        eng.register(f"tenant{i}", _synthetic_panel(rng, args.T, args.N))
    print(json.dumps({
        "phase": "register", "tenants": eng.tenant_ids(),
        "bucket": list(bucket_shape(args.T, args.N)),
    }))

    for _ in range(args.ticks):
        for tid in eng.tenant_ids():
            row = rng.standard_normal(args.N)
            eng.handle({"kind": "tick", "tenant": tid, "x": row})
    nc = eng.handle({"kind": "nowcast", "tenant": "tenant0", "horizon": 0})
    print(json.dumps({
        "phase": "ticks", "n_ticks": args.ticks * args.tenants,
        "nowcast0_head": [round(float(v), 4) for v in np.asarray(nc)[:4]],
    }))

    for tid in eng.tenant_ids():
        eng.handle({"kind": "refit", "tenant": tid})
    results = eng.flush_refits()
    print(json.dumps({
        "phase": "refit",
        "results": {
            tid: {
                "n_iter": r.n_iter,
                "converged": r.converged,
                "health": r.health,
            }
            for tid, r in sorted(results.items())
        },
    }))

    sc = eng.handle({
        "kind": "scenario", "tenant": "tenant0",
        "scenario": {
            "kind": "stress", "horizon": 6,
            "shocks": np.eye(4)[:2].tolist(),
        },
    })
    print(json.dumps({
        "phase": "scenario", "scenario": "stress",
        "fan_shape": list(np.asarray(sc.mean).shape),
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
