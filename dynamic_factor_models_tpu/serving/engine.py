"""Synchronous request-loop driver for multi-tenant nowcast serving.

The engine owns per-tenant state (panel, fitted params, ServingModel,
FilterState), routes requests, and brackets every request in a telemetry
RunRecord so the `telemetry summarize` CLI sees serving traffic next to
EM runs.  Request dicts:

    {"kind": "tick",     "tenant": id, "x": (N,) row, "mask": (N,) bool}
    {"kind": "nowcast",  "tenant": id, "horizon": h}
    {"kind": "refit",    "tenant": id}
    {"kind": "scenario", "tenant": id, "scenario": {"kind": ..., ...}}

`tick` is the O(1) constant-gain update (serving/online.py) — no refit,
no refactorization; `refit` only QUEUES the tenant, and `flush_refits()`
executes the queue batched per (T, N) compile bucket (serving/batch.py).
`scenario` hands the inner dict to scenarios.run_scenario against the
tenant's current fit and panel.  State persists per tenant through
serving/store.py.

Availability contract (docs/robustness.md): `handle()` ALWAYS returns a
typed `Response` envelope — client error, tenant fault, or system
fault, never an uncaught exception (injected external kills —
SimulatedCrash / SimulatedPreemption — excepted: those model the
process dying).  The hardening around the clean path:

* requests are validated up front (client errors name the offending
  field), carry an optional wall-clock deadline, and transient store
  I/O faults are retried with bounded exponential backoff and
  deterministic jitter (serving/resilience.py);
* a failed tick lands its row in the tenant's REPLAY BUFFER and the
  tenant serves DEGRADED nowcasts from last-good state (stamped
  `degraded` / `ticks_behind`) until recovery reconciles the buffer via
  one exact refilter — pinned against the never-faulted run;
* k consecutive faults open a per-tenant CIRCUIT BREAKER: ticks
  fast-fail into the buffer with no compute until a cooldown admits a
  half-open probe, whose reconcile closes it;
* every committed tick is WRITE-AHEAD journaled (serving/journal.py)
  before the in-memory commit, so a kill/restart replays snapshot +
  journal to a bit-identical FilterState with no caller-side panel.

The device programs are untouched: all hardening is host-side wrapping
around the same tick/nowcast executables (HLO pinned byte-identical by
tests/test_serving.py).

``python -m dynamic_factor_models_tpu.serve`` runs the demo loop below.
"""

from __future__ import annotations

import argparse
import json
import re as _re
import sys
import time

import jax.numpy as jnp
import numpy as np

from ..models import ssm as _ssm
from ..utils import faults as _faults
from ..utils.compile import bucket_shape
from ..utils.guards import host_finite
from ..utils.telemetry import (
    _NULL_RECORD,
    _NULL_TRACE,
    emit_histograms,
    gauge_set,
    inc,
    register_hist,
    run_record,
    trace_span,
    trace_span_on,
)
from .batch import RefitRequest, refit_batch
from .online import (
    FilterState,
    derive_serving_model,
    nowcast,
    online_tick,
    replay_ticks,
)
from .resilience import (
    BREAKER_OPEN,
    CLIENT_ERROR,
    SYSTEM_FAULT,
    TENANT_FAULT,
    CircuitBreaker,
    Deadline,
    ErrorInfo,
    Response,
    RetryPolicy,
    call_with_retries,
)
from .store import TenantState, TenantStore, template_state

__all__ = ["ServingEngine", "default_params", "main"]

_REQ_KINDS = ("tick", "nowcast", "refit", "scenario")


def default_params(N: int, r: int = 4, p: int = 4, dtype=float) -> _ssm.SSMParams:
    """Benign warm start for a tenant registered without a fit: unit
    loading on the first factor, unit noise, mildly persistent stationary
    factor VAR — the same shape bench.py's chaos section seeds with."""
    dt = jnp.result_type(dtype)  # respects the x64 switch
    lam = jnp.zeros((N, r), dt).at[:, 0].set(1.0)
    A = jnp.zeros((p, r, r), dt).at[0].set(0.5 * jnp.eye(r, dtype=dt))
    return _ssm.SSMParams(lam, jnp.ones((N,), dt), A, jnp.eye(r, dtype=dt))


class _History:
    """Amortized-append panel history.

    The old path re-built the panel with `np.vstack` on every tick — an
    O(T) copy per O(1) update, O(T^2) total bytes moved over a tenant's
    life.  This keeps (capacity, N) buffers, doubles capacity on
    overflow, and exposes zero-copy views of the live prefix; appending
    T rows is O(T) amortized.  `reallocs` counts doublings (bounded by
    log2 of the growth factor), which the perf regression test pins
    instead of flaky wall time."""

    __slots__ = ("_x", "_mask", "n", "reallocs", "_shared")

    def __init__(self, x, mask):
        self.n = int(x.shape[0])
        self._x = np.array(x, float, copy=True)
        self._mask = np.array(mask, bool, copy=True)
        self.reallocs = 0
        self._shared = False

    @classmethod
    def share(cls, other: "_History") -> "_History":
        """Zero-copy clone sharing `other`'s buffers copy-on-append.
        Safe against the source growing: the source writes rows only at
        indices >= this clone's frozen `n`, outside its views; the first
        append on the CLONE copies the prefix into private buffers."""
        h = cls.__new__(cls)
        h._x, h._mask, h.n = other._x, other._mask, other.n
        h.reallocs = 0
        h._shared = True
        return h

    @property
    def x(self) -> np.ndarray:
        return self._x[: self.n]

    @property
    def mask(self) -> np.ndarray:
        return self._mask[: self.n]

    def append(self, x_row, mask_row) -> None:
        if self._shared or self.n == self._x.shape[0]:
            cap = max(2 * self._x.shape[0], 8)
            nx = np.zeros((cap,) + self._x.shape[1:], self._x.dtype)
            nm = np.zeros((cap,) + self._mask.shape[1:], bool)
            nx[: self.n] = self._x[: self.n]
            nm[: self.n] = self._mask[: self.n]
            self._x, self._mask = nx, nm
            self.reallocs += 1
            self._shared = False
        self._x[self.n] = x_row
        self._mask[self.n] = mask_row
        self.n += 1


class _Tenant:
    __slots__ = (
        "hist", "params", "model", "state", "breaker", "replay", "suspect",
    )

    def __init__(self, hist, params, model, state, breaker):
        self.hist = hist        # _History or None (panel-less resume)
        self.params = params
        self.model = model      # ServingModel
        self.state = state      # FilterState (last-good, committed)
        self.breaker = breaker  # CircuitBreaker
        self.replay = []        # [(x_row, mask_row)] failed-tick rows
        self.suspect = False    # force a deep finite check on next tick


class ServingEngine:
    """Single-process, synchronous multi-tenant serving driver."""

    def __init__(
        self,
        store_dir: str | None = None,
        tol: float = 1e-6,
        max_em_iter: int = 200,
        deadline_s: float | None = None,
        retry_policy: RetryPolicy | None = None,
        breaker_threshold: int = 3,
        breaker_cooldown: int = 4,
        max_refit_retries: int = 2,
        slos=None,
    ):
        self.store = TenantStore(store_dir) if store_dir else None
        self.tol = tol
        self.max_em_iter = max_em_iter
        self.deadline_s = deadline_s  # default per-request budget
        self.retry_policy = retry_policy or RetryPolicy()
        self.breaker_threshold = breaker_threshold
        self.breaker_cooldown = breaker_cooldown
        self.max_refit_retries = max_refit_retries
        self.slos = list(slos or [])  # utils.slo.SLO monitors, by kind
        self._tenants: dict[str, _Tenant] = {}
        self._refit_queue: list[str] = []
        self._refit_retries: dict[str, int] = {}
        self._requests = 0  # admission counter (slow_req/engine_crash sites)
        self._ticks = 0     # computed-tick counter (tick_nan site)
        # (kind, outcome) -> LatencyHistogram, held directly so the hot
        # path never takes the registry lock (register_hist once per key)
        self._lat_hists: dict = {}

    # -- registration ----------------------------------------------------

    def register(self, tenant_id: str, x, mask=None, params=None) -> None:
        """Admit a tenant with its history panel.  `x` (T, N) may carry
        NaNs at missing entries when `mask` is omitted; `params` defaults
        to the benign warm start (call refit to actually fit).  Derives
        the ServingModel (one DARE solve) and seeds the filter state from
        one exact pass over the history — ticks are O(1) from here on."""
        x = np.asarray(x, float)
        if mask is None:
            mask = np.isfinite(x)
        mask = np.asarray(mask, bool)
        xz = np.where(mask, x, 0.0)
        if params is None:
            params = default_params(x.shape[1])
        self._install(tenant_id, xz, mask, params)

    def register_shared(self, tenant_id: str, like: str) -> None:
        """Admit `tenant_id` by CLONING tenant `like`'s fit: params,
        ServingModel (the DARE solve), and the history buffer are SHARED
        (history copy-on-append); only the small FilterState is fresh
        per clone.  O(1) per tenant instead of a DARE solve plus a full
        refilter — what makes 1k-100k synthetic tenants registrable in
        seconds for `bench.py --load`.  Ticks/nowcasts/refits/scenarios
        behave exactly as after `register()` with the same panel."""
        src = self._tenants[like]
        state = FilterState(s=src.state.s, t=src.state.t)
        self._persist(tenant_id, src.params, state)
        self._tenants[tenant_id] = _Tenant(
            None if src.hist is None else _History.share(src.hist),
            src.params, src.model, state,
            CircuitBreaker(self.breaker_threshold, self.breaker_cooldown),
        )

    def _install(self, tenant_id, xz, mask, params) -> None:
        """(Re)derive a tenant's serving constants from `params` and its
        exact filter state from a full refilter of the panel; persist
        the snapshot and reset the tick journal, THEN commit in memory —
        a persistence failure (OSError after retries) leaves the
        previous tenant state untouched."""
        model = derive_serving_model(params)
        xnan = np.where(mask, xz, np.nan)
        filt = _ssm.kalman_filter(params, xnan)
        state = FilterState(
            s=jnp.asarray(filt.means[-1]),
            t=jnp.asarray(xz.shape[0], jnp.int32),
        )
        self._persist(tenant_id, params, state)
        prev = self._tenants.get(tenant_id)
        breaker = prev.breaker if prev is not None else CircuitBreaker(
            self.breaker_threshold, self.breaker_cooldown
        )
        self._tenants[tenant_id] = _Tenant(
            _History(xz, mask), params, model, state, breaker
        )

    def _persist(self, tenant_id, params, state) -> int:
        """Snapshot + journal reset, retried on transient I/O faults.
        Returns the retry count consumed (0 without a store)."""
        if self.store is None:
            return 0

        def _save():
            self.store.save(
                tenant_id,
                TenantState(
                    params=params,
                    s=state.s,
                    t=state.t,
                    r=jnp.asarray(params.r, jnp.int32),
                    p=jnp.asarray(params.p, jnp.int32),
                ),
            )
            self.store.journal(tenant_id).reset(int(state.t))

        _, retries = call_with_retries(
            _save, self.retry_policy, key=f"{tenant_id}:install"
        )
        return retries

    def tenant_ids(self) -> list[str]:
        return sorted(self._tenants)

    # -- request routing -------------------------------------------------

    def handle(self, req) -> Response:
        """Route one request dict; ALWAYS returns a typed `Response`.

        Successful requests carry the result (new FilterState for tick,
        the (N,) vector for nowcast, queue position for refit, the
        ScenarioResult for scenario); failures carry an `ErrorInfo`
        classifying the cause.  The only exceptions that escape are the
        injected external kills (SimulatedCrash / SimulatedPreemption)
        and KeyboardInterrupt — everything else is an envelope."""
        self._requests += 1
        reqno = self._requests
        if _faults.site_hits("engine_crash", reqno):
            _faults.fault_fired("engine_crash")
            raise _faults.SimulatedCrash(
                f"injected engine_crash at request {reqno}"
            )
        kind = req.get("kind") if isinstance(req, dict) else None
        tenant_id = req.get("tenant") if isinstance(req, dict) else None
        if not isinstance(tenant_id, str):
            tenant_id = None
        rkind = kind if kind in _REQ_KINDS else "invalid"
        t0 = time.perf_counter()
        # one enabled() probe per request: run_record() already performs
        # it, and returning the null singleton tells us the trace layer
        # is off too — a second probe (~1.6µs of env lookups) would blow
        # a visible hole in the <5% envelope bar
        rec_cm = run_record(
            "serving", kind=rkind, config={"tenant": tenant_id}
        )
        if rec_cm is _NULL_RECORD:
            tr_cm = _NULL_TRACE
        else:
            # deterministic trace identity: the request's own id, else
            # its admission index — identical request streams yield
            # identical span trees (pinned by tests/test_request_obs.py)
            rid = req.get("request_id") if isinstance(req, dict) else None
            tr_cm = trace_span_on(
                "serving.request", seed=rid or f"{tenant_id}:{reqno}",
                kind=rkind, tenant=tenant_id,
            )
        with tr_cm, rec_cm as rec:
            try:
                resp = self._dispatch(req, kind, tenant_id, reqno)
            except (
                _faults.SimulatedCrash,
                _faults.SimulatedPreemption,
                KeyboardInterrupt,
            ):
                raise
            except Exception as e:  # last resort: nothing else escapes
                inc("serving.internal_absorbed")
                resp = Response(
                    ok=False, kind=rkind, tenant=tenant_id,
                    error=ErrorInfo(
                        SYSTEM_FAULT, "internal",
                        f"{type(e).__name__}: {e}",
                    ),
                )
            outcome = (
                ("degraded" if resp.degraded else "ok")
                if resp.ok else resp.error.category
            )
            latency_s = time.perf_counter() - t0
            if rec is not _NULL_RECORD:
                rec.set(
                    outcome=outcome,
                    error_kind=(
                        None if resp.error is None else resp.error.code
                    ),
                    retries=resp.retries,
                    breaker_state=resp.breaker_state,
                    latency_s=round(latency_s, 9),
                )
        self._observe(rkind, outcome, latency_s, resp.ok)
        if (reqno & 1023) == 0 and rec is not _NULL_RECORD:
            self.flush_metrics()
        return resp

    def _observe(self, kind, outcome, latency_s, ok) -> None:
        """O(1) host-side per-request accounting: one histogram bucket
        increment per (kind, outcome) plus the SLO window counters for
        monitors matching this kind.  Never touches a device."""
        try:
            h = self._lat_hists[(kind, outcome)]
        except KeyError:
            h = register_hist(
                "serving.request.latency",
                entry="serving", kind=kind, outcome=outcome,
            )
            self._lat_hists[(kind, outcome)] = h
        h.record(latency_s)
        if self.slos:
            for slo in self.slos:
                if slo.kind == kind:
                    slo.observe(latency_s, ok)

    def flush_metrics(self) -> int:
        """Push SLO burn-rate gauges into the telemetry registry and
        snapshot the latency histograms into the JSONL sink (when one is
        active).  Called every 1024th request automatically; call
        explicitly at the end of a run to flush the tail."""
        for slo in self.slos:
            for name, val in slo.gauges().items():
                gauge_set(name, val)
        return emit_histograms()

    def _dispatch(self, req, kind, tenant_id, reqno) -> Response:
        if not isinstance(req, dict):
            return Response(
                ok=False, kind="invalid", tenant=None,
                error=ErrorInfo(
                    CLIENT_ERROR, "bad_request",
                    f"request must be a dict, got {type(req).__name__}",
                ),
            )
        if kind is None:
            return self._client_err(
                "invalid", tenant_id, "missing_field",
                "request is missing 'kind'", field="kind",
            )
        if kind not in _REQ_KINDS:
            return self._client_err(
                "invalid", tenant_id, "unknown_kind",
                f"unknown request kind {kind!r} "
                f"(valid: {', '.join(_REQ_KINDS)})", field="kind",
            )
        if tenant_id is None:
            return self._client_err(
                kind, None, "missing_field",
                "request is missing 'tenant'", field="tenant",
            )
        if tenant_id not in self._tenants:
            return self._client_err(
                kind, tenant_id, "unknown_tenant",
                f"unknown tenant {tenant_id!r}", field="tenant",
            )
        ten = self._tenants[tenant_id]
        deadline = Deadline(req.get("deadline_s", self.deadline_s))
        if _faults.site_hits("slow_req", reqno):
            _faults.fault_fired("slow_req")
            deadline.expire()
        bstate = ten.breaker.on_request()
        if kind == "tick":
            return self._tick(tenant_id, ten, req, deadline, bstate)
        if deadline.exceeded():  # nothing to buffer for read-only kinds
            return self._fault_resp(
                kind, tenant_id, ten,
                ErrorInfo(
                    SYSTEM_FAULT, "deadline_exceeded",
                    f"deadline of {deadline.budget_s}s exceeded",
                ),
            )
        if kind == "nowcast":
            return self._nowcast(tenant_id, ten, req)
        if kind == "refit":
            pos = self._queue_refit(tenant_id)
            return Response(
                ok=True, kind="refit", tenant=tenant_id, result=pos,
                breaker_state=ten.breaker.state,
            )
        return self._scenario(tenant_id, ten, req)

    # -- envelope helpers ------------------------------------------------

    def _client_err(self, kind, tenant_id, code, msg, field) -> Response:
        ten = self._tenants.get(tenant_id) if tenant_id else None
        inc("serving.client_errors")
        return Response(
            ok=False, kind=kind, tenant=tenant_id,
            error=ErrorInfo(CLIENT_ERROR, code, msg, field),
            degraded=bool(ten.replay) if ten else False,
            ticks_behind=len(ten.replay) if ten else 0,
            breaker_state=ten.breaker.state if ten else "closed",
        )

    def _fault_resp(
        self, kind, tenant_id, ten, err, retries=0,
        count_fault=True, recovered=False,
    ) -> Response:
        """A tenant/system fault envelope: stamps the degradation state
        and (unless `count_fault=False`, e.g. a fast-fail against an
        already-open breaker) counts one fault toward the breaker."""
        if count_fault:
            ten.breaker.record_fault()
        inc("serving.faults." + err.code)
        return Response(
            ok=False, kind=kind, tenant=tenant_id, error=err,
            degraded=bool(ten.replay), ticks_behind=len(ten.replay),
            retries=retries, breaker_state=ten.breaker.state,
            recovered=recovered,
        )

    # -- tick ------------------------------------------------------------

    def _tick(self, tenant_id, ten, req, deadline, bstate) -> Response:
        # validation: name the offending field, never a raw KeyError
        if "x" not in req:
            return self._client_err(
                "tick", tenant_id, "missing_field",
                "tick request is missing 'x'", field="x",
            )
        try:
            x_t = np.asarray(req["x"], float)
        except (TypeError, ValueError):
            return self._client_err(
                "tick", tenant_id, "bad_value",
                "'x' is not convertible to a float array", field="x",
            )
        N = ten.model.Wb.shape[0]
        if x_t.shape != (N,):
            return self._client_err(
                "tick", tenant_id, "bad_shape",
                f"'x' must have shape ({N},), got {x_t.shape}", field="x",
            )
        if req.get("mask") is None:
            mask_t = np.isfinite(x_t)
        else:
            try:
                mask_t = np.asarray(req["mask"], bool)
            except (TypeError, ValueError):
                return self._client_err(
                    "tick", tenant_id, "bad_value",
                    "'mask' is not convertible to a bool array",
                    field="mask",
                )
            if mask_t.shape != (N,):
                return self._client_err(
                    "tick", tenant_id, "bad_shape",
                    f"'mask' must have shape ({N},), got {mask_t.shape}",
                    field="mask",
                )
        row = (np.where(mask_t, x_t, 0.0), mask_t)

        if bstate == BREAKER_OPEN:
            ten.replay.append(row)
            return self._fault_resp(
                "tick", tenant_id, ten,
                ErrorInfo(
                    TENANT_FAULT, "breaker_open",
                    "circuit breaker open; tick buffered for replay",
                ),
                count_fault=False,
            )

        # recovery: reconcile any buffered rows before applying this one
        recovered = False
        if ten.replay:
            try:
                with trace_span("serving.reconcile", n_rows=len(ten.replay)):
                    self._reconcile(tenant_id, ten)
                ten = self._tenants[tenant_id]  # reconcile reinstalls
                recovered = True
            except OSError as e:
                ten.replay.append(row)
                return self._fault_resp(
                    "tick", tenant_id, ten,
                    ErrorInfo(
                        SYSTEM_FAULT, "store_io",
                        f"reconcile persistence failed: {e}",
                    ),
                )

        if deadline.exceeded():
            ten.replay.append(row)
            return self._fault_resp(
                "tick", tenant_id, ten,
                ErrorInfo(
                    SYSTEM_FAULT, "deadline_exceeded",
                    f"deadline of {deadline.budget_s}s exceeded",
                ),
                recovered=recovered,
            )

        self._ticks += 1
        new_state = online_tick(ten.model, ten.state, row[0], row[1])
        if _faults.site_hits("tick_nan", self._ticks):
            _faults.fault_fired("tick_nan")
            new_state = FilterState(s=new_state.s * np.nan, t=new_state.t)
        # The deep check materializes the state on host — a forced device
        # sync that breaks dispatch pipelining, ~the whole envelope
        # budget on its own.  The committed state is provably finite when
        # the previous state and this row were (the update is linear in
        # both with finite install-time constants), so the clean fast
        # path samples the sync every 8th tick and goes deep only when a
        # cheap host signal says it must: an observed non-finite input,
        # an active fault plan (injection bypasses the invariant by
        # poisoning the output directly), a panel-less tenant (its
        # reconcile path cannot refilter from scratch), or a suspect
        # flag raised by a non-finite materialized nowcast.
        deep = (
            ten.suspect
            or ten.hist is None
            or not np.isfinite(row[0]).all()
            or _faults.active_plan().any()
            or (self._ticks & 7) == 0
        )
        if deep and not host_finite(new_state.s):
            ten.replay.append(row)
            return self._fault_resp(
                "tick", tenant_id, ten,
                ErrorInfo(
                    TENANT_FAULT, "nonfinite_state",
                    "tick produced a non-finite filter state; "
                    "row buffered for replay",
                ),
                recovered=recovered,
            )
        if deadline.exceeded():  # final probe before the commit point
            ten.replay.append(row)
            return self._fault_resp(
                "tick", tenant_id, ten,
                ErrorInfo(
                    SYSTEM_FAULT, "deadline_exceeded",
                    f"deadline of {deadline.budget_s}s exceeded",
                ),
                recovered=recovered,
            )

        # write-ahead: the journal append is the commit point
        retries = 0
        if self.store is not None:
            journal = self.store.journal(tenant_id)
            t_idx = int(ten.state.t)
            try:
                with trace_span("tick.journal_append", t=t_idx):
                    _, retries = call_with_retries(
                        lambda: journal.append(t_idx, row[0], row[1]),
                        self.retry_policy,
                        key=f"{tenant_id}:tick:{t_idx}",
                        deadline=deadline,
                    )
            except OSError as e:
                ten.replay.append(row)
                return self._fault_resp(
                    "tick", tenant_id, ten,
                    ErrorInfo(
                        SYSTEM_FAULT, "store_io",
                        f"tick journal append failed: {e}",
                    ),
                    retries=self.retry_policy.max_retries,
                    recovered=recovered,
                )

        ten.state = new_state
        if deep:
            ten.suspect = False  # committed state re-verified on host
        if ten.hist is not None:
            ten.hist.append(row[0], row[1])
        ten.breaker.record_success()
        return Response(
            ok=True, kind="tick", tenant=tenant_id, result=new_state,
            retries=retries, breaker_state=ten.breaker.state,
            recovered=recovered,
        )

    def _reconcile(self, tenant_id, ten) -> None:
        """Fold the replay buffer back into committed state.

        Panel tenants get ONE exact refilter over history + buffered
        rows (`_install`), the recovery the chaos tests pin ≤ 1e-10
        against the never-faulted run; panel-less resumed tenants
        replay the buffered rows through the same tick executable.
        Raises OSError when persistence keeps failing — the caller
        leaves the buffer intact and reports a system fault."""
        rows, ten.replay = ten.replay, []
        try:
            if ten.hist is not None:
                xs = np.vstack([ten.hist.x] + [r[0][None] for r in rows])
                ms = np.vstack([ten.hist.mask] + [r[1][None] for r in rows])
                self._install(tenant_id, xs, ms, ten.params)
            else:
                state = ten.state
                for x_row, m_row in rows:
                    if self.store is not None:
                        journal = self.store.journal(tenant_id)
                        t_idx = int(state.t)
                        call_with_retries(
                            lambda: journal.append(t_idx, x_row, m_row),
                            self.retry_policy,
                            key=f"{tenant_id}:reconcile:{t_idx}",
                        )
                    state = online_tick(ten.model, state, x_row, m_row)
                ten.state = state
        except OSError:
            ten.replay = rows + ten.replay  # keep the rows for next try
            raise
        inc("serving.reconciles")

    # -- nowcast / refit / scenario --------------------------------------

    def _nowcast(self, tenant_id, ten, req) -> Response:
        try:
            horizon = int(req.get("horizon", 0))
        except (TypeError, ValueError):
            return self._client_err(
                "nowcast", tenant_id, "bad_value",
                "'horizon' must be a non-negative integer", field="horizon",
            )
        if horizon < 0:
            return self._client_err(
                "nowcast", tenant_id, "bad_value",
                f"'horizon' must be >= 0, got {horizon}", field="horizon",
            )
        # degraded mode: last-good state still answers, with an explicit
        # staleness stamp, while the tenant's ticks are buffered
        vec = np.asarray(nowcast(ten.model, ten.state, horizon))
        # the result just materialized on host, so this check is free —
        # it is the backstop for the sampled deep check in _tick: a
        # non-finite state can never reach a caller unflagged
        if not np.isfinite(vec).all():
            ten.suspect = True
            return self._fault_resp(
                "nowcast", tenant_id, ten,
                ErrorInfo(
                    TENANT_FAULT, "nonfinite_state",
                    "nowcast drew on a non-finite filter state; "
                    "tenant flagged for deep check",
                ),
            )
        return Response(
            ok=True, kind="nowcast", tenant=tenant_id, result=vec,
            degraded=bool(ten.replay), ticks_behind=len(ten.replay),
            breaker_state=ten.breaker.state,
        )

    def _scenario(self, tenant_id, ten, req) -> Response:
        from ..scenarios import ScenarioRequest, run_scenario

        spec = req.get("scenario")
        if spec is None:
            return self._client_err(
                "scenario", tenant_id, "missing_field",
                "scenario request is missing 'scenario'", field="scenario",
            )
        if not isinstance(spec, dict):
            return self._client_err(
                "scenario", tenant_id, "bad_value",
                f"'scenario' must be a dict, got {type(spec).__name__}",
                field="scenario",
            )
        if ten.hist is None:
            return self._fault_resp(
                "scenario", tenant_id, ten,
                ErrorInfo(
                    TENANT_FAULT, "no_history",
                    "tenant was resumed without a panel; re-register "
                    "with history to run scenarios",
                ),
                count_fault=False,
            )
        try:
            sreq = ScenarioRequest(**spec)
        except TypeError as e:
            m = _re.search(r"'(\w+)'", str(e))
            field = f"scenario.{m.group(1)}" if m else "scenario"
            return self._client_err(
                "scenario", tenant_id, "unknown_scenario_field",
                str(e), field=field,
            )
        x = np.where(ten.hist.mask, ten.hist.x, np.nan)
        try:
            result = run_scenario(ten.params, x, sreq)
        except ValueError as e:  # unknown scenario kind / bad spec values
            return self._client_err(
                "scenario", tenant_id, "bad_scenario",
                str(e), field="scenario",
            )
        return Response(
            ok=True, kind="scenario", tenant=tenant_id, result=result,
            degraded=bool(ten.replay), ticks_behind=len(ten.replay),
            breaker_state=ten.breaker.state,
        )

    def _queue_refit(self, tenant_id: str) -> int:
        if tenant_id not in self._refit_queue:
            self._refit_queue.append(tenant_id)
        return self._refit_queue.index(tenant_id)

    # -- batched refits --------------------------------------------------

    def flush_refits(self) -> Response:
        """Execute the refit queue, batched per (T, N) compile bucket.

        Healthy tenants get new params + re-derived serving constants +
        an exact refiltered state; a tenant whose loop tripped keeps its
        previous fit and is RE-QUEUED, up to `max_refit_retries` flushes,
        after which it is surfaced as a permanent failure (and counted in
        telemetry) instead of silently dropped.  Returns a Response whose
        `result` maps tenant_id -> RefitResult and whose `info` carries
        ``installed`` / ``requeued`` / ``permanent_failures``."""
        queue, self._refit_queue = self._refit_queue, []
        if not queue:
            return Response(
                ok=True, kind="refit_flush", tenant=None, result={},
                info={"installed": 0, "requeued": [],
                      "permanent_failures": []},
            )
        reqs = []
        for tid in queue:
            ten = self._tenants[tid]
            if ten.hist is None:  # panel-less: nothing to refit against
                self._refit_retries.pop(tid, None)
                continue
            reqs.append(RefitRequest(
                tenant_id=tid,
                x=jnp.asarray(ten.hist.x),
                mask=jnp.asarray(ten.hist.mask),
                params=ten.params,
            ))
        with run_record(
            "serving", kind="refit_flush", config={"n_tenants": len(reqs)},
        ) as rec:
            results = refit_batch(
                reqs, tol=self.tol, max_em_iter=self.max_em_iter,
                isolate_errors=True,
            )
            installed, requeued, permanent = 0, [], []
            for res in results:
                ten = self._tenants[res.tenant_id]
                ok = res.health == 0
                if ok:
                    try:
                        self._install(
                            res.tenant_id, ten.hist.x, ten.hist.mask,
                            res.params,
                        )
                    except OSError:
                        ok = False  # persistence failed: retry the refit
                if ok:
                    installed += 1
                    self._refit_retries.pop(res.tenant_id, None)
                    continue
                n = self._refit_retries.get(res.tenant_id, 0) + 1
                self._refit_retries[res.tenant_id] = n
                if n <= self.max_refit_retries:
                    requeued.append(res.tenant_id)
                    if res.tenant_id not in self._refit_queue:
                        self._refit_queue.append(res.tenant_id)
                else:
                    permanent.append(res.tenant_id)
                    self._refit_retries.pop(res.tenant_id, None)
                    inc("serving.refit.permanent_failures")
            rec.set(
                n_installed=installed,
                outcome="ok" if not permanent else "tenant_fault",
                error_kind=None if not permanent else "refit_permanent",
                retries=max(
                    (self._refit_retries.get(t, 0) for t in requeued),
                    default=0,
                ),
                breaker_state="closed",
            )
        return Response(
            ok=True, kind="refit_flush", tenant=None,
            result={res.tenant_id: res for res in results},
            info={"installed": installed, "requeued": requeued,
                  "permanent_failures": permanent},
        )

    # -- persistence -----------------------------------------------------

    def resume(self, tenant_id: str, x=None, mask=None) -> bool:
        """Re-admit a tenant from the store.  Returns False when the
        store has no intact state for the id (never saved, or its
        archive was quarantined as corrupt) — register() it afresh.

        With a panel `x` supplied, the snapshot's params are re-derived
        against the caller's history (the classic path).  WITHOUT a
        panel — the crash-restart path — the snapshot's FilterState is
        restored and the write-ahead tick journal replayed through the
        same tick executable, landing bit-identically on the killed
        process's committed state; the tenant then serves ticks and
        nowcasts normally but answers `no_history` to refit/scenario
        until re-registered with history."""
        if self.store is None:
            return False
        # the template is structure-only (leaf shapes come from the
        # archive), so one (1, 1, 1) template loads any (N, r, p) tenant
        stored = self.store.load(tenant_id, template_state(1, 1, 1))
        if stored is None:
            return False
        params = stored.params
        r, p = int(stored.r), int(stored.p)
        if params.lam.shape[1] != r or params.A.shape[0] != p:
            inc("serving.store.inconsistent")
            return False
        if x is not None:
            x = np.asarray(x, float)
            if mask is None:
                mask = np.isfinite(x)
            mask = np.asarray(mask, bool)
            self._install(tenant_id, np.where(mask, x, 0.0), mask, params)
            return True
        model = derive_serving_model(params)
        state = FilterState(
            s=jnp.asarray(stored.s), t=jnp.asarray(stored.t, jnp.int32)
        )
        rep = self.store.journal(tenant_id).replay()
        if rep is not None:
            base_t, rows = rep
            if base_t == int(stored.t) and rows:
                state = replay_ticks(model, state, rows)
            # a journal anchored at a different t predates this snapshot
            # (crash between save and journal reset): already folded in
        self._tenants[tenant_id] = _Tenant(
            None, params, model, state,
            CircuitBreaker(self.breaker_threshold, self.breaker_cooldown),
        )
        return True


# -- CLI demo ------------------------------------------------------------


def _synthetic_panel(rng, T: int, N: int, r: int = 4):
    f = rng.standard_normal((T, r)).cumsum(0) * 0.1
    lam = rng.standard_normal((N, r))
    x = f @ lam.T + 0.5 * rng.standard_normal((T, N))
    return x


def main(argv=None) -> int:
    """Demo loop: register a few synthetic tenants, stream ticks, serve
    nowcasts, run one batched refit flush; prints one JSON line per
    phase.  ``python -m dynamic_factor_models_tpu.serve``."""
    ap = argparse.ArgumentParser(
        prog="python -m dynamic_factor_models_tpu.serve",
        description="multi-tenant nowcast serving demo",
    )
    ap.add_argument("--tenants", type=int, default=3)
    ap.add_argument("--T", type=int, default=96)
    ap.add_argument("--N", type=int, default=8)
    ap.add_argument("--ticks", type=int, default=12)
    ap.add_argument("--store-dir", default=None)
    ap.add_argument("--max-em-iter", type=int, default=30)
    args = ap.parse_args(argv)

    rng = np.random.default_rng(0)
    eng = ServingEngine(store_dir=args.store_dir, max_em_iter=args.max_em_iter)
    for i in range(args.tenants):
        eng.register(f"tenant{i}", _synthetic_panel(rng, args.T, args.N))
    print(json.dumps({
        "phase": "register", "tenants": eng.tenant_ids(),
        "bucket": list(bucket_shape(args.T, args.N)),
    }))

    for _ in range(args.ticks):
        for tid in eng.tenant_ids():
            row = rng.standard_normal(args.N)
            eng.handle({"kind": "tick", "tenant": tid, "x": row})
    resp = eng.handle({"kind": "nowcast", "tenant": "tenant0", "horizon": 0})
    print(json.dumps({
        "phase": "ticks", "n_ticks": args.ticks * args.tenants,
        "degraded": resp.degraded,
        "nowcast0_head": [
            round(float(v), 4) for v in np.asarray(resp.result)[:4]
        ],
    }))

    for tid in eng.tenant_ids():
        eng.handle({"kind": "refit", "tenant": tid})
    flush = eng.flush_refits()
    print(json.dumps({
        "phase": "refit",
        "results": {
            tid: {
                "n_iter": r.n_iter,
                "converged": r.converged,
                "health": r.health,
            }
            for tid, r in sorted(flush.result.items())
        },
        "permanent_failures": flush.info["permanent_failures"],
    }))

    sc = eng.handle({
        "kind": "scenario", "tenant": "tenant0",
        "scenario": {
            "kind": "stress", "horizon": 6,
            "shocks": np.eye(4)[:2].tolist(),
        },
    })
    print(json.dumps({
        "phase": "scenario", "scenario": "stress",
        "fan_shape": list(np.asarray(sc.result.mean).shape),
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
