"""Pipelined serving runtime: double-buffered rounds behind an async
admission front.

`flush_period()` runs a round's four stages strictly in sequence —
admit → dispatch → journal → commit — so the host sits idle during the
device dispatch and the device sits idle during the journal fsyncs;
that serialization is why batched admission beat sequential serving by
only 1.353x (docs/BENCH_load.json) and why ROADMAP item 1 calls for
overlap.  This module overlaps them with an EXPLICIT stage-handoff
structure instead of ad-hoc threading:

* **Rounds** are first-class (`_Round`): each owns its entries, lanes,
  staged states, responses, and a back-half step list.  The stage
  functions are the engine's own `_admit_lanes` / `_dispatch_lanes` /
  `_journal_lanes` / `_commit_lanes` — the exact code path
  `flush_period()` runs, so sequential and pipelined rounds cannot
  drift.
* **Two-slot ring**: at most `slots` (default 2) rounds are in flight.
  `pump()` forms round k+1 and runs its FRONT half (admit + dispatch)
  on the caller thread while round k's BACK half (journal fsync +
  commit) runs on the backstage; when the ring is full, the caller
  blocks on the oldest round — bounded buffering, not an unbounded
  task soup.
* **Commit ordering**: the backstage executes back halves strictly
  FIFO by round index (a single worker, a single queue), so round k's
  commit always precedes round k+1's — the acked⇔durable-per-round
  invariant needs no cross-round reasoning.
* **Per-tenant exclusion**: round formation skips any tenant already
  in flight (its queued ticks wait for the next round), so a tenant's
  lane never dispatches from a speculative state and the crash
  analysis stays per-round: a tenant has AT MOST ONE un-acked
  journaled tick at any kill point (`acked ≤ recovered ≤ acked+1` per
  tenant, tests/test_pipeline.py).  In-flight tenants are also pinned
  against budget eviction via the engine's `_admission_pin`.

The **admission front** is a bounded queue with typed shedding: a full
queue answers `queue_full` (system fault, flight-recorded) instead of
buffering unboundedly, and entries whose deadline expired while queued
are shed at round formation without ever dispatching.  Queue depth and
shed counters ride the telemetry registry
(``serving.admission.depth`` / ``serving.admission.shed.*``), and each
stage feeds the PR 17 occupancy split — including the new ``admit``
phase — so `bench.py --load` can show the before/after overlap.

Backstages (the threading doctrine, docs/ARCHITECTURE.md):

* ``thread`` — one daemon worker owns every journal fsync and memory
  commit; real overlap.  Exceptions (including the injected
  SimulatedCrash kills) are captured per round and re-raised on the
  caller thread at the next pump/drain — the pipeline is dead after.
* ``serial`` — back halves run inline on the caller thread at
  hand-off: identical stage structure and ordering, zero concurrency;
  what the crash drills use so kills surface synchronously.
* ``manual`` — back halves advance only via `step_back()`, one stage
  at a time; with `interleavings()` this makes every legal stage
  ordering ENUMERABLE instead of timing-dependent, which is how the
  kill-at-every-stage-boundary matrix is driven.

Results come back via `poll()` / `drain()` in SUBMISSION order (a
shed request still yields exactly one typed Response), mirroring
`flush_period()`'s one-response-per-entry contract.
"""

from __future__ import annotations

import collections
import queue as _queue_mod
import threading
import time

from ..utils import faults as _faults
from ..utils import flight as _flight
from ..utils.telemetry import _NULL_RECORD, gauge_set, inc, run_record
from .resilience import SYSTEM_FAULT, Deadline, ErrorInfo, Response

__all__ = ["ServingPipeline", "interleavings", "BACK_STAGES"]

BACK_STAGES = ("journal", "commit")
_BACKSTAGES = ("thread", "serial", "manual")


class _Round:
    """One in-flight round: entries, staged artifacts, and back-half
    progress.  Stage data flows admit→lanes→staged→commits→responses;
    `done` flips once the commit stage (or a captured exception) ends
    the round's life on the backstage."""

    __slots__ = (
        "idx", "entries", "seqs", "tenants", "responses", "lanes",
        "staged", "commits", "obs", "t_form", "stage_wall", "back_steps",
        "done", "exc",
    )

    def __init__(self, idx, entries, seqs, tenants, obs):
        self.idx = idx
        self.entries = entries      # [(req, Deadline, t_submit)]
        self.seqs = seqs            # submission seq per entry
        self.tenants = tenants      # frozenset of tenant ids in-round
        self.responses = [None] * len(entries)
        self.lanes = []
        self.staged = None
        self.commits = None
        self.obs = obs
        self.t_form = time.perf_counter()
        self.stage_wall = 0.0       # attributed stage seconds (envelope)
        self.back_steps = collections.deque(BACK_STAGES)
        self.done = threading.Event()
        self.exc = None


class ServingPipeline:
    """Double-buffered round pipeline over one `ServingEngine`.

    ``submit()`` admits tick requests into the bounded queue (typed
    sheds, never an exception); ``pump()`` forms and advances one
    round; ``drain()`` runs the pipeline dry and returns every
    releasable Response in submission order; ``close()`` stops the
    backstage worker.  Attaching a pipeline moves the engine's
    every-1024-requests metrics flush onto the commit stage."""

    def __init__(
        self,
        engine,
        max_queue: int = 4096,
        slots: int = 2,
        max_round_lanes: int = 1024,
        backstage: str = "thread",
        boundary_hook=None,
    ):
        if backstage not in _BACKSTAGES:
            raise ValueError(
                f"backstage must be one of {_BACKSTAGES}, got {backstage!r}"
            )
        if slots < 1:
            raise ValueError("slots must be >= 1")
        if max_queue < 1:
            raise ValueError("max_queue must be >= 1")
        self.engine = engine
        self.max_queue = int(max_queue)
        self.slots = int(slots)
        self.max_round_lanes = int(max_round_lanes)
        self.backstage = backstage
        # test hook: called as hook(stage, round) AFTER each completed
        # stage — the kill-matrix injects SimulatedCrash here to model
        # a death at every stage boundary
        self.boundary_hook = boundary_hook
        self._queue: collections.deque = collections.deque()
        self._inflight: collections.deque = collections.deque()
        self._completed: dict = {}   # seq -> Response
        self._next_seq = 0
        self._next_out = 0
        self._submits = 0            # queue_full fault-site counter
        self._rounds_formed = 0
        self._shed_queue_full = 0
        self._shed_deadline = 0
        self._max_inflight = 0       # high-water mark (ring-bound pin)
        self._fatal = None
        self._closed = False
        self._work_q = None
        self._worker = None
        engine._pipeline = self
        if backstage == "thread":
            self._work_q = _queue_mod.SimpleQueue()
            self._worker = threading.Thread(
                target=self._worker_main,
                name="dfm-pipeline-backstage",
                daemon=True,
            )
            self._worker.start()

    # -- admission front -------------------------------------------------

    def submit(self, req) -> int:
        """Admit one request into the bounded queue; returns its
        submission sequence number.  Shares the engine's admission
        fault sites (``engine_crash`` / ``slow_req`` fire against the
        same request counter as `handle()`/`submit()`); a full queue —
        or an injected ``queue_full@n`` — sheds the request with a
        typed system fault delivered through `poll()` like any other
        response, so callers always get one Response per submission."""
        self._reraise()
        eng = self.engine
        seq = self._next_seq
        self._next_seq += 1
        self._submits += 1
        eng._requests += 1
        reqno = eng._requests
        if _faults.site_hits("engine_crash", reqno):
            _faults.fault_fired("engine_crash")
            _flight.dump("engine_crash", force=True, reqno=reqno)
            raise _faults.SimulatedCrash(
                f"injected engine_crash at request {reqno}"
            )
        if (reqno & 1023) == 0:
            # deferred onto the commit stage (engine._commit_lanes):
            # the admission front never blocks on telemetry I/O
            eng._metrics_due = True
        budget = (
            req.get("deadline_s", eng.deadline_s)
            if isinstance(req, dict) else eng.deadline_s
        )
        deadline = Deadline(budget)
        if _faults.site_hits("slow_req", reqno):
            _faults.fault_fired("slow_req")
            deadline.expire()
        tid = req.get("tenant") if isinstance(req, dict) else None
        if not isinstance(tid, str):
            tid = None
        forced = _faults.site_hits("queue_full", self._submits)
        if forced or len(self._queue) >= self.max_queue:
            if forced:
                _faults.fault_fired("queue_full")
            self._shed_queue_full += 1
            inc("serving.admission.shed.queue_full")
            _flight.record(
                "serving.queue_full", tenant=tid, depth=len(self._queue),
            )
            _flight.dump("queue_full", depth=len(self._queue))
            resp = Response(
                ok=False, kind="tick", tenant=tid,
                error=ErrorInfo(
                    SYSTEM_FAULT, "queue_full",
                    f"admission queue at capacity ({self.max_queue}); "
                    "request shed",
                ),
            )
            eng._observe("tick", SYSTEM_FAULT, 0.0, False)
            self._completed[seq] = resp
            return seq
        inc("serving.admission.submitted")
        self._queue.append((seq, req, deadline, time.perf_counter()))
        return seq

    def depth(self) -> int:
        """Current admission-queue depth (excludes in-flight rounds)."""
        return len(self._queue)

    # -- the pipeline ----------------------------------------------------

    def pump(self) -> int:
        """Advance the pipeline one step: retire finished rounds, then
        form at most one new round from the queue and run its front
        half (admit + dispatch) on this thread, handing the back half
        (journal + commit) to the backstage.  Returns the number of
        lanes admitted into the new round (0 = nothing formed)."""
        self._reraise()
        if self.backstage == "manual":
            self._collect_finished()
            if len(self._inflight) >= self.slots:
                raise RuntimeError(
                    "pipeline ring full: run step_back() before pump()"
                )
        else:
            while len(self._inflight) >= self.slots:
                self._retire_oldest(block=True)
            self._collect_finished()
        entries, seqs, tenants = self._form_round()
        if not entries:
            # nothing admissible now: let the backstage make progress
            # so excluded tenants free up (thread/serial only — manual
            # stepping stays under the test scheduler's control)
            if self._inflight and self.backstage != "manual":
                self._retire_oldest(block=True)
            return 0
        eng = self.engine
        idx = self._rounds_formed
        self._rounds_formed += 1
        with run_record(
            "serving", kind="tick_round",
            config={"n_lanes": len(entries), "round": idx},
            **eng._rec_extra,
        ) as rec:
            obs = rec is not _NULL_RECORD
            eng._obs_live = obs
            rnd = _Round(idx, entries, seqs, frozenset(tenants), obs)
            # pin BEFORE admit: faulting in lane k must not evict a
            # tenant of any in-flight round (or this round's lane j)
            eng._admission_pin = eng._admission_pin | rnd.tenants
            t0 = time.perf_counter()
            try:
                eng._admit_lanes(
                    entries, list(range(len(entries))),
                    rnd.responses, rnd.lanes, obs=obs,
                )
                self._hook("admit", rnd)
                rnd.staged = eng._dispatch_lanes(rnd.lanes, obs=obs)
                self._hook("dispatch", rnd)
            finally:
                rnd.stage_wall += time.perf_counter() - t0
            if obs:
                gauge_set("serving.admission.depth", len(self._queue))
                rec.set(
                    outcome="ok", n_lanes=len(entries),
                    n_ok=sum(1 for r in rnd.responses if r is None),
                    breaker_state="closed",
                )
        self._inflight.append(rnd)
        self._max_inflight = max(self._max_inflight, len(self._inflight))
        if self.backstage == "thread":
            self._work_q.put(rnd)
        elif self.backstage == "serial":
            self._run_back(rnd)
            self._retire_oldest(block=True)  # re-raises a captured kill
        # manual: back_steps pending, advanced by step_back()
        return len(rnd.lanes)

    def _form_round(self):
        """Pop the next round's entries off the queue: FIFO, at most
        one lane per tenant not already in flight (skipped entries keep
        their place at the head), deadline-shedding entries whose
        budget burned down while queued."""
        entries, seqs, tenants, skipped = [], [], set(), []
        busy = set()
        for rnd in self._inflight:
            busy |= rnd.tenants
        eng = self.engine
        while self._queue and len(entries) < self.max_round_lanes:
            seq, req, deadline, t_sub = self._queue.popleft()
            tid = req.get("tenant") if isinstance(req, dict) else None
            if not isinstance(tid, str):
                tid = None
            if tid is not None and (tid in busy or tid in tenants):
                skipped.append((seq, req, deadline, t_sub))
                continue
            if deadline.exceeded():
                self._shed_deadline += 1
                inc("serving.admission.shed.deadline")
                ten = eng._tenants.get(tid) if tid is not None else None
                resp = Response(
                    ok=False, kind="tick", tenant=tid,
                    error=ErrorInfo(
                        SYSTEM_FAULT, "deadline_exceeded",
                        f"deadline of {deadline.budget_s}s exceeded in "
                        "the admission queue",
                    ),
                    degraded=bool(ten.replay) if ten else False,
                    ticks_behind=len(ten.replay) if ten else 0,
                    breaker_state=ten.breaker.state if ten else "closed",
                )
                eng._observe(
                    "tick", SYSTEM_FAULT,
                    time.perf_counter() - t_sub, False,
                )
                self._completed[seq] = resp
                continue
            if tid is not None:
                tenants.add(tid)
            entries.append((req, deadline, t_sub))
            seqs.append(seq)
        # skipped entries go back to the HEAD, order preserved
        self._queue.extendleft(reversed(skipped))
        return entries, seqs, tenants

    # -- back half -------------------------------------------------------

    def _stage_back(self, rnd, stage) -> None:
        eng = self.engine
        t0 = time.perf_counter()
        try:
            if stage == "journal":
                rnd.commits = eng._journal_lanes(
                    rnd.staged, rnd.responses, obs=rnd.obs,
                )
            elif stage == "commit":
                eng._commit_lanes(rnd.commits, rnd.responses, obs=rnd.obs)
            else:  # pragma: no cover - internal invariant
                raise AssertionError(f"unknown back stage {stage!r}")
            self._hook(stage, rnd)
        finally:
            rnd.stage_wall += time.perf_counter() - t0

    def _run_back(self, rnd) -> None:
        """Run the round's remaining back stages in order, capturing
        any exception (including injected kills) on the round."""
        try:
            while rnd.back_steps:
                self._stage_back(rnd, rnd.back_steps.popleft())
        except BaseException as e:
            rnd.exc = e
        finally:
            rnd.done.set()

    def _worker_main(self) -> None:
        while True:
            rnd = self._work_q.get()
            if rnd is None:
                return
            self._run_back(rnd)

    def _step_round(self, rnd) -> str:
        """Advance one round by exactly one back stage.  Sets `done`
        when the last stage completes (WITHOUT retiring the round — the
        caller owns the `_inflight` deque) and on failure records the
        exception on the round before re-raising."""
        stage = rnd.back_steps.popleft()
        try:
            self._stage_back(rnd, stage)
        except BaseException as e:
            rnd.exc = e
            rnd.done.set()
            raise
        if not rnd.back_steps:
            rnd.done.set()
        return stage

    def step_back(self):
        """Manual backstage only: run the OLDEST in-flight round's next
        back stage (strict FIFO — the single-writer commit ordering).
        Returns ``(round_idx, stage)``; raises the stage's exception
        synchronously.  A fully stepped round retires immediately, so
        its responses become pollable."""
        if self.backstage != "manual":
            raise RuntimeError("step_back() requires backstage='manual'")
        self._reraise()
        self._collect_finished()
        if not self._inflight:
            raise RuntimeError("step_back(): no round in flight")
        rnd = self._inflight[0]
        try:
            stage = self._step_round(rnd)
        except BaseException as e:
            self._fatal = e
            raise
        if rnd.done.is_set():
            self._collect_finished()
        return rnd.idx, stage

    # -- retire / deliver ------------------------------------------------

    def _collect_finished(self) -> None:
        while self._inflight and self._inflight[0].done.is_set():
            self._retire_oldest(block=False)

    def _retire_oldest(self, block: bool) -> bool:
        if not self._inflight:
            return False
        rnd = self._inflight[0]
        if not rnd.done.is_set():
            if not block:
                return False
            if self.backstage == "manual":
                while not rnd.done.is_set():
                    try:
                        self._step_round(rnd)
                    except BaseException:
                        break  # rnd.exc carries it; re-raised below
            else:
                rnd.done.wait()
        self._inflight.popleft()
        eng = self.engine
        # unpin and re-enforce the budget: exclusion keeps in-flight
        # tenant sets disjoint, so subtraction is exact
        eng._admission_pin = eng._admission_pin - rnd.tenants
        if rnd.exc is not None:
            self._fatal = rnd.exc
            raise rnd.exc
        eng._enforce_budget()
        now = time.perf_counter()
        if rnd.obs:
            # envelope = round wall-clock beyond the attributed stage
            # walls: queue handoff, ring waits, response delivery
            eng._occ_add(
                "envelope", max(0.0, (now - rnd.t_form) - rnd.stage_wall)
            )
        for (req, _dl, t_sub), resp, seq in zip(
            rnd.entries, rnd.responses, rnd.seqs
        ):
            outcome = (
                ("degraded" if resp.degraded else "ok")
                if resp.ok else resp.error.category
            )
            eng._observe("tick", outcome, now - t_sub, resp.ok)
            self._completed[seq] = resp
        inc("serving.pipeline.rounds")
        return True

    def poll(self) -> list:
        """Responses releasable so far, in submission order (stops at
        the first still-pending seq so ordering is never violated)."""
        out = []
        while self._next_out in self._completed:
            out.append(self._completed.pop(self._next_out))
            self._next_out += 1
        return out

    def drain(self) -> list:
        """Pump until the queue is empty and every in-flight round has
        retired, then return all releasable responses in submission
        order.  The pipelined analogue of `flush_period()`."""
        self._reraise()
        while self._queue or self._inflight:
            if self._queue:
                if len(self._inflight) >= self.slots:
                    self._retire_oldest(block=True)
                if self.pump() == 0 and self._queue and self._inflight:
                    # all queued tenants are in flight: make backstage
                    # progress so exclusion frees them up
                    self._retire_oldest(block=True)
            else:
                self._retire_oldest(block=True)
        if self.engine._obs_live:
            gauge_set("serving.admission.depth", len(self._queue))
        return self.poll()

    # -- lifecycle -------------------------------------------------------

    def stats(self) -> dict:
        """Host-side pipeline counters (tests and bench)."""
        return {
            "submitted": self._submits,
            "rounds": self._rounds_formed,
            "shed_queue_full": self._shed_queue_full,
            "shed_deadline": self._shed_deadline,
            "queue_depth": len(self._queue),
            "inflight": len(self._inflight),
            "max_inflight": self._max_inflight,
        }

    def close(self) -> None:
        """Stop the backstage worker and detach from the engine (the
        engine reverts to inline metrics flushes).  Idempotent; does
        NOT drain — call `drain()` first if responses matter."""
        if self._closed:
            return
        self._closed = True
        if self._worker is not None:
            self._work_q.put(None)
            self._worker.join(timeout=10.0)
            self._worker = None
        if self.engine._pipeline is self:
            self.engine._pipeline = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    # -- internals -------------------------------------------------------

    def _hook(self, stage, rnd) -> None:
        if self.boundary_hook is not None:
            self.boundary_hook(stage, rnd.idx)

    def _reraise(self) -> None:
        if self._fatal is not None:
            raise self._fatal


def interleavings(n_rounds: int = 2, slots: int = 2):
    """Enumerate every legal stage interleaving of `n_rounds` pipelined
    rounds — the deterministic scheduler behind the interleaving tests.

    Yields token sequences; each token is ``("pump", k)`` (round k's
    front half: admit + dispatch) or ``("back", k, stage)`` (round k's
    next back stage).  The constraints encoded are exactly the
    pipeline's: rounds form in order; a round's stages run in order;
    back halves are globally FIFO by round (single-writer commit
    ordering); at most `slots` rounds are in flight at once.  Feed each
    sequence to a ``backstage="manual"`` pipeline — `pump()` for pump
    tokens, `step_back()` for back tokens — and every schedule must
    produce bit-identical end states (tests/test_pipeline.py)."""
    n_back = len(BACK_STAGES)

    def gen(pumped, backed, acc):
        # backed = total back stages completed, globally FIFO: round
        # b = backed // n_back is the round whose back half is next
        if pumped == n_rounds and backed == n_rounds * n_back:
            yield list(acc)
            return
        b_round, b_stage = divmod(backed, n_back)
        inflight = pumped - b_round  # formed, not fully committed
        if pumped < n_rounds and inflight < slots:
            acc.append(("pump", pumped))
            yield from gen(pumped + 1, backed, acc)
            acc.pop()
        if b_round < pumped:
            acc.append(("back", b_round, BACK_STAGES[b_stage]))
            yield from gen(pumped, backed + 1, acc)
            acc.pop()

    yield from gen(0, 0, [])
