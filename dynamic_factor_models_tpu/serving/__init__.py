"""Multi-tenant nowcast serving layer.

Turns fitted DFMs into a request-serving system on top of the PR 1-4
foundation:

* `online` — `ServingModel` (steady-gain constants derived once per
  refit) + the O(1) constant-gain tick `s_t = Abar s_{t-1} + K b_t` and
  nowcast readout; no per-tick factorization, latency independent of T.
* `batch` — full EM re-estimation batched across tenants sharing a
  (T, N) compile bucket: one vmapped guarded while-loop over B stacked
  panels (models/emloop.run_em_loop_batched).
* `store` — per-tenant persisted state (params + filter state) through
  utils/checkpoint's checksummed archives; corruption quarantines one
  tenant, never the store.
* `engine` — the synchronous request-loop driver routing tick / nowcast
  / refit requests, each bracketed in a telemetry RunRecord; exposed as
  ``python -m dynamic_factor_models_tpu.serve``.
* `pipeline` — double-buffered round pipeline over one engine: a
  bounded async admission queue feeds rounds whose journal/commit back
  half overlaps the next round's admit/dispatch (two-slot ring, FIFO
  commits, per-tenant exclusion).
* `router` — tenant-sharded serving: M engine workers (in-process or
  OS processes), each owning a hash slice of tenants with its own
  store partition; refits gang-schedule through one batched EM.  A
  supervision layer (deadline-bounded RPCs + `WorkerSupervisor`)
  detects dead/stalled workers, sheds their requests as typed
  ``worker_unavailable`` responses, and respawns + recovers them from
  their untouched partition.

See docs/serving.md for the request types and state-store layout.
"""

from .batch import RefitResult, refit_batch, refit_sequential
from .engine import ServingEngine
from .pipeline import ServingPipeline
from .router import TenantRouter, WorkerUnavailable
from .online import (
    FilterState,
    ServingModel,
    derive_serving_model,
    derive_serving_model_mf,
    nowcast,
    online_tick,
)
from .store import TenantState, TenantStore

__all__ = [
    "FilterState",
    "ServingModel",
    "derive_serving_model",
    "derive_serving_model_mf",
    "nowcast",
    "online_tick",
    "RefitResult",
    "refit_batch",
    "refit_sequential",
    "TenantState",
    "TenantStore",
    "ServingEngine",
    "ServingPipeline",
    "TenantRouter",
    "WorkerUnavailable",
]
