"""Dual-form burst catch-up: GEMM prefill for every replay path.

After the steady-state horizon t* a serving tenant's tick stream
(serving/online.py) is a constant-gain linear recursion

    s_{t+1} = Abar[j] s_t + K[j] (xz_t @ Wb),      j = t mod d,

with d = 1 (complete panel) or d = 3 (mixed-frequency cyclostationary
gains).  That recursion has an EXACT convolutional dual: a backlog of k
ticks collapses to

    s_{t+k} = M^C s_t + sum_{c<C} M^{C-1-c} g_c   (+ <d remainder ticks)

where M is the per-cycle composite transition (C = k // d full cycles)
and the forcing rows g_c come out of ONE batched (k, q) input-response
GEMM — the LLM prefill/decode split applied to serving.  k sequential
O(k_dim^2) dispatches become one Ā-power stack (log-depth
square-and-multiply, models/steady.power_stack — the power-table half of
`linear_recursion`'s blocked einsum) plus one GEMM, exact after t* by
the PR 3 steady-state argument.

Two kernel forms, picked per call site:

* `_prefill_impl` — the GEMM dual.  O(log k) matmul depth, ~1e-15-close
  to sequential replay (matmul reassociation), NOT bitwise.  Used by
  the replay paths (fault-in, reconcile, recover) for backlogs of at
  least `min_gemm_depth()` ticks; shorter journals keep the sequential
  `replay_ticks` loop so the seed bit-identity pins (tests/
  test_eviction.py) hold unchanged.  Parity vs sequential replay is
  pinned at 1e-14 (complete) / 1e-12 (MF period-3) by
  tests/test_prefill.py over k in 1..1024 including ragged depths.
* `_tick_block_impl` — the decode-form block: k sequential ticks inside
  ONE scan dispatch, per-step arithmetic exactly `online._tick`'s, so
  the result is BITWISE identical to k single-tick dispatches.  Used by
  `flush_period` block lanes, where batched admission is pinned
  bit-equal to sequential `handle` ticks.

Burst depths are padded to power-of-two buckets (`PREFILL_BUCKETS`) so
AOT plans key on ceil(log2 k): `utils/compile.precompile` registers
`serving_prefill@K{2^j}` / `serving_tick_block@K{2^j}` plans when
`CompileSpec.prefill_depth > 0`, and every backlog in a bucket shares
one executable (the actual depth is a traced operand; padded steps are
masked inert).  Backlogs beyond `MAX_PREFILL_DEPTH` chunk through the
top bucket.  `DFM_PREFILL=0` disables the dual everywhere (the bench
A/B off arm); `DFM_PREFILL_MIN_K` moves the GEMM threshold.
"""

from __future__ import annotations

import os
import warnings

import jax
import jax.numpy as jnp
import numpy as np

from ..models.steady import power_stack
from ..utils.compile import aot_call
from ..utils.telemetry import inc, register_hist
from .online import FilterState, ServingModel, _tick, replay_ticks

__all__ = [
    "PREFILL_BUCKETS",
    "MAX_PREFILL_DEPTH",
    "prefill_bucket",
    "prefill_enabled",
    "min_gemm_depth",
    "prefill_ticks",
    "tick_block",
]

# power-of-two burst-depth buckets: one AOT plan per bucket, so a cold
# depth costs at most one compile and a warm fleet sees ceil(log2 1024)
# + 1 = 11 executables total per panel bucket
PREFILL_BUCKETS = (1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024)
MAX_PREFILL_DEPTH = PREFILL_BUCKETS[-1]


def prefill_enabled() -> bool:
    """`DFM_PREFILL=0` forces every replay back to the sequential tick
    loop — the bench A/B off arm and the escape hatch."""
    return os.environ.get("DFM_PREFILL", "1") != "0"


def min_gemm_depth() -> int:
    """Backlogs shorter than this keep the sequential `replay_ticks`
    loop: below it the dual's power-stack setup costs more than it
    saves, and — the binding constraint — sequential replay is BITWISE
    identical to the live tick stream, which the eviction/recover
    bit-identity pins rely on for short journals."""
    try:
        return max(1, int(os.environ.get("DFM_PREFILL_MIN_K", "8")))
    except ValueError:
        return 8


def prefill_bucket(k: int) -> int:
    """Smallest power-of-two bucket holding a k-tick burst (capped at
    MAX_PREFILL_DEPTH; deeper backlogs chunk)."""
    if k <= 1:
        return 1
    if k >= MAX_PREFILL_DEPTH:
        return MAX_PREFILL_DEPTH
    return 1 << (k - 1).bit_length()


# ---------------------------------------------------------------------------
# GEMM dual
# ---------------------------------------------------------------------------


def _cycle_maps(model: ServingModel):
    """Trace-time candidates, one per start phase ph in 0..d-1: the
    composite per-cycle transition

        M(ph) = Abar[ph+d-1] @ ... @ Abar[ph]

    and the within-cycle input-response maps

        E_j(ph) = (Abar[ph+d-1] @ ... @ Abar[ph+j+1]) @ K[ph+j]

    (indices mod d), so one cycle starting at phase ph advances

        s' = M(ph) s + sum_j E_j(ph) b_j.

    d is static and tiny (1 or 3): the candidate products are a handful
    of (k, k) matmuls folded at trace time; the traced start phase picks
    its row by gather."""
    d = model.Abar.shape[0]
    kdim = model.Abar.shape[1]
    eye = jnp.eye(kdim, dtype=model.Abar.dtype)
    M_cands, E_cands = [], [[] for _ in range(d)]
    for ph in range(d):
        suf = eye  # suffix product Abar[ph+d-1] @ ... @ Abar[ph+j+1]
        Ej = [None] * d
        for j in range(d - 1, -1, -1):
            Ej[j] = suf @ model.K[(ph + j) % d]
            suf = suf @ model.Abar[(ph + j) % d]
        M_cands.append(suf)
        for j in range(d):
            E_cands[j].append(Ej[j])
    return jnp.stack(M_cands), [jnp.stack(E) for E in E_cands]


@jax.jit
def _prefill_impl(model: ServingModel, state: FilterState, X, mask, k_actual):
    """The dual-form catch-up kernel: post-burst FilterState from one
    power stack + one batched input-response GEMM.

    X (Kb, N) / mask (Kb, N) hold the burst rows padded to the static
    depth bucket Kb; `k_actual` (traced i32, <= Kb) is the live depth —
    padding enters only through masked gathers, never the state.  The
    phase of tick i is (t + i) mod d with the start phase traced, so MF
    period-3 tenants fold d ticks per composite cycle and finish with
    up to d-1 masked remainder ticks.  Matmuls and selects only — no
    factorization, O(log Kb) matmul depth."""
    d = model.Abar.shape[0]  # static: 1 complete, 3 mixed-frequency
    Kb = X.shape[0]  # static: the depth bucket
    Cmax = -(-Kb // d)  # ceil: max whole cycles in the bucket
    phi = state.t % d

    # the batched collapse: every burst row's b_i in one (Kb, N)x(N, q)
    xz = jnp.where(mask, X, jnp.zeros((), X.dtype))
    B = xz @ model.Wb  # (Kb, q)

    M_cands, E_cands = _cycle_maps(model)
    M = jnp.take(M_cands, phi, axis=0)
    # per-cycle forcing g_c = sum_j E_j(phi) b_{cd+j}: pad B to whole
    # cycles, then d skinny GEMMs (one per within-cycle offset)
    Bp = jnp.zeros((Cmax * d, B.shape[1]), B.dtype).at[:Kb].set(B)
    Bc = Bp.reshape(Cmax, d, -1)
    g = sum(
        Bc[:, j, :] @ jnp.take(E_cands[j], phi, axis=0).T for j in range(d)
    )  # (Cmax, kdim)

    P = power_stack(M, Cmax)  # (Cmax+1, k, k), log-depth
    C = k_actual // d  # traced: live whole cycles
    rho = k_actual - C * d  # traced: remainder ticks < d
    c_idx = jnp.arange(Cmax)
    Wp = jnp.where(
        (c_idx < C)[:, None, None],
        jnp.take(P, jnp.clip(C - 1 - c_idx, 0, Cmax), axis=0),
        jnp.zeros((), P.dtype),
    )
    s = jnp.take(P, C, axis=0) @ state.s + jnp.einsum("cab,cb->a", Wp, g)

    # remainder: up to d-1 sequential ticks, masked inert past rho
    for m in range(d - 1):
        i = C * d + m
        b_i = jnp.take(B, jnp.clip(i, 0, Kb - 1), axis=0)
        jm = (phi + m) % d
        s_new = (
            jnp.take(model.Abar, jm, axis=0) @ s
            + jnp.take(model.K, jm, axis=0) @ b_i
        )
        s = jnp.where(m < rho, s_new, s)
    return FilterState(s=s, t=state.t + jnp.asarray(k_actual, state.t.dtype))


# the lane-batched prefill is DERIVED, not hand-written — the same
# batch() doctrine as online._tick_batched: vmap over a leading lane
# axis of the SAME jitted kernel (per-lane depths ride the traced
# k_actual operand, so ragged backlogs share one executable per
# (lane bucket, depth bucket) pair)
_prefill_batched = jax.jit(jax.vmap(_prefill_impl))


# ---------------------------------------------------------------------------
# decode-form block (bitwise-exact scan)
# ---------------------------------------------------------------------------


@jax.jit
def _tick_block_impl(model: ServingModel, state: FilterState, X, mask, k_actual):
    """Decode-form block: k sequential ticks inside ONE scan dispatch.

    The step body IS `online._tick` (inlined by jit), so every per-step
    contraction runs in the same order as k single-tick dispatches and
    the result is BITWISE identical to them — the property flush block
    lanes need, where batched admission is pinned bit-equal to
    sequential `handle` ticks (tests/test_eviction.py).  NOT vmapped
    across tenants: batching the scan re-associates the per-step
    matvecs and breaks bit-equality (measured), so the engine dispatches
    one block per backlogged tenant.  Steps at or past `k_actual` are
    inert selects (padding to the depth bucket).  Returns (final
    FilterState, per-step FilterState stack (Kb,...))."""

    def step(st, inp):
        i, x, m = inp
        new = _tick(model, st, x, m)
        live = i < k_actual
        st2 = FilterState(
            s=jnp.where(live, new.s, st.s),
            t=jnp.where(live, new.t, st.t),
        )
        return st2, st2

    idx = jnp.arange(X.shape[0])
    return jax.lax.scan(step, state, (idx, X, mask))


# ---------------------------------------------------------------------------
# host wrappers
# ---------------------------------------------------------------------------

_depth_hist = None


def _observe_depth(k: int) -> None:
    global _depth_hist
    if _depth_hist is None:
        # unit label: NOT a latency — summarize keeps it out of the
        # per-entry latency merge and reads its p50 for the ticks-per-
        # prefill column
        _depth_hist = register_hist("serving.prefill.depth", unit="ticks")
    _depth_hist.record(float(k))


def _pad_block(model: ServingModel, rows, Kb: int):
    """Stack journal rows ((t, x, mask) or (x, mask)) into the bucketed
    (Kb, N) block; padded rows are zero/unobserved (inert by masking)."""
    N = model.Wb.shape[0]
    dt = np.dtype(model.Wb.dtype)
    X = np.zeros((Kb, N), dt)
    Mk = np.zeros((Kb, N), bool)
    for i, row in enumerate(rows):
        x_r, m_r = row[-2], row[-1]
        m = np.asarray(m_r, bool)
        X[i] = np.where(m, np.asarray(x_r, dt), 0.0)
        Mk[i] = m
    return jnp.asarray(X), jnp.asarray(Mk)


def _prefill_call(model, state, X, mask, k):
    return aot_call(
        "serving_prefill", _prefill_impl, model, state, X, mask,
        jnp.asarray(k, jnp.int32),
    )


def _tick_block_call(model, state, X, mask, k):
    return aot_call(
        "serving_tick_block", _tick_block_impl, model, state, X, mask,
        jnp.asarray(k, jnp.int32),
    )


def prefill_ticks(
    model: ServingModel, state: FilterState, rows, *, t_star=None
) -> FilterState:
    """Dual-form catch-up over journaled rows.

    `rows` iterates ``(t, x, mask)`` (journal format) or ``(x, mask)``
    (replay-buffer format) in append order.  Dispatch policy:

    * disabled (`DFM_PREFILL=0`) or short (< `min_gemm_depth()` rows):
      sequential `replay_ticks` — bitwise identical to the live stream;
    * pre-t* (caller passed `t_star` and state.t < t_star): the gains
      are not yet at their fixed point, so the dual would be silently
      wrong — warn LOUDLY, count it, and fall back to sequential;
    * else: chunked GEMM prefill, one dispatch per depth bucket.

    Returns the post-burst FilterState: exact equal to sequential
    replay below the GEMM threshold, <= 1e-14 (complete) / 1e-12 (MF
    period-3) above it (tests/test_prefill.py)."""
    rows = list(rows)
    k = len(rows)
    if k == 0:
        return state
    if not prefill_enabled() or k < min_gemm_depth():
        return replay_ticks(model, state, rows)
    if t_star is not None and int(state.t) < int(t_star):
        warnings.warn(
            f"prefill_ticks: state.t={int(state.t)} is before the "
            f"steady-state horizon t*={int(t_star)}; the dual form is "
            "only exact past t* — falling back to sequential replay",
            RuntimeWarning,
            stacklevel=2,
        )
        inc("serving.prefill.pre_tstar_fallback")
        return replay_ticks(model, state, rows)
    blocks = 0
    i = 0
    while i < k:
        chunk = rows[i : i + MAX_PREFILL_DEPTH]
        Kb = prefill_bucket(len(chunk))
        X, Mk = _pad_block(model, chunk, Kb)
        state = _prefill_call(model, state, X, Mk, len(chunk))
        blocks += 1
        i += len(chunk)
    inc("serving.prefill.blocks", blocks)
    inc("serving.prefill.ticks", k)
    _observe_depth(k)
    return state


def tick_block(model: ServingModel, state: FilterState, rows):
    """Bitwise-exact decode-form catch-up for one tenant's tick block.

    One scan dispatch per depth bucket instead of one dispatch per tick;
    per-row states come back for the per-request Responses.  Returns
    ``(final_state, [FilterState per row])`` — every element bit-equal
    to the sequential single-tick path."""
    rows = list(rows)
    k = len(rows)
    if k == 0:
        return state, []
    per_row = []
    i = 0
    blocks = 0
    while i < k:
        chunk = rows[i : i + MAX_PREFILL_DEPTH]
        Kb = prefill_bucket(len(chunk))
        X, Mk = _pad_block(model, chunk, Kb)
        state, traj = _tick_block_call(model, state, X, Mk, len(chunk))
        for j in range(len(chunk)):
            per_row.append(FilterState(s=traj.s[j], t=traj.t[j]))
        blocks += 1
        i += len(chunk)
    inc("serving.prefill.blocks", blocks)
    inc("serving.prefill.ticks", k)
    _observe_depth(k)
    return state, per_row
