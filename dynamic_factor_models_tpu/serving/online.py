"""O(1) online filter updates from a fitted model's steady gains.

A fitted DFM's filter converges to a Riccati fixed point (models/steady.py),
after which the measurement update is a CONSTANT linear map: with the
collapsed observation b_t = H' R^-1 x_t (observed entries only) the
filtered state advances as

    s_t = Abar[j] s_{t-1} + K[j] b_t,        j = t mod d,

d = 1 for a complete (time-invariant) observation pattern and d = 3 for
the mixed-frequency monthly/quarterly cycle.  `derive_serving_model`
solves the DARE once per (re)fit and freezes every constant the tick
needs into a `ServingModel` pytree; `online_tick` is then two matvecs and
one (N, q) matvec for the collapse — O(N q + k^2) per tick, independent
of the sample length, with no factorization anywhere in its HLO (pinned
by tests/test_serving.py).  This is the O(1) autoregressive-caching /
edge-Kalman specialization of PAPERS.md applied to the nowcast filter.

Parity contract: started from the exact filter's state at any time past
the convergence horizon (`ssm._steady_plan` / the periodic cycle's
verified convergence), the tick reproduces the full refilter's means to
the DARE tolerance — ~1e-12 relative in f64, pinned at 1e-10 over 50
ticks by the serving tests for both the complete and period-3 masks.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp

from ..models import mixed_freq as _mf
from ..models import ssm as _ssm
from ..models.steady import constant_gain_tick, steady_state
from ..utils.compile import aot_call

__all__ = [
    "ServingModel",
    "FilterState",
    "derive_serving_model",
    "derive_serving_model_mf",
    "online_tick",
    "online_tick_batched",
    "replay_ticks",
    "nowcast",
]


class ServingModel(NamedTuple):
    """Steady-gain serving constants, derived once per (re)fit.

    Wb: (N, q) collapse weights H_q / R (b_t = xz_t @ Wb); H: (N, q) the
    observation-loaded state columns (nowcast readout x_hat = H s[:q]);
    Tm: (k, k) companion transition (h-step forecasts); Abar: (d, k, k)
    per-phase closed-loop transition; K: (d, k, q) per-phase steady gain
    on the collapsed observation.  d = Abar.shape[0] is the observation
    period (1 complete, 3 mixed-frequency).  N may include trailing
    zero-padded series (`n_pad`) so every tenant in a compile bucket
    shares one tick executable."""

    Wb: jnp.ndarray
    H: jnp.ndarray
    Tm: jnp.ndarray
    Abar: jnp.ndarray
    K: jnp.ndarray

    @property
    def period(self) -> int:
        return self.Abar.shape[0]


class FilterState(NamedTuple):
    """Per-tenant filter state: the current filtered mean s (k,) and the
    ABSOLUTE time index t (i32) of the next tick — the observation phase
    is t mod d, so t must count from the same origin as the mask cycle
    (quarter-end months at t % 3 == 2, the mixed_freq convention)."""

    s: jnp.ndarray
    t: jnp.ndarray


def _pad_rows(M, n_pad: int | None):
    if n_pad is None or M.shape[0] == n_pad:
        return M
    if n_pad < M.shape[0]:
        raise ValueError(f"n_pad={n_pad} smaller than N={M.shape[0]}")
    return jnp.zeros((n_pad, M.shape[1]), M.dtype).at[: M.shape[0]].set(M)


# Jitted so repeated derives (tenant fault-ins under an eviction budget
# call this once per fault) reuse ONE compiled solve per (shape, q)
# bucket.  Calling `steady_state` eagerly would re-trace its inner
# `lax.while_loop` each call — the closed-over numpy constants defeat
# the dispatch cache, and every re-trace leaks an LLVM JIT code mapping,
# which at serving rates exhausts vm.max_map_count within minutes.
@partial(jax.jit, static_argnames=("q",))
def _steady_state_jit(Tm, Cq, Qs, q: int):
    return steady_state(Tm, Cq, Qs, q=q)


def derive_serving_model(
    params: _ssm.SSMParams, n_pad: int | None = None
) -> ServingModel:
    """Serving constants for a complete-observation (d = 1) tenant.

    Solves the collapsed DARE at `params` (Q floored exactly as
    `kalman_filter` does, so the tick's fixed point is the filter's) and
    freezes Abar / K / the collapse weights.  `n_pad` zero-pads the
    series dimension to a compile bucket (padded rows are inert: zero
    collapse weight, zero readout).  Host-side, concrete params only;
    raises when the DARE solve does not converge (non-stationary A)."""
    params = params._replace(Q=_ssm._psd_floor(params.Q))
    Tm, Qs = _ssm._companion(params)
    C_inf = (params.lam.T * (1.0 / params.R)) @ params.lam
    st = _steady_state_jit(Tm, C_inf, Qs, q=params.r)
    if not bool(st.converged):
        raise ValueError(
            "derive_serving_model: DARE solve did not converge (factor VAR "
            "not stationary?); refit before deriving serving constants"
        )
    return ServingModel(
        Wb=_pad_rows(params.lam / params.R[:, None], n_pad),
        H=_pad_rows(params.lam, n_pad),
        Tm=Tm,
        Abar=st.Abar[None],
        K=st.K[None],
    )


def derive_serving_model_mf(
    params: _mf.MixedFreqParams, pattern=None, n_pad: int | None = None
) -> ServingModel:
    """Serving constants for a mixed-frequency (period-3) tenant.

    `mixed_freq.steady_gains` solves the periodic DARE over the
    monthly/quarterly mask cycle (default `pattern`: quarterly series
    observed at t % 3 == 2 only); phase j of the returned model serves
    ticks with t % 3 == j.  The collapse loads the first q5 = 5r state
    dims through `_obs_matrix`."""
    ps = _mf.steady_gains(params, pattern)  # raises on non-finite params
    if not bool(ps.converged):
        raise ValueError(
            "derive_serving_model_mf: periodic DARE did not converge; "
            "refit before deriving serving constants"
        )
    q5 = _mf._N_AGG * params.r
    H5 = _mf._obs_matrix(params)[:, :q5]
    Tm, _ = _ssm._companion(_mf._as_ssm(params))
    return ServingModel(
        Wb=_pad_rows(H5 / params.R[:, None], n_pad),
        H=_pad_rows(H5, n_pad),
        Tm=Tm,
        Abar=ps.Abar,
        K=ps.K[:, :, :q5],
    )


@jax.jit
def _tick(model: ServingModel, state: FilterState, x_t, mask_t):
    """The jitted O(1) tick: collapse the (masked) observation row, one
    constant-gain step, advance the clock.  Matmuls and selects only —
    the compiled HLO carries no cholesky / triangular op (pinned)."""
    xz = jnp.where(mask_t, x_t, jnp.zeros((), x_t.dtype))
    b = xz @ model.Wb
    j = state.t % model.Abar.shape[0]
    s = constant_gain_tick(model.Abar, model.K, state.s, b, j)
    return FilterState(s=s, t=state.t + 1)


def online_tick(
    model: ServingModel, state: FilterState, x_t, mask_t
) -> FilterState:
    """Advance one tenant's filter state by one data tick.

    x_t: (N,) new observation row (NaN or anything at masked entries);
    mask_t: (N,) bool observed indicators.  Dispatches to a precompiled
    executable when `utils.compile.precompile` registered one for this
    bucket (kernel "serving_tick"), else the live jit."""
    x_t = jnp.asarray(x_t, model.Wb.dtype)
    mask_t = jnp.asarray(mask_t, bool)
    return aot_call("serving_tick", _tick, model, state, x_t, mask_t)


# The batched tick is DERIVED, not hand-written: exactly the transform-
# stack batch() doctrine (models/transforms.py) applied to the serving
# tick — vmap over a leading lane axis of the SAME jitted `_tick`, so
# there is no second kernel body to keep in sync.  Per-lane results are
# bit-identical to the sequential `_tick` on every output element: the
# per-lane contractions (xz @ Wb, Abar[j] @ s, K[j] @ b) batch to
# independent rows of a larger matmul with the same reduction order, so
# one executable serves both the live batched commit and the sequential
# journal replay that must reproduce it after a crash (pinned exactly by
# tests/test_eviction.py).
_tick_batched = jax.jit(jax.vmap(_tick))


def online_tick_batched(models, states, x_B, mask_B) -> FilterState:
    """Advance B tenants' filter states by one tick each in ONE vmapped
    dispatch.

    `models` / `states` are lane-stacked pytrees (every leaf carries a
    leading B axis; lanes in one batch share leaf SHAPES — the engine
    groups by (N, q, k, d) and pads the lane count to a compile bucket
    with inert zero lanes).  x_B: (B, N) observation rows; mask_B:
    (B, N) bool.  Dispatches to the precompiled "serving_tick_batched"
    executable when `utils.compile.precompile` registered one for this
    lane bucket, else the live jit."""
    x_B = jnp.asarray(x_B, models.Wb.dtype)
    mask_B = jnp.asarray(mask_B, bool)
    return aot_call(
        "serving_tick_batched", _tick_batched, models, states, x_B, mask_B
    )


def replay_ticks(model: ServingModel, state: FilterState, rows) -> FilterState:
    """Re-apply journaled ticks: `rows` iterates ``(t, x, mask)``
    (journal format, serving/journal.py) or ``(x, mask)`` (replay-buffer
    format) in append order.  Each row goes through the SAME
    `online_tick` executable the live path used, so a restart that
    replays snapshot + journal lands on a bit-identical FilterState —
    same program, same inputs, same floats.  Host loop: journals are
    short (ticks since the last snapshot) — deep backlogs go through
    serving/prefill.py's GEMM dual instead."""
    for row in rows:
        state = online_tick(model, state, row[-2], row[-1])
    return state


@jax.jit
def _nowcast(model: ServingModel, s):
    q = model.H.shape[1]
    return model.H @ s[:q]


def nowcast(model: ServingModel, state: FilterState, horizon: int = 0):
    """Fitted-panel readout x_hat_{t+h|t} = H (Tm^h s_t)[:q].  horizon=0
    is the nowcast of the current tick's row; h > 0 iterates the
    transition (h is tiny — an eager python loop, no compile churn)."""
    s = state.s
    for _ in range(int(horizon)):
        s = model.Tm @ s
    return _nowcast(model, s)
