"""Panel-batched EM re-estimation: refit many tenants in one device loop.

Serving-scale refits arrive as a queue of (tenant, panel, params) requests
with heterogeneous raw shapes.  Shape bucketing (utils/compile.bucket_shape
/ pad_panel) makes panels in the same (T, N) bucket literally identical in
shape, padding exactly inert under the masks — so a bucket's worth of
refits stacks into ONE leading batch axis and runs as a single vmapped
guarded EM while-loop (models/emloop.run_em_loop_batched).  B panels cost
one compile and one loop; the health sentinel is vectorized per tenant, so
a divergent panel is rolled back to its last-good iterate and frozen
without touching its bucket-mates (pinned by tests/test_serving.py).

`refit_sequential` runs the same per-tenant programs one at a time — the
parity reference and the denominator of the bench's batched-vs-sequential
speedup.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from ..models import ssm as _ssm
from ..models.emloop import run_em_loop, run_em_loop_batched
from ..parallel.mesh import series_pad as _series_pad
from ..utils.compile import (
    aot_call,
    bucket_shape,
    pad_panel,
    pad_ssm_params,
    unpad_ssm_params,
)
from ..utils.telemetry import inc, trace_span

__all__ = [
    "HEALTH_BUCKET_ERROR",
    "LANE_BUCKETS",
    "RefitRequest",
    "RefitResult",
    "lane_bucket",
    "batched_tick_dispatch",
    "batched_prefill_dispatch",
    "refit_batch",
    "refit_sequential",
]

# Health code for "the bucket's device program itself raised" — distinct
# from the in-loop utils.guards codes (0 ok, 1 nonfinite, 2 ll-decrease)
# so telemetry can tell a numerics rollback from an engine-level failure.
HEALTH_BUCKET_ERROR = 3


class RefitRequest(NamedTuple):
    """One tenant's refit work item: zero-filled panel `x` (T, N), bool
    `mask` (T, N), warm-start `params` (SSMParams at the RAW N)."""

    tenant_id: str
    x: jnp.ndarray
    mask: jnp.ndarray
    params: _ssm.SSMParams


class RefitResult(NamedTuple):
    """Per-tenant refit outcome.  `params` is unpadded back to the
    tenant's raw N; `health` is the utils.guards code (0 healthy — a
    non-zero tenant was rolled back and its params equal the last-good
    iterate, NOT a converged fit)."""

    tenant_id: str
    params: _ssm.SSMParams
    n_iter: int
    converged: bool
    health: int
    loglik: float


def _prepare(req: RefitRequest, t_pad: int, n_pad: int):
    """Pad one request to its bucket and build its masked panel stats."""
    x = jnp.asarray(req.x)
    mask = jnp.asarray(req.mask, bool)
    xz = jnp.where(mask, x, jnp.zeros((), x.dtype))
    xz_p, mask_p, tw = pad_panel(xz, mask, t_pad, n_pad)
    params_p = pad_ssm_params(req.params, n_pad)
    stats = _ssm.compute_panel_stats(xz_p, mask_p)._replace(tw=tw)
    return params_p, xz_p, mask_p, stats


def _group_by_bucket(requests):
    groups: dict[tuple, list] = {}
    for req in requests:
        key = bucket_shape(*req.x.shape)
        groups.setdefault(key, []).append(req)
    return groups


# ---------------------------------------------------------------------------
# continuous tick batching: lane grouping + bucket padding
# ---------------------------------------------------------------------------

# Lane-count compile buckets for the batched tick, mirroring the (T, N)
# panel buckets: the admitted lane count is padded UP to the nearest
# bucket so a varying admission queue cycles through a handful of
# executables instead of compiling per batch size.
LANE_BUCKETS = (1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024)


def lane_bucket(n: int) -> int:
    """Smallest lane bucket >= n (past the table: next power of two)."""
    if n < 1:
        raise ValueError(f"lane count must be >= 1, got {n}")
    for b in LANE_BUCKETS:
        if n <= b:
            return b
    b = LANE_BUCKETS[-1]
    while b < n:
        b *= 2
    return b


def _lane_sig(model, state, x):
    """Shape/dtype signature two lanes must share to stack: model leaf
    shapes carry (N, q, k, d), the state carries k, x carries N."""
    leaves = jax.tree.leaves((model, state))
    return (
        tuple((a.shape, str(a.dtype)) for a in leaves),
        (np.asarray(x).shape, str(np.asarray(x).dtype)),
    )


def batched_tick_dispatch(lanes):
    """Advance many tenants one tick each in as few vmapped dispatches
    as possible.

    `lanes` is a list of ``(model, state, x, mask)`` — one admitted tick
    per tenant (the engine's admission queue guarantees at most one lane
    per tenant per round).  Lanes are grouped by exact leaf signature,
    each group stacked along a new leading lane axis, padded to
    `lane_bucket` with INERT lanes (lane 0's model replicated over a
    zero state / zero row / all-False mask — vmap carries no cross-lane
    op, so padding cannot perturb a real lane; the real-lane outputs are
    pinned bit-identical to sequential `_tick` calls by
    tests/test_eviction.py), and dispatched through ONE
    `online_tick_batched` per group.  Returns the new FilterStates in
    input order.  Pure compute: no journal, no commit — the engine owns
    the write-ahead ordering around this call."""
    from .online import FilterState, online_tick_batched

    if not lanes:
        return []
    groups: dict[tuple, list[int]] = {}
    for i, (model, state, x, _mask) in enumerate(lanes):
        groups.setdefault(_lane_sig(model, state, x), []).append(i)
    out: list = [None] * len(lanes)
    for idxs in groups.values():
        n = len(idxs)
        bucket = lane_bucket(n)
        models = [lanes[i][0] for i in idxs]
        states = [lanes[i][1] for i in idxs]
        xs = [np.asarray(lanes[i][2]) for i in idxs]
        masks = [np.asarray(lanes[i][3], bool) for i in idxs]
        if bucket > n:  # inert padding lanes
            pad = bucket - n
            s0 = np.asarray(states[0].s)
            zs = FilterState(
                s=np.zeros_like(s0),
                t=np.zeros((), np.asarray(states[0].t).dtype),
            )
            states += [zs] * pad
            xs += [np.zeros_like(xs[0])] * pad
            masks += [np.zeros_like(masks[0])] * pad
        # register_shared clones carry the SAME model object — stack it
        # as one broadcast per leaf instead of a B-way concatenation
        # (padding lanes replicate lane 0's model either way)
        if all(m is models[0] for m in models[1:]):
            model_B = jax.tree.map(
                lambda a: jnp.broadcast_to(a, (bucket,) + a.shape),
                models[0],
            )
        else:
            models += [models[0]] * (bucket - n)
            model_B = jax.tree.map(lambda *ls: jnp.stack(ls), *models)
        # states/rows stack on HOST (np): per-flush glue must not cost
        # a device dispatch per lane or batching loses to sequential
        state_B = jax.tree.map(
            lambda *ls: np.stack([np.asarray(a) for a in ls]), *states
        )
        with trace_span(
            "tick.batch", lanes=n, bucket=bucket,
        ):
            new_B = online_tick_batched(
                model_B, state_B, np.stack(xs), np.stack(masks)
            )
        # materialize once, hand out zero-copy numpy row views — the
        # same floats the device produced, so per-lane bit-identity to
        # sequential ticks is preserved through the unstack
        new_np = jax.tree.map(np.asarray, new_B)
        for j, i in enumerate(idxs):
            out[i] = jax.tree.map(lambda a, j=j: a[j], new_np)
    return out


def batched_prefill_dispatch(lanes):
    """Dual-form catch-up for many tenants in as few vmapped dispatches
    as possible — `recover()`'s prewarm fan-in for deep journals.

    `lanes` is a list of ``(model, state, rows)`` with `rows` one
    tenant's journal backlog.  Lanes are grouped by (leaf signature,
    depth bucket), each group's blocks stacked along a new leading lane
    axis padded to `lane_bucket` with inert zero lanes (depth 0: the
    dual degenerates to the zero state's identity carry), and dispatched
    through ONE vmapped GEMM prefill per group
    (serving/prefill._prefill_batched — derived by vmap from the scalar
    kernel, per-lane ragged depths ride the traced depth operand).
    Backlogs past the top depth bucket fall back to the per-lane chunked
    host loop.  Returns post-burst FilterStates in input order.

    NOT bitwise vs sequential replay (vmap re-associates the matvecs):
    callers keep short backlogs on the round-based bitwise path and
    route only >= `min_gemm_depth()` backlogs here — parity is pinned at
    1e-14 / 1e-12 by tests/test_prefill.py."""
    from .online import FilterState
    from .prefill import (
        MAX_PREFILL_DEPTH,
        _pad_block,
        _prefill_batched,
        prefill_bucket,
        prefill_ticks,
    )

    if not lanes:
        return []
    out: list = [None] * len(lanes)
    groups: dict[tuple, list[int]] = {}
    for i, (model, state, rows) in enumerate(lanes):
        if not rows:
            out[i] = state
        elif len(rows) > MAX_PREFILL_DEPTH:
            out[i] = prefill_ticks(model, state, rows)  # chunked host loop
        else:
            key = (
                _lane_sig(model, state, np.asarray(rows[0][-2])),
                prefill_bucket(len(rows)),
            )
            groups.setdefault(key, []).append(i)
    for (_sig, Kb), idxs in groups.items():
        n = len(idxs)
        bucket = lane_bucket(n)
        models = [lanes[i][0] for i in idxs]
        states = [lanes[i][1] for i in idxs]
        Xs, Ms, ks = [], [], []
        for i in idxs:
            X, Mk = _pad_block(lanes[i][0], lanes[i][2], Kb)
            Xs.append(np.asarray(X))
            Ms.append(np.asarray(Mk))
            ks.append(len(lanes[i][2]))
        if bucket > n:  # inert padding lanes (depth 0)
            pad = bucket - n
            s0 = np.asarray(states[0].s)
            zs = FilterState(
                s=np.zeros_like(s0),
                t=np.zeros((), np.asarray(states[0].t).dtype),
            )
            states += [zs] * pad
            Xs += [np.zeros_like(Xs[0])] * pad
            Ms += [np.zeros_like(Ms[0])] * pad
            ks += [0] * pad
        if all(m is models[0] for m in models[1:]):
            model_B = jax.tree.map(
                lambda a: jnp.broadcast_to(a, (bucket,) + a.shape),
                models[0],
            )
        else:
            models += [models[0]] * (bucket - n)
            model_B = jax.tree.map(lambda *ls: jnp.stack(ls), *models)
        state_B = jax.tree.map(
            lambda *ls: np.stack([np.asarray(a) for a in ls]), *states
        )
        with trace_span(
            "prefill.batch", lanes=n, bucket=bucket, depth=Kb,
        ):
            new_B = aot_call(
                "serving_prefill_batched", _prefill_batched,
                model_B, state_B, np.stack(Xs), np.stack(Ms),
                np.asarray(ks, np.int32),
            )
        new_np = jax.tree.map(np.asarray, new_B)
        for j, i in enumerate(idxs):
            out[i] = jax.tree.map(lambda a, j=j: a[j], new_np)
        inc("serving.prefill.blocks", n)
        inc("serving.prefill.ticks", float(sum(ks[:n])))
    return out


def refit_batch(
    requests,
    tol: float = 1e-6,
    max_em_iter: int = 200,
    step=None,
    isolate_errors: bool = False,
) -> list[RefitResult]:
    """Refit every request, batching within each (T, N) compile bucket.

    Requests are grouped by `bucket_shape`; each group is padded to the
    bucket, stacked along a new leading axis, and run through ONE vmapped
    EM loop.  Results come back in input order, params unpadded to each
    tenant's raw series count.  A tenant whose loop tripped the health
    sentinel gets its rolled-back last-good params and health != 0 —
    callers (serving/engine.py) keep the old fit for that tenant.

    `isolate_errors=True` additionally contains a bucket whose program
    RAISES (shape bug, compile failure, injected fault): its tenants
    come back with ``health=HEALTH_BUCKET_ERROR`` and their warm-start
    params untouched, and the other buckets still run — one poisoned
    bucket must not kill a multi-tenant flush.  Simulated external
    kills (preemption/crash injections) are never contained.

    With `step=None` (the default) each bucket resolves its own step
    from the transform stack: a bucket whose padded N crosses
    ``ssm.LARGE_N_THRESHOLD`` dispatches the collapse-first kernel
    (`emcore.em_step_collapsed` — the explicit-payload twin of
    `em_step_stats`, bit-identical per iteration, pinned by
    tests/test_serving_large_n.py), so wide-bucket refits collapse the
    (T, N) panel before the vmapped scan instead of carrying it through.
    An explicit `step=` suppresses the dispatch for every bucket."""
    from ..models import transforms as _tfm
    from ..utils.faults import SimulatedCrash, SimulatedPreemption

    requests = list(requests)
    auto_step = step is None
    step = step or _ssm.em_step_stats
    out: dict[int, RefitResult] = {}
    order = {id(req): i for i, req in enumerate(requests)}
    for (t_pad, n_pad), group in _group_by_bucket(requests).items():
        bucket_step = step
        if auto_step and n_pad > _ssm.LARGE_N_THRESHOLD:
            bucket_step = _tfm.resolve(
                _tfm.Stack("ssm", (_tfm.collapse(),))
            ).step
        # bucket membership lands in the requesting span tree: a refit
        # request's trace shows WHICH (T, N) bucket ran its tenant and
        # who shared the compiled program
        with trace_span(
            "refit.bucket", t_pad=int(t_pad), n_pad=int(n_pad),
            tenants=[req.tenant_id for req in group],
        ):
            try:
                prepped = [_prepare(req, t_pad, n_pad) for req in group]
                params_B = jax.tree.map(lambda *xs: jnp.stack(xs),
                                        *[p[0] for p in prepped])
                x_B = jnp.stack([p[1] for p in prepped])
                mask_B = jnp.stack([p[2] for p in prepped])
                stats_B = jax.tree.map(lambda *xs: jnp.stack(xs),
                                       *[p[3] for p in prepped])
                res = run_em_loop_batched(
                    bucket_step, params_B, (x_B, mask_B, stats_B), tol,
                    max_em_iter,
                )
            except (SimulatedPreemption, SimulatedCrash, KeyboardInterrupt):
                raise
            except Exception:
                if not isolate_errors:
                    raise
                for req in group:
                    out[order[id(req)]] = RefitResult(
                        tenant_id=req.tenant_id,
                        params=req.params,
                        n_iter=0,
                        converged=False,
                        health=HEALTH_BUCKET_ERROR,
                        loglik=float("nan"),
                    )
                continue
        for b, req in enumerate(group):
            params_b = jax.tree.map(lambda a: a[b], res.params)
            ll_path = res.llpath[b]
            ll = ll_path[res.n_iter[b] - 1] if res.n_iter[b] >= 1 else np.nan
            out[order[id(req)]] = RefitResult(
                tenant_id=req.tenant_id,
                params=unpad_ssm_params(params_b, req.x.shape[1]),
                n_iter=int(res.n_iter[b]),
                converged=bool(res.converged[b]),
                health=int(res.health[b]),
                loglik=float(ll),
            )
    return [out[i] for i in range(len(requests))]


def refit_sequential(
    requests,
    tol: float = 1e-6,
    max_em_iter: int = 200,
    step=None,
    n_shards: int | None = None,
) -> list[RefitResult]:
    """Per-tenant reference path: the SAME padded program per tenant, run
    one at a time through the scalar loop — the parity oracle for
    `refit_batch` and the bench speedup baseline.

    `n_shards > 1` runs each tenant's step sharded over the cross-section
    (models/ssm._sharded_step_for): the bucket's N is further padded to a
    shard multiple — inert under the same mask/tw contract as bucket
    padding — and the per-iteration program is the zero-host-sync sharded
    EM step.  Tenants too small to shard profitably still work; the knob
    exists so a serving node with a mesh can refit its largest panels
    without a separate code path.

    In a `jax.distributed`-initialized runtime `_sharded_step_for`
    resolves onto the process-spanning ``("dcn", "ici")`` mesh (PR 15)
    with the hierarchical ICI+DCN reduction, so a multi-host serving
    node refits across OS processes unmodified — n_shards must then be a
    multiple of `jax.process_count()` and `jax.device_count()` counts
    the GLOBAL mesh, so the guard below already sizes correctly."""
    ns = int(n_shards) if n_shards else 0
    if ns > 1:
        if step is not None:
            raise ValueError("pass either step= or n_shards=, not both")
        if ns > jax.device_count():
            raise ValueError(
                f"n_shards={ns} exceeds device_count={jax.device_count()}"
            )
        step = _ssm._sharded_step_for(ns)
    step = step or _ssm.em_step_stats
    results = []
    for req in requests:
        t_pad, n_pad = bucket_shape(*req.x.shape)
        if ns > 1:
            n_pad = _series_pad(n_pad, ns)
        params_p, xz_p, mask_p, stats = _prepare(req, t_pad, n_pad)
        res = run_em_loop(
            step,
            params_p,
            (xz_p, mask_p, stats),
            tol,
            max_em_iter,
        )
        ll = res.loglik_path[res.n_iter - 1] if res.n_iter >= 1 else np.nan
        results.append(
            RefitResult(
                tenant_id=req.tenant_id,
                params=unpad_ssm_params(res.params, req.x.shape[1]),
                n_iter=int(res.n_iter),
                converged=bool(res.converged),
                health=int(getattr(res, "health", 0)),
                loglik=float(ll),
            )
        )
    return results
