"""Per-tenant state store: checksummed persistence with quarantine.

One .npz archive per tenant under a store directory, written through
utils/checkpoint's `save_pytree` (sha256 content digest over leaves +
structure) with the same atomic-rename protocol the EM checkpoint driver
uses: write to a per-writer unique temp name, `os.replace` into place, so
a crashed save never leaves a half-written archive under a live id.  Loads
inherit `load_pytree`'s verification: a corrupt archive is quarantined to
``<id>.npz.corrupt`` and reported as missing — one tenant's bad disk
sector (or an injected ``DFM_FAULTS=ckpt_corrupt@n``) costs that tenant a
refit, never the store.  `checkpoint.list_entries` enumerates the live
ids, naturally excluding quarantined and in-flight temp files.
"""

from __future__ import annotations

import os
import re
import uuid
from typing import NamedTuple

import jax
import jax.numpy as jnp

from ..models.ssm import SSMParams
from ..utils import faults as _faults
from ..utils.checkpoint import (
    CheckpointCorruptError,
    list_entries,
    load_pytree,
    save_pytree,
)
from ..utils.telemetry import inc
from .journal import TickJournal

__all__ = [
    "TenantState", "TenantStore", "template_state", "worker_partition",
]

_ID_RE = re.compile(r"^[A-Za-z0-9_\-]+$")


def worker_partition(directory: str, worker: int) -> str:
    """Store-partition path for one sharded engine worker
    (serving/router.py): each worker owns a DISJOINT subdirectory of
    the store, so snapshots and journals of different workers never
    share a file and the per-tenant crash analysis is per-partition.
    Pure path arithmetic — `TenantStore` creates the directory."""
    return os.path.join(directory, f"worker{int(worker):03d}")


class TenantState(NamedTuple):
    """Everything a tenant needs to serve after a process restart: the
    fitted `params`, the current filtered mean `s` (k,), the absolute
    time index `t` of the next tick (the observation phase is t mod d),
    and the factor count `r` / VAR order `p` as stored leaves — so a
    tenant fitted with non-default (r, p) round-trips without the loader
    guessing shapes.  The ServingModel itself is NOT stored — it is a
    pure function of `params` (one DARE solve) and is re-derived on
    load.

    `breaker` packs the tenant's circuit-breaker position at snapshot
    time as int32 ``(state_code, consecutive_faults, cooldown_left)``
    (resilience.CircuitBreaker.pack) so an evicted open-breaker tenant
    faults back in STILL OPEN — eviction must not silently re-admit a
    tenant its breaker had quarantined.  The scalar default keeps
    hand-built TenantStates (tests, older writers) valid; readers treat
    anything that is not a 3-vector as "fresh breaker"."""

    params: SSMParams
    s: jnp.ndarray
    t: jnp.ndarray
    r: jnp.ndarray
    p: jnp.ndarray
    breaker: jnp.ndarray = 0


def template_state(N: int, r: int, p: int, dtype=float) -> TenantState:
    """Structure-only template for `load_pytree` (dummy leaves).

    Leaf SHAPES here are placeholders — `load_pytree` verifies leaf
    count and treedef, then takes shapes from the archive — so one
    template covers tenants of any (r, p)."""
    dt = jnp.result_type(dtype)  # respects the x64 switch
    k = r * p
    return TenantState(
        params=SSMParams(
            jnp.zeros((N, r), dt),
            jnp.ones((N,), dt),
            jnp.zeros((p, r, r), dt),
            jnp.eye(r, dtype=dt),
        ),
        s=jnp.zeros((k,), dt),
        t=jnp.zeros((), jnp.int32),
        r=jnp.asarray(r, jnp.int32),
        p=jnp.asarray(p, jnp.int32),
        breaker=jnp.zeros((3,), jnp.int32),
    )


class TenantStore:
    """Directory-backed map tenant_id -> TenantState.

    ids are restricted to ``[A-Za-z0-9_-]+`` (they become file stems; no
    separators, no traversal).  `load` returns None both for an id that
    was never saved and for one whose archive failed verification — in
    the latter case the archive has already been quarantined and the
    `serving.store.quarantined` counter incremented, so the engine treats
    the tenant as needing re-registration/refit."""

    def __init__(self, directory: str):
        self.directory = directory
        os.makedirs(directory, exist_ok=True)
        self._saves = 0
        self._io_ops = 0

    def _path(self, tenant_id: str) -> str:
        if not _ID_RE.match(tenant_id):
            raise ValueError(
                f"invalid tenant id {tenant_id!r}: use [A-Za-z0-9_-]+ only"
            )
        return os.path.join(self.directory, tenant_id + ".npz")

    def io_probe(self) -> None:
        """Count one store I/O operation against the ``store_io@n`` and
        ``crash_io@n`` fault sites.  Snapshot saves and journal writes
        share THIS counter, so one spec drives a deterministic fault
        sequence across both paths.  Raises OSError when the store_io
        site fires (a transient fault the engine's retry absorbs) and
        SimulatedCrash when the crash_io site fires (a process kill the
        engine must NOT absorb — the kill-at-every-step drill: each
        store op is atomic, so killing before op n covers every crash
        point between consecutive ops)."""
        self._io_ops += 1
        if _faults.site_hits("crash_io", self._io_ops):
            _faults.fault_fired("crash_io")
            raise _faults.SimulatedCrash(
                f"injected crash_io kill (op {self._io_ops})"
            )
        if _faults.site_hits("store_io", self._io_ops):
            _faults.fault_fired("store_io")
            raise OSError(
                f"injected store_io fault (op {self._io_ops})"
            )

    def journal(self, tenant_id: str) -> TickJournal:
        """This tenant's write-ahead tick journal, wired to the store's
        fault-counted `io_probe` (file lives next to the snapshot)."""
        path = self._path(tenant_id)[: -len(".npz")] + ".journal"
        return TickJournal(path, io_probe=self.io_probe)

    def save(self, tenant_id: str, state: TenantState) -> None:
        """Atomically persist one tenant (temp file + rename; a crash
        mid-save leaves the previous archive intact).  Honors the
        utils.faults ``ckpt_corrupt@n`` site: the n-th save through this
        store instance is damaged after landing — the chaos drill the
        quarantine path is pinned against."""
        path = self._path(tenant_id)
        self.io_probe()
        tmp = f"{path}.tmp.{os.getpid()}.{uuid.uuid4().hex[:8]}.npz"
        try:
            # stored (uncompressed): eviction compaction writes one of
            # these per cold tenant; deflate would dominate its cost
            save_pytree(tmp, state, compress=False)
            # the eviction contract is snapshot DURABLE before the
            # journal truncates (docs/robustness.md crash matrix), so
            # fsync the archive before rename — rename alone orders
            # metadata, not the data blocks
            fd = os.open(tmp, os.O_RDONLY)
            try:
                os.fsync(fd)
            finally:
                os.close(fd)
            os.replace(tmp, path)
        except BaseException:
            try:  # a failed save must not leak its temp file
                os.remove(tmp)
            except OSError:
                pass
            raise
        self._saves += 1
        inc("serving.store.saves")
        plan = _faults.active_plan()
        if plan.ckpt_corrupt is not None and self._saves == plan.ckpt_corrupt:
            _faults.corrupt_file(path)

    def load(self, tenant_id: str, like: TenantState) -> TenantState | None:
        """Load one tenant, or None when absent OR quarantined-corrupt.
        `like` supplies the pytree structure (see `template_state`)."""
        path = self._path(tenant_id)
        if not os.path.exists(path):
            return None
        try:
            state = load_pytree(path, like)
        except CheckpointCorruptError:
            # load_pytree already moved the file to <path>.corrupt
            inc("serving.store.quarantined")
            return None
        return jax.tree.map(jnp.asarray, state)

    def list(self) -> list[str]:
        """Live tenant ids, sorted.  Delegates to
        `checkpoint.list_entries`, which admits only ``<id>.npz`` names —
        quarantined ``*.corrupt`` files, in-flight ``*.npz.tmp.*``
        temporaries, and the ``.journal`` / ``.journal.corrupt`` /
        ``.journal.tmp.*`` siblings all fail the suffix filter and never
        leak into the id listing (pinned by tests/test_eviction.py with
        planted stray files)."""
        return list_entries(self.directory)

    def snapshot_mtime(self, tenant_id: str) -> float:
        """Last-modified time of the tenant's snapshot archive (0.0 when
        absent) — `engine.recover` prewarms most-recently-written ids
        first, a cheap proxy for 'hot before the crash'."""
        try:
            return os.path.getmtime(self._path(tenant_id))
        except OSError:
            return 0.0

    def delete(self, tenant_id: str) -> bool:
        path = self._path(tenant_id)
        self.journal(tenant_id).delete()
        try:
            os.remove(path)
            return True
        except FileNotFoundError:
            return False
