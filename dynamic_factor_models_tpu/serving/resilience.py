"""Request-hardening primitives for the serving engine.

The serving loop's availability contract (docs/serving.md): every
request gets a TYPED response — never an uncaught exception — and a
faulted tenant degrades instead of taking the engine down.  This module
supplies the host-side pieces the engine composes; nothing here touches
a device program, so the tick/nowcast HLO stays byte-identical to the
pre-hardening build (pinned by tests/test_serving.py).

* **Error taxonomy** — every failure is classified into one of three
  CATEGORIES, each with a machine-readable CODE:

  - ``client_error``: the request itself is wrong (missing field, bad
    shape, unknown tenant/kind).  Never retried, never counts against a
    tenant's circuit breaker.
  - ``tenant_fault``: this tenant's serving state is unhealthy (a
    non-finite tick result, an open breaker).  The tick lands in the
    tenant's replay buffer; nowcasts degrade to last-good state; other
    tenants are unaffected.
  - ``system_fault``: the engine's own machinery failed (store I/O,
    deadline blown, unexpected exception).  Transient system faults are
    retried with bounded exponential backoff before surfacing.

* **Response envelope** — a NamedTuple carrying the result OR an
  `ErrorInfo`, plus the staleness stamp (`degraded`, `ticks_behind`),
  the retry count, and the tenant's breaker state, so a caller — or the
  chaos harness — can compute availability from responses alone.

* **CircuitBreaker** — per-tenant, classic three-state: `closed` →
  (k consecutive tenant faults) → `open` (requests fast-fail into the
  replay buffer, no compute) → (cooldown requests) → `half_open` (one
  probe allowed; success closes via the recovery reconcile, failure
  re-opens).

* **RetryPolicy** — exponential backoff with DETERMINISTIC jitter: the
  jitter fraction is sha256(key:attempt), so a chaos run's retry timing
  is reproducible bit-for-bit while distinct tenants still decorrelate.

* **Deadline** — a started wall-clock budget; `exceeded()` probes are
  placed at admission and immediately before any state commit, so a
  blown deadline can never half-apply a tick.

* **WorkerSupervisor** — the per-worker liveness state machine behind
  `TenantRouter`'s process supervision (docs/robustness.md, worker
  supervision):  ``healthy → suspect → dead → respawning → recovering
  → healthy``.  The supervisor itself is pure bookkeeping plus
  telemetry (``serving.worker.*`` gauges, detect-latency and RTO
  histograms); the router drives the transitions from its
  deadline-bounded RPC layer and performs the actual reap / respawn /
  `engine.recover()` work.
"""

from __future__ import annotations

import hashlib
import time
from typing import Any, NamedTuple

from ..utils.telemetry import gauge_set, inc, register_hist, trace_event

__all__ = [
    "CLIENT_ERROR",
    "TENANT_FAULT",
    "SYSTEM_FAULT",
    "BREAKER_CLOSED",
    "BREAKER_OPEN",
    "BREAKER_HALF_OPEN",
    "WORKER_HEALTHY",
    "WORKER_SUSPECT",
    "WORKER_DEAD",
    "WORKER_RESPAWNING",
    "WORKER_RECOVERING",
    "WORKER_STATES",
    "ErrorInfo",
    "Response",
    "CircuitBreaker",
    "RetryPolicy",
    "Deadline",
    "WorkerSupervisor",
    "call_with_retries",
]

CLIENT_ERROR = "client_error"
TENANT_FAULT = "tenant_fault"
SYSTEM_FAULT = "system_fault"

BREAKER_CLOSED = "closed"
BREAKER_OPEN = "open"
BREAKER_HALF_OPEN = "half_open"


class ErrorInfo(NamedTuple):
    """One classified failure.  `category` is the taxonomy bucket above;
    `code` the machine-readable cause (e.g. ``missing_field``,
    ``nonfinite_state``, ``deadline_exceeded``, ``store_io``);
    `field` names the offending request field for client errors."""

    category: str
    code: str
    message: str
    field: str | None = None


class Response(NamedTuple):
    """Typed envelope for one serving request (or one refit flush).

    `ok` is True when `result` holds the requested answer; False means
    `error` explains why (and for a degraded nowcast, `result` may
    STILL carry the stale answer — check `degraded`).  `ticks_behind`
    counts the tenant's buffered-but-unapplied ticks at response time;
    `retries` how many transient-fault retries the request consumed;
    `breaker_state` the tenant's breaker after the request; `recovered`
    flags a response whose handling completed a recovery reconcile.
    `info` carries per-kind extras (flush retry/permanent lists)."""

    ok: bool
    kind: str
    tenant: str | None
    result: Any = None
    error: ErrorInfo | None = None
    degraded: bool = False
    ticks_behind: int = 0
    retries: int = 0
    breaker_state: str = BREAKER_CLOSED
    recovered: bool = False
    info: dict | None = None


class CircuitBreaker:
    """Per-tenant three-state breaker over CONSECUTIVE tenant faults.

    `threshold` consecutive faults open the breaker; while open, each
    observed request decrements a cooldown of `cooldown` requests, after
    which the breaker half-opens and admits exactly one probe.  A
    successful probe (the engine's recovery reconcile) closes it; a
    failed probe re-opens with a fresh cooldown.  Client errors must not
    be recorded here — only genuine tenant faults."""

    __slots__ = ("threshold", "cooldown", "state", "consecutive",
                 "_cooldown_left", "opens")

    def __init__(self, threshold: int = 3, cooldown: int = 4):
        if threshold < 1 or cooldown < 1:
            raise ValueError("breaker threshold and cooldown must be >= 1")
        self.threshold = threshold
        self.cooldown = cooldown
        self.state = BREAKER_CLOSED
        self.consecutive = 0
        self._cooldown_left = 0
        self.opens = 0  # lifetime open transitions (telemetry)

    def _transition(self, new_state: str) -> None:
        """Every state change lands in metrics and the active span tree
        (``serving.breaker.transitions{state="..."}``) — recovery is
        visible without reading logs."""
        self.state = new_state
        inc(f'serving.breaker.transitions{{state="{new_state}"}}')
        trace_event("breaker.transition", state=new_state)

    def on_request(self) -> str:
        """Observe one request against this tenant; while open, burn one
        cooldown slot and half-open when it reaches zero.  Returns the
        state the request should be admitted under."""
        if self.state == BREAKER_OPEN:
            self._cooldown_left -= 1
            if self._cooldown_left <= 0:
                self._transition(BREAKER_HALF_OPEN)
        return self.state

    def record_success(self) -> None:
        self.consecutive = 0
        if self.state != BREAKER_CLOSED:
            self._transition(BREAKER_CLOSED)

    def record_fault(self) -> None:
        self.consecutive += 1
        if self.state == BREAKER_HALF_OPEN or (
            self.state == BREAKER_CLOSED
            and self.consecutive >= self.threshold
        ):
            self._transition(BREAKER_OPEN)
            self._cooldown_left = self.cooldown
            self.opens += 1

    # -- snapshot persistence (serving/store.TenantState.breaker) --------

    _STATE_CODES = (BREAKER_CLOSED, BREAKER_OPEN, BREAKER_HALF_OPEN)

    def pack(self):
        """The breaker's position as int32 ``(state_code, consecutive,
        cooldown_left)`` — the leaf `TenantState` persists so eviction /
        restart RESTORES the breaker instead of silently re-closing it
        (docs/serving.md, breaker x eviction)."""
        import numpy as np

        return np.asarray(
            [self._STATE_CODES.index(self.state), self.consecutive,
             max(self._cooldown_left, 0)],
            np.int32,
        )

    @classmethod
    def from_packed(cls, threshold: int, cooldown: int, packed):
        """Rebuild a breaker from a packed snapshot leaf.  Anything that
        is not a 3-vector (the scalar default of a hand-built or legacy
        TenantState) yields a fresh closed breaker.  Restoring does NOT
        re-emit transition metrics — the state change happened in a past
        process."""
        import numpy as np

        b = cls(threshold, cooldown)
        arr = np.asarray(packed).ravel()
        if arr.size != 3:
            return b
        code = int(arr[0])
        if 0 <= code < len(cls._STATE_CODES):
            b.state = cls._STATE_CODES[code]
        b.consecutive = int(arr[1])
        b._cooldown_left = int(arr[2])
        return b


class RetryPolicy(NamedTuple):
    """Bounded exponential backoff with deterministic jitter.

    Attempt a's delay is ``min(cap, base * 2**a) * (0.5 + 0.5 * u)``
    with ``u = sha256(key:a) / 2**64`` — reproducible for a given
    (key, attempt), decorrelated across tenants.  ``base=0`` (the test
    configuration) makes every delay exactly zero."""

    max_retries: int = 2
    backoff_base_s: float = 0.02
    backoff_cap_s: float = 0.25

    def delay_s(self, key: str, attempt: int) -> float:
        base = min(self.backoff_cap_s, self.backoff_base_s * (2.0 ** attempt))
        if base <= 0.0:
            return 0.0
        h = hashlib.sha256(f"{key}:{attempt}".encode()).digest()
        u = int.from_bytes(h[:8], "big") / float(1 << 64)
        return base * (0.5 + 0.5 * u)


WORKER_HEALTHY = "healthy"
WORKER_SUSPECT = "suspect"
WORKER_DEAD = "dead"
WORKER_RESPAWNING = "respawning"
WORKER_RECOVERING = "recovering"

# ordinal codes: what the `serving.worker.state{worker="i"}` gauge
# carries and what `TenantState`-style packing would use — the ORDER is
# the lifecycle order and is part of the telemetry contract
WORKER_STATES = (
    WORKER_HEALTHY, WORKER_SUSPECT, WORKER_DEAD,
    WORKER_RESPAWNING, WORKER_RECOVERING,
)


class WorkerSupervisor:
    """Liveness state machine for M router workers.

    One instance tracks every worker's lifecycle position::

        healthy --deadline missed--> suspect --confirmed--> dead
        healthy --pipe EOF / SIGKILL observed--------------> dead
        suspect --late reply arrived-----------------------> healthy
        dead --router spawns a fresh process---> respawning
        respawning --ping answered, recover() driven--> recovering
        respawning / recovering --died again--> dead   (double kill)
        recovering --first successful client ack--> healthy

    The supervisor records, per worker: death and respawn counts, the
    detect latency (first missed observation → declared dead; bounded
    by the router's heartbeat deadline), and the RTO (first missed
    observation → first successful ack from the respawned worker, i.e.
    detect→respawn→recover→first-ack).  Every transition lands in the
    metrics registry (``serving.worker.state{worker="i"}`` gauge with
    the `WORKER_STATES` ordinal, a ``serving.worker.transitions``
    counter per target state) and the active span tree; detect latency
    and RTO feed ``serving.worker.detect_latency`` / RTO histograms and
    last-value gauges so `summarize` can render the worker column
    without a live process."""

    def __init__(self, n_workers: int):
        if n_workers < 1:
            raise ValueError("n_workers must be >= 1")
        self.n_workers = int(n_workers)
        self._states = [WORKER_HEALTHY] * self.n_workers
        self.deaths = [0] * self.n_workers
        self.respawns = [0] * self.n_workers
        self.detect_s = [None] * self.n_workers   # last detect latency
        self.rto_s = [None] * self.n_workers      # last full RTO
        self._t_fail = [None] * self.n_workers    # first missed obs
        self._h_detect = register_hist(
            "serving.worker.detect_latency", entry="serving"
        )
        self._h_rto = register_hist("serving.worker.rto", entry="serving")
        for w in range(self.n_workers):
            gauge_set(f'serving.worker.state{{worker="{w}"}}', 0)

    def state(self, w: int) -> str:
        return self._states[w]

    def all_healthy(self) -> bool:
        return all(s == WORKER_HEALTHY for s in self._states)

    def _transition(self, w: int, new_state: str) -> None:
        self._states[w] = new_state
        gauge_set(
            f'serving.worker.state{{worker="{w}"}}',
            WORKER_STATES.index(new_state),
        )
        inc(f'serving.worker.transitions{{state="{new_state}"}}')
        trace_event("worker.transition", worker=w, state=new_state)

    # -- transitions driven by the router's RPC layer --------------------

    def mark_suspect(self, w: int) -> None:
        """An RPC deadline expired: the worker may be stalled or dead.
        Stamps the first-missed-observation clock that detect latency
        and RTO are measured from (kept across suspect→dead)."""
        if self._t_fail[w] is None:
            self._t_fail[w] = time.perf_counter()
        if self._states[w] == WORKER_HEALTHY:
            self._transition(w, WORKER_SUSPECT)

    def mark_healthy_probe(self, w: int) -> None:
        """A suspect worker answered after all (late reply): false
        alarm, back to healthy, failure clock cleared."""
        self._t_fail[w] = None
        if self._states[w] == WORKER_SUSPECT:
            self._transition(w, WORKER_HEALTHY)

    def mark_dead(self, w: int, reason: str = "unknown") -> float:
        """Confirm death (pipe EOF, kill observed, or grace expired).
        Returns the detect latency in seconds — 0.0 for an instantly
        observable death (EOF arrives with no deadline wait)."""
        if self._t_fail[w] is None:
            self._t_fail[w] = time.perf_counter()
            detect = 0.0
        else:
            detect = time.perf_counter() - self._t_fail[w]
        self.detect_s[w] = detect
        self.deaths[w] += 1
        self._h_detect.record(detect)
        gauge_set(f'serving.worker.detect_s{{worker="{w}"}}', detect)
        inc("serving.worker.deaths")
        inc(f'serving.worker.deaths{{reason="{reason}"}}')
        self._transition(w, WORKER_DEAD)
        return detect

    def mark_respawning(self, w: int) -> None:
        self.respawns[w] += 1
        inc("serving.worker.respawns")
        self._transition(w, WORKER_RESPAWNING)

    def mark_recovering(self, w: int) -> None:
        self._transition(w, WORKER_RECOVERING)

    def mark_first_ack(self, w: int) -> None:
        """First successful client-facing ack from the respawned worker
        closes the loop: stamp the RTO and return to healthy.  Also the
        no-op fast path (`healthy` stays `healthy`) so the router can
        call it on every successful RPC."""
        if self._states[w] == WORKER_HEALTHY:
            return
        if self._states[w] == WORKER_SUSPECT:
            self.mark_healthy_probe(w)
            return
        if self._t_fail[w] is not None:
            rto = time.perf_counter() - self._t_fail[w]
            self.rto_s[w] = rto
            self._h_rto.record(rto)
            gauge_set(f'serving.worker.rto_s{{worker="{w}"}}', rto)
            self._t_fail[w] = None
        self._transition(w, WORKER_HEALTHY)


class Deadline:
    """A started wall-clock budget.  `budget_s=None` never expires."""

    __slots__ = ("budget_s", "_t0")

    def __init__(self, budget_s: float | None):
        self.budget_s = None if budget_s is None else float(budget_s)
        self._t0 = time.perf_counter()

    def elapsed_s(self) -> float:
        return time.perf_counter() - self._t0

    def exceeded(self) -> bool:
        return self.budget_s is not None and self.elapsed_s() > self.budget_s

    def expire(self) -> None:
        """Force the budget spent — the ``slow_req@n`` injection models a
        stall past the deadline without actually sleeping the budget (a
        None budget stays un-expirable: no deadline means no stall)."""
        self._t0 = float("-inf")


def call_with_retries(
    fn,
    policy: RetryPolicy,
    key: str,
    retryable: tuple = (OSError,),
    deadline: Deadline | None = None,
    sleep=time.sleep,
):
    """Run `fn()` with up to `policy.max_retries` retries on `retryable`
    exceptions, backing off per `policy.delay_s(key, attempt)`.

    Returns ``(result, retries_used)``.  A deadline cuts retrying short:
    once exceeded, the last exception propagates to the caller (which
    classifies it) rather than burning further attempts.  Non-retryable
    exceptions propagate immediately with zero extra attempts."""
    attempt = 0
    while True:
        try:
            return fn(), attempt
        except retryable:
            if attempt >= policy.max_retries or (
                deadline is not None and deadline.exceeded()
            ):
                raise
            trace_event("retry", key=key, attempt=attempt)
            sleep(policy.delay_s(key, attempt))
            attempt += 1
