"""Request-hardening primitives for the serving engine.

The serving loop's availability contract (docs/serving.md): every
request gets a TYPED response — never an uncaught exception — and a
faulted tenant degrades instead of taking the engine down.  This module
supplies the host-side pieces the engine composes; nothing here touches
a device program, so the tick/nowcast HLO stays byte-identical to the
pre-hardening build (pinned by tests/test_serving.py).

* **Error taxonomy** — every failure is classified into one of three
  CATEGORIES, each with a machine-readable CODE:

  - ``client_error``: the request itself is wrong (missing field, bad
    shape, unknown tenant/kind).  Never retried, never counts against a
    tenant's circuit breaker.
  - ``tenant_fault``: this tenant's serving state is unhealthy (a
    non-finite tick result, an open breaker).  The tick lands in the
    tenant's replay buffer; nowcasts degrade to last-good state; other
    tenants are unaffected.
  - ``system_fault``: the engine's own machinery failed (store I/O,
    deadline blown, unexpected exception).  Transient system faults are
    retried with bounded exponential backoff before surfacing.

* **Response envelope** — a NamedTuple carrying the result OR an
  `ErrorInfo`, plus the staleness stamp (`degraded`, `ticks_behind`),
  the retry count, and the tenant's breaker state, so a caller — or the
  chaos harness — can compute availability from responses alone.

* **CircuitBreaker** — per-tenant, classic three-state: `closed` →
  (k consecutive tenant faults) → `open` (requests fast-fail into the
  replay buffer, no compute) → (cooldown requests) → `half_open` (one
  probe allowed; success closes via the recovery reconcile, failure
  re-opens).

* **RetryPolicy** — exponential backoff with DETERMINISTIC jitter: the
  jitter fraction is sha256(key:attempt), so a chaos run's retry timing
  is reproducible bit-for-bit while distinct tenants still decorrelate.

* **Deadline** — a started wall-clock budget; `exceeded()` probes are
  placed at admission and immediately before any state commit, so a
  blown deadline can never half-apply a tick.
"""

from __future__ import annotations

import hashlib
import time
from typing import Any, NamedTuple

from ..utils.telemetry import inc, trace_event

__all__ = [
    "CLIENT_ERROR",
    "TENANT_FAULT",
    "SYSTEM_FAULT",
    "BREAKER_CLOSED",
    "BREAKER_OPEN",
    "BREAKER_HALF_OPEN",
    "ErrorInfo",
    "Response",
    "CircuitBreaker",
    "RetryPolicy",
    "Deadline",
    "call_with_retries",
]

CLIENT_ERROR = "client_error"
TENANT_FAULT = "tenant_fault"
SYSTEM_FAULT = "system_fault"

BREAKER_CLOSED = "closed"
BREAKER_OPEN = "open"
BREAKER_HALF_OPEN = "half_open"


class ErrorInfo(NamedTuple):
    """One classified failure.  `category` is the taxonomy bucket above;
    `code` the machine-readable cause (e.g. ``missing_field``,
    ``nonfinite_state``, ``deadline_exceeded``, ``store_io``);
    `field` names the offending request field for client errors."""

    category: str
    code: str
    message: str
    field: str | None = None


class Response(NamedTuple):
    """Typed envelope for one serving request (or one refit flush).

    `ok` is True when `result` holds the requested answer; False means
    `error` explains why (and for a degraded nowcast, `result` may
    STILL carry the stale answer — check `degraded`).  `ticks_behind`
    counts the tenant's buffered-but-unapplied ticks at response time;
    `retries` how many transient-fault retries the request consumed;
    `breaker_state` the tenant's breaker after the request; `recovered`
    flags a response whose handling completed a recovery reconcile.
    `info` carries per-kind extras (flush retry/permanent lists)."""

    ok: bool
    kind: str
    tenant: str | None
    result: Any = None
    error: ErrorInfo | None = None
    degraded: bool = False
    ticks_behind: int = 0
    retries: int = 0
    breaker_state: str = BREAKER_CLOSED
    recovered: bool = False
    info: dict | None = None


class CircuitBreaker:
    """Per-tenant three-state breaker over CONSECUTIVE tenant faults.

    `threshold` consecutive faults open the breaker; while open, each
    observed request decrements a cooldown of `cooldown` requests, after
    which the breaker half-opens and admits exactly one probe.  A
    successful probe (the engine's recovery reconcile) closes it; a
    failed probe re-opens with a fresh cooldown.  Client errors must not
    be recorded here — only genuine tenant faults."""

    __slots__ = ("threshold", "cooldown", "state", "consecutive",
                 "_cooldown_left", "opens")

    def __init__(self, threshold: int = 3, cooldown: int = 4):
        if threshold < 1 or cooldown < 1:
            raise ValueError("breaker threshold and cooldown must be >= 1")
        self.threshold = threshold
        self.cooldown = cooldown
        self.state = BREAKER_CLOSED
        self.consecutive = 0
        self._cooldown_left = 0
        self.opens = 0  # lifetime open transitions (telemetry)

    def _transition(self, new_state: str) -> None:
        """Every state change lands in metrics and the active span tree
        (``serving.breaker.transitions{state="..."}``) — recovery is
        visible without reading logs."""
        self.state = new_state
        inc(f'serving.breaker.transitions{{state="{new_state}"}}')
        trace_event("breaker.transition", state=new_state)

    def on_request(self) -> str:
        """Observe one request against this tenant; while open, burn one
        cooldown slot and half-open when it reaches zero.  Returns the
        state the request should be admitted under."""
        if self.state == BREAKER_OPEN:
            self._cooldown_left -= 1
            if self._cooldown_left <= 0:
                self._transition(BREAKER_HALF_OPEN)
        return self.state

    def record_success(self) -> None:
        self.consecutive = 0
        if self.state != BREAKER_CLOSED:
            self._transition(BREAKER_CLOSED)

    def record_fault(self) -> None:
        self.consecutive += 1
        if self.state == BREAKER_HALF_OPEN or (
            self.state == BREAKER_CLOSED
            and self.consecutive >= self.threshold
        ):
            self._transition(BREAKER_OPEN)
            self._cooldown_left = self.cooldown
            self.opens += 1

    # -- snapshot persistence (serving/store.TenantState.breaker) --------

    _STATE_CODES = (BREAKER_CLOSED, BREAKER_OPEN, BREAKER_HALF_OPEN)

    def pack(self):
        """The breaker's position as int32 ``(state_code, consecutive,
        cooldown_left)`` — the leaf `TenantState` persists so eviction /
        restart RESTORES the breaker instead of silently re-closing it
        (docs/serving.md, breaker x eviction)."""
        import numpy as np

        return np.asarray(
            [self._STATE_CODES.index(self.state), self.consecutive,
             max(self._cooldown_left, 0)],
            np.int32,
        )

    @classmethod
    def from_packed(cls, threshold: int, cooldown: int, packed):
        """Rebuild a breaker from a packed snapshot leaf.  Anything that
        is not a 3-vector (the scalar default of a hand-built or legacy
        TenantState) yields a fresh closed breaker.  Restoring does NOT
        re-emit transition metrics — the state change happened in a past
        process."""
        import numpy as np

        b = cls(threshold, cooldown)
        arr = np.asarray(packed).ravel()
        if arr.size != 3:
            return b
        code = int(arr[0])
        if 0 <= code < len(cls._STATE_CODES):
            b.state = cls._STATE_CODES[code]
        b.consecutive = int(arr[1])
        b._cooldown_left = int(arr[2])
        return b


class RetryPolicy(NamedTuple):
    """Bounded exponential backoff with deterministic jitter.

    Attempt a's delay is ``min(cap, base * 2**a) * (0.5 + 0.5 * u)``
    with ``u = sha256(key:a) / 2**64`` — reproducible for a given
    (key, attempt), decorrelated across tenants.  ``base=0`` (the test
    configuration) makes every delay exactly zero."""

    max_retries: int = 2
    backoff_base_s: float = 0.02
    backoff_cap_s: float = 0.25

    def delay_s(self, key: str, attempt: int) -> float:
        base = min(self.backoff_cap_s, self.backoff_base_s * (2.0 ** attempt))
        if base <= 0.0:
            return 0.0
        h = hashlib.sha256(f"{key}:{attempt}".encode()).digest()
        u = int.from_bytes(h[:8], "big") / float(1 << 64)
        return base * (0.5 + 0.5 * u)


class Deadline:
    """A started wall-clock budget.  `budget_s=None` never expires."""

    __slots__ = ("budget_s", "_t0")

    def __init__(self, budget_s: float | None):
        self.budget_s = None if budget_s is None else float(budget_s)
        self._t0 = time.perf_counter()

    def elapsed_s(self) -> float:
        return time.perf_counter() - self._t0

    def exceeded(self) -> bool:
        return self.budget_s is not None and self.elapsed_s() > self.budget_s

    def expire(self) -> None:
        """Force the budget spent — the ``slow_req@n`` injection models a
        stall past the deadline without actually sleeping the budget (a
        None budget stays un-expirable: no deadline means no stall)."""
        self._t0 = float("-inf")


def call_with_retries(
    fn,
    policy: RetryPolicy,
    key: str,
    retryable: tuple = (OSError,),
    deadline: Deadline | None = None,
    sleep=time.sleep,
):
    """Run `fn()` with up to `policy.max_retries` retries on `retryable`
    exceptions, backing off per `policy.delay_s(key, attempt)`.

    Returns ``(result, retries_used)``.  A deadline cuts retrying short:
    once exceeded, the last exception propagates to the caller (which
    classifies it) rather than burning further attempts.  Non-retryable
    exceptions propagate immediately with zero extra attempts."""
    attempt = 0
    while True:
        try:
            return fn(), attempt
        except retryable:
            if attempt >= policy.max_retries or (
                deadline is not None and deadline.exceeded()
            ):
                raise
            trace_event("retry", key=key, attempt=attempt)
            sleep(policy.delay_s(key, attempt))
            attempt += 1
