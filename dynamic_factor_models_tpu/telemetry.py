"""Alias + CLI entry for the telemetry layer (implementation:
utils/telemetry.py).

    python -m dynamic_factor_models_tpu.telemetry summarize run.jsonl

renders per-run and aggregate tables from a ``DFM_TELEMETRY`` JSONL file;
``--entry`` filters to one entry point, ``--json`` dumps raw records.
"""

from .utils.telemetry import *  # noqa: F401,F403
from .utils.telemetry import main

if __name__ == "__main__":
    import sys

    sys.exit(main())
