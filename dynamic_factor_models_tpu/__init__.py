"""TPU-native dynamic factor model framework (JAX / XLA / pjit)."""

__version__ = "0.1.0"
