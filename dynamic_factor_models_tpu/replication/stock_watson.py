"""Stock-Watson (2016) replication driver: Figures 1-7 and Tables 2-5 as data.

Mirrors the reference driver notebook (Stock_Watson.ipynb) end to end on this
framework.  Each function returns plain arrays/dicts (plotting left to the
caller); `run_all` produces the complete replication bundle.  Golden values
for the committed notebook outputs are asserted in tests/ (BASELINE.md).

Benchmark hyperparameters (driver cell 15): nt_min_fe=20, nt_min_fle=40,
nfac_o=0, nfac_u=1, n_uarlag=4, n_factorlag=4, tol=1e-8.
"""

from __future__ import annotations

import dataclasses
import os

import jax.numpy as jnp
import numpy as np

from ..io import find_row_number
from ..io.cache import benchmark_ingest, cached_dataset
from ..models.constraints import construct_constraint
from ..models.dfm import (
    DFMConfig,
    compute_series,
    estimate_dfm,
    estimate_factor,
    estimate_factor_batch,
)
from ..models.favar_instruments import choose_stepwise, favar_instrument_table
from ..models.instability import instability_scan
from ..models.selection import ahn_horenstein_er, estimate_factor_numbers
from ..ops.filters import (
    baxter_king_lowpass_weight,
    compute_bw_weight,
    compute_gain,
    hp_trend_weight,
    ma_weight,
)
from ..ops.lags import detrended_year_growth

BENCHMARK_CONFIG = DFMConfig(
    nfac_u=1, nfac_o=0, nt_min_factor=20, nt_min_loading=40,
    tol=1e-8, n_uarlag=4, n_factorlag=4,
)

PERIODS_ALL = ((1959, 3), (2014, 4))
PERIODS_PRE = ((1959, 3), (1983, 4))
PERIODS_POST = ((1984, 1), (2014, 4))


def load_datasets(path: str | None = None):
    """Both datasets with the driver's ingest settings (cells 6-10)."""
    if path is None:
        return cached_dataset("Real"), cached_dataset("All")
    return benchmark_ingest("Real", path=path), benchmark_ingest("All", path=path)


def _window(ds, periods):
    return (
        find_row_number(periods[0], ds.calds),
        find_row_number(periods[1], ds.calds),
    )


def figure1(ds, config: DFMConfig = BENCHMARK_CONFIG):
    """4-quarter growth of GDP/IP/employment/sales vs 1-factor common
    component (cells 13-24)."""
    i0, i1 = _window(ds, PERIODS_ALL)
    res = estimate_dfm(ds.bpdata, ds.inclcode, i0, i1, config)
    names = ["GDPC96", "INDPRO", "PAYEMS", "A0M057"]
    out = {}
    for name in names:
        i = ds.bpnamevec.index(name)
        yf = compute_series(res, i)
        out[name] = {
            "actual": 100 * np.asarray(detrended_year_growth(jnp.asarray(ds.bpdata[:, i]))),
            "common": 100 * np.asarray(detrended_year_growth(yf)),
        }
    return {"year": np.asarray(ds.calvec), "series": out}


def figure2(hp_weight_path: str | None = None):
    """Filter weights and spectral gains (cell 26).

    The reference ships the HP-filter weights as precomputed data
    (data/hpfilter_trend.asc); here they are computed directly
    (`ops.filters.hp_trend_weight`, matches the file to its 6-decimal
    precision).  Pass hp_weight_path (or set DFM_HP_WEIGHTS_PATH) to use a
    weight file instead.
    """
    maxlag = 100
    wvec = np.linspace(0.0, np.pi, 500)
    weights = {
        "biweight": np.asarray(compute_bw_weight(maxlag)),
        "ma40": np.asarray(ma_weight(maxlag, 40)),
        "bandpass": np.asarray(baxter_king_lowpass_weight(maxlag)),
    }
    hp_weight_path = hp_weight_path or os.environ.get("DFM_HP_WEIGHTS_PATH")
    if hp_weight_path is not None:
        weights["hp"] = np.loadtxt(hp_weight_path)
    else:
        weights["hp"] = np.asarray(hp_trend_weight(maxlag))
    gains = {
        k: np.asarray(compute_gain(jnp.asarray(w), jnp.asarray(wvec)))
        for k, w in weights.items()
    }
    return {"laglead": np.arange(-maxlag, maxlag + 1), "weights": weights,
            "frequencies": wvec, "gains": gains}


def table2(ds_real, ds_all, config: DFMConfig = BENCHMARK_CONFIG,
           max_nfac_a: int = 6, max_nfac_b: int = 11, dynamic: bool = True):
    """Factor-number statistics: panels A (:Real), B (:All), C (AW)
    (cells 29-39)."""
    i0, i1 = _window(ds_real, PERIODS_ALL)
    fa = estimate_factor_numbers(
        ds_real.bpdata, ds_real.inclcode, i0, i1, config, max_nfac_a, dynamic=dynamic
    )
    fb = estimate_factor_numbers(
        ds_all.bpdata, ds_all.inclcode, i0, i1, config, max_nfac_b, dynamic=dynamic
    )
    return {
        "A": {"trace_r2": fa.trace_r2, "marginal_r2": fa.marginal_r2,
              "bn_icp": fa.bn_icp, "ah_er": ahn_horenstein_er(fa.marginal_r2)},
        "B": {"trace_r2": fb.trace_r2, "marginal_r2": fb.marginal_r2,
              "bn_icp": fb.bn_icp, "ah_er": ahn_horenstein_er(fb.marginal_r2)},
        "C": {"aw_icp": fb.aw_icp},
    }


def figure4(ds, config: DFMConfig = BENCHMARK_CONFIG, nfacs=(1, 3, 5)):
    """GDP common component for r in {1,3,5} (cells 41-43)."""
    i0, i1 = _window(ds, PERIODS_ALL)
    i = ds.bpnamevec.index("GDPC96")
    out = {"year": np.asarray(ds.calvec),
           "gdp_growth": np.asarray(detrended_year_growth(jnp.asarray(ds.bpdata[:, i])))}
    for nf in nfacs:
        res = estimate_dfm(ds.bpdata, ds.inclcode, i0, i1,
                           dataclasses.replace(config, nfac_u=nf))
        out[f"common_r{nf}"] = np.asarray(detrended_year_growth(compute_series(res, i)))
    return out


def normalize_split_sample(fac_full: np.ndarray, fac_sub: np.ndarray) -> np.ndarray:
    """Rescale a subsample factor to the full-sample factor's STD over the
    subsample's support; the subsample mean is kept (cell 45 does the same —
    it re-adds m_p, not m_f — so this is deliberate parity, not a bug)."""
    m = np.isfinite(fac_sub)
    sf = np.nanstd(fac_full[m], ddof=1)
    mp, sp = np.nanmean(fac_sub[m]), np.nanstd(fac_sub[m], ddof=1)
    out = fac_sub.copy()
    out[m] = (fac_sub[m] - mp) * sf / sp + mp
    return out


def figure5(ds, config: DFMConfig = BENCHMARK_CONFIG):
    """First factor: full vs pre-84 vs post-84 estimates (cells 45-47)."""
    facs = []
    for periods in (PERIODS_ALL, PERIODS_PRE, PERIODS_POST):
        i0, i1 = _window(ds, periods)
        F, _ = estimate_factor(ds.bpdata, ds.inclcode, i0, i1, config)
        facs.append(np.asarray(F[:, 0]))
    f_full, f_pre, f_post = facs
    f_pre = normalize_split_sample(f_full, f_pre)
    f_post = normalize_split_sample(f_full, f_post)
    out = {
        k: -np.asarray(detrended_year_growth(jnp.asarray(v)))
        for k, v in {"full": f_full, "pre": f_pre, "post": f_post}.items()
    }
    out["year"] = np.asarray(ds.calvec)
    return out


def figure6(ds_all, config: DFMConfig = BENCHMARK_CONFIG, max_r: int = 60):
    """Cumulative trace R^2 for r = 1..max_r, single ALS iteration
    (cells 49-53; 180 model fits in the reference — here one batched ALS
    per sample window via `estimate_factor_batch`)."""
    out = {}
    incl = np.asarray(ds_all.inclcode)
    data = np.asarray(ds_all.bpdata)
    for label, periods in (("all", PERIODS_ALL), ("pre", PERIODS_PRE),
                           ("post", PERIODS_POST)):
        i0, i1 = _window(ds_all, periods)
        est = data[:, incl == 1][i0 : i1 + 1]
        nbal = int((~np.isnan(est)).all(axis=0).sum())
        rs = [r for r in range(1, max_r + 1) if r <= nbal]
        tr = np.full(max_r, np.nan)  # r beyond the balanced block stays NaN
        if rs:
            batch = estimate_factor_batch(
                [(data, incl, i0, i1, r) for r in rs], config, max_iter=1,
                compute_R2=False,
            )
            tr[np.asarray(rs) - 1] = 1.0 - np.asarray(batch.ssr) / np.asarray(
                batch.tss
            )
        out[label] = tr
    return out


def table3(ds_all, config: DFMConfig = BENCHMARK_CONFIG, nfac_max: int = 10):
    """Per-series R^2 vs number of factors (cell 55; 207 x 10).

    Factors for every r come from one batched ALS; the (cheap, already
    series-batched) loading regressions then run per r."""
    from ..models.dfm import estimate_factor_loading

    i0, i1 = _window(ds_all, PERIODS_ALL)
    batch = estimate_factor_batch(
        [(ds_all.bpdata, ds_all.inclcode, i0, i1, r) for r in range(1, nfac_max + 1)],
        config,
    )
    r2 = np.full((len(ds_all.inclcode), nfac_max), np.nan)
    for i, nfac in enumerate(range(1, nfac_max + 1)):
        _, r2_i, _, _, _ = estimate_factor_loading(
            ds_all.bpdata, batch.factor[i][:, :nfac], i0, i1,
            dataclasses.replace(config, nfac_u=nfac),
        )
        r2[:, i] = np.asarray(r2_i)
    return r2


def table4(ds_all, config: DFMConfig = BENCHMARK_CONFIG, nfac_us=(4, 8)):
    """Instability statistics (cell 57)."""
    i0, i1 = _window(ds_all, PERIODS_ALL)
    ibrk = find_row_number((1984, 4), ds_all.calds)
    out = {}
    for nfac in nfac_us:
        cfg = dataclasses.replace(config, nfac_u=nfac)
        F_full, _ = estimate_factor(ds_all.bpdata, ds_all.inclcode, i0, i1, cfg)
        F_pre, _ = estimate_factor(ds_all.bpdata, ds_all.inclcode, i0, ibrk, cfg)
        F_post, _ = estimate_factor(ds_all.bpdata, ds_all.inclcode, ibrk + 1, i1, cfg)
        out[nfac] = instability_scan(
            ds_all.bpdata, F_full, F_pre, F_post, ibrk + 1, nfac
        )
    return out


def table5(ds_all, config: DFMConfig = BENCHMARK_CONFIG, stepwise: bool = True):
    """FAVAR instrument canonical correlations (cells 60-61)."""
    i0, i1 = _window(ds_all, PERIODS_ALL)
    res = estimate_dfm(ds_all.bpdata, ds_all.inclcode, i0, i1,
                       dataclasses.replace(config, nfac_u=8))
    sets = {
        "A": ["GDPC96", "PAYEMS", "PCECTPI", "FEDFUNDS"],
        "B": ["GDPC96", "PAYEMS", "PCECTPI", "FEDFUNDS",
              "NAPMPRI", "WPU0561", "CP90_TBILL", "GS10_TB3M"],
        "O": ["OILPROD_SA", "GLOBAL_ACT", "WPU0561", "GDPC96",
              "PAYEMS", "PCECTPI", "FEDFUNDS", "TWEXMMTH"],
    }
    if stepwise:
        sets["C"] = choose_stepwise(
            ds_all.bpdata, ds_all.bpnamevec, res.factor, res.var, 8, 4, i0, i1
        )
    out = {}
    for key, names in sets.items():
        r_res, r_lev = favar_instrument_table(
            ds_all.bpdata, ds_all.bpnamevec, names, res.factor, res.var, 4, i0, i1
        )
        out[key] = {"variables": names, "residual_cca": r_res, "level_cca": r_lev}
    return out


def figure7(ds_all, config: DFMConfig = BENCHMARK_CONFIG):
    """Oil-price DFM with unit-loading constraint, post-85, r=8
    (cells 63-65)."""
    i0 = find_row_number((1985, 1), ds_all.calds)
    i1 = find_row_number((2014, 4), ds_all.calds)
    nfac = 8
    varnames = ["WPU0561", "MCOILWTICO", "MCOILBRENTEU", "RAC_IMP"]
    incl_names = [n for n, c in zip(ds_all.bpnamevec, ds_all.inclcode) if c == 1]
    R = np.eye(nfac)
    r = np.eye(nfac)[0]
    res = estimate_dfm(
        ds_all.bpdata, ds_all.inclcode, i0, i1,
        dataclasses.replace(config, nfac_u=nfac),
        constraint_factor=construct_constraint(varnames, incl_names, R, r),
        constraint_loading=construct_constraint(varnames, ds_all.bpnamevec, R, r),
    )
    oil_ids = [ds_all.bpnamevec.index(v) for v in varnames]
    return {
        "year": np.asarray(ds_all.calvec),
        "oil_prices": 400 * np.asarray(ds_all.bpdata)[:, oil_ids],
        "common_component": 400 * np.asarray(compute_series(res, oil_ids[0])),
        "names": varnames,
    }


def run_all(fast: bool = True, path: str | None = None) -> dict:
    """Full replication bundle.  fast=True trims the heaviest sweeps
    (Table 2 AW refits, Figure 6 r<=60, stepwise Table 5 column)."""
    ds_real, ds_all = load_datasets(path)
    return {
        "figure1": figure1(ds_real),
        "figure2": figure2(),
        "table2": table2(ds_real, ds_all,
                         max_nfac_a=6, max_nfac_b=11 if not fast else 6,
                         dynamic=not fast),
        "figure4": figure4(ds_real),
        "figure5": figure5(ds_real),
        "figure6": figure6(ds_all, max_r=10 if fast else 60),
        "table3": table3(ds_all, nfac_max=4 if fast else 10),
        "table4": table4(ds_all, nfac_us=(4,) if fast else (4, 8)),
        "table5": table5(ds_all, stepwise=not fast),
        "figure7": figure7(ds_all),
    }
