"""CLI replication driver: `python -m dynamic_factor_models_tpu.replication`.

The reference's driver is a notebook run by hand (Stock_Watson.ipynb); this
is the framework equivalent — one command reproduces Figures 1-7 and
Tables 2-5 from the xlsx, writing PNG figures and a JSON table bundle.

    python -m dynamic_factor_models_tpu.replication --out ./replication_out
    python -m dynamic_factor_models_tpu.replication --full   # untrimmed sweeps
    python -m dynamic_factor_models_tpu.replication --backend cpu --x64
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time


def _to_jsonable(obj):
    import numpy as np

    if isinstance(obj, dict):
        return {str(k): _to_jsonable(v) for k, v in obj.items()}
    if hasattr(obj, "_asdict"):  # NamedTuple results — BEFORE the tuple branch
        return _to_jsonable(obj._asdict())
    if isinstance(obj, (list, tuple)):
        return [_to_jsonable(v) for v in obj]
    if isinstance(obj, np.ndarray):
        return np.where(np.isfinite(obj), obj.astype(float), None).tolist() \
            if obj.dtype.kind == "f" else obj.tolist()
    if hasattr(obj, "tolist"):
        return obj.tolist()
    if isinstance(obj, float) and obj != obj:
        return None
    return obj


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m dynamic_factor_models_tpu.replication",
        description="Reproduce Stock-Watson (2016) Figures 1-7 / Tables 2-5.",
    )
    ap.add_argument("--out", default="replication_out", help="output directory")
    ap.add_argument("--full", action="store_true",
                    help="untrimmed sweeps (full AW refits, r<=60, stepwise)")
    ap.add_argument("--xlsx", default=None, help="panel xlsx path override")
    ap.add_argument("--backend", default=None, choices=("cpu", "tpu"),
                    help="device for the estimators (default: JAX default)")
    ap.add_argument("--x64", action="store_true",
                    help="enable float64 (recommended on CPU for parity)")
    ap.add_argument("--no-figures", action="store_true",
                    help="skip PNG rendering, write only tables.json")
    ap.add_argument("--extras", action="store_true",
                    help="also render the beyond-reference capability "
                         "panels (SV volatility, posterior IRFs, TVP "
                         "loadings, coherence) — adds a few minutes")
    ap.add_argument("--telemetry", metavar="PATH", default=None,
                    help="record a RunRecord JSONL for every estimation "
                         "call (sets DFM_TELEMETRY for this run)")
    args = ap.parse_args(argv)

    if args.telemetry:
        path = os.path.abspath(args.telemetry)
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        os.environ["DFM_TELEMETRY"] = path

    import jax

    if args.backend == "cpu":
        # restrict the platform registry BEFORE any backend initializes:
        # merely querying devices initializes every registered plugin, so a
        # cpu run must never leave the TPU client reachable (conftest.py
        # uses the same recipe)
        jax.config.update("jax_platforms", "cpu")
    if args.x64:
        jax.config.update("jax_enable_x64", True)

    # after the platform/precision config (both change compiled programs,
    # so they must be settled before any cache key is computed)
    from ..utils.compile import configure_compilation_cache

    configure_compilation_cache()

    from ..utils.backend import on_backend
    from . import stock_watson as sw

    os.makedirs(args.out, exist_ok=True)
    t0 = time.time()
    full = args.full
    written = []
    # "tpu" resolves the chip through the library's own device selection and
    # raises if none is reachable; "cpu" is handled by the platform
    # restriction above
    with on_backend(args.backend if args.backend == "tpu" else None):
        ds_real, ds_all = sw.load_datasets(args.xlsx)
        if not args.no_figures:
            # render_all computes every figure itself — don't recompute them
            # for the JSON; only the tables are fit below
            from .plotting import render_all

            written += render_all(args.out, fast=not full, path=args.xlsx)
            if args.extras:
                from .plotting import render_extras

                written += render_extras(args.out, ds_real=ds_real)
        tables = {
            "table2": sw.table2(ds_real, ds_all,
                                max_nfac_b=11 if full else 6, dynamic=full),
            "table3": sw.table3(ds_all, nfac_max=10 if full else 4),
            "table4": sw.table4(ds_all, nfac_us=(4, 8) if full else (4,)),
            "table5": sw.table5(ds_all, stepwise=full),
            "figure6": sw.figure6(ds_all, max_r=60 if full else 10),
        }
    with open(os.path.join(args.out, "tables.json"), "w") as f:
        json.dump(_to_jsonable(tables), f, indent=1)
    written.append(os.path.join(args.out, "tables.json"))
    print(
        f"replication bundle written to {args.out} "
        f"({len(written)} files, {time.time() - t0:.1f}s)"
    )
    for w in written:
        print(" ", w)
    return 0


if __name__ == "__main__":
    sys.exit(main())
