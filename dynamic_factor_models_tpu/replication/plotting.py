"""Render the Stock-Watson replication figures to PNG.

Covers the reference's plot helpers (S13: `plot_skipmissing`,
`compare_series!`, Stock_Watson.ipynb cells 21-22) the array-first way: the
figure*() functions in `stock_watson.py` return data; this module draws it
with matplotlib when a rendered artifact is wanted.  NaN gaps are native to
matplotlib lines, which is exactly what `plot_skipmissing` hand-rolled.

Styling: categorical series colors in fixed order from a CVD-validated
palette; one y-axis per panel; thin (2px) lines; recessive grid; legends
whenever a panel has >= 2 series.
"""

from __future__ import annotations

import os

import numpy as np

# fixed-order categorical palette (validated default; see docs/PARITY.md)
SERIES_COLORS = ["#2a78d6", "#eb6834", "#1baf7a", "#eda100"]
TEXT_PRIMARY = "#0b0b0b"
TEXT_SECONDARY = "#52514e"
SURFACE = "#fcfcfb"
GRID = "#e4e3df"

__all__ = ["render_all", "render_extras", "line_panel"]


def _style_axis(ax, title):
    ax.set_facecolor(SURFACE)
    ax.grid(True, color=GRID, linewidth=0.8, zorder=0)
    for side in ("top", "right"):
        ax.spines[side].set_visible(False)
    for side in ("left", "bottom"):
        ax.spines[side].set_color(GRID)
    ax.tick_params(colors=TEXT_SECONDARY, labelsize=8)
    ax.set_title(title, color=TEXT_PRIMARY, fontsize=10, loc="left")


def line_panel(ax, x, series: dict, title: str):
    """One panel of NaN-gapped 2px lines, fixed-order colors, legend if >=2."""
    for i, (name, y) in enumerate(series.items()):
        ax.plot(
            x,
            np.asarray(y, float),
            label=name,
            color=SERIES_COLORS[i % len(SERIES_COLORS)],
            linewidth=2.0,
            zorder=2 + i,
        )
    _style_axis(ax, title)
    if len(series) >= 2:
        ax.legend(
            loc="upper left",
            frameon=False,
            fontsize=8,
            labelcolor=TEXT_SECONDARY,
        )


def render_all(out_dir: str, fast: bool = True, path: str | None = None) -> list[str]:
    """Compute and render Figures 1-7 to PNG; returns the written paths.

    Calls the `stock_watson.figure*` / `table2` functions directly with
    rendering-friendly settings (table2 without the O(r^2) AW refits;
    figure6 max_r=15 when fast) — NOT the `run_all` bundle, whose dict uses
    its own fast/full settings and also computes Tables 3-5.
    """
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    from . import stock_watson as sw

    os.makedirs(out_dir, exist_ok=True)
    ds_real, ds_all = sw.load_datasets(path)
    written = []
    save = _make_saver(out_dir, plt, written)

    # Figure 1: per-series detrended 4q growth vs 1-factor common component
    f1 = sw.figure1(ds_real)
    fig, axes = plt.subplots(2, 2, figsize=(10, 6))
    for ax, (name, d) in zip(axes.ravel(), f1["series"].items()):
        line_panel(
            ax, f1["year"], {"actual": d["actual"], "common": d["common"]}, name
        )
    save(fig, "figure1.png")

    # Figure 2: filter weights and spectral gains (4 filters, fixed order)
    f2 = sw.figure2()
    fig, (ax1, ax2) = plt.subplots(1, 2, figsize=(10, 4))
    line_panel(ax1, f2["laglead"], f2["weights"], "filter weights")
    line_panel(ax2, f2["frequencies"], f2["gains"], "spectral gains")
    save(fig, "figure2.png")

    # Figure 3: factor-number statistics (the scree view of Table 2)
    t2 = sw.table2(ds_real, ds_all, dynamic=False)
    fig, axes = plt.subplots(1, 3, figsize=(12, 3.5))
    for ax, stat, title in zip(
        axes,
        ("trace_r2", "bn_icp", "ah_er"),
        ("trace R2", "Bai-Ng ICp2", "Ahn-Horenstein ER"),
    ):
        series = {
            "Real": np.asarray(t2["A"][stat]),
            "All": np.asarray(t2["B"][stat]),
        }
        line_panel(
            ax, 1 + np.arange(len(series["All"])),
            {k: np.pad(v.astype(float), (0, len(series["All"]) - len(v)),
                       constant_values=np.nan) for k, v in series.items()},
            title,
        )
    save(fig, "figure3.png")

    # Figure 4: GDP growth vs common component for r in {1, 3, 5}
    f4 = sw.figure4(ds_real)
    fig, ax = plt.subplots(figsize=(10, 4))
    series = {"GDP": f4["gdp_growth"]}
    series.update(
        {k.replace("common_", ""): v for k, v in f4.items()
         if k.startswith("common_")}
    )
    line_panel(ax, f4["year"], series, "GDP 4q growth vs common component")
    save(fig, "figure4.png")

    # Figure 5: first factor, full vs pre-84 vs post-84
    f5 = sw.figure5(ds_real)
    fig, ax = plt.subplots(figsize=(10, 4))
    line_panel(
        ax,
        f5["year"],
        {k: f5[k] for k in ("full", "pre", "post")},
        "first factor: full vs split samples",
    )
    save(fig, "figure5.png")

    # Figure 6: cumulative trace R2 by r, three samples
    f6 = sw.figure6(ds_all, max_r=15 if fast else 60)
    fig, ax = plt.subplots(figsize=(10, 4))
    r_grid = 1 + np.arange(len(f6["all"]))
    line_panel(ax, r_grid, f6, "cumulative trace R2 by number of factors")
    save(fig, "figure6.png")

    # Figure 7: oil price vs unit-loading constrained common component
    f7 = sw.figure7(ds_all)
    fig, ax = plt.subplots(figsize=(10, 4))
    line_panel(
        ax,
        f7["year"],
        {
            f7["names"][0]: f7["oil_prices"][:, 0],
            "common component": f7["common_component"],
        },
        "oil-price inflation vs constrained common component",
    )
    save(fig, "figure7.png")

    return written


def _make_saver(out_dir, plt, written):
    """Shared PNG writer (render_all and render_extras must not drift)."""

    def save(fig, name):
        p = os.path.join(out_dir, name)
        fig.savefig(p, dpi=150, facecolor=SURFACE, bbox_inches="tight")
        plt.close(fig)
        written.append(p)

    return save


def render_extras(
    out_dir: str,
    path: str | None = None,
    ds_real=None,
    n_keep: int = 40,
    n_burn: int = 40,
    n_chains: int = 2,
    ms_steps: int = 400,
) -> list[str]:
    """Render the beyond-reference capability panels to PNG: stochastic-
    volatility path, posterior IRF fan, TVP loading drift, and coherence
    spectra.  Small default chain sizes keep this a minutes-scale CPU run;
    raise n_keep/n_burn for production-quality bands.
    """
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt
    import jax.numpy as jnp

    from ..models import (
        DFMConfig,
        coherence,
        estimate_dfm,
        estimate_dfm_bayes,
        estimate_dfm_sv,
        posterior_irfs,
        tvp_loadings,
    )
    from ..ops.linalg import standardize_data
    from . import stock_watson as sw

    os.makedirs(out_dir, exist_ok=True)
    if ds_real is None:
        ds_real, _ = sw.load_datasets(path)
    cfg = DFMConfig(nfac_u=4)
    incl = np.asarray(ds_real.inclcode) == 1
    # benchmark sample window, derived like every stock_watson figure (the
    # row offsets shift if a revised/extended panel is passed via `path`)
    i0, i1 = sw._window(ds_real, sw.PERIODS_ALL)
    year = np.asarray(ds_real.calvec)[i0 : i1 + 1]
    written = []
    save = _make_saver(out_dir, plt, written)

    # stochastic volatility: posterior mean +/- band of factor-1 innovation sd
    sv = estimate_dfm_sv(ds_real.bpdata, ds_real.inclcode, i0, i1, cfg,
                         n_keep=n_keep, n_burn=n_burn, n_chains=n_chains)
    vol = np.asarray(sv.vol_draws)[..., 0].reshape(-1, sv.vol_draws.shape[2])
    lo, mid, hi = np.quantile(vol, [0.16, 0.5, 0.84], axis=0)
    fig, ax = plt.subplots(figsize=(10, 4))
    line_panel(ax, year, {"median": mid, "16%": lo, "84%": hi},
               "factor-1 innovation volatility (SV-DFM posterior)")
    save(fig, "extra_sv_volatility.png")

    # posterior IRF fan of factor 1 to its own shock
    post = estimate_dfm_bayes(ds_real.bpdata, ds_real.inclcode, i0, i1, cfg,
                              n_keep=n_keep, n_burn=n_burn, n_chains=n_chains)
    qs, _ = posterior_irfs(post, horizon=16)
    qs = np.asarray(qs)  # (nq, r, H, r)
    fig, ax = plt.subplots(figsize=(8, 4))
    h = np.arange(qs.shape[2])
    line_panel(ax, h, {lbl: qs[k, 0, :, 0] for k, lbl in
                       enumerate(("5%", "16%", "median", "84%", "95%"))},
               "factor-1 IRF to own shock (posterior bands)")
    save(fig, "extra_posterior_irf.png")

    # point DFM fit, shared by the TVP and series-IRF panels below
    res = estimate_dfm(ds_real.bpdata, ds_real.inclcode, i0, i1, cfg)

    # TVP loading drift: the most unstable series' loading path on factor 1
    data = np.asarray(ds_real.bpdata)[i0 : i1 + 1][:, incl]
    xz, _ = standardize_data(jnp.asarray(data))
    F = jnp.asarray(np.asarray(res.factor)[i0 : i1 + 1])
    tvp = tvp_loadings(xz, F)
    names = [n for n, i in zip(ds_real.bpnamevec, incl) if i]
    top = np.argsort(-np.asarray(tvp.drift))[:3]
    fig, ax = plt.subplots(figsize=(10, 4))
    line_panel(ax, year,
               {names[i]: np.asarray(tvp.lam_path)[:, i, 0] for i in top},
               "factor-1 loadings of the most unstable series (TVP paths)")
    save(fig, "extra_tvp_loadings.png")

    # series-space FAVAR bands: bootstrap draws of the factor IRFs pushed
    # through the loadings — response of GDP to the first recursive shock
    from ..models import (
        bootstrap_forecast_fan,
        series_forecast_fan,
        series_irfs,
        wild_bootstrap_irfs,
    )

    boot = wild_bootstrap_irfs(res.factor, cfg.n_factorlag, i0, i1,
                               horizon=16, n_reps=400, seed=0)
    j_gdp = list(ds_real.bpnamevec).index("GDPC96")
    s = series_irfs(boot, res.lam, series_idx=[j_gdp])
    sq = np.asarray(s.quantiles)[:, 0, :, 0]  # (nq, H), shock 1
    fig, ax = plt.subplots(figsize=(8, 4))
    line_panel(ax, np.arange(sq.shape[1]), {
        "point": np.asarray(s.point)[0, :, 0],
        "5%": sq[0], "median": sq[2], "95%": sq[-1],
    }, "GDPC96 response to shock 1 (wild-bootstrap 5-95% band)")
    save(fig, "extra_series_irf_band.png")

    # forecast fan chart: factor fan (parameter + shock uncertainty)
    # pushed through the loadings to GDP, original units
    fan = bootstrap_forecast_fan(res.factor, cfg.n_factorlag, i0, i1,
                                 horizon=12, n_reps=400, seed=0)
    sf = series_forecast_fan(
        fan, jnp.nan_to_num(res.lam), const=jnp.nan_to_num(res.lam_const),
        series_idx=[j_gdp],
    )
    fq = np.asarray(sf.quantiles)[:, 0, :]
    fig, ax = plt.subplots(figsize=(8, 4))
    line_panel(ax, np.arange(1, fq.shape[1] + 1), {
        "point": np.asarray(sf.point)[0],
        "5%": fq[0], "median": fq[2], "95%": fq[-1],
    }, "GDPC96 common-component fan chart (bootstrap 5-95%)")
    save(fig, "extra_forecast_fan.png")

    # Markov-switching DFM: smoothed recession probability (Chauvet-Piger
    # readout) over the sample, with the factor path underneath
    from ..models import fit_ms_dfm

    ms = fit_ms_dfm(data, n_steps=ms_steps)
    prob0 = np.asarray(ms.smoothed_probs[:, 0])
    fig, (ax1, ax2) = plt.subplots(2, 1, figsize=(8, 5), sharex=True)
    ax1.fill_between(year, 0.0, prob0, color="0.55", alpha=0.8)
    ax1.set_ylim(0, 1)
    ax1.set_title("MS-DFM smoothed recession probability (low-mean regime)")
    ax2.plot(year, np.asarray(ms.factor), lw=1.0)
    ax2.axhline(0.0, color="0.8", lw=0.8)
    ax2.set_title("filtered switching factor")
    fig.tight_layout()
    save(fig, "extra_recession_prob.png")

    # coherence with the first included series across frequencies
    freqs, coh2, _ = coherence(ds_real.bpdata, M=24)
    freqs, coh2 = np.asarray(freqs), np.asarray(coh2)
    half = freqs <= np.pi
    full_names = list(ds_real.bpnamevec)
    j0 = int(np.flatnonzero(incl)[0])
    others = np.flatnonzero(incl)[1:4]
    fig, ax = plt.subplots(figsize=(8, 4))
    line_panel(ax, freqs[half],
               {full_names[j]: coh2[half, j0, j] for j in others},
               f"squared coherence with {full_names[j0]}")
    save(fig, "extra_coherence.png")

    return written
