"""Render the Stock-Watson replication figures to PNG.

Covers the reference's plot helpers (S13: `plot_skipmissing`,
`compare_series!`, Stock_Watson.ipynb cells 21-22) the array-first way: the
figure*() functions in `stock_watson.py` return data; this module draws it
with matplotlib when a rendered artifact is wanted.  NaN gaps are native to
matplotlib lines, which is exactly what `plot_skipmissing` hand-rolled.

Styling: categorical series colors in fixed order from a CVD-validated
palette; one y-axis per panel; thin (2px) lines; recessive grid; legends
whenever a panel has >= 2 series.
"""

from __future__ import annotations

import os

import numpy as np

# fixed-order categorical palette (validated default; see docs/PARITY.md)
SERIES_COLORS = ["#2a78d6", "#eb6834", "#1baf7a", "#eda100"]
TEXT_PRIMARY = "#0b0b0b"
TEXT_SECONDARY = "#52514e"
SURFACE = "#fcfcfb"
GRID = "#e4e3df"

__all__ = ["render_all", "line_panel"]


def _style_axis(ax, title):
    ax.set_facecolor(SURFACE)
    ax.grid(True, color=GRID, linewidth=0.8, zorder=0)
    for side in ("top", "right"):
        ax.spines[side].set_visible(False)
    for side in ("left", "bottom"):
        ax.spines[side].set_color(GRID)
    ax.tick_params(colors=TEXT_SECONDARY, labelsize=8)
    ax.set_title(title, color=TEXT_PRIMARY, fontsize=10, loc="left")


def line_panel(ax, x, series: dict, title: str):
    """One panel of NaN-gapped 2px lines, fixed-order colors, legend if >=2."""
    for i, (name, y) in enumerate(series.items()):
        ax.plot(
            x,
            np.asarray(y, float),
            label=name,
            color=SERIES_COLORS[i % len(SERIES_COLORS)],
            linewidth=2.0,
            zorder=2 + i,
        )
    _style_axis(ax, title)
    if len(series) >= 2:
        ax.legend(
            loc="upper left",
            frameon=False,
            fontsize=8,
            labelcolor=TEXT_SECONDARY,
        )


def render_all(out_dir: str, fast: bool = True, path: str | None = None) -> list[str]:
    """Compute and render Figures 1-7 to PNG; returns the written paths.

    Calls the `stock_watson.figure*` / `table2` functions directly with
    rendering-friendly settings (table2 without the O(r^2) AW refits;
    figure6 max_r=15 when fast) — NOT the `run_all` bundle, whose dict uses
    its own fast/full settings and also computes Tables 3-5.
    """
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    from . import stock_watson as sw

    os.makedirs(out_dir, exist_ok=True)
    ds_real, ds_all = sw.load_datasets(path)
    written = []

    def save(fig, name):
        p = os.path.join(out_dir, name)
        fig.savefig(p, dpi=150, facecolor=SURFACE, bbox_inches="tight")
        plt.close(fig)
        written.append(p)

    # Figure 1: per-series detrended 4q growth vs 1-factor common component
    f1 = sw.figure1(ds_real)
    fig, axes = plt.subplots(2, 2, figsize=(10, 6))
    for ax, (name, d) in zip(axes.ravel(), f1["series"].items()):
        line_panel(
            ax, f1["year"], {"actual": d["actual"], "common": d["common"]}, name
        )
    save(fig, "figure1.png")

    # Figure 2: filter weights and spectral gains (4 filters, fixed order)
    f2 = sw.figure2()
    fig, (ax1, ax2) = plt.subplots(1, 2, figsize=(10, 4))
    line_panel(ax1, f2["laglead"], f2["weights"], "filter weights")
    line_panel(ax2, f2["frequencies"], f2["gains"], "spectral gains")
    save(fig, "figure2.png")

    # Figure 3: factor-number statistics (the scree view of Table 2)
    t2 = sw.table2(ds_real, ds_all, dynamic=False)
    fig, axes = plt.subplots(1, 3, figsize=(12, 3.5))
    for ax, stat, title in zip(
        axes,
        ("trace_r2", "bn_icp", "ah_er"),
        ("trace R2", "Bai-Ng ICp2", "Ahn-Horenstein ER"),
    ):
        series = {
            "Real": np.asarray(t2["A"][stat]),
            "All": np.asarray(t2["B"][stat]),
        }
        line_panel(
            ax, 1 + np.arange(len(series["All"])),
            {k: np.pad(v.astype(float), (0, len(series["All"]) - len(v)),
                       constant_values=np.nan) for k, v in series.items()},
            title,
        )
    save(fig, "figure3.png")

    # Figure 4: GDP growth vs common component for r in {1, 3, 5}
    f4 = sw.figure4(ds_real)
    fig, ax = plt.subplots(figsize=(10, 4))
    series = {"GDP": f4["gdp_growth"]}
    series.update(
        {k.replace("common_", ""): v for k, v in f4.items()
         if k.startswith("common_")}
    )
    line_panel(ax, f4["year"], series, "GDP 4q growth vs common component")
    save(fig, "figure4.png")

    # Figure 5: first factor, full vs pre-84 vs post-84
    f5 = sw.figure5(ds_real)
    fig, ax = plt.subplots(figsize=(10, 4))
    line_panel(
        ax,
        f5["year"],
        {k: f5[k] for k in ("full", "pre", "post")},
        "first factor: full vs split samples",
    )
    save(fig, "figure5.png")

    # Figure 6: cumulative trace R2 by r, three samples
    f6 = sw.figure6(ds_all, max_r=15 if fast else 60)
    fig, ax = plt.subplots(figsize=(10, 4))
    r_grid = 1 + np.arange(len(f6["all"]))
    line_panel(ax, r_grid, f6, "cumulative trace R2 by number of factors")
    save(fig, "figure6.png")

    # Figure 7: oil price vs unit-loading constrained common component
    f7 = sw.figure7(ds_all)
    fig, ax = plt.subplots(figsize=(10, 4))
    line_panel(
        ax,
        f7["year"],
        {
            f7["names"][0]: f7["oil_prices"][:, 0],
            "common component": f7["common_component"],
        },
        "oil-price inflation vs constrained common component",
    )
    save(fig, "figure7.png")

    return written
