"""Parallelism package: meshes, collectives, and sequence parallelism.

Also holds the single copy of the `shard_map` compatibility shim: the
function moved from ``jax.experimental.shard_map`` to ``jax.shard_map``
and its replication-check kwarg was renamed ``check_rep`` ->
``check_vma`` on a DIFFERENT version boundary than the import move, so
every call site used to re-sniff both.  `shard_map_nocheck` resolves
both once, here — defined before the submodule imports below so
``from . import shard_map_nocheck`` inside them cannot recurse.
"""

import inspect as _inspect

try:  # jax >= 0.4.35
    from jax import shard_map as _shard_map
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map as _shard_map

SHARD_MAP_NOCHECK_KW = (
    {"check_vma": False}
    if "check_vma" in _inspect.signature(_shard_map).parameters
    else {"check_rep": False}
)
del _inspect


def shard_map_nocheck(f, mesh, in_specs, out_specs):
    """``shard_map`` with the replication check disabled, version-proof.

    Every shard_map in this codebase runs with the static replication
    check off (the collapse payload reductions and prefix exchanges
    produce replicated outputs the checker cannot prove), so the kwarg
    sniffing lives here once instead of inline at each call site.
    """
    return _shard_map(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        **SHARD_MAP_NOCHECK_KW,
    )


from .mesh import Mesh, NamedSharding, P, data_mesh, make_mesh, replicate, shard_over
from .distributed import global_mesh, initialize_distributed
from .timescan import sharded_scan, time_sharding
