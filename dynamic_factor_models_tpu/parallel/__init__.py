from .mesh import Mesh, NamedSharding, P, make_mesh, replicate, shard_over
from .distributed import global_mesh, initialize_distributed
from .timescan import sharded_scan, time_sharding
