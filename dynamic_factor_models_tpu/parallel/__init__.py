from .mesh import Mesh, NamedSharding, P, make_mesh, replicate, shard_over
