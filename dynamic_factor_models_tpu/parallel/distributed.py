"""Multi-host initialization and global meshes (ICI + DCN).

The reference has no distributed communication backend at all (SURVEY.md
section 2.6); the TPU-native equivalent is JAX's built-in runtime: one
process per host, `jax.distributed.initialize` over DCN, then a single
global `Mesh` whose inner axes ride ICI (fast, within a slice) and whose
outer axis spans hosts.  XLA emits every collective; there is no NCCL/MPI
analogue to wrap.

Layout guidance (the scaling-book recipe): put the embarrassing axis
(bootstrap replications, panels) on the outer/DCN axis — its only
collective is the final quantile/moment aggregation — and keep
series/tensor sharding (`sp`, psum-heavy) on inner/ICI axes.

The multi-process branch is exercised for real by
tests/test_distributed_multiprocess.py: two OS processes x 4 virtual CPU
devices joined through the coordination service, cross-process psum over
Gloo, and the replication-sharded bootstrap on the resulting global mesh.
"""

from __future__ import annotations

import os

import jax
import numpy as np
from jax.sharding import Mesh

__all__ = ["initialize_distributed", "global_mesh"]


def initialize_distributed(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> bool:
    """Initialize the multi-host JAX runtime; returns True if distributed.

    Pass the coordinator explicitly or set JAX_COORDINATOR_ADDRESS /
    JAX_NUM_PROCESSES / JAX_PROCESS_ID (on TPU pods num_processes and
    process_id are then auto-detected from the metadata server).
    Single-process runs (no coordinator configured) are a no-op so the same
    entry point works from a laptop to a pod.
    """
    coordinator_address = coordinator_address or os.environ.get(
        "JAX_COORDINATOR_ADDRESS"
    )
    env_np = os.environ.get("JAX_NUM_PROCESSES")
    num_processes = num_processes if num_processes is not None else (
        int(env_np) if env_np else None
    )
    env_pid = os.environ.get("JAX_PROCESS_ID")
    process_id = process_id if process_id is not None else (
        int(env_pid) if env_pid else None
    )
    if coordinator_address is None:
        return False  # single-process
    # CPU cross-process collectives need an explicit implementation: the
    # flag defaults to "none" and the TFRT CPU client then refuses ANY
    # compile whose device assignment crosses a process boundary
    # ("Multiprocess computations aren't implemented on the CPU backend").
    # Pick Gloo before the backend instantiates; TPU/GPU ignore the flag.
    try:
        from jax._src import xla_bridge as _xb

        current = _xb.CPU_COLLECTIVES_IMPLEMENTATION.value
    except Exception:
        current = None
    if current in (None, "none"):
        try:
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
        except Exception:
            pass  # newer jax: gloo is the default and the flag may be gone
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    return jax.process_count() > 1


def global_mesh(axis_names=("rep",), shape=None, devices=None) -> Mesh:
    """Mesh over all global devices (every process's chips).

    Default: 1-D mesh over everything.  Pass `shape` to factor the device
    count into named axes, e.g. shape=(n_hosts, chips_per_host) with
    axis_names=("dp", "sp") to pin the outer axis to DCN and the inner to
    ICI (jax.devices() orders devices process-major, so the outer axis
    strides across hosts).
    """
    devs = list(jax.devices()) if devices is None else list(devices)
    if shape is None:
        shape = (len(devs),)
    if int(np.prod(shape)) != len(devs):
        raise ValueError(f"shape {shape} does not tile {len(devs)} devices")
    if len(shape) != len(axis_names):
        raise ValueError("axis_names and shape must have the same length")
    return Mesh(np.asarray(devs).reshape(shape), axis_names)
