"""Device-mesh helpers: replication sharding + collective aggregation.

The reference has no parallelism or communication backend of any kind
(SURVEY.md section 2.6) — everything here is new TPU-native design: a
``jax.sharding.Mesh`` over the chips, ``NamedSharding`` placement of the
embarrassing axes (bootstrap replications, series blocks), and XLA-emitted
collectives (psum/all_gather) instead of NCCL/MPI calls.  Over a v5e slice the
collectives ride ICI; the same program runs on the virtual CPU mesh in CI.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = [
    "make_mesh",
    "data_mesh",
    "rep_pad",
    "series_pad",
    "shard_over",
    "replicate",
    "P",
    "Mesh",
    "NamedSharding",
]


def rep_pad(n_reps: int, n_dev: int, bucket: int | None = None) -> int:
    """Padded replication count: round n_reps up to a device multiple,
    then (optionally) up to a multiple of `bucket` so every bootstrap
    batch size in a session maps onto ONE compiled executable —
    `jax.random.split` prefix stability makes the first n_reps draws of a
    padded batch identical to the unpadded batch, so callers slice
    `[:n_reps]` and results are exact (pinned in tests/test_favar.py).

    bucket=None reads ``DFM_REP_BUCKET`` (0 disables; e.g. 256 buckets
    every count into {256, 512, ...} multiples).
    """
    if bucket is None:
        import os

        bucket = int(os.environ.get("DFM_REP_BUCKET", "0"))
    n = ((n_reps + n_dev - 1) // n_dev) * n_dev
    if bucket > 0:
        step = -(-bucket // n_dev) * n_dev  # lcm-ish: keep device multiple
        n = ((n + step - 1) // step) * step
    return n


def series_pad(n_series: int, n_shards: int) -> int:
    """Padded cross-section size: round N up to a shard multiple so the
    series axis splits evenly over the ``data`` mesh.  Padding series are
    inert by the `compile.pad_ssm_params` contract (zero loadings, unit
    idiosyncratic variance, all-False mask): they contribute exactly zero
    to every collapsed statistic that crosses the mesh (C, b, ld_R, xRx
    are N-sums with zero terms; log R = log 1 = 0), so the reduced Gram —
    and therefore the filter path and the loglik — match the unpadded
    panel bit-for-bit on each shard (pinned in tests/test_sharding.py).
    """
    if n_shards <= 1:
        return n_series
    return ((n_series + n_shards - 1) // n_shards) * n_shards


def data_mesh(n_shards: int | None = None, hosts: int = 1) -> Mesh:
    """Cross-section (N axis) mesh used by the sharded EM step.

    hosts <= 1 (the default, and the resolution of hosts=0/None in a
    single-process runtime) builds the flat 1-D ``("data",)`` mesh over
    the first n_shards devices — byte-identical to the pre-multi-host
    construction, so the single-host HLO pins are preserved.

    hosts > 1 builds the process-spanning 2-D ``("dcn", "ici")`` mesh:
    the outer axis enumerates hosts (cross-process psum rides DCN), the
    inner axis a host's local devices (Pallas ring rides ICI).  Sharded
    arrays flatten both axes into one logical data axis via a tuple
    PartitionSpec entry ``P(("dcn", "ici"), ...)``.  In a multi-process
    runtime each host contributes its own first ``n_shards // hosts``
    devices, relying on the process-major ordering of ``jax.devices()``;
    single-process callers (the tier-1 8-device proxy) get the same
    topology by reshaping the first n_shards local devices.

    On TPU the inner axis rides ICI; in CI the same program runs on the
    forced 8-device CPU platform (tests/conftest.py)."""
    if hosts is None or hosts == 0:
        hosts = jax.process_count()
    hosts = max(int(hosts), 1)
    if hosts <= 1:
        return make_mesh(n_shards, axis_names=("data",))
    devs = jax.devices()
    if n_shards is None:
        n_shards = len(devs)
    if n_shards % hosts != 0:
        raise ValueError(
            f"n_shards={n_shards} must divide evenly over hosts={hosts} "
            f"(each host owns n_shards // hosts local devices)"
        )
    local = n_shards // hosts
    nproc = jax.process_count()
    if nproc > 1:
        if hosts != nproc:
            raise ValueError(
                f"hosts={hosts} must equal jax.process_count()={nproc} in a "
                f"multi-process runtime (one DCN rank per OS process)"
            )
        per_proc = len(devs) // nproc
        if local > per_proc:
            raise ValueError(
                f"n_shards={n_shards} over hosts={hosts} needs {local} devices "
                f"per process but only {per_proc} are visible"
            )
        # Process-major: take each process's first `local` devices so the
        # "ici" axis never crosses a process boundary.
        picked = [devs[h * per_proc + j] for h in range(hosts) for j in range(local)]
    else:
        if n_shards > len(devs):
            raise ValueError(
                f"n_shards={n_shards} exceeds the {len(devs)} visible devices"
            )
        picked = list(devs[:n_shards])
    return Mesh(np.array(picked).reshape(hosts, local), ("dcn", "ici"))


def make_mesh(n_devices: int | None = None, axis_names=("rep",), shape=None) -> Mesh:
    """Build a mesh over the first n_devices (default: all).

    axis_names/shape allow 2-D meshes, e.g. ("rep", "series") for bootstrap
    x series-block sharding.
    """
    devs = jax.devices()
    if n_devices is None:
        n_devices = len(devs)
    devs = np.array(devs[:n_devices])
    if shape is None:
        shape = (n_devices,) + (1,) * (len(axis_names) - 1)
    return Mesh(devs.reshape(shape), axis_names)


def shard_over(mesh: Mesh, axis: str, x, dim: int = 0):
    """Place array x with dimension `dim` sharded over mesh axis `axis`."""
    spec = [None] * x.ndim
    spec[dim] = axis
    return jax.device_put(x, NamedSharding(mesh, P(*spec)))


def replicate(mesh: Mesh, x):
    return jax.device_put(x, NamedSharding(mesh, P()))
