"""Device-mesh helpers: replication sharding + collective aggregation.

The reference has no parallelism or communication backend of any kind
(SURVEY.md section 2.6) — everything here is new TPU-native design: a
``jax.sharding.Mesh`` over the chips, ``NamedSharding`` placement of the
embarrassing axes (bootstrap replications, series blocks), and XLA-emitted
collectives (psum/all_gather) instead of NCCL/MPI calls.  Over a v5e slice the
collectives ride ICI; the same program runs on the virtual CPU mesh in CI.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = [
    "make_mesh",
    "data_mesh",
    "rep_pad",
    "series_pad",
    "shard_over",
    "replicate",
    "P",
    "Mesh",
    "NamedSharding",
]


def rep_pad(n_reps: int, n_dev: int, bucket: int | None = None) -> int:
    """Padded replication count: round n_reps up to a device multiple,
    then (optionally) up to a multiple of `bucket` so every bootstrap
    batch size in a session maps onto ONE compiled executable —
    `jax.random.split` prefix stability makes the first n_reps draws of a
    padded batch identical to the unpadded batch, so callers slice
    `[:n_reps]` and results are exact (pinned in tests/test_favar.py).

    bucket=None reads ``DFM_REP_BUCKET`` (0 disables; e.g. 256 buckets
    every count into {256, 512, ...} multiples).
    """
    if bucket is None:
        import os

        bucket = int(os.environ.get("DFM_REP_BUCKET", "0"))
    n = ((n_reps + n_dev - 1) // n_dev) * n_dev
    if bucket > 0:
        step = -(-bucket // n_dev) * n_dev  # lcm-ish: keep device multiple
        n = ((n + step - 1) // step) * step
    return n


def series_pad(n_series: int, n_shards: int) -> int:
    """Padded cross-section size: round N up to a shard multiple so the
    series axis splits evenly over the ``data`` mesh.  Padding series are
    inert by the `compile.pad_ssm_params` contract (zero loadings, unit
    idiosyncratic variance, all-False mask): they contribute exactly zero
    to every collapsed statistic that crosses the mesh (C, b, ld_R, xRx
    are N-sums with zero terms; log R = log 1 = 0), so the reduced Gram —
    and therefore the filter path and the loglik — match the unpadded
    panel bit-for-bit on each shard (pinned in tests/test_sharding.py).
    """
    if n_shards <= 1:
        return n_series
    return ((n_series + n_shards - 1) // n_shards) * n_shards


def data_mesh(
    n_shards: int | None = None, hosts: int = 1, t_blocks: int = 0
) -> Mesh:
    """Cross-section (N axis) mesh used by the sharded EM step.

    hosts <= 1 (the default, and the resolution of hosts=0/None in a
    single-process runtime) builds the flat 1-D ``("data",)`` mesh over
    the first n_shards devices — byte-identical to the pre-multi-host
    construction, so the single-host HLO pins are preserved.

    hosts > 1 builds the process-spanning 2-D ``("dcn", "ici")`` mesh:
    the outer axis enumerates hosts (cross-process psum rides DCN), the
    inner axis a host's local devices (Pallas ring rides ICI).  Sharded
    arrays flatten both axes into one logical data axis via a tuple
    PartitionSpec entry ``P(("dcn", "ici"), ...)``.  In a multi-process
    runtime each host contributes its own first ``n_shards // hosts``
    devices, relying on the process-major ordering of ``jax.devices()``;
    single-process callers (the tier-1 8-device proxy) get the same
    topology by reshaping the first n_shards local devices.

    t_blocks > 1 inserts a THIRD axis between them — the 3-D
    ``("dcn", "time", "ici")`` mesh of the parallel-in-time EM path
    (parallel/timescan, models/emtime): each host owns t_blocks
    contiguous time slabs, each slab an ICI group of ``n_shards // hosts``
    series shards, so the O(k^2) slab-boundary exchange stays on-host
    (ICI/shared memory) while only the hierarchical payload reduction
    crosses DCN.  Device order stays process-major; ``t_blocks <= 1``
    returns exactly the flat/2-D mesh above (byte-identity guarantee —
    pinned in tests/test_multihost.py).

    On TPU the inner axis rides ICI; in CI the same program runs on the
    forced 8-device CPU platform (tests/conftest.py)."""
    if hosts is None or hosts == 0:
        hosts = jax.process_count()
    hosts = max(int(hosts), 1)
    t_blocks = max(int(t_blocks), 0)
    if t_blocks <= 1 and hosts <= 1:
        return _publish_axes(make_mesh(n_shards, axis_names=("data",)))
    devs = jax.devices()
    if n_shards is None:
        n_shards = len(devs) if t_blocks <= 1 else len(devs) // max(t_blocks, 1)
    if n_shards % hosts != 0:
        raise ValueError(
            f"n_shards={n_shards} must divide evenly over hosts={hosts} "
            f"(each host owns n_shards // hosts local devices)"
        )
    local = n_shards // hosts
    per_host = local * max(t_blocks, 1)  # devices one host contributes
    nproc = jax.process_count()
    if nproc > 1:
        if hosts != nproc:
            raise ValueError(
                f"hosts={hosts} must equal jax.process_count()={nproc} in a "
                f"multi-process runtime (one DCN rank per OS process)"
            )
        per_proc = len(devs) // nproc
        if per_host > per_proc:
            raise ValueError(
                f"n_shards={n_shards} x t_blocks={t_blocks} over "
                f"hosts={hosts} needs {per_host} devices per process but "
                f"only {per_proc} are visible"
            )
        # Process-major: take each process's first `per_host` devices so
        # neither the "time" nor the "ici" axis crosses a process boundary.
        picked = [
            devs[h * per_proc + j] for h in range(hosts) for j in range(per_host)
        ]
    else:
        if hosts * per_host > len(devs):
            raise ValueError(
                f"n_shards={n_shards} x t_blocks={max(t_blocks, 1)} exceeds "
                f"the {len(devs)} visible devices"
            )
        picked = list(devs[: hosts * per_host])
    if t_blocks <= 1:
        return _publish_axes(
            Mesh(np.array(picked).reshape(hosts, local), ("dcn", "ici"))
        )
    return _publish_axes(
        Mesh(
            np.array(picked).reshape(hosts, t_blocks, local),
            ("dcn", "time", "ici"),
        )
    )


def _publish_axes(mesh: Mesh) -> Mesh:
    """Publish the mesh topology as inline-labeled telemetry gauges
    (``mesh.axis_size{axis="dcn"}`` etc.) so the comm-bytes ledger
    (utils/roofline.comm_summary) can be read against the axis sizes it
    is attributed over.  gauge_set is ungated and the data_mesh call
    sites are lru-cached, so this fires once per topology."""
    try:
        from ..utils.telemetry import gauge_set

        for name, size in mesh.shape.items():
            gauge_set(f'mesh.axis_size{{axis="{name}"}}', int(size))
        gauge_set("mesh.n_devices", int(mesh.devices.size))
    except Exception:
        pass
    return mesh


def make_mesh(n_devices: int | None = None, axis_names=("rep",), shape=None) -> Mesh:
    """Build a mesh over the first n_devices (default: all).

    axis_names/shape allow 2-D meshes, e.g. ("rep", "series") for bootstrap
    x series-block sharding.
    """
    devs = jax.devices()
    if n_devices is None:
        n_devices = len(devs)
    devs = np.array(devs[:n_devices])
    if shape is None:
        shape = (n_devices,) + (1,) * (len(axis_names) - 1)
    return Mesh(devs.reshape(shape), axis_names)


def shard_over(mesh: Mesh, axis: str, x, dim: int = 0):
    """Place array x with dimension `dim` sharded over mesh axis `axis`."""
    spec = [None] * x.ndim
    spec[dim] = axis
    return jax.device_put(x, NamedSharding(mesh, P(*spec)))


def replicate(mesh: Mesh, x):
    return jax.device_put(x, NamedSharding(mesh, P()))
