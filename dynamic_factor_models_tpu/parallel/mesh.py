"""Device-mesh helpers: replication sharding + collective aggregation.

The reference has no parallelism or communication backend of any kind
(SURVEY.md section 2.6) — everything here is new TPU-native design: a
``jax.sharding.Mesh`` over the chips, ``NamedSharding`` placement of the
embarrassing axes (bootstrap replications, series blocks), and XLA-emitted
collectives (psum/all_gather) instead of NCCL/MPI calls.  Over a v5e slice the
collectives ride ICI; the same program runs on the virtual CPU mesh in CI.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = [
    "make_mesh",
    "rep_pad",
    "shard_over",
    "replicate",
    "P",
    "Mesh",
    "NamedSharding",
]


def rep_pad(n_reps: int, n_dev: int, bucket: int | None = None) -> int:
    """Padded replication count: round n_reps up to a device multiple,
    then (optionally) up to a multiple of `bucket` so every bootstrap
    batch size in a session maps onto ONE compiled executable —
    `jax.random.split` prefix stability makes the first n_reps draws of a
    padded batch identical to the unpadded batch, so callers slice
    `[:n_reps]` and results are exact (pinned in tests/test_favar.py).

    bucket=None reads ``DFM_REP_BUCKET`` (0 disables; e.g. 256 buckets
    every count into {256, 512, ...} multiples).
    """
    if bucket is None:
        import os

        bucket = int(os.environ.get("DFM_REP_BUCKET", "0"))
    n = ((n_reps + n_dev - 1) // n_dev) * n_dev
    if bucket > 0:
        step = -(-bucket // n_dev) * n_dev  # lcm-ish: keep device multiple
        n = ((n + step - 1) // step) * step
    return n


def make_mesh(n_devices: int | None = None, axis_names=("rep",), shape=None) -> Mesh:
    """Build a mesh over the first n_devices (default: all).

    axis_names/shape allow 2-D meshes, e.g. ("rep", "series") for bootstrap
    x series-block sharding.
    """
    devs = jax.devices()
    if n_devices is None:
        n_devices = len(devs)
    devs = np.array(devs[:n_devices])
    if shape is None:
        shape = (n_devices,) + (1,) * (len(axis_names) - 1)
    return Mesh(devs.reshape(shape), axis_names)


def shard_over(mesh: Mesh, axis: str, x, dim: int = 0):
    """Place array x with dimension `dim` sharded over mesh axis `axis`."""
    spec = [None] * x.ndim
    spec[dim] = axis
    return jax.device_put(x, NamedSharding(mesh, P(*spec)))


def replicate(mesh: Mesh, x):
    return jax.device_put(x, NamedSharding(mesh, P()))
