"""Sequence parallelism: associative scans sharded over a mesh's time axis.

The DFM analogue of ring/context parallelism for long sequences (the global
design requirement; SURVEY.md section 5.7): a time recursion whose combine is
associative — the parallel Kalman filter/smoother elements
(models/pkalman.py), cumulative products of companion matrices for IRFs,
prefix log-likelihoods — runs time-block-sharded across devices:

    1. each device runs a local ``lax.associative_scan`` on its block;
    2. ONE ``all_gather`` over the mesh axis exchanges the per-block totals
       (the classic Blelchoch block-scan exchange; O(n_dev * elem) bytes on
       ICI, independent of T);
    3. each device folds the gathered prefixes (n_dev tiny combines) and
       applies its exclusive block-prefix to the local results.

Implemented with ``shard_map`` so the collective is explicit and rides the
mesh axis; everything composes with jit.  The reference has no distributed
code of any kind (SURVEY.md section 2.6) — this is new TPU-native design.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:  # jax >= 0.4.35
    from jax import shard_map
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map

# the replication-check kwarg was renamed check_rep -> check_vma on a
# different jax version boundary than the import move, so pick by signature
import inspect as _inspect

_params = _inspect.signature(shard_map).parameters
_SHARD_MAP_KW = (
    {"check_vma": False} if "check_vma" in _params else {"check_rep": False}
)
del _inspect, _params

__all__ = ["sharded_scan", "time_sharding"]


def time_sharding(mesh: Mesh, axis: str = "time"):
    """NamedSharding placing an elements-pytree's leading (time) dim on
    `axis`."""
    return NamedSharding(mesh, P(axis))


def sharded_scan(combine, elems, mesh: Mesh, axis: str = "time"):
    """Inclusive associative scan over the leading axis of an elements pytree,
    sharded over `mesh[axis]`.

    `combine(earlier, later)` must be associative (not necessarily
    commutative).  The leading dimension must divide evenly by the mesh-axis
    size.  Returns the same pytree, scanned, with the same sharding.
    """
    n_dev = mesh.shape[axis]
    T = jax.tree.leaves(elems)[0].shape[0]
    if T % n_dev:
        raise ValueError(f"time length {T} not divisible by mesh axis size {n_dev}")

    spec = P(axis)

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(spec,),
        out_specs=spec,
        **_SHARD_MAP_KW,
    )
    def block_scan(local):
        # 1. local inclusive scan on this device's time block
        scanned = jax.lax.associative_scan(combine, local)
        # 2. exchange block totals: (n_dev, ...) on every device
        total = jax.tree.map(lambda a: a[-1], scanned)
        gathered = jax.tree.map(
            lambda a: jax.lax.all_gather(a, axis_name=axis), total
        )
        # 3. exclusive prefix of the gathered totals for this device's block
        idx = jax.lax.axis_index(axis)

        def fold(i, carry):
            nxt = jax.tree.map(lambda a: a[i], gathered)
            return jax.lax.cond(
                i < idx, lambda: combine(carry, nxt), lambda: carry
            )

        first = jax.tree.map(lambda a: a[0], gathered)
        prefix = jax.lax.fori_loop(1, n_dev, fold, first)
        # apply: block 0 keeps its local scan; others fold the prefix in front
        with_prefix = jax.vmap(lambda e: combine(prefix, e))(scanned)
        return jax.tree.map(
            lambda a, b: jnp.where(idx == 0, a, b), scanned, with_prefix
        )

    return block_scan(elems)
