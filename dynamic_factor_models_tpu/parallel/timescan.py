"""Sequence parallelism: associative scans sharded over a mesh's time axis.

The DFM analogue of ring/context parallelism for long sequences (the global
design requirement; SURVEY.md section 5.7): a time recursion whose combine is
associative — the parallel Kalman filter/smoother elements
(models/pkalman.py), cumulative products of companion matrices for IRFs,
prefix log-likelihoods — runs time-block-sharded across devices:

    1. each device owns one contiguous time slab and runs a LOCAL inclusive
       scan on it — either ``lax.associative_scan`` (log-depth, ~2x combine
       work) or, with ``local="sequential"``, a plain ``lax.scan`` of the
       combine (~1x work; the blocked-slab production choice, since within a
       device depth costs nothing);
    2. the per-slab totals take part in a Hillis-Steele exclusive-prefix
       exchange over the mesh axis: ceil(log2(n_dev)) + 1 non-wrapping
       ``ppermute`` rounds, each moving ONE boundary element (O(k^2) bytes)
       per device — never an all-gather of all n_dev totals;
    3. each device folds its exclusive block prefix into its local results
       (one vmapped combine).

Ragged time lengths are handled by padding the element pytree AT THE END
with repeats of the last element: an inclusive forward scan is causal, so
positions [:T] are unaffected and the padded outputs are sliced off —
boundary/padded steps are exactly inert (pinned in tests/test_pkalman.py).

Implemented with ``shard_map`` so the collectives are explicit and ride the
mesh axis; everything composes with jit.  The reference has no distributed
code of any kind (SURVEY.md section 2.6) — this is new TPU-native design.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from . import shard_map_nocheck

__all__ = ["sharded_scan", "time_sharding"]

_LOCAL_KINDS = ("associative", "sequential")


def time_sharding(mesh: Mesh, axis: str = "time"):
    """NamedSharding placing an elements-pytree's leading (time) dim on
    `axis`."""
    return NamedSharding(mesh, P(axis))


def _local_inclusive_scan(combine, elems, kind: str):
    """Within-slab inclusive scan: log-depth associative form, or the
    cheap sequential ``lax.scan`` of the combine (~1x combine evaluations
    per element vs the up/down-sweep's ~2x — within one device the extra
    depth of the sequential recursion is free, so it wins on FLOPs)."""
    if kind == "associative":
        return jax.lax.associative_scan(combine, elems)
    first = jax.tree.map(lambda a: a[0], elems)
    rest = jax.tree.map(lambda a: a[1:], elems)

    def step(carry, e):
        c = combine(carry, e)
        return c, c

    _, out = jax.lax.scan(step, first, rest)
    return jax.tree.map(
        lambda f, o: jnp.concatenate([f[None], o], axis=0), first, out
    )


def block_scan_body(combine, local_elems, axis: str, n_blocks: int,
                    local: str = "associative"):
    """The slab-scan body, callable inside ANY shard_map that carries mesh
    axis `axis` with one time slab per device: local inclusive scan, then a
    Hillis-Steele exclusive-prefix exchange of the O(1)-per-device slab
    totals, then one vmapped fold of the prefix into the local results.

    ppermute fills non-receiving devices with zeros, which must never flow
    through an arbitrary combine as DATA — every round therefore masks the
    folded value back to the unfolded one on devices that received nothing
    (`jnp.where` on the block index is free; the combine on garbage operands
    is still well-defined arithmetic, merely discarded)."""
    scanned = _local_inclusive_scan(combine, local_elems, local)
    if n_blocks == 1:
        return scanned
    idx = jax.lax.axis_index(axis)
    cur = jax.tree.map(lambda a: a[-1], scanned)
    # comm accounting (PR 17): the exchange moves ONE boundary pytree
    # per device per round, ceil(log2(n_blocks)) rounds plus the final
    # exclusive shift — a static property of the traced program,
    # recorded host-side at trace time (utils/roofline.py)
    from ..utils.roofline import record_collective, tensor_nbytes

    boundary_bytes = sum(
        tensor_nbytes(a) for a in jax.tree.leaves(cur)
    )
    n_rounds = 1 + max(1, (n_blocks - 1)).bit_length()
    record_collective(
        "timescan.block_scan_boundary", axis, boundary_bytes,
        hops=n_rounds, collective="ppermute",
    )
    shift = 1
    while shift < n_blocks:
        perm = [(s, s + shift) for s in range(n_blocks - shift)]
        recv = jax.tree.map(
            lambda a: jax.lax.ppermute(a, axis, perm), cur
        )
        folded = combine(recv, cur)
        cur = jax.tree.map(
            lambda f, c: jnp.where(idx >= shift, f, c), folded, cur
        )
        shift *= 2
    # cur now holds the INCLUSIVE prefix of slab totals; one more shift
    # converts it to the exclusive prefix this slab must fold in front
    perm1 = [(s, s + 1) for s in range(n_blocks - 1)]
    prefix = jax.tree.map(lambda a: jax.lax.ppermute(a, axis, perm1), cur)
    with_prefix = jax.vmap(lambda e: combine(prefix, e))(scanned)
    # slab 0 has no predecessor: its local scan IS the global prefix
    return jax.tree.map(
        lambda a, b: jnp.where(idx == 0, a, b), scanned, with_prefix
    )


def sharded_scan(combine, elems, mesh: Mesh, axis: str = "time",
                 local: str = "associative"):
    """Inclusive associative scan over the leading axis of an elements
    pytree, sharded over `mesh[axis]` in contiguous per-device time slabs.

    `combine(earlier, later)` must be associative (not necessarily
    commutative).  Any time length is accepted: a `T` that does not divide
    the mesh-axis size is padded at the end with repeats of the last
    element (causally inert for an inclusive forward scan) and the padded
    outputs are sliced off.  `local` picks the within-slab recursion:
    "associative" (log-depth) or "sequential" (`lax.scan` of the combine;
    ~half the combine work — the blocked-slab default for EM).  Returns
    the same pytree, scanned, with the same sharding.
    """
    if local not in _LOCAL_KINDS:
        raise ValueError(
            f"local must be one of {_LOCAL_KINDS}, got {local!r}"
        )
    n_dev = mesh.shape[axis]
    T = jax.tree.leaves(elems)[0].shape[0]
    if n_dev <= 1:
        # single-block degeneracy: no collective, no padding
        return _local_inclusive_scan(combine, elems, local)
    slab = -(-T // n_dev)
    T_pad = slab * n_dev
    if T_pad != T:
        # Pad via a static front update + where-mask, NOT
        # concatenate([a, repeats]): an uneven concatenate along the
        # to-be-time-sharded axis miscompiles in the XLA SPMD partitioner
        # when this runs under jit on the mesh (the same hazard documented
        # in models/pkalman._filter_elements_from_collapsed).
        def _pad_with_last(a):
            base = jnp.zeros((T_pad,) + a.shape[1:], a.dtype).at[:T].set(a)
            keep = (jnp.arange(T_pad) < T).reshape(
                (-1,) + (1,) * (a.ndim - 1)
            )
            return jnp.where(keep, base, a[-1])

        elems = jax.tree.map(_pad_with_last, elems)

    # Partitioner firewall: pin the element pytree REPLICATED at the
    # boundary of the manual region.  Without this, GSPMD is free to
    # shard the caller's upstream glue (flips, shifted concatenations,
    # padding) along the time dim, and the XLA SPMD partitioner
    # miscompiles several such ops when the per-device extent is
    # uneven/padded (observed: uneven concatenate, reverse).  All
    # time-axis slicing then happens exclusively inside shard_map, where
    # the blocks are explicit.
    repl = NamedSharding(mesh, P())
    elems = jax.tree.map(
        lambda a: (
            jax.lax.with_sharding_constraint(a, repl)
            if isinstance(a, jax.core.Tracer)
            else a
        ),
        elems,
    )

    spec = P(axis)
    block_scan = shard_map_nocheck(
        lambda e: block_scan_body(combine, e, axis, n_dev, local),
        mesh=mesh,
        in_specs=(spec,),
        out_specs=spec,
    )
    out = block_scan(elems)
    # Same firewall on the way out: the scan's result leaves shard_map
    # committed to P(axis), and caller-side glue on that layout (the
    # smoother's flips, the un-padding slice, lag-one shifts) hits the
    # identical partitioner hazards.  Pinning the result replicated makes
    # the manual region the ONLY place the time axis is ever sharded.
    out = jax.tree.map(
        lambda a: (
            jax.lax.with_sharding_constraint(a, repl)
            if isinstance(a, jax.core.Tracer)
            else a
        ),
        out,
    )
    if T_pad != T:
        out = jax.tree.map(lambda a: a[:T], out)
    return out
