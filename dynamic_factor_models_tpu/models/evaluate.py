"""Pseudo-out-of-sample forecast evaluation: the diffusion-index horse race.

New capability (the reference never forecasts; SURVEY.md section 0): the
standard evaluation exercise of the Stock-Watson diffusion-index literature.
For every rolling origin the factors are re-estimated on that window only
(ONE batched ALS across all origins — `rolling_factor_estimates`), then for
every (origin, series, horizon) the direct h-step regressions

    DFM:  y_{i,t+h} = c + beta' F_t + gamma(L) y_{i,t} + e   (diffusion index)
    AR :  y_{i,t+h} = c + gamma(L) y_{i,t} + e               (benchmark)

are fit within the window by masked least squares and forecast at the
origin; errors against the realized values give per-series RMSEs and the
relative MSE that headlines every paper in this literature.

TPU-first shape: the per-(origin, series) regressions share a design-tensor
layout, so each horizon is ONE einsum pair + one vmapped solve over the
(origins x series) batch — no loops over windows or series; the AR
benchmark reuses the same design tensor with the factor columns dropped.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.linalg import solve_normal
from ..utils.backend import on_backend
from .dfm import DFMConfig, rolling_factor_estimates

__all__ = ["ForecastEvaluation", "evaluate_forecasts", "DieboldMariano", "diebold_mariano"]


class ForecastEvaluation(NamedTuple):
    origins: np.ndarray  # (W,) panel row of each forecast origin
    horizons: np.ndarray  # (H,)
    errors_dfm: jnp.ndarray  # (H, W, N) forecast errors, NaN where undefined
    errors_ar: jnp.ndarray  # (H, W, N)
    rmse_dfm: jnp.ndarray  # (H, N)
    rmse_ar: jnp.ndarray  # (H, N)
    rel_mse: jnp.ndarray  # (H, N) DFM / AR mean-squared-error ratio
    n_forecasts: jnp.ndarray  # (H, N) origins entering each RMSE


@partial(jax.jit, static_argnames=("h", "y_lags", "r"))
def _direct_forecasts(Yw, Fw, y_next, h: int, y_lags: int, r: int):
    """One horizon: fit the direct regressions inside every window and
    forecast at the window end.

    Yw: (W, win, N) raw window panels; Fw: (W, win, r) window factors;
    y_next: (W, N) realized values at origin + h.  Returns (err_dfm,
    err_ar): (W, N) forecast errors (NaN when the regression or the
    realized value is unavailable)."""
    W, win, N = Yw.shape
    dtype = Fw.dtype
    t_idx = jnp.arange(win)

    # per-series lag stack: lags[w, t, i, j] = y_{i, t-j}
    lags = jnp.stack(
        [jnp.roll(Yw, j, axis=1) for j in range(y_lags)], axis=3
    )  # (W, win, N, y_lags); rows t < j are wrapped garbage -> masked below

    ones = jnp.ones((W, win, N, 1), dtype)
    # sanitize like the lag block: NaN * zero-weight is NaN in the Gram
    # einsums, so the isfinite mask terms only work on zero-filled inputs
    Fb = jnp.broadcast_to(jnp.nan_to_num(Fw)[:, :, None, :], (W, win, N, r))
    X = jnp.concatenate([ones, Fb, jnp.nan_to_num(lags)], axis=3)
    K = 1 + r + y_lags

    # training target: y_{i, t+h} (window-relative)
    targ = jnp.roll(Yw, -h, axis=1)  # rows >= win-h wrap -> masked below
    valid = (
        (t_idx[None, :, None] >= y_lags - 1)
        & (t_idx[None, :, None] < win - h)
        & jnp.isfinite(lags).all(axis=3)
        & jnp.isfinite(targ)
        & jnp.isfinite(Fw).all(axis=2)[:, :, None]
    )
    M = valid.astype(dtype)
    tz = jnp.nan_to_num(targ)

    # one Gram/rhs pair over the FULL design; the AR benchmark's normal
    # equations are the factor-free sub-block, sliced instead of recomputed
    # (the einsums are the dominant O(W*win*N*K^2) cost)
    A = jnp.einsum("wtnk,wtn,wtnl->wnkl", X, M, X)
    b = jnp.einsum("wtnk,wtn,wtn->wnk", X, M, tz)
    ok_end = jnp.isfinite(lags[:, -1]).all(axis=2) & jnp.isfinite(
        Fw[:, -1]
    ).all(axis=1)[:, None]

    def fit_and_forecast(cols):
        Ac = A[:, :, np.ix_(cols, cols)[0], np.ix_(cols, cols)[1]]
        bc = b[..., cols]
        beta = jax.vmap(jax.vmap(solve_normal))(Ac, bc)  # (W, N, K')
        x_end = X[:, -1][..., cols]  # (W, N, K') design row at the origin
        enough = M.sum(axis=1) > 2.0 * len(cols)
        fc = jnp.einsum("wnk,wnk->wn", x_end, beta)
        return jnp.where(ok_end & enough, fc, jnp.nan)

    cols_dfm = np.arange(K)
    cols_ar = np.r_[0, np.arange(1 + r, K)]  # drop the factor block
    fc_dfm = fit_and_forecast(cols_dfm)
    fc_ar = fit_and_forecast(cols_ar)
    return fc_dfm - y_next, fc_ar - y_next


def evaluate_forecasts(
    data,
    inclcode,
    window: int,
    nfac: int = 4,
    horizons=(1, 2, 4),
    y_lags: int = 4,
    step: int = 1,
    initperiod: int = 0,
    lastperiod: int | None = None,
    config: DFMConfig = DFMConfig(),
    backend: str | None = None,
    mesh=None,
) -> ForecastEvaluation:
    """Rolling pseudo-out-of-sample evaluation of diffusion-index forecasts
    against direct-AR benchmarks, for every included series and horizon.

    Factors are re-estimated on each length-`window` rolling window (one
    batched ALS — shardable over `mesh`); forecasts are evaluated on the
    TRANSFORMED panel units (the units the reference's tcodes produce).
    rel_mse < 1 means the factors improve on the series' own lags.
    """
    with on_backend(backend):
        data_np = np.asarray(data)
        T = data_np.shape[0]
        last = T - 1 if lastperiod is None else lastperiod
        if not 0 <= last <= T - 1:
            raise ValueError(f"lastperiod={last} outside the {T}-row panel")
        horizons = np.asarray(sorted(horizons), np.int64)
        hmax = int(horizons[-1])
        if last - hmax - initperiod + 1 < window:
            raise ValueError(
                f"window={window} with max horizon {hmax} does not fit in "
                f"rows {initperiod}..{last}"
            )

        rolling = rolling_factor_estimates(
            data_np, inclcode, window, nfac, config,
            step=step, initperiod=initperiod, lastperiod=last - hmax,
            backend=backend, mesh=mesh,
        )
        starts = rolling.starts
        origins = starts + window - 1
        Fw = rolling.batch.factor[:, :, :nfac]  # (W, win, r) window-relative

        incl = np.asarray(inclcode) == 1
        y = data_np[:, incl]  # evaluate the included series
        Yw = jnp.asarray(
            np.stack([y[s : s + window] for s in starts])
        )  # (W, win, N)

        errs_dfm, errs_ar = [], []
        for h in horizons:
            y_next = jnp.asarray(y[origins + int(h)])  # (W, N)
            e_dfm, e_ar = _direct_forecasts(
                Yw, Fw, y_next, int(h), y_lags, nfac
            )
            errs_dfm.append(e_dfm)
            errs_ar.append(e_ar)
        E_dfm = jnp.stack(errs_dfm)  # (H, W, N)
        E_ar = jnp.stack(errs_ar)

        # RMSEs over the origins where BOTH forecasts exist (fair horse
        # race); series with no usable origin report NaN, not a spurious 0
        both = jnp.isfinite(E_dfm) & jnp.isfinite(E_ar)
        n = both.sum(axis=1)
        none = n == 0
        mse_dfm = jnp.where(
            none, jnp.nan,
            jnp.where(both, E_dfm**2, 0.0).sum(axis=1) / jnp.maximum(n, 1),
        )
        mse_ar = jnp.where(
            none, jnp.nan,
            jnp.where(both, E_ar**2, 0.0).sum(axis=1) / jnp.maximum(n, 1),
        )
        return ForecastEvaluation(
            origins=origins,
            horizons=horizons,
            errors_dfm=E_dfm,
            errors_ar=E_ar,
            rmse_dfm=jnp.sqrt(mse_dfm),
            rmse_ar=jnp.sqrt(mse_ar),
            rel_mse=mse_dfm / jnp.maximum(mse_ar, 1e-12),
            n_forecasts=n,
        )


class DieboldMariano(NamedTuple):
    stat: jnp.ndarray  # (H, N) DM statistics (negative = DFM better)
    pvalue: jnp.ndarray  # (H, N) two-sided p-values (normal approximation)
    n: jnp.ndarray  # (H, N) loss-differential observations


def diebold_mariano(ev: ForecastEvaluation) -> DieboldMariano:
    """Diebold-Mariano (1995) equal-predictive-accuracy tests for the horse
    race, with the Harvey-Leybourne-Newbold small-sample correction.

    For each (horizon h, series): d_t = e_dfm^2 - e_ar^2 over the common
    origins; DM = mean(d) / sqrt(LRV(d)/n) with a Bartlett long-run
    variance at lag h-1 (direct h-step errors are MA(h-1) by construction).
    Negative statistics mean the diffusion-index forecast beats the AR
    benchmark; p-values use the normal approximation.
    """
    from jax.scipy.stats import norm

    from ..ops.hac import form_kernel

    e1, e2 = ev.errors_dfm, ev.errors_ar  # (H, W, N)
    both = jnp.isfinite(e1) & jnp.isfinite(e2)
    d = jnp.where(both, jnp.nan_to_num(e1) ** 2 - jnp.nan_to_num(e2) ** 2, 0.0)
    m = both.astype(d.dtype)
    n = m.sum(axis=1)  # (H, N)
    nn = jnp.maximum(n, 1.0)
    dbar = d.sum(axis=1) / nn
    dc = (d - dbar[:, None, :]) * m

    stats, pvals = [], []
    for i, h in enumerate(ev.horizons):
        q = max(int(h) - 1, 0)
        kern = form_kernel(q)
        v = kern[0] * (dc[i] * dc[i]).sum(axis=0)
        W = dc.shape[1]
        for j in range(1, q + 1):
            gam = (dc[i, j:] * dc[i, : W - j]).sum(axis=0)
            v = v + 2.0 * kern[j] * gam
        lrv = v / nn[i]
        # Harvey-Leybourne-Newbold factor for h-step forecasts
        hh = float(h)
        corr = jnp.sqrt(
            jnp.maximum(nn[i] + 1 - 2 * hh + hh * (hh - 1) / nn[i], 1.0) / nn[i]
        )
        # dtype-aware floor: a fixed 1e-300 underflows to 0 in f32 and a
        # zero loss differential would become NaN instead of 0
        floor = jnp.finfo(d.dtype).tiny
        dm = corr * dbar[i] / jnp.sqrt(jnp.maximum(lrv / nn[i], floor))
        dm = jnp.where(n[i] > 2 * hh, dm, jnp.nan)
        stats.append(dm)
        # survival function, not 1-cdf: keeps precision for |dm| > 8
        pvals.append(2.0 * norm.sf(jnp.abs(dm)))
    return DieboldMariano(jnp.stack(stats), jnp.stack(pvals), n)
