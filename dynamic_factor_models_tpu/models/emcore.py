"""Composed EM core steps: the kernels the transform stack resolves to.

PRs 3-10 each made ONE axis of the Stock-Watson EM fast — steady-state
tails (models/steady.py), cross-section sharding (`ssm._sharded_step_for`),
batched refits (emloop.run_em_loop_batched), and the large-N
quasi-differenced AR collapse (`ssm_ar.em_step_ar_qd`) — but every fast
path was its own hand-written kernel, so no panel ever got two wins at
once.  This module holds the PRODUCTS of those axes:

  * `em_step_collapsed` — the explicit collapse pipeline for the iid
    model (partial payload -> unpack -> pre-reduced-stats scan): the
    single-device body of `ssm._sharded_step_for`, i.e. exactly what the
    shard transform wraps a ring all-reduce around.  Drop-in for
    `em_step_stats` (parity pinned), vmappable for batched refits.
  * `_ar_steady_step_for(t_star, block)` — collapsed AR x steady tail:
    a 100k-series panel pays neither N (quasi-differenced collapse) nor
    T (constant-gain tail, closed-form tail moments) per iteration.
    `ar_steady_plan` is the host-side gate; `QDTailStats` holds the
    loop-invariant tail data moments that let the M-step's phi/sigv2
    update skip the tail residual panels entirely.
  * `_ar_sharded_step_for(n_shards)` — collapsed AR x data mesh: the
    collapse's (T, N) pre-scan GEMMs (where ALL large-N FLOPs live) run
    shard-local, one ring all-reduce restores the global payload, the
    N-free scan runs replicated, the per-series M-step stays local.
  * `_ar_steady_sharded_step_for(t_star, block, n_shards)` — all three.

The composition algebra is deliberate: shard wraps the collapse's
pre-scan (the reduction commutes with the series sum — partials reduce
EXACTLY), steady splits the collapse's time axis (head exact, tail
constant), and both leave the numerics of the wrapped pieces untouched —
the steady head scan IS `_filter_ar_qd`'s scan at length t*, and the
sharded payload after reduction IS `_collapse_obs_qd`'s output.
models/transforms.py names these products; utils/compile.py derives AOT
registration from the stack instead of enumerating kernels.

Exactness of the AR x steady tail split: `ar_steady_plan` places t* so
that every cell at t >= t* is INTERIOR (observed with the previous
period observed — it requires a complete tail and pads past the last
incomplete row).  On interior cells the quasi-differencing weights are
per-series constants (Vinv = 1/sigv^2, beta = phi), so the per-step
information matrix is the constant C_inf, log|V_t| is a constant, and
the tail's share of every M-step panel contraction collapses to either
a closed-form covariance sum (n_tail*Ps_inf + S_dev, as in
`ssm._em_step_steady_impl`) or a loop-invariant data moment
(`QDTailStats`).  The only full-T panel work left per iteration is the
collapsed observation b_t (the tail recursion consumes it every step)
and four tail cross GEMMs shared by the loading rhs and the phi/sigv2
moments.
"""

from __future__ import annotations

from functools import lru_cache
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.linalg import solve_normal
from .ssm import (
    PanelStats,
    SSMParams,
    _collapse_obs_stats_partial,
    _em_m_step,
    _filter_scan_collapsed_stats,
    _info_filter_scan,
    _psd_floor,
    _rts_scan,
    _smoother_scan,
    _sym_pack_idx,
    _unpack_collapsed,
)
from .ssm_ar import (
    QDStats,
    SSMARParams,
    _guard_params_qd,
    _m_step_ar_qd,
    _qd_companion,
    _qd_weight_panels,
)

__all__ = [
    "ARSteadyState",
    "QDTailStats",
    "compute_qd_tail_stats",
    "em_step_collapsed",
    "ar_steady_plan",
    "em_step_ar_steady",
    "em_step_ar_sharded",
    "pad_ar_params",
    "unpad_ar_params",
]


# ======================= iid model: explicit collapse ========================


@jax.jit
def em_step_collapsed(params: SSMParams, x, mask, stats: PanelStats):
    """One EM iteration through the explicit collapse pipeline — the
    single-device body of `ssm._sharded_step_for`: per-series partial
    payload, unpack, pre-reduced-stats scan, shared M-step.  Same
    (params, x, mask, stats) -> (params, loglik) contract and fixed point
    as `em_step_stats` (parity pinned at 1e-10 in
    tests/test_transform_stack.py); the program the shard transform
    produces when the mesh has one device, kept mesh-free here so batched
    refits can vmap it over wide buckets."""
    del mask  # the collapse payload already carries the mask
    params = params._replace(Q=_psd_floor(params.Q))
    payload, ll_corr = _collapse_obs_stats_partial(
        params.lam, params.R, x, stats
    )
    C, b, ld_R = _unpack_collapsed(payload, params.r)
    filt, pinvs = _filter_scan_collapsed_stats(
        params, C, b, ld_R, stats.n_obs, ll_corr, want_pinv=True
    )
    s_sm, P_sm, lag1 = _smoother_scan(params, filt, pinvs=pinvs)
    return (
        _em_m_step(params, x, stats.m, s_sm, P_sm, lag1, stats=stats),
        filt.loglik,
    )


# ================== collapsed AR: shard-reducible payloads ===================


def _collapse_obs_qd_partial(params: SSMARParams, x, qd: QDStats):
    """Per-shard half of `ssm_ar._collapse_obs_qd`: every collapsed
    statistic of the quasi-differenced model — the three packed blocks of
    the [f_t, f_{t-1}] information matrix, the gain rhs b, log|V_t|, and
    the data quadratic — is a SUM over series, so a shard computes the
    same GEMMs on its N-slice and one all-reduce of the packed
    (T, 3*npack + 2 + 2r) payload restores the full-panel values
    exactly.  Column layout: [Cu00 | Cu01 | Cu11 | ld_V | xRx | b]."""
    r = params.r
    iu, iv, _ = _sym_pack_idx(r)
    Vinv, beta = _qd_weight_panels(params, qd, transposed=False)
    z = x - beta * qd.x_prev
    u = Vinv * z
    w1 = -Vinv * beta
    pair = params.lam[:, iu] * params.lam[:, iv]  # (N, npack)
    Cu00 = Vinv @ pair
    Cu01 = w1 @ pair
    Cu11 = (-w1 * beta) @ pair
    b = jnp.concatenate([u @ params.lam, (w1 * z) @ params.lam], axis=1)
    ld_V = qd.m @ jnp.log(params.sigv2) - qd.first @ jnp.log1p(
        -params.phi * params.phi
    )
    xRx = (u * z).sum(axis=1)
    return jnp.concatenate(
        [Cu00, Cu01, Cu11, ld_V[:, None], xRx[:, None], b], axis=1
    )


def _unpack_qd_collapsed(payload, r: int):
    """Invert the `_collapse_obs_qd_partial` packing after reduction:
    returns (C (T, 2r, 2r), b (T, 2r), ld_V (T,), xRx (T,))."""
    npack = r * (r + 1) // 2
    _, _, unpack = _sym_pack_idx(r)
    C00 = payload[:, :npack][:, unpack].reshape(-1, r, r)
    C01 = payload[:, npack : 2 * npack][:, unpack].reshape(-1, r, r)
    C11 = payload[:, 2 * npack : 3 * npack][:, unpack].reshape(-1, r, r)
    C = jnp.concatenate(
        [
            jnp.concatenate([C00, C01], axis=2),
            jnp.concatenate([C01, C11], axis=2),
        ],
        axis=1,
    )
    ld_V = payload[:, 3 * npack]
    xRx = payload[:, 3 * npack + 1]
    b = payload[:, 3 * npack + 2 :]
    return C, b, ld_V, xRx


def _qd_filter_from_collapsed(params: SSMARParams, C, b, ld_V, xRx, n_obs,
                              want_pinv=False):
    """`_filter_ar_qd`'s scan assembly on pre-reduced collapsed
    statistics.  Kept as a separate function — not a refactor of
    `ssm_ar._filter_ar_qd` — so the single-device collapsed-AR program
    stays byte-identical to its HLO pin
    (tests/test_perf_regression.py::test_collapsed_ar_scan_body_hlo_is_n_free),
    mirroring `ssm._filter_scan_collapsed_stats`."""
    r = params.r
    Tm, Qs = _qd_companion(params)
    k = Tm.shape[0]
    dtype = b.dtype
    s0 = jnp.zeros(k, dtype)
    P0 = 1e2 * jnp.eye(k, dtype=dtype)
    q2 = 2 * r

    def obs_step(inp, sp):
        Ct, bt, ld, xr, no = inp
        f2 = sp[:q2]
        Cf = jnp.zeros((k, k), dtype).at[:q2, :q2].set(Ct)
        rhs = jnp.zeros(k, dtype).at[:q2].set(bt - Ct @ f2)
        quad0 = xr - 2.0 * (f2 @ bt) + f2 @ Ct @ f2
        return Cf, rhs, ld, quad0, no

    return _info_filter_scan(
        Tm, Qs, (C, b, ld_V, xRx, n_obs), obs_step, s0, P0,
        want_pinv=want_pinv,
    )


# ===================== collapsed AR x steady-state tail ======================


class ARSteadyState(NamedTuple):
    """EM-loop carry of the collapsed-AR steady path: parameters plus the
    previous iteration's steady predicted covariance Pp_inf (DARE warm
    start) and the cumulative doubling count — the `SteadyEMState` twin
    for the quasi-differenced model.  Rides `run_em_loop`'s opaque params
    pytree; the guards' covariance maps and `emaccel.unwrap_state` both
    recurse through the `.params` field."""

    params: SSMARParams
    Pp: jnp.ndarray  # (k, k) previous steady predicted covariance
    riccati_iters: jnp.ndarray  # () i32 cumulative doubling steps


class QDTailStats(NamedTuple):
    """Loop-invariant tail data moments of the quasi-differenced model,
    computed once per estimate at the static t* and threaded through the
    EM loop.  They close the M-step's phi/sigv2 sums over the tail —
    sum ehat^2, sum ehat*ehat_prev, sum ehat_prev^2 expand into these
    data moments plus factor-moment contractions already needed for the
    loading update — so no (n_tail, N) residual panel is ever built."""

    sxx: jnp.ndarray  # (N,) sum_{t>=t*} x_it^2
    sxx1: jnp.ndarray  # (N,) sum_{t>=t*} x_it x_{i,t-1}
    spp: jnp.ndarray  # (N,) sum_{t>=t*} x_{i,t-1}^2


def compute_qd_tail_stats(qd: QDStats, t_star: int) -> QDTailStats:
    """Materialize the per-series tail data moments from the stored
    transposed panels (contiguous (N, n_tail) reductions)."""
    xt = qd.xT[:, t_star:]
    xp = qd.x_prevT[:, t_star:]
    return QDTailStats(
        sxx=(xt * xt).sum(axis=1),
        sxx1=(xt * xp).sum(axis=1),
        spp=(xp * xp).sum(axis=1),
    )


def _qd_steady_collapse_partial(params: SSMARParams, x, qd: QDStats,
                                t_star: int):
    """Split collapse of the quasi-differenced model at the convergence
    horizon: exact per-step statistics on the head rows only (GEMMs at
    (t*, N)), per-series CONSTANTS on the tail — every tail cell is
    interior by `ar_steady_plan`'s placement of t*, so Vinv = 1/sigv^2
    and beta = phi there, making C_t = C_inf and log|V_t| constant.  b_t
    stays full-T (the constant-gain recursion consumes it each step) and
    the tail data quadratic leaves the scan as one scalar.

    Returns a shard-reducible pair: `payload` (T, 3*npack + 2 + 2r) with
    the head statistics in rows [:t*] of the leading columns and b in
    the trailing 2r columns, and `const_vec` (3*npack + 2,) packing
    [c00 | c01 | c11 | ld_inf | quad_tail].  Both are series sums, so
    the sharded variant ring-reduces the payload and psums the
    constants; the single-device step consumes them directly."""
    r = params.r
    iu, iv, _ = _sym_pack_idx(r)
    npack = r * (r + 1) // 2
    Vinv, beta = _qd_weight_panels(params, qd, transposed=False)
    z = x - beta * qd.x_prev
    u = Vinv * z
    w1 = -Vinv * beta
    pair = params.lam[:, iu] * params.lam[:, iv]  # (N, npack)
    # head: exact per-step collapse on the (t*, N) slices
    Cu00_h = Vinv[:t_star] @ pair
    Cu01_h = w1[:t_star] @ pair
    Cu11_h = (-w1[:t_star] * beta[:t_star]) @ pair
    ld_h = qd.m[:t_star] @ jnp.log(params.sigv2) - qd.first[
        :t_star
    ] @ jnp.log1p(-params.phi * params.phi)
    uz = u * z
    xrx_h = uz[:t_star].sum(axis=1)
    # tail: per-series constant weights -> one column sum each
    vinv_c = 1.0 / params.sigv2
    w1_c = -params.phi * vinv_c
    w2_c = params.phi * params.phi * vinv_c
    c00 = vinv_c @ pair
    c01 = w1_c @ pair
    c11 = w2_c @ pair
    ld_inf = jnp.log(params.sigv2).sum()
    quad_tail = uz[t_star:].sum()
    b = jnp.concatenate([u @ params.lam, (w1 * z) @ params.lam], axis=1)
    head_cols = jnp.concatenate(
        [Cu00_h, Cu01_h, Cu11_h, ld_h[:, None], xrx_h[:, None]], axis=1
    )
    payload = (
        jnp.zeros((x.shape[0], 3 * npack + 2 + 2 * r), x.dtype)
        .at[:t_star, : 3 * npack + 2]
        .set(head_cols)
        .at[:, 3 * npack + 2 :]
        .set(b)
    )
    const_vec = jnp.concatenate(
        [c00, c01, c11, ld_inf[None], quad_tail[None]]
    )
    return payload, const_vec


def _unpack_qd_steady(payload, const_vec, r: int, t_star: int):
    """Invert the `_qd_steady_collapse_partial` packing after reduction."""
    npack = r * (r + 1) // 2
    _, _, unpack = _sym_pack_idx(r)

    def blocks(c00u, c01u, c11u):
        C00 = c00u[..., unpack].reshape(*c00u.shape[:-1], r, r)
        C01 = c01u[..., unpack].reshape(*c00u.shape[:-1], r, r)
        C11 = c11u[..., unpack].reshape(*c00u.shape[:-1], r, r)
        return jnp.concatenate(
            [
                jnp.concatenate([C00, C01], axis=-1),
                jnp.concatenate([C01, C11], axis=-1),
            ],
            axis=-2,
        )

    head = payload[:t_star]
    C_head = blocks(
        head[:, :npack], head[:, npack : 2 * npack],
        head[:, 2 * npack : 3 * npack],
    )
    ld_h = head[:, 3 * npack]
    xrx_h = head[:, 3 * npack + 1]
    b = payload[:, 3 * npack + 2 :]
    C_inf = blocks(
        const_vec[:npack], const_vec[npack : 2 * npack],
        const_vec[2 * npack : 3 * npack],
    )
    ld_inf = const_vec[3 * npack]
    quad_tail = const_vec[3 * npack + 1]
    return C_head, b, ld_h, xrx_h, C_inf, ld_inf, quad_tail


def _ar_steady_core(params: SSMARParams, C_head, b, ld_h, xrx_h, C_inf,
                    ld_inf, quad_tail, n_obs, Pp0, t_star: int, block: int):
    """Forward + backward pass of the collapsed-AR steady split: DARE at
    the 2r-dim collapsed observation (warm-started from the previous
    iteration's Pp_inf), exact head scan of t* steps — the same scan body
    as `_filter_ar_qd` — then the factorization-free constant-gain tail
    and the boundary-closed RTS head, exactly as
    `ssm._em_step_steady_impl` does for the iid model.  Returns
    (steady, f_sm (T, k), P_head (t*, k, k), lag1_h (t*, k, k), ll)."""
    from .steady import steady_smooth_tail, steady_state, steady_tail

    Tm, Qs = _qd_companion(params)
    k = Tm.shape[0]
    q2 = 2 * params.r
    dtype = b.dtype
    s0 = jnp.zeros(k, dtype)
    P0 = 1e2 * jnp.eye(k, dtype=dtype)
    st = steady_state(Tm, C_inf, Qs, q=q2, Pp0=Pp0)

    def obs_step(inp, sp):
        Ct, bt, ld, xr, no = inp
        f2 = sp[:q2]
        Cf = jnp.zeros((k, k), dtype).at[:q2, :q2].set(Ct)
        rhs = jnp.zeros(k, dtype).at[:q2].set(bt - Ct @ f2)
        quad0 = xr - 2.0 * (f2 @ bt) + f2 @ Ct @ f2
        return Cf, rhs, ld, quad0, no

    means_h, covs_h, pmeans_h, pcovs_h, lls_h = _info_filter_scan(
        Tm, Qs, (C_head, b[:t_star], ld_h, xrx_h, n_obs[:t_star]),
        obs_step, s0, P0,
    )
    ld_const = ld_inf + st.ld_pp - st.ld_pu
    su_tail, lls_tail = steady_tail(
        Tm, C_inf, st.Pu[:q2, :q2], st.K, st.Abar, b[t_star:],
        means_h[-1], n_obs[t_star:], ld_const, block=block,
    )
    s_sm_tail = steady_smooth_tail(Tm, st.J, su_tail, block=block)
    s_all, P_head, lag1_h = _rts_scan(
        Tm,
        jnp.concatenate([means_h, s_sm_tail[:1]]),
        jnp.concatenate([covs_h, st.Ps[None]]),
        jnp.concatenate([pmeans_h, (Tm @ means_h[-1])[None]]),
        jnp.concatenate([pcovs_h, st.Pp[None]]),
    )
    f_sm = jnp.concatenate([s_all[:t_star], s_sm_tail])
    # steady_tail's quadratic omits the data term x'V^-1x (it rides the
    # reduced scalar), so the tail likelihood closes with -quad_tail/2
    ll = lls_h.sum() + lls_tail.sum() - 0.5 * quad_tail
    return st, f_sm, P_head[:t_star], lag1_h, ll


def _m_step_ar_qd_steady(params: SSMARParams, x, qd: QDStats,
                         tail: QDTailStats, f_sm, P_head, lag1_h, st,
                         t_star: int):
    """`_m_step_ar_qd` with the tail contractions in closed form.

    Every tail sum splits into loop-invariant data moments (QDTailStats),
    the closed-form tail covariance sum Psum = n_tail*Ps_inf + S_dev, and
    four (N, n_tail) x (n_tail, r) cross GEMMs Sxf0/Sxf1/Spf0/Spf1 that
    the loading rhs and the phi/sigv2 moments share.  Head sums run on
    (t*,)-sliced panels exactly as the full M-step does.  Same fixed
    point as `_m_step_ar_qd` up to the steady approximation the plan
    verified (tail covariances within the DARE tolerance of their exact
    values)."""
    r, p = params.r, params.p
    rp = r * p
    Tn = x.shape[0]
    n_tail = Tn - t_star
    iu, iv, unpack = _sym_pack_idx(r)
    f0 = f_sm[:, :r]
    f1 = f_sm[:, r : 2 * r]
    f0h, f1h = f0[:t_star], f1[:t_star]
    f0t, f1t = f0[t_star:], f1[t_star:]

    # --- closed-form tail factor moments ---
    Psum = n_tail * st.Ps + st.Sdev  # sum_{t>=t*} P_sm_t
    Pt00 = Psum[:r, :r]
    Pt01 = Psum[:r, r : 2 * r]
    Pt11 = Psum[r : 2 * r, r : 2 * r]
    sumF00_t = (f0t.T @ f0t + Pt00)[iu, iv]  # (npack,)
    sumF11_t = (f1t.T @ f1t + Pt11)[iu, iv]
    G01t = f0t.T @ f1t + Pt01
    sumF01s_t = (G01t + G01t.T)[iu, iv]

    # --- head factor moments (packed, per step) ---
    P00h = P_head[:, :r, :r]
    P01h = P_head[:, :r, r : 2 * r]
    P11h = P_head[:, r : 2 * r, r : 2 * r]
    F00u_h = f0h[:, iu] * f0h[:, iv] + P00h[:, iu, iv]
    F11u_h = f1h[:, iu] * f1h[:, iv] + P11h[:, iu, iv]
    F01_h = f0h[:, :, None] * f1h[:, None, :] + P01h
    F01su_h = (F01_h + jnp.swapaxes(F01_h, 1, 2))[:, iu, iv]

    # --- loadings: head weight panels + constant tail weights ---
    VinvT_h, betaT_h = (
        (qd.mT[:, :t_star] - qd.firstT[:, :t_star]
         * (params.phi * params.phi)[:, None]) / params.sigv2[:, None],
        params.phi[:, None] * qd.interiorT[:, :t_star],
    )
    w1T_h = -VinvT_h * betaT_h
    w2T_h = -w1T_h * betaT_h
    vinv_c = 1.0 / params.sigv2
    w1_c = -params.phi * vinv_c
    w2_c = params.phi * params.phi * vinv_c
    G = (
        VinvT_h @ F00u_h + w1T_h @ F01su_h + w2T_h @ F11u_h
        + vinv_c[:, None] * sumF00_t[None, :]
        + w1_c[:, None] * sumF01s_t[None, :]
        + w2_c[:, None] * sumF11_t[None, :]
    )
    Gram = G[:, unpack].reshape(-1, r, r)
    zT_h = qd.xT[:, :t_star] - betaT_h * qd.x_prevT[:, :t_star]
    rhs_h = (VinvT_h * zT_h) @ f0h + (w1T_h * zT_h) @ f1h
    # tail cross GEMMs, shared with the phi/sigv2 moments below
    Sxf0 = qd.xT[:, t_star:] @ f0t  # (N, r)
    Sxf1 = qd.xT[:, t_star:] @ f1t
    Spf0 = qd.x_prevT[:, t_star:] @ f0t
    Spf1 = qd.x_prevT[:, t_star:] @ f1t
    rhs_t = (
        vinv_c[:, None] * (Sxf0 - params.phi[:, None] * Spf0)
        + w1_c[:, None] * (Sxf1 - params.phi[:, None] * Spf1)
    )
    lam = jax.vmap(solve_normal)(Gram, rhs_h + rhs_t)

    # --- phi / sigv2 given the new loadings ---
    dupe = jnp.where(iu == iv, 1.0, 2.0).astype(x.dtype)
    pair2 = (lam[:, iu] * lam[:, iv]) * dupe[None, :]  # (N, npack)
    # head: materialized residual panels at (t*, N), as in the full step
    ehat_h = x[:t_star] - f0h @ lam.T
    ehat_p_h = qd.x_prev[:t_star] - f1h @ lam.T
    q00_h = P00h[:, iu, iv] @ pair2.T  # (t*, N)
    q11_h = P11h[:, iu, iv] @ pair2.T
    P01s_h = 0.5 * (P01h + jnp.swapaxes(P01h, 1, 2))
    q01_h = P01s_h[:, iu, iv] @ pair2.T
    int_h = qd.interior[:t_star]
    num_h = jnp.einsum("tn,tn->n", int_h, ehat_h * ehat_p_h + q01_h)
    den_h = jnp.einsum("tn,tn->n", int_h, ehat_p_h * ehat_p_h + q11_h)
    S2_h = jnp.einsum("tn,tn->n", int_h, ehat_h * ehat_h + q00_h)
    # tail: expand the residual sums into data moments + factor moments
    #   sum ehat*ehat_p = sxx1 - lam.(Sxf1 + Spf0) + lam'(sym tail F01)lam
    num_t = (
        tail.sxx1 - (lam * (Sxf1 + Spf0)).sum(axis=1)
        + pair2 @ (0.5 * sumF01s_t)
    )
    den_t = tail.spp - 2.0 * (lam * Spf1).sum(axis=1) + pair2 @ sumF11_t
    S2_t = tail.sxx - 2.0 * (lam * Sxf0).sum(axis=1) + pair2 @ sumF00_t
    num = num_h + num_t
    den = den_h + den_t
    S2 = S2_h + S2_t
    phi = jnp.clip(num / jnp.maximum(den, 1e-12), -0.99, 0.99)
    sigv2 = (S2 - 2.0 * phi * num + phi * phi * den) / jnp.maximum(
        qd.n_int, 1.0
    )
    sigv2 = jnp.maximum(sigv2, 1e-8)
    has = qd.n_int > 0
    phi = jnp.where(has, phi, params.phi)
    sigv2 = jnp.where(has, sigv2, params.sigv2)

    # --- factor VAR: head sums + closed-form tail constants ---
    s1, s0_ = f_sm[1:, :r], f_sm[:-1, :rp]
    S11 = (
        jnp.einsum("tr,ts->rs", s1, s1)
        + P_head[1:, :r, :r].sum(axis=0)
        + Psum[:r, :r]
    )
    S00 = (
        jnp.einsum("tk,tl->kl", s0_, s0_)
        + P_head[:, :rp, :rp].sum(axis=0)
        + (Psum - st.Pu)[:rp, :rp]
    )
    S10 = (
        jnp.einsum("tr,tk->rk", s1, s0_)
        + lag1_h[:, :r, :rp].sum(axis=0)
        + ((Psum - st.Ps) @ st.J.T)[:r, :rp]
    )
    Ak = S10 @ jnp.linalg.pinv(S00, hermitian=True)
    Q = _psd_floor((S11 - Ak @ S10.T) / (Tn - 1))
    A = jnp.stack([Ak[:, i * r : (i + 1) * r] for i in range(p)])
    return SSMARParams(lam, phi, sigv2, A, Q)


def _ar_steady_impl(state: ARSteadyState, x, qd: QDStats,
                    tail: QDTailStats, t_star: int, block: int):
    params = _guard_params_qd(state.params)
    payload, const_vec = _qd_steady_collapse_partial(params, x, qd, t_star)
    C_head, b, ld_h, xrx_h, C_inf, ld_inf, quad_tail = _unpack_qd_steady(
        payload, const_vec, params.r, t_star
    )
    st, f_sm, P_head, lag1_h, ll = _ar_steady_core(
        params, C_head, b, ld_h, xrx_h, C_inf, ld_inf, quad_tail,
        qd.n_obs, state.Pp, t_star, block,
    )
    new = _m_step_ar_qd_steady(
        params, x, qd, tail, f_sm, P_head, lag1_h, st, t_star
    )
    return (
        ARSteadyState(new, st.Pp, state.riccati_iters + st.riccati_iters),
        ll,
    )


@lru_cache(maxsize=None)
def _ar_steady_step_for(t_star: int, block: int = 0):
    """The jitted collapsed-AR steady EM step specialized to a static
    convergence horizon and tail block size; lru_cached and named per
    specialization so `run_em_loop`'s AOT-registry statics key
    (utils.compile.aot_statics uses __module__ + __qualname__) is stable
    across processes, like `ssm._steady_step_for`."""

    def step(state: ARSteadyState, x, qd: QDStats, tail: QDTailStats):
        return _ar_steady_impl(state, x, qd, tail, t_star, block)

    step.__name__ = step.__qualname__ = (
        f"em_step_ar_steady_t{t_star}_b{block}"
    )
    step.__module__ = __name__
    return jax.jit(step)


def em_step_ar_steady(state, x, qd: QDStats, tail: QDTailStats,
                      t_star: int, block: int = 0):
    """One collapsed-AR steady EM iteration (see `_ar_steady_impl`).
    `state` is an `ARSteadyState`; a bare `SSMARParams` is wrapped with a
    cold-start carry."""
    if not isinstance(state, ARSteadyState):
        k = state.r * max(state.p, 2)
        state = ARSteadyState(
            params=state,
            Pp=jnp.zeros((k, k), state.lam.dtype),
            riccati_iters=jnp.asarray(0, jnp.int32),
        )
    return _ar_steady_step_for(int(t_star), int(block))(state, x, qd, tail)


def ar_steady_plan(params: SSMARParams, mask, min_tail: int = 8):
    """Host-side dispatch gate for the collapsed-AR steady tail — the
    `ssm._steady_plan` twin for the quasi-differenced model.

    Requirements beyond the iid plan's: the tail must be INTERIOR, not
    just complete — every tail cell needs its previous period observed so
    the quasi-differencing weights are the per-series constants the
    closed forms assume.  Placing t* at least one step past the last
    incomplete row guarantees it (row t*-1 is fully observed), and the
    same 1.5x + 8 safety pad as the iid plan covers EM's parameter drift
    between horizon computations.  MUST be called on the unpadded mask:
    an all-missing padded series would push `complete_from` to T and gate
    the plan off, even though padded series contribute exactly zero to
    every tail sum.

    Returns (t_star, SteadyState at the init params, rho) or None."""
    from .steady import convergence_horizon, steady_state

    m_np = np.asarray(mask)
    T = int(m_np.shape[0])
    full = m_np.all(axis=1)
    nz = np.nonzero(~full)[0]
    complete_from = 0 if nz.size == 0 else int(nz[-1]) + 1
    if complete_from >= T:
        return None
    params = _guard_params_qd(params)
    r = params.r
    Tm, Qs = _qd_companion(params)
    vinv_c = np.asarray(1.0 / params.sigv2)
    phi = np.asarray(params.phi)
    lam = np.asarray(params.lam)
    C00 = (lam.T * vinv_c) @ lam
    C01 = (lam.T * (-phi * vinv_c)) @ lam
    C11 = (lam.T * (phi * phi * vinv_c)) @ lam
    C_inf = jnp.asarray(
        np.block([[C00, C01], [C01.T, C11]]), lam.dtype
    )
    # C01 = sum_i w1_c_i lam_i lam_i' is symmetric; np.block keeps the
    # exact float symmetry via the explicit transpose
    st = steady_state(Tm, C_inf, Qs, q=2 * r)
    if not bool(st.converged):
        return None
    k = Tm.shape[0]
    P0 = 1e2 * jnp.eye(k, dtype=Tm.dtype)
    t_model, rho = convergence_horizon(
        Tm, C_inf, Qs, st, P0, t_max=max(4 * T, 64)
    )
    if t_model > T:
        return None
    t_pad = int(np.ceil(1.5 * t_model)) + 8
    t_star = max(complete_from + t_pad, 2)
    if T - t_star < max(t_pad, min_tail):
        return None
    return t_star, st, rho


# ======================= collapsed AR x data mesh ============================


def _ar_params_spec(dax="data"):
    from ..parallel.mesh import P

    return SSMARParams(
        lam=P(dax, None), phi=P(dax), sigv2=P(dax), A=P(), Q=P()
    )


def _qd_stats_spec(dax="data"):
    from ..parallel.mesh import P

    return QDStats(
        m=P(None, dax), first=P(None, dax), interior=P(None, dax),
        x_prev=P(None, dax), mT=P(dax, None), firstT=P(dax, None),
        interiorT=P(dax, None), xT=P(dax, None),
        x_prevT=P(dax, None), n_int=P(dax), n_obs=P(),
    )


def _ar_sharded_step_for(n_shards: int, hosts: int = 0):
    """The collapsed-AR EM step sharded over the ``("data",)`` N-axis mesh
    — same (params, x, qd) -> (params, loglik) contract as
    `em_step_ar_qd`, N must be a shard multiple (`estimate_dfm_em_ar`
    pads with inert series first).

    The shard transform wraps exactly the collapse's pre-scan: the
    (T, N) quasi-differencing GEMMs — where ALL the large-N FLOPs live —
    run on local N-slices, one ring all-reduce of the packed payload
    (`ops.pallas_gram.ring_allreduce`: Pallas RDMA ring on TPU, lax.psum
    on the CPU mesh) restores the global collapsed statistics, the
    N-free O(k^3) scan and factor-VAR moments run replicated, and the
    M-step's per-series solves stay shard-local.  Inert-padding contract:
    a padded series (lam = 0, phi = 0, sigv2 = 1, all-False mask) has
    Vinv = beta = z = 0, so it contributes exactly zero to every payload
    column, its Gram/rhs are zero (the minimum-norm solve returns
    lam = 0), and has = n_int > 0 keeps its phi/sigv2 fixed.

    `hosts=0` resolves to `jax.process_count()` (see
    `ssm._sharded_step_for`): hosts<=1 keeps the flat single-host mesh
    and program; hosts>1 runs the process-spanning ``("dcn", "ici")``
    mesh with the hierarchical ICI-ring + DCN-psum reduction.  Plain
    dispatcher over an lru_cached impl so `f(2)` and `f(2, hosts=0)`
    return one object (resolve-identity pins)."""
    from .ssm import _resolve_mesh_hosts

    return _ar_sharded_step_impl(int(n_shards), _resolve_mesh_hosts(hosts))


@lru_cache(maxsize=None)
def _ar_sharded_step_impl(n_shards: int, hosts: int):
    from ..ops.pallas_gram import hierarchical_allreduce, ring_allreduce
    from ..parallel import shard_map_nocheck
    from ..parallel.mesh import P, data_mesh

    mesh = data_mesh(n_shards, hosts=hosts)
    if hosts > 1:
        dax = ("dcn", "ici")
        n_ici = n_shards // hosts

        def _reduce(payload):
            return hierarchical_allreduce(payload, "ici", "dcn", n_ici)

        name = f"em_step_ar_sharded_d{n_shards}_h{hosts}"
    else:
        dax = "data"

        def _reduce(payload):
            return ring_allreduce(payload, "data", n_shards)

        name = f"em_step_ar_sharded_d{n_shards}"

    def step(params: SSMARParams, x, qd: QDStats):
        params = _guard_params_qd(params)
        payload = _collapse_obs_qd_partial(params, x, qd)
        payload = _reduce(payload)
        C, b, ld_V, xRx = _unpack_qd_collapsed(payload, params.r)
        means, covs, pmeans, pcovs, lls, pinvs = _qd_filter_from_collapsed(
            params, C, b, ld_V, xRx, qd.n_obs, want_pinv=True
        )
        Tm, _ = _qd_companion(params)
        s_sm, P_sm, lag1 = _rts_scan(
            Tm, means, covs, pmeans, pcovs, pinvs=pinvs
        )
        return _m_step_ar_qd(params, x, qd, s_sm, P_sm, lag1), lls.sum()

    step.__name__ = step.__qualname__ = name
    step.__module__ = __name__

    return jax.jit(
        shard_map_nocheck(
            step,
            mesh=mesh,
            in_specs=(_ar_params_spec(dax), P(None, dax), _qd_stats_spec(dax)),
            out_specs=(_ar_params_spec(dax), P()),
        )
    )


def em_step_ar_sharded(params: SSMARParams, x, qd: QDStats, n_shards: int):
    """One sharded collapsed-AR EM iteration (see `_ar_sharded_step_for`)."""
    return _ar_sharded_step_for(int(n_shards))(params, x, qd)


def _ar_steady_sharded_step_for(t_star: int, block: int, n_shards: int, hosts: int = 0):
    """All three composed axes on one panel: the quasi-differenced
    collapse (N-free scan), the steady tail (T-free tail), and the data
    mesh (shard-local pre-scan GEMMs).  The steady split's payload and
    constant vector are both series sums, so the shard transform applies
    unchanged: one ring all-reduce + one psum per iteration, then the
    replicated steady core and the shard-local closed-form M-step.
    `hosts` follows `_ar_sharded_step_for` (0 = process count; >1 =
    hierarchical ICI+DCN reduction)."""
    from .ssm import _resolve_mesh_hosts

    return _ar_steady_sharded_step_impl(
        int(t_star), int(block), int(n_shards), _resolve_mesh_hosts(hosts)
    )


@lru_cache(maxsize=None)
def _ar_steady_sharded_step_impl(t_star: int, block: int, n_shards: int, hosts: int):
    from ..ops.pallas_gram import hierarchical_allreduce, ring_allreduce
    from ..parallel import shard_map_nocheck
    from ..parallel.mesh import P, data_mesh

    mesh = data_mesh(n_shards, hosts=hosts)
    if hosts > 1:
        dax = ("dcn", "ici")
        n_ici = n_shards // hosts

        def _reduce(payload):
            return hierarchical_allreduce(payload, "ici", "dcn", n_ici)

        name = f"em_step_ar_all_t{t_star}_b{block}_d{n_shards}_h{hosts}"
    else:
        dax = "data"

        def _reduce(payload):
            return ring_allreduce(payload, "data", n_shards)

        name = f"em_step_ar_all_t{t_star}_b{block}_d{n_shards}"

    def step(state: ARSteadyState, x, qd: QDStats, tail: QDTailStats):
        params = _guard_params_qd(state.params)
        payload, const_vec = _qd_steady_collapse_partial(
            params, x, qd, t_star
        )
        payload = _reduce(payload)
        # comm accounting (PR 17): the steady split's second collective —
        # one psum of the O(r^2) constant vector over the full series
        # axis — recorded host-side at trace time like the payload reduce
        from ..utils.roofline import record_collective, tensor_nbytes

        record_collective(
            "emcore.steady_const_vec", dax, tensor_nbytes(const_vec),
            hops=1, collective="psum", dtype=str(const_vec.dtype),
        )
        const_vec = jax.lax.psum(const_vec, dax)
        C_head, b, ld_h, xrx_h, C_inf, ld_inf, quad_tail = (
            _unpack_qd_steady(payload, const_vec, params.r, t_star)
        )
        st, f_sm, P_head, lag1_h, ll = _ar_steady_core(
            params, C_head, b, ld_h, xrx_h, C_inf, ld_inf, quad_tail,
            qd.n_obs, state.Pp, t_star, block,
        )
        new = _m_step_ar_qd_steady(
            params, x, qd, tail, f_sm, P_head, lag1_h, st, t_star
        )
        return (
            ARSteadyState(
                new, st.Pp, state.riccati_iters + st.riccati_iters
            ),
            ll,
        )

    step.__name__ = step.__qualname__ = name
    step.__module__ = __name__

    state_spec = ARSteadyState(
        params=_ar_params_spec(dax), Pp=P(), riccati_iters=P()
    )
    tail_spec = QDTailStats(sxx=P(dax), sxx1=P(dax), spp=P(dax))
    return jax.jit(
        shard_map_nocheck(
            step,
            mesh=mesh,
            in_specs=(
                state_spec, P(None, dax), _qd_stats_spec(dax), tail_spec,
            ),
            out_specs=((state_spec, P())),
        )
    )


# ======================= inert AR-series padding =============================


def pad_ar_params(params: SSMARParams, n_pad: int) -> SSMARParams:
    """Extend an AR parameter set with `n_pad - N` inert series: zero
    loadings, zero AR roots, unit innovation variances — together with an
    all-False mask column these contribute exactly zero to every collapse
    payload column, Gram, rhs, and log-det term (the `pad_ssm_params`
    twin; inertness argued at `_ar_sharded_step_for`)."""
    N = params.lam.shape[0]
    if n_pad <= N:
        return params
    dtype = params.lam.dtype
    extra = n_pad - N
    return params._replace(
        lam=jnp.concatenate(
            [params.lam, jnp.zeros((extra, params.r), dtype)]
        ),
        phi=jnp.concatenate([params.phi, jnp.zeros(extra, dtype)]),
        sigv2=jnp.concatenate([params.sigv2, jnp.ones(extra, dtype)]),
    )


def unpad_ar_params(params: SSMARParams, n_real: int) -> SSMARParams:
    """Slice an AR parameter set back to the real series."""
    return params._replace(
        lam=params.lam[:n_real],
        phi=params.phi[:n_real],
        sigv2=params.sigv2[:n_real],
    )
