"""Bayesian state-space DFM: Gibbs sampling with a Durbin-Koopman
simulation smoother, chains ``vmap``-ed (and mesh-shardable) on device.

New capability (no counterpart in the reference, which is entirely
frequentist — dfm_functions.ipynb implements only the non-parametric ALS
path, SURVEY.md section 0): full posterior inference for the state-space DFM

    x_t = Lam f_t + eps_t,   eps_t ~ N(0, diag(R))
    f_t = A_1 f_{t-1} + ... + A_p f_{t-p} + u_t,   u_t ~ N(0, Q)

with conjugate priors (Normal-InverseGamma rows of Lam/R, Matrix-Normal-
InverseWishart factor VAR).  Factor paths are drawn with the Durbin-Koopman
(2002) mean-correction simulation smoother on the masked information-form
Kalman filter (ssm._filter_scan) — exact for any factor-lag order p, unlike
a backward pass that conditions only on the drawn f_{t+1}, and built from
two filter+RTS scans with no sequential conditional draws.

TPU-first design: one Gibbs iteration (two filter+RTS scans for the factor
draw + three conjugate blocks) is a single jitted function; the iteration
loop is a ``lax.scan``; independent chains are one ``vmap`` whose chain axis
shards over a device mesh exactly like bootstrap replications
(models/favar.py).
"""

from __future__ import annotations

import warnings
from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
import jax.scipy.linalg as jsl
import numpy as np

from ..ops.linalg import solve_normal, standardize_data
from ..ops.masking import fillz, mask_of
from ..parallel.mesh import NamedSharding, P
from ..utils.backend import on_backend
from .dfm import DFMConfig
from .ssm import (
    LARGE_N_THRESHOLD,
    SSMParams,
    _collapse_obs,
    _companion,
    _filter_scan,
    _filter_scan_collapsed_stats,
    _init_params_from_als,
    _init_state,
    _psd_floor,
    _psd_sqrt,
    _smoother_scan,
)

__all__ = [
    "BayesPriors",
    "BayesResults",
    "PosteriorForecast",
    "PosteriorSeriesIRFs",
    "BayesModelComparison",
    "dic",
    "select_nfac_bayes",
    "estimate_dfm_bayes",
    "simulation_smoother",
    "posterior_forecast",
    "posterior_irfs",
    "posterior_series_irfs",
    "rhat",
    "ess",
]


class BayesPriors(NamedTuple):
    """Conjugate prior hyperparameters (diffuse defaults).

    lam_scale: prior sd tau of each loading, lam_i ~ N(0, tau^2 I);
    r_shape/r_rate: R_i ~ InvGamma(a0, b0);
    q_df_extra: IW degrees of freedom nu0 = r + 1 + q_df_extra;
    q_scale: IW scale matrix S0 = q_scale * I.
    """

    lam_scale: float = 10.0
    r_shape: float = 0.01
    r_rate: float = 0.01
    q_df_extra: float = 1.0
    q_scale: float = 0.01


class BayesResults(NamedTuple):
    factor_draws: jnp.ndarray  # (chains_kept, keep, T, r)
    lam_draws: jnp.ndarray  # (chains_kept, keep, N, r)
    r_draws: jnp.ndarray  # (chains_kept, keep, N)
    a_draws: jnp.ndarray  # (chains_kept, keep, p, r, r)
    q_draws: jnp.ndarray  # (chains_kept, keep, r, r)
    loglik_path: np.ndarray  # (chains, total_iters) filter loglik per sweep
    rhat_loglik: float  # split-R-hat of the post-burn loglik path
    stds: jnp.ndarray  # per-series standardization scale
    means: jnp.ndarray  # per-series means (original units)
    # appended with defaults so pre-scenario-engine construction sites and
    # pickles keep working; draw arrays hold HEALTHY chains only, the
    # loglik path keeps every chain (the diagnostic trace)
    chain_health: np.ndarray | None = None  # (chains,) utils.guards codes
    ess_loglik: float | None = None  # cross-chain ESS of the kept loglik


def _draw_mvn(key, mean, cov):
    """One draw from N(mean, cov) via Cholesky with a jitter floor scaled to
    the covariance magnitude (cf. ssm._psd_floor): backward-pass downdates on
    O(1e2) filtered covariances carry rounding error far above absolute eps,
    and a NaN Cholesky here would silently poison the whole chain scan."""
    d = mean.shape[0]
    eps = jnp.asarray(jnp.finfo(cov.dtype).eps, cov.dtype)
    scale = jnp.maximum(jnp.diagonal(cov).max(), 1.0)
    L = jnp.linalg.cholesky(
        0.5 * (cov + cov.T) + 16.0 * eps * scale * jnp.eye(d, dtype=cov.dtype)
    )
    return mean + L @ jax.random.normal(key, (d,), dtype=cov.dtype)


def _simulation_smoother_core(params: SSMParams, x, mask, key, qdiag=None):
    """Draw a factor path f_{0:T-1} | x, params by the Durbin-Koopman (2002)
    mean-correction simulation smoother.  Returns (f_draw (T, r), loglik).

    Forward-simulate an unconditional path (s+, x+) from the model, smooth
    both the real and the simulated data with the shared RTS machinery, and
    return f+ + E[f|x] - E[f|x+].  Exact for ANY factor-lag order p — a
    Carter-Kohn backward pass that conditions only on the drawn f_{t+1}
    (the seemingly natural choice for the singular companion transition) is
    exact only for p=1, because for p>=2 the future f_{t+2:t+p} loads on
    f_t directly through the companion state.  Two filter+smoother scans
    per draw, no sequential conditional sampling — the TPU-friendly shape.

    `qdiag` (T, r) switches the factor innovations to time-varying diagonal
    variances (stochastic-volatility models, models/sv.py).
    """
    r = params.r
    Tm, _ = _companion(params)
    k = Tm.shape[0]
    T = x.shape[0]
    dtype = x.dtype

    k0, ku, ke = jax.random.split(key, 3)
    s0_mean, P0 = _init_state(params)
    s0 = s0_mean + jnp.linalg.cholesky(P0) @ jax.random.normal(k0, (k,), dtype)
    if qdiag is None:
        Lq = jnp.linalg.cholesky(_psd_floor(params.Q))
        u = jax.random.normal(ku, (T, r), dtype) @ Lq.T
    else:
        u = jnp.sqrt(qdiag) * jax.random.normal(ku, (T, r), dtype)

    def sim_step(s_prev, u_t):
        s_t = (Tm @ s_prev).at[:r].add(u_t)
        return s_t, s_t

    _, s_plus = jax.lax.scan(sim_step, s0, u)
    f_plus = s_plus[:, :r]
    eps = jax.random.normal(ke, x.shape, dtype) * jnp.sqrt(params.R)
    mb = mask.astype(bool)
    x_plus = jnp.where(mb, f_plus @ params.lam.T + eps, 0.0)

    filt = _filter_scan(params, x, mask, qdiag)
    filt_p = _filter_scan(params, x_plus, mask, qdiag)
    sm, _, _ = _smoother_scan(params, filt)
    sm_p, _, _ = _smoother_scan(params, filt_p)
    f = f_plus + sm[:, :r] - sm_p[:, :r]
    return f, filt.loglik


def _sim_plus_path(params: SSMParams, key, T: int, dtype):
    """Unconditional forward simulation of the DK smoother's f+ path: draw
    s_0 ~ N(s0, P0) and iterate the factor VAR with fresh innovations.
    Splits `key` three ways exactly like `_simulation_smoother_core`
    (k0 init, ku innovations) and returns (f_plus (T, r), unused third
    subkey) — the caller spends the third key on its measurement-noise
    draw (dense: eps panel; collapsed: the r-dim zeta)."""
    r = params.r
    Tm, _ = _companion(params)
    k = Tm.shape[0]
    k0, ku, ke = jax.random.split(key, 3)
    s0_mean, P0 = _init_state(params)
    s0 = s0_mean + jnp.linalg.cholesky(P0) @ jax.random.normal(k0, (k,), dtype)
    Lq = jnp.linalg.cholesky(_psd_floor(params.Q))
    u = jax.random.normal(ku, (T, r), dtype) @ Lq.T

    def sim_step(s_prev, u_t):
        s_t = (Tm @ s_prev).at[:r].add(u_t)
        return s_t, s_t[:r]

    _, f_plus = jax.lax.scan(sim_step, s0, u)
    return f_plus, ke


def _simulation_smoother_core_collapsed(
    params: SSMParams, C, b, ld_R, n_obs, ll_corr, sqrtC, key
):
    """Durbin-Koopman draw on the COLLAPSED observation statistics: the
    large-N form of `_simulation_smoother_core`, with no (T, N) operand
    anywhere past the one-time collapse.

    The simulated panel never needs materializing: collapsing
    x+ = M_t(Lam f+ + eps) gives b+_t = C_t f+_t + Lam'R^-1 M_t eps_t,
    and the noise term is exactly N(0, C_t) — so the r-dim pseudo-
    observation b+_t = C_t f+_t + C_t^{1/2} zeta_t (zeta ~ N(0, I_r)) has
    the same joint law with f+ as a collapsed simulated panel.  Smoothed
    means are LINEAR in b for a fixed C stack (zero prior mean), so the
    mean-correction smooth(b) - smooth(b+) collapses to ONE filter+RTS
    pass on the difference db = b - b+ — a draw costs one r*p-state
    filter+smoother scan, not two N-collapses plus two scans.

    The real-data loglik is draw-independent; callers needing it run one
    `_filter_scan_collapsed_stats(params, C, b, ld_R, n_obs, ll_corr)`
    per panel, not per draw.  Returns f_draw (T, r)."""
    r = params.r
    f_plus, kz = _sim_plus_path(params, key, C.shape[0], b.dtype)
    zeta = jax.random.normal(kz, (C.shape[0], r), b.dtype)
    b_plus = jnp.einsum("trs,ts->tr", C, f_plus) + jnp.einsum(
        "trs,ts->tr", sqrtC, zeta
    )
    filt_d = _filter_scan_collapsed_stats(
        params, C, b - b_plus, ld_R, n_obs, jnp.zeros((), b.dtype)
    )
    sm_d, _, _ = _smoother_scan(params, filt_d)
    return f_plus + sm_d[:, :r]


@jax.jit
def _simulation_smoother_collapsed_entry(params: SSMParams, xz, m, key):
    C, b, ld_R, xRx, n_obs = _collapse_obs(params.lam, params.R, xz, m)
    ll_corr = -0.5 * xRx.sum()
    filt = _filter_scan_collapsed_stats(
        params, C, b, ld_R, n_obs, ll_corr
    )
    f = _simulation_smoother_core_collapsed(
        params, C, b, ld_R, n_obs, ll_corr, _psd_sqrt(C), key
    )
    return f, filt.loglik


def simulation_smoother(
    params: SSMParams,
    x,
    seed: int = 0,
    backend: str | None = None,
    collapsed: bool | None = None,
):
    """Public entry: one posterior factor-path draw f | x, params.

    x: (T, N) panel with NaN missing.  Returns ((T, r) draw, loglik).
    vmap over seeds (via jax.random.split outside) for multiple draws.

    `collapsed` selects the large-N variant that shares one observation
    collapse and runs one r*p-state scan pass per draw instead of two
    N-dim smoother passes; default None auto-enables it for
    N > ssm.LARGE_N_THRESHOLD.  Both variants draw from the identical
    posterior (the collapse is exact); the draws differ only in their
    PRNG stream."""
    with on_backend(backend):
        params = params._replace(Q=_psd_floor(params.Q))
        x = jnp.asarray(x)
        if collapsed is None:
            collapsed = x.shape[1] > LARGE_N_THRESHOLD
        if collapsed:
            xz = fillz(x)
            return _simulation_smoother_collapsed_entry(
                params, xz, mask_of(x).astype(xz.dtype),
                jax.random.PRNGKey(seed),
            )
        return _simulation_smoother_core(
            params, fillz(x), mask_of(x), jax.random.PRNGKey(seed)
        )


def _prepare_panel(data, inclcode, initperiod: int, lastperiod: int):
    """Shared sampler data path (same as estimate_dfm_em): standardized
    included panel over the window, with mask and original-unit moments —
    delegates to ssm._window_panel, the single copy of the prologue.

    Returns (data, inclcode, xz, m_arr, stds, n_mean)."""
    from .ssm import _window_panel

    data = jnp.asarray(data)
    inclcode = np.asarray(inclcode)
    xz, m_arr, stds, n_mean = _window_panel(
        data, inclcode, initperiod, lastperiod
    )
    return data, inclcode, xz, m_arr, stds, n_mean


def _draw_lam_r_block(key, f, xz, m, R_prev, lam_scale, a0, b0):
    """Conjugate (lam_i | R_i) then (R_i | lam_i) draws, batched over series
    (shared by the homoskedastic and stochastic-volatility samplers).

    R_i ~ InvGamma(a0 + n_i/2, b0 + ssr_i/2) drawn as (b0 + ssr/2)/Gamma."""
    dtype = xz.dtype
    N = xz.shape[1]
    r = f.shape[1]
    Fg = jnp.einsum("ti,tr,ts->irs", m, f, f)
    Fx = jnp.einsum("ti,tr->ir", m * xz, f)
    n_i = m.sum(axis=0)
    klam, kr = jax.random.split(key)
    lam_keys = jax.random.split(klam, N)

    def draw_lam_i(Fg_i, Fx_i, R_i, k_i):
        prec = Fg_i + (R_i / lam_scale**2) * jnp.eye(r, dtype=dtype)
        pinv = jnp.linalg.pinv(prec, hermitian=True)
        return _draw_mvn(k_i, pinv @ Fx_i, R_i * pinv)

    lam = jax.vmap(draw_lam_i)(Fg, Fx, R_prev, lam_keys)
    resid = jnp.where(m.astype(bool), xz - f @ lam.T, 0.0)
    ssr = (resid**2).sum(axis=0)
    g = jax.random.gamma(kr, a0 + 0.5 * n_i, dtype=dtype)
    R = jnp.maximum((b0 + 0.5 * ssr) / g, 1e-8)
    return lam, R


def _draw_var_mniw(key, f, p: int, q_df_extra, q_scale):
    """Joint (Q, A) | f draw for the factor VAR under a flat prior on A and
    IW(r+1+q_df_extra, q_scale I) prior on Q, with A integrated out of the
    Q marginal (a collapsed draw, not a conditional on the previous A).

    Marginalizing A under the flat prior contributes |Q|^{rp/2} to the
    integrand, so the Q marginal is IW(nu0 + (T-p) - rp, S0 + E0'E0) with
    E0 the OLS residuals — the matrix version of the scalar n - k
    degrees-of-freedom correction.  (Without the -rp the stationary
    distribution concentrates Q ~7% tight at reference scale.)  Then
    vec(A) | Q ~ N(vec(Ahat), Q kron ZZ^{-1})."""
    dtype = f.dtype
    T, r = f.shape
    Z = jnp.concatenate([f[p - 1 - i : T - 1 - i] for i in range(p)], axis=1)
    Y = f[p:]
    ZZ = Z.T @ Z + 1e-8 * jnp.eye(r * p, dtype=dtype)
    Ahat = solve_normal(ZZ, Z.T @ Y)  # (r*p, r)
    E0 = Y - Z @ Ahat
    S = q_scale * jnp.eye(r, dtype=dtype) + E0.T @ E0
    nu = (r + 1.0 + q_df_extra) + (T - p) - r * p

    kq, ka = jax.random.split(key)
    # Q ~ IW(nu, S): Q = inv(W), W ~ Wishart(nu, S^{-1}) by Bartlett
    Ls_inv = jnp.linalg.cholesky(jnp.linalg.pinv(0.5 * (S + S.T), hermitian=True))
    kchi, knorm = jax.random.split(kq)
    chi = jnp.sqrt(
        2.0 * jax.random.gamma(kchi, 0.5 * (nu - jnp.arange(r, dtype=dtype)), dtype=dtype)
    )
    Bl = jnp.tril(jax.random.normal(knorm, (r, r), dtype=dtype), -1) + jnp.diag(chi)
    Wc = Ls_inv @ Bl  # chol factor of W
    W = Wc @ Wc.T
    Q = _psd_floor(jnp.linalg.pinv(W, hermitian=True))

    # vec(A) | Q ~ N(vec(Ahat), Q kron ZZ^{-1}): A = Ahat + Lzz^{-T} E Lq'
    Lzz = jnp.linalg.cholesky(0.5 * (ZZ + ZZ.T))
    Eg = jax.random.normal(ka, (r * p, r), dtype=dtype)
    Adraw = Ahat + jsl.solve_triangular(Lzz.T, Eg, lower=False) @ jnp.linalg.cholesky(Q).T
    A = jnp.stack([Adraw[i * r : (i + 1) * r].T for i in range(p)])
    return A, Q


def _gibbs_sweep(carry, xz, m, p: int, priors: tuple):
    """One full Gibbs sweep: f | params  ->  (lam, R) | f  ->  (A, Q) | f."""
    key, params = carry
    lam_scale, a0, b0, q_df_extra, q_scale = priors

    key, kf, klamr, kvar = jax.random.split(key, 4)

    # --- factors ---
    f, ll = _simulation_smoother_core(params, xz, m, kf)

    # --- loadings + idiosyncratic variances (batched over series) ---
    lam, R = _draw_lam_r_block(klamr, f, xz, m, params.R, lam_scale, a0, b0)

    # --- factor VAR (Matrix-Normal-Inverse-Wishart, collapsed Q draw) ---
    A, Q = _draw_var_mniw(kvar, f, p, q_df_extra, q_scale)

    new_params = SSMParams(lam=lam, R=R, A=A, Q=Q)
    return (key, new_params), (f, lam, R, A, Q, ll)


@partial(jax.jit, static_argnames=("n_burn", "n_keep", "thin", "p"))
def _chain(
    key,
    params0: SSMParams,
    xz,
    m,
    n_burn: int,
    n_keep: int,
    thin: int,
    p: int,
    priors: tuple,
):
    """One Gibbs chain: a carry-only burn-in scan, then a keep-phase scan
    that materializes only every thin-th sweep — device memory holds n_keep
    draws, not n_burn + n_keep*thin.  Returns ((f, lam, R, A, Q) kept draws,
    loglik of every sweep in order)."""

    def sweep_ll(carry, _):
        carry, outs = _gibbs_sweep(carry, xz, m, p, priors)
        return carry, outs[5]

    def keep_body(carry, _):
        carry, lls_thin = jax.lax.scan(sweep_ll, carry, None, length=thin - 1)
        carry, outs = _gibbs_sweep(carry, xz, m, p, priors)
        return carry, (outs[:5], jnp.concatenate([lls_thin, outs[5][None]]))

    carry = (key, params0)
    carry, ll_burn = jax.lax.scan(sweep_ll, carry, None, length=n_burn)
    _, (kept, ll_keep) = jax.lax.scan(keep_body, carry, None, length=n_keep)
    lls = jnp.concatenate([ll_burn, ll_keep.reshape(-1)])
    return kept + (lls,)  # (f, lam, R, A, Q, lls)


def _scale_normalize(f, lam, A, Q):
    """Per-draw scale normalization: the likelihood is invariant under
    (lam c^-1, c f, c^2 Q) per factor, and chains drift along that ridge;
    rescale every draw so Q has a unit diagonal (correlations preserved):
    f / c, lam * c, C^-1 A C, C^-1 Q C^-1 with c = sqrt(diag Q)."""
    c = jnp.sqrt(jnp.maximum(jnp.diagonal(Q, axis1=-2, axis2=-1), 1e-12))
    f_n = f / c[..., None, :]
    lam_n = lam * c[..., None, :]
    A_n = A / c[..., None, :, None] * c[..., None, None, :]
    Q_n = Q / c[..., :, None] / c[..., None, :]
    return f_n, lam_n, A_n, Q_n


def _procrustes_align(f, lam, A, Q, lam_ref):
    """Rotation-align every draw to a common loading reference (orthogonal
    Procrustes): factor-model posteriors are identified only up to rotation,
    so cross-draw averages (posterior-mean loadings/factors, DIC's
    theta_bar) are meaningless without alignment — observed on the real
    panel as DIC p_D of -25k at r=4 before this step.

    f: (..., T, r); lam: (..., N, r); A: (..., p, r, r); Q: (..., r, r);
    lam_ref: (N, r).  Applies lam R, f R, R' A R, R' Q R with
    R = argmin ||lam_d R - lam_ref||_F over orthogonal R (SVD solution)."""

    def one(f_d, lam_d, A_d, Q_d):
        u, _, vt = jnp.linalg.svd(lam_d.T @ lam_ref)
        R = u @ vt
        return (
            f_d @ R,
            lam_d @ R,
            jnp.einsum("sr,lst,tu->lru", R, A_d, R),
            R.T @ Q_d @ R,
        )

    shape = f.shape[:-2]
    flat = lambda a: a.reshape((-1,) + a.shape[len(shape):])
    fo, lo, ao, qo = jax.vmap(one)(flat(f), flat(lam), flat(A), flat(Q))
    unflat = lambda a: a.reshape(shape + a.shape[1:])
    return unflat(fo), unflat(lo), unflat(ao), unflat(qo)


def _sign_normalize(f, lam, A, Q):
    """Per-draw sign normalization: flip each factor so its loading column
    sums positive (factors are identified up to sign; without this, chain
    draws mix over the 2^r sign orbit and posterior means collapse to 0)."""
    s = jnp.sign(lam.sum(axis=-2))  # (..., r)
    s = jnp.where(s == 0, 1.0, s)
    f_n = f * s[..., None, :]
    lam_n = lam * s[..., None, :]
    A_n = A * s[..., None, :, None] * s[..., None, None, :]
    Q_n = Q * s[..., :, None] * s[..., None, :]
    return f_n, lam_n, A_n, Q_n


def _split_rhat_2d(x: np.ndarray) -> float:
    """Split-R-hat of a (chains, draws) float64 array (chains >= 1: each
    chain is split in halves, so one chain still yields a diagnostic)."""
    c, n = x.shape
    half = n // 2
    x = x[:, : 2 * half].reshape(2 * c, half)
    cm = x.mean(axis=1)
    W = x.var(axis=1, ddof=1).mean()
    B = half * cm.var(ddof=1)
    var_plus = (half - 1) / half * W + B / half
    return float(np.sqrt(var_plus / W))


def rhat(draws):
    """Split-R-hat (Gelman-Rubin) of stacked posterior draws.

    Accepts 1-D ``(n,)`` — a single chain, split in halves; 2-D
    ``(chains, draws)`` — the classic scalar diagnostic; or
    ``(chains, draws, ...)`` — per-component split-R-hat over the
    trailing dims (e.g. ``rhat(res.lam_draws)`` -> (N, r) array).
    Scalar inputs return a float, stacked inputs an ndarray of the
    trailing shape."""
    x = np.asarray(draws, np.float64)
    if x.ndim == 1:
        x = x[None, :]
    if x.ndim == 2:
        return _split_rhat_2d(x)
    c, n = x.shape[:2]
    flat = x.reshape(c, n, -1)
    out = np.array(
        [_split_rhat_2d(flat[:, :, j]) for j in range(flat.shape[2])]
    )
    return out.reshape(x.shape[2:])


def ess(draws):
    """Cross-chain effective sample size of stacked posterior draws.

    Standard autocorrelation estimator: per-chain FFT autocovariances
    averaged across chains, combined with the between-chain variance
    into split-R-hat's var_plus, truncated by Geyer's initial positive
    sequence.  Shapes as in `rhat`; returns min(c*n, c*n/tau).

    Degenerate inputs — fewer than 4 draws per chain, or chains with no
    within/between variance (constant draws) — cannot support the
    autocorrelation estimate; they return NaN with a warning rather
    than a silently optimistic ``c * n``."""
    x = np.asarray(draws, np.float64)
    if x.ndim == 1:
        x = x[None, :]
    if x.ndim > 2:
        c, n = x.shape[:2]
        flat = x.reshape(c, n, -1)
        out = np.array([ess(flat[:, :, j]) for j in range(flat.shape[2])])
        return out.reshape(x.shape[2:])
    c, n = x.shape
    if n < 4:
        warnings.warn(
            f"ess needs >= 4 draws per chain to estimate autocorrelation, "
            f"got {n}; returning NaN",
            stacklevel=2,
        )
        return float("nan")
    xc = x - x.mean(axis=1, keepdims=True)
    nfft = 1 << (2 * n - 1).bit_length()
    f = np.fft.rfft(xc, nfft, axis=1)
    acov = np.fft.irfft(f * np.conj(f), nfft, axis=1)[:, :n].real / n
    mean_acov = acov.mean(axis=0)
    W = mean_acov[0] * n / (n - 1.0)
    B = n * x.mean(axis=1).var(ddof=1) if c > 1 else 0.0
    var_plus = (n - 1.0) / n * W + B / n
    if not var_plus > 0:
        warnings.warn(
            "ess got constant chains (zero within- and between-chain "
            "variance); the effective sample size is undefined, "
            "returning NaN",
            stacklevel=2,
        )
        return float("nan")
    rho = 1.0 - (W - mean_acov * n / (n - 1.0)) / var_plus
    tau, t = 1.0, 1
    while t + 1 < n:
        pair = rho[t] + rho[t + 1]
        if pair < 0:
            break
        tau += 2.0 * pair
        t += 2
    return float(min(c * n, c * n / max(tau, 1e-12)))


def estimate_dfm_bayes(
    data,
    inclcode,
    initperiod: int,
    lastperiod: int,
    config: DFMConfig = DFMConfig(nfac_u=4),
    n_keep: int = 500,
    n_burn: int = 500,
    thin: int = 1,
    n_chains: int = 2,
    seed: int = 0,
    priors: BayesPriors = BayesPriors(),
    mesh=None,
    backend: str | None = None,
) -> BayesResults:
    """Posterior sampling of the state-space DFM by Gibbs, chains in
    parallel on device.

    Same data path as `estimate_dfm_em` (standardized included panel,
    NaN-masked), initialized from the non-parametric ALS fit, with the
    chain axis ``vmap``-ed — pass a 1-axis `mesh` to shard chains across
    devices like bootstrap replications.  Returns sign-normalized posterior
    draws (post burn-in, thinned) and a split-R-hat convergence diagnostic
    on the log-likelihood path.
    """
    with on_backend(backend):
        data, inclcode, xz, m_arr, stds, n_mean = _prepare_panel(
            data, inclcode, initperiod, lastperiod
        )
        params0 = _init_params_from_als(
            data, inclcode, initperiod, lastperiod, config, xz, m_arr
        )
        p = config.n_factorlag
        r = config.nfac_u
        T_w = xz.shape[0]
        # the collapsed Q draw (IW with nu = r+1+extra + (T-p) - rp) needs
        # every Bartlett gamma shape positive: nu > r - 1.  Below that,
        # jax.random.gamma would return silent NaNs and the whole chain
        # would go NaN — refuse loudly instead
        if (r + 1.0 + float(priors.q_df_extra)) + (T_w - p) - r * p <= r - 1:
            raise ValueError(
                f"sample too short for the factor-VAR posterior: need "
                f"T - p > r*p - 2 - q_df_extra (T={T_w}, p={p}, r={r}); "
                "reduce n_factorlag or nfac_u"
            )
        prior_t = (
            float(priors.lam_scale),
            float(priors.r_shape),
            float(priors.r_rate),
            float(priors.q_df_extra),
            float(priors.q_scale),
        )

        keys = jax.random.split(jax.random.PRNGKey(seed), n_chains)
        if mesh is not None:
            # shard the chain axis over the mesh's (single) axis, whatever
            # its name — make_mesh() defaults to "rep"
            keys = jax.device_put(
                keys, NamedSharding(mesh, P(mesh.axis_names[0]))
            )

        # guarded multi-chain kernel (scenarios/gibbs.py): all chains in
        # one scan-outside/vmap-inside program, per-chain health sentinel
        # (lazy import: scenarios imports this module at load)
        from ..scenarios.gibbs import sample_chains

        mc = sample_chains(
            keys, params0, xz, m_arr.astype(xz.dtype),
            n_burn=n_burn, n_keep=n_keep, thin=thin, p=p, priors=prior_t,
        )
        f_k, lam_k, r_k, a_k, q_k = (
            mc.factor_draws, mc.lam_draws, mc.r_draws, mc.a_draws,
            mc.q_draws,
        )

        # normalize each draw's scale (unit-diag Q), rotation-align to the
        # (chain-shared) ALS init loadings, then fix signs: draws become
        # averageable across chains and sweeps (the likelihood is invariant
        # along both the scale ridge and the rotation orbit).  Normalize
        # BEFORE dropping divergent chains: the per-draw maps are
        # elementwise over the chain axis, so surviving chains stay
        # bit-identical to a fault-free run of the same batch shape
        f_k, lam_k, a_k, q_k = _scale_normalize(f_k, lam_k, a_k, q_k)
        f_k, lam_k, a_k, q_k = _procrustes_align(
            f_k, lam_k, a_k, q_k, params0.lam
        )
        f_k, lam_k, a_k, q_k = _sign_normalize(f_k, lam_k, a_k, q_k)

        health = mc.health
        healthy = health == 0
        if not healthy.any():
            raise RuntimeError(
                "every Gibbs chain diverged (non-finite draws) — the "
                "posterior is empty; loosen priors, reduce nfac_u, or "
                "inspect the panel for pathological scaling"
            )
        ll_np = np.asarray(mc.loglik_path)
        if not healthy.all():
            hidx = np.nonzero(healthy)[0]
            f_k, lam_k, r_k, a_k, q_k = (
                a[hidx] for a in (f_k, lam_k, r_k, a_k, q_k)
            )
        ll_post = ll_np[healthy][:, n_burn:]
        return BayesResults(
            factor_draws=f_k,
            lam_draws=lam_k,
            r_draws=r_k,
            a_draws=a_k,
            q_draws=q_k,
            loglik_path=ll_np,
            rhat_loglik=rhat(ll_post),
            stds=stds,
            means=n_mean,
            chain_health=health,
            ess_loglik=ess(ll_post),
        )


def _irf_one_draw(a_i, q_i, horizon: int):
    """Cholesky-identified factor IRFs (r, horizon, r) of one (A, Q) draw."""
    from .var import companion_matrices

    p, r = a_i.shape[0], a_i.shape[1]
    beta = jnp.concatenate(
        [jnp.zeros((1, r), a_i.dtype)] + [a_i[j].T for j in range(p)],
        axis=0,
    )
    M, Qs, G = companion_matrices(beta, _psd_floor(q_i), p)

    def step(x, _):
        return M @ x, Qs @ x

    def one_shock(g):
        _, out = jax.lax.scan(step, g, None, length=horizon)
        return out.T

    return jax.vmap(one_shock, in_axes=1, out_axes=2)(G)


def posterior_irfs(
    results: BayesResults,
    horizon: int = 24,
    quantile_levels=(0.05, 0.16, 0.5, 0.84, 0.95),
):
    """Posterior IRF bands of the factor VAR under recursive identification:
    each kept (A, Q) draw maps to Cholesky-identified IRFs (models/var.py
    companion machinery), vmapped over the flattened chain x draw axis.

    Returns (quantiles (nq, r, horizon, r), draws (n, r, horizon, r))."""
    a = results.a_draws.reshape((-1,) + results.a_draws.shape[2:])
    q = results.q_draws.reshape((-1,) + results.q_draws.shape[2:])

    draws = jax.jit(jax.vmap(partial(_irf_one_draw, horizon=horizon)))(a, q)
    qs = jnp.quantile(draws, jnp.asarray(quantile_levels), axis=0)
    return qs, draws


class PosteriorSeriesIRFs(NamedTuple):
    mean: jnp.ndarray  # (nsel, horizon, r) posterior-mean series IRFs
    quantiles: jnp.ndarray  # (nq, nsel, horizon, r)
    quantile_levels: np.ndarray
    draws: jnp.ndarray  # (n_draws, nsel, horizon, r)


def posterior_series_irfs(
    results: BayesResults,
    horizon: int = 24,
    series_idx=None,
    quantile_levels=(0.05, 0.16, 0.5, 0.84, 0.95),
) -> PosteriorSeriesIRFs:
    """Posterior IRF bands in OBSERVED-SERIES space, original data units.

    Full posterior propagation: draw d's factor IRFs (from its own A_d, Q_d)
    are contracted with the SAME draw's loadings Lam_d — so the bands carry
    both VAR-parameter and loading uncertainty, unlike the FAVAR bootstrap
    (models/favar.py `series_irfs`) which holds loadings at the point
    estimate.  The standardized-panel loadings are rescaled by the stored
    per-series stds, putting the response in the units of the raw series
    ("response of GDPC96 to shock 1, 5-95% credible band").

    series_idx: optional indices into the INCLUDED-series axis (the order of
    `results.lam_draws`); default all.
    """
    a = results.a_draws.reshape((-1,) + results.a_draws.shape[2:])
    q = results.q_draws.reshape((-1,) + results.q_draws.shape[2:])
    lam = results.lam_draws.reshape((-1,) + results.lam_draws.shape[2:])
    scale = results.stds
    if series_idx is not None:
        # bounds-check host-side: jnp gather clamps out-of-range indices
        # silently — the exact hazard of passing a full-panel index where
        # an included-series index is expected
        idx = np.asarray(series_idx)
        n_incl = lam.shape[1]
        if idx.size and (idx.min() < -n_incl or idx.max() >= n_incl):
            raise IndexError(
                f"series_idx out of range for {n_incl} included series: "
                f"[{idx.min()}, {idx.max()}]"
            )
        lam, scale = lam[:, idx], scale[idx]

    def one(a_i, q_i, lam_i):
        irf = _irf_one_draw(a_i, q_i, horizon)  # (r, H, r)
        return jnp.einsum("nk,khj->nhj", lam_i * scale[:, None], irf)

    draws = jax.jit(jax.vmap(one))(a, q, lam)
    qs = jnp.quantile(draws, jnp.asarray(quantile_levels), axis=0)
    return PosteriorSeriesIRFs(
        draws.mean(axis=0), qs, np.asarray(quantile_levels), draws
    )


def _standardized_window(results: BayesResults, data, inclcode,
                         initperiod: int, lastperiod: int):
    """Slice the included panel to the fit window and standardize with the
    fit's stored per-series moments (shared by posterior_forecast / dic)."""
    data = jnp.asarray(data)
    inclcode = np.asarray(inclcode)
    xw = data[initperiod : lastperiod + 1][:, inclcode == 1]
    if xw.shape[1] != results.means.shape[0]:
        raise ValueError(
            f"panel has {xw.shape[1]} included series; the fit stored "
            f"moments for {results.means.shape[0]}"
        )
    return (xw - results.means[None, :]) / results.stds[None, :]


class PosteriorForecast(NamedTuple):
    draws: jnp.ndarray  # (n_draws, horizon, N) predictive draws
    mean: jnp.ndarray  # (horizon, N)
    quantiles: np.ndarray  # (nq, horizon, N)
    quantile_levels: np.ndarray


def posterior_forecast(
    results: BayesResults,
    data,
    inclcode,
    initperiod: int,
    lastperiod: int,
    horizon: int,
    seed: int = 0,
    quantile_levels=(0.05, 0.16, 0.5, 0.84, 0.95),
    backend: str | None = None,
) -> PosteriorForecast:
    """Posterior predictive forecasts: full parameter AND state uncertainty,
    in ORIGINAL data units.

    Takes the same (data, inclcode, window) the sampler was fitted on and
    standardizes internally with the fit's stored per-series means/stds
    (`results.means` / `results.stds`) — no hand-built standardized panel.
    For every kept Gibbs draw (lam, R, A, Q): filter the panel to the last
    filtered state, draw the terminal state, simulate the factor VAR
    `horizon` steps with fresh innovations, and add measurement noise —
    ``vmap``-ed over the flattened chain x draw axis.  The quantiles are
    genuine predictive bands (point-estimate nowcasts understate them by
    ignoring parameter uncertainty).
    """
    if horizon < 1:
        raise ValueError(f"horizon must be >= 1, got {horizon}")
    with on_backend(backend):
        x = _standardized_window(results, data, inclcode, initperiod, lastperiod)
        flat = lambda a: a.reshape((-1,) + a.shape[2:])
        lam_d, r_d = flat(results.lam_draws), flat(results.r_draws)
        a_d, q_d = flat(results.a_draws), flat(results.q_draws)
        # the kept factor paths are joint posterior draws consistent with
        # the same sweep's (lam, R, A, Q) (normalized together), so their
        # last p rows ARE the terminal companion state — no filter re-run
        p = results.a_draws.shape[2]
        f_tail = flat(results.factor_draws)[:, -p:, :]  # (n, p, r)
        s_term = f_tail[:, ::-1].reshape(f_tail.shape[0], -1)  # newest first
        n_draws = lam_d.shape[0]
        keys = jax.random.split(jax.random.PRNGKey(seed), n_draws)

        # shared fan-out kernel (scenarios/fanout.py): posterior forecasts
        # and scenario draw fans run the same AOT-registered program
        from ..scenarios.fanout import forecast_fan

        draws_std = forecast_fan(
            lam_d, r_d, a_d, q_d, s_term, keys, int(horizon)
        )
        # back to original units with the fit's moments
        draws = draws_std * results.stds[None, None, :] + results.means[None, None, :]
        q = np.quantile(np.asarray(draws), np.asarray(quantile_levels), axis=0)
        return PosteriorForecast(
            draws, draws.mean(axis=0), q, np.asarray(quantile_levels)
        )


class BayesModelComparison(NamedTuple):
    nfacs: np.ndarray  # (K,) candidate factor counts
    dic: np.ndarray  # (K,) deviance information criterion (lower = better)
    p_d: np.ndarray  # (K,) effective number of parameters
    mean_loglik: np.ndarray  # (K,) posterior mean of log p(x | theta)
    loglik_at_mode: np.ndarray  # (K,) log p(x | best-loglik kept draw)
    best_nfac: int


def dic(results: BayesResults, data, inclcode, initperiod: int,
        lastperiod: int, backend: str | None = None):
    """Deviance information criterion from Gibbs output, posterior-mode
    plug-in variant (Celeux et al. 2006): DIC = -2 log p(x|theta*) + 2 p_D
    with theta* the best-loglik kept draw and
    p_D = 2 (log p(x|theta*) - E[log p(x|theta)]).

    The classic posterior-MEAN plug-in is meaningless for latent-factor
    models: even after scale/rotation/sign normalization the mean of draws
    is not a coherent parameter point (measured on the real r=4 panel as
    p_D of -15k).  Using the best kept draw keeps the plug-in coherent by
    construction and p_D >= 0 always.  The per-draw logliks are evaluated
    directly (one vmapped filter pass over the kept draws).
    Returns (dic, p_d, mean_ll, ll_at_mode).
    """
    with on_backend(backend):
        x = _standardized_window(results, data, inclcode, initperiod, lastperiod)
        xz, m = fillz(x), mask_of(x).astype(x.dtype)

        flat = lambda a: a.reshape((-1,) + a.shape[2:])
        lam_d, r_d = flat(results.lam_draws), flat(results.r_draws)
        a_d, q_d = flat(results.a_draws), flat(results.q_draws)

        def ll_of(lam_i, R_i, A_i, Q_i):
            params = SSMParams(lam=lam_i, R=R_i, A=A_i, Q=_psd_floor(Q_i))
            return _filter_scan(params, xz, m).loglik

        lls = np.asarray(jax.jit(jax.vmap(ll_of))(lam_d, r_d, a_d, q_d))
        mean_ll = float(lls.mean())
        ll_star = float(lls.max())
        p_d = 2.0 * (ll_star - mean_ll)
        return -2.0 * ll_star + 2.0 * p_d, p_d, mean_ll, ll_star


def select_nfac_bayes(
    data,
    inclcode,
    initperiod: int,
    lastperiod: int,
    nfacs=(1, 2, 3, 4),
    config: DFMConfig = DFMConfig(),
    n_keep: int = 200,
    n_burn: int = 200,
    n_chains: int = 2,
    seed: int = 0,
    priors: BayesPriors = BayesPriors(),
    backend: str | None = None,
) -> BayesModelComparison:
    """Bayesian factor-number selection by DIC: fit the Gibbs sampler for
    each candidate r and rank (the Bayesian counterpart of the Bai-Ng /
    Amengual-Watson criteria in models/selection.py).

    Each candidate runs the full chain-vmapped sampler; candidates
    themselves loop on host (their shapes differ in r).
    """
    import dataclasses

    dics, pds, mlls, llmodes = [], [], [], []
    for r in nfacs:
        cfg_r = dataclasses.replace(config, nfac_u=int(r))
        res = estimate_dfm_bayes(
            data, inclcode, initperiod, lastperiod, cfg_r,
            n_keep=n_keep, n_burn=n_burn, n_chains=n_chains,
            seed=seed, priors=priors, backend=backend,
        )
        d, p_d, mll, llm = dic(
            res, data, inclcode, initperiod, lastperiod, backend=backend
        )
        dics.append(d)
        pds.append(p_d)
        mlls.append(mll)
        llmodes.append(llm)
    dics = np.asarray(dics)
    return BayesModelComparison(
        nfacs=np.asarray(nfacs),
        dic=dics,
        p_d=np.asarray(pds),
        mean_loglik=np.asarray(mlls),
        loglik_at_mode=np.asarray(llmodes),
        best_nfac=int(np.asarray(nfacs)[dics.argmin()]),
    )
