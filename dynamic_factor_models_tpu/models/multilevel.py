"""Two-level dynamic factor model: global + block (country) factors.

New capability (BASELINE.json config 5, `Barigozzi et al. (2014) two-level
euro-area DFM with country-block factors`); the reference has no multilevel
model.  Model:

    x_it = lam_g_i' F_t + lam_b_i' G_t^{b(i)} + e_it

with F_t global factors loading on every series and G_t^b block factors
loading only within block b.  Estimation is alternating least squares across
levels (Breitung-Eickmeier / Barigozzi-style):

  1. estimate global factors on the full panel (masked ALS);
  2. per block: estimate block factors on the global residuals;
  3. re-estimate the global level on x minus block components; iterate until
     the total SSR change falls below tol * T * N.

Each level reuses the jitted ALS core of models/dfm.py; the per-block step
is a loop over blocks of one batched masked solve each (blocks are ragged,
so they shard naturally over devices by block).
"""

from __future__ import annotations

from typing import NamedTuple, Sequence

import jax.numpy as jnp
import numpy as np

from ..ops.linalg import pca_score, standardize_data
from ..ops.masking import fillz, mask_of
from ..utils.backend import on_backend
from .dfm import _als_core

__all__ = [
    "MultilevelIRFs",
    "MultilevelResults",
    "estimate_multilevel_dfm",
    "multilevel_series_irfs",
]


class MultilevelResults(NamedTuple):
    global_factors: jnp.ndarray  # (T, r_g)
    global_loadings: jnp.ndarray  # (N, r_g)
    block_factors: list  # per block: (T, r_b)
    block_loadings: list  # per block: (n_b, r_b)
    blocks: list  # per block: column indices into the panel
    ssr: float
    tss: float
    n_iter: int
    variance_decomposition: dict  # {"global", "block", "idiosyncratic"}
    stds: jnp.ndarray  # (N,) standardization scale (original-unit bands)


def _als_level(xz, m, f0, nfac, tol_scaled, max_iter):
    """Masked ALS at one level: the jitted ALS core of models/dfm.py with
    every series loading (lam_ok = all-true) and no constraint."""
    lam_ok = jnp.ones(xz.shape[1], dtype=bool)
    f, lam, ssr, _ = _als_core(xz, m, lam_ok, f0, tol_scaled, nfac, max_iter)
    return f, lam, ssr


def estimate_multilevel_dfm(
    data,
    blocks: Sequence[np.ndarray],
    r_global: int,
    r_block: int | Sequence[int],
    initperiod: int = 0,
    lastperiod: int | None = None,
    tol: float = 1e-8,
    max_outer: int = 200,
    max_inner: int = 2000,
    backend: str | None = None,
) -> MultilevelResults:
    """Estimate the two-level DFM on a (T, N) panel.

    blocks: sequence of integer index arrays partitioning the columns (e.g.
    one array of series indices per country).  r_block may be a single int or
    one per block.
    """
    with on_backend(backend):
        data = jnp.asarray(data)
        if lastperiod is None:
            lastperiod = data.shape[0] - 1
        xw = data[initperiod : lastperiod + 1]
        xstd, stds = standardize_data(xw)
        mask = mask_of(xstd)
        m = mask.astype(xstd.dtype)
        xz = fillz(xstd)
        Tw, N = xz.shape

        blocks = [np.asarray(b) for b in blocks]
        if not blocks or any(b.size == 0 for b in blocks):
            raise ValueError("blocks must be a non-empty sequence of non-empty index arrays")
        if max_outer < 1:
            raise ValueError(f"max_outer must be >= 1, got {max_outer}")
        covered = np.concatenate(blocks)
        if len(set(covered.tolist())) != len(covered):
            raise ValueError("blocks must be disjoint")
        if covered.min() < 0 or covered.max() >= N:
            # jnp gather/scatter clip out-of-bounds silently; fail loudly here
            raise ValueError(
                f"block indices must lie in [0, {N}); got "
                f"[{covered.min()}, {covered.max()}]"
            )
        r_blocks = (
            [r_block] * len(blocks) if isinstance(r_block, int) else list(r_block)
        )
        if len(r_blocks) != len(blocks):
            raise ValueError(
                f"r_block has {len(r_blocks)} entries for {len(blocks)} blocks"
            )

        tss = float((xz**2 * m).sum())
        tol_scaled = tol * Tw * N

        # init: global PCA on the zero-filled panel (works for any missing
        # pattern; the ALS iterations refine it under the true mask)
        Fg = pca_score(xz * m, r_global)

        block_comp = jnp.zeros_like(xz)
        ssr_prev = jnp.inf
        n_iter = 0
        for n_iter in range(1, max_outer + 1):
            # global level on x net of block components
            Fg, Lg, _ = _als_level(
                xz - block_comp, m, Fg, r_global, tol_scaled, max_inner
            )
            global_comp = Fg @ Lg.T
            resid_g = xz - global_comp

            Gb_list, Lb_list = [], []
            block_comp = jnp.zeros_like(xz)
            for b, rb in zip(blocks, r_blocks):
                xb = resid_g[:, b]
                mb = m[:, b]
                # PCA init on the block residual (masked entries are zero)
                f0 = pca_score(xb * mb, rb)
                Gb, Lb, _ = _als_level(xb, mb, f0, rb, tol * Tw * len(b), max_inner)
                Gb_list.append(Gb)
                Lb_list.append(Lb)
                block_comp = block_comp.at[:, b].set(Gb @ Lb.T)

            ssr = float((m * (xz - global_comp - block_comp) ** 2).sum())
            if abs(ssr_prev - ssr) < tol_scaled:
                break
            ssr_prev = ssr

        gvar = float((m * global_comp**2).sum())
        bvar = float((m * block_comp**2).sum())
        return MultilevelResults(
            global_factors=Fg,
            global_loadings=Lg,
            block_factors=Gb_list,
            block_loadings=Lb_list,
            blocks=[b for b in blocks],
            ssr=ssr,
            tss=tss,
            n_iter=n_iter,
            variance_decomposition={
                "global": gvar / tss,
                "block": bvar / tss,
                "idiosyncratic": ssr / tss,
            },
            stds=jnp.asarray(stds).reshape(-1),
        )


class MultilevelIRFs(NamedTuple):
    """Per-block series-space IRFs to shocks of the joint [F, G_b] system."""

    series: list  # per block: favar.SeriesIRFs (original data units)
    factor_boots: list  # per block: favar.BootstrapIRFs of the joint system
    r_global: int  # shocks [0, r_global) are global-factor innovations


def multilevel_series_irfs(
    results: MultilevelResults,
    horizon: int = 24,
    nlag: int = 2,
    n_reps: int = 500,
    seed: int = 0,
    quantile_levels=(0.05, 0.16, 0.5, 0.84, 0.95),
    normalize_global: bool = True,
    mesh=None,
    backend: str | None = None,
) -> MultilevelIRFs:
    """Responses of every series to a common (global-factor) shock, per
    block, with wild-bootstrap bands — the Barigozzi-Conti-Luciani (2014,
    OBES 76(5)) headline exercise: "do euro-area countries respond
    asymmetrically to the common monetary policy?", answered by comparing
    block-level IRF bands to one global shock.

    Per block b: a VAR(nlag) on the joint system y_b = [F, G^b] (global
    factors ordered first, so Cholesky shocks 0..r_global-1 are the common
    shocks and the block shocks are orthogonalized against them), wild-
    bootstrap replications sharded over the mesh (models/favar.py), and
    every draw pushed through the block's loadings [Lam_g | Lam_b] and the
    stored standardization scale — series-space bands in original units.

    Each block fits its own joint VAR, so a one-sd Cholesky innovation to
    F_j is NOT the same size across blocks (F's residual variance differs by
    system).  With ``normalize_global=True`` (default) every draw's IRFs to
    global shock j are rescaled to a UNIT IMPACT on F_j in that draw's
    system — the unit-effect normalization of the structural-VAR literature
    — which removes the shock-size difference and makes cross-block bands
    comparable.  Residual caveat for reading asymmetry off the bands: the
    per-block parameter draws are still independent estimations (a shared
    seed reuses the Rademacher signs only), so treat band overlap as a
    diagnostic, not a formal test of equal responses.
    """
    from .favar import BootstrapIRFs, series_irfs, wild_bootstrap_irfs

    r_g = results.global_factors.shape[1]

    def _unit_impact(arr):
        # arr (..., ns_sys, H, K): rescale global-shock columns j < r_g so
        # the impact response of F_j to shock j is exactly 1 per draw.
        # Cholesky impacts are positive in exact arithmetic, but a
        # degenerate bootstrap draw (near-zero F_j residual variance) can
        # produce a ~0 impact; guard the divisor so such draws yield large
        # finite responses instead of inf/NaN bands that poison the
        # quantile step.
        eps = jnp.asarray(jnp.finfo(arr.dtype).eps, arr.dtype)
        cols = []
        for j in range(arr.shape[-1]):
            col = arr[..., :, :, j]
            if j < r_g:
                impact = arr[..., j, 0, j][..., None, None]
                safe = jnp.where(
                    jnp.abs(impact) > eps,
                    impact,
                    jnp.where(impact < 0, -eps, eps),
                )
                col = col / safe
            cols.append(col)
        return jnp.stack(cols, axis=-1)

    series_out, boots = [], []
    for idx, Gb, Lb in zip(
        results.blocks, results.block_factors, results.block_loadings
    ):
        y = jnp.concatenate([results.global_factors, Gb], axis=1)
        bs = wild_bootstrap_irfs(
            y,
            nlag,
            0,
            y.shape[0] - 1,
            horizon=horizon,
            n_reps=n_reps,
            seed=seed,
            quantile_levels=quantile_levels,
            mesh=mesh,
            backend=backend,
        )
        if normalize_global:
            point = _unit_impact(bs.point)
            draws = _unit_impact(bs.draws)
            q = jnp.quantile(draws, jnp.asarray(quantile_levels), axis=0)
            bs = BootstrapIRFs(point, draws, q, np.asarray(quantile_levels))
        lam = jnp.concatenate([results.global_loadings[idx], Lb], axis=1)
        series_out.append(series_irfs(bs, lam, scale=results.stds[idx]))
        boots.append(bs)
    return MultilevelIRFs(series_out, boots, r_g)
