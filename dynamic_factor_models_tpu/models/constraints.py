"""Linear restrictions on factor loadings (restricted least squares).

Rewrite of the reference constraint machinery (dfm_functions.ipynb cells
60-67): per constrained series, the OLS coefficient vector is projected onto
{b : R b = r} via b <- b - (X'X)^-1 R' (R (X'X)^-1 R')^-1 (R b - r).

The per-series blocks are stored dense — (nc, k, nfac) — so the projection is
one ``vmap`` inside the jitted ALS loop instead of the reference's per-series
dispatch.
"""

from __future__ import annotations

from typing import NamedTuple, Sequence

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["LambdaConstraint", "construct_constraint", "project_constrained"]


class LambdaConstraint(NamedTuple):
    series: np.ndarray  # (nc,) indices of constrained series in the panel
    R: jnp.ndarray  # (nc, k, nfac)
    r: jnp.ndarray  # (nc, k) raw restriction values (unstandardized units)

    def standardized(self, stds: jnp.ndarray) -> jnp.ndarray:
        """r in standardized-data units: r / std of the constrained series
        (reference cell 67, `standardize_constraint!`)."""
        return self.r / stds[jnp.asarray(self.series)][:, None]

    def with_const_column(self) -> jnp.ndarray:
        """R with a zero column appended for the loading-regression constant
        (reference cell 64, `get_Rr(..., Val(:loading))`)."""
        nc, k, _ = self.R.shape
        return jnp.concatenate([self.R, jnp.zeros((nc, k, 1), self.R.dtype)], axis=2)


def construct_constraint(
    varnames: Sequence[str],
    used_varnames: Sequence[str],
    R,
    r,
) -> LambdaConstraint:
    """Build per-series restriction blocks from variable names (cell 62).

    Each named series gets the full (k, nfac) block R and value vector r.
    """
    used = list(used_varnames)
    series = np.array([used.index(v) for v in varnames], dtype=np.int32)
    R = jnp.asarray(np.asarray(R, dtype=np.float64))
    r = jnp.asarray(np.asarray(r, dtype=np.float64)).reshape(-1)
    nc = len(series)
    return LambdaConstraint(
        series=series,
        R=jnp.broadcast_to(R, (nc, *R.shape)),
        r=jnp.broadcast_to(r, (nc, r.shape[0])),
    )


def project_constrained(
    b: jnp.ndarray,
    A: jnp.ndarray,
    R: jnp.ndarray,
    r: jnp.ndarray,
) -> jnp.ndarray:
    """Restricted-LS projection for one series (cell 64, `impose_constraint!`).

    b: (K,) unrestricted OLS coefficients; A: (K, K) normal matrix X'WX.
    """
    Ainv = jnp.linalg.pinv(A, hermitian=True)
    RA = R @ Ainv  # (k, K)
    S = RA @ R.T  # (k, k)
    corr = Ainv @ R.T @ (jnp.linalg.pinv(S) @ (R @ b - r))
    return b - corr


def apply_constraint_batch(
    lam: jnp.ndarray,
    A: jnp.ndarray,
    constraint: LambdaConstraint | None,
    r_values: jnp.ndarray | None = None,
    R_blocks: jnp.ndarray | None = None,
    ok: jnp.ndarray | None = None,
) -> jnp.ndarray:
    """Project the constrained rows of a batched coefficient matrix.

    lam: (ns, K) coefficients; A: (ns, K, K) normal matrices.  r_values /
    R_blocks default to the raw constraint arrays.  `ok` masks series whose
    sample passed the minimum-observation rule (constraints are only imposed
    on estimated rows, matching the reference's in-loop placement).
    """
    if constraint is None:
        return lam
    cs = jnp.asarray(constraint.series)
    R = R_blocks if R_blocks is not None else constraint.R
    r = r_values if r_values is not None else constraint.r
    b_c = jax.vmap(project_constrained)(lam[cs], A[cs], R, r)
    if ok is not None:
        b_c = jnp.where(ok[cs][:, None], b_c, lam[cs])
    return lam.at[cs].set(b_c)
