from .dfm import (
    BatchFactorResults,
    RollingFactorResults,
    DFMConfig,
    DFMResults,
    FactorEstimateStats,
    compute_series,
    estimate_dfm,
    estimate_factor,
    estimate_factor_batch,
    estimate_factor_loading,
    rolling_factor_estimates,
)
from .var import (
    GrangerCausality,
    HistoricalDecomposition,
    VARLagSelection,
    VARResults,
    estimate_var,
    generalized_irf,
    granger_causality,
    historical_decomposition,
    impulse_response,
    select_var_lag,
)
from .selection import (
    FactorNumberEstimateStats,
    ahn_horenstein_er,
    ahn_horenstein_gr,
    amengual_watson_test,
    bai_ng_criterion,
    bai_ng_criterion_variant,
    estimate_factor_numbers,
    onatski_ed,
)
from .constraints import LambdaConstraint, construct_constraint
from .instability import InstabilityResults, instability_scan
from .favar_instruments import cca_with_factors, choose_stepwise, favar_instrument_table
from .emaccel import SquaremState, squarem, squarem_state
from .msdfm import (
    MSDFMParams,
    MSDFMResults,
    MSForecast,
    MSStandardErrors,
    fit_ms_dfm,
    ms_standard_errors,
    forecast_ms,
    kim_filter,
    kim_smoother_probs,
)
from .ssm import (
    EMResults,
    PanelStats,
    SSMParams,
    SteadyEMState,
    compute_panel_stats,
    em_step,
    em_step_assoc,
    em_step_sqrt,
    em_step_sqrt_collapsed,
    em_step_stats,
    em_step_steady,
    estimate_dfm_em,
    estimate_dfm_mle,
    estimate_dfm_twostep,
    ssm_standard_errors,
    kalman_filter,
    kalman_smoother,
)
from .steady import (
    PeriodicSteadyState,
    SteadyState,
    dare_doubling,
    linear_recursion,
    periodic_dare,
    steady_state,
)
from .favar import (
    BootstrapIRFs,
    ForecastFan,
    SeriesFan,
    SeriesIRFs,
    block_bootstrap_irfs,
    bootstrap_forecast_fan,
    series_forecast_fan,
    series_irfs,
    wild_bootstrap_irfs,
    wild_bootstrap_irfs_resumable,
)
from .dynpca import (
    DynamicPCAResults,
    HallinLiskaResults,
    coherence,
    dynamic_pca,
    forecast_common_component,
    hallin_liska_q,
    spectral_density,
)
from .multilevel import (
    MultilevelIRFs,
    MultilevelResults,
    estimate_multilevel_dfm,
    multilevel_series_irfs,
)
from .ssm_ar import (
    EMARResults,
    SSMARParams,
    em_step_ar,
    estimate_dfm_em_ar,
    nowcast_em_ar,
)
from .mixed_freq import (
    MFResults,
    MixedFreqParams,
    estimate_mixed_freq_dfm,
    steady_gains,
)
from .news import NowcastNews, nowcast_news
from .bayes import (
    BayesModelComparison,
    BayesPriors,
    BayesResults,
    PosteriorForecast,
    PosteriorSeriesIRFs,
    dic,
    select_nfac_bayes,
    estimate_dfm_bayes,
    posterior_forecast,
    posterior_irfs,
    posterior_series_irfs,
    rhat,
    simulation_smoother,
)
from .sv import SVPriors, SVResults, estimate_dfm_sv
from .evaluate import (
    DieboldMariano,
    ForecastEvaluation,
    diebold_mariano,
    evaluate_forecasts,
)
from .tvp import TVPLoadings, tvp_loadings
from .svar import (
    LocalProjection,
    ProxyBootstrapIRFs,
    ProxyImpact,
    SignRestriction,
    SignRestrictionIRFs,
    local_projection,
    proxy_bootstrap_irfs,
    proxy_impact,
    proxy_irfs,
    sign_restriction_irfs,
)
from .forecast import (
    ConditionalForecast,
    DFMForecast,
    conditional_forecast,
    forecast_factors,
    forecast_series,
    nowcast_em,
    nowcast_ssm,
)
