"""SQUAREM acceleration for EM fixed-point iterations.

Varadhan & Roland (2008, Scand. J. Statist.) squared extrapolation with the
S3 steplength and their self-tuning steplength bound: one cycle evaluates
the EM map F three times —

    p1 = F(p0), p2 = F(p1)
    r = p1 - p0,  v = (p2 - p1) - r
    alpha = -||r|| / ||v||, clamped into [-alphamax, -1]
    cand = p0 - 2 alpha r + alpha^2 v      # alpha = -1 reproduces p2
    result = F(cand)  if loglik(cand) >= loglik(p1) and finite, else p2

— and contracts the slow geometric tail of EM (persistent-factor models
are exactly the slow-EM regime) by squaring the linearized map's
contraction factor per cycle.  The unbounded scheme wastes its third
evaluation whenever a large extrapolation overshoots the ridge of the
likelihood (measured on the persistent-factor test panel: rejection runs
of 4-5 cycles); the bound makes overshoot self-correcting — accepted
steps that hit the bound double `alphamax`, rejections halve it back
toward the plain-EM endpoint, so the cycle re-earns large steps instead
of re-losing them.

The loglik guard bounds the downside: a rejected cycle returns p2 (two
plain EM steps of progress exactly), an accepted cycle returns F(cand)
with loglik(F(cand)) >= loglik(cand) >= loglik(p1) — i.e. at least one
plain step's monotone progress, in practice far more.

This is a *step transformer* for `emloop.run_em_loop`: `squarem(step)`
keeps the loop contract `step(state, *args) -> (new_state,
loglik-of-input)`, with the steplength bound threaded through the loop as
part of an augmented parameter pytree (`SquaremState`) — wrap the initial
parameters with `squarem_state`, unwrap the result with `.params`.  The
same on-device while_loop, checkpointing, and tolerance semantics apply
unchanged; one loop "iteration" is one cycle (three F evaluations).

The reference has no acceleration anywhere (its only EM-family code path,
`Parametric()`, is declared but unimplemented — SURVEY.md §2.3); this is
framework-side capability on top of reference parity.
"""

from __future__ import annotations

from functools import lru_cache
from typing import Any, NamedTuple

import jax
import jax.numpy as jnp

__all__ = ["SquaremState", "squarem", "squarem_state", "unwrap_state"]

_ALPHAMAX_INIT = 4.0


class SquaremState(NamedTuple):
    """EM parameters + the self-tuning SQUAREM steplength bound."""

    params: Any
    alphamax: jnp.ndarray


def squarem_state(params) -> SquaremState:
    """Wrap initial EM parameters for a `squarem`-accelerated loop."""
    return SquaremState(params, jnp.asarray(_ALPHAMAX_INIT))


def unwrap_state(state):
    """Strip step-transformer / fast-path wrappers down to the bare
    parameter pytree: every augmented loop carry in this codebase
    (SquaremState here, ssm.SteadyEMState) holds the real parameters
    under ``.params``, and bare parameter types do not have that
    attribute.  Used by the estimation entry points and the recovery
    ladder's demote rung (emloop `fallback_unwrap`), which must peel
    whatever wrapper the tripped loop happened to be running under."""
    while hasattr(state, "params"):
        state = state.params
    return state


def _sq_norm(tree):
    leaves = jax.tree.leaves(tree)
    return sum(jnp.vdot(l, l).real for l in leaves)


@lru_cache(maxsize=None)
def squarem(step, project=None):
    """Wrap EM map `step` into one SQUAREM (S3) cycle.

    The returned function has the run_em_loop step contract but over
    `SquaremState` instead of bare parameters: `accel_step(state, *args)
    -> (new_state, loglik-of-state.params)`.

    `project` (optional, module-level for caching) maps an extrapolated
    parameter pytree back into the feasible region before evaluation
    (e.g. variance floors, covariance PSD projection) — extrapolation is
    unconstrained and can leave the region the EM map guarantees.

    Cached on (step, project) identity so repeated calls return the same
    function object and `_em_while`'s static-argument jit cache hits.
    """

    def accel_step(state: SquaremState, *args):
        p0, alphamax = state.params, state.alphamax
        p1, ll0 = step(p0, *args)
        p2, ll1 = step(p1, *args)
        r = jax.tree.map(lambda a, b: a - b, p1, p0)
        v = jax.tree.map(lambda a2, a1, rr: (a2 - a1) - rr, p2, p1, r)
        rn = _sq_norm(r)
        vn = _sq_norm(v)
        tiny = jnp.asarray(jnp.finfo(rn.dtype).tiny, rn.dtype)
        alpha_raw = -jnp.sqrt(jnp.maximum(rn, tiny) / jnp.maximum(vn, tiny))
        # clamp into [-alphamax, -1]: -1 is the plain-EM endpoint (alpha =
        # -1 gives cand = p2 exactly), -alphamax the earned trust region
        alpha = jnp.clip(alpha_raw, -alphamax.astype(alpha_raw.dtype), -1.0)
        cand = jax.tree.map(
            lambda t0, rr, vv: (
                t0 - 2.0 * alpha.astype(t0.dtype) * rr
                + (alpha * alpha).astype(t0.dtype) * vv
            ),
            p0,
            r,
            v,
        )
        if project is not None:
            cand = project(cand)
        p3, ll_cand = step(cand, *args)
        # accept the extrapolation only when its own loglik is finite and
        # at least EM-monotone relative to p1 (EM guarantees ll(p2) >= ll1,
        # so rejecting keeps the cycle a plain double EM step)
        ok = jnp.isfinite(ll_cand) & (ll_cand >= ll1)
        new_params = jax.tree.map(lambda a, b: jnp.where(ok, a, b), p3, p2)
        at_bound = jnp.abs(alpha) >= alphamax.astype(alpha.dtype) - 1e-6
        new_alphamax = jnp.where(
            ok & at_bound,
            alphamax * 2.0,  # earned a larger trust region
            jnp.where(ok, alphamax, jnp.maximum(alphamax * 0.5, 1.0)),
        )
        return SquaremState(new_params, new_alphamax), ll0

    return accel_step
