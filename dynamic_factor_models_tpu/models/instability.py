"""Table-4 instability scan: per-series Chow and QLR tests + split-sample
fitted-value correlations.

Rewrite of the reference's widest driver loop (Stock_Watson.ipynb cell 57,
SURVEY.md section 3.5): thousands of small HAC regressions become one
``lax.scan`` over break dates whose body is a ``vmap`` over all series — the
scan carries the per-series running sup-Wald maxima, so memory stays
O(ns * T * k) instead of O(ns * breaks * T * k).

Per-series row compaction semantics follow the reference exactly: the rows of
[y, F] with any missing value are dropped (here: stable-compacted with a zero
pad, which leaves every Gram/autocovariance sum unchanged), and the break
index is taken on the compacted series.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from scipy import stats as sps

from ..ops.hac import compute_chow
from ..ops.linalg import solve_normal
from ..ops.masking import fillz, mask_of

__all__ = ["InstabilityResults", "instability_scan", "split_sample_fitted_correlations"]

# hard-coded QLR critical values used by the reference (cell 57:10), indexed
# by number of factors; levels 99/95/90%
QLR_THRESHOLDS = {4: 4 * np.array([5.12, 4.09, 3.59]), 8: 8 * np.array([3.57, 2.98, 2.69])}
LEVELS = (0.99, 0.95, 0.90)
COR_PCT = (0.05, 0.25, 0.50, 0.75, 0.95)


class InstabilityResults(NamedTuple):
    chow_stats: np.ndarray  # (ns,) NaN where the 80/80 sample rule fails
    qlr_stats: np.ndarray  # (ns,)
    chow_rej_ratios: np.ndarray  # (3,) at 99/95/90%
    qlr_rej_ratios: np.ndarray  # (3,)
    cor_pre_quantiles: np.ndarray  # (5,) at 5/25/50/75/95%
    cor_post_quantiles: np.ndarray  # (5,)


def _compact_series(y: np.ndarray, X: np.ndarray):
    """Host-side stable compaction of [y X] complete rows, zero-padded.

    Zero pad rows contribute nothing to any X'X / HAC sum, so downstream
    statistics equal those on the dropped-row series.
    """
    T = y.shape[0]
    m = np.isfinite(y) & np.isfinite(X).all(axis=1)
    order = np.argsort(~m, kind="stable")
    yc = np.where(m[order], y[order], 0.0)
    Xc = np.where(m[order][:, None], X[order], 0.0)
    return yc, Xc, int(m.sum())


# On zero-padded compacted series, `ops.hac.compute_chow` is exact as-is:
# pad rows have y = 0 and X = 0, so their residuals, break-dummy
# interactions, and every Gram/autocovariance contribution vanish — no
# padded re-implementation of the HAC-Wald sandwich is needed.
_chow_vmapped = jax.vmap(compute_chow, in_axes=(0, 0, None, None))


@partial(jax.jit, static_argnames=("q", "ccut", "compute_q0"))
def _scan_qlr(Y, X, counts, q: int, ccut: float, compute_q0: bool = False):
    """sup-Wald over central break dates for every series at once.

    Y: (ns, T) compacted series; X: (ns, T, k); counts: (ns,).
    Break grid is global; per-series validity window is
    [floor(ccut*count), count - floor(ccut*count)] as in the reference.
    The q=0 variant (the reference's `lm`) is skipped unless requested —
    Table 4 only consumes the HAC(q) variant, and each pass is a full
    vmapped HAC regression per break.
    """
    ns, T = Y.shape
    n1t = jnp.floor(ccut * counts).astype(jnp.int32)
    n2t = counts - n1t

    def body(carry, b):
        lm0, lmq = carry
        valid = (b >= n1t) & (b <= n2t)
        sq = _chow_vmapped(Y, X, q, b)
        lmq = jnp.where(valid, jnp.maximum(lmq, sq), lmq)
        if compute_q0:
            s0 = _chow_vmapped(Y, X, 0, b)
            lm0 = jnp.where(valid, jnp.maximum(lm0, s0), lm0)
        return (lm0, lmq), None

    init = (jnp.full(ns, -jnp.inf), jnp.full(ns, -jnp.inf))
    (lm0, lmq), _ = jax.lax.scan(body, init, jnp.arange(T + 1))
    return lm0, lmq


@partial(jax.jit, static_argnames=("q",))
def _chow_fixed(Y, X, n_pre, q: int):
    return _chow_vmapped(Y, X, q, n_pre)


def split_sample_fitted_correlations(data, factor_full, factor_pre, factor_post):
    """Correlations of full-sample vs subsample fitted values (cell 57:41-52).

    For each series: OLS of y on each factor set over complete rows (no
    constant, matching the reference), fitted values X @ b, correlation over
    jointly observed rows.
    """
    data = jnp.asarray(data)

    def fitted(y, X):
        w = (mask_of(y) & mask_of(X).all(axis=1)).astype(data.dtype)
        Xz = fillz(X)
        Xw = Xz * w[:, None]
        b = solve_normal(Xw.T @ Xz, Xw.T @ (fillz(y) * w))
        yhat = X @ b  # NaN outside the factor window
        return yhat

    def corr(a, b):
        m = mask_of(a) & mask_of(b)
        az = jnp.where(m, a, 0.0)  # NaN*0 is NaN, so zero out first
        bz = jnp.where(m, b, 0.0)
        n = m.sum()
        av = jnp.where(m, az - az.sum() / n, 0.0)
        bv = jnp.where(m, bz - bz.sum() / n, 0.0)
        return (av * bv).sum() / jnp.sqrt((av**2).sum() * (bv**2).sum())

    def per_series(y):
        yh = fitted(y, jnp.asarray(factor_full))
        yh_pre = fitted(y, jnp.asarray(factor_pre))
        yh_post = fitted(y, jnp.asarray(factor_post))
        return corr(yh, yh_pre), corr(yh, yh_post)

    return jax.vmap(per_series, in_axes=1)(data)


def instability_scan(
    data,
    factor_full,
    factor_pre,
    factor_post,
    n_pre_break: int,
    nfac: int,
    q: int = 6,
    ccut: float = 0.15,
    min_obs: int = 80,
    qlr_thresholds: np.ndarray | None = None,
) -> InstabilityResults:
    """Full Table-4 computation for one factor count (cell 57).

    n_pre_break: number of panel rows up to and including the break quarter
    (the reference's 1-based `lastpreberiod`, e.g. 104 for a 1984Q4 break).
    """
    data_np = np.asarray(data)
    F = np.asarray(factor_full)
    T, ns = data_np.shape

    Yc = np.zeros((ns, T))
    Xc = np.zeros((ns, T, F.shape[1]))
    counts = np.zeros(ns, dtype=np.int64)
    eligible = np.zeros(ns, dtype=bool)
    for i in range(ns):
        y = data_np[:, i]
        pre_obs = np.isfinite(y[:n_pre_break]).sum()
        post_obs = np.isfinite(y[n_pre_break:]).sum()
        eligible[i] = (pre_obs >= min_obs) and (post_obs >= min_obs)
        Yc[i], Xc[i], counts[i] = _compact_series(y, F)

    chow = np.asarray(_chow_fixed(jnp.asarray(Yc), jnp.asarray(Xc), n_pre_break, q))
    _, qlr = _scan_qlr(jnp.asarray(Yc), jnp.asarray(Xc), jnp.asarray(counts), q, ccut)
    qlr = np.asarray(qlr)
    chow = np.where(eligible, chow, np.nan)
    qlr = np.where(eligible, qlr, np.nan)

    chi2_thr = sps.chi2.ppf(LEVELS, df=nfac)
    n_valid = np.isfinite(chow).sum()
    chow_rej = np.array([(chow > t).sum() / n_valid for t in chi2_thr])
    if qlr_thresholds is not None:
        qlr_thr = np.asarray(qlr_thresholds)
    elif nfac in QLR_THRESHOLDS:
        qlr_thr = QLR_THRESHOLDS[nfac]
    else:
        raise ValueError(
            f"no built-in QLR critical values for nfac={nfac} (the reference "
            "hard-codes nfac 4 and 8); pass qlr_thresholds explicitly"
        )
    qlr_rej = np.array([(qlr > t).sum() / n_valid for t in qlr_thr])

    cor_pre, cor_post = split_sample_fitted_correlations(
        data, factor_full, factor_pre, factor_post
    )
    cor_pre = np.where(eligible, np.asarray(cor_pre), np.nan)
    cor_post = np.where(eligible, np.asarray(cor_post), np.nan)
    cor_pre_q = np.quantile(cor_pre[np.isfinite(cor_pre)], COR_PCT)
    cor_post_q = np.quantile(cor_post[np.isfinite(cor_post)], COR_PCT)

    return InstabilityResults(chow, qlr, chow_rej, qlr_rej, cor_pre_q, cor_post_q)
