"""Steady-state Kalman machinery: DARE fixed point + constant-gain tail.

The Stock-Watson state-space model (PAPER.md) is time-invariant, so the
filter's Riccati recursion Pp_{t+1} = Tm (Pp_t^-1 + C)^-1 Tm' + Qs converges
geometrically to a fixed point Pp∞ — typically within a few dozen of the 224
sample quarters.  Past that horizon every per-step Cholesky in the filter,
the smoother's per-step gain solve, and the E-step's O(T k^2) covariance
reductions are recomputing constants.  This module holds the model-agnostic
pieces of the `method="steady"` execution path (ssm.py wires them into the
DFM estimator):

  * `dare_doubling` — a jittable structure-preserving doubling solver (SDA;
    Chu-Fan-Lin 2005) for the filter-form DARE

        X = H + A' X (I + G X)^-1 A,        A = Tm', G = C, H = Qs,

    whose iterates satisfy H_k = Phi^{2^k}(0): quadratic convergence, ~6-8
    doublings cold.  The same recursion tracks the COMPOSED map applied to
    an arbitrary start, X_k = Phi^{2^k}(X0) = H_k + A_k' X0 (I+G_k X0)^-1 A_k,
    which is what makes EM warm starts cheap: with X0 the previous
    iteration's Pp∞ the transient is tiny and the early-exit fires after
    2-3 doublings instead of a cold solve.
  * `steady_state` — derived constants at the fixed point: Pu∞, the steady
    gain K∞ on the collapsed observation, the closed-loop transition
    Ā = (I - Pu∞C)Tm (so s_t = Ā s_{t-1} + K∞ b_t), the steady RTS gain
    J∞ = Pu∞Tm'Pp∞^-1, the steady smoothed covariance Ps∞ (a Stein
    equation, solved by Smith doubling), the right-boundary deviation sum
    S_dev = Σ_{j>=0} J∞^j (Pu∞ - Ps∞) J∞'^j, and the log-det constants of
    the steady per-step likelihood.
  * `convergence_horizon` — host-side t*: the number of exact head steps
    after which the time-varying recursion is within `tol` of the fixed
    point, from the spectral radius of Ā (forward and backward transients
    share it: rho(J∞) = rho(Ā) because J∞ = Pu∞Tm'Pp∞^-1 and
    Ā = Pu∞Pp∞^-1Tm have equal spectra) and verified by running the exact
    recursion.  t* is a SHAPE (the head scan length), so it is computed
    once per estimate call, never traced.
  * `linear_recursion` / `steady_tail` / `steady_smooth_tail` — the
    factorization-free tail kernels: a time-invariant linear recursion
    s_t = M s_{t-1} + g_t evaluated either as a `lax.scan` of matvecs or
    block-parallel (precomputed M^d powers, one einsum per block — the
    MXU-shaped form), plus the vectorized constant-gain per-step
    log-likelihood and the backward smoothed-mean recursion
    e_t = J∞ e_{t+1} + (I - J∞Tm) su_t.  Their jitted HLO contains no
    cholesky / triangular_solve ops (pinned by tests/test_perf_regression).
  * `periodic_dare` — the cyclostationary generalization for the
    mixed-frequency monthly/quarterly observation pattern: the mask cycle
    makes C_t periodic with period d, the Riccati map converges to a
    d-cycle of fixed points, and mixed_freq.steady_gains exposes the
    per-phase gain set.

Validated against `scipy.linalg.solve_discrete_are` in tests/test_steady.py.
"""

from __future__ import annotations

import os as _os
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

__all__ = [
    "SteadyState",
    "PeriodicSteadyState",
    "dare_doubling",
    "stein_sum",
    "steady_state",
    "convergence_horizon",
    "periodic_dare",
    "linear_recursion",
    "power_stack",
    "constant_gain_tick",
    "steady_tail",
    "steady_smooth_tail",
]

# same env knob as the ssm scans (read once at import; see ssm._SCAN_UNROLL)
_SCAN_UNROLL = int(_os.environ.get("DFM_SCAN_UNROLL", "8"))


def _sym(X):
    return 0.5 * (X + X.swapaxes(-1, -2))


def _default_tol(dtype) -> float:
    """Relative fixed-point tolerance: ~1e-12 in f64, ~2e-6 in f32."""
    return float(jnp.finfo(dtype).eps) ** 0.75


# ---------------------------------------------------------------------------
# DARE: structure-preserving doubling
# ---------------------------------------------------------------------------


def dare_doubling(Tm, C, Qs, X0=None, tol: float | None = None, max_iter: int = 64):
    """Solve the filter-form DARE by structure-preserving doubling.

    Fixed point of the information-form covariance recursion

        Pp = Tm (Pp^-1 + C)^-1 Tm' + Qs
           = Qs + Tm Pp (I + C Pp)^-1 Tm',

    i.e. X = H + A' X (I + G X)^-1 A with A = Tm', G = C, H = Qs.  The SDA
    iteration doubles the map each step,

        M_k     = (I + G_k H_k)^-1
        A_{k+1} = A_k M_k A_k
        G_{k+1} = G_k + A_k M_k G_k A_k'
        H_{k+1} = H_k + A_k' H_k M_k A_k,

    and the triple represents the 2^k-fold composed Riccati map
    Phi^{2^k}(X) = H_k + A_k' X (I + G_k X)^-1 A_k.  The iterate tracked
    for convergence is X_k = Phi^{2^k}(X0): with X0 = 0 (cold) X_k = H_k
    is the classical SDA sequence; with X0 a previous solve (EM warm
    start) the early-exit fires after the transient — 2-3 doublings —
    instead of the full cold count.  Quadratic convergence either way.

    Everything is `lax.while_loop`-jittable: pass concrete arrays for a
    host solve or call under jit for the in-graph EM warm start.

    Returns (X, iters, converged): the fixed point (symmetrized), the
    number of doubling steps taken (i32), and a bool.  Requires Tm stable
    (spectral radius < 1) and Qs PSD with the pair detectable — the
    conditions the DFM's stationary factor VAR satisfies.
    """
    dtype = Tm.dtype
    k = Tm.shape[0]
    eye = jnp.eye(k, dtype=dtype)
    tol = _default_tol(dtype) if tol is None else float(tol)
    A0 = Tm.T
    G0 = _sym(jnp.asarray(C, dtype))
    H0 = _sym(jnp.asarray(Qs, dtype))
    X0 = jnp.zeros((k, k), dtype) if X0 is None else _sym(jnp.asarray(X0, dtype))

    def apply_map(A, G, H):
        # Phi^{2^k}(X0) = H + A' X0 (I + G X0)^-1 A
        Z = jnp.linalg.solve(eye + G @ X0, A)
        return _sym(H + A.T @ X0 @ Z)

    def body(carry):
        A, G, H, X, _, it = carry
        M = jnp.linalg.solve(eye + G @ H, eye)
        AM = A @ M
        A1 = AM @ A
        G1 = _sym(G + AM @ G @ A.T)
        H1 = _sym(H + A.T @ H @ M @ A)
        return A1, G1, H1, apply_map(A1, G1, H1), X, it + 1

    def cond(carry):
        _, _, _, X, X_prev, it = carry
        num = jnp.linalg.norm(X - X_prev)
        den = jnp.maximum(jnp.linalg.norm(X), jnp.asarray(1.0, dtype))
        return (num > tol * den) & (it < max_iter)

    init = (A0, G0, H0, apply_map(A0, G0, H0), X0, jnp.asarray(0, jnp.int32))
    A, G, H, X, X_prev, iters = jax.lax.while_loop(cond, body, init)
    num = jnp.linalg.norm(X - X_prev)
    den = jnp.maximum(jnp.linalg.norm(X), jnp.asarray(1.0, dtype))
    return X, iters, num <= tol * den


def stein_sum(J, W, tol: float | None = None, max_iter: int = 48):
    """Sum the geometric matrix series X = Σ_{j>=0} J^j W J'^j by Smith
    doubling: X_{m+1} = X_m + J_m X_m J_m', J_{m+1} = J_m^2 — each step
    doubles the number of terms, so a spectral radius rho needs
    ~log2(log(tol)/log(rho)) iterations (6-8 in practice).  X solves the
    Stein equation X = W + J X J'.  Requires rho(J) < 1."""
    dtype = J.dtype
    tol = _default_tol(dtype) if tol is None else float(tol)

    def body(carry):
        Jc, X, it = carry
        X1 = _sym(X + Jc @ X @ Jc.T)
        return Jc @ Jc, X1, it + 1

    def cond(carry):
        Jc, X, it = carry
        # remaining terms are bounded by ||J_c||^2 * ||X||-scale
        return (jnp.linalg.norm(Jc) > tol) & (it < max_iter)

    _, X, _ = jax.lax.while_loop(
        cond, body, (J, _sym(W), jnp.asarray(0, jnp.int32))
    )
    return X


# ---------------------------------------------------------------------------
# Steady-state constants
# ---------------------------------------------------------------------------


class SteadyState(NamedTuple):
    """Constants of the converged filter/smoother, for a collapsed
    observation loading only the first q state dims (ssm.py: q = r).

    Pp/Pu: steady predicted/updated covariances (k, k); K: steady gain on
    the collapsed observation b_t (k, q) — s_t = Abar s_{t-1} + K b_t;
    Abar: closed-loop transition (I - Pu C)Tm; J: steady RTS gain
    Pu Tm' Pp^-1; Ps: steady smoothed covariance (interior); Sdev:
    Σ_{j>=0} J^j (Pu - Ps) J'^j, the right-boundary smoothed-covariance
    deviation sum (P_sm_{T-1-j} = Ps + J^j (Pu - Ps) J'^j); ld_pp/ld_pu:
    log|Pp| / log|Pu| (the per-step likelihood constant is
    ld_R∞ + ld_pp - ld_pu); riccati_iters: doubling steps of the DARE
    solve; converged: solver flag."""

    Pp: jnp.ndarray
    Pu: jnp.ndarray
    K: jnp.ndarray
    Abar: jnp.ndarray
    J: jnp.ndarray
    Ps: jnp.ndarray
    Sdev: jnp.ndarray
    ld_pp: jnp.ndarray
    ld_pu: jnp.ndarray
    riccati_iters: jnp.ndarray
    converged: jnp.ndarray


def _steady_from_pp(Tm, Cq, Pp, q: int, riccati_iters, converged) -> SteadyState:
    """Derive every SteadyState constant from the DARE solution Pp.
    Factorizations happen HERE, once — never in the tail kernels."""
    k = Tm.shape[0]
    dtype = Tm.dtype
    eye = jnp.eye(k, dtype=dtype)
    Cf = jnp.zeros((k, k), dtype).at[:q, :q].set(Cq)
    Lp = jnp.linalg.cholesky(_sym(Pp))
    Ppinv = jax.scipy.linalg.cho_solve((Lp, True), eye)
    M = _sym(Ppinv + Cf)
    Lm = jnp.linalg.cholesky(M)
    Pu = _sym(jax.scipy.linalg.cho_solve((Lm, True), eye))
    ld_pp = 2.0 * jnp.log(jnp.diagonal(Lp)).sum()
    ld_pu = -2.0 * jnp.log(jnp.diagonal(Lm)).sum()
    K = Pu[:, :q]
    Abar = Tm - (K @ Cq) @ Tm[:q, :]  # (I - Pu Cf) Tm without the k^3 zero block
    J = jax.scipy.linalg.cho_solve((Lp, True), Tm @ Pu).T  # Pu Tm' Pp^-1
    # steady smoothed covariance: Ps = Pu + J (Ps - Pp) J'  =>  Stein with
    # W = Pu - J Pp J'
    Ps = stein_sum(J, _sym(Pu - J @ Pp @ J.T))
    Sdev = stein_sum(J, _sym(Pu - Ps))
    return SteadyState(
        Pp=Pp, Pu=Pu, K=K, Abar=Abar, J=J, Ps=Ps, Sdev=Sdev,
        ld_pp=ld_pp, ld_pu=ld_pu,
        riccati_iters=riccati_iters, converged=converged,
    )


def steady_state(
    Tm, Cq, Qs, q: int | None = None, Pp0=None,
    tol: float | None = None, max_iter: int = 64,
) -> SteadyState:
    """Solve the DARE for the collapsed model and derive all steady
    constants.  `Cq` is the (q, q) leading block of the information matrix
    C = Lam'R^-1Lam (q = r for ssm.py; q inferred from Cq when omitted);
    `Pp0` warm-starts the doubling (pass the previous EM iteration's Pp∞).
    Jittable end-to-end."""
    q = Cq.shape[0] if q is None else q
    k = Tm.shape[0]
    dtype = Tm.dtype
    Cf = jnp.zeros((k, k), dtype).at[:q, :q].set(Cq)
    Pp, iters, ok = dare_doubling(Tm, Cf, Qs, X0=Pp0, tol=tol, max_iter=max_iter)
    return _steady_from_pp(Tm, Cq, Pp, q, iters, ok)


def convergence_horizon(
    Tm, Cq, Qs, steady: SteadyState, P0, tol: float | None = None,
    t_max: int = 4096,
):
    """Host-side convergence horizon t*: the first t at which the exact
    time-varying recursion started from P0 has ||Pu_t - Pu∞||_max <= tol.

    The spectral gap gives the a-priori estimate — deviations contract
    like rho(Ā)^{2t} (the covariance transient is quadratic in the state
    transient) — and the exact information-form recursion, run here in
    NumPy at k x k cost, confirms it; the returned t* is the verified
    count.  Returns (t_star, rho); t_star = t_max + 1 when the recursion
    has not converged within t_max (callers gate the fast path off), and
    immediately when rho >= 1 - 1e-6 (no usable steady state).

    t* is a static quantity (it becomes the head scan LENGTH), which is
    why this runs on host with concrete arrays, never under jit.
    """
    Tm = np.asarray(Tm, np.float64)
    Cq = np.asarray(Cq, np.float64)
    Qs = np.asarray(Qs, np.float64)
    P0 = np.asarray(P0, np.float64)
    Pu_inf = np.asarray(steady.Pu, np.float64)
    Abar = np.asarray(steady.Abar, np.float64)
    k = Tm.shape[0]
    q = Cq.shape[0]
    if tol is None:
        tol = _default_tol(np.asarray(steady.Pu).dtype)
    rho = float(np.max(np.abs(np.linalg.eigvals(Abar))))
    if not np.isfinite(rho) or rho >= 1.0 - 1e-6:
        return t_max + 1, rho
    Cf = np.zeros((k, k))
    Cf[:q, :q] = Cq
    eye = np.eye(k)
    scale = max(np.max(np.abs(Pu_inf)), 1.0)
    P = P0
    for t in range(1, t_max + 1):
        Pp = Tm @ P @ Tm.T + Qs
        Pp = 0.5 * (Pp + Pp.T)
        Pu = np.linalg.solve(np.linalg.inv(Pp) + Cf, eye)
        P = 0.5 * (Pu + Pu.T)
        if np.max(np.abs(P - Pu_inf)) <= tol * scale:
            return t, rho
    return t_max + 1, rho


# ---------------------------------------------------------------------------
# Periodic (cyclostationary) DARE — mixed-frequency mask cycles
# ---------------------------------------------------------------------------


class PeriodicSteadyState(NamedTuple):
    """Per-phase steady constants of a period-d observation cycle.  Phase j
    holds the quantities of a step whose measurement uses C_j: Pp[j] is the
    covariance PREDICTED INTO phase j (from phase j-1 mod d), Pu[j] the
    updated covariance, K[j] the gain (on the full state — slice [:, :q]
    for a q-dim collapsed observation), Abar[j] the closed-loop transition
    INTO phase j.  cycles counts full period sweeps of the solver."""

    Pp: jnp.ndarray  # (d, k, k)
    Pu: jnp.ndarray  # (d, k, k)
    K: jnp.ndarray  # (d, k, k)  = Pu[j] (information form: gain on b rides Pu)
    Abar: jnp.ndarray  # (d, k, k)
    J: jnp.ndarray  # (d, k, k)  RTS gain pairing phase j with phase j+1's Pp
    cycles: jnp.ndarray
    converged: jnp.ndarray


def periodic_dare(
    Tm, Cs, Qs, tol: float | None = None, max_cycles: int = 512,
) -> PeriodicSteadyState:
    """Fixed cycle of the Riccati recursion under a period-d observation
    pattern: C_t = Cs[t mod d] (full (k, k) information matrices).  The
    composed d-phase Riccati map is iterated (linear convergence at
    rho^(2d) per sweep — a handful of sweeps in practice) until the
    phase-0 predicted covariance stops moving, then one recording sweep
    materializes the per-phase constants.  Jittable."""
    Cs = jnp.asarray(Cs)
    d = Cs.shape[0]
    k = Tm.shape[0]
    dtype = Tm.dtype
    eye = jnp.eye(k, dtype=dtype)
    tol = _default_tol(dtype) if tol is None else float(tol)

    def riccati_phase(Pp, Cj):
        # update with Cj, then predict — returns (Pu_j, Pp into next phase)
        Lp = jnp.linalg.cholesky(_sym(Pp))
        Ppinv = jax.scipy.linalg.cho_solve((Lp, True), eye)
        Lm = jnp.linalg.cholesky(_sym(Ppinv + Cj))
        Pu = _sym(jax.scipy.linalg.cho_solve((Lm, True), eye))
        return Pu, _sym(Tm @ Pu @ Tm.T + Qs)

    def sweep(Pp0):
        def phase(Pp, Cj):
            Pu, Pp_next = riccati_phase(Pp, Cj)
            return Pp_next, (Pp, Pu)

        Pp_end, (Pps, Pus) = jax.lax.scan(phase, Pp0, Cs)
        return Pp_end, Pps, Pus

    def body(carry):
        Pp0, _, it = carry
        Pp1, _, _ = sweep(Pp0)
        return Pp1, Pp0, it + 1

    def cond(carry):
        Pp0, Pp_prev, it = carry
        num = jnp.linalg.norm(Pp0 - Pp_prev)
        den = jnp.maximum(jnp.linalg.norm(Pp0), jnp.asarray(1.0, dtype))
        return (num > tol * den) & (it < max_cycles)

    Pp_init = _sym(Tm @ Qs @ Tm.T + Qs) + eye
    Pp0, Pp_prev, cycles = jax.lax.while_loop(
        cond, body, (Pp_init, Pp_init + eye, jnp.asarray(0, jnp.int32))
    )
    num = jnp.linalg.norm(Pp0 - Pp_prev)
    den = jnp.maximum(jnp.linalg.norm(Pp0), jnp.asarray(1.0, dtype))
    ok = num <= tol * den
    # recording sweep at the fixed cycle
    _, Pps, Pus = sweep(Pp0)
    Abar = jnp.einsum("dij,jl->dil", eye[None] - jnp.einsum(
        "dij,djl->dil", Pus, Cs), Tm)
    # J[j] pairs phase j's update with phase j+1's prediction:
    # J_j = Pu_j Tm' Pp_{j+1}^-1
    Pp_next = jnp.roll(Pps, -1, axis=0)
    J = jax.vmap(
        lambda Pu, Ppn: jax.scipy.linalg.cho_solve(
            (jnp.linalg.cholesky(_sym(Ppn)), True), Tm @ Pu
        ).T
    )(Pus, Pp_next)
    return PeriodicSteadyState(
        Pp=Pps, Pu=Pus, K=Pus, Abar=Abar, J=J,
        cycles=cycles, converged=ok,
    )


# ---------------------------------------------------------------------------
# Factorization-free tail kernels
# ---------------------------------------------------------------------------


def linear_recursion(M, g, s_init, block: int = 0):
    """Evaluate the time-invariant linear recursion

        s_0 = M s_init + g_0,     s_t = M s_{t-1} + g_t

    over g (n, k), returning (n, k).  block == 0 runs a `lax.scan` of
    matvecs (the right shape for small n on CPU); block >= 2 runs the
    block-parallel MXU form: precompute the powers M^0..M^block once,
    build the lower-triangular block operator W[j, i] = M^{j-i}, and each
    length-`block` chunk is ONE einsum

        out[j] = Σ_{i<=j} M^{j-i} g_i + M^{j+1} s_carry

    — a (B, B, k, k) x (B, k) contraction plus a (B, k, k) x (k) carry
    term, scanned over ceil(n / block) chunks.  Identical results (same
    f64 bits up to matmul reassociation); no factorizations either way.
    """
    n, k = g.shape
    dtype = g.dtype
    if block <= 1 or n < 2 * block:

        def step(s, gt):
            s2 = M @ s + gt
            return s2, s2

        _, out = jax.lax.scan(step, s_init, g, unroll=_SCAN_UNROLL)
        return out

    nb = -(-n // block)  # ceil
    pad = nb * block - n
    gp = jnp.concatenate([g, jnp.zeros((pad, k), dtype)]) if pad else g
    # M^0 .. M^block (block is static: unrolled python loop at trace time)
    powers = [jnp.eye(k, dtype=dtype)]
    for _ in range(block):
        powers.append(M @ powers[-1])
    P = jnp.stack(powers)  # (block+1, k, k)
    idx = np.arange(block)[:, None] - np.arange(block)[None, :]  # j - i
    W = jnp.where(
        jnp.asarray(idx >= 0)[:, :, None, None],
        P[jnp.asarray(np.clip(idx, 0, block))],
        jnp.zeros((), dtype),
    )  # (B, B, k, k) lower-triangular in (j, i)
    Pcarry = P[1:]  # (B, k, k): M^{j+1}

    def chunk(s, gblk):
        out = jnp.einsum("jiab,ib->ja", W, gblk) + jnp.einsum(
            "jab,b->ja", Pcarry, s
        )
        return out[-1], out

    _, out = jax.lax.scan(chunk, s_init, gp.reshape(nb, block, k))
    return out.reshape(nb * block, k)[:n]


def power_stack(M, depth: int):
    """All powers M^0 .. M^depth as ONE (depth+1, k, k) stack, built by
    log-depth square-and-multiply: a stack holding powers 0..n extends
    to 0..2n with a single batched matmul (M^n @ [M^1..M^n]), so a
    depth-1024 stack costs 10 batched (k, k) GEMMs instead of 1024
    sequential ones.  `depth` is STATIC (a compile-time block bucket —
    serving/prefill.py buckets burst depths to powers of two so one
    executable serves every backlog in the bucket).  This is the
    power-table half of `linear_recursion`'s blocked einsum, factored
    out so the dual-form burst catch-up shares it."""
    if depth <= 0:
        return jnp.eye(M.shape[-1], dtype=M.dtype)[None]
    P = jnp.stack([jnp.eye(M.shape[-1], dtype=M.dtype), M])  # powers 0..1
    n = 1
    while n < depth:
        # M^{n+j} = M^n @ M^j for j = 1..n: one batched matmul doubles
        # the covered range
        P = jnp.concatenate(
            [P, jnp.einsum("ab,ibc->iac", P[-1], P[1:])], axis=0
        )
        n *= 2
    return P[: depth + 1]


def constant_gain_tick(Abar, K, s, b, phase):
    """One O(1) online filter update at the steady (or periodic-steady)
    fixed point: s' = Abar[j] s + K[j] b with j the observation phase.

    `Abar` (d, k, k) and `K` (d, k, q) hold the per-phase closed-loop
    transition and gain — d = 1 for a time-invariant observation pattern
    (`steady_state`), d = 3 for the mixed-frequency monthly/quarterly
    cycle (`periodic_dare` via mixed_freq.steady_gains).  `phase` is a
    traced i32 already reduced mod d.  Two matvecs, no factorization,
    no dependence on the sample length — the per-tick unit of the
    serving layer (serving/online.py wraps it with the collapsed-
    observation construction of b)."""
    return Abar[phase] @ s + K[phase] @ b


def steady_tail(Tm, Cq, Pu_qq, K, Abar, b, s_init, n_obs_const, ld_const, block: int = 0):
    """Constant-gain filter tail: filtered means + per-step log-likelihood
    terms for the steps past the convergence horizon.  All inputs are
    steady constants except b (n, q) — the collapsed observations — and
    s_init, the last exact-head filtered state.  Returns (su (n, k),
    lls (n,)).

    ll_t = -1/2 (n_obs log2pi + ld_const + quad_t) with
    ld_const = ld_R∞ + log|Pp∞| - log|Pu∞| and

        quad_t = -2 f_p'b_t + f_p'C f_p - rhs'Pu rhs,   rhs = b_t - C f_p,

    exactly `_info_filter_scan`'s likelihood with the covariances pinned
    at the fixed point (the x'R^-1x piece rides the PanelStats ll_corr as
    in the sequential path).  Contains matmuls and einsums only — the
    compiled HLO is factorization-free by construction.
    """
    q = Cq.shape[0]
    dtype = b.dtype
    log2pi = jnp.asarray(np.log(2.0 * np.pi), dtype)
    su = linear_recursion(Abar, b @ K.T, s_init, block=block)
    s_prev = jnp.concatenate([s_init[None], su[:-1]])
    fp = (s_prev @ Tm.T)[:, :q]
    rhs = b - fp @ Cq
    quad = (
        -2.0 * (fp * b).sum(axis=1)
        + jnp.einsum("ti,ij,tj->t", fp, Cq, fp)
        - jnp.einsum("ti,ij,tj->t", rhs, Pu_qq, rhs)
    )
    lls = -0.5 * (n_obs_const * log2pi + ld_const + quad)
    return su, lls


def steady_smooth_tail(Tm, J, su, block: int = 0):
    """Backward smoothed means over the tail from its filtered means:
    e_{T-1} = su_{T-1} (the smoothed mean equals the filtered mean at the
    sample end) and, with the steady RTS gain,

        e_t = J e_{t+1} + (I - J Tm) su_t.

    Runs as the SAME linear recursion as the forward pass, time-reversed —
    factorization-free.  Returns the (n, k) smoothed means."""
    g = su @ (jnp.eye(Tm.shape[0], dtype=su.dtype) - J @ Tm).T
    if su.shape[0] == 1:
        return su
    e_rev = linear_recursion(J, g[:-1][::-1], su[-1], block=block)
    return jnp.concatenate([e_rev[::-1], su[-1:]])
