"""Structural identification beyond the recursive ordering: external
instruments (proxy SVAR), sign restrictions, and Jorda local projections.

New capability: the reference identifies structural shocks only through the
Cholesky ordering (dfm_functions.ipynb cell 24), and its Table 5 merely
*selects* instrument variable sets by canonical correlation
(Stock_Watson.ipynb cells 60-61).  The Handbook chapter the reference
replicates (Stock-Watson 2016, sections 4-5) goes on to estimate structural
IRFs from such instruments; this module completes that workflow TPU-first:

- ``proxy_impact`` / ``proxy_irfs``: external-instrument (Mertens-Ravn)
  identification of one structural shock from VAR residuals and an
  instrument, with the closed-form one-standard-deviation scale and a
  jointly-resampled wild bootstrap ``vmap``-ed over replications.
- ``sign_restriction_irfs``: Haar-rotation rejection sampling (Uhlig) —
  candidate impact matrices ``chol(seps) @ Q`` for random orthogonal Q,
  IRF sign checks fully batched on device; thousands of draws are one
  ``vmap``-ed program, embarrassingly shardable like the bootstrap.
- ``local_projection``: direct Jorda IRF regressions at every horizon as one
  batched masked least-squares (``ops.linalg.ols_batched_series`` over a
  leads matrix) with per-horizon HAC bands from the shared Bartlett kernel
  (``ops.hac``).
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.hac import hac, hac_weighted
from ..ops.lags import lagmat
from ..ops.linalg import ols_batched_series, solve_normal
from ..ops.masking import fillz, mask_of
from ..utils.backend import on_backend
from .var import VARResults, companion_matrices

__all__ = [
    "ProxyImpact",
    "ProxyBootstrapIRFs",
    "proxy_impact",
    "proxy_irfs",
    "proxy_bootstrap_irfs",
    "SignRestriction",
    "SignRestrictionIRFs",
    "sign_restriction_irfs",
    "LocalProjection",
    "local_projection",
]


# ---------------------------------------------------------------------------
# External-instrument (proxy) identification
# ---------------------------------------------------------------------------


class ProxyImpact(NamedTuple):
    impact: jnp.ndarray  # (ns,) one-sd structural impact column
    relative: jnp.ndarray  # (ns,) unit-normalized impacts (policy entry = 1)
    first_stage_f: jnp.ndarray  # scalar first-stage F statistic
    shock_scale: jnp.ndarray  # scalar b_policy: policy impact of a 1-sd shock


def _proxy_moments(u: jnp.ndarray, z: jnp.ndarray, w: jnp.ndarray):
    """Masked covariance moments E[u z] and E[u u'] over jointly complete
    rows (w is the 0/1 row mask)."""
    n_used = w.sum()
    uz = fillz(u) * w[:, None]
    zc = fillz(z) * w - (fillz(z) * w).sum() / n_used * w  # demeaned on mask
    cov_uz = uz.T @ zc / n_used
    sigma = uz.T @ uz / n_used
    return cov_uz, sigma, zc, n_used


@partial(jax.jit, static_argnames=("policy",))
def _proxy_impact_core(u: jnp.ndarray, z: jnp.ndarray, policy: int):
    w = (mask_of(u).all(axis=1) & mask_of(z)).astype(u.dtype)
    cov_uz, sigma, zc, n_used = _proxy_moments(u, z, w)

    # relative impacts: b_i / b_policy = E[u_i z] / E[u_policy z]
    relative = cov_uz / cov_uz[policy]

    # first-stage F: u_policy on [1, z] over masked rows
    up = fillz(u[:, policy]) * w
    upc = up - up.sum() / n_used * w
    bz = (zc @ upc) / (zc @ zc)
    e = (upc - bz * zc) * w
    ssr, tss = e @ e, upc @ upc
    f_stat = (tss - ssr) / (ssr / (n_used - 2))

    # Mertens-Ravn closed form for the one-sd scale: order the policy
    # variable first, write beta for the remaining relative impacts, then
    #   gamma = Sig_21 - beta Sig_11
    #   Qm    = beta Sig_11 beta' - (Sig_21 beta' + beta Sig_12) + Sig_22
    #   b_policy^2 = Sig_11 - gamma' Qm^{-1} gamma
    order = np.r_[policy, [i for i in range(u.shape[1]) if i != policy]]
    sp = sigma[jnp.ix_(order, order)]
    beta = relative[order][1:]
    s11, s21, s22 = sp[0, 0], sp[1:, 0], sp[1:, 1:]
    gamma = s21 - beta * s11
    qm = (
        jnp.outer(beta, beta) * s11
        - (jnp.outer(s21, beta) + jnp.outer(beta, s21))
        + s22
    )
    b_policy = jnp.sqrt(s11 - gamma @ solve_normal(qm, gamma))
    return ProxyImpact(relative * b_policy, relative, f_stat, b_policy)


def proxy_impact(resid, z, policy: int = 0) -> ProxyImpact:
    """Identify one structural impact column from VAR residuals and an
    external instrument (Mertens-Ravn 2013 / Stock-Watson 2016 section 4).

    resid: (T, ns) reduced-form residuals (NaN rows allowed — e.g.
    ``VARResults.resid`` straight from ``estimate_var``); z: (T,) instrument,
    NaN where unavailable; policy: 0-based index of the normalization
    variable.  Moments use the jointly complete rows.

    Returns the one-standard-deviation impact column (``impact``), the
    unit-normalized relative impacts, the first-stage F statistic of
    ``resid[:, policy]`` on the instrument, and the closed-form shock scale.
    """
    return _proxy_impact_core(jnp.asarray(resid), jnp.asarray(z), int(policy))


def _irf_single_impact(var: VARResults, b: jnp.ndarray, horizon: int):
    """(ns, horizon) IRF to one impact column lifted into companion space."""
    ns = var.seps.shape[0]
    g = jnp.zeros((var.M.shape[0],), dtype=b.dtype).at[:ns].set(b)

    def step(x, _):
        return var.M @ x, var.Q @ x

    _, out = jax.lax.scan(step, g, None, length=horizon)
    return out.T


def proxy_irfs(
    var: VARResults, z, policy: int = 0, horizon: int = 24
) -> tuple[jnp.ndarray, ProxyImpact]:
    """IRFs to the instrumented structural shock: (ns, horizon) for a one-sd
    shock, plus the identified impact."""
    pid = proxy_impact(var.resid, z, policy)
    return _irf_single_impact(var, pid.impact, horizon), pid


class ProxyBootstrapIRFs(NamedTuple):
    point: jnp.ndarray  # (ns, H)
    draws: jnp.ndarray  # (n_reps, ns, H)
    quantiles: jnp.ndarray  # (nq, ns, H)
    quantile_levels: np.ndarray
    impact: ProxyImpact


@partial(jax.jit, static_argnames=("nlag", "policy", "horizon", "n_reps"))
def _proxy_bootstrap_core(
    yw, zw, key, nlag: int, policy: int, horizon: int, n_reps: int
):
    from .favar import _fit_dense_var, _wild_recursion  # shared bootstrap core

    Tw, ns = yw.shape
    betahat, ehat, _ = _fit_dense_var(yw, nlag)
    y_init = yw[:nlag]
    z_tail = zw[nlag:]  # NaN where the instrument is missing: sign-flipping
    # keeps the NaN, so resampled moments mask the same rows as the point fit

    def one_rep(k):
        # Mertens-Ravn wild bootstrap: ONE Rademacher sign per period flips
        # the residual row and the instrument together, preserving their
        # relevance covariance E[u z] in every resample
        signs = jax.random.rademacher(k, (Tw - nlag,), dtype=yw.dtype)
        z_star = jnp.concatenate([zw[:nlag], z_tail * signs])
        ystar = _wild_recursion(y_init, betahat, ehat * signs[:, None], nlag)

        b_star, e_star, seps_star = _fit_dense_var(ystar, nlag, solver="chol")
        resid_full = jnp.full((Tw, ns), jnp.nan, yw.dtype).at[nlag:].set(e_star)
        pid = _proxy_impact_core(resid_full, z_star, policy)

        M, Q, _ = companion_matrices(b_star, seps_star, nlag)
        g = jnp.zeros((ns * nlag,), yw.dtype).at[:ns].set(pid.impact)

        def step(x, _):
            return M @ x, Q @ x

        _, out = jax.lax.scan(step, g, None, length=horizon)
        return out.T

    keys = jax.random.split(key, n_reps)
    return jax.vmap(one_rep)(keys)


def proxy_bootstrap_irfs(
    y,
    z,
    nlag: int,
    initperiod: int,
    lastperiod: int,
    policy: int = 0,
    horizon: int = 24,
    n_reps: int = 1000,
    seed: int = 0,
    quantile_levels=(0.05, 0.16, 0.5, 0.84, 0.95),
    backend: str | None = None,
) -> ProxyBootstrapIRFs:
    """Wild bootstrap of proxy-identified IRFs, ``vmap``-ed over replications.

    y: (T, ns) VAR data; z: (T,) instrument aligned with y.  The window must
    be complete in y (as for ``wild_bootstrap_irfs``); instrument NaNs are
    allowed and masked inside the moment computation.  Each replication
    flips residual rows and the instrument with the same Rademacher sign.
    """
    from .favar import _prepare_window
    from .var import estimate_var

    with on_backend(backend):
        yw = _prepare_window(y, initperiod, lastperiod)
        zw = jnp.asarray(z)[initperiod : lastperiod + 1][-yw.shape[0] :]

        var = estimate_var(yw, nlag, 0, yw.shape[0] - 1, withconst=True)
        point, pid = proxy_irfs(var, zw, policy, horizon)

        draws = _proxy_bootstrap_core(
            yw, zw, jax.random.PRNGKey(seed), nlag, policy, horizon, n_reps
        )
        q = jnp.nanquantile(draws, jnp.asarray(quantile_levels), axis=0)
        return ProxyBootstrapIRFs(point, draws, q, np.asarray(quantile_levels), pid)


# ---------------------------------------------------------------------------
# Sign-restriction identification
# ---------------------------------------------------------------------------


class SignRestriction(NamedTuple):
    """One restriction: IRF of `variable` to `shock` at `horizon` has `sign`
    (+1 or -1)."""

    variable: int
    shock: int
    horizon: int
    sign: int


class SignRestrictionIRFs(NamedTuple):
    draws: jnp.ndarray  # (n_draws, ns, H, nshock) candidate IRFs
    accepted: jnp.ndarray  # (n_draws,) bool acceptance mask
    quantiles: np.ndarray  # (nq, ns, H, nshock) over accepted draws
    quantile_levels: np.ndarray
    acceptance_rate: float


@partial(jax.jit, static_argnames=("horizon", "n_draws"))
def _sign_restriction_core(M, Q, chol_s, restr, key, horizon: int, n_draws: int):
    ns = chol_s.shape[0]
    nstate = M.shape[0]

    def one_draw(k):
        # Haar-distributed orthogonal Q0: QR of a Gaussian matrix with the
        # R-diagonal sign fix (Rubio-Ramirez, Waggoner, Zha 2010)
        gauss = jax.random.normal(k, (ns, ns), dtype=chol_s.dtype)
        q0, r = jnp.linalg.qr(gauss)
        q0 = q0 * jnp.sign(jnp.diagonal(r))[None, :]
        B = chol_s @ q0  # candidate impact: B B' = seps

        g = jnp.zeros((nstate, ns), dtype=B.dtype).at[:ns, :].set(B)

        def step(x, _):
            return M @ x, Q @ x

        def one_shock(gcol):
            _, out = jax.lax.scan(step, gcol, None, length=horizon)
            return out.T  # (ns, H)

        irfs = jax.vmap(one_shock, in_axes=1, out_axes=2)(g)  # (ns, H, ns)

        vals = irfs[restr[:, 0], restr[:, 1], restr[:, 2]]
        ok = (vals * restr[:, 3] > 0).all()
        return irfs, ok

    keys = jax.random.split(key, n_draws)
    return jax.vmap(one_draw)(keys)


def sign_restriction_irfs(
    var: VARResults,
    restrictions,
    horizon: int = 24,
    n_draws: int = 2000,
    seed: int = 0,
    quantile_levels=(0.05, 0.16, 0.5, 0.84, 0.95),
    backend: str | None = None,
) -> SignRestrictionIRFs:
    """Set-identified IRFs by sign restrictions (Uhlig 2005 rejection
    sampling with Haar rotation draws).

    restrictions: iterable of ``SignRestriction`` (or (variable, shock,
    horizon, sign) tuples).  All `n_draws` candidate rotations are evaluated
    as one ``vmap``-ed, jit-compiled program — draws, IRF scans, and the
    sign checks all stay on device; only the quantile summary over the
    accepted set (data-dependent size) runs host-side.

    Returns all candidate IRF draws, the acceptance mask, and pointwise
    quantiles over accepted draws.
    """
    restr = np.asarray(
        [tuple(r) for r in restrictions], dtype=np.int32
    ).reshape(-1, 4)
    ns = int(var.seps.shape[0])
    # validate host-side: out-of-range indices would otherwise be clamped by
    # JAX's gather semantics and silently check the wrong IRF entry
    if ((restr[:, 0] < 0) | (restr[:, 0] >= ns)).any():
        raise ValueError(f"restriction variable index out of range [0, {ns})")
    if ((restr[:, 1] < 0) | (restr[:, 1] >= ns)).any():
        raise ValueError(f"restriction shock index out of range [0, {ns})")
    if ((restr[:, 2] < 0) | (restr[:, 2] >= horizon)).any():
        raise ValueError("restriction horizon outside [0, horizon)")
    if not np.isin(restr[:, 3], (-1, 1)).all():
        raise ValueError("restriction sign must be +1 or -1")
    with on_backend(backend):
        chol_s = jnp.linalg.cholesky(0.5 * (var.seps + var.seps.T))
        draws, ok = _sign_restriction_core(
            var.M, var.Q, chol_s, jnp.asarray(restr),
            jax.random.PRNGKey(seed), horizon, n_draws,
        )
        ok_np = np.asarray(ok)
        acc = np.asarray(draws)[ok_np]
        if acc.shape[0] == 0:
            raise ValueError(
                f"no accepted draws out of {n_draws}; restrictions may be "
                "mutually inconsistent — widen them or raise n_draws"
            )
        q = np.quantile(acc, np.asarray(quantile_levels), axis=0)
        return SignRestrictionIRFs(
            draws, ok, q, np.asarray(quantile_levels),
            float(ok_np.mean()),
        )


# ---------------------------------------------------------------------------
# Jorda local projections
# ---------------------------------------------------------------------------


class LocalProjection(NamedTuple):
    irf: jnp.ndarray  # (H+1,) shock coefficient at horizons 0..H
    se: jnp.ndarray  # (H+1,) HAC standard errors
    betas: jnp.ndarray  # (K, H+1) full coefficient matrix per horizon
    nobs: jnp.ndarray  # (H+1,) usable observations per horizon


@partial(jax.jit, static_argnames=("max_horizon", "q"))
def _local_projection_core(y, shock, controls, max_horizon: int, q: int | None):
    T = y.shape[0]
    H = max_horizon
    X = jnp.hstack([jnp.ones((T, 1), y.dtype), shock[:, None], controls])

    # leads matrix: column h holds y_{t+h} (trailing NaN)
    idx = jnp.arange(T)[:, None] + jnp.arange(H + 1)[None, :]
    Y = jnp.where(idx < T, fillz(y)[jnp.clip(idx, 0, T - 1)], jnp.nan)
    valid = (
        (idx < T)
        & mask_of(y)[jnp.clip(idx, 0, T - 1)]
        & mask_of(X).all(axis=1)[:, None]
    )
    W = valid.astype(y.dtype)
    X = fillz(X)  # zero-fill AFTER the row mask: 0-weight rows must not NaN
    # the Gram contractions (NaN * 0 weight is NaN, not 0)

    # one batched masked solve across all horizons (the per-horizon
    # regressions share the regressor block, exactly the ops/linalg shape)
    betas, resid = ols_batched_series(jnp.where(valid, Y, jnp.nan), X, W)

    # per-horizon HAC of the shock coefficient: masking rows out of both
    # X and u (0/1 weights) drops end-of-sample leads from the moments and
    # the bread, so the shared sandwich applies unchanged.  The truncation
    # is per-horizon (q_h = h, the MA(h) order of the direct-projection
    # error) via traced Bartlett weights at a shared static q_max; an
    # explicit q applies one shared truncation to every horizon.
    q_max = H if q is None else q
    qs = jnp.arange(H + 1) if q is None else jnp.full(H + 1, q)

    def hac_one(u_h, w_h, q_h):
        kern = jnp.maximum(0.0, 1.0 - jnp.arange(q_max + 1) / (q_h + 1.0))
        _, se_h = hac_weighted(fillz(u_h), X * w_h[:, None], kern)
        return se_h[1]

    se = jax.vmap(hac_one, in_axes=(1, 1, 0))(resid, W, qs)
    return betas, se, W.sum(axis=0)


def local_projection(
    y,
    shock,
    max_horizon: int = 24,
    controls=None,
    n_lags: int = 4,
    q: int | None = None,
    backend: str | None = None,
) -> LocalProjection:
    """Jorda (2005) local-projection IRF of `y` to `shock`.

    For each horizon h = 0..max_horizon regresses ``y_{t+h}`` on
    ``[1, shock_t, controls_t]`` and reports the shock coefficient with a
    HAC band.  The default truncation is h-aware: horizon h uses q_h = h,
    the MA(h) order of the error a direct projection induces, so short
    horizons are not over-truncated.  Passing an explicit ``q`` applies
    that one shared truncation to every horizon.  `controls` defaults to
    ``n_lags`` lags of y and of the shock.  All horizons are solved in one
    batched masked regression; HAC runs ``vmap``-ed over horizons.
    """
    y = jnp.asarray(y)
    shock = jnp.asarray(shock)
    if controls is None:
        controls = jnp.hstack(
            [lagmat(y, range(1, n_lags + 1)), lagmat(shock, range(1, n_lags + 1))]
        )
    else:
        controls = jnp.atleast_2d(jnp.asarray(controls).T).T
    with on_backend(backend):
        betas, se, nobs = _local_projection_core(
            y, shock, controls, int(max_horizon), None if q is None else int(q)
        )
        return LocalProjection(betas[1], se, betas, nobs)
