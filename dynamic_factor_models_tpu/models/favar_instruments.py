"""FAVAR instrument analysis: how well small VAR variable sets span the
factor space (Table 5), and greedy CCA-based variable selection.

Rewrite of Stock_Watson.ipynb cells 60-61: for a candidate variable set,
estimate a VAR(p) and compute canonical correlations between (a) its
residuals and the factor-VAR residuals and (b) its levels and the factors.
`choose_stepwise` greedily grows the set maximizing the smallest canonical
correlation of the residual blocks.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.cca import canonical_correlations
from .var import VARResults, estimate_var

__all__ = ["cca_with_factors", "choose_stepwise", "favar_instrument_table"]


def _complete_rows(*arrays):
    m = None
    for a in arrays:
        am = np.isfinite(np.asarray(a)).all(axis=1)
        m = am if m is None else m & am
    return m


def _residual_cca(var_resid, factor_var_resid) -> np.ndarray:
    """Canonical correlations of two residual blocks over jointly complete
    periods (shared by the Table-5 rows and the stepwise search)."""
    m = _complete_rows(var_resid, factor_var_resid)
    return np.asarray(
        canonical_correlations(
            jnp.asarray(np.asarray(var_resid)[m]),
            jnp.asarray(np.asarray(factor_var_resid)[m]),
        )
    )


def cca_with_factors(X, factor, var_resid, factor_var_resid):
    """Canonical correlations of residual and level blocks (cell 61).

    Returns (r_res, r_lev): correlations between VAR residuals and the
    factor-VAR residuals, and between variable levels and factor levels,
    each over jointly complete periods.
    """
    r_res = _residual_cca(var_resid, factor_var_resid)
    m2 = _complete_rows(X, factor)
    r_lev = canonical_correlations(
        jnp.asarray(np.asarray(X)[m2]), jnp.asarray(np.asarray(factor)[m2])
    )
    return r_res, np.asarray(r_lev)


def favar_instrument_table(data, names, var_names, factor, factor_var: VARResults,
                           nlag: int, initperiod: int, lastperiod: int):
    """One Table-5 row set: estimate the VAR on the named variables and
    return (r_res, r_lev)."""
    names = list(names)
    cols = [names.index(v) for v in var_names]
    X = np.asarray(data)[:, cols]
    var = estimate_var(jnp.asarray(X), nlag, initperiod, lastperiod, withconst=True,
                       compute_matrices=False)
    return cca_with_factors(X, factor, var.resid, factor_var.resid)


@partial(jax.jit, static_argnames=("nlag",))
def _stepwise_scores_batch(Xs, fvr_rows, rows_idx, nlag: int):
    """Score every candidate of one stepwise step in ONE vmapped program
    (module-level jit: repeat choose_stepwise calls hit the compile cache
    per set size instead of re-wrapping).

    Xs: (C, Tw, k) dense candidate windows; fvr_rows: (Tm, q) the
    factor-VAR residuals at the jointly complete rows; rows_idx: (Tm,)
    indices of those rows WITHIN the candidate-residual support (window
    rows nlag..).  Returns the min canonical correlation per candidate.
    """
    from .favar import _fit_dense_var

    k = Xs.shape[2]
    q = fvr_rows.shape[1]

    def one(Xw):
        _, ehat, _ = _fit_dense_var(Xw, nlag)  # (Tw - nlag, k)
        r = canonical_correlations(ehat[rows_idx], fvr_rows)
        return r[min(k, q) - 1]

    return jax.vmap(one)(Xs)


def choose_stepwise(data, names, factor, factor_var: VARResults, nfac: int,
                    nlag: int, initperiod: int, lastperiod: int) -> list[str]:
    """Greedy CCA-based instrument choice (cell 60, `choose_stepwise`).

    Candidates are the series fully observed on [initperiod, lastperiod];
    at each step the variable maximizing the smallest canonical correlation
    between the candidate-VAR residuals and the factor-VAR residuals joins
    the set.  The reference scores candidates serially (O(candidates x
    nfac) VAR fits); here each step's candidates are ONE vmapped batch of
    dense VAR fits + CCAs — same shapes within a step, so one compile per
    set size.
    """
    data = np.asarray(data)
    names = list(names)
    window = slice(initperiod, lastperiod + 1)
    avail = np.isfinite(data[window]).all(axis=0)
    cand_idx = list(np.flatnonzero(avail))
    fvr = np.asarray(factor_var.resid)

    # candidate residual support: window rows nlag.. (dense candidates);
    # intersect with the factor-VAR residual rows once — identical for
    # every candidate and every step
    support = np.arange(initperiod + nlag, lastperiod + 1)
    fvr_ok = np.isfinite(fvr[support]).all(axis=1)
    rows_idx = jnp.asarray(np.flatnonzero(fvr_ok))
    fvr_rows = jnp.asarray(fvr[support][fvr_ok])
    if rows_idx.size == 0:
        raise ValueError(
            "no overlap between the candidate window and the factor-VAR "
            "residual rows"
        )

    chosen: list[int] = []
    for _ in range(nfac):
        if not cand_idx:
            raise ValueError(
                f"stepwise selection stalled after {len(chosen)} of {nfac} "
                "variables: no fully-observed candidates remain"
            )
        Xs = jnp.asarray(
            np.stack([data[window][:, chosen + [j]] for j in cand_idx])
        )
        scores = np.asarray(
            _stepwise_scores_batch(Xs, fvr_rows, rows_idx, nlag)
        )
        if not np.isfinite(scores).any():
            raise ValueError(
                f"stepwise selection stalled after {len(chosen)} of {nfac} "
                "variables: no fully-observed candidate yields a finite "
                "canonical correlation"
            )
        best_j = cand_idx[int(np.nanargmax(scores))]
        chosen.append(best_j)
        cand_idx.remove(best_j)
    return [names[j] for j in chosen]
