"""FAVAR instrument analysis: how well small VAR variable sets span the
factor space (Table 5), and greedy CCA-based variable selection.

Rewrite of Stock_Watson.ipynb cells 60-61: for a candidate variable set,
estimate a VAR(p) and compute canonical correlations between (a) its
residuals and the factor-VAR residuals and (b) its levels and the factors.
`choose_stepwise` greedily grows the set maximizing the smallest canonical
correlation of the residual blocks.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..ops.cca import canonical_correlations
from .var import VARResults, estimate_var

__all__ = ["cca_with_factors", "choose_stepwise", "favar_instrument_table"]


def _complete_rows(*arrays):
    m = None
    for a in arrays:
        am = np.isfinite(np.asarray(a)).all(axis=1)
        m = am if m is None else m & am
    return m


def _residual_cca(var_resid, factor_var_resid) -> np.ndarray:
    """Canonical correlations of two residual blocks over jointly complete
    periods (shared by the Table-5 rows and the stepwise search)."""
    m = _complete_rows(var_resid, factor_var_resid)
    return np.asarray(
        canonical_correlations(
            jnp.asarray(np.asarray(var_resid)[m]),
            jnp.asarray(np.asarray(factor_var_resid)[m]),
        )
    )


def cca_with_factors(X, factor, var_resid, factor_var_resid):
    """Canonical correlations of residual and level blocks (cell 61).

    Returns (r_res, r_lev): correlations between VAR residuals and the
    factor-VAR residuals, and between variable levels and factor levels,
    each over jointly complete periods.
    """
    r_res = _residual_cca(var_resid, factor_var_resid)
    m2 = _complete_rows(X, factor)
    r_lev = canonical_correlations(
        jnp.asarray(np.asarray(X)[m2]), jnp.asarray(np.asarray(factor)[m2])
    )
    return r_res, np.asarray(r_lev)


def favar_instrument_table(data, names, var_names, factor, factor_var: VARResults,
                           nlag: int, initperiod: int, lastperiod: int):
    """One Table-5 row set: estimate the VAR on the named variables and
    return (r_res, r_lev)."""
    names = list(names)
    cols = [names.index(v) for v in var_names]
    X = np.asarray(data)[:, cols]
    var = estimate_var(jnp.asarray(X), nlag, initperiod, lastperiod, withconst=True,
                       compute_matrices=False)
    return cca_with_factors(X, factor, var.resid, factor_var.resid)


def choose_stepwise(data, names, factor, factor_var: VARResults, nfac: int,
                    nlag: int, initperiod: int, lastperiod: int) -> list[str]:
    """Greedy CCA-based instrument choice (cell 60, `choose_stepwise`).

    Candidates are the series fully observed on [initperiod, lastperiod];
    at each step the variable maximizing the smallest canonical correlation
    between the candidate-VAR residuals and the factor-VAR residuals joins
    the set.
    """
    data = np.asarray(data)
    names = list(names)
    window = slice(initperiod, lastperiod + 1)
    avail = np.isfinite(data[window]).all(axis=0)
    cand_idx = list(np.flatnonzero(avail))
    fvr = np.asarray(factor_var.resid)

    chosen: list[int] = []
    for _ in range(nfac):
        best_r, best_j = -np.inf, None
        for j in cand_idx:
            X = data[:, chosen + [j]]
            var = estimate_var(jnp.asarray(X), nlag, initperiod, lastperiod,
                               withconst=True, compute_matrices=False)
            r = _residual_cca(var.resid, fvr)
            r_min = float(r[min(X.shape[1], fvr.shape[1]) - 1])
            if r_min > best_r:
                best_r, best_j = r_min, j
        if best_j is None:
            raise ValueError(
                f"stepwise selection stalled after {len(chosen)} of {nfac} "
                "variables: no fully-observed candidate yields a finite "
                "canonical correlation"
            )
        chosen.append(best_j)
        cand_idx.remove(best_j)
    return [names[j] for j in chosen]
