"""VAR(p) in companion state-space form, with Cholesky-identified IRFs.

TPU-native rewrite of the reference VAR layer (dfm_functions.ipynb cells 3,
22-24, 42-43): masked balanced OLS replaces row dropping, the companion/
selector/impact matrices are built functionally, and impulse responses are a
``lax.scan`` over the horizon ``vmap``-ed over shocks (the reference's
per-shock matvec loop, cell 43).
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.lags import lagmat
from ..ops.linalg import solve_normal
from ..ops.masking import fillz, mask_of

__all__ = [
    "VARResults",
    "estimate_var",
    "impulse_response",
    "companion_matrices",
    "long_run_impact",
    "impulse_response_longrun",
    "fevd",
    "HistoricalDecomposition",
    "historical_decomposition",
]


class VARResults(NamedTuple):
    """Estimated VAR: y_t = Q z_t, z_t = M z_{t-1} + G u_t (reference cell 3)."""

    betahat: jnp.ndarray  # (1+ns*nlag, ns) coefficient matrix (const first)
    resid: jnp.ndarray  # (T, ns) residuals, NaN outside used rows
    seps: jnp.ndarray  # (ns, ns) innovation covariance, dof-corrected
    M: jnp.ndarray  # (ns*nlag, ns*nlag) companion
    Q: jnp.ndarray  # (ns, ns*nlag) selector
    G: jnp.ndarray  # (ns*nlag, ns) structural impact (Cholesky, recursive id)
    T_used: jnp.ndarray  # scalar: rows entering the regression
    nlag: int


def companion_matrices(betahat: jnp.ndarray, seps: jnp.ndarray, nlag: int):
    """Companion M, selector Q, impact G = chol(seps) (reference cell 24).

    betahat rows: [const, lag-1 block, ..., lag-p block]; G's lower-triangular
    Cholesky factor encodes the recursive (ordering-dependent) identification.
    """
    ns = seps.shape[0]
    b = betahat[1:].T  # (ns, ns*nlag): row per equation, const dropped
    M = jnp.zeros((ns * nlag, ns * nlag), dtype=betahat.dtype)
    M = M.at[:ns, :].set(b)
    if nlag > 1:
        M = M.at[ns:, : ns * (nlag - 1)].set(jnp.eye(ns * (nlag - 1), dtype=betahat.dtype))
    Q = jnp.zeros((ns, ns * nlag), dtype=betahat.dtype).at[:, :ns].set(jnp.eye(ns, dtype=betahat.dtype))
    G = jnp.zeros((ns * nlag, ns), dtype=betahat.dtype).at[:ns, :].set(jnp.linalg.cholesky(seps))
    return M, Q, G


@partial(jax.jit, static_argnames=("nlag", "withconst", "compute_matrices"))
def _estimate_var_window(yw, nlag: int, withconst: bool, compute_matrices: bool):
    Tw, ns = yw.shape
    xlag = lagmat(yw, range(1, nlag + 1))
    x = jnp.hstack([jnp.ones((Tw, 1), dtype=yw.dtype), fillz(xlag)]) if withconst else fillz(xlag)
    w = mask_of(yw).all(axis=1) & mask_of(xlag).all(axis=1)
    wf = w.astype(yw.dtype)
    Xw = x * wf[:, None]
    A = Xw.T @ x
    betahat = solve_normal(A, Xw.T @ fillz(yw))
    ehat = jnp.where(w[:, None], fillz(yw) - x @ betahat, jnp.nan)
    T_used = w.sum()
    K = x.shape[1]
    e0 = jnp.where(w[:, None], fillz(ehat), 0.0)
    seps = e0.T @ e0 / (T_used - K)
    if compute_matrices:
        M, Q, G = companion_matrices(
            betahat if withconst else jnp.vstack([jnp.zeros((1, ns), yw.dtype), betahat]),
            seps,
            nlag,
        )
    else:
        M = Q = G = jnp.zeros((0, 0), dtype=yw.dtype)
    return betahat, ehat, seps, M, Q, G, T_used


def estimate_var(
    y,
    nlag: int = 1,
    initperiod: int = 0,
    lastperiod: int | None = None,
    withconst: bool = True,
    compute_matrices: bool = True,
) -> VARResults:
    """Estimate a VAR(nlag) on rows [initperiod, lastperiod] of y
    (0-based inclusive window; reference cell 23).

    Rows with any missing value in [y, lags] are excluded (Balanced rule);
    seps uses the (T_used - K) dof correction.
    """
    y = jnp.asarray(y)
    if lastperiod is None:
        lastperiod = y.shape[0] - 1
    yw = y[initperiod : lastperiod + 1]
    betahat, ehat, seps, M, Q, G, T_used = _estimate_var_window(
        yw, nlag, withconst, compute_matrices
    )
    resid = jnp.full_like(y, jnp.nan).at[initperiod : lastperiod + 1].set(ehat)
    return VARResults(betahat, resid, seps, M, Q, G, T_used, nlag)


@partial(jax.jit, static_argnames=("T",))
def _irf_all(M, Q, G, T: int):
    def step(x, _):
        return M @ x, Q @ x

    def one_shock(g):
        _, out = jax.lax.scan(step, g, None, length=T)
        return out.T  # (ns, T)

    return jax.vmap(one_shock, in_axes=1, out_axes=2)(G)  # (ns, T, nshock)


def impulse_response(var: VARResults, shock_ids, T: int) -> jnp.ndarray:
    """IRFs to Cholesky-orthogonalized shocks (reference cells 42-43).

    shock_ids: "all", an int, or a sequence of 0-based shock indices.
    Returns (ns, T, nshock) — or (ns, T) for a scalar shock id.  The
    reference's scalar path references an undefined variable (SURVEY.md
    section 2.5 quirk 1); it is implemented correctly here.
    """
    irfs = _irf_all(var.M, var.Q, var.G, T)
    if isinstance(shock_ids, str) and shock_ids == "all":
        return irfs
    if isinstance(shock_ids, int):
        return irfs[:, :, shock_ids]
    return irfs[:, :, jnp.asarray(shock_ids)]


def long_run_impact(var: VARResults) -> jnp.ndarray:
    """Blanchard-Quah long-run identification: impact matrix B with
    C(1) B lower-triangular, B B' = seps.

    New capability (the reference identifies only recursively via Cholesky,
    cell 24): C(1) = (I - A_1 - ... - A_p)^{-1} is the long-run cumulative
    response; B = C(1)^{-1} chol(C(1) seps C(1)') makes long-run responses of
    earlier-ordered variables invariant to later-ordered shocks.
    Returns the (ns, ns) structural impact in observation space.
    """
    ns = var.seps.shape[0]
    # lag blocks from the companion top rows: correct for both withconst
    # layouts (betahat's const row is padded only when withconst=True)
    A_sum = sum(var.M[:ns, i * ns : (i + 1) * ns] for i in range(var.nlag))
    K = jnp.eye(ns, dtype=var.seps.dtype) - A_sum
    C1 = jnp.linalg.inv(K)
    S = C1 @ var.seps @ C1.T
    # B = C1^{-1} chol(S) = K chol(S): matmul, no second factorization (K is
    # the better-conditioned operand in the near-unit-root regime)
    return K @ jnp.linalg.cholesky(0.5 * (S + S.T))


def _lift_impact(var: VARResults, B: jnp.ndarray) -> jnp.ndarray:
    """(ns, ns) observation-space impact -> companion-space G."""
    ns = var.seps.shape[0]
    return jnp.zeros_like(var.G).at[:ns, :].set(B)


def impulse_response_longrun(var: VARResults, T: int) -> jnp.ndarray:
    """IRFs to long-run-identified shocks: (ns, T, nshock)."""
    return _irf_all(var.M, var.Q, _lift_impact(var, long_run_impact(var)), T)


def fevd(var: VARResults, T: int, impact=None) -> jnp.ndarray:
    """Forecast-error variance decomposition over horizons 1..T.

    Returns (ns, T, nshock): share of variable i's h-step forecast-error
    variance attributed to structural shock j (rows sum to 1 over shocks at
    every horizon).  Cholesky identification by default; pass an (ns, ns)
    observation-space `impact` (e.g. `long_run_impact(var)`) to decompose
    under a different identification — it is lifted to companion space here.
    """
    Gm = var.G if impact is None else _lift_impact(var, jnp.asarray(impact))
    irfs = _irf_all(var.M, var.Q, Gm, T)  # (ns, T, nshock)
    cum = jnp.cumsum(irfs**2, axis=1)  # sum over horizons of squared IRFs
    total = cum.sum(axis=2, keepdims=True)
    return cum / total


class HistoricalDecomposition(NamedTuple):
    contributions: jnp.ndarray  # (Tu, ns, nshock) per-shock contributions
    baseline: jnp.ndarray  # (Tu, ns) deterministic + initial-condition path
    shocks: jnp.ndarray  # (Tu, ns) recovered structural shocks
    rows: np.ndarray  # original row indices the decomposition covers


def historical_decomposition(var: VARResults, y) -> "HistoricalDecomposition":
    """Historical decomposition under recursive identification: split each
    series' realized path into the cumulative contributions of each
    structural shock plus the deterministic/initial-condition baseline.

    New capability (the reference computes IRFs only, cells 42-43): with
    eps_t = chol(seps)^{-1} u_t, the identity

        y_t = baseline_t + sum_j contribution_{j,t}

    holds exactly on the estimation window — baseline carries the constant
    and the pre-sample lags through the companion recursion, contribution j
    is a ``lax.scan`` of the companion driven only by shock j, ``vmap``-ed
    over shocks.

    y: the panel `var` was estimated on (same row indexing as var.resid).
    """
    import jax.scipy.linalg as jsl

    y = jnp.asarray(y)
    ns = var.seps.shape[0]
    p = var.nlag
    finite = np.asarray(mask_of(var.resid).all(axis=1))
    rows = np.flatnonzero(finite)
    if rows.size == 0:
        raise ValueError("var has no usable residual rows")
    if not finite[rows[0] : rows[-1] + 1].all():
        raise ValueError("historical decomposition needs a contiguous window")
    t0 = int(rows[0])
    if t0 < p:
        raise ValueError("window start leaves no room for the initial lags")

    u = fillz(var.resid[rows])  # (Tu, ns) reduced-form residuals
    L = var.G[:ns, :]  # chol(seps): observation-space impact
    eps = jsl.solve_triangular(L, u.T, lower=True).T  # structural shocks

    # betahat layout depends on withconst: (1 + ns*p, ns) with const first,
    # or (ns*p, ns) without — reading row 0 as the const in the latter case
    # would silently break the reconstruction identity
    if var.betahat.shape[0] == 1 + ns * p:
        const = var.betahat[0]
    elif var.betahat.shape[0] == ns * p:
        const = jnp.zeros(ns, dtype=y.dtype)
    else:
        raise ValueError(
            f"betahat shape {var.betahat.shape} inconsistent with "
            f"ns={ns}, nlag={p}"
        )
    c_vec = jnp.zeros(ns * p, dtype=y.dtype).at[:ns].set(const)
    z0 = jnp.concatenate([y[t0 - 1 - i] for i in range(p)])  # most recent first

    def base_step(z, _):
        z_n = var.M @ z + c_vec
        return z_n, var.Q @ z_n

    _, baseline = jax.lax.scan(base_step, z0, None, length=rows.size)

    def one_shock(g_col, eps_col):
        def step(z, e_t):
            z_n = var.M @ z + g_col * e_t
            return z_n, var.Q @ z_n

        _, contrib = jax.lax.scan(step, jnp.zeros_like(z0), eps_col)
        return contrib  # (Tu, ns)

    contribs = jax.vmap(one_shock, in_axes=(1, 1), out_axes=2)(var.G, eps)
    return HistoricalDecomposition(contribs, baseline, eps, rows)
