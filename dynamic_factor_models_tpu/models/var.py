"""VAR(p) in companion state-space form, with Cholesky-identified IRFs.

TPU-native rewrite of the reference VAR layer (dfm_functions.ipynb cells 3,
22-24, 42-43): masked balanced OLS replaces row dropping, the companion/
selector/impact matrices are built functionally, and impulse responses are a
``lax.scan`` over the horizon ``vmap``-ed over shocks (the reference's
per-shock matvec loop, cell 43).
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp

from ..ops.lags import lagmat
from ..ops.linalg import solve_normal
from ..ops.masking import fillz, mask_of

__all__ = [
    "VARResults",
    "estimate_var",
    "impulse_response",
    "companion_matrices",
    "long_run_impact",
    "impulse_response_longrun",
    "fevd",
]


class VARResults(NamedTuple):
    """Estimated VAR: y_t = Q z_t, z_t = M z_{t-1} + G u_t (reference cell 3)."""

    betahat: jnp.ndarray  # (1+ns*nlag, ns) coefficient matrix (const first)
    resid: jnp.ndarray  # (T, ns) residuals, NaN outside used rows
    seps: jnp.ndarray  # (ns, ns) innovation covariance, dof-corrected
    M: jnp.ndarray  # (ns*nlag, ns*nlag) companion
    Q: jnp.ndarray  # (ns, ns*nlag) selector
    G: jnp.ndarray  # (ns*nlag, ns) structural impact (Cholesky, recursive id)
    T_used: jnp.ndarray  # scalar: rows entering the regression
    nlag: int


def companion_matrices(betahat: jnp.ndarray, seps: jnp.ndarray, nlag: int):
    """Companion M, selector Q, impact G = chol(seps) (reference cell 24).

    betahat rows: [const, lag-1 block, ..., lag-p block]; G's lower-triangular
    Cholesky factor encodes the recursive (ordering-dependent) identification.
    """
    ns = seps.shape[0]
    b = betahat[1:].T  # (ns, ns*nlag): row per equation, const dropped
    M = jnp.zeros((ns * nlag, ns * nlag), dtype=betahat.dtype)
    M = M.at[:ns, :].set(b)
    if nlag > 1:
        M = M.at[ns:, : ns * (nlag - 1)].set(jnp.eye(ns * (nlag - 1), dtype=betahat.dtype))
    Q = jnp.zeros((ns, ns * nlag), dtype=betahat.dtype).at[:, :ns].set(jnp.eye(ns, dtype=betahat.dtype))
    G = jnp.zeros((ns * nlag, ns), dtype=betahat.dtype).at[:ns, :].set(jnp.linalg.cholesky(seps))
    return M, Q, G


@partial(jax.jit, static_argnames=("nlag", "withconst", "compute_matrices"))
def _estimate_var_window(yw, nlag: int, withconst: bool, compute_matrices: bool):
    Tw, ns = yw.shape
    xlag = lagmat(yw, range(1, nlag + 1))
    x = jnp.hstack([jnp.ones((Tw, 1), dtype=yw.dtype), fillz(xlag)]) if withconst else fillz(xlag)
    w = mask_of(yw).all(axis=1) & mask_of(xlag).all(axis=1)
    wf = w.astype(yw.dtype)
    Xw = x * wf[:, None]
    A = Xw.T @ x
    betahat = solve_normal(A, Xw.T @ fillz(yw))
    ehat = jnp.where(w[:, None], fillz(yw) - x @ betahat, jnp.nan)
    T_used = w.sum()
    K = x.shape[1]
    e0 = jnp.where(w[:, None], fillz(ehat), 0.0)
    seps = e0.T @ e0 / (T_used - K)
    if compute_matrices:
        M, Q, G = companion_matrices(
            betahat if withconst else jnp.vstack([jnp.zeros((1, ns), yw.dtype), betahat]),
            seps,
            nlag,
        )
    else:
        M = Q = G = jnp.zeros((0, 0), dtype=yw.dtype)
    return betahat, ehat, seps, M, Q, G, T_used


def estimate_var(
    y,
    nlag: int = 1,
    initperiod: int = 0,
    lastperiod: int | None = None,
    withconst: bool = True,
    compute_matrices: bool = True,
) -> VARResults:
    """Estimate a VAR(nlag) on rows [initperiod, lastperiod] of y
    (0-based inclusive window; reference cell 23).

    Rows with any missing value in [y, lags] are excluded (Balanced rule);
    seps uses the (T_used - K) dof correction.
    """
    y = jnp.asarray(y)
    if lastperiod is None:
        lastperiod = y.shape[0] - 1
    yw = y[initperiod : lastperiod + 1]
    betahat, ehat, seps, M, Q, G, T_used = _estimate_var_window(
        yw, nlag, withconst, compute_matrices
    )
    resid = jnp.full_like(y, jnp.nan).at[initperiod : lastperiod + 1].set(ehat)
    return VARResults(betahat, resid, seps, M, Q, G, T_used, nlag)


@partial(jax.jit, static_argnames=("T",))
def _irf_all(M, Q, G, T: int):
    def step(x, _):
        return M @ x, Q @ x

    def one_shock(g):
        _, out = jax.lax.scan(step, g, None, length=T)
        return out.T  # (ns, T)

    return jax.vmap(one_shock, in_axes=1, out_axes=2)(G)  # (ns, T, nshock)


def impulse_response(var: VARResults, shock_ids, T: int) -> jnp.ndarray:
    """IRFs to Cholesky-orthogonalized shocks (reference cells 42-43).

    shock_ids: "all", an int, or a sequence of 0-based shock indices.
    Returns (ns, T, nshock) — or (ns, T) for a scalar shock id.  The
    reference's scalar path references an undefined variable (SURVEY.md
    section 2.5 quirk 1); it is implemented correctly here.
    """
    irfs = _irf_all(var.M, var.Q, var.G, T)
    if isinstance(shock_ids, str) and shock_ids == "all":
        return irfs
    if isinstance(shock_ids, int):
        return irfs[:, :, shock_ids]
    return irfs[:, :, jnp.asarray(shock_ids)]


def long_run_impact(var: VARResults) -> jnp.ndarray:
    """Blanchard-Quah long-run identification: impact matrix B with
    C(1) B lower-triangular, B B' = seps.

    New capability (the reference identifies only recursively via Cholesky,
    cell 24): C(1) = (I - A_1 - ... - A_p)^{-1} is the long-run cumulative
    response; B = C(1)^{-1} chol(C(1) seps C(1)') makes long-run responses of
    earlier-ordered variables invariant to later-ordered shocks.
    Returns the (ns, ns) structural impact in observation space.
    """
    ns = var.seps.shape[0]
    # lag blocks from the companion top rows: correct for both withconst
    # layouts (betahat's const row is padded only when withconst=True)
    A_sum = sum(var.M[:ns, i * ns : (i + 1) * ns] for i in range(var.nlag))
    K = jnp.eye(ns, dtype=var.seps.dtype) - A_sum
    C1 = jnp.linalg.inv(K)
    S = C1 @ var.seps @ C1.T
    # B = C1^{-1} chol(S) = K chol(S): matmul, no second factorization (K is
    # the better-conditioned operand in the near-unit-root regime)
    return K @ jnp.linalg.cholesky(0.5 * (S + S.T))


def _lift_impact(var: VARResults, B: jnp.ndarray) -> jnp.ndarray:
    """(ns, ns) observation-space impact -> companion-space G."""
    ns = var.seps.shape[0]
    return jnp.zeros_like(var.G).at[:ns, :].set(B)


def impulse_response_longrun(var: VARResults, T: int) -> jnp.ndarray:
    """IRFs to long-run-identified shocks: (ns, T, nshock)."""
    return _irf_all(var.M, var.Q, _lift_impact(var, long_run_impact(var)), T)


def fevd(var: VARResults, T: int, impact=None) -> jnp.ndarray:
    """Forecast-error variance decomposition over horizons 1..T.

    Returns (ns, T, nshock): share of variable i's h-step forecast-error
    variance attributed to structural shock j (rows sum to 1 over shocks at
    every horizon).  Cholesky identification by default; pass an (ns, ns)
    observation-space `impact` (e.g. `long_run_impact(var)`) to decompose
    under a different identification — it is lifted to companion space here.
    """
    Gm = var.G if impact is None else _lift_impact(var, jnp.asarray(impact))
    irfs = _irf_all(var.M, var.Q, Gm, T)  # (ns, T, nshock)
    cum = jnp.cumsum(irfs**2, axis=1)  # sum over horizons of squared IRFs
    total = cum.sum(axis=2, keepdims=True)
    return cum / total
