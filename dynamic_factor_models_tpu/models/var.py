"""VAR(p) in companion state-space form, with Cholesky-identified IRFs.

TPU-native rewrite of the reference VAR layer (dfm_functions.ipynb cells 3,
22-24, 42-43): masked balanced OLS replaces row dropping, the companion/
selector/impact matrices are built functionally, and impulse responses are a
``lax.scan`` over the horizon ``vmap``-ed over shocks (the reference's
per-shock matvec loop, cell 43).
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.lags import lagmat
from ..ops.linalg import solve_normal
from ..ops.masking import fillz, mask_of

__all__ = [
    "VARResults",
    "estimate_var",
    "impulse_response",
    "companion_matrices",
    "long_run_impact",
    "impulse_response_longrun",
    "fevd",
    "HistoricalDecomposition",
    "historical_decomposition",
    "VARLagSelection",
    "select_var_lag",
    "generalized_irf",
    "GrangerCausality",
    "granger_causality",
]


class VARResults(NamedTuple):
    """Estimated VAR: y_t = Q z_t, z_t = M z_{t-1} + G u_t (reference cell 3)."""

    betahat: jnp.ndarray  # (1+ns*nlag, ns) coefficient matrix (const first)
    resid: jnp.ndarray  # (T, ns) residuals, NaN outside used rows
    seps: jnp.ndarray  # (ns, ns) innovation covariance, dof-corrected
    M: jnp.ndarray  # (ns*nlag, ns*nlag) companion
    Q: jnp.ndarray  # (ns, ns*nlag) selector
    G: jnp.ndarray  # (ns*nlag, ns) structural impact (Cholesky, recursive id)
    T_used: jnp.ndarray  # scalar: rows entering the regression
    nlag: int


def companion_matrices(betahat: jnp.ndarray, seps: jnp.ndarray, nlag: int):
    """Companion M, selector Q, impact G = chol(seps) (reference cell 24).

    betahat rows: [const, lag-1 block, ..., lag-p block]; G's lower-triangular
    Cholesky factor encodes the recursive (ordering-dependent) identification.
    """
    ns = seps.shape[0]
    b = betahat[1:].T  # (ns, ns*nlag): row per equation, const dropped
    M = jnp.zeros((ns * nlag, ns * nlag), dtype=betahat.dtype)
    M = M.at[:ns, :].set(b)
    if nlag > 1:
        M = M.at[ns:, : ns * (nlag - 1)].set(jnp.eye(ns * (nlag - 1), dtype=betahat.dtype))
    Q = jnp.zeros((ns, ns * nlag), dtype=betahat.dtype).at[:, :ns].set(jnp.eye(ns, dtype=betahat.dtype))
    G = jnp.zeros((ns * nlag, ns), dtype=betahat.dtype).at[:ns, :].set(jnp.linalg.cholesky(seps))
    return M, Q, G


@partial(jax.jit, static_argnames=("nlag", "withconst", "compute_matrices"))
def _estimate_var_window(
    yw, nlag: int, withconst: bool, compute_matrices: bool, row_mask=None
):
    """Masked balanced VAR OLS on one window.  `row_mask` (Tw,) optionally
    restricts the sample further (lag-selection fits every candidate order
    on one common sample this way).  Also returns X'X for Wald tests."""
    Tw, ns = yw.shape
    xlag = lagmat(yw, range(1, nlag + 1))
    x = jnp.hstack([jnp.ones((Tw, 1), dtype=yw.dtype), fillz(xlag)]) if withconst else fillz(xlag)
    w = mask_of(yw).all(axis=1) & mask_of(xlag).all(axis=1)
    if row_mask is not None:
        w = w & row_mask
    wf = w.astype(yw.dtype)
    Xw = x * wf[:, None]
    A = Xw.T @ x
    betahat = solve_normal(A, Xw.T @ fillz(yw))
    ehat = jnp.where(w[:, None], fillz(yw) - x @ betahat, jnp.nan)
    T_used = w.sum()
    K = x.shape[1]
    e0 = jnp.where(w[:, None], fillz(ehat), 0.0)
    seps = e0.T @ e0 / (T_used - K)
    if compute_matrices:
        M, Q, G = companion_matrices(
            betahat if withconst else jnp.vstack([jnp.zeros((1, ns), yw.dtype), betahat]),
            seps,
            nlag,
        )
    else:
        M = Q = G = jnp.zeros((0, 0), dtype=yw.dtype)
    return betahat, ehat, seps, M, Q, G, T_used, A


def estimate_var(
    y,
    nlag: int = 1,
    initperiod: int = 0,
    lastperiod: int | None = None,
    withconst: bool = True,
    compute_matrices: bool = True,
) -> VARResults:
    """Estimate a VAR(nlag) on rows [initperiod, lastperiod] of y
    (0-based inclusive window; reference cell 23).

    Rows with any missing value in [y, lags] are excluded (Balanced rule);
    seps uses the (T_used - K) dof correction.
    """
    y = jnp.asarray(y)
    if lastperiod is None:
        lastperiod = y.shape[0] - 1
    yw = y[initperiod : lastperiod + 1]
    betahat, ehat, seps, M, Q, G, T_used, _ = _estimate_var_window(
        yw, nlag, withconst, compute_matrices
    )
    resid = jnp.full_like(y, jnp.nan).at[initperiod : lastperiod + 1].set(ehat)
    return VARResults(betahat, resid, seps, M, Q, G, T_used, nlag)


@partial(jax.jit, static_argnames=("T",))
def _irf_all(M, Q, G, T: int):
    def step(x, _):
        return M @ x, Q @ x

    def one_shock(g):
        _, out = jax.lax.scan(step, g, None, length=T)
        return out.T  # (ns, T)

    return jax.vmap(one_shock, in_axes=1, out_axes=2)(G)  # (ns, T, nshock)


def impulse_response(var: VARResults, shock_ids, T: int) -> jnp.ndarray:
    """IRFs to Cholesky-orthogonalized shocks (reference cells 42-43).

    shock_ids: "all", an int, or a sequence of 0-based shock indices.
    Returns (ns, T, nshock) — or (ns, T) for a scalar shock id.  The
    reference's scalar path references an undefined variable (SURVEY.md
    section 2.5 quirk 1); it is implemented correctly here.
    """
    irfs = _irf_all(var.M, var.Q, var.G, T)
    if isinstance(shock_ids, str) and shock_ids == "all":
        return irfs
    if isinstance(shock_ids, int):
        return irfs[:, :, shock_ids]
    return irfs[:, :, jnp.asarray(shock_ids)]


def long_run_impact(var: VARResults) -> jnp.ndarray:
    """Blanchard-Quah long-run identification: impact matrix B with
    C(1) B lower-triangular, B B' = seps.

    New capability (the reference identifies only recursively via Cholesky,
    cell 24): C(1) = (I - A_1 - ... - A_p)^{-1} is the long-run cumulative
    response; B = C(1)^{-1} chol(C(1) seps C(1)') makes long-run responses of
    earlier-ordered variables invariant to later-ordered shocks.
    Returns the (ns, ns) structural impact in observation space.
    """
    ns = var.seps.shape[0]
    # lag blocks from the companion top rows: correct for both withconst
    # layouts (betahat's const row is padded only when withconst=True)
    A_sum = sum(var.M[:ns, i * ns : (i + 1) * ns] for i in range(var.nlag))
    K = jnp.eye(ns, dtype=var.seps.dtype) - A_sum
    C1 = jnp.linalg.inv(K)
    S = C1 @ var.seps @ C1.T
    # B = C1^{-1} chol(S) = K chol(S): matmul, no second factorization (K is
    # the better-conditioned operand in the near-unit-root regime)
    return K @ jnp.linalg.cholesky(0.5 * (S + S.T))


def _lift_impact(var: VARResults, B: jnp.ndarray) -> jnp.ndarray:
    """(ns, ns) observation-space impact -> companion-space G."""
    ns = var.seps.shape[0]
    return jnp.zeros_like(var.G).at[:ns, :].set(B)


def impulse_response_longrun(var: VARResults, T: int) -> jnp.ndarray:
    """IRFs to long-run-identified shocks: (ns, T, nshock)."""
    return _irf_all(var.M, var.Q, _lift_impact(var, long_run_impact(var)), T)


def fevd(var: VARResults, T: int, impact=None) -> jnp.ndarray:
    """Forecast-error variance decomposition over horizons 1..T.

    Returns (ns, T, nshock): share of variable i's h-step forecast-error
    variance attributed to structural shock j (rows sum to 1 over shocks at
    every horizon).  Cholesky identification by default; pass an (ns, ns)
    observation-space `impact` (e.g. `long_run_impact(var)`) to decompose
    under a different identification — it is lifted to companion space here.
    """
    Gm = var.G if impact is None else _lift_impact(var, jnp.asarray(impact))
    irfs = _irf_all(var.M, var.Q, Gm, T)  # (ns, T, nshock)
    cum = jnp.cumsum(irfs**2, axis=1)  # sum over horizons of squared IRFs
    total = cum.sum(axis=2, keepdims=True)
    return cum / total


class HistoricalDecomposition(NamedTuple):
    contributions: jnp.ndarray  # (Tu, ns, nshock) per-shock contributions
    baseline: jnp.ndarray  # (Tu, ns) deterministic + initial-condition path
    shocks: jnp.ndarray  # (Tu, ns) recovered structural shocks
    rows: np.ndarray  # original row indices the decomposition covers


def historical_decomposition(var: VARResults, y) -> "HistoricalDecomposition":
    """Historical decomposition under recursive identification: split each
    series' realized path into the cumulative contributions of each
    structural shock plus the deterministic/initial-condition baseline.

    New capability (the reference computes IRFs only, cells 42-43): with
    eps_t = chol(seps)^{-1} u_t, the identity

        y_t = baseline_t + sum_j contribution_{j,t}

    holds exactly on the estimation window — baseline carries the constant
    and the pre-sample lags through the companion recursion, contribution j
    is a ``lax.scan`` of the companion driven only by shock j, ``vmap``-ed
    over shocks.

    y: the panel `var` was estimated on (same row indexing as var.resid).
    """
    import jax.scipy.linalg as jsl

    y = jnp.asarray(y)
    ns = var.seps.shape[0]
    p = var.nlag
    finite = np.asarray(mask_of(var.resid).all(axis=1))
    rows = np.flatnonzero(finite)
    if rows.size == 0:
        raise ValueError("var has no usable residual rows")
    if not finite[rows[0] : rows[-1] + 1].all():
        raise ValueError("historical decomposition needs a contiguous window")
    t0 = int(rows[0])
    if t0 < p:
        raise ValueError("window start leaves no room for the initial lags")

    u = fillz(var.resid[rows])  # (Tu, ns) reduced-form residuals
    L = var.G[:ns, :]  # chol(seps): observation-space impact
    eps = jsl.solve_triangular(L, u.T, lower=True).T  # structural shocks

    # betahat layout depends on withconst: (1 + ns*p, ns) with const first,
    # or (ns*p, ns) without — reading row 0 as the const in the latter case
    # would silently break the reconstruction identity
    if var.betahat.shape[0] == 1 + ns * p:
        const = var.betahat[0]
    elif var.betahat.shape[0] == ns * p:
        const = jnp.zeros(ns, dtype=y.dtype)
    else:
        raise ValueError(
            f"betahat shape {var.betahat.shape} inconsistent with "
            f"ns={ns}, nlag={p}"
        )
    c_vec = jnp.zeros(ns * p, dtype=y.dtype).at[:ns].set(const)
    z0 = jnp.concatenate([y[t0 - 1 - i] for i in range(p)])  # most recent first

    def base_step(z, _):
        z_n = var.M @ z + c_vec
        return z_n, var.Q @ z_n

    _, baseline = jax.lax.scan(base_step, z0, None, length=rows.size)

    def one_shock(g_col, eps_col):
        def step(z, e_t):
            z_n = var.M @ z + g_col * e_t
            return z_n, var.Q @ z_n

        _, contrib = jax.lax.scan(step, jnp.zeros_like(z0), eps_col)
        return contrib  # (Tu, ns)

    contribs = jax.vmap(one_shock, in_axes=(1, 1), out_axes=2)(var.G, eps)
    return HistoricalDecomposition(contribs, baseline, eps, rows)


# ---------------------------------------------------------------------------
# lag-order selection, generalized IRFs, Granger causality (beyond reference)
# ---------------------------------------------------------------------------


class VARLagSelection(NamedTuple):
    aic: np.ndarray  # (max_lag,) criterion values for p = 1..max_lag
    bic: np.ndarray
    hq: np.ndarray
    best: dict  # {"aic": p, "bic": p, "hq": p}


def select_var_lag(
    y,
    max_lag: int,
    initperiod: int = 0,
    lastperiod: int | None = None,
    withconst: bool = True,
) -> VARLagSelection:
    """VAR lag-order selection by AIC / BIC (Schwarz) / Hannan-Quinn.

    All candidate orders are fit on the SAME effective sample — the rows a
    VAR(max_lag) can use, intersected across orders, so the criteria stay
    comparable even when missing values knock out different rows per order.
    Criteria use the ML innovation covariance (no dof correction):

        IC(p) = log|Sigma_p| + penalty(T) * k_p / T,   k_p = ns(ns p + const)

    with penalty 2 (AIC), log T (BIC), 2 log log T (HQ).
    """
    y = jnp.asarray(y)
    if lastperiod is None:
        lastperiod = y.shape[0] - 1
    if max_lag < 1:
        raise ValueError(f"max_lag must be >= 1, got {max_lag}")
    yw = y[initperiod : lastperiod + 1]
    ns = yw.shape[1]
    # common sample: rows whose max_lag-deep lag window is fully observed
    xlag_max = lagmat(yw, range(1, max_lag + 1))
    w_common = mask_of(yw).all(axis=1) & mask_of(xlag_max).all(axis=1)
    T_eff = float(w_common.sum())
    vals = {"aic": [], "bic": [], "hq": []}
    for p in range(1, max_lag + 1):
        _, ehat, _, _, _, _, T_used, _ = _estimate_var_window(
            yw, p, withconst, False, row_mask=w_common
        )
        if float(T_used) != T_eff:  # the common-sample guarantee
            raise RuntimeError(
                f"lag-selection invariant violated: VAR({p}) used "
                f"{float(T_used):g} rows, common sample has {T_eff:g}"
            )
        e0 = jnp.where(w_common[:, None], fillz(ehat), 0.0)
        sigma_ml = np.asarray(e0.T @ e0) / T_eff
        logdet = float(np.linalg.slogdet(sigma_ml)[1])
        k = ns * (ns * p + int(withconst))
        vals["aic"].append(logdet + 2.0 * k / T_eff)
        vals["bic"].append(logdet + np.log(T_eff) * k / T_eff)
        vals["hq"].append(logdet + 2.0 * np.log(np.log(T_eff)) * k / T_eff)
    arrs = {c: np.asarray(v) for c, v in vals.items()}
    best = {c: int(np.argmin(a)) + 1 for c, a in arrs.items()}
    return VARLagSelection(arrs["aic"], arrs["bic"], arrs["hq"], best)


def generalized_irf(var: VARResults, T: int) -> jnp.ndarray:
    """Generalized IRFs (Koop-Pesaran-Potter 1996 / Pesaran-Shin 1998):
    order-invariant responses to a one-standard-deviation shock in each
    variable,

        GIRF_j(h) = Phi_h Sigma e_j / sqrt(sigma_jj),

    i.e. the impact column is the j-th column of Sigma scaled by its own
    standard deviation (conditional-expectation shock under joint
    normality), instead of the Cholesky column.  For the FIRST variable the
    GIRF equals the recursive IRF with that variable ordered first; for
    diagonal Sigma every GIRF equals the corresponding Cholesky IRF.

    Returns (ns, T, nshock) like `impulse_response(var, "all", T)`.
    """
    ns = var.seps.shape[0]
    k = var.M.shape[0]
    sd = jnp.sqrt(jnp.diagonal(var.seps))
    impact = var.seps / sd[None, :]  # column j = Sigma e_j / sqrt(sigma_jj)
    G_gen = jnp.zeros((k, ns), dtype=impact.dtype).at[:ns, :].set(impact)
    return _irf_all(var.M, var.Q, G_gen, T)


class GrangerCausality(NamedTuple):
    wald: float  # Wald statistic
    df: int
    pvalue: float
    caused: tuple
    causing: tuple


def granger_causality(
    y,
    caused,
    causing,
    nlag: int,
    initperiod: int = 0,
    lastperiod: int | None = None,
) -> GrangerCausality:
    """Block Granger-causality Wald test: H0 = all lag coefficients of the
    `causing` variables are zero in the `caused` equations.

    Classical (homoskedastic) covariance Var(vec B) = Sigma x (X'X)^{-1},
    chi-square reference with df = nlag * |causing| * |caused| (the
    standard textbook VAR test, e.g. Luetkepohl 2005 sec. 3.6; a
    HAC-robust single-equation variant is `ops.hac.regress_hac`).

    Sigma is the dof-corrected innovation covariance e'e/(T - K) that
    `estimate_var` reports — a deliberate choice: the statistic is
    (T - K)/T times the ML-covariance textbook version, i.e. slightly
    conservative in small samples, and agrees asymptotically.  This keeps
    one Sigma convention across the VAR layer (reference
    dfm_functions.ipynb cell 23 uses the same dof correction).
    """
    from jax.scipy.special import gammaincc

    y = jnp.asarray(y)
    caused = tuple(np.atleast_1d(caused).tolist())
    causing = tuple(np.atleast_1d(causing).tolist())
    ns = y.shape[1]
    for j in caused + causing:
        if not 0 <= j < ns:
            raise ValueError(f"variable index {j} out of range for ns={ns}")
    if set(caused) & set(causing):
        raise ValueError("caused and causing must be disjoint")
    if lastperiod is None:
        lastperiod = y.shape[0] - 1

    yw = y[initperiod : lastperiod + 1]
    betahat, _, sigma_j, _, _, _, _, XtX = _estimate_var_window(
        yw, nlag, True, False
    )
    sigma = np.asarray(sigma_j)

    # restriction rows: coefficient (1 + lag*ns + causing_var) in each
    # caused equation
    rows = np.asarray(
        [1 + lag * ns + j for lag in range(nlag) for j in causing]
    )
    XtX_inv = np.linalg.inv(np.asarray(XtX))
    b_r = np.asarray(betahat)[np.ix_(rows, list(caused))]  # (nr, nc)
    # Var(vec of the restricted block) = Sigma[caused,caused] x XtX_inv[rows,rows]
    V = np.kron(
        sigma[np.ix_(list(caused), list(caused))], XtX_inv[np.ix_(rows, rows)]
    )
    theta = b_r.T.reshape(-1)  # vec by equation (matches the kron order)
    wald = float(theta @ np.linalg.solve(V, theta))
    df = len(rows) * len(caused)
    # survival function directly (1 - gammainc cancels to 0.0 for large Wald)
    pvalue = float(gammaincc(df / 2.0, wald / 2.0))
    return GrangerCausality(wald, df, pvalue, caused, causing)
