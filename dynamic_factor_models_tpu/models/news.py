"""Nowcast news: attribute a nowcast revision to individual data releases.

New capability (Banbura-Modugno 2014 section 5 tradition; the reference has
no forecasting at all, SURVEY.md section 0): when a data vintage arrives,
the change in the model nowcast decomposes into the contributions of the
newly released observations.  This is THE operational diagnostic of
production nowcasting systems ("today's IP release moved the GDP nowcast by
+0.1").

Design: releases are added to the information set one at a time; each step's
nowcast change is that release's news.  For a linear-Gaussian state space
each step is an exact conditional-expectation update, so the contributions
telescope exactly to the total revision (pinned by test); individual
contributions depend on the chosen ordering when releases are correlated
(the classic sequential-orthogonalization caveat — the default order is the
order given, i.e. release order).  All K+1 information sets share one panel
shape and differ only in their masks, so the whole decomposition is ONE
``vmap``-ed masked-smoother run over a stack of cumulative masks.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from functools import partial

from ..ops.masking import fillz, mask_of
from ..utils.backend import on_backend
from .ssm import (
    LARGE_N_THRESHOLD,
    SSMParams,
    _collapse_obs,
    _filter_scan,
    _filter_scan_collapsed_stats,
    _psd_floor,
    _smoother_scan,
)

__all__ = [
    "NowcastNews",
    "NowcastNewsBatch",
    "nowcast_news",
    "nowcast_news_batch",
]


@partial(jax.jit, static_argnames=("t_tgt", "i_tgt"))
def _nowcast_paths(params: SSMParams, xz, masks, t_tgt: int, i_tgt: int):
    """Target nowcast under each stacked information set (module-level so
    repeat calls — one per data vintage in production — hit the jit cache
    instead of retracing a per-call closure)."""

    def nowcast_under(mask_k):
        filt = _filter_scan(params, xz * mask_k.astype(xz.dtype), mask_k)
        sm, _, _ = _smoother_scan(params, filt)
        return params.lam[i_tgt] @ sm[t_tgt, : params.r]

    return jax.vmap(nowcast_under)(masks)


@jax.jit
def _nowcast_paths_multi(params: SSMParams, xz, masks, tgt_rows, tgt_cols):
    """Every target's nowcast under each stacked information set:
    (K+1, n_tgt).  The targets ride as TRACED gather indices (not the
    single-target version's static ints), so one compiled program serves
    every target set of the same size — the scenario engine's batched
    news kernel.  The smoother stack is shared across targets: n_tgt
    extra nowcasts cost two gathers and a contraction, not n_tgt
    smoother runs."""

    def nowcast_under(mask_k):
        filt = _filter_scan(params, xz * mask_k.astype(xz.dtype), mask_k)
        sm, _, _ = _smoother_scan(params, filt)
        f_t = sm[tgt_rows, : params.r]  # (n_tgt, r)
        return jnp.einsum("kr,kr->k", params.lam[tgt_cols], f_t)

    return jax.vmap(nowcast_under)(masks)


@jax.jit
def _nowcast_paths_multi_collapsed(
    params: SSMParams, xz, m_old_f, rel_t, rel_i, tgt_rows, tgt_cols
):
    """Collapsed news stack: the O(N) work is ONE base-vintage collapse —
    each of the K releases is a rank-1 increment to the collapsed
    statistics at its release time (dC = lam_i lam_i' / R_i, db =
    lam_i x_ti / R_i), so the K+1 information sets become a cumulative
    sum of r-sized stacks and the vmapped smoother never touches an
    N-sized operand.  Exact: adding one observed cell to a diagonal-R
    panel changes (C_t, b_t) by exactly that rank-1 term.

    The loglik-constant pieces (x'R^-1 x correction) are dropped
    (ll_corr = 0): nowcast means are independent of additive loglik
    constants.  Returns (K+1, n_tgt) nowcast paths."""
    r = params.r
    T = xz.shape[0]
    K = rel_t.shape[0]
    dt = xz.dtype
    C0, b0, ld0, _, no0 = _collapse_obs(params.lam, params.R, xz * m_old_f, m_old_f)

    lam_r = params.lam[rel_i]  # (K, r)
    rinv = 1.0 / params.R[rel_i]  # (K,)
    xv = xz[rel_t, rel_i]  # (K,)
    kk = jnp.arange(K)
    dC = jnp.zeros((K, T, r, r), dt).at[kk, rel_t].add(
        lam_r[:, :, None] * lam_r[:, None, :] * rinv[:, None, None]
    )
    db = jnp.zeros((K, T, r), dt).at[kk, rel_t].add(
        lam_r * (xv * rinv)[:, None]
    )
    dld = jnp.zeros((K, T), dt).at[kk, rel_t].add(jnp.log(params.R[rel_i]))
    dno = jnp.zeros((K, T), dt).at[kk, rel_t].add(1.0)

    def stack(base, d):
        z = jnp.zeros((1,) + d.shape[1:], dt)
        return base[None] + jnp.concatenate([z, jnp.cumsum(d, axis=0)], 0)

    Cs, bs, lds, nos = stack(C0, dC), stack(b0, db), stack(ld0, dld), stack(no0, dno)

    def nowcast_under(Ck, bk, ldk, nok):
        filt = _filter_scan_collapsed_stats(
            params, Ck, bk, ldk, nok, jnp.zeros((), dt)
        )
        sm, _, _ = _smoother_scan(params, filt)
        f_t = sm[tgt_rows, :r]  # (n_tgt, r)
        return jnp.einsum("kr,kr->k", params.lam[tgt_cols], f_t)

    return jax.vmap(nowcast_under)(Cs, bs, lds, nos)


def _validate_vintages(x_old, x_new):
    """Shared nested-vintage validation; returns (m_old, m_new) numpy
    masks.  Raises on shape mismatch, missing overlap observations, or
    revised (not purely released) values."""
    if x_old.shape != x_new.shape:
        raise ValueError(
            f"vintage shapes differ: {x_old.shape} vs {x_new.shape}"
        )
    m_old = np.asarray(mask_of(x_old))
    m_new = np.asarray(mask_of(x_new))
    if (m_old & ~m_new).any():
        raise ValueError(
            "x_new is missing observations present in x_old — vintages "
            "must be nested"
        )
    vals_match = np.asarray(
        jnp.where(mask_of(x_old), fillz(x_old) - fillz(x_new), 0.0)
    )
    if np.abs(vals_match).max() > 1e-10:
        raise ValueError(
            "overlapping observations differ between vintages; "
            "nowcast_news decomposes pure releases, not revisions to "
            "already-published values"
        )
    return m_old, m_new


def _cumulative_masks(m_old, rel):
    """K+1 stacked masks: info set 0 = old vintage, k = old + first k
    releases (host-side; the device sees one boolean stack)."""
    K = rel.shape[0]
    masks = np.repeat(m_old[None], K + 1, axis=0)
    for k in range(K):
        masks[k + 1 :, rel[k, 0], rel[k, 1]] = True
    return jnp.asarray(masks)


class NowcastNews(NamedTuple):
    total_revision: float  # nowcast(new vintage) - nowcast(old vintage)
    releases: np.ndarray  # (K, 2) [row, series] of each new observation
    news: jnp.ndarray  # (K,) per-release contribution (sums to total)
    nowcast_path: jnp.ndarray  # (K+1,) nowcast after 0..K releases
    old_nowcast: float
    new_nowcast: float


def nowcast_news(
    params: SSMParams,
    x_old,
    x_new,
    target: tuple[int, int],
    order=None,
    backend: str | None = None,
    collapsed: bool | None = None,
) -> NowcastNews:
    """Decompose the revision of the target nowcast between two vintages
    into per-release news contributions.

    x_old, x_new: (T, N) standardized panels (NaN missing); x_new must
    contain every observation of x_old plus the new releases.  `target` is
    the (row, series) entry being nowcast — typically (T-1, gdp_idx) with
    that entry missing in both vintages.  `order` optionally reorders the
    release sequence (default: row-major order of the new observations).

    `collapsed` (default None = auto for N > ssm.LARGE_N_THRESHOLD)
    replaces the K+1 masked-panel smoother runs with one base-vintage
    collapse plus rank-1 release increments — exact, and the device stack
    is r-sized instead of N-sized.

    The smoother conditional mean of the target entry is lam_i' E[f_t | Omega];
    contributions telescope exactly to `total_revision`.
    """
    with on_backend(backend):
        params = params._replace(Q=_psd_floor(params.Q))
        x_old = jnp.asarray(x_old)
        x_new = jnp.asarray(x_new)
        m_old, m_new = _validate_vintages(x_old, x_new)
        t_tgt, i_tgt = target
        if m_new[t_tgt, i_tgt]:
            raise ValueError(
                f"target entry {target} is observed in the new vintage — "
                "nothing to nowcast"
            )

        rel = np.argwhere(m_new & ~m_old)  # (K, 2) row-major
        if order is not None:
            order = np.asarray(order)
            if sorted(order.tolist()) != list(range(len(rel))):
                raise ValueError("order must be a permutation of the releases")
            rel = rel[order]

        xz = fillz(x_new)
        if collapsed is None:
            collapsed = x_new.shape[1] > LARGE_N_THRESHOLD
        if collapsed:
            path = _nowcast_paths_multi_collapsed(
                params, xz, jnp.asarray(m_old, xz.dtype),
                jnp.asarray(rel[:, 0]), jnp.asarray(rel[:, 1]),
                jnp.asarray([t_tgt]), jnp.asarray([i_tgt]),
            )[:, 0]
        else:
            masks_j = _cumulative_masks(m_old, rel)
            path = _nowcast_paths(params, xz, masks_j, int(t_tgt), int(i_tgt))
        news = jnp.diff(path)
        return NowcastNews(
            total_revision=float(path[-1] - path[0]),
            releases=rel,
            news=news,
            nowcast_path=path,
            old_nowcast=float(path[0]),
            new_nowcast=float(path[-1]),
        )


class NowcastNewsBatch(NamedTuple):
    """Batched news: one smoother-stack run, every target's decomposition.

    Per-target arrays carry the target axis LAST so `news[:, j]` is
    target j's per-release contributions (summing to
    `total_revision[j]`, the telescoping exactness of the scalar
    decomposition — pinned per target by test)."""

    targets: np.ndarray  # (n_tgt, 2) [row, series] per target
    total_revision: np.ndarray  # (n_tgt,)
    releases: np.ndarray  # (K, 2) shared release sequence
    news: jnp.ndarray  # (K, n_tgt)
    nowcast_path: jnp.ndarray  # (K+1, n_tgt)
    old_nowcast: np.ndarray  # (n_tgt,)
    new_nowcast: np.ndarray  # (n_tgt,)


def nowcast_news_batch(
    params: SSMParams,
    x_old,
    x_new,
    targets,
    order=None,
    backend: str | None = None,
    collapsed: bool | None = None,
) -> NowcastNewsBatch:
    """`nowcast_news` for MANY target entries at once (the scenario
    engine's batched decomposition): the K+1 masked-smoother runs are
    shared across targets — total device work is one vmapped smoother
    stack regardless of how many nowcasts are being attributed.

    `targets`: (n_tgt, 2) [row, series] entries, each missing in the new
    vintage.  Release sequencing (and its ordering caveat) is identical
    to the scalar entry point, as is the `collapsed` large-N routing
    (one base collapse + rank-1 release increments)."""
    with on_backend(backend):
        params = params._replace(Q=_psd_floor(params.Q))
        x_old = jnp.asarray(x_old)
        x_new = jnp.asarray(x_new)
        m_old, m_new = _validate_vintages(x_old, x_new)
        tgt = np.atleast_2d(np.asarray(targets, np.int64))
        if tgt.shape[1] != 2:
            raise ValueError(
                f"targets must be (n_tgt, 2) [row, series], got "
                f"{tgt.shape}"
            )
        observed = [tuple(t) for t in tgt if m_new[t[0], t[1]]]
        if observed:
            raise ValueError(
                f"target entries {observed} are observed in the new "
                "vintage — nothing to nowcast"
            )

        rel = np.argwhere(m_new & ~m_old)
        if order is not None:
            order = np.asarray(order)
            if sorted(order.tolist()) != list(range(len(rel))):
                raise ValueError("order must be a permutation of the releases")
            rel = rel[order]

        xz = fillz(x_new)
        if collapsed is None:
            collapsed = x_new.shape[1] > LARGE_N_THRESHOLD
        if collapsed:
            paths = _nowcast_paths_multi_collapsed(
                params, xz, jnp.asarray(m_old, xz.dtype),
                jnp.asarray(rel[:, 0]), jnp.asarray(rel[:, 1]),
                jnp.asarray(tgt[:, 0]), jnp.asarray(tgt[:, 1]),
            )  # (K+1, n_tgt)
        else:
            masks_j = _cumulative_masks(m_old, rel)
            paths = _nowcast_paths_multi(
                params, xz, masks_j,
                jnp.asarray(tgt[:, 0]), jnp.asarray(tgt[:, 1]),
            )  # (K+1, n_tgt)
        news = jnp.diff(paths, axis=0)
        p_np = np.asarray(paths)
        return NowcastNewsBatch(
            targets=tgt,
            total_revision=p_np[-1] - p_np[0],
            releases=rel,
            news=news,
            nowcast_path=paths,
            old_nowcast=p_np[0],
            new_nowcast=p_np[-1],
        )
