"""FAVAR impulse-response wild bootstrap: vmapped over replications, sharded
over the device mesh.

New capability (BASELINE.json config 3): the reference only provides the
point-estimate IRF machinery (dfm_functions.ipynb cells 42-43); the bootstrap
is specified by the north star — 1000 wild-bootstrap replications of the
factor-VAR IRFs, ``vmap``-ed and sharded across chips, < 10 s on a v5e-8.

Design: one replication = (resample residuals with Rademacher signs) ->
(rebuild y* by the VAR recursion, a ``lax.scan``) -> (re-estimate the VAR,
one dense solve) -> (IRFs, a ``lax.scan`` over horizon).  The replication
axis is embarrassingly parallel: the PRNG keys are sharded over the mesh's
"rep" axis and XLA partitions the whole vmapped program; the percentile
reduction at the end is the only cross-chip communication (an all-gather).
"""

from __future__ import annotations

from functools import lru_cache, partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
import jax.scipy.linalg as jsl
import numpy as np

from ..ops.lags import lagmat
from ..ops.linalg import solve_normal
from ..ops.masking import mask_of
from ..parallel.mesh import NamedSharding, P, make_mesh, rep_pad
from ..utils.backend import on_backend
from ..utils.compile import configure_compilation_cache, donation_enabled
from .var import VARResults, companion_matrices, estimate_var, impulse_response

__all__ = [
    "BootstrapIRFs",
    "ForecastFan",
    "SeriesFan",
    "SeriesIRFs",
    "block_bootstrap_irfs",
    "bootstrap_forecast_fan",
    "series_forecast_fan",
    "series_irfs",
    "wild_bootstrap_irfs",
    "wild_bootstrap_irfs_resumable",
]


class BootstrapIRFs(NamedTuple):
    point: jnp.ndarray  # (ns, H, nshock) point-estimate IRFs
    draws: jnp.ndarray  # (n_reps, ns, H, nshock)
    quantiles: jnp.ndarray  # (nq, ns, H, nshock)
    quantile_levels: np.ndarray
    # finite-replication accounting: nanquantile silently narrows the
    # effective sample when replications go non-finite (an exploding
    # resampled VAR), so the count rides along with the bands.  None only
    # on legacy constructions.
    n_finite: int | None = None  # replications with fully finite IRFs
    finite_fraction: float | None = None  # n_finite / n_reps


class SeriesIRFs(NamedTuple):
    """Per-series (observable-space) IRF bands: factor-system draws pushed
    through the loadings."""

    point: jnp.ndarray  # (nsel, H, nshock) loadings @ point IRFs
    quantiles: jnp.ndarray  # (nq, nsel, H, nshock)
    quantile_levels: np.ndarray


def _validate_series_idx(n_series: int, series_idx) -> np.ndarray:
    """Host-side bounds check: jnp gather clamps out-of-range indices
    silently, which would return the wrong series' band."""
    idx = np.asarray(series_idx)
    if idx.size and (idx.min() < -n_series or idx.max() >= n_series):
        raise IndexError(
            f"series_idx out of range for {n_series} series: "
            f"[{idx.min()}, {idx.max()}]"
        )
    return idx


def series_irfs(
    boot: BootstrapIRFs,
    lam,
    series_idx=None,
    scale=None,
    quantile_levels=None,
) -> SeriesIRFs:
    """Propagate bootstrap IRF uncertainty from the factor system to the
    observed series: every draw of the factor IRFs is contracted with the
    loadings (one vmapped ``lam @ irf`` einsum, sharded like the draws), and
    the bands are taken in series space — the actual FAVAR deliverable
    ("response of GDPC96 to shock 1 with a 5-95% band").

    Composition of the reference's `compute_series` (dfm_functions.ipynb
    cell 28: common component ``F lam_i'``) with its IRF machinery (cells
    42-43); the reference itself never propagates uncertainty at all.

    lam: (ns, r) loadings on the bootstrapped r-variable system — e.g.
    ``DFMResults.lam``, which is in original data units (the loading
    regression runs on raw series), so no rescaling is needed.  If the
    loadings are instead on a standardized panel, pass the per-series
    standard deviations as `scale`.  Quantiles are recomputed per series
    from the draws (a quantile does not commute with the contraction), so
    band coverage is exact in series space.
    """
    lam = jnp.asarray(lam)
    if scale is not None:
        scale = jnp.asarray(scale)
        if scale.shape[0] != lam.shape[0]:
            raise ValueError(
                f"scale has {scale.shape[0]} entries for {lam.shape[0]} series"
            )
    if series_idx is not None:
        idx = _validate_series_idx(lam.shape[0], series_idx)
        lam = lam[idx]
        if scale is not None:
            scale = scale[idx]
    if lam.shape[-1] != boot.point.shape[0]:
        raise ValueError(
            f"loadings have {lam.shape[-1]} factor columns; the bootstrap "
            f"system has {boot.point.shape[0]} variables"
        )
    if quantile_levels is None:
        quantile_levels = boot.quantile_levels

    point = jnp.einsum("nk,khj->nhj", lam, boot.point)
    draws = jnp.einsum("nk,dkhj->dnhj", lam, boot.draws)
    if scale is not None:
        s = jnp.asarray(scale)[:, None, None]
        point, draws = point * s, draws * s[None]
    q = jnp.nanquantile(draws, jnp.asarray(quantile_levels), axis=0)
    return SeriesIRFs(point, q, np.asarray(quantile_levels))


def _fit_dense_var(y, nlag: int, solver: str = "pinv"):
    """Dense (no-missing) VAR fit: returns betahat, resid, seps.

    solver="pinv" (default) keeps the minimum-norm convention every
    estimation path uses.  solver="chol" is the bootstrap's per-replication
    fast path: a Cholesky solve of the ridged Gram — under vmap the pinv's
    batched eigendecomposition of the (1+ns*nlag)-square Gram is the
    single most accelerator-hostile op in the replication program (small
    batched eigh maps terribly onto the MXU), while batched triangular
    solves are nearly free.  A max-diagonal-relative ridge keeps the
    factorization clear of the f32 breakdown threshold on degenerate
    resamples, and the band quantiles are nan-aware so a pathological
    replication drops out instead of poisoning the band; the OUTER fit
    (the reported point IRF) always uses pinv, so switching the rep
    solver moves only Monte-Carlo band noise."""
    Tw = y.shape[0]
    x = jnp.hstack([jnp.ones((Tw, 1), y.dtype), lagmat(y, range(1, nlag + 1))])
    x = x[nlag:]
    yr = y[nlag:]
    A = x.T @ x
    if solver == "chol":
        k = A.shape[0]
        # ridge scaled by the LARGEST diagonal entry: f32 Cholesky breaks
        # down at ~eps_f32 * lambda_max(A), and lambda_max <= k * max(diag)
        # for PSD A, so 1e-5 * max(diag) clears the breakdown threshold
        # with margin on any eigenvalue spread (a mean-trace ridge does
        # not); the perturbation is ~1e-5 relative — invisible against
        # Monte-Carlo band noise
        ridge = 1e-5 * jnp.max(jnp.diagonal(A)) + 1e-30
        c, lo = jsl.cho_factor(A + ridge * jnp.eye(k, dtype=A.dtype))
        betahat = jsl.cho_solve((c, lo), x.T @ yr)
        # one iterative-refinement step against the UNRIDGED normal
        # equations: near-unit-root panels reach cond(A) ~ 1e3, where the
        # ridge alone biases beta by ~ridge*cond (~1%); refinement drops
        # that to O((ridge*cond)^2).  The unridged residual rhs - A beta
        # equals ridge*beta EXACTLY (since (A + ridge I) beta = rhs), so
        # the step is one extra triangular solve — no residual matmul, no
        # f32 cancellation
        betahat = betahat + ridge * jsl.cho_solve((c, lo), betahat)
    else:
        betahat = solve_normal(A, x.T @ yr)
    ehat = yr - x @ betahat
    seps = ehat.T @ ehat / (yr.shape[0] - x.shape[1])
    return betahat, ehat, seps


def _wild_recursion(y_init, betahat, eta, nlag: int) -> jnp.ndarray:
    """Rebuild a resampled panel y* by the VAR recursion: y_init (nlag, ns)
    seed rows, betahat (1+ns*nlag, ns) with const first, eta (T-nlag, ns)
    resampled residuals.  Shared by the FAVAR and proxy-SVAR wild bootstraps."""
    ns = y_init.shape[1]
    const = betahat[0]
    blocks = [betahat[1 + i * ns : 1 + (i + 1) * ns].T for i in range(nlag)]

    def recurse(lags, e_t):
        # lags: (nlag, ns), most recent first
        y_t = const + e_t
        for i in range(nlag):
            y_t = y_t + blocks[i] @ lags[i]
        return jnp.concatenate([y_t[None], lags[:-1]], axis=0), y_t

    # unroll: the per-step body is a couple of tiny matmuls, so loop
    # overhead dominates the T-step recursion on accelerators
    _, tail = jax.lax.scan(recurse, y_init[::-1], eta, unroll=4)
    return jnp.concatenate([y_init, tail], axis=0)


def _resample_wild(k, ehat):
    """Wild resampling: one Rademacher sign per period, shared across
    equations — preserves the cross-equation residual correlation."""
    signs = jax.random.rademacher(k, (ehat.shape[0],), dtype=ehat.dtype)
    return ehat * signs[:, None]


@lru_cache(maxsize=16)
def _block_resampler(block: int):
    """Moving-block resampler (Kuensch 1989 MBB): blocks of `block`
    consecutive residual rows, preserving the serial dependence the wild
    bootstrap's independent sign flips destroy.  Each slot is centered by
    its conditional expectation over the random start (Brueggemann-Jentsch-
    Trenkler): edge rows are undersampled by the sliding window, so the
    full-sample zero mean of OLS residuals is NOT enough to make the
    resampled innovations mean-zero.  Cached per block size so the jitted
    core's static arg keeps a stable identity across calls."""

    def resample(k, ehat):
        Te = ehat.shape[0]
        n_blocks = -(-Te // block)
        n_st = Te - block + 1
        starts = jax.random.randint(k, (n_blocks,), 0, n_st)
        idx = starts[:, None] + jnp.arange(block)[None, :]  # (n_blocks, block)
        # E*[draw at slot s] = mean of ehat[s : s + n_st]
        slot_means = jnp.stack(
            [ehat[s : s + n_st].mean(axis=0) for s in range(block)]
        )
        eta = ehat[idx] - slot_means[None, :, :]
        return eta.reshape(-1, ehat.shape[1])[:Te]

    return resample


@partial(jax.jit, static_argnames=("nlag", "horizon", "n_reps", "resample"))
def _bootstrap_core(yw, key, nlag: int, horizon: int, n_reps: int,
                    resample=_resample_wild):
    betahat, ehat, _ = _fit_dense_var(yw, nlag)
    y_init = yw[:nlag]

    def one_rep(k):
        ystar = _wild_recursion(y_init, betahat, resample(k, ehat), nlag)

        b_star, _, seps_star = _fit_dense_var(ystar, nlag, solver="chol")
        M, Q, G = companion_matrices(b_star, seps_star, nlag)

        def step(xv, _):
            return M @ xv, Q @ xv

        def one_shock(g):
            _, out = jax.lax.scan(step, g, None, length=horizon, unroll=4)
            return out.T

        return jax.vmap(one_shock, in_axes=1, out_axes=2)(G)

    keys = jax.random.split(key, n_reps)
    return jax.vmap(one_rep)(keys)


@lru_cache(maxsize=8)
def _sharded_core(out_sharding):
    """Jitted sharded bootstrap, cached per output sharding so repeat calls
    (and bench warm-up) hit the compile cache instead of re-wrapping."""
    return jax.jit(
        _bootstrap_core,
        static_argnames=("nlag", "horizon", "n_reps", "resample"),
        out_shardings=out_sharding,
    )


def _prepare_window(y, initperiod: int, lastperiod: int) -> jnp.ndarray:
    """Window [initperiod, lastperiod], leading all-NaN rows dropped; raises
    if what remains is not a contiguous complete block."""
    yw = jnp.asarray(y)[initperiod : lastperiod + 1]
    complete = np.asarray(mask_of(yw).all(axis=1))
    first = int(np.argmax(complete))
    if not complete[first:].all():
        raise ValueError(
            "bootstrap window must be contiguous and complete after the "
            "first observed row"
        )
    return yw[first:]


def _default_mesh(mesh):
    """All local devices on a 1-D "rep" mesh unless the caller chose one."""
    if mesh is None and len(jax.devices()) > 1:
        return make_mesh()
    return mesh


@partial(jax.jit, donate_argnums=(0,), static_argnames=("n",))
def _donated_slice(draws, n: int):
    """Slice the first n replications out of a padded draw batch, donating
    the padded buffer so XLA can free/reuse it immediately (rep bucketing
    can pad substantially; without donation both buffers coexist until GC).
    Only used when donation is supported (utils.compile.donation_enabled)."""
    return draws[:n]


def _slice_reps(draws, n_reps: int):
    if draws.shape[0] == n_reps:
        return draws
    if donation_enabled():
        return _donated_slice(draws, n_reps)
    return draws[:n_reps]


def _dispatch_reps(core_fn, sharded_factory, mesh, n_reps, args_before, args_after=()):
    """Shared pad-and-slice dispatch for every rep-vmapped core: round
    n_reps up to a device multiple (and a ``DFM_REP_BUCKET`` bucket
    multiple, so varying rep counts share one compiled executable), jit
    with a "rep" out-sharding when a mesh is given, slice back.
    `core_fn(*args_before, n_reps, *args_after)`.  `jax.random.split`
    prefix stability makes the slice exact."""
    if mesh is not None:
        n_padded = rep_pad(n_reps, mesh.devices.size)
        core = sharded_factory(NamedSharding(mesh, P("rep")))
        return _slice_reps(core(*args_before, n_padded, *args_after), n_reps)
    n_padded = rep_pad(n_reps, 1)
    return _slice_reps(core_fn(*args_before, n_padded, *args_after), n_reps)


def _run_core(yw, key, nlag, horizon, n_reps, mesh, resample=_resample_wild):
    """Dispatch one batch of replications, mesh-sharded when a mesh is given."""
    return _dispatch_reps(
        _bootstrap_core, _sharded_core, mesh, n_reps,
        (yw, key, nlag, horizon), (resample,),
    )


def _finite_rep_stats(draws, n_reps: int):
    """Count replications whose IRF draw is entirely finite; warn when the
    nanquantile bands rest on < 99% of the requested replications (the
    bands silently narrow their effective sample otherwise)."""
    import warnings

    n_finite = int(
        jnp.isfinite(draws).all(axis=tuple(range(1, draws.ndim))).sum()
    )
    frac = n_finite / n_reps if n_reps else 1.0
    if frac < 0.99:
        warnings.warn(
            f"bootstrap: only {n_finite}/{n_reps} replications produced "
            f"finite IRFs ({frac:.1%}); quantile bands are computed on the "
            "finite subset — consider more lags, a longer window, or "
            "checking the input panel for outliers",
            stacklevel=3,
        )
    return n_finite, frac


def _bootstrap_driver(
    y, nlag, initperiod, lastperiod, horizon, n_reps, seed,
    quantile_levels, mesh, backend, resample,
) -> BootstrapIRFs:
    """Shared bootstrap frame: window prep -> point IRFs -> mesh default ->
    vmapped replications (`resample` picks the scheme) -> quantiles."""
    from ..utils.telemetry import run_record, span

    configure_compilation_cache()
    with on_backend(backend), run_record(
        "bootstrap_irfs",
        config={
            "resample": getattr(resample, "__name__", repr(resample)),
            "nlag": nlag, "horizon": horizon, "n_reps": n_reps, "seed": seed,
        },
    ) as rec:
        # drop leading incomplete rows (factor windows start with NaN lags)
        yw = _prepare_window(y, initperiod, lastperiod)
        rec.set(shapes={
            "T": int(yw.shape[0]), "N": int(yw.shape[1]), "n_reps": n_reps,
        })

        var = estimate_var(yw, nlag, 0, yw.shape[0] - 1, withconst=True)
        point = impulse_response(var, "all", horizon)

        key = jax.random.PRNGKey(seed)
        mesh = _default_mesh(mesh)
        # the replication program is embarrassingly parallel: GSPMD shards the
        # vmapped body over the mesh's "rep" axis
        with span("bootstrap_core"):
            draws = _run_core(yw, key, nlag, horizon, n_reps, mesh, resample)

        q = jnp.nanquantile(draws, jnp.asarray(quantile_levels), axis=0)
        n_finite, frac = _finite_rep_stats(draws, n_reps)
        rec.set(
            n_iter=n_reps,
            converged=bool(frac >= 0.99),
            final_loglik=None,
            n_finite=n_finite,
            finite_fraction=round(frac, 6),
        )
        return BootstrapIRFs(
            point, draws, q, np.asarray(quantile_levels), n_finite, frac
        )


def wild_bootstrap_irfs(
    y,
    nlag: int,
    initperiod: int,
    lastperiod: int,
    horizon: int = 24,
    n_reps: int = 1000,
    seed: int = 0,
    quantile_levels=(0.05, 0.16, 0.5, 0.84, 0.95),
    mesh=None,
    backend: str | None = None,
) -> BootstrapIRFs:
    """1000-replication wild bootstrap of Cholesky-identified VAR IRFs.

    y: (T, ns) panel (e.g. estimated factors, or factors + observables for a
    FAVAR); the window [initperiod, lastperiod] must contain a contiguous
    complete block after dropping leading rows with missing lags.

    Replications are sharded over the mesh's "rep" axis (all devices by
    default); on TPU hardware the only cross-chip traffic is the final
    quantile all-gather.
    """
    return _bootstrap_driver(
        y, nlag, initperiod, lastperiod, horizon, n_reps, seed,
        quantile_levels, mesh, backend, _resample_wild,
    )


def wild_bootstrap_irfs_resumable(
    y,
    nlag: int,
    initperiod: int,
    lastperiod: int,
    checkpoint_path: str,
    horizon: int = 24,
    n_reps: int = 1000,
    chunk_reps: int = 100,
    seed: int = 0,
    quantile_levels=(0.05, 0.16, 0.5, 0.84, 0.95),
    mesh=None,
    backend: str | None = None,
) -> BootstrapIRFs:
    """Fault-tolerant bootstrap: checkpoints partial draws after every chunk.

    The failure-recovery subsystem the reference lacks (SURVEY.md section
    5.3): replications run in chunks of `chunk_reps`, each chunk keyed by
    ``fold_in(seed_key, chunk_index)`` so the draw stream is independent of
    where a run was interrupted; after each chunk the draws-so-far and the
    next chunk index are written to `checkpoint_path` (npz, atomic rename).
    Re-invoking with the same arguments resumes at the first incomplete
    chunk and returns results identical to an uninterrupted run.  A
    checkpoint whose spec (seed, chunking, model, window, horizon) or data
    fingerprint differs is discarded, never silently blended.
    """
    import hashlib
    import os
    import uuid

    from ..utils.telemetry import run_record, span

    with on_backend(backend), run_record(
        "wild_bootstrap_irfs_resumable",
        config={
            "nlag": nlag, "horizon": horizon, "n_reps": n_reps,
            "chunk_reps": chunk_reps, "seed": seed,
        },
    ) as rec:
        yw = _prepare_window(y, initperiod, lastperiod)
        rec.set(shapes={
            "T": int(yw.shape[0]), "N": int(yw.shape[1]), "n_reps": n_reps,
        })
        var = estimate_var(yw, nlag, 0, yw.shape[0] - 1, withconst=True)
        point = impulse_response(var, "all", horizon)
        mesh = _default_mesh(mesh)

        spec = np.asarray([seed, chunk_reps, nlag, initperiod, lastperiod, horizon])
        fingerprint = hashlib.sha1(
            np.ascontiguousarray(np.asarray(yw, np.float64)).tobytes()
        ).hexdigest()

        n_chunks = -(-n_reps // chunk_reps)
        start_chunk = 0
        done: list[np.ndarray] = []
        if os.path.exists(checkpoint_path):
            with np.load(checkpoint_path) as z:
                if (
                    "spec" in z
                    and np.array_equal(z["spec"], spec)
                    and str(z["fingerprint"]) == fingerprint
                ):
                    start_chunk = min(int(z["next_chunk"]), n_chunks)
                    done = list(z["draws"][:start_chunk])

        key = jax.random.PRNGKey(seed)
        rec.set(start_chunk=start_chunk, n_chunks=n_chunks)
        for c in range(start_chunk, n_chunks):
            with span("bootstrap_chunk"):
                draws_c = _run_core(
                    yw, jax.random.fold_in(key, c), nlag, horizon, chunk_reps, mesh
                )
            done.append(np.asarray(draws_c))
            # unique suffix: concurrent runs against the same checkpoint path
            # must not clobber each other's half-written temp file
            tmp = f"{checkpoint_path}.tmp.{os.getpid()}.{uuid.uuid4().hex[:8]}.npz"
            try:
                np.savez(
                    tmp,
                    draws=np.stack(done),
                    next_chunk=c + 1,
                    spec=spec,
                    fingerprint=fingerprint,
                )
                os.replace(tmp, checkpoint_path)
            except BaseException:
                try:
                    os.remove(tmp)
                except OSError:
                    pass
                raise

        draws = jnp.asarray(np.concatenate(done, axis=0)[:n_reps])
        q = jnp.nanquantile(draws, jnp.asarray(quantile_levels), axis=0)
        n_finite, frac = _finite_rep_stats(draws, n_reps)
        rec.set(
            n_iter=n_reps,
            converged=bool(frac >= 0.99),
            final_loglik=None,
            n_finite=n_finite,
            finite_fraction=round(frac, 6),
        )
        return BootstrapIRFs(
            point, draws, q, np.asarray(quantile_levels), n_finite, frac
        )


def block_bootstrap_irfs(
    y,
    nlag: int,
    initperiod: int,
    lastperiod: int,
    horizon: int = 24,
    n_reps: int = 1000,
    block: int = 8,
    seed: int = 0,
    quantile_levels=(0.05, 0.16, 0.5, 0.84, 0.95),
    mesh=None,
    backend: str | None = None,
) -> BootstrapIRFs:
    """Moving-block bootstrap of Cholesky-identified VAR IRFs.

    Complement to `wild_bootstrap_irfs`: the wild bootstrap is robust to
    heteroskedasticity but whitens residual serial dependence; resampling
    blocks of `block` consecutive residual rows preserves it (Kuensch 1989
    MBB).  Shares the vmapped/mesh-sharded replication driver — only the
    resampler differs.
    """
    with on_backend(backend):
        Te = _prepare_window(y, initperiod, lastperiod).shape[0] - nlag
    if not 1 <= block <= Te:
        raise ValueError(f"block={block} must be in [1, {Te}]")
    return _bootstrap_driver(
        y, nlag, initperiod, lastperiod, horizon, n_reps, seed,
        quantile_levels, mesh, backend, _block_resampler(int(block)),
    )


# ---------------------------------------------------------------------------
# bootstrap forecast fans (frequentist counterpart of bayes.posterior_forecast)
# ---------------------------------------------------------------------------


class ForecastFan(NamedTuple):
    point: jnp.ndarray  # (horizon, ns) deterministic iterated forecast
    draws: jnp.ndarray  # (n_reps, horizon, ns) parameter + shock draws
    quantiles: jnp.ndarray  # (nq, horizon, ns)
    quantile_levels: np.ndarray


@partial(jax.jit, static_argnames=("nlag", "horizon", "n_reps"))
def _fan_core(yw, key, nlag: int, horizon: int, n_reps: int):
    """One fan draw = refit on a wild-resampled panel (parameter
    uncertainty) + a forward simulation with wild-resampled future shocks
    from the refit residuals (shock uncertainty), seeded from the ACTUAL
    last nlag observations."""
    betahat, ehat, _ = _fit_dense_var(yw, nlag)
    y_init = yw[:nlag]
    y_last = yw[-nlag:]
    Te = ehat.shape[0]

    def one_rep(k):
        k1, k2, k3 = jax.random.split(k, 3)
        ystar = _wild_recursion(y_init, betahat, _resample_wild(k1, ehat), nlag)
        b_star, e_star, _ = _fit_dense_var(ystar, nlag, solver="chol")
        idx = jax.random.randint(k2, (horizon,), 0, Te)
        signs = jax.random.rademacher(k3, (horizon,), dtype=yw.dtype)
        e_fut = e_star[idx] * signs[:, None]
        return _wild_recursion(y_last, b_star, e_fut, nlag)[nlag:]

    keys = jax.random.split(key, n_reps)
    return jax.vmap(one_rep)(keys)


@lru_cache(maxsize=8)
def _sharded_fan_core(out_sharding):
    return jax.jit(
        _fan_core,
        static_argnames=("nlag", "horizon", "n_reps"),
        out_shardings=out_sharding,
    )


def bootstrap_forecast_fan(
    y,
    nlag: int,
    initperiod: int,
    lastperiod: int,
    horizon: int = 8,
    n_reps: int = 1000,
    seed: int = 0,
    quantile_levels=(0.05, 0.16, 0.5, 0.84, 0.95),
    mesh=None,
    backend: str | None = None,
) -> ForecastFan:
    """Bootstrap forecast fan ("fan chart") for a VAR system — e.g. the
    estimated factors: predictive bands carrying BOTH parameter uncertainty
    (each draw refits the VAR on a wild-resampled panel, exactly the
    `wild_bootstrap_irfs` scheme) and future-shock uncertainty (forward
    simulation with wild-resampled residuals).  The frequentist counterpart
    of `bayes.posterior_forecast`; replications shard over the mesh's
    "rep" axis like every other bootstrap here.

    The point path is the deterministic iterated forecast from the actual
    last `nlag` rows (identical to `forecast.forecast_factors` on the same
    VAR); the fan's median tracks it.
    """
    from ..utils.telemetry import run_record, span

    with on_backend(backend), run_record(
        "bootstrap_forecast_fan",
        config={
            "nlag": nlag, "horizon": horizon, "n_reps": n_reps, "seed": seed,
        },
    ) as rec:
        yw = _prepare_window(y, initperiod, lastperiod)
        rec.set(shapes={
            "T": int(yw.shape[0]), "N": int(yw.shape[1]), "n_reps": n_reps,
        })
        betahat, _, _ = _fit_dense_var(yw, nlag)
        point = _wild_recursion(
            yw[-nlag:], betahat,
            jnp.zeros((horizon, yw.shape[1]), yw.dtype), nlag,
        )[nlag:]

        key = jax.random.PRNGKey(seed)
        mesh = _default_mesh(mesh)
        with span("fan_core"):
            draws = _dispatch_reps(
                _fan_core, _sharded_fan_core, mesh, n_reps, (yw, key, nlag, horizon)
            )
        q = jnp.nanquantile(draws, jnp.asarray(quantile_levels), axis=0)
        rec.set(n_iter=n_reps, converged=True, final_loglik=None)
        return ForecastFan(point, draws, q, np.asarray(quantile_levels))


class SeriesFan(NamedTuple):
    """Per-series predictive fan (no shock axis, unlike SeriesIRFs)."""

    point: jnp.ndarray  # (nsel, horizon)
    quantiles: jnp.ndarray  # (nq, nsel, horizon)
    quantile_levels: np.ndarray


def series_forecast_fan(
    fan: ForecastFan,
    lam,
    const=None,
    series_idx=None,
    quantile_levels=None,
) -> SeriesFan:
    """Push a factor forecast fan through the loadings to per-series
    predictive bands: draws (d, h, r) @ lam' (+ const) -> (d, h, nsel),
    quantiles recomputed in series space.  `lam`/`const` in original data
    units (`DFMResults.lam`/`lam_const`) give original-unit fan charts.
    """
    lam = jnp.asarray(lam)
    if lam.shape[-1] != fan.point.shape[1]:
        raise ValueError(
            f"loadings have {lam.shape[-1]} factor columns; the fan system "
            f"has {fan.point.shape[1]} variables"
        )
    if const is None:
        c = jnp.zeros(lam.shape[0], lam.dtype)
    else:
        c = jnp.atleast_1d(jnp.asarray(const))
        if c.shape[0] == 1:
            c = jnp.broadcast_to(c, (lam.shape[0],))
        elif c.shape[0] != lam.shape[0]:
            raise ValueError(
                f"const has {c.shape[0]} entries for {lam.shape[0]} series"
            )
    if series_idx is not None:
        idx = _validate_series_idx(lam.shape[0], series_idx)
        lam, c = lam[idx], c[idx]
    if quantile_levels is None:
        quantile_levels = fan.quantile_levels

    point = fan.point @ lam.T + c[None, :]  # (h, nsel)
    draws = jnp.einsum("dhk,nk->dhn", fan.draws, lam) + c[None, None, :]
    q = jnp.nanquantile(draws, jnp.asarray(quantile_levels), axis=0)
    return SeriesFan(point.T, jnp.moveaxis(q, 2, 1), np.asarray(quantile_levels))
