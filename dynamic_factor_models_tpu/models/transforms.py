"""Composable EM transform stacks: one core step, orthogonal wrappers.

PRs 3-10 grew a cross-product of hand-enumerated EM kernel variants
(`em_loop_guarded@steady`, `em_loop_batched`, `em_loop_guarded@sharded`,
`em_step_ar_qd`, ...): every fast axis was its own kernel, every new axis
multiplied the enumeration in emloop.py and utils/compile.py, and no
panel could get two wins at once.  This module replaces the enumeration
with a tiny algebra — the effect-handler idea of NumPyro and BlackJAX's
kernel-composition API applied to EM: a `Stack` names a CORE step (which
model's E/M maps run) and an ordered tuple of `Transform`s (how the
step/carry are wrapped), and `resolve` maps the stack to the LITERAL
jitted step object plus its calling convention.

Two kinds of transform, two binding sites:

* STEP transforms — `collapse`, `steady_tail`, `shard` — change what one
  EM iteration computes around unchanged numerics: collapse reduces the
  (T, N) panel to q-dim sufficient statistics before the scan,
  steady_tail splits the time axis at the convergence horizon t* (exact
  head scan, constant-gain tail with closed-form tail moments), shard
  runs the collapse's pre-scan GEMMs shard-local under shard_map with
  one ring all-reduce.  `resolve` maps (core, step transforms) to a step.
* LOOP transforms — `guard`, `batch`, `donate`, `accel` — change how the
  convergence loop drives any step: the guarded while-loop's sentinel +
  rollback rungs, the vmapped per-lane carry, carry donation, SQUAREM
  cycling.  They are step-agnostic by construction (models/emloop.py,
  models/emaccel.py) and `resolve` records them as loop policy.

Composition ORDER is part of the algebra and not arbitrary (see
docs/ARCHITECTURE.md):

* guard wraps batch wraps (accel wraps) the step: the health sentinel
  must see the loop carry each lane actually iterates, so it lives in
  the loop body OUTSIDE the vmapped step — guarding inside a lane would
  roll back one lane's params mid-vmap and desynchronize the carry.
* shard wraps the COLLAPSE'S PRE-SCAN, not the whole step: every
  collapsed statistic is a sum over series, so the only cross-shard
  communication an EM iteration needs is one all-reduce of the packed
  payload; the N-free scan then runs replicated and the per-series
  M-step stays shard-local.  Sharding outside collapse (whole-step SPMD)
  would all-reduce O(T k^2) filter state per scan step instead.
* steady_tail splits INSIDE collapse: the head scan consumes the same
  per-step collapsed statistics the plain scan would, the tail replaces
  them with their per-series-constant limit — so steady x shard composes
  by reducing the split payload exactly like the unsplit one.

`resolve` returns the SAME module-level jitted objects the hand-written
call sites always dispatched (ssm.em_step_stats, ssm._steady_step_for,
ssm._sharded_step_for, ssm_ar.em_step_ar_qd, ...), so every stack that
reproduces a pre-stack variant is HLO byte-identical by construction —
the PR 1-4/8 byte-identity pins define "no regression" and keep holding.
The previously-unreachable PRODUCTS resolve to models/emcore.py.

`enumerate_stacks(spec)` derives utils.compile's AOT kernel plan from
the same table (one entry per reachable stack x loop kind), replacing
the hand-enumerated plan bodies; tests/test_transform_stack.py pins the
derived registry against the frozen pre-stack kernel set.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import NamedTuple

__all__ = [
    "Stack",
    "Transform",
    "accel",
    "batch",
    "collapse",
    "donate",
    "enumerate_stacks",
    "guard",
    "PlanEntry",
    "resolve",
    "Resolved",
    "shard",
    "steady_tail",
    "time_shard",
    "unwrap_params",
    "wrap_params",
]

_STEP_KINDS = ("collapse", "steady", "shard", "time")
_LOOP_KINDS = ("guard", "batch", "donate", "accel")

CORES = (
    "ssm",
    "ssm.legacy",
    "ssm.assoc",
    "ssm.sqrt",
    "ssm.sqrt_collapsed",
    "ar",
    "mf",
)


@dataclass(frozen=True)
class Transform:
    """One wrapper in a stack: a kind tag plus its static parameters
    (hashable, so stacks can key caches and registry entries)."""

    kind: str
    args: tuple = ()


@dataclass(frozen=True)
class Stack:
    """A core step name plus the transforms wrapped around it, outermost
    last: Stack("ar", (collapse(), steady_tail(64), shard(8)))."""

    core: str
    transforms: tuple = field(default_factory=tuple)


def collapse() -> Transform:
    """Collapse the (T, N) observation panel to q-dim per-step sufficient
    statistics before the scan (Jungbacker-Koopman for the iid core,
    quasi-differenced for the AR core): the scan body becomes N-free."""
    return Transform("collapse")


def steady_tail(t_star: int, block: int = 0) -> Transform:
    """Split the time axis at the static convergence horizon `t_star`:
    exact scan on [0, t*), constant-gain recursion + closed-form tail
    moments on [t*, T).  `block` >= 2 selects the blocked (einsum) form
    of the tail recursions."""
    return Transform("steady", (int(t_star), int(block)))


def shard(n_shards: int, hosts: int = 0) -> Transform:
    """Run the collapse's pre-scan (T, N) GEMMs shard-local over the
    series data mesh, all-reducing the packed payload with the
    Pallas/psum ring; the N-free scan runs replicated, the per-series
    M-step shard-local.

    hosts=0 (the default) resolves to ``jax.process_count()`` at resolve
    time: a single-process runtime gets the flat ``("data",)`` mesh, a
    `jax.distributed`-initialized runtime the process-spanning
    ``("dcn", "ici")`` mesh with the hierarchical ICI-ring + DCN-psum
    reduction.  Pass hosts explicitly to force a topology (the tier-1
    multi-host proxy runs hosts=2 on one process)."""
    return Transform("shard", (int(n_shards), int(hosts)))


def time_shard(t_blocks: int) -> Transform:
    """Run the E-step scans PARALLEL IN TIME over `t_blocks` contiguous
    per-device time slabs (models/emtime): the collapsed per-step payload
    feeds fused O(r^3) scan elements (pkalman.filter_elements_collapsed),
    each slab runs the cheap sequential combine recursion locally, and
    only O(k^2) slab-boundary elements cross devices in the log-depth
    exclusive-prefix exchange (parallel/timescan.sharded_scan).  Composes
    with `shard` into the 3-D ("dcn", "time", "ici") mesh."""
    return Transform("time", (int(t_blocks),))


def batch(B: int) -> Transform:
    """vmap the step over B same-shape panels inside one device loop,
    with per-lane convergence scalars and health flags in the carry
    (models/emloop.run_em_loop_batched)."""
    return Transform("batch", (int(B),))


def guard(on: bool = True) -> Transform:
    """The numerical-health sentinel + rollback rungs folded into the
    convergence loop (utils/guards.py via the guarded while-loop)."""
    return Transform("guard", (bool(on),))


def donate() -> Transform:
    """Donate the loop carry to XLA (input-output buffer aliasing)."""
    return Transform("donate")


def accel(name: str = "squarem") -> Transform:
    """Wrap the step in an acceleration cycle (models/emaccel.squarem)."""
    return Transform("accel", (str(name),))


class Resolved(NamedTuple):
    """A stack resolved to its executable pieces.

    step       the literal module-level jitted step object
    core       the stack's core name
    arg_kind   step argument convention past the params/carry:
               "stats" (x, mask, PanelStats), "panel" (x, mask),
               "ar_panel" (x, mask), "qd" (x, QDStats),
               "qd_tail" (x, QDStats, QDTailStats)
    carry      what the loop iterates: "bare" params, "steady"
               (ssm.SteadyEMState), "ar_steady" (emcore.ARSteadyState)
    n_shards   data-mesh width (0 = unsharded)
    t_star     steady split point (None = no steady tail)
    block      steady tail block size
    batch      vmapped lane count (0 = scalar loop)
    guard      loop guard policy (None = env default DFM_GUARDS)
    donate     carry donation policy (None = env default)
    accel      acceleration name or None
    fallback_step  the exact step the guard ladder's demote rung targets
    hosts      mesh host count as requested by shard() (0 = resolve to
               jax.process_count(); >1 = process-spanning ("dcn", "ici")
               mesh with the hierarchical reduction)
    t_blocks   parallel-in-time slab count (0 = sequential scans; > 1 =
               blocked slabs over the mesh "time" axis, models/emtime)
    """

    step: object
    core: str
    arg_kind: str
    carry: str
    n_shards: int = 0
    t_star: int | None = None
    block: int = 0
    batch: int = 0
    guard: bool | None = None
    donate: bool | None = None
    accel: str | None = None
    fallback_step: object = None
    hosts: int = 0
    t_blocks: int = 0


def _split(stack: Stack):
    step_t: dict[str, Transform] = {}
    loop_t: dict[str, Transform] = {}
    for t in stack.transforms:
        if t.kind in _STEP_KINDS:
            dst = step_t
        elif t.kind in _LOOP_KINDS:
            dst = loop_t
        else:
            raise ValueError(f"unknown transform kind {t.kind!r}")
        if t.kind in dst:
            raise ValueError(f"duplicate {t.kind!r} transform in {stack}")
        dst[t.kind] = t
    return step_t, loop_t


def resolve(stack: Stack) -> Resolved:
    """Map a stack to its step object + calling convention.

    Imports lazily so this module stays import-cheap; every return value
    is the module-level jitted object the hand-written call sites used
    (byte-identical programs), or the emcore composed step for stacks no
    hand-written variant covered.
    """
    if stack.core not in CORES:
        raise ValueError(
            f"unknown core {stack.core!r}; expected one of {CORES}"
        )
    step_t, loop_t = _split(stack)
    axes = frozenset(step_t)
    t_star, block = (
        step_t["steady"].args if "steady" in step_t else (None, 0)
    )
    sargs = step_t["shard"].args if "shard" in step_t else (0,)
    n_shards = sargs[0]
    hosts = sargs[1] if len(sargs) > 1 else 0
    t_blocks = step_t["time"].args[0] if "time" in step_t else 0
    kw = dict(
        n_shards=n_shards,
        hosts=hosts,
        t_star=t_star,
        block=block,
        batch=loop_t["batch"].args[0] if "batch" in loop_t else 0,
        guard=loop_t["guard"].args[0] if "guard" in loop_t else None,
        donate=True if "donate" in loop_t else None,
        accel=loop_t["accel"].args[0] if "accel" in loop_t else None,
        t_blocks=t_blocks,
    )
    if t_blocks:
        if t_blocks <= 1:
            raise ValueError(
                f"time_shard needs t_blocks > 1, got {t_blocks}"
            )
        if kw["batch"] > 0:
            raise ValueError(
                "time_shard x batch is not composable: each vmapped lane "
                "would need its own time mesh — run batched panels with "
                "sequential scans, or one panel time-sharded"
            )
        if t_star is not None:
            raise ValueError(
                "time_shard x steady_tail is not composable: the "
                "constant-gain tail is already O(1) in T, so there is "
                "nothing left for the slab scan to split — pick one"
            )

    if stack.core == "ssm":
        from . import ssm

        # em_step_stats already collapses inside its scan and the steady
        # and sharded steps collapse by construction, so `collapse` is
        # implied by `steady`/`shard` and only selects the explicit
        # payload pipeline (emcore.em_step_collapsed) when alone
        if axes <= {"collapse"}:
            if "collapse" in axes:
                from . import emcore

                return Resolved(
                    emcore.em_step_collapsed, "ssm", "stats", "bare",
                    fallback_step=ssm.em_step_stats, **kw,
                )
            return Resolved(ssm.em_step_stats, "ssm", "stats", "bare", **kw)
        if axes <= {"collapse", "steady"}:
            return Resolved(
                ssm._steady_step_for(t_star, block), "ssm", "stats",
                "steady", fallback_step=ssm.em_step_stats, **kw,
            )
        if axes <= {"collapse", "shard"}:
            return Resolved(
                ssm._sharded_step_for(n_shards, hosts), "ssm", "stats",
                "bare", fallback_step=ssm.em_step_stats, **kw,
            )
        if axes <= {"collapse", "time"}:
            from . import emtime

            return Resolved(
                emtime.em_step_tp_for(t_blocks), "ssm", "stats", "bare",
                fallback_step=ssm.em_step_stats, **kw,
            )
        if axes <= {"collapse", "time", "shard"}:
            from . import emtime

            return Resolved(
                emtime.em_step_tp_for(t_blocks, n_shards, hosts), "ssm",
                "stats", "bare", fallback_step=ssm.em_step_stats, **kw,
            )
        raise ValueError(
            "the iid core has no steady x shard product yet; compose "
            "steady and shard on the 'ar' core (ROADMAP item 2)"
        )

    if stack.core in (
        "ssm.legacy", "ssm.assoc", "ssm.sqrt", "ssm.sqrt_collapsed"
    ):
        from . import ssm

        if axes:
            raise ValueError(
                f"core {stack.core!r} accepts no step transforms "
                f"(got {sorted(axes)})"
            )
        step = {
            "ssm.legacy": ssm.em_step,
            "ssm.assoc": ssm.em_step_assoc,
            "ssm.sqrt": ssm.em_step_sqrt,
            "ssm.sqrt_collapsed": ssm.em_step_sqrt_collapsed,
        }[stack.core]
        # guard-ladder demotion target: the exact sequential filter on the
        # same (x, mask) args (the legacy core IS that filter)
        fb = None if stack.core == "ssm.legacy" else ssm.em_step
        return Resolved(
            step, stack.core, "panel", "bare", fallback_step=fb, **kw
        )

    if stack.core == "ar":
        from . import ssm_ar

        if not axes:
            return Resolved(
                ssm_ar.em_step_ar, "ar", "ar_panel", "bare", **kw
            )
        if "collapse" not in axes:
            raise ValueError(
                "the dense AR step has no collapsed statistics to split "
                "or shard; 'steady'/'shard'/'time' on the 'ar' core "
                "require 'collapse' first"
            )
        from . import emcore

        if axes == {"collapse"}:
            return Resolved(
                ssm_ar.em_step_ar_qd, "ar", "qd", "bare",
                fallback_step=ssm_ar.em_step_ar, **kw,
            )
        if axes == {"collapse", "time"}:
            from . import emtime

            return Resolved(
                emtime.em_step_ar_tp_for(t_blocks), "ar", "qd", "bare",
                fallback_step=ssm_ar.em_step_ar_qd, **kw,
            )
        if "time" in axes:
            raise ValueError(
                "the AR core's time_shard composes with 'collapse' only: "
                "its per-series M-step GEMMs are not sharded, so "
                "time x shard has no AR product yet — shard the iid core "
                "instead, or drop one axis"
            )
        if axes == {"collapse", "steady"}:
            return Resolved(
                emcore._ar_steady_step_for(t_star, block), "ar",
                "qd_tail", "ar_steady",
                fallback_step=ssm_ar.em_step_ar_qd, **kw,
            )
        if axes == {"collapse", "shard"}:
            return Resolved(
                emcore._ar_sharded_step_for(n_shards, hosts), "ar", "qd",
                "bare", fallback_step=ssm_ar.em_step_ar_qd, **kw,
            )
        # all three speed axes on one panel
        return Resolved(
            emcore._ar_steady_sharded_step_for(t_star, block, n_shards, hosts),
            "ar", "qd_tail", "ar_steady",
            fallback_step=ssm_ar.em_step_ar_qd, **kw,
        )

    # stack.core == "mf"
    from . import mixed_freq

    if axes == {"shard"}:
        # the MF step collapses through H5 inside its own scan (collapse
        # is implied), so shard is the one extra axis it composes with:
        # per-series E-step terms stay independent sums even through the
        # Mariano-Murasawa aggregation rows
        return Resolved(
            mixed_freq._mf_sharded_step_for(n_shards, hosts), "mf",
            "stats", "bare", fallback_step=mixed_freq.em_step_mf_stats,
            **kw,
        )
    if axes:
        raise ValueError(
            "the mixed-frequency core supports no step transforms other "
            "than 'shard': it already collapses through H5 inside its "
            "scan (an explicit 'collapse' would be a no-op), and the "
            "period-3 quarterly mask cycle has no single steady horizon "
            "for 'steady' to split at"
        )
    return Resolved(
        mixed_freq.em_step_mf_stats, "mf", "stats", "bare", **kw
    )


def wrap_params(res: Resolved, params):
    """Wrap bare parameters into the carry `res.step` iterates."""
    import jax.numpy as jnp

    if res.carry == "bare":
        return params
    if res.carry == "steady":
        from .ssm import SteadyEMState

        k = params.r * params.p
        return SteadyEMState(
            params=params,
            Pp=jnp.zeros((k, k), params.lam.dtype),
            riccati_iters=jnp.asarray(0, jnp.int32),
        )
    if res.carry == "ar_steady":
        from .emcore import ARSteadyState

        k = params.r * max(params.p, 2)
        return ARSteadyState(
            params=params,
            Pp=jnp.zeros((k, k), params.lam.dtype),
            riccati_iters=jnp.asarray(0, jnp.int32),
        )
    raise ValueError(f"unknown carry kind {res.carry!r}")


def unwrap_params(res: Resolved, state):
    """Peel the loop carry back to bare parameters (inverse of
    `wrap_params` up to the warm-started steady fields)."""
    return state if res.carry == "bare" else state.params


class PlanEntry(NamedTuple):
    """One derived AOT-plan entry: the registry key utils.compile uses
    (``@variant`` suffixes distinguish statics under one kernel name),
    the stack it resolves, and the loop kind wrapped around it (None =
    register the bare step, "plain"/"guarded"/"batched" = the matching
    emloop while-loop program)."""

    key: str
    stack: Stack
    loop: str | None = None


def enumerate_stacks(spec) -> list:
    """Derive the EM-family AOT kernel plan from a CompileSpec.

    Every entry is a (key, stack, loop) triple; utils.compile._kernel_plan
    builds avals/statics/warmup inputs generically from the resolved
    stack, so adding a stack here is ALL it takes to make it
    precompilable — there is no hand-written plan body per kernel left.

    Keys, gating, and statics reproduce the pre-stack hand enumeration
    exactly for the historical kernel names (the frozen set
    tests/test_transform_stack.py pins); the composed emcore kernels are
    opt-in by name so existing specs compile the same set as before.
    """
    ks = spec.kernels
    st = (
        (steady_tail(spec.t_star, spec.steady_block),)
        if spec.t_star is not None
        else None
    )
    sh = (
        (shard(spec.n_shards, getattr(spec, "mesh_hosts", 0)),)
        if spec.n_shards > 1
        else None
    )
    tp = (
        (time_shard(spec.t_blocks),)
        if getattr(spec, "t_blocks", 0) > 1
        else None
    )
    entries: list[PlanEntry] = []
    add = entries.append

    if "em_step_stats" in ks:
        add(PlanEntry("em_step_stats", Stack("ssm")))
    for key, core in (
        ("em_step", "ssm.legacy"),
        ("em_step_sqrt", "ssm.sqrt"),
        ("em_step_sqrt_collapsed", "ssm.sqrt_collapsed"),
    ):
        if key in ks:
            add(PlanEntry(key, Stack(core)))
    if "em_step_collapsed" in ks:
        add(PlanEntry("em_step_collapsed", Stack("ssm", (collapse(),))))
    if st is not None:
        if "em_step_steady" in ks:
            add(PlanEntry("em_step_steady", Stack("ssm", st)))
        if "em_loop@steady" in ks:
            add(PlanEntry("em_loop@steady", Stack("ssm", st), "plain"))
        if "em_loop_guarded@steady" in ks:
            add(
                PlanEntry(
                    "em_loop_guarded@steady", Stack("ssm", st), "guarded"
                )
            )
    if "em_step_ar" in ks:
        add(PlanEntry("em_step_ar", Stack("ar")))
    if "em_step_ar_qd" in ks:
        add(PlanEntry("em_step_ar_qd", Stack("ar", (collapse(),))))
    if st is not None and "em_step_ar_steady" in ks:
        add(
            PlanEntry(
                "em_step_ar_steady", Stack("ar", (collapse(),) + st)
            )
        )
    if sh is not None and "em_step_ar_sharded" in ks:
        add(
            PlanEntry(
                "em_step_ar_sharded", Stack("ar", (collapse(),) + sh)
            )
        )
    if st is not None and sh is not None and "em_step_ar_all" in ks:
        add(
            PlanEntry(
                "em_step_ar_all", Stack("ar", (collapse(),) + st + sh)
            )
        )
    if "em_loop" in ks:
        add(PlanEntry("em_loop", Stack("ssm"), "plain"))
    if "em_loop_guarded" in ks:
        add(PlanEntry("em_loop_guarded", Stack("ssm"), "guarded"))
    if sh is not None:
        if "em_step_sharded" in ks:
            add(PlanEntry("em_step_sharded", Stack("ssm", sh)))
        if "em_step_mf_sharded" in ks:
            add(PlanEntry("em_step_mf_sharded", Stack("mf", sh)))
        if "em_loop_guarded@sharded" in ks:
            add(
                PlanEntry(
                    "em_loop_guarded@sharded", Stack("ssm", sh), "guarded"
                )
            )
    if tp is not None:
        # parallel-in-time entries are opt-in by name, like the composed
        # emcore kernels, so existing specs compile the same set as before
        if "em_step_tp" in ks:
            add(PlanEntry("em_step_tp", Stack("ssm", tp)))
        if "em_step_ar_tp" in ks:
            add(PlanEntry("em_step_ar_tp", Stack("ar", (collapse(),) + tp)))
        if sh is not None and "em_step_tp_sharded" in ks:
            add(PlanEntry("em_step_tp_sharded", Stack("ssm", tp + sh)))
    if spec.em_batch > 0:
        add(
            PlanEntry(
                "em_loop_batched",
                Stack("ssm", (batch(spec.em_batch),)),
                "batched",
            )
        )
    return entries


class SMCPlanEntry(NamedTuple):
    """One derived SMC AOT-plan entry: the ``smc_filter@<model>``
    registry key, the particle-model name, and the particle count.
    `scenarios/smc.aot_plan` builds the avals/statics/warmup generically
    from the entry, so — like the EM stacks above — adding a model here
    is ALL it takes to precompile it."""

    key: str
    model: str
    particles: int


# the particle models with a data-free plan: "tvp" is excluded because
# its aux carries a panel-length factor path (the plan would key on a
# run's data, not its shape), so tvp requests warm through the jit cache
SMC_AOT_MODELS = ("lg", "sv", "msdfm")


def enumerate_smc(spec) -> list:
    """Derive the SMC-family AOT kernel plan from a CompileSpec: one
    ``smc_filter@<model>`` entry per AOT-able particle model, gated on
    ``particle_count > 0`` so existing specs register nothing new (the
    kernel-count pin in tests/test_perf_regression.py holds the line)."""
    P = getattr(spec, "particle_count", 0)
    if P <= 0:
        return []
    return [
        SMCPlanEntry(f"smc_filter@{m}", m, int(P)) for m in SMC_AOT_MODELS
    ]
