"""Parallel-in-time Kalman filter/smoother via associative scans.

Temporal parallelization of the masked state-space DFM filter (models/ssm.py)
following Sarkka & Garcia-Fernandez (2020), "Temporal Parallelization of
Bayesian Smoothers" (IEEE TAC 66(1)) — the sequence-parallelism story of this
framework: the O(T) sequential `lax.scan` recursion becomes an
O(log T)-depth ``jax.lax.associative_scan`` whose per-step elements are
independent, so XLA can spread the time axis over the MXU *and*, combined
with `parallel.timescan.sharded_scan`, over the chips of a mesh (time-block
sharding with a single all-gather of per-block prefixes — the DFM analogue of
ring/sequence parallelism for long contexts).

The reference has no state-space code at all (SURVEY.md section 0: the
`Parametric` method is declared in dfm_functions.ipynb cell 1:3 and never
implemented), so both the sequential and this parallel formulation are new
capability; they agree to float tolerance (tests/test_pkalman.py).

Masked-panel adaptation: with observation model x_t = Lam f_t + eps,
eps ~ N(0, diag(R)), and missing entries encoded as zero rows of the masked
loading  Lam_t = m_t * Lam, every element of the parallel filter reduces to
r-dimensional algebra through the Woodbury identity — per-element cost
O(N r + r^3 + k^2 r) with k = r*p, never O(N^3) or O(k^3) in the element
construction (the associative combine itself is O(k^3), same as one
sequential step).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from .ssm import KalmanResult, SSMParams, _companion, _init_state

__all__ = [
    "FilterElement",
    "SmootherElement",
    "filter_elements",
    "filter_elements_collapsed",
    "combine_filter",
    "combine_smoother",
    "kalman_filter_associative",
    "kalman_filter_associative_collapsed",
    "kalman_smoother_associative",
    "kalman_smoother_associative_collapsed",
]


class FilterElement(NamedTuple):
    """One conditional-Gaussian element (A, b, C, eta, J) of the parallel
    filter: p(s_t | s_{t-1}, y_t) ~ N(A s_{t-1} + b, C) with information
    pair (eta, J) flowing backward (Sarkka-GF lemma 7)."""

    A: jnp.ndarray  # (k, k)
    b: jnp.ndarray  # (k,)
    C: jnp.ndarray  # (k, k)
    eta: jnp.ndarray  # (k,)
    J: jnp.ndarray  # (k, k)


class SmootherElement(NamedTuple):
    """Backward element (E, g, L): p(s_t | s_{t+1}, y_{1:t}) ~
    N(E s_{t+1} + g, L) (Sarkka-GF lemma 9)."""

    E: jnp.ndarray  # (k, k)
    g: jnp.ndarray  # (k,)
    L: jnp.ndarray  # (k, k)


def _mT(a):
    return jnp.swapaxes(a, -1, -2)


def _mv(M, v):
    return (M @ v[..., None])[..., 0]


def combine_filter(e1: FilterElement, e2: FilterElement) -> FilterElement:
    """Associative combine, e1 the earlier block (Sarkka-GF lemma 8).

    Batch-aware over leading dims (``lax.associative_scan`` calls the combine
    on time-sliced stacks of elements).
    """
    k = e1.A.shape[-1]
    eye = jnp.eye(k, dtype=e1.A.dtype)
    # D = A2 (I + C1 J2)^{-1}
    D = _mT(jnp.linalg.solve(_mT(eye + e1.C @ e2.J), _mT(e2.A)))
    A = D @ e1.A
    b = _mv(D, e1.b + _mv(e1.C, e2.eta)) + e2.b
    C = D @ e1.C @ _mT(e2.A) + e2.C
    # E = A1' (I + J2 C1)^{-1}
    E = _mT(jnp.linalg.solve(_mT(eye + e2.J @ e1.C), e1.A))
    eta = _mv(E, e2.eta - _mv(e2.J, e1.b)) + e1.eta
    J = E @ e2.J @ e1.A + e1.J
    return FilterElement(A, b, 0.5 * (C + _mT(C)), eta, 0.5 * (J + _mT(J)))


def combine_smoother(e1: SmootherElement, e2: SmootherElement) -> SmootherElement:
    """Associative combine for the backward pass, e1 the earlier block
    (batch-aware)."""
    E = e1.E @ e2.E
    g = _mv(e1.E, e2.g) + e1.g
    L = e1.E @ e2.L @ _mT(e1.E) + e1.L
    return SmootherElement(E, g, 0.5 * (L + _mT(L)))


def _generic_elements(params: SSMParams, x, m):
    """Elements for t >= 2 (predictive covariance = Qs), batched over time.

    All observation-space algebra collapses to r x r through Woodbury:
    with Zr = Lam' diag(m/R) Lam and w = Lam' (m/R * x),
        Lam_m' S^{-1} Lam_m = (I + Zr Q)^{-1} Zr,
        Lam_m' S^{-1} x     = (I + Zr Q)^{-1} w.
    """
    Tm, _ = _companion(params)
    r = params.r
    k = Tm.shape[0]
    lam = params.lam
    dtype = x.dtype
    eye_r = jnp.eye(r, dtype=dtype)

    def one(xt, mt):
        rinv = mt / params.R  # (N,), 0 at missing
        lam_r = lam * rinv[:, None]
        Zr = lam.T @ lam_r  # (r, r)
        w = lam_r.T @ xt  # (r,)
        # key r x r factor: (I + Zr Q)^{-1}
        IZQ = eye_r + Zr @ params.Q
        SinvZ = jnp.linalg.solve(IZQ, Zr)  # Lam'S^{-1}Lam
        Sinvw = jnp.linalg.solve(IZQ, w)  # Lam'S^{-1}x
        # lift to state dim: only the first r state coords load on obs
        KH = jnp.zeros((k, k), dtype).at[:r, :r].set(params.Q @ SinvZ)
        A = Tm - KH @ Tm
        b = jnp.zeros(k, dtype).at[:r].set(params.Q @ Sinvw)
        C = jnp.zeros((k, k), dtype)
        # (Q^{-1} + Zr)^{-1} = (I + Q Zr)^{-1} Q, no Q inverse required
        C = C.at[:r, :r].set(jnp.linalg.solve(IZQ.T, params.Q))
        eta = Tm.T @ jnp.zeros(k, dtype).at[:r].set(Sinvw)
        J = Tm.T @ jnp.zeros((k, k), dtype).at[:r, :r].set(SinvZ) @ Tm
        return FilterElement(A, b, 0.5 * (C + C.T), eta, 0.5 * (J + J.T))

    return jax.vmap(one)(x, m)


def _generic_elements_collapsed(Tm, Qs, C, b):
    """Elements for t >= 2 built from COLLAPSED per-step statistics — the
    fused form that retires the unfused O(N r)-per-element construction.

    C[t] = H_a' diag(m_t/R) H_a (q, q) and b[t] = H_a' (m_t/R * z_t) (q,)
    are the Jungbacker-Koopman collapse of a model whose observation loads
    only the leading q state coordinates (q = r for the iid core, 2r for
    the quasi-differenced AR core); they come out of TWO (T, N) panel
    GEMMs (ssm._collapse_obs / ssm_ar._collapse_obs_qd), so element
    construction here is O(q^3) per step with NO N-sized operand — the
    reason the shipped `ssm.assoc` kernel lost to the sequential scan
    (BENCH_r05: 92 vs 157 EM it/s) and this one does not.  The Woodbury
    algebra is `_generic_elements`' own, written against the active block:
        H_a' S^{-1} H_a = (I + C Q_a)^{-1} C,
        H_a' S^{-1} z   = (I + C Q_a)^{-1} b,
    with Q_a the active block of the transition noise (singular Q_a is
    fine — the identity is rational in Q_a)."""
    k = Tm.shape[0]
    q = b.shape[-1]
    dtype = b.dtype
    eye_q = jnp.eye(q, dtype=dtype)
    Qa = Qs[:q, :q]
    Qcols = Qs[:, :q]  # (k, q); only these columns of Qs meet the obs map
    Tma = Tm[:q, :]  # (q, k) rows of Tm feeding the active block

    def one(Ct, bt):
        IZQ = eye_q + Ct @ Qa
        SinvZ = jnp.linalg.solve(IZQ, Ct)  # H_a'S^{-1}H_a
        Sinvw = jnp.linalg.solve(IZQ, bt)  # H_a'S^{-1}z
        KH = jnp.zeros((k, k), dtype).at[:, :q].set(Qcols @ SinvZ)
        A = Tm - KH @ Tm
        b_el = Qcols @ Sinvw
        C_el = Qs - (Qcols @ SinvZ) @ Qs[:q, :]
        eta = Tma.T @ Sinvw
        J = Tma.T @ SinvZ @ Tma
        return FilterElement(
            A, b_el, 0.5 * (C_el + C_el.T), eta, 0.5 * (J + J.T)
        )

    return jax.vmap(one)(C, b)


def _first_element_collapsed(Tm, Qs, s0, P0, C0, b0):
    """t = 1 element from collapsed statistics: full-state posterior from
    the prior (A=0, b=m_{1|1}, C=P_{1|1}) — `_first_element` with
    C0 = H_a'diag(m/R)H_a and b0 = H_a'(m/R * z_0) supplied instead of
    rebuilt from the (N, q) loadings."""
    k = Tm.shape[0]
    q = b0.shape[0]
    dtype = b0.dtype
    sp = Tm @ s0
    Pp = Tm @ P0 @ Tm.T + Qs
    Z = jnp.zeros((k, k), dtype).at[:q, :q].set(C0)
    rhs = jnp.zeros(k, dtype).at[:q].set(b0 - C0 @ sp[:q])
    Pu = jnp.linalg.pinv(jnp.linalg.pinv(Pp, hermitian=True) + Z, hermitian=True)
    su = sp + Pu @ rhs
    zk = jnp.zeros(k, dtype)
    zkk = jnp.zeros((k, k), dtype)
    return FilterElement(zkk, su, 0.5 * (Pu + Pu.T), zk, zkk)


def _filter_elements_from_collapsed(Tm, Qs, s0, P0, C, b) -> FilterElement:
    # The generic build runs over ALL T rows and row 0 is then overwritten,
    # instead of concatenate([first[None], generic(C[1:], b[1:])]): a
    # 1 + (T-1) concatenate along a mesh-sharded time axis miscompiles in
    # the XLA SPMD partitioner (uneven-operand padding), while a static
    # row-0 update partitions cleanly.  One wasted q^3 solve per call.
    first = _first_element_collapsed(Tm, Qs, s0, P0, C[0], b[0])
    full = _generic_elements_collapsed(Tm, Qs, C, b)
    return jax.tree.map(lambda f, a: a.at[0].set(f), first, full)


def _loglik_from_filtered_collapsed(
    Tm, Qs, s0, P0, C, b, ld_R, xRx, n_obs, means, covs
):
    """`_loglik_from_filtered` on collapsed statistics: the observation
    quadratic (x - H sp)'R^{-1}(x - H sp) expands to
    xRx_t - 2 f'b_t + f'C_t f (f the active predicted state), so no
    N-sized operand enters.  On the PanelStats path xRx is identically
    zero and the caller adds the scalar ll_corr = -1/2 sum_i Sxx_i/R_i
    (ssm._collapse_obs_stats convention)."""
    k = Tm.shape[0]
    q = b.shape[-1]
    dtype = b.dtype
    log2pi = jnp.asarray(np.log(2.0 * np.pi), dtype)

    # roll + row-0 update, not concatenate([x0[None], x[:-1]]): the uneven
    # concatenate miscompiles under the SPMD partitioner on a time-sharded
    # mesh (see _filter_elements_from_collapsed); roll lowers to a clean
    # collective permute.
    prev_means = jnp.roll(means, 1, axis=0).at[0].set(s0)
    prev_covs = jnp.roll(covs, 1, axis=0).at[0].set(P0)
    pred_means = prev_means @ Tm.T
    pred_covs = jnp.einsum("ij,tjl,kl->tik", Tm, prev_covs, Tm) + Qs[None]

    def one(Ct, bt, ld, xr, no, sp, Pp, Pu):
        f = sp[:q]
        rhs = jnp.zeros(k, dtype).at[:q].set(bt - Ct @ f)
        _, ld_pp = jnp.linalg.slogdet(Pp)
        _, ld_pu = jnp.linalg.slogdet(Pu)
        quad = xr - 2.0 * (f @ bt) + f @ Ct @ f - rhs @ Pu @ rhs
        return -0.5 * (no * log2pi + ld + ld_pp - ld_pu + quad)

    lls = jax.vmap(one)(C, b, ld_R, xRx, n_obs, pred_means, pred_covs, covs)
    return lls.sum(), pred_means, pred_covs


def _first_element(params: SSMParams, x0, m0):
    """t = 1 element: full-state posterior from the diffuse prior
    (A=0, b=m_{1|1}, C=P_{1|1}; eta/J never read for the earliest block)."""
    Tm, Qs = _companion(params)
    k = Tm.shape[0]
    r = params.r
    dtype = x0.dtype
    s0, P0 = _init_state(params)
    sp = Tm @ s0
    Pp = Tm @ P0 @ Tm.T + Qs
    rinv = m0 / params.R
    lam_r = params.lam * rinv[:, None]
    Z = jnp.zeros((k, k), dtype).at[:r, :r].set(params.lam.T @ lam_r)
    v = x0 - params.lam @ sp[:r]
    rhs = jnp.zeros(k, dtype).at[:r].set(lam_r.T @ v)
    Pu = jnp.linalg.pinv(jnp.linalg.pinv(Pp, hermitian=True) + Z, hermitian=True)
    su = sp + Pu @ rhs
    zk = jnp.zeros(k, dtype)
    zkk = jnp.zeros((k, k), dtype)
    return FilterElement(zkk, su, 0.5 * (Pu + Pu.T), zk, zkk)


def filter_elements(params: SSMParams, x, mask) -> FilterElement:
    """Per-step elements for the whole panel; x (T, N) NaN-free, mask (T, N)
    float/bool.  Element t=0 folds in the prior."""
    m = mask.astype(x.dtype)
    first = _first_element(params, x[0], m[0])
    rest = _generic_elements(params, x[1:], m[1:])
    return jax.tree.map(
        lambda a, b: jnp.concatenate([a[None], b], axis=0), first, rest
    )


def _loglik_from_filtered(params: SSMParams, x, m, means, covs):
    """Per-step predictive log-likelihoods recomputed from the filtered path
    (vmapped over t — embarrassingly parallel, unlike the sequential scan).

    Identical decomposition to ssm._filter_scan: via the matrix determinant
    lemma, log|S_t| = sum_obs log R_ii + log|Pp_t| - log|Pu_t|.
    """
    Tm, Qs = _companion(params)
    r = params.r
    k = Tm.shape[0]
    dtype = x.dtype
    log2pi = jnp.asarray(np.log(2.0 * np.pi), dtype)
    s0, P0 = _init_state(params)

    pred_means = jnp.concatenate([(Tm @ s0)[None], (means[:-1] @ Tm.T)], axis=0)
    pred_covs = (
        jnp.einsum("ij,tjl,kl->tik", Tm, jnp.concatenate([P0[None], covs[:-1]]), Tm)
        + Qs[None]
    )

    def one(xt, mt, sp, Pp, Pu):
        rinv = mt / params.R
        lam_r = params.lam * rinv[:, None]
        v = xt - params.lam @ sp[:r]
        rhs = jnp.zeros(k, dtype).at[:r].set(lam_r.T @ v)
        _, ld_pp = jnp.linalg.slogdet(Pp)
        _, ld_pu = jnp.linalg.slogdet(Pu)
        ld_R = (mt * jnp.log(params.R)).sum()
        quad = (rinv * v * v).sum() - rhs @ Pu @ rhs
        return -0.5 * (mt.sum() * log2pi + ld_R + ld_pp - ld_pu + quad)

    lls = jax.vmap(one)(x, m, pred_means, pred_covs, covs)
    return lls.sum(), pred_means, pred_covs


def kalman_filter_associative(
    params: SSMParams, x, mask, scan=None
) -> KalmanResult:
    """Masked Kalman filter with O(log T) depth.

    `scan` lets callers swap the scan implementation — the default is
    ``jax.lax.associative_scan``; pass `parallel.timescan.sharded_scan`'s
    bound form to run time-block-sharded across a mesh.
    """
    elems = filter_elements(params, x, mask)
    if scan is None:
        scanned = jax.lax.associative_scan(combine_filter, elems)
    else:
        scanned = scan(combine_filter, elems)
    means, covs = scanned.b, scanned.C
    m = mask.astype(x.dtype)
    ll, pred_means, pred_covs = _loglik_from_filtered(params, x, m, means, covs)
    return KalmanResult(ll, means, covs, pred_means, pred_covs)


def _smoother_elements_generic(Tm, Qs, means, covs) -> SmootherElement:
    """Backward elements from a filtered path, batched over time — already
    N-free (only the k-dim posterior enters), shared by the panel-built
    and collapsed-built forward passes."""
    k = Tm.shape[0]

    def one(su, Pu):
        Pp = Tm @ Pu @ Tm.T + Qs
        E = jnp.linalg.solve(Pp.T, Tm @ Pu).T  # Pu Tm' Pp^{-1} (RTS gain)
        g = su - E @ (Tm @ su)
        L = Pu - E @ Tm @ Pu
        return SmootherElement(E, g, 0.5 * (L + L.T))

    # Vmapped over ALL T rows with the terminal row overwritten in place —
    # the (T-1) + 1 concatenate along time miscompiles under the SPMD
    # partitioner on a time-sharded mesh (see
    # _filter_elements_from_collapsed); a static last-row update is clean.
    full = jax.vmap(one)(means, covs)
    last = SmootherElement(
        jnp.zeros((k, k), means.dtype), means[-1], covs[-1]
    )
    return jax.tree.map(lambda a, b: a.at[-1].set(b), full, last)


def smoother_elements(params: SSMParams, filt: KalmanResult) -> SmootherElement:
    """Backward elements from the filtered path, batched over time."""
    Tm, Qs = _companion(params)
    return _smoother_elements_generic(Tm, Qs, filt.means, filt.covs)


def kalman_smoother_associative(params: SSMParams, x, mask, scan=None):
    """Parallel filter + parallel RTS smoother.

    Returns (smoothed_means, smoothed_covs, loglik, lag1) where
    lag1[t] = Cov(s_{t+1}, s_t | y_{1:T}) for t = 0..T-2 — the quantity the
    EM M-step consumes (ssm.em_step).
    """
    filt = kalman_filter_associative(params, x, mask, scan=scan)
    elems = smoother_elements(params, filt)
    # backward pass = forward scan over time-flipped elements with swapped
    # operand order (combine is non-commutative; explicit flip keeps the
    # "earlier ⊗ later" convention independent of the scan implementation)
    rev = jax.tree.map(lambda a: jnp.flip(a, 0), elems)
    swapped = lambda a, b: combine_smoother(b, a)
    sm = (
        jax.lax.associative_scan(swapped, rev)
        if scan is None
        else scan(swapped, rev)
    )
    sm = jax.tree.map(lambda a: jnp.flip(a, 0), sm)
    means, covs = sm.g, sm.L
    # lag-one smoothed covariance: P_{t+1|T} E_t'
    lag1 = jnp.einsum("tij,tkj->tik", covs[1:], elems.E[:-1])
    return means, covs, filt.loglik, lag1


# ------------------- collapsed (fused) parallel smoother --------------------


def filter_elements_collapsed(params: SSMParams, C, b) -> FilterElement:
    """Per-step elements from the iid core's collapsed statistics
    (`ssm._collapse_obs` / `_collapse_obs_stats` C and b); element t=0
    folds in the diffuse prior.  O(r^3) per element — never O(N r)."""
    Tm, Qs = _companion(params)
    s0, P0 = _init_state(params)
    return _filter_elements_from_collapsed(Tm, Qs, s0, P0, C, b)


def _assoc_smooth_collapsed(
    Tm, Qs, s0, P0, C, b, ld_R, xRx, n_obs, ll_corr, scan=None
):
    """Model-agnostic fused parallel filter + RTS smoother on collapsed
    statistics: (Tm, Qs, s0, P0) define the linear-Gaussian state model,
    (C, b, ld_R, xRx, n_obs) its collapsed per-step observations over the
    leading q = b.shape[1] state coordinates.  Returns
    (s_sm, P_sm, loglik + ll_corr, lag1).  `scan` swaps the scan
    implementation (default ``jax.lax.associative_scan``; pass
    `parallel.timescan.sharded_scan`'s bound form to run time-sharded —
    its end-padding repeats the LAST element, which an inclusive causal
    scan never reads back into real positions, so padded/boundary steps
    are exactly inert)."""
    run = (
        (lambda comb, e: jax.lax.associative_scan(comb, e))
        if scan is None
        else scan
    )
    elems = _filter_elements_from_collapsed(Tm, Qs, s0, P0, C, b)
    scanned = run(combine_filter, elems)
    means, covs = scanned.b, scanned.C
    ll, _, _ = _loglik_from_filtered_collapsed(
        Tm, Qs, s0, P0, C, b, ld_R, xRx, n_obs, means, covs
    )
    sm_elems = _smoother_elements_generic(Tm, Qs, means, covs)
    rev = jax.tree.map(lambda a: jnp.flip(a, 0), sm_elems)
    swapped = lambda a, b_: combine_smoother(b_, a)
    sm = run(swapped, rev)
    sm = jax.tree.map(lambda a: jnp.flip(a, 0), sm)
    s_sm, P_sm = sm.g, sm.L
    lag1 = jnp.einsum("tij,tkj->tik", P_sm[1:], sm_elems.E[:-1])
    return s_sm, P_sm, ll + ll_corr, lag1


def kalman_filter_associative_collapsed(
    params: SSMParams, C, b, ld_R, xRx, n_obs, ll_corr=0.0, scan=None
) -> KalmanResult:
    """Fused parallel filter on the iid core's collapsed statistics."""
    Tm, Qs = _companion(params)
    s0, P0 = _init_state(params)
    elems = _filter_elements_from_collapsed(Tm, Qs, s0, P0, C, b)
    scanned = (
        jax.lax.associative_scan(combine_filter, elems)
        if scan is None
        else scan(combine_filter, elems)
    )
    means, covs = scanned.b, scanned.C
    ll, pred_means, pred_covs = _loglik_from_filtered_collapsed(
        Tm, Qs, s0, P0, C, b, ld_R, xRx, n_obs, means, covs
    )
    return KalmanResult(ll + ll_corr, means, covs, pred_means, pred_covs)


def kalman_smoother_associative_collapsed(
    params: SSMParams, C, b, ld_R, xRx, n_obs, ll_corr=0.0, scan=None
):
    """Fused parallel filter + smoother on the iid core's collapsed
    statistics: returns (s_sm, P_sm, loglik, lag1) — the E-step quartet
    `ssm._em_m_step` consumes, built without any O(N r) per-element
    work."""
    Tm, Qs = _companion(params)
    s0, P0 = _init_state(params)
    return _assoc_smooth_collapsed(
        Tm, Qs, s0, P0, C, b, ld_R, xRx, n_obs, ll_corr, scan=scan
    )
