"""State-space DFM: Kalman filter/smoother (lax.scan) + EM, end-to-end jitted.

This is the `Parametric` estimation path the reference declared but never
implemented (dfm_functions.ipynb cell 1:3; SURVEY.md section 0) — the spec is
Doz-Giannone-Reichlin (2012) / Banbura-Modugno (2014) EM for factor models
with arbitrary missing-data patterns:

    x_t = Lam f_t + eps_t,        eps_t ~ N(0, diag(R))
    f_t = A_1 f_{t-1} + ... + A_p f_{t-p} + u_t,   u_t ~ N(0, Q)

TPU-first design choices:
  * the filter/smoother are ``lax.scan`` over time with static shapes;
  * missing observations are handled by masking rows of Lam (never by
    changing shapes), so one compiled program serves every missing pattern;
  * the measurement update uses the information (Woodbury) form — per-step
    cost O(N r^2 + k^3) with k = r*p the state dim, never O(N^3);
  * one EM iteration (E-step scans + closed-form M-step) is a single jitted
    function; `em iters/sec` is the tracked benchmark metric (BASELINE.json).
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
import jax.scipy.linalg as jsl
import numpy as np

from ..ops.linalg import solve_normal, standardize_data
from ..ops.masking import fillz, mask_of
from ..utils.backend import on_backend
from .dfm import DFMConfig, estimate_dfm

__all__ = [
    "SSMParams",
    "KalmanResult",
    "kalman_filter",
    "kalman_smoother",
    "em_step",
    "em_step_assoc",
    "em_step_sqrt",
    "estimate_dfm_em",
    "EMResults",
]


class SSMParams(NamedTuple):
    """Parameters of the state-space DFM.

    lam: (N, r) loadings; R: (N,) idiosyncratic variances;
    A: (p, r, r) VAR coefficient blocks; Q: (r, r) factor innovation cov.
    """

    lam: jnp.ndarray
    R: jnp.ndarray
    A: jnp.ndarray
    Q: jnp.ndarray

    @property
    def r(self) -> int:
        return self.lam.shape[1]

    @property
    def p(self) -> int:
        return self.A.shape[0]


class KalmanResult(NamedTuple):
    loglik: jnp.ndarray
    means: jnp.ndarray  # (T, k) filtered or smoothed state means
    covs: jnp.ndarray  # (T, k, k)
    pred_means: jnp.ndarray  # (T, k) one-step-ahead means (filter only)
    pred_covs: jnp.ndarray  # (T, k, k)


def _psd_floor(Q: jnp.ndarray) -> jnp.ndarray:
    """Symmetrize and floor the eigenvalues of a covariance estimate.

    The filter's Cholesky updates require Q strictly PD (Pp = TPT' + Qs is
    PD iff Q and P are); the EM M-step covariance S11 - A S10' is only PSD
    up to float error and can acquire tiny negative eigenvalues with
    near-collinear factors.  Flooring at eps-scale keeps the fast Cholesky
    path valid without measurably moving a healthy Q.
    """
    Q = 0.5 * (Q + Q.T)
    e, v = jnp.linalg.eigh(Q)
    eps = jnp.asarray(jnp.finfo(Q.dtype).eps, Q.dtype)
    floor = jnp.maximum(e[-1] * 16.0 * eps, eps)
    return (v * jnp.maximum(e, floor)) @ v.T


def _companion(params: SSMParams):
    r, p = params.r, params.p
    k = r * p
    Tm = jnp.zeros((k, k), params.A.dtype)
    Tm = Tm.at[:r, :].set(jnp.concatenate([params.A[i] for i in range(p)], axis=1))
    if p > 1:
        Tm = Tm.at[r:, : k - r].set(jnp.eye(k - r, dtype=params.A.dtype))
    Qs = jnp.zeros((k, k), params.Q.dtype).at[:r, :r].set(params.Q)
    return Tm, Qs


def _init_state(params: SSMParams):
    """Diffuse-ish init: zero mean, large isotropic covariance."""
    k = params.r * params.p
    return jnp.zeros(k, params.lam.dtype), 1e2 * jnp.eye(k, dtype=params.lam.dtype)


def _info_filter_scan(Tm, Qs, x, mask, obs_step, s0, P0, qdiag=None):
    """Generic masked information-form Kalman filter (shared scan body).

    `obs_step(xt, mt, sp) -> (C, rhs, ld_R, quad0, n_obs)` supplies the
    model-specific measurement update: information matrix C = H'R⁻¹H, gain
    right-hand side rhs = H'R⁻¹(x - H sp), the observed-rows log|R|, the
    observation quadratic Σ (x - H sp)'R⁻¹(x - H sp), and the count.  The
    prediction, Cholesky updates, and determinant-lemma log-likelihood are
    identical across models (ssm.py restricted-loading form; ssm_ar.py dense
    observation map) and live only here.

    `qdiag` (T, r) optionally supplies time-varying transition-noise
    variances for the leading r state dims (stochastic-volatility models);
    it is ADDED to the constant Qs, so pass Qs with a zero top-left block
    when the variances are fully time-varying.
    """
    k = Tm.shape[0]
    dtype = x.dtype
    log2pi = jnp.asarray(np.log(2.0 * np.pi), dtype)
    eye_k = jnp.eye(k, dtype=dtype)
    r_tv = 0 if qdiag is None else qdiag.shape[1]

    def step(carry, inp):
        s, P = carry
        if qdiag is None:
            xt, mt = inp
        else:
            xt, mt, qt = inp
        sp = Tm @ s
        Pp = Tm @ P @ Tm.T + Qs
        Pp = 0.5 * (Pp + Pp.T)
        if qdiag is not None:
            Pp = Pp.at[jnp.arange(r_tv), jnp.arange(r_tv)].add(qt)
        C, rhs, ld_R, quad0, n_obs = obs_step(xt, mt, sp)
        # Pp is PD (Q PD ⇒ the prediction keeps full rank), so Cholesky
        # replaces the eigh-based pinv and yields log-dets for free
        Lp = jnp.linalg.cholesky(Pp)
        Ppinv = jsl.cho_solve((Lp, True), eye_k)
        M = Ppinv + C
        Lm = jnp.linalg.cholesky(0.5 * (M + M.T))
        Pu = jsl.cho_solve((Lm, True), eye_k)
        Pu = 0.5 * (Pu + Pu.T)
        su = sp + Pu @ rhs
        # log-likelihood via matrix determinant lemma:
        # log|S| = log|R|_obs + log|Pp| - log|Pu|
        ld_pp = 2.0 * jnp.log(jnp.diagonal(Lp)).sum()
        ld_pu = -2.0 * jnp.log(jnp.diagonal(Lm)).sum()
        quad = quad0 - rhs @ Pu @ rhs
        ll = -0.5 * (n_obs * log2pi + ld_R + ld_pp - ld_pu + quad)
        return (su, Pu), (su, Pu, sp, Pp, ll)

    inputs = (
        (x, mask.astype(dtype))
        if qdiag is None
        else (x, mask.astype(dtype), qdiag)
    )
    (_, _), (means, covs, pmeans, pcovs, lls) = jax.lax.scan(
        step, (s0, P0), inputs
    )
    return means, covs, pmeans, pcovs, lls.sum()


@jax.jit
def _sqrt_filter_scan(params: SSMParams, x, mask):
    """Square-root (array-form) masked Kalman filter: propagates Cholesky
    factors of the covariances through one QR per step instead of the
    covariances themselves (Kailath-Sayed array algorithm).

    The precision option for f32 TPU runs (SURVEY.md section 7.3): the
    effective condition number seen by the recursion is sqrt of the
    covariance filter's, and updated covariances are S S' — symmetric PSD
    by construction, no drift to fix up.  Measured on ill-conditioned DGPs
    (R 1e-4..1e-1, rho up to 0.999, f32 vs f64 truth): the log-likelihood
    error drops ~8-16x vs the information filter (whose Cholesky solves
    already keep the state estimates comparable) — the quantity EM
    convergence tests and model comparison actually consume.  Costs one
    (N+k)-square QR per step (vs the information form's O(N r^2 + k^3)),
    so it is the accuracy-critical path, not the throughput default.

    Missing data: masked rows get a zero observation row and unit dummy
    variance — the innovation is exactly zero and the dummy rows are
    uncoupled, so they contribute nothing to the update, the determinant,
    or the quadratic (no shape change, one compiled program per pattern).

        prediction:   qr([S_u' Tm' ; chol(Q_s)'])          -> S_p'
        measurement:  qr([R^1/2  0 ; S_p' H'  S_p']) = [S_e'  K' ; 0  S_u']
        update:       s_u = s_p + K solve(S_e, v)
        loglik:       log|HPH'+R| = 2 sum log diag S_e  (dummy rows add 0)
    """
    Tm, _ = _companion(params)
    k = Tm.shape[0]
    r = params.r
    N = params.lam.shape[0]
    dtype = x.dtype
    log2pi = jnp.asarray(np.log(2.0 * np.pi), dtype)
    # Q is pre-floored by every caller (the _filter_scan contract), so the
    # Cholesky here is safe without a second eps-floor
    sqrtQ = jnp.linalg.cholesky(params.Q)  # (r, r)
    s0, P0 = _init_state(params)
    S0 = jnp.sqrt(P0[0, 0]) * jnp.eye(k, dtype=dtype)  # P0 isotropic

    def _pos_diag(Rf):
        # QR sign convention: flip rows so the triangular factor has a
        # positive diagonal (keeps log-det real and factors comparable)
        sgn = jnp.sign(jnp.diagonal(Rf))
        sgn = jnp.where(sgn == 0, 1.0, sgn)
        return sgn[:, None] * Rf

    def step(carry, inp):
        s, S = carry  # S lower: P = S S'
        xt, mt = inp
        # --- prediction (array form) ---
        sp = Tm @ s
        pre_p = jnp.concatenate([S.T @ Tm.T, jnp.zeros((r, k), dtype).at[:, :r].set(sqrtQ.T)])
        Sp = _pos_diag(jnp.linalg.qr(pre_p, mode="r")).T  # (k, k) lower

        # --- measurement update (array form, masked) ---
        lam_m = params.lam * mt[:, None]  # zero rows at missing
        rstd = jnp.where(mt > 0, jnp.sqrt(params.R), 1.0)  # dummy unit sd
        HS = lam_m @ Sp[:r, :]  # (N, k): H = [lam_m, 0] so H @ Sp hits top rows
        pre = jnp.zeros((N + k, N + k), dtype)
        pre = pre.at[:N, :N].set(jnp.diag(rstd))
        pre = pre.at[N:, :N].set(HS.T)
        pre = pre.at[N:, N:].set(Sp.T)
        post = _pos_diag(jnp.linalg.qr(pre, mode="r")).T  # lower
        Se = post[:N, :N]  # (N, N) lower sqrt innovation cov
        Kbar = post[N:, :N]  # (k, N) = P_p H' S_e^{-T}
        Su = post[N:, N:]  # (k, k) lower sqrt updated cov

        v = mt * (xt - params.lam @ sp[:r])  # masked innovation
        e = jsl.solve_triangular(Se, v, lower=True)
        su = sp + Kbar @ e
        # dummy rows: diag(Se) = 1 there, e = 0 there — both sums exact
        ll = -0.5 * (
            mt.sum() * log2pi
            + 2.0 * jnp.log(jnp.diagonal(Se)).sum()
            + (e * e).sum()
        )
        return (su, Su), (su, Su @ Su.T, sp, Sp @ Sp.T, ll)

    (_, _), (means, covs, pmeans, pcovs, lls) = jax.lax.scan(
        step, (s0, S0), (x, mask.astype(dtype))
    )
    return KalmanResult(lls.sum(), means, covs, pmeans, pcovs)


@jax.jit
def _filter_scan(params: SSMParams, x, mask, qdiag=None):
    """Masked Kalman filter; x (T, N) NaN-free (pre-filled), mask (T, N).

    Only the first r state dims load on observations, so the measurement
    update is the Woodbury-restricted obs_step below.  `qdiag` (T, r)
    replaces params.Q with time-varying diagonal factor-innovation
    variances (stochastic-volatility models).
    """
    Tm, Qs = _companion(params)
    if qdiag is not None:
        Qs = jnp.zeros_like(Qs)  # fully time-varying top block
    k = Tm.shape[0]
    r = params.r
    lam = params.lam  # (N, r) — state loadings are [lam, 0, ..., 0]
    s0, P0 = _init_state(params)
    dtype = x.dtype

    def obs_step(xt, mt, sp):
        rinv = mt / params.R  # (N,), 0 at missing
        lam_r = lam * rinv[:, None]  # (N, r)
        C = jnp.zeros((k, k), dtype).at[:r, :r].set(lam.T @ lam_r)
        v = xt - lam @ sp[:r]  # innovation (garbage at missing; weighted by 0)
        rhs = jnp.zeros(k, dtype).at[:r].set(lam_r.T @ v)
        ld_R = (mt * jnp.log(params.R)).sum()
        return C, rhs, ld_R, (rinv * v * v).sum(), mt.sum()

    means, covs, pmeans, pcovs, ll = _info_filter_scan(
        Tm, Qs, x, mask, obs_step, s0, P0, qdiag=qdiag
    )
    return KalmanResult(ll, means, covs, pmeans, pcovs)


_FILTER_METHODS = ("sequential", "associative", "sqrt")


def kalman_filter(
    params: SSMParams, x, backend: str | None = None, method: str = "sequential"
) -> KalmanResult:
    """Masked Kalman filter over a (T, N) panel with NaN missing values.

    method="sequential" is the O(T) ``lax.scan``; "associative" is the
    O(log T)-depth parallel-in-time formulation (models/pkalman.py) —
    identical results to float tolerance, preferable for long samples;
    "sqrt" is the square-root array filter (`_sqrt_filter_scan`) — same
    results in f64, an order of magnitude tighter log-likelihood in f32
    (the TPU precision option).
    """
    if method not in _FILTER_METHODS:
        raise ValueError(f"method must be one of {_FILTER_METHODS}, got {method!r}")
    with on_backend(backend):
        # the Cholesky-based recursions need Q strictly PD; floor here so a
        # caller-supplied singular/indefinite Q degrades gracefully
        params = params._replace(Q=_psd_floor(params.Q))
        x = jnp.asarray(x)
        mask = mask_of(x)
        if method == "associative":
            from .pkalman import kalman_filter_associative

            return kalman_filter_associative(params, fillz(x), mask)
        if method == "sqrt":
            return _sqrt_filter_scan(params, fillz(x), mask)
        return _filter_scan(params, fillz(x), mask)


def _rts_scan(Tm, means, covs, pmeans, pcovs):
    """Rauch-Tung-Striebel backward pass (shared scan body); also returns
    lag-one covariances lag1[t] = Cov(s_{t+1}, s_t | T) for t = 0..T-2."""

    def step(carry, inp):
        s_next, P_next = carry
        su, Pu, sp_next, Pp_next = inp
        # J = Pu Tm' Pp_next^{-1}; Pp_next PD, Pu symmetric, so solve the
        # transposed system with Cholesky instead of forming a pinv
        J = jsl.cho_solve((jnp.linalg.cholesky(Pp_next), True), Tm @ Pu).T
        s_sm = su + J @ (s_next - sp_next)
        P_sm = Pu + J @ (P_next - Pp_next) @ J.T
        lag1 = P_next @ J.T
        return (s_sm, P_sm), (s_sm, P_sm, lag1)

    # iterate t = T-2 .. 0 pairing (filtered_t, predicted_{t+1}, smoothed_{t+1})
    last = (means[-1], covs[-1])
    inputs = (means[:-1], covs[:-1], pmeans[1:], pcovs[1:])
    (_, _), (s_sm, P_sm, lag1) = jax.lax.scan(step, last, inputs, reverse=True)
    s_all = jnp.concatenate([s_sm, means[-1:]], axis=0)
    P_all = jnp.concatenate([P_sm, covs[-1:]], axis=0)
    return s_all, P_all, lag1


@jax.jit
def _smoother_scan(params: SSMParams, filt: KalmanResult):
    """RTS backward pass for the SSMParams model (shared body: _rts_scan)."""
    Tm, _ = _companion(params)
    return _rts_scan(Tm, filt.means, filt.covs, filt.pred_means, filt.pred_covs)


def kalman_smoother(
    params: SSMParams, x, backend: str | None = None, method: str = "sequential"
):
    """Kalman smoother: returns (smoothed_means, smoothed_covs, loglik).

    The `backend={"cpu","tpu"}` kwarg follows the north-star API
    (BASELINE.json): same program, device chosen by flag.  method as in
    `kalman_filter`; "associative" also parallelizes the backward pass;
    "sqrt" runs the RTS pass on the square-root filter's outputs (the
    forward pass dominates the error, so f32 accuracy improves with it).
    """
    if method not in _FILTER_METHODS:
        raise ValueError(f"method must be one of {_FILTER_METHODS}, got {method!r}")
    with on_backend(backend):
        params = params._replace(Q=_psd_floor(params.Q))
        x = jnp.asarray(x)
        if method == "associative":
            from .pkalman import kalman_smoother_associative

            means, covs, ll, _ = kalman_smoother_associative(
                params, fillz(x), mask_of(x)
            )
            return means, covs, ll
        filt_fn = _sqrt_filter_scan if method == "sqrt" else _filter_scan
        filt = filt_fn(params, fillz(x), mask_of(x))
        means, covs, _ = _smoother_scan(params, filt)
        return means, covs, filt.loglik


# ---------------------------------------------------------------------------
# EM
# ---------------------------------------------------------------------------


def _em_m_step(params: SSMParams, x, m, s_sm, P_sm, lag1):
    """Closed-form M-step from smoothed first/second moments (shared by the
    sequential-scan and associative E-steps)."""
    r, p = params.r, params.p
    f = s_sm[:, :r]  # E[f_t | T]
    Pf = P_sm[:, :r, :r]  # Var(f_t | T)

    # --- loadings + R (masked, batched over series) ---
    # Sxf_i = sum_t m_it x_it E[f_t]';  Sff_i = sum_t m_it (E f E f' + Pf).
    # The E[f]E[f]' part and Sxf are exactly the batched masked-Gram shape
    # (X = f shared regressors, Y = x targets, W = m), so they route through
    # the fused Pallas kernel at scale; only the Pf correction needs the
    # extra (N, T) @ (T, r^2) contraction.
    from ..ops.pallas_gram import masked_gram

    Tn = x.shape[0]
    Sff_ff, Sxf = masked_gram(f, x, m)  # (N, r, r), (N, r)
    Sff = Sff_ff + (m.T @ Pf.reshape(Tn, r * r)).reshape(-1, r, r)
    lam = jax.vmap(solve_normal)(Sff, Sxf)  # (N, r)
    resid = x - f @ lam.T
    extra = jnp.einsum("ir,trs,is->ti", lam, Pf, lam)  # lam' Pf lam
    n_i = m.sum(axis=0)
    R = ((m * (resid**2 + extra)).sum(axis=0)) / n_i
    R = jnp.maximum(R, 1e-8)

    # --- factor VAR blocks + Q from smoothed second moments ---
    S11 = (jnp.einsum("tr,ts->rs", s_sm[1:, :r], s_sm[1:, :r])
           + P_sm[1:, :r, :r].sum(axis=0))
    S00 = (jnp.einsum("tk,tl->kl", s_sm[:-1], s_sm[:-1]) + P_sm[:-1].sum(axis=0))
    S10 = (jnp.einsum("tr,tk->rk", s_sm[1:, :r], s_sm[:-1])
           + lag1[:, :r, :].sum(axis=0))
    Ak = S10 @ jnp.linalg.pinv(S00, hermitian=True)  # (r, k)
    Q = _psd_floor((S11 - Ak @ S10.T) / (Tn - 1))
    A = jnp.stack([Ak[:, i * r : (i + 1) * r] for i in range(p)])
    return SSMParams(lam, R, A, Q)


@jax.jit
def em_step(params: SSMParams, x, mask):
    """One EM iteration (sequential-scan E-step + closed-form M-step);
    returns (new_params, loglik of the *current* params)."""
    m = mask.astype(x.dtype)
    # guard caller-supplied params the same way kalman_filter does: the
    # Cholesky recursions need Q strictly PD (M-step outputs are pre-floored,
    # so for internal EM loops this is a no-op re-floor)
    params = params._replace(Q=_psd_floor(params.Q))
    filt = _filter_scan(params, x, mask)
    s_sm, P_sm, lag1 = _smoother_scan(params, filt)
    return _em_m_step(params, x, m, s_sm, P_sm, lag1), filt.loglik


@jax.jit
def em_step_sqrt(params: SSMParams, x, mask):
    """`em_step` with the square-root array E-step: in f32 the convergence
    test consumes a log-likelihood an order of magnitude more accurate
    (see `_sqrt_filter_scan`) — the accuracy-first EM variant for chips
    without f64."""
    m = mask.astype(x.dtype)
    params = params._replace(Q=_psd_floor(params.Q))
    filt = _sqrt_filter_scan(params, x, mask)
    s_sm, P_sm, lag1 = _smoother_scan(params, filt)
    return _em_m_step(params, x, m, s_sm, P_sm, lag1), filt.loglik


@jax.jit
def em_step_assoc(params: SSMParams, x, mask):
    """`em_step` with the parallel-in-time (associative-scan) E-step
    (models.pkalman): log-depth instead of T-depth recursions — the
    TPU-friendly shape when the sequential scan's per-step latency
    dominates."""
    from .pkalman import kalman_smoother_associative

    m = mask.astype(x.dtype)
    params = params._replace(Q=_psd_floor(params.Q))
    s_sm, P_sm, ll, lag1 = kalman_smoother_associative(params, x, mask)
    return _em_m_step(params, x, m, s_sm, P_sm, lag1), ll


class EMResults(NamedTuple):
    params: SSMParams
    factors: jnp.ndarray  # (T, r) smoothed factors (standardized units)
    factor_covs: jnp.ndarray  # (T, r, r)
    loglik_path: np.ndarray
    n_iter: int
    stds: jnp.ndarray  # per-series standardization scale
    means: jnp.ndarray
    trace: object | None = None  # ConvergenceTrace when collect_path=True


def _init_params_from_als(
    data, inclcode, initperiod, lastperiod, config, xz, m_arr
) -> SSMParams:
    """Initialize EM from the non-parametric ALS fit: VAR blocks from the
    factor VAR, loadings/R from masked OLS of the standardized panel on the
    ALS factors."""
    res = estimate_dfm(data, inclcode, initperiod, lastperiod, config)
    r = config.nfac_u
    p = config.n_factorlag
    b = res.var.betahat[1:].T  # (r, r*p) companion top rows
    A = jnp.stack([b[:, i * r : (i + 1) * r] for i in range(p)])
    Q = _psd_floor(res.var.seps)
    fw = res.factor[initperiod : lastperiod + 1]
    W = m_arr.astype(xz.dtype)
    Sff = jnp.einsum("ti,tr,ts->irs", W, fw, fw)
    Sxf = jnp.einsum("ti,tr->ir", W * xz, fw)
    lam0 = jax.vmap(solve_normal)(Sff, Sxf)
    resid0 = jnp.where(m_arr, xz - fw @ lam0.T, 0.0)
    R0 = jnp.maximum((resid0**2).sum(axis=0) / W.sum(axis=0), 1e-6)
    return SSMParams(lam0, R0, A, Q)


def estimate_dfm_em(
    data,
    inclcode,
    initperiod: int,
    lastperiod: int,
    config: DFMConfig = DFMConfig(nfac_u=4),
    max_em_iter: int = 200,
    tol: float = 1e-6,
    backend: str | None = None,
    collect_path: bool = False,
    method: str = "sequential",
    checkpoint_path: str | None = None,
    checkpoint_every: int = 25,
) -> EMResults:
    """State-space DFM via EM on the standardized included panel
    (BASELINE.json config 2: `State-space DFM via EM + Kalman smoother`).

    Converges when the relative log-likelihood improvement drops below tol.
    The convergence loop runs on device (`emloop.run_em_loop`);
    collect_path=True switches to a host loop whose per-iteration wall
    clock is recorded in EMResults.trace.  method="associative" swaps the
    E-step for the parallel-in-time scans (`em_step_assoc`); method="sqrt"
    uses the square-root array E-step (`em_step_sqrt`, f32-accurate).
    """
    if method not in _FILTER_METHODS:
        raise ValueError(f"method must be one of {_FILTER_METHODS}, got {method!r}")
    with on_backend(backend):
        data = jnp.asarray(data)
        inclcode = np.asarray(inclcode)
        est = data[:, inclcode == 1]
        xw = est[initperiod : lastperiod + 1]
        xstd, stds = standardize_data(xw)
        m_arr = mask_of(xstd)
        xz = fillz(xstd)
        # original (pre-standardization) per-series means, for reconstruction
        mw = mask_of(xw)
        n_mean = (fillz(xw) * mw).sum(axis=0) / mw.sum(axis=0)

        r = config.nfac_u
        params = _init_params_from_als(
            data, inclcode, initperiod, lastperiod, config, xz, m_arr
        )

        from .emloop import run_em_loop

        step = {
            "sequential": em_step,
            "associative": em_step_assoc,
            "sqrt": em_step_sqrt,
        }[method]
        params, llpath, n_iter, trace = run_em_loop(
            step, params, (xz, m_arr), tol, max_em_iter,
            collect_path=collect_path, trace_name=f"em_dfm_{method}",
            checkpoint_path=checkpoint_path, checkpoint_every=checkpoint_every,
        )

        means, covs, _ = kalman_smoother(params, jnp.where(m_arr, xz, jnp.nan))
        return EMResults(
            params=params,
            factors=means[:, :r],
            factor_covs=covs[:, :r, :r],
            loglik_path=llpath,
            n_iter=n_iter,
            stds=stds,
            means=n_mean,
            trace=trace,
        )
